"""Deterministic chaos suite: fault-inject every remote touchpoint and
assert graceful, bounded degradation (ISSUE 1 tentpole).

Everything host-side runs under the frozen ``utils/time_util`` clock and
a seeded ``FaultInjector`` — no wall-clock sleeps. The socket scenarios
(a real token server partitioned mid-traffic) necessarily use real time,
but with millisecond-scale budgets/backoffs so the suite stays tier-1
fast.
"""

from __future__ import annotations

import logging
import socket
import threading
import time

import pytest

import sentinel_tpu as st
from sentinel_tpu.cluster.client import ClusterTokenClient
from sentinel_tpu.cluster.constants import THRESHOLD_GLOBAL, TokenResultStatus
from sentinel_tpu.cluster.rules import ClusterFlowRuleManager
from sentinel_tpu.cluster.server import ClusterTokenServer
from sentinel_tpu.cluster.token_service import DefaultTokenService
from sentinel_tpu.core.exceptions import BlockException
from sentinel_tpu.datasource.base import AutoRefreshDataSource
from sentinel_tpu.models.flow import FlowRule
from sentinel_tpu.resilience import (
    STATE_CLOSED,
    STATE_OPEN,
    DeadlineBudget,
    FaultInjected,
    FaultInjector,
    HealthGate,
    RetryPolicy,
    faults,
    health_snapshot,
)
from sentinel_tpu.transport.heartbeat import HeartbeatSender
from sentinel_tpu.utils import time_util

pytestmark = pytest.mark.chaos

SEED = 1234


@pytest.fixture()
def injector():
    with FaultInjector(seed=SEED) as inj:
        yield inj


@pytest.fixture()
def live_engine():
    """Fresh engine on the REAL clock (socket scenarios need real time),
    with a fast token-client reconnect cadence via the config plane (the
    production default is 2s; these scenarios force reconnects)."""
    from sentinel_tpu.core.config import config
    from sentinel_tpu.core.context import replace_context

    time_util.unfreeze_time()
    config.set("csp.sentinel.resilience.cluster.client.retry.base.ms", "50")
    config.set("csp.sentinel.resilience.cluster.client.retry.max.ms", "200")
    replace_context(None)
    eng = st.reset(capacity=256)
    yield eng
    replace_context(None)
    eng.cluster.stop()
    config.set("csp.sentinel.resilience.cluster.client.retry.base.ms", "")
    config.set("csp.sentinel.resilience.cluster.client.retry.max.ms", "")
    st.reset(capacity=256)


# -- primitives (frozen clock, no sockets) ------------------------------------


def test_retry_policy_is_seed_deterministic_and_capped():
    p = RetryPolicy(base_ms=100, max_ms=800, seed=7)
    a = [p.session().next_delay_ms() for _ in range(1)]
    s1, s2 = p.session(), p.session()
    seq1 = [s1.next_delay_ms() for _ in range(10)]
    seq2 = [s2.next_delay_ms() for _ in range(10)]
    assert seq1 == seq2
    assert seq1[0] == 100 == a[0]  # first delay is exactly base
    assert all(0 <= d <= 800 for d in seq1)
    s1.reset()
    assert s1.next_delay_ms() == 100  # reset restores the base cadence


def test_retry_policy_no_jitter_is_plain_exponential():
    s = RetryPolicy(base_ms=10, max_ms=100, multiplier=2.0,
                    jitter="none").session()
    assert [s.next_delay_ms() for _ in range(6)] == [10, 20, 40, 80, 100, 100]


def test_retry_policy_config_overrides():
    from sentinel_tpu.core.config import config

    config.set("csp.sentinel.resilience.heartbeat.retry.base.ms", "77")
    config.set("csp.sentinel.resilience.retry.max.ms", "9999")
    try:
        p = RetryPolicy.from_config("heartbeat", base_ms=10, max_ms=100)
        assert p.base_ms == 77       # component-specific key
        assert p.max_ms == 9999      # generic key
        q = RetryPolicy.from_config("datasource", base_ms=10, max_ms=100000)
        assert q.base_ms == 10       # untouched default
        assert q.max_ms == 9999
    finally:
        config.set("csp.sentinel.resilience.heartbeat.retry.base.ms", "")
        config.set("csp.sentinel.resilience.retry.max.ms", "")


def test_health_gate_full_cycle(frozen_time):
    g = HealthGate(failure_threshold=3, open_ms=1000, half_open_probes=1)
    for _ in range(2):
        g.record_failure()
    assert g.state == STATE_CLOSED and g.allow()
    g.record_failure()  # third consecutive: trip
    assert g.state == STATE_OPEN
    assert not g.allow() and g.snapshot()["rejectedCount"] == 1
    frozen_time.advance_time(999)
    assert not g.allow()
    frozen_time.advance_time(1)
    assert g.allow()                   # first arrival becomes the probe
    assert g.state_name == "HALF_OPEN"
    assert not g.allow()               # concurrent probe bounded
    g.record_failure()                 # failed probe: re-open, fresh window
    assert g.state == STATE_OPEN and not g.allow()
    frozen_time.advance_time(1000)
    assert g.allow()
    g.record_success()
    assert g.state == STATE_CLOSED and g.snapshot()["openCount"] == 2
    # recovery resets the consecutive counter: 2 failures don't re-trip
    g.record_failure(); g.record_failure()
    assert g.state == STATE_CLOSED


def test_deadline_budget_clamps_waits(frozen_time):
    b = DeadlineBudget(300)
    assert b.remaining_ms() == 300 and not b.expired
    frozen_time.advance_time(250)
    assert b.clamp_wait_ms(500) == 50
    frozen_time.advance_time(100)
    assert b.expired and b.clamp_wait_ms(500) == 0


def test_fault_injector_schedule_probability_and_replay():
    def run():
        fired = []
        with FaultInjector(seed=SEED) as inj:
            inj.arm("datasource.read", "error", probability=0.5, times=3)
            for i in range(20):
                try:
                    faults.fire("datasource.read")
                    fired.append(False)
                except FaultInjected:
                    fired.append(True)
        return fired

    first, second = run(), run()
    assert first == second           # seeded: exact replay
    assert sum(first) == 3           # times cap respected
    assert any(first) and not all(first)


def test_arming_a_new_point_mid_run_never_shifts_other_streams():
    """Replay stability across campaign episodes (ISSUE 15 satellite):
    probability draws come from PER-POINT RNG streams derived from
    ``(seed, point)``, so arming a NEW point mid-run — exactly what a
    chaos schedule does at its scheduled second — cannot shift the draw
    sequence of already-armed points. (The old single shared stream
    interleaved every armed point's draws: one new consumer reshuffled
    everyone after it.)"""
    def run(arm_second_mid_run: bool):
        fired = []
        with FaultInjector(seed=SEED) as inj:
            inj.arm("datasource.read", "error", probability=0.5)
            for i in range(24):
                if arm_second_mid_run and i == 12:
                    inj.arm("heartbeat.post", "error", probability=0.5)
                if arm_second_mid_run and i >= 12:
                    try:
                        faults.fire("heartbeat.post")  # consumes ITS stream
                    except FaultInjected:
                        pass
                try:
                    faults.fire("datasource.read")
                    fired.append(False)
                except FaultInjected:
                    fired.append(True)
        return fired

    baseline = run(False)
    assert run(True) == baseline
    assert any(baseline) and not all(baseline)
    # and the same point re-armed draws the same stream from the top
    assert run(False) == baseline


def test_thread_scoped_injector_ignores_foreign_threads():
    """scope_thread=True (the chaos campaign's stance): a foreign
    thread's fire()/mutate() is a transparent no-op that consumes NO
    spec budget and NO RNG draw — a live host engine's threads can
    neither suffer a campaign's faults nor shift its replay."""
    import threading

    with FaultInjector(seed=SEED, scope_thread=True) as inj:
        inj.arm("datasource.read", "error", times=1)
        inj.arm("cluster.server.frame", "garbage", garbage=b"XX", times=1)
        results = []

        def foreign():
            try:
                faults.fire("datasource.read")
                results.append("no-fire")
            except FaultInjected:
                results.append("fired")
            results.append(faults.mutate("cluster.server.frame", b"ok"))

        t = threading.Thread(target=foreign)
        t.start()
        t.join()
        assert results == ["no-fire", b"ok"]     # transparent elsewhere
        assert inj.fires("datasource.read") == 0  # budget untouched
        with pytest.raises(FaultInjected):
            faults.fire("datasource.read")        # owner thread still armed
        assert faults.mutate("cluster.server.frame", b"ok") == b"XX"


def test_reactor_conn_drop_seam_kills_and_recovers(live_engine, injector):
    """cluster.reactor.conn.drop (ISSUE 15): an armed error closes the
    reactor-side connection mid-stream — the client request fails, the
    reconnector dials back in, and service resumes with nothing
    stranded (droppedReplies counts any verdicts in flight)."""
    rules = ClusterFlowRuleManager()
    rules.load_rules("default", [_cluster_rule(900, local_count=1000.0)])
    service = DefaultTokenService(rules=rules)
    # Warm the width-1 acquire jit off the timed path: the cold compile
    # outlasts the 1s request timeout and would read as a fake FAIL.
    service.request_tokens([(None, 0, False)])
    server = ClusterTokenServer(service=service, host="127.0.0.1").start()
    client = ClusterTokenClient(
        "127.0.0.1", server.bound_port, request_timeout_s=1.0,
        retry_policy=RetryPolicy(base_ms=50, max_ms=200, seed=SEED),
        health_gate=None)
    try:
        client.start()
        deadline = time.monotonic() + 5
        while not client.is_connected() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert client.request_token(900).status == TokenResultStatus.OK

        injector.arm("cluster.reactor.conn.drop", "error", times=1)
        tr = client.request_token(900)   # the read that serves it drops
        assert tr.status == TokenResultStatus.FAIL
        assert injector.fires("cluster.reactor.conn.drop") == 1

        deadline = time.monotonic() + 5
        ok = False
        while time.monotonic() < deadline:
            if client.is_connected() \
                    and client.request_token(900).status \
                    == TokenResultStatus.OK:
                ok = True
                break
            time.sleep(0.02)
        assert ok, "client never recovered after the injected conn drop"
    finally:
        client.stop()
        server.stop()


def test_fault_injector_unarmed_and_uninstalled_are_noops():
    faults.fire("heartbeat.post")  # no injector installed
    assert faults.mutate("cluster.server.frame", b"x") == b"x"
    with FaultInjector(seed=0):
        faults.fire("heartbeat.post")  # installed but not armed
        assert faults.mutate("cluster.server.frame", b"x") == b"x"
    with pytest.raises(ValueError):
        FaultInjector().arm("no.such.point", "error")


# -- engine fail-open accounting (satellite) ----------------------------------


def test_note_fail_open_counts_and_rate_limits_logging(engine, frozen_time, caplog):
    with caplog.at_level(logging.WARNING, logger="sentinel_tpu"):
        for _ in range(5):
            engine._note_fail_open("test-channel")
        assert engine.fail_open_count == 5
        logs = [r for r in caplog.records if "UNGUARDED" in r.getMessage()]
        assert len(logs) == 1  # rate-limited: once per second
        frozen_time.advance_time(1000)
        engine._note_fail_open("test-channel")
        assert engine.fail_open_count == 6
        logs = [r for r in caplog.records if "UNGUARDED" in r.getMessage()]
        assert len(logs) == 2
    assert engine.resilience_stats()["failOpenCount"] == 6


def test_resilience_command_surfaces_stats(engine, frozen_time):
    import json
    import urllib.request

    from sentinel_tpu.transport.command_center import CommandCenter

    engine._note_fail_open("test")
    engine._note_cluster_fallback()
    center = CommandCenter(engine, port=0)
    center.start()
    try:
        url = f"http://127.0.0.1:{center.bound_port}/resilience"
        with urllib.request.urlopen(url, timeout=5) as r:
            body = json.loads(r.read().decode())
        assert body["failOpenCount"] == 1
        assert body["clusterFallbackCount"] == 1
        assert body["clusterEntryBudgetMs"] == engine.cluster_entry_budget_ms
        assert "probes" in body and "tokenClientBreaker" in body
    finally:
        center.stop()


# -- token client fail-fast + breaker (satellite + tentpole) ------------------


def test_request_token_fails_immediately_when_disconnected():
    client = ClusterTokenClient("127.0.0.1", 1, request_timeout_s=2.0)
    # never started/connected: no socket, no reconnector
    t0 = time.monotonic()
    tr = client.request_token(900)
    elapsed = time.monotonic() - t0
    assert tr.status == TokenResultStatus.FAIL
    assert elapsed < 0.25, f"disconnected FAIL took {elapsed:.3f}s"


def test_open_breaker_fails_fast_without_wire(frozen_time):
    gate = HealthGate(failure_threshold=1, open_ms=10_000)
    client = ClusterTokenClient("127.0.0.1", 1, health_gate=gate)
    gate.record_failure()
    assert gate.state == STATE_OPEN
    tr = client.request_token(900)
    assert tr.status == TokenResultStatus.FAIL
    assert gate.snapshot()["rejectedCount"] == 1


def test_gate_neutral_misses_do_not_trip_the_breaker(frozen_time):
    gate = HealthGate(failure_threshold=1, open_ms=10_000)
    client = ClusterTokenClient("127.0.0.1", 1, health_gate=gate)
    # A starved-deadline miss (budget drained) is breaker-neutral...
    assert client.request_token(900, gate_neutral=True).status \
        == TokenResultStatus.FAIL
    assert gate.state == STATE_CLOSED
    # ...a plain miss still counts.
    client.request_token(900)
    assert gate.state == STATE_OPEN


def test_dead_probe_owners_self_prune():
    import gc

    src = _ListSource(recommend_refresh_ms=60_000)
    from sentinel_tpu.resilience import register_probe

    register_probe("chaos-dead-probe", src.health)
    assert "chaos-dead-probe" in health_snapshot()
    del src
    gc.collect()
    assert "chaos-dead-probe" not in health_snapshot()


# -- datasource backoff + health (satellite) ----------------------------------


class _ListSource(AutoRefreshDataSource):
    def __init__(self, **kw):
        super().__init__(converter=lambda s: s, **kw)
        self.value = ["a"]

    def read_source(self):
        return list(self.value)


def test_datasource_backoff_and_last_success(frozen_time, injector):
    src = _ListSource(
        recommend_refresh_ms=100,
        retry_policy=RetryPolicy(base_ms=100, max_ms=1000, multiplier=2.0,
                                 jitter="none"))
    src.first_load()
    assert src.last_success_ms == time_util.current_time_millis()
    t_good = src.last_success_ms

    injector.arm("datasource.read", "error")
    frozen_time.advance_time(500)
    waits = [src._poll_once() for _ in range(4)]
    assert src.consecutive_failures == 4
    assert waits == [100, 200, 400, 800]  # backoff past the cadence
    assert src.last_success_ms == t_good  # stale age observable

    injector.disarm("datasource.read")
    assert src._poll_once() == 100        # recovery restores the cadence
    assert src.consecutive_failures == 0
    assert src.last_success_ms == time_util.current_time_millis()
    h = src.health()
    assert h["consecutiveFailures"] == 0 and h["lastSuccessMs"] > t_good


def test_datasource_probe_registered_while_running(frozen_time):
    src = _ListSource(recommend_refresh_ms=60_000)
    src.start()
    try:
        names = [n for n in health_snapshot() if n.startswith("datasource.")]
        assert any("_ListSource" in n for n in names)
    finally:
        src.close()
    assert not any("_ListSource" in n for n in health_snapshot())


# -- heartbeat rotation backoff (satellite) -----------------------------------


class _Beat(HeartbeatSender):
    def _post(self, req) -> bool:
        return True


def test_heartbeat_backs_off_after_full_rotation(frozen_time, injector):
    hb = _Beat(dashboards=["d1:80", "d2:80"], interval_ms=100, api_port=1,
               retry_policy=RetryPolicy(base_ms=100, max_ms=1600,
                                        multiplier=2.0, jitter="none"))
    injector.arm("heartbeat.post", "error")
    waits = [hb._next_wait_ms(hb.send_once()) for _ in range(6)]
    # every odd beat completes a full rotation of the 2 dashboards
    assert waits == [100, 100, 100, 200, 100, 400]
    assert hb.consecutive_failures == 6
    assert hb._idx == 6  # rotated past every dashboard
    injector.disarm("heartbeat.post")
    assert hb._next_wait_ms(hb.send_once()) == 100  # healthy cadence back
    assert hb.consecutive_failures == 0
    assert hb.last_success_ms == time_util.current_time_millis()


# -- the partition scenario (acceptance criterion) ----------------------------


class _Blackhole:
    """Accepts token-client connections, reads, never replies — a
    connected-but-partitioned token server."""

    def __init__(self):
        self._srv = socket.create_server(("127.0.0.1", 0))
        self.port = self._srv.getsockname()[1]
        self._conns = []
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        self._srv.settimeout(0.1)
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                continue
            conn.settimeout(0.1)
            self._conns.append(conn)
            threading.Thread(target=self._drain, args=(conn,),
                             daemon=True).start()

    def _drain(self, conn):
        while not self._stop.is_set():
            try:
                if not conn.recv(4096):
                    return
            except socket.timeout:
                continue
            except OSError:
                return

    def close(self):
        self._stop.set()
        for c in self._conns:
            try:
                c.close()
            except OSError:
                pass
        self._srv.close()
        self._thread.join(timeout=1.0)


def _cluster_rule(flow_id: int, local_count: float) -> FlowRule:
    return FlowRule(
        resource="shared", count=local_count, cluster_mode=True,
        cluster_config={"flowId": flow_id, "thresholdType": THRESHOLD_GLOBAL,
                        "fallbackToLocalWhenFail": True})


def _entry_once(eng):
    """One entry/exit; returns (blocked, elapsed_s)."""
    t0 = time.monotonic()
    try:
        with eng.entry("shared"):
            pass
        return False, time.monotonic() - t0
    except BlockException:
        return True, time.monotonic() - t0


def test_partition_mid_traffic_bounded_fallback_and_heal(live_engine):
    """The acceptance scenario end-to-end, on one engine:

    1. healthy: remote token server grants, entries pass;
    2. partition (connected blackhole): per-entry overhead is bounded by
       the deadline budget — never the 2s socket timeout — and after the
       breaker trips, entries are wire-free fast;
    3. local fallback enforces the rule's local threshold meanwhile;
    4. heal: the breaker's probe closes it and remote grants resume;
    5. every stage is visible in engine.resilience_stats().
    """
    eng = live_engine
    eng.cluster_entry_budget_ms = 250

    # Remote side: generous global threshold so the healthy phase passes.
    rules = ClusterFlowRuleManager()
    rules.load_rules("default", [_cluster_rule(900, local_count=1000.0)])
    service = DefaultTokenService(rules=rules)
    server = ClusterTokenServer(service=service, host="127.0.0.1").start()
    blackhole = _Blackhole()
    try:
        # Local side: same flowId, tight LOCAL threshold for the fallback.
        st.load_flow_rules([_cluster_rule(900, local_count=3.0)])

        eng.cluster.set_to_client("127.0.0.1", server.bound_port,
                                  request_timeout_s=2.0)
        client = eng.cluster.token_client
        client.health_gate = HealthGate(failure_threshold=2, open_ms=400)
        deadline = time.monotonic() + 5
        while eng.cluster.client_if_active() is None \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        assert eng.cluster.client_if_active() is not None
        # Warm the token service's jit (first width-1 batch compiles; on
        # a loaded CI box that can outlast the request timeout and read
        # as a fallback, which is not what this test measures).
        deadline = time.monotonic() + 10
        while client.request_token(900).status != TokenResultStatus.OK \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        client.health_gate.record_success()
        fallbacks0 = eng.cluster_fallback_count

        # 1. healthy: remote grants well past the local threshold.
        for _ in range(6):
            blocked, _ = _entry_once(eng)
            assert not blocked
        assert eng.cluster_fallback_count == fallbacks0

        # 2. partition mid-traffic: swap the live connection to a
        # blackhole (server keeps the old port; the client reconnects to
        # it only after heal). Redirect + force a reconnect.
        client.port = blackhole.port
        client._drop_connection()
        deadline = time.monotonic() + 5
        while not client.is_connected() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert client.is_connected()  # connected... into a blackhole
        time.sleep(1.1)  # healthy-phase passes age out of the 1s window

        # First entries pay at most ~the budget each (never the 2s
        # socket timeout) and trip the breaker.
        for _ in range(2):
            blocked, elapsed = _entry_once(eng)
            assert not blocked            # 3 local tokens available
            assert elapsed < 1.0, f"entry took {elapsed:.3f}s (budget 250ms)"
        assert client.health_gate.state == STATE_OPEN

        # Breaker OPEN: wire-free fast failure + local enforcement.
        blocked, elapsed = _entry_once(eng)
        assert not blocked and elapsed < 0.1   # 3rd local token
        blocked, elapsed = _entry_once(eng)
        assert blocked and elapsed < 0.1       # local rule enforces at 3/s
        stats = eng.resilience_stats()
        assert stats["clusterFallbackCount"] >= 4
        assert stats["tokenClientBreaker"]["state"] == "OPEN"

        # 4. heal: back to the real server; probe closes the breaker.
        client.port = server.bound_port
        client._drop_connection()
        deadline = time.monotonic() + 5
        while not client.is_connected() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert client.is_connected()
        time.sleep(0.45)  # let the 400ms open window elapse
        tr = client.request_token(900)  # the HALF_OPEN probe
        assert tr.status == TokenResultStatus.OK
        assert client.health_gate.state == STATE_CLOSED
        blocked, elapsed = _entry_once(eng)
        assert not blocked and elapsed < 1.0   # remote grants again
        assert eng.resilience_stats()["tokenClientBreaker"]["state"] == "CLOSED"
    finally:
        blackhole.close()
        eng.cluster.stop()
        server.stop()


@pytest.mark.slow
def test_budget_exhaustion_covers_remaining_rules(live_engine):
    """Many cluster rules against a blackholed server: the FIRST request
    eats the budget; the rest must not wait at all (aggregate bound).

    Slow-marked (ISSUE 15 tier-1 trim): 22s measured — the heaviest
    chaos seed; the partition drill above keeps the budget-bounded-entry
    contract in tier-1 and this aggregate flavor runs in the full
    suite."""
    eng = live_engine
    eng.cluster_entry_budget_ms = 150
    blackhole = _Blackhole()
    try:
        st.load_flow_rules([
            FlowRule(resource="shared", count=1000.0, cluster_mode=True,
                     cluster_config={"flowId": fid,
                                     "thresholdType": THRESHOLD_GLOBAL,
                                     "fallbackToLocalWhenFail": True})
            for fid in (901, 902, 903, 904, 905)])
        eng.warmup([1])  # keep the first measured entry off the XLA compile
        eng.cluster.set_to_client("127.0.0.1", blackhole.port,
                                  request_timeout_s=2.0)
        client = eng.cluster.token_client
        # Breaker off the table for this test: measure the raw budget.
        client.health_gate = HealthGate(failure_threshold=10_000, open_ms=10)
        deadline = time.monotonic() + 5
        while not client.is_connected() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert client.is_connected()

        blocked, elapsed = _entry_once(eng)
        assert not blocked  # local fallback: generous local threshold
        # 5 rules x 2s timeout would be 10s un-budgeted; the old code's
        # floor was one request_timeout_s. Budgeted: ~0.15s.
        assert elapsed < 1.0, f"5-rule entry took {elapsed:.3f}s"
        assert eng.cluster_budget_exhausted_count >= 1
        assert eng.cluster_fallback_count >= 5
    finally:
        blackhole.close()
        eng.cluster.stop()


# -- garbage frames (tentpole: reader-thread survival) ------------------------


def test_garbage_frames_never_kill_the_reader(live_engine, injector):
    """A server replying garbage desyncs the stream: the client must drop
    the connection (not die in the reader thread), reconnect, and serve
    token requests again once the stream is clean."""
    rules = ClusterFlowRuleManager()
    rules.load_rules("default", [_cluster_rule(900, local_count=1000.0)])
    server = ClusterTokenServer(
        service=DefaultTokenService(rules=rules), host="127.0.0.1").start()
    client = ClusterTokenClient(
        "127.0.0.1", server.bound_port, request_timeout_s=1.0,
        retry_policy=RetryPolicy(base_ms=50, max_ms=200, seed=SEED),
        health_gate=None)
    try:
        client.start()
        deadline = time.monotonic() + 5
        while not client.is_connected() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert client.request_token(900).status == TokenResultStatus.OK

        # Corrupt the next TWO reply frames (the PING reply of the
        # auto-reconnect is the second), then heal. The payload is a
        # COMPLETE frame with an undecodable 1-byte body: the decode
        # error (not a framing stall) must be what the reader survives.
        injector.arm("cluster.server.frame", "garbage", times=2,
                     garbage=b"\x00\x01\xff")
        tr = client.request_token(900)
        assert tr.status == TokenResultStatus.FAIL  # garbage -> fail fast
        assert injector.fires("cluster.server.frame") >= 1

        deadline = time.monotonic() + 5
        ok = False
        while time.monotonic() < deadline:
            if client.is_connected() \
                    and client.request_token(900).status == TokenResultStatus.OK:
                ok = True
                break
            time.sleep(0.02)
        assert ok, "client never recovered after garbage frames"
        # the reader thread of the LIVE connection is alive and named
        names = [t.name for t in threading.enumerate()]
        assert "sentinel-token-reader" in names
    finally:
        client.stop()
        server.stop()

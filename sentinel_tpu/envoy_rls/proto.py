"""Runtime-built protobuf messages for ``envoy.service.ratelimit.v2``
AND ``envoy.service.ratelimit.v3`` (the reference ships the v2 proto +
generated stubs — ``src/main/proto/envoy/service/ratelimit/v2/rls.proto``;
v3 is what current Envoy speaks, same shape under renamed packages. This
environment has the protobuf runtime but no protoc codegen, so both
schemas are registered through hand-built ``FileDescriptorProto``s —
wire-compatible with Envoy's RLS clients).

Field numbers mirror the official protos (identical across v2/v3 for
the subset served):
  RateLimitRequest  { domain=1; descriptors=2; hits_addend=3 }
  RateLimitDescriptor { entries=1 } / Entry { key=1; value=2 }
  RateLimitResponse { overall_code=1; statuses=2 }
  DescriptorStatus  { code=1; current_limit=2; limit_remaining=3 }
  RateLimit         { requests_per_unit=1; unit=2 }
v3 moves the descriptor type to
``envoy.extensions.common.ratelimit.v3`` (file
``envoy/extensions/common/ratelimit/v3/ratelimit.proto``) and the
service to ``envoy.service.ratelimit.v3.RateLimitService``.
"""

from __future__ import annotations

from google.protobuf import descriptor_pb2, descriptor_pool, message_factory

_PKG = "envoy.service.ratelimit.v2"
_RL_PKG = "envoy.api.v2.ratelimit"
_PKG_V3 = "envoy.service.ratelimit.v3"
_RL_PKG_V3 = "envoy.extensions.common.ratelimit.v3"

# Response codes (RateLimitResponse.Code).
CODE_UNKNOWN = 0
CODE_OK = 1
CODE_OVER_LIMIT = 2

# RateLimit.Unit.
UNIT_UNKNOWN = 0
UNIT_SECOND = 1

_T = descriptor_pb2.FieldDescriptorProto


def _field(name, number, ftype, label=_T.LABEL_OPTIONAL, type_name=None):
    f = _T(name=name, number=number, type=ftype, label=label)
    if type_name:
        f.type_name = type_name
    return f


def _add_version(pool, rl_file, rl_pkg, rls_file, rls_pkg) -> None:
    """Register one version's descriptor + service files (the schema is
    shape-identical across v2/v3; only files/packages differ)."""
    rl = descriptor_pb2.FileDescriptorProto(name=rl_file, package=rl_pkg)
    desc = rl.message_type.add(name="RateLimitDescriptor")
    entry = desc.nested_type.add(name="Entry")
    entry.field.append(_field("key", 1, _T.TYPE_STRING))
    entry.field.append(_field("value", 2, _T.TYPE_STRING))
    desc.field.append(_field(
        "entries", 1, _T.TYPE_MESSAGE, _T.LABEL_REPEATED,
        f".{rl_pkg}.RateLimitDescriptor.Entry"))
    pool.Add(rl)

    rls = descriptor_pb2.FileDescriptorProto(
        name=rls_file, package=rls_pkg, dependency=[rl_file])

    req = rls.message_type.add(name="RateLimitRequest")
    req.field.append(_field("domain", 1, _T.TYPE_STRING))
    req.field.append(_field(
        "descriptors", 2, _T.TYPE_MESSAGE, _T.LABEL_REPEATED,
        f".{rl_pkg}.RateLimitDescriptor"))
    req.field.append(_field("hits_addend", 3, _T.TYPE_UINT32))

    resp = rls.message_type.add(name="RateLimitResponse")
    code_enum = resp.enum_type.add(name="Code")
    for n, v in (("UNKNOWN", 0), ("OK", 1), ("OVER_LIMIT", 2)):
        code_enum.value.add(name=n, number=v)
    ratelimit = resp.nested_type.add(name="RateLimit")
    unit_enum = ratelimit.enum_type.add(name="Unit")
    for n, v in (("UNKNOWN", 0), ("SECOND", 1), ("MINUTE", 2),
                 ("HOUR", 3), ("DAY", 4)):
        unit_enum.value.add(name=n, number=v)
    ratelimit.field.append(_field("requests_per_unit", 1, _T.TYPE_UINT32))
    ratelimit.field.append(_field(
        "unit", 2, _T.TYPE_ENUM,
        type_name=f".{rls_pkg}.RateLimitResponse.RateLimit.Unit"))
    status = resp.nested_type.add(name="DescriptorStatus")
    status.field.append(_field(
        "code", 1, _T.TYPE_ENUM,
        type_name=f".{rls_pkg}.RateLimitResponse.Code"))
    status.field.append(_field(
        "current_limit", 2, _T.TYPE_MESSAGE,
        type_name=f".{rls_pkg}.RateLimitResponse.RateLimit"))
    status.field.append(_field("limit_remaining", 3, _T.TYPE_UINT32))
    resp.field.append(_field(
        "overall_code", 1, _T.TYPE_ENUM,
        type_name=f".{rls_pkg}.RateLimitResponse.Code"))
    resp.field.append(_field(
        "statuses", 2, _T.TYPE_MESSAGE, _T.LABEL_REPEATED,
        f".{rls_pkg}.RateLimitResponse.DescriptorStatus"))
    pool.Add(rls)


def _build_pool() -> descriptor_pool.DescriptorPool:
    pool = descriptor_pool.DescriptorPool()
    _add_version(pool, "envoy/api/v2/ratelimit/ratelimit.proto", _RL_PKG,
                 "envoy/service/ratelimit/v2/rls.proto", _PKG)
    _add_version(pool,
                 "envoy/extensions/common/ratelimit/v3/ratelimit.proto",
                 _RL_PKG_V3,
                 "envoy/service/ratelimit/v3/rls.proto", _PKG_V3)
    return pool


_pool = _build_pool()


def _cls(full_name: str):
    return message_factory.GetMessageClass(_pool.FindMessageTypeByName(full_name))


RateLimitDescriptor = _cls(f"{_RL_PKG}.RateLimitDescriptor")
RateLimitRequest = _cls(f"{_PKG}.RateLimitRequest")
RateLimitResponse = _cls(f"{_PKG}.RateLimitResponse")

RateLimitDescriptorV3 = _cls(f"{_RL_PKG_V3}.RateLimitDescriptor")
RateLimitRequestV3 = _cls(f"{_PKG_V3}.RateLimitRequest")
RateLimitResponseV3 = _cls(f"{_PKG_V3}.RateLimitResponse")

SERVICE_NAME = f"{_PKG}.RateLimitService"
SERVICE_NAME_V3 = f"{_PKG_V3}.RateLimitService"
METHOD_NAME = "ShouldRateLimit"

package com.alibaba.csp.sentinel.cluster;

/** Vendored signature stub (see vendored/README.md). Reference:
 * core:cluster/TokenResultStatus.java (values are wire-visible and
 * must match cluster/constants.py TokenResultStatus). */
public final class TokenResultStatus {

    public static final int BAD_REQUEST = -4;
    public static final int TOO_MANY_REQUEST = -2;
    public static final int FAIL = -1;
    public static final int OK = 0;
    public static final int BLOCKED = 1;
    public static final int SHOULD_WAIT = 2;
    public static final int NO_RULE_EXISTS = 3;
    public static final int NO_REF_RULE_EXISTS = 4;
    public static final int NOT_AVAILABLE = 5;
    /** TPU wire extension (not upstream): the token server shed this
     * request before admission (bounded-queue overload protection).
     * Clients that predate it treat 6 as unknown -> fallbackToLocal. */
    public static final int OVERLOADED = 6;
    /** TPU wire extension (not upstream): a sharded leader answered a
     * request for a flow whose hash slice it does not own — the
     * client's shard map is stale; the reply names the server's map
     * version so routing clients self-heal. Clients that predate it
     * treat 7 as unknown -> fallbackToLocal. */
    public static final int WRONG_SLICE = 7;

    private TokenResultStatus() {
    }
}

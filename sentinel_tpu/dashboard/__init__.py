"""The sentinel-tpu dashboard (reference: ``sentinel-dashboard``, SURVEY.md
§2.6): machine discovery from heartbeats, a metrics poller + 5-minute
in-memory repository, rule CRUD pushed through each engine's command port,
cluster token-server assignment, and a single-page live UI.

Run standalone::

    python -m sentinel_tpu.dashboard --port 8080

then point engines at it with ``csp.sentinel.dashboard.server=host:8080``.
"""

from sentinel_tpu.dashboard.auth import AuthService, AuthUser
from sentinel_tpu.dashboard.client import ApiError, SentinelApiClient
from sentinel_tpu.dashboard.discovery import AppManagement, MachineInfo
from sentinel_tpu.dashboard.metrics import InMemoryMetricsRepository, MetricFetcher
from sentinel_tpu.dashboard.server import DashboardServer

__all__ = [
    "ApiError",
    "AppManagement",
    "AuthService",
    "AuthUser",
    "DashboardServer",
    "InMemoryMetricsRepository",
    "MachineInfo",
    "MetricFetcher",
    "SentinelApiClient",
]

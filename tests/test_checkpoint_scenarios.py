"""Checkpoint × cluster × lease scenarios (VERDICT r3 #7): the
warm-restart superset must actually hold under the fast paths — serve
leased traffic, checkpoint, "crash", restore, and prove quota continuity
on BOTH the device window and the host lease mirror; then the same for a
pod-parallel state snapshot.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jax.sharding import Mesh

import sentinel_tpu as st
from sentinel_tpu.core import constants as C
from sentinel_tpu.core.checkpoint import (
    restore_checkpoint,
    restore_pod_checkpoint,
    save_checkpoint,
    save_pod_checkpoint,
)
from sentinel_tpu.core.registry import NodeRegistry
from sentinel_tpu.models import authority as A
from sentinel_tpu.models import degrade as D_
from sentinel_tpu.models import flow as F
from sentinel_tpu.models import param_flow as PF
from sentinel_tpu.models import system as Y
from sentinel_tpu.ops import step as S
from sentinel_tpu.parallel import cluster as PC
from sentinel_tpu.utils import time_util

NOW0 = 1_700_000_000_000
NDEV = 8


def test_leased_traffic_checkpoint_crash_restore(engine, frozen_time,
                                                 tmp_path):
    """Serve leased traffic (entries AND exits through the async
    committer) -> checkpoint -> crash -> restore: the device window, the
    lease mirror, and continued admission all agree on the spent quota."""
    st.load_flow_rules([st.FlowRule(resource="lw", count=10)])
    assert "lw" in engine._leases  # the scenario must exercise the lease
    for _ in range(6):
        h = st.entry_ok("lw")
        assert h
        h.exit()
    engine._flush_committer()
    snap = engine.node_snapshot()["lw"]
    assert snap["passQps"] == 6 and snap["successQps"] == 6

    ckpt = str(tmp_path / "lease.npz")
    save_checkpoint(engine, ckpt)

    fresh = st.reset(capacity=512)           # the crash
    st.load_flow_rules([st.FlowRule(resource="lw", count=10)])
    restore_checkpoint(fresh, ckpt)

    # device window continuity
    snap2 = fresh.node_snapshot()["lw"]
    assert snap2["passQps"] == 6 and snap2["successQps"] == 6
    # mirror continuity: host admission sees the restored usage
    now = time_util.current_time_millis()
    assert fresh._leases["lw"].usage(now) == pytest.approx(6.0)
    # quota continuity end-to-end: 4 remaining admits, then block
    got = [bool(st.entry_ok("lw")) for _ in range(6)]
    assert got == [True] * 4 + [False] * 2
    # ... and the mirror + window still agree after the new traffic
    fresh._flush_committer()
    assert fresh.node_snapshot()["lw"]["passQps"] == 10
    assert fresh._leases["lw"].usage(
        time_util.current_time_millis()) == pytest.approx(10.0)


def test_restore_resets_thread_gauge(engine, frozen_time, tmp_path):
    """Entries in flight at the crash died with their process: restoring
    their concurrency would starve THREAD-grade rules forever, so the
    gauge resets while the windows persist (docs/SEMANTICS.md)."""
    st.load_flow_rules([st.FlowRule(resource="tg", count=2,
                                    grade=C.FLOW_GRADE_THREAD)])
    h1 = st.entry("tg")
    h2 = st.entry("tg")                       # concurrency now 2 of 2
    assert not st.entry_ok("tg")              # saturated pre-crash
    ckpt = str(tmp_path / "threads.npz")
    save_checkpoint(engine, ckpt)
    del h1, h2                                # in-flight at the "crash"

    fresh = st.reset(capacity=512)
    st.load_flow_rules([st.FlowRule(resource="tg", count=2,
                                    grade=C.FLOW_GRADE_THREAD)])
    restore_checkpoint(fresh, ckpt)
    # windows survived (the block above is visible in history)...
    assert fresh.node_snapshot()["tg"]["blockQps"] == 1
    # ...but the dead process's phantom threads do not hold slots
    h = st.entry_ok("tg")
    assert h
    h.exit()


# -- pod state -----------------------------------------------------------


@pytest.fixture(scope="module")
def mesh():
    devices = jax.devices()
    assert len(devices) >= NDEV, "conftest must force 8 CPU devices"
    return Mesh(np.asarray(devices[:NDEV]), (PC.AXIS,))


def _build_pod(capacity=128, threshold=64):
    reg = NodeRegistry(capacity)
    row = reg.cluster_row("shared")
    rules = [st.FlowRule(resource="shared", count=threshold,
                         cluster_mode=True,
                         cluster_config={"flowId": 1,
                                         "thresholdType": 1})]
    ft, _ = F.compile_flow_rules(rules, reg, capacity)
    dt, di = D_.compile_degrade_rules([], reg, capacity)
    pt = PF.compile_param_rules([], reg, capacity)
    pack = S.RulePack(
        flow=ft, degrade=dt,
        authority=A.compile_authority_rules([], reg, capacity),
        system=Y.compile_system_rules([]), param=pt)
    one = S.make_state(capacity, ft.num_rules, NOW0,
                       degrade=D_.make_degrade_state(dt, di),
                       param=PF.make_param_state(pt.num_rules))
    return row, pack, one


def _batch(row, per_dev):
    from sentinel_tpu.core.batch import EntryBatch, make_entry_batch_np

    buf = make_entry_batch_np(NDEV * per_dev)
    buf["cluster_row"][:] = row
    buf["dn_row"][:] = -1
    buf["count"][:] = 1
    return EntryBatch(**{k: jnp.asarray(v) for k, v in buf.items()})


def test_pod_state_checkpoint_roundtrip_keeps_global_quota(mesh, tmp_path):
    """Pod saturates its global quota -> snapshot -> crash -> restore
    into a fresh pod: the psum'd global window still counts the pre-crash
    usage, so the restored pod admits NOTHING while a cold pod would
    re-grant the full quota."""
    row, pack, one = _build_pod(threshold=64)
    pod = PC.make_pod_state(NDEV, one)
    entry, _ = PC.make_pod_steps(mesh)
    entry = jax.jit(entry)

    pod, dec1 = entry(pod, pack, _batch(row, 8),
                      jnp.asarray(NOW0, jnp.int64))  # exactly 64 of 64
    assert int((np.asarray(dec1.reason) == C.BlockReason.PASS).sum()) == 64

    ckpt = str(tmp_path / "pod.npz")
    save_pod_checkpoint(pod, ckpt)

    row2, pack2, one2 = _build_pod(threshold=64)
    template = PC.make_pod_state(NDEV, one2)
    restored = restore_pod_checkpoint(template, ckpt)

    # a cold pod (what a non-warm restart would run) re-grants everything
    _, cold = entry(PC.make_pod_state(NDEV, one2), pack2, _batch(row2, 6),
                    jnp.asarray(NOW0 + 1, jnp.int64))
    assert int((np.asarray(cold.reason) == C.BlockReason.PASS).sum()) == 48
    # the restored pod sees the spent global window: zero re-grant
    _, dec2 = entry(restored, pack2, _batch(row2, 6),
                    jnp.asarray(NOW0 + 1, jnp.int64))
    assert int((np.asarray(dec2.reason) == C.BlockReason.PASS).sum()) == 0


def test_pod_checkpoint_rejects_mismatched_template(mesh, tmp_path):
    row, pack, one = _build_pod()
    pod = PC.make_pod_state(NDEV, one)
    ckpt = str(tmp_path / "pod_bad.npz")
    save_pod_checkpoint(pod, ckpt)
    _, _, small = _build_pod(capacity=64)
    with pytest.raises(ValueError, match="leaf"):
        restore_pod_checkpoint(PC.make_pod_state(NDEV, small), ckpt)

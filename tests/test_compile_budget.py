"""Compile-time resource-budget guard (VERDICT r3 #8).

BENCH_r01 died on-chip with "scoped allocation 19.09M > 16.00M" — a VMEM
blowup in the widest fused-scan step that no CPU test could see, because
nothing asserted anything about the compiled program's footprint. This
file lower().compile()s the bench's EXACT widest shape (10k resources /
32k rows / 8192-wide batch / 16-step scan) and pins its memory and work
metrics, so a scan/width/step change that balloons intermediates fails
here instead of only on real hardware.

CPU compilation is not TPU compilation, but the blowup class this guards
against (materializing per-step state copies, un-fused [steps, batch, R]
intermediates) inflates the CPU temp allocation the same way. Budgets
carry ~3x headroom over measured values (temp 155MB, 6.9 GFLOP, 796MB
accessed per dispatch at pinning time); a legit regression that trips
them should raise the budget CONSCIOUSLY, with a bench run on chip.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sentinel_tpu.core.batch import BATCH_WIDTHS, EntryBatch, make_entry_batch_np
from sentinel_tpu.core.registry import NodeRegistry
from sentinel_tpu.models import authority as A
from sentinel_tpu.models import degrade as D
from sentinel_tpu.models import flow as F
from sentinel_tpu.models import param_flow as P
from sentinel_tpu.models import system as Y
from sentinel_tpu.ops import step as S

N_RES, CAPACITY, BATCH_N, SCAN_STEPS = 10_000, 32_768, 8192, 16
NOW0 = 1_700_000_000_000

TEMP_BYTES_BUDGET = 512 * 1024 * 1024     # measured 155MB
FLOPS_PER_ENTRY_BUDGET = 150_000          # measured ~53k
BYTES_ACCESSED_PER_ENTRY_BUDGET = 20_000  # measured ~6.1k


def _bench_program():
    """The bench's widest fused program, byte-for-byte the same shapes."""
    reg = NodeRegistry(CAPACITY)
    rules = [F.FlowRule(resource=f"res{i}", count=1e9)
             for i in range(0, N_RES, 10)]
    drules = [D.DegradeRule(resource=f"res{i}", count=100, grade=i % 3,
                            time_window=10) for i in range(0, N_RES, 20)]
    prules = [P.ParamFlowRule(f"res{i}", param_idx=0, count=1e9)
              for i in range(0, N_RES, 40)]
    rows = np.asarray([reg.cluster_row(f"res{i}") for i in range(N_RES)])
    ft, _ = F.compile_flow_rules(rules, reg, CAPACITY)
    dt, di = D.compile_degrade_rules(drules, reg, CAPACITY)
    pt = P.compile_param_rules(prules, reg, CAPACITY)
    pack = S.RulePack(
        flow=ft, degrade=dt,
        authority=A.compile_authority_rules([], reg, CAPACITY),
        system=Y.compile_system_rules([Y.SystemRule(qps=1e12)]),
        param=pt)
    state = S.make_state(CAPACITY, ft.num_rules, NOW0,
                         degrade=D.make_degrade_state(dt, di),
                         param=P.make_param_state(pt.num_rules))
    buf = make_entry_batch_np(BATCH_N)
    buf["cluster_row"][:] = rows[np.arange(BATCH_N) % N_RES]
    buf["count"][:] = 1
    batch = EntryBatch(**{k: jnp.asarray(v) for k, v in buf.items()})

    def multi(state, now_start):
        def body(st_, i):
            st_, dec = S.entry_step(st_, pack, batch, now_start + i)
            return st_, dec.reason[0]

        return jax.lax.scan(body, state,
                            jnp.arange(SCAN_STEPS, dtype=jnp.int64))

    return jax.jit(multi, donate_argnums=(0,)), state


def test_widest_fused_step_compiles_within_budget():
    fn, state = _bench_program()
    compiled = fn.lower(state, jnp.asarray(NOW0, jnp.int64)).compile()

    mem = compiled.memory_analysis()
    assert mem.temp_size_in_bytes < TEMP_BYTES_BUDGET, (
        f"fused-step temp allocation {mem.temp_size_in_bytes / 1e6:.1f}MB "
        f"blew the {TEMP_BYTES_BUDGET / 1e6:.0f}MB budget — this is the "
        "BENCH_r01 VMEM-OOM class; check for materialized per-step "
        "intermediates before raising the budget")
    # donation must alias the big state buffers, not copy them
    assert mem.alias_size_in_bytes >= 0.9 * mem.argument_size_in_bytes

    cost = compiled.cost_analysis()
    # jax < 0.4.35 wrapped the per-device cost dict in a single-element
    # list; newer versions return the dict directly. Accept both.
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    entries = SCAN_STEPS * BATCH_N
    flops_per_entry = cost.get("flops", 0.0) / entries
    assert flops_per_entry < FLOPS_PER_ENTRY_BUDGET, (
        f"{flops_per_entry:.0f} flops/entry (budget "
        f"{FLOPS_PER_ENTRY_BUDGET}) — per-entry work regressed")
    bytes_per_entry = cost.get("bytes accessed", 0.0) / entries
    assert bytes_per_entry < BYTES_ACCESSED_PER_ENTRY_BUDGET, (
        f"{bytes_per_entry:.0f} bytes accessed/entry (budget "
        f"{BYTES_ACCESSED_PER_ENTRY_BUDGET}) — HBM traffic regressed")


def test_engine_ladder_widths_compile_within_budget(engine, frozen_time):
    """Every interactive ladder width the engine can dispatch stays well
    under the widest-budget too (these are the pipeline's shapes)."""
    import sentinel_tpu as st

    st.load_flow_rules([st.FlowRule(resource="w", count=100)])
    st.load_degrade_rules([st.DegradeRule(resource="w", count=50, grade=0,
                                          time_window=10)])
    engine._ensure_compiled()
    state, pack = engine._state, engine._rules
    for width in BATCH_WIDTHS:
        buf = make_entry_batch_np(width)
        buf["count"][:] = 1
        batch = EntryBatch(**{k: jnp.asarray(v) for k, v in buf.items()})
        compiled = jax.jit(
            S.entry_step, static_argnames=(), donate_argnums=(0,)
        ).lower(state, pack, batch,
                jnp.asarray(NOW0, jnp.int64)).compile()
        mem = compiled.memory_analysis()
        assert mem.temp_size_in_bytes < TEMP_BYTES_BUDGET

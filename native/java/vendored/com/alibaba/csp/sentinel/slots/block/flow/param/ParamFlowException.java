package com.alibaba.csp.sentinel.slots.block.flow.param;

import com.alibaba.csp.sentinel.slots.block.BlockException;

/** Vendored signature stub (see vendored/README.md). Reference:
 * core:slots/block/flow/param/ParamFlowException.java. */
public class ParamFlowException extends BlockException {

    public ParamFlowException(String resourceName, String message) {
        super(resourceName, message);
    }
}

"""Declarative SLO objectives + multi-window burn-rate rules.

An objective names a resource, an SLI, and a target good-fraction; each
objective carries a list of (long window, short window, burn threshold,
severity) rules — the SRE workbook's multiwindow multi-burn-rate alert
pairs, scaled to this system's second-granular retention (the classic
1h/5m + 6h/30m pairs assume month-long windows; here the flight
recorder retains ~17 minutes by default, so the shipped defaults are a
60s/5s fast-burn page and a 300s/60s slow-burn ticket).

SLI vocabulary (all derived from one flight-recorder second, exactly):

* ``availability`` — good = admitted entries; ``bad = block``,
  ``total = pass + block`` (acquire-count weighted, like the recorder).
* ``latency`` — good = successful completions with RT <= the objective's
  ``latency_ms``; derived from the per-second RT histogram, so the
  threshold SNAPS UP to the nearest log2 bucket edge
  (``attribution.RT_BUCKET_EDGES_MS``) — the snapped value is what the
  objective actually enforces and what :func:`snap_latency_ms` reports.

Burn rate over a window W ending at the newest complete second:

    error_rate(W) = sum(bad) / sum(total)        (0 when total == 0)
    burn(W)       = error_rate(W) / (1 - objective)

A rule FIRES while ``burn(long) >= threshold AND burn(short) >=
threshold`` and the long window saw at least ``min_events`` total
events; it RESOLVES as soon as either side drops. Idle seconds are
zeros by construction (stamp arithmetic — a missing second contributes
to neither numerator nor denominator).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from sentinel_tpu.telemetry.attribution import RT_BUCKET_EDGES_MS

SLI_AVAILABILITY = "availability"
SLI_LATENCY = "latency"
SLIS = (SLI_AVAILABILITY, SLI_LATENCY)

SEVERITY_PAGE = "page"
SEVERITY_TICKET = "ticket"
SEVERITIES = (SEVERITY_PAGE, SEVERITY_TICKET)


@dataclass(frozen=True)
class BurnWindow:
    """One fast/slow-burn rule: both windows must exceed ``burn``."""

    long_s: int
    short_s: int
    burn: float
    severity: str = SEVERITY_PAGE

    def validate(self) -> "BurnWindow":
        if self.long_s <= 0 or self.short_s <= 0 \
                or self.short_s > self.long_s:
            raise ValueError(
                f"burn window needs 0 < shortSeconds <= longSeconds, got "
                f"{self.short_s}/{self.long_s}")
        if self.burn <= 0:
            raise ValueError(f"burn threshold must be > 0, got {self.burn}")
        if self.severity not in SEVERITIES:
            raise ValueError(
                f"severity must be one of {SEVERITIES}, got "
                f"{self.severity!r}")
        return self


# Fast-burn page + slow-burn ticket, scaled to second-level retention.
DEFAULT_BURN_WINDOWS: Tuple[BurnWindow, ...] = (
    BurnWindow(long_s=60, short_s=5, burn=14.4, severity=SEVERITY_PAGE),
    BurnWindow(long_s=300, short_s=60, burn=6.0, severity=SEVERITY_TICKET),
)

DEFAULT_MIN_EVENTS = 10


def snap_latency_ms(latency_ms: int) -> int:
    """The latency threshold the RT histogram can enforce exactly: the
    smallest bucket edge >= the requested value (requests above the top
    edge land in the +Inf bucket, so anything past it means "good =
    every finite bucket")."""
    for edge in RT_BUCKET_EDGES_MS:
        if latency_ms <= edge:
            return int(edge)
    return int(RT_BUCKET_EDGES_MS[-1])


@dataclass(frozen=True)
class SloObjective:
    """One resource's target: ``objective`` is the good-fraction target
    (e.g. 0.99 = at most 1% bad), strictly inside (0, 1) so the error
    budget ``1 - objective`` is never zero."""

    resource: str
    sli: str = SLI_AVAILABILITY
    objective: float = 0.99
    latency_ms: int = 256          # latency SLI only; snapped to an edge
    min_events: int = DEFAULT_MIN_EVENTS
    windows: Tuple[BurnWindow, ...] = DEFAULT_BURN_WINDOWS
    name: str = ""

    def validate(self) -> "SloObjective":
        if not self.resource:
            raise ValueError("objective needs a resource")
        if self.sli not in SLIS:
            raise ValueError(f"sli must be one of {SLIS}, got {self.sli!r}")
        if not 0.0 < self.objective < 1.0:
            raise ValueError(
                f"objective must be in (0, 1), got {self.objective}")
        if self.sli == SLI_LATENCY and self.latency_ms <= 0:
            raise ValueError(
                f"latency objective needs latencyMs > 0, got "
                f"{self.latency_ms}")
        if self.min_events < 0:
            raise ValueError(f"minEvents must be >= 0, got {self.min_events}")
        if not self.windows:
            raise ValueError("objective needs at least one burn window")
        for w in self.windows:
            w.validate()
        return self

    @property
    def key(self) -> str:
        return self.name or f"{self.resource}:{self.sli}"

    @property
    def budget(self) -> float:
        return 1.0 - self.objective

    @property
    def snapped_latency_ms(self) -> int:
        return snap_latency_ms(self.latency_ms)

    def bad_total(self, second: Dict) -> Tuple[int, int]:
        """(bad, total) events of this SLI in one rendered recorder
        second (the ``second_to_dict`` per-resource cell). The ONE
        derivation both the live evaluator and the test oracle share the
        definition of — the oracle reimplements it in numpy."""
        if self.sli == SLI_AVAILABILITY:
            bad = int(second.get("block", 0))
            total = bad + int(second.get("pass", 0))
            return bad, total
        buckets = second.get("rtBuckets") or []
        total = int(sum(buckets))
        edge = self.snapped_latency_ms
        good = sum(int(buckets[b]) for b in range(len(buckets))
                   if b < len(RT_BUCKET_EDGES_MS)
                   and RT_BUCKET_EDGES_MS[b] <= edge)
        return total - good, total


def max_window_seconds(objectives) -> int:
    """Retention the evaluator needs: the widest long window in play."""
    return max((w.long_s for o in objectives for w in o.windows), default=0)

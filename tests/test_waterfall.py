"""Latency-waterfall tests (ISSUE 18): per-second fold exactness against
an independent oracle, the O(1) log2 bucketer vs the linear-scan oracle,
exemplar -> stitched-span joins (unit + real loopback sockets), the
regression sentry's fire/resolve cycle (stubbed sink + the real
SloManager path), the A/B zero-device-work guard, timebase-reset
inertness under injected clocks (ISSUE 13), and the ops command."""

import socket

import numpy as np
import pytest

import sentinel_tpu as st
from sentinel_tpu.cluster import codec
from sentinel_tpu.cluster.constants import MSG_FLOW, TokenResultStatus
from sentinel_tpu.cluster.rules import ClusterFlowRuleManager
from sentinel_tpu.cluster.server import ClusterTokenServer
from sentinel_tpu.cluster.token_service import DefaultTokenService
from sentinel_tpu.telemetry.attribution import (
    NUM_WF_BUCKETS,
    WF_BUCKET_EDGES_MS,
    bucket_index_of,
    histogram_quantile_edges,
)
from sentinel_tpu.telemetry.spans import new_trace_context
from sentinel_tpu.telemetry.waterfall import (
    LANE_STAGES,
    WIRE_STAGES,
    WaterfallRecorder,
    _fast_bucket,
)
from sentinel_tpu.utils import time_util

BASE_MS = 1_700_000_100_000
FLOW_ID = 8400


# -- bucket geometry ----------------------------------------------------------


def test_fast_bucket_matches_linear_oracle():
    """Differential: the O(1) ceil-log2 bucketer == the linear ``le``
    scan on every edge (exactly, one ulp above, one below) and on a
    dense random sweep across the whole range plus both overflows."""
    rng = np.random.default_rng(7)
    probes = [0.0, -1.0, 1e-9, 1e9]
    for e in WF_BUCKET_EDGES_MS:
        probes += [e, np.nextafter(e, 0), np.nextafter(e, np.inf)]
    probes += list(rng.uniform(0.0, WF_BUCKET_EDGES_MS[-1] * 4, 20_000))
    probes += list(np.exp(rng.uniform(np.log(1e-4), np.log(1e5), 20_000)))
    for v in probes:
        v = float(v)
        assert _fast_bucket(v) == bucket_index_of(v), v


# -- per-second fold exactness ------------------------------------------------


def _scripted_stream(seed, n_secs, max_rps):
    """Deterministic observation stream: [(sec_ms, kind, payload)]."""
    rng = np.random.default_rng(seed)
    events = []
    for si in range(n_secs):
        sec = BASE_MS + si * 1000
        for _ in range(int(rng.integers(1, max_rps + 1))):
            durs = np.exp(rng.uniform(np.log(1e-3), np.log(500.0), 8))
            if rng.random() < 0.05:
                durs[int(rng.integers(0, 8))] = -1.0  # clamp path
            events.append((sec, "wire", [float(d) for d in durs]))
        for _ in range(int(rng.integers(0, max_rps // 2 + 1))):
            events.append((sec, "pipeline",
                           [float(np.exp(rng.uniform(-5, 5))),
                            float(np.exp(rng.uniform(-5, 5)))]))
    return events


def _oracle_fold(events):
    """Independent fold: per-second per-stage bucket counts + sums via
    the linear-scan bucketer, same clamp convention."""
    per_sec = {}
    for sec, kind, durs in events:
        rec = per_sec.setdefault(sec, {
            lane: ([[0] * NUM_WF_BUCKETS for _ in stages],
                   [0.0] * len(stages))
            for lane, stages in LANE_STAGES.items()})
        rec.setdefault("rtt", None)
        lane = "wire" if kind == "wire" else "pipeline"
        counts, sums = rec[lane]
        total = 0.0
        for i, d in enumerate(durs):
            d = d if d > 0.0 else 0.0
            counts[i][bucket_index_of(d)] += 1
            sums[i] += d
            total += d
        if kind == "wire":
            rtt = rec.get("rtt") or ([0] * NUM_WF_BUCKETS, [0.0])
            rtt[0][bucket_index_of(total)] += 1
            rtt[1][0] += total
            rec["rtt"] = rtt
    return per_sec


@pytest.mark.parametrize("seed,n_secs,max_rps", [
    (5, 20, 40),
    pytest.param(29, 120, 200, marks=pytest.mark.slow),
    pytest.param(83, 120, 200, marks=pytest.mark.slow),
])
def test_fold_matches_oracle(seed, n_secs, max_rps):
    """The recorder's sealed seconds are EXACT: bucket counts, stage
    sums, RTT histogram, quantiles, and the stage-sum == RTT-sum
    reconciliation all match an independent oracle fold."""
    clock = {"now": BASE_MS}
    wf = WaterfallRecorder(now_ms=lambda: clock["now"])
    assert wf.enabled
    events = _scripted_stream(seed, n_secs, max_rps)
    for sec, kind, durs in events:
        clock["now"] = sec + 137  # mid-second stamp
        if kind == "wire":
            wf.observe_wire(durs)
        else:
            wf.observe_pipeline(durs[0], durs[1])
        if sec > BASE_MS:  # interleave folds with writes: idempotent
            wf.roll(sec)
    clock["now"] = BASE_MS + (n_secs + 1) * 1000
    wf.roll(clock["now"])

    oracle = _oracle_fold(events)
    snap = wf.snapshot(limit=n_secs + 5)
    recent = {r["timestamp"]: r for r in snap["recent"]}
    assert set(recent) == set(oracle)
    assert snap["stagedSeconds"] == 0
    for sec, orec in oracle.items():
        rec = recent[sec]
        for lane, stages in LANE_STAGES.items():
            counts, sums = orec[lane]
            if not any(sum(row) for row in counts):
                assert lane not in rec["lanes"]
                continue
            for i, name in enumerate(stages):
                cell = rec["lanes"][lane][name]
                assert cell["buckets"] == counts[i], (sec, lane, name)
                assert cell["count"] == sum(counts[i])
                assert cell["sumMs"] == round(sums[i], 4)
                assert cell["p50Ms"] == round(histogram_quantile_edges(
                    counts[i], 0.5, WF_BUCKET_EDGES_MS), 4)
                assert cell["p99Ms"] == round(histogram_quantile_edges(
                    counts[i], 0.99, WF_BUCKET_EDGES_MS), 4)
                assert cell["concurrency"] == round(sums[i] / 1000.0, 4)
        rtt = orec["rtt"]
        assert rec["rtt"]["buckets"] == rtt[0]
        assert rec["rtt"]["count"] == sum(rtt[0])
        assert rec["rtt"]["sumMs"] == round(rtt[1][0], 4)
    # Cumulative == sum over sealed seconds; the eight wire stages
    # telescope, so their summed time IS the summed RTT (float fuzz
    # only — different addition order).
    n_wire = sum(1 for _, k, _ in events if k == "wire")
    assert snap["observedRequests"] == n_wire
    assert snap["rtt"]["count"] == n_wire
    assert snap["reconciliation"]["relativeError"] <= 1e-9


def test_late_observation_after_seal_is_dropped_not_misfiled():
    """An observation stamped into an already-sealed second increments
    ``lateDrops`` and never lands in cumulative (exactness guarantee:
    sealed histograms are immutable)."""
    clock = {"now": BASE_MS}
    wf = WaterfallRecorder(now_ms=lambda: clock["now"])
    wf.observe_wire([1.0] * 8)
    wf.roll(BASE_MS + 2000)
    before = wf.snapshot()["rtt"]["count"]
    clock["now"] = BASE_MS  # stale stamp, second already sealed
    wf.observe_wire([1.0] * 8)
    wf.roll(BASE_MS + 3000)
    snap = wf.snapshot()
    assert snap["lateDrops"] == 1
    assert snap["rtt"]["count"] == before


# -- exemplars ----------------------------------------------------------------


def test_exemplar_retention_slowest_and_cadence():
    """Traced requests emit exemplars: the per-second slowest always
    qualifies, the bounded set keeps the slowest, and the cumulative
    per-RTT-bucket map retains the latest per bucket."""
    clock = {"now": BASE_MS}
    wf = WaterfallRecorder(now_ms=lambda: clock["now"])
    for i in range(10):
        durs = [0.0] * 7 + [float(i + 1)]  # RTT = 1..10ms
        wf.observe_wire(durs, trace_id=f"{i:032x}")
    wf.observe_wire([100.0] * 8)  # untraced: never an exemplar
    wf.roll(BASE_MS + 2000)
    snap = wf.snapshot()
    assert 0 < snap["exemplarsCaptured"] <= 4
    assert snap["exemplars"], "no exemplar retained"
    got = {ex["traceId"] for ex in snap["exemplars"]}
    assert f"{9:032x}" in got  # the slowest traced request
    for ex in snap["exemplars"]:
        assert ex["bucket"] == bucket_index_of(ex["valueMs"])
        assert ex["timestampMs"] == BASE_MS


def test_exemplar_joins_stitched_span_over_loopback():
    """End to end over real sockets: traced wire requests produce RTT
    exemplars whose trace ids resolve to the server span collector's
    stitched traces — the exemplar is a forensic pointer INTO the span
    store, not a free-floating id. Also pins the acceptance
    reconciliation: stage sums == summed RTT for the run."""
    rules = ClusterFlowRuleManager()
    rules.load_rules("default", [st.FlowRule(
        resource="wf-join", count=1e9, cluster_mode=True,
        cluster_config={"flowId": FLOW_ID, "thresholdType": 1})])
    svc = DefaultTokenService(rules)
    svc.request_tokens([(FLOW_ID, 1, False)] * 4)  # absorb compiles
    server = ClusterTokenServer(svc, host="127.0.0.1", port=0).start()
    wf = WaterfallRecorder()  # perf_counter-derived ms timebase
    server.attach_waterfall(wf)
    n = 24
    ctxs = [new_trace_context() for _ in range(n)]
    try:
        with socket.create_connection(
                ("127.0.0.1", server.bound_port), timeout=10) as sock:
            sock.settimeout(10)
            for xid, ctx in enumerate(ctxs, start=1):
                body = codec.encode_flow_request(FLOW_ID, 1, False)
                body = codec.append_trace_tlv(body, ctx.traceparent())
                sock.sendall(codec.encode_request(xid, MSG_FLOW, body))
            reader = codec.FrameReader()
            got = []
            while len(got) < n:
                data = sock.recv(65536)
                assert data, "server closed early"
                got += [codec.decode_response(b) for b in reader.feed(data)]
        assert all(r.status == TokenResultStatus.OK for r in got)
    finally:
        server.stop()
    wf.roll(wf._now_ms() + 2000)  # seal everything observed
    snap = wf.snapshot()
    assert snap["observedRequests"] == n
    assert snap["reconciliation"]["relativeError"] <= 1e-6
    assert snap["exemplars"], "traced requests produced no exemplar"
    trace_ids = {t["traceId"] for t in svc.spans.traces()}
    assert {c.trace_id for c in ctxs} == trace_ids
    for ex in snap["exemplars"]:
        assert ex["traceId"] in trace_ids, "exemplar lost its span join"


# -- regression sentry --------------------------------------------------------


def _sentry_feed(wf, clock, secs, device_ms, per_sec=60):
    for _ in range(secs):
        for _ in range(per_sec):
            wf.observe_wire([0.1, 0.1, 0.1, 0.1, device_ms, 0.1, 0.1, 0.1])
        clock["now"] += 1000
        wf.roll(clock["now"])


def test_sentry_fires_on_breach_and_resolves_on_recovery():
    """Scripted breach: a sustained wire.device budget breach fires the
    60s/5s page pair through the injected sink; sustained recovery
    resolves it. Counting is exact off the sealed histograms with the
    budget snapped up to its log2 edge."""
    transitions = []

    def sink(key, firing, now_ms, fields):
        transitions.append((key, firing, now_ms, dict(fields)))

    clock = {"now": BASE_MS}
    wf = WaterfallRecorder(now_ms=lambda: clock["now"], transition=sink)
    budget = wf.sentry.budgets["wire.device"]
    _sentry_feed(wf, clock, secs=8, device_ms=budget * 4)  # all breaching
    fired = [t for t in transitions if t[1]
             and t[3]["severity"] == "page"
             and t[3]["stage"] == "wire.device"]
    assert fired, "sustained breach never paged"
    assert fired[0][3]["kind"] == "waterfall_budget"
    assert fired[0][3]["resource"] == "waterfall:wire.device"
    assert fired[0][3]["burnLong"] >= 14.4
    # Recovery: long window (60s) must drain below burn threshold.
    transitions.clear()
    _sentry_feed(wf, clock, secs=70, device_ms=0.5)
    page_states = [t[1] for t in transitions
                   if t[3]["severity"] == "page"
                   and t[3]["stage"] == "wire.device"]
    assert page_states and page_states[-1] is False, "breach never resolved"
    burn = wf.sentry.snapshot()["burn"]["wire.device"]
    assert all(not r["firing"] for r in burn)


def test_sentry_respects_min_events_floor():
    """Sparse traffic (below ``sentry.min.events`` per long window)
    never fires, no matter how slow: a regression verdict needs
    evidence, not three unlucky requests."""
    transitions = []
    clock = {"now": BASE_MS}
    wf = WaterfallRecorder(
        now_ms=lambda: clock["now"],
        transition=lambda *a: transitions.append(a))
    budget = wf.sentry.budgets["wire.device"]
    # 5 breaching requests/s * 8s = 40 < the 50-event floor.
    _sentry_feed(wf, clock, secs=8, device_ms=budget * 4, per_sec=5)
    assert not any(firing for _, firing, *_ in transitions)


def test_sentry_alert_lands_in_slo_store(engine):
    """The real sink: a breach fed through ``engine.waterfall`` pages
    via ``SloManager.external_transition`` — same alert store, journal
    stream, and health-score surface as an availability burn."""
    wf = engine.waterfall
    budget = wf.sentry.budgets["wire.device"]
    now = BASE_MS
    for _ in range(8):
        time_util.freeze_time(now)
        for _ in range(60):
            wf.observe_wire([0.1, 0.1, 0.1, 0.1, budget * 4,
                             0.1, 0.1, 0.1])
        now += 1000
        time_util.freeze_time(now)
        engine.slo_refresh(now_ms=now)
    snap = engine.slo.alerts_snapshot()
    assert snap["counters"]["fired"] > 0
    active = [a for a in snap["active"]
              if a.get("kind") == "waterfall_budget"]
    assert active and active[0]["resource"] == "waterfall:wire.device"
    assert "waterfall:wire.device" in engine.slo.health_scores()["resources"]
    # Removing the budget RESOLVES its fired alerts (verify-drive catch:
    # evaluate stops iterating a removed key, so without the explicit
    # resolve in set_budgets the alert would sit active forever).
    wf.sentry.set_budgets({"wire.device": -1})
    snap = engine.slo.alerts_snapshot()
    assert not [a for a in snap["active"]
                if a.get("kind") == "waterfall_budget"]
    assert snap["counters"]["resolved"] > 0


# -- A/B guard: zero device work ----------------------------------------------


def test_waterfall_fold_adds_no_device_work():
    """A/B guard: the same admission stream dispatches the SAME number
    of device programs with the waterfall enabled (folding + sentry
    paging every second) and disabled — the whole subsystem is host
    arithmetic riding the existing per-second spill."""
    from sentinel_tpu.core.config import config
    from tests.test_telemetry import _batch

    def run(enabled):
        from sentinel_tpu.core.context import replace_context

        config.set("csp.sentinel.waterfall.enabled",
                   "true" if enabled else "false")
        replace_context(None)
        eng = st.reset(capacity=256)
        assert eng.waterfall.enabled is enabled
        st.load_flow_rules([st.FlowRule(resource="wfab", count=1e9)])
        budget = eng.waterfall.sentry.budgets["wire.device"]
        now = BASE_MS
        for _ in range(6):
            time_util.freeze_time(now)
            eng._run_entry_batch(_batch(eng, [("wfab", "", None)] * 4))
            for _ in range(60):  # wire stream riding the same seconds
                eng.waterfall.observe_wire(
                    [0.1, 0.1, 0.1, 0.1, budget * 4, 0.1, 0.1, 0.1])
            eng.slo_refresh(now_ms=now)
            now += 1000
        time_util.freeze_time(now)
        eng.slo_refresh(now_ms=now)
        dispatches = {k: v["dispatches"]
                      for k, v in eng.step_timer.snapshot().items()}
        return dispatches, eng.slo.alerts_snapshot()["counters"]["fired"]

    time_util.freeze_time(BASE_MS)
    try:
        on_dispatches, on_fired = run(True)
        off_dispatches, off_fired = run(False)
    finally:
        config.set("csp.sentinel.waterfall.enabled", "true")
        time_util.unfreeze_time()
        st.reset(capacity=512)
    assert on_fired > 0, "the A/B run never exercised the sentry"
    assert off_fired == 0
    assert on_dispatches == off_dispatches


# -- injected-clock inertness (ISSUE 13) --------------------------------------


def test_set_clock_resets_waterfall_timebase(engine):
    """A clock swap (simulator attach) drops staged cells and sealed
    history — stamps of the OLD timebase must never leak into the new
    one — while cumulative counters survive (they are totals, not
    stamps)."""
    wf = engine.waterfall
    wf.observe_wire([1.0] * 8)
    engine.slo_refresh(now_ms=engine.now_ms() + 2000)
    assert wf.snapshot()["sealedSeconds"] == 1
    wf.observe_wire([1.0] * 8)  # staged, unsealed: dropped by the swap
    engine.set_clock(lambda: 5_000_000)
    snap = wf.snapshot()
    assert snap["stagedSeconds"] == 0 and not snap["recent"]
    assert snap["rtt"]["count"] == 1  # SEALED cumulative survives
    # The new timebase records cleanly from zero.
    wf.observe_wire([1.0] * 8)
    wf.roll(5_000_000 + 2000)
    assert wf.snapshot()["recent"][-1]["timestamp"] == 5_000_000


# -- ops command --------------------------------------------------------------


def test_waterfall_command_status_and_budgets(engine):
    """``waterfall`` op=status serves the snapshot; op=budgets merges
    operator overrides (journaled), rejects unknown stages, and <= 0
    removes a budget."""
    import json

    import sentinel_tpu.transport.handlers  # noqa: F401 — registers cmds
    from sentinel_tpu.transport.command_center import (
        CommandRequest,
        get_handler,
    )

    h = get_handler("waterfall")
    assert h is not None
    engine.waterfall.observe_wire([1.0] * 8)
    resp = h(CommandRequest(parameters={"op": "status"}, engine=engine))
    assert resp.success
    snap = json.loads(resp.result)
    assert snap["enabled"] and snap["stages"]["wire"] == list(WIRE_STAGES)
    assert snap["sentry"]["budgetsMs"]

    resp = h(CommandRequest(
        parameters={"op": "budgets",
                    "data": json.dumps({"wire.read": 8.0,
                                        "wire.queue": -1})},
        engine=engine))
    assert resp.success
    budgets = json.loads(resp.result)["budgetsMs"]
    assert budgets["wire.read"] == 8.0 and "wire.queue" not in budgets
    assert engine.journal.tail(kind="waterfallBudgets"), "not journaled"

    resp = h(CommandRequest(
        parameters={"op": "budgets", "data": '{"wire.nope": 5}'},
        engine=engine))
    assert not resp.success

package com.alibaba.csp.sentinel.cluster.client;

import java.util.Collection;

import com.alibaba.csp.sentinel.cluster.TokenResult;
import com.alibaba.csp.sentinel.cluster.TokenServerDescriptor;

/** Vendored signature stub (see vendored/README.md). Reference:
 * core:cluster/client/ClusterTokenClient.java — the SPI
 * FlowRuleChecker/ParamFlowChecker resolve for cluster acquires. */
public interface ClusterTokenClient {

    TokenServerDescriptor currentServer();

    void start();

    void stop();

    int getState();

    TokenResult requestToken(Long flowId, int acquireCount, boolean prioritized);

    TokenResult requestParamToken(Long flowId, int acquireCount,
                                  Collection<Object> params);
}

"""Deterministic chaos campaign engine (ISSUE 15 tentpole).

Tier-1 keeps: one seeded replay-determinism oracle, a small
zero-violation campaign at HEAD, every invariant checker FIRING against
a hand-built violating history (a checker that cannot fail is
decoration), shrinker determinism, the shrinker proof-of-life
(known-fixed bug reintroduced -> caught -> shrunk to <= 3 faults), and
the new seam pins (torn checkpoint write, journal disk-full, datasource
flap). Full multi-episode campaigns are ``slow``-marked per the 870s
tier-1 discipline — the 200-episode acceptance campaign is committed as
BENCH_14.json's ``chaos_campaign`` phase.
"""

from __future__ import annotations

import os

import pytest

from sentinel_tpu.chaos import counters
from sentinel_tpu.chaos.campaign import ChaosCampaign
from sentinel_tpu.chaos.invariants import (
    CHECKERS,
    History,
    check_all,
    check_conservation,
    check_degraded_bound,
    check_epoch_monotone,
    check_journal_monotone,
    check_no_stranded,
    check_overadmission,
    check_shed_not_half_admitted,
)
from sentinel_tpu.chaos.regressions import KNOWN, reintroduce, reintroduced
from sentinel_tpu.chaos.scheduler import FaultScheduler, episode_seed
from sentinel_tpu.chaos.shrink import ddmin

pytestmark = pytest.mark.chaos

THRESHOLDS = {9000: (6.0, 1000), 9001: (6.0, 1000)}
DIVISOR = 2


# -- invariant checkers: every one must FIRE on a violating history ----------


def _clean_history() -> History:
    h = History()
    h.add("offered", op=0, flow=9000, sec=0)
    h.add("grant", op=0, flow=9000, leader="A", win=0)
    h.add("fence", scope=0, epoch=1, accepted=True)
    h.add("verdict", op=0, flow=9000, status="pass", by="A", sec=0)
    h.add("journal", leader="A", seqs=[1, 2, 3])
    return h


def test_clean_history_passes_every_checker():
    assert check_all(_clean_history(), THRESHOLDS, DIVISOR) == []


def test_conservation_checker_fires():
    h = History()
    h.add("offered", op=0, flow=9000, sec=0)
    h.add("offered", op=1, flow=9000, sec=0)
    h.add("verdict", op=0, flow=9000, status="pass", by="A", sec=0)
    # op 1 vanished: offered 2 != terminal 1
    vs = check_conservation(h, THRESHOLDS, DIVISOR)
    assert vs and vs[0].invariant == "conservation"
    # unknown terminal category is a violation too, never a silent bucket
    h2 = History()
    h2.add("offered", op=0, flow=9000, sec=0)
    h2.add("verdict", op=0, flow=9000, status="granted??", by="A", sec=0)
    assert check_conservation(h2, THRESHOLDS, DIVISOR)


def test_no_stranded_checker_fires_on_missing_and_double():
    h = History()
    h.add("offered", op=0, flow=9000, sec=0)
    assert check_no_stranded(h, THRESHOLDS, DIVISOR)  # stranded
    h.add("verdict", op=0, flow=9000, status="pass", by="A", sec=0)
    assert check_no_stranded(h, THRESHOLDS, DIVISOR) == []
    h.add("verdict", op=0, flow=9000, status="dropped", by=None, sec=0)
    vs = check_no_stranded(h, THRESHOLDS, DIVISOR)   # double verdict
    assert vs and "2 terminal" in vs[0].detail


def test_shed_half_admitted_checker_fires():
    h = History()
    h.add("offered", op=0, flow=9000, sec=0)
    h.add("grant", op=0, flow=9000, leader="A", win=0)
    h.add("shedBy", op=0, flow=9000, leader="A")  # shed AND consumed
    h.add("verdict", op=0, flow=9000, status="shed", by="A", sec=0)
    vs = check_shed_not_half_admitted(h, THRESHOLDS, DIVISOR)
    assert vs and vs[0].invariant == "shed_not_half_admitted"
    # a DIFFERENT leader consuming for the op is not the shedder's sin
    h2 = History()
    h2.add("grant", op=0, flow=9000, leader="B", win=0)
    h2.add("shedBy", op=0, flow=9000, leader="A")
    assert check_shed_not_half_admitted(h2, THRESHOLDS, DIVISOR) == []


def test_overadmission_checker_fires_and_respects_margin():
    h = History()
    for i in range(7):  # threshold 6: the 7th grant in one window fires
        h.add("grant", op=i, flow=9000, leader="A", win=1000)
    vs = check_overadmission(h, THRESHOLDS, DIVISOR)
    assert vs and vs[0].invariant == "overadmission"
    # a handoff credits the standing grants as margin: same counts pass
    h2 = History()
    for i in range(4):
        h2.add("grant", op=i, flow=9000, leader="A", win=1000)
    h2.add("transfer", flow=9000, slice=6, frm="A", to="B", win=1000)
    for i in range(4, 10):
        h2.add("grant", op=i, flow=9000, leader="B", win=1000)
    assert check_overadmission(h2, THRESHOLDS, DIVISOR) == []
    # ...but the margin is bounded: exceed threshold + standing and it fires
    h2.add("grant", op=10, flow=9000, leader="B", win=1000)
    assert check_overadmission(h2, THRESHOLDS, DIVISOR)


def test_degraded_bound_checker_fires():
    h = History()
    for i in range(3):  # share = 6 / divisor 2 = 3: the 4th fires
        h.add("degradedGrant", op=i, flow=9000, win=0)
    assert check_degraded_bound(h, THRESHOLDS, DIVISOR) == []
    h.add("degradedGrant", op=3, flow=9000, win=0)
    vs = check_degraded_bound(h, THRESHOLDS, DIVISOR)
    assert vs and vs[0].invariant == "degraded_bound"


def test_epoch_monotone_checker_fires():
    h = History()
    h.add("fence", scope=4, epoch=3, accepted=True)
    h.add("fence", scope=4, epoch=2, accepted=False)  # rejected: fine
    assert check_epoch_monotone(h, THRESHOLDS, DIVISOR) == []
    h.add("fence", scope=4, epoch=2, accepted=True)   # ACCEPTED lower
    vs = check_epoch_monotone(h, THRESHOLDS, DIVISOR)
    assert vs and vs[0].invariant == "epoch_monotone"


def test_journal_monotone_checker_fires():
    h = History()
    h.add("journal", leader="A", seqs=[1, 2, 5, 9])
    assert check_journal_monotone(h, THRESHOLDS, DIVISOR) == []
    h.add("journal", leader="B", seqs=[1, 2, 2])  # seq reuse after restart
    vs = check_journal_monotone(h, THRESHOLDS, DIVISOR)
    assert vs and vs[0].invariant == "journal_monotone"


def test_checker_registry_is_complete():
    assert len(CHECKERS) == 9
    assert {name for name, _ in CHECKERS} == {
        "conservation", "no_stranded", "shed_not_half_admitted",
        "overadmission", "degraded_bound", "epoch_monotone",
        "journal_monotone", "slice_conservation", "slot_conservation"}


# -- scheduler: pure function of (campaign_seed, episode_index) --------------


def test_schedule_is_pure_and_seed_sensitive():
    s = FaultScheduler(seconds=12, max_faults=6)
    a = s.schedule(14, 3)
    assert a == s.schedule(14, 3)           # pure
    assert a != s.schedule(14, 4) or a != s.schedule(15, 3)  # sensitive
    assert episode_seed(14, 3) == episode_seed(14, 3)
    assert episode_seed(14, 3) != episode_seed(14, 4)
    for act in a:
        assert 1 <= act["at"] < 12
        assert act["kind"] in (
            "conn.drop", "conn.stall", "halfopen", "stale.epoch",
            "link.down", "crash", "rebalance", "publish", "torn.publish",
            "ckpt.crash", "journal.full", "journal.restart", "flap",
            "map.split", "zombie", "router.stale", "skew", "overload")


def test_schedule_empty_for_one_second_episodes():
    """A 1-second episode drives only sec 0; schedules fire from sec 1 —
    the scheduler must return an honestly EMPTY schedule, never actions
    the episode loop silently skips (false fault coverage)."""
    assert FaultScheduler(seconds=1).schedule(14, 3) == []
    assert FaultScheduler(seconds=2).schedule(14, 3) != []


def test_initial_assignment_handles_colliding_flow_slices():
    """Two flows hashing into the same slice must place it exactly once
    (every slice one owner), or the scheduler plan and the mesh map
    diverge on the first rebalance."""
    from sentinel_tpu.chaos.mesh import initial_assignment
    from sentinel_tpu.cluster.sharding import slice_of

    flows = {9000: 6.0, 9002: 6.0}           # both hash to slice 6 (N=8)
    assert slice_of(9000, 8) == slice_of(9002, 8)
    assign = initial_assignment(("A", "B", "C"), flows, 8)
    owners = [m for m, sls in assign.items() for s in sls
              if s == slice_of(9000, 8)]
    assert owners == ["A"]                   # placed once, first leader
    all_slices = sorted(s for sls in assign.values() for s in sls)
    assert all_slices == list(range(8))      # total, no double ownership


# -- shrinker: deterministic ddmin -------------------------------------------


def test_ddmin_minimizes_deterministically():
    items = list(range(12))

    def failing(subset):
        # violation iff BOTH 3 and 7 present (a 2-fault interaction)
        return 3 in subset and 7 in subset

    minimal, runs = ddmin(failing, items)
    assert sorted(minimal) == [3, 7]
    again, runs2 = ddmin(failing, items)
    assert again == minimal and runs2 == runs  # bit-deterministic
    single, _ = ddmin(lambda s: 5 in s, items)
    assert single == [5]


# -- the real mesh: replay + zero violations at HEAD -------------------------


def test_episode_replays_bit_identically():
    """Acceptance: re-running any single episode from
    ``(campaign_seed, episode_index)`` reproduces its fault firing
    sequence and verdict-stream hash bit-identically."""
    c = ChaosCampaign(campaign_seed=7, episodes=1, seconds=8, per_second=3)
    a = c.run_episode(0)
    b = c.run_episode(0)
    assert a.verdict_sha256 == b.verdict_sha256
    assert a.fault_sha256 == b.fault_sha256
    assert a.schedule == b.schedule
    assert a.violations == [] and b.violations == []
    assert a.ops == 8 * 3 * 3 and a.ops == b.ops
    assert a.grants == b.grants > 0


def test_small_campaign_zero_violations_at_head():
    before = counters()
    report = ChaosCampaign(campaign_seed=14, episodes=3, seconds=8,
                           per_second=3).run()
    assert report["episodesRun"] == 3
    assert report["violations"] == 0 and report["bundles"] == []
    assert report["ops"] == 3 * 8 * 3 * 3
    after = counters()
    assert after["episodes"] - before["episodes"] == 3
    assert after["faultsFired"] > before["faultsFired"]


@pytest.mark.slow
def test_medium_campaign_zero_violations_at_head():
    report = ChaosCampaign(campaign_seed=14, episodes=25).run()
    assert report["episodesRun"] == 25
    assert report["violations"] == 0


# -- shrinker proof-of-life (acceptance) -------------------------------------


def test_reintroduced_known_bug_is_caught_and_shrunk():
    """A deliberately re-introduced known-fixed bug (degraded mode
    granting full-local amnesty instead of the per-client share) is
    caught by the campaign and shrunk to a minimal schedule of <= 3
    faults — and the shrink is deterministic."""
    assert "degraded-amnesty" in KNOWN and not reintroduced(
        "degraded-amnesty")
    c = ChaosCampaign(campaign_seed=7, episodes=4, seconds=8,
                      per_second=5, stop_on_violation=True)
    with reintroduce("degraded-amnesty"):
        report = c.run()
        assert report["violations"] >= 1
        assert len(report["bundles"]) == 1
        bundle = report["bundles"][0]
        assert {v["invariant"] for v in bundle["violations"]} \
            == {"degraded_bound"}
        assert 1 <= len(bundle["minimalSchedule"]) <= 3
        assert bundle["minimalViolations"]
        # forensic join: every seat's journal tail + causeSeq chain +
        # the shard map in force at the violation second
        for seat in ("A", "B", "C"):
            j = bundle["journal"][seat]
            assert j["lastSeq"] > 0 and j["tail"] and j["chain"]
            assert j["mapInForce"]["kind"] == "shardMapApply"
        # shrink determinism: same episode -> same minimal schedule
        idx = bundle["episode"]
        minimal2, final2, _runs = c.shrink_episode(
            idx, c.episode_schedule(idx))
        assert minimal2 == bundle["minimalSchedule"]
        assert [v.to_dict() for v in final2.violations] \
            == bundle["minimalViolations"]
    # the flag is scoped: outside the block the fixed behavior is back
    assert not reintroduced("degraded-amnesty")
    clean = c.run_episode(idx)
    assert clean.violations == []


# -- new seam pins ------------------------------------------------------------


def test_torn_checkpoint_write_seam(tmp_path, frozen_time):
    from sentinel_tpu.cluster.rules import ClusterFlowRuleManager
    from sentinel_tpu.cluster.token_service import DefaultTokenService
    from sentinel_tpu.core import checkpoint as ckpt
    from sentinel_tpu.models.flow import FlowRule
    from sentinel_tpu.resilience import FaultInjected, FaultInjector

    rules = ClusterFlowRuleManager()
    rules.load_rules("default", [FlowRule(
        resource="r", count=5, cluster_mode=True,
        cluster_config={"flowId": 900, "thresholdType": 0})])
    svc = DefaultTokenService(rules=rules)
    svc.request_token(900)
    path = str(tmp_path / "torn.ck")
    ckpt.save_cluster_checkpoint(svc, path)  # a good file exists
    with FaultInjector(seed=1) as inj:
        # error mode: crash BEFORE the rename — the good file survives
        inj.arm("checkpoint.torn.write", "error", times=1)
        with pytest.raises(FaultInjected):
            ckpt.save_cluster_checkpoint(svc, path)
        svc2 = DefaultTokenService(rules=rules)
        assert ckpt.restore_cluster_checkpoint(svc2, path) == 1
        # garbage mode: the rename PUBLISHES a torn file — restore must
        # reject it as one clear ValueError, never a zip traceback
        inj.arm("checkpoint.torn.write", "garbage", times=1)
        ckpt.save_cluster_checkpoint(svc, path)
        svc3 = DefaultTokenService(rules=rules)
        with pytest.raises(ValueError, match="corrupted or truncated"):
            ckpt.restore_cluster_checkpoint(svc3, path)
    assert not [p for p in os.listdir(tmp_path)
                if p.endswith(".ckpt.tmp")]  # no temp litter either way


def test_journal_disk_full_seam_degrades_then_restart_resumes(tmp_path):
    from sentinel_tpu.resilience import FaultInjector
    from sentinel_tpu.telemetry.journal import ControlPlaneJournal

    path = str(tmp_path / "j.jsonl")
    j = ControlPlaneJournal(lambda: 1000, path=path)
    j.record("ruleLoad", family="flow")
    assert j.stats()["durable"]
    with FaultInjector(seed=1) as inj:
        inj.arm("journal.disk.full", "error", times=1)
        seq = j.record("ruleLoad", family="flow")  # disk full mid-append
        assert seq == 2
    stats = j.stats()
    assert not stats["durable"]            # degraded to the memory tail
    assert stats["lastSeq"] == 2           # which kept recording
    j.close()
    # restart: recovery resumes ABOVE the highest DURABLE seq — the
    # journal-monotonicity invariant across crash/restart
    j2 = ControlPlaneJournal(lambda: 2000, path=path)
    assert j2.record("ruleLoad", family="flow") > 1
    seqs = [r["seq"] for r in j2.replay()]
    assert seqs == sorted(set(seqs))       # strictly monotone durable set
    j2.close()


def test_datasource_flap_seam_backs_off_like_a_failure(frozen_time):
    from sentinel_tpu.datasource.base import AutoRefreshDataSource
    from sentinel_tpu.resilience import FaultInjector

    class _Src(AutoRefreshDataSource):
        def __init__(self):
            super().__init__(converter=lambda s: s,
                             recommend_refresh_ms=100)
            self.reads = 0

        def read_source(self):
            self.reads += 1
            return ["v"]

    src = _Src()
    src.first_load()
    reads_before = src.reads
    with FaultInjector(seed=1) as inj:
        inj.arm("datasource.flap", "error", times=1)
        src._poll_once()                    # the flap: no read happened
        assert src.reads == reads_before
        assert src.consecutive_failures == 1
        src._poll_once()                    # next cadence tick catches up
        assert src.reads == reads_before + 1
        assert src.consecutive_failures == 0


def test_chaos_counters_reach_exporter(engine):
    from sentinel_tpu.telemetry.exporter import render_engine_metrics

    text = render_engine_metrics(engine)
    for family in ("sentinel_tpu_chaos_episodes",
                   "sentinel_tpu_chaos_violations",
                   "sentinel_tpu_chaos_faults_fired",
                   "sentinel_tpu_chaos_shrink_steps"):
        assert family in text

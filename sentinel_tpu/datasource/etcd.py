"""etcd v3 datasource: the gRPC watch protocol (reference:
``sentinel-datasource-etcd``'s ``EtcdDataSource`` — an initial KV get
plus a Watch stream keyed on revisions — SURVEY.md §2.2).

This speaks actual etcd3 gRPC: ``etcdserverpb.KV/Range``, ``KV/Put``
and the bidirectional ``etcdserverpb.Watch/Watch`` stream, with message
schemas (field numbers mirroring etcd's ``rpc.proto`` / ``kv.proto``)
registered at runtime the same way ``envoy_rls/proto.py`` does — the
environment has the protobuf runtime but no protoc codegen. Wire-
compatible with a real etcd server for the subset used.

The connector owns reconnect/backoff and revision bookkeeping: every
(re)connected watch starts at ``last seen revision + 1``, and the fake
(like real etcd) replays the current value when the start revision is
in the past, so updates missed during an outage are recovered. Bad
payloads keep the last good rules; deletes keep the last good rules.

``MiniEtcdServer`` is the in-repo fake (Range/Put/Watch subset over a
real grpcio server); point the datasource at a real etcd and no line of
the connector changes.
"""

from __future__ import annotations

import queue
import threading
from typing import Dict, List, Optional, Tuple

from google.protobuf import descriptor_pb2, descriptor_pool, message_factory

from sentinel_tpu.datasource.base import (
    AbstractDataSource,
    Converter,
    ReconnectingWatchMixin,
    T,
    WritableDataSource,
    _log_warn,
)

_T = descriptor_pb2.FieldDescriptorProto

EVENT_PUT = 0
EVENT_DELETE = 1


def _field(name, number, ftype, label=_T.LABEL_OPTIONAL, type_name=None):
    f = _T(name=name, number=number, type=ftype, label=label)
    if type_name:
        f.type_name = type_name
    return f


def _build_pool() -> descriptor_pool.DescriptorPool:
    pool = descriptor_pool.DescriptorPool()

    kv = descriptor_pb2.FileDescriptorProto(
        name="etcd/mvccpb/kv.proto", package="mvccpb")
    keyvalue = kv.message_type.add(name="KeyValue")
    keyvalue.field.append(_field("key", 1, _T.TYPE_BYTES))
    keyvalue.field.append(_field("create_revision", 2, _T.TYPE_INT64))
    keyvalue.field.append(_field("mod_revision", 3, _T.TYPE_INT64))
    keyvalue.field.append(_field("version", 4, _T.TYPE_INT64))
    keyvalue.field.append(_field("value", 5, _T.TYPE_BYTES))
    keyvalue.field.append(_field("lease", 6, _T.TYPE_INT64))
    event = kv.message_type.add(name="Event")
    etype = event.enum_type.add(name="EventType")
    etype.value.add(name="PUT", number=0)
    etype.value.add(name="DELETE", number=1)
    event.field.append(_field(
        "type", 1, _T.TYPE_ENUM, type_name=".mvccpb.Event.EventType"))
    event.field.append(_field(
        "kv", 2, _T.TYPE_MESSAGE, type_name=".mvccpb.KeyValue"))
    pool.Add(kv)

    rpc = descriptor_pb2.FileDescriptorProto(
        name="etcd/etcdserverpb/rpc.proto", package="etcdserverpb",
        dependency=["etcd/mvccpb/kv.proto"])

    header = rpc.message_type.add(name="ResponseHeader")
    header.field.append(_field("cluster_id", 1, _T.TYPE_UINT64))
    header.field.append(_field("member_id", 2, _T.TYPE_UINT64))
    header.field.append(_field("revision", 3, _T.TYPE_INT64))
    header.field.append(_field("raft_term", 4, _T.TYPE_UINT64))

    rng = rpc.message_type.add(name="RangeRequest")
    rng.field.append(_field("key", 1, _T.TYPE_BYTES))
    rng.field.append(_field("range_end", 2, _T.TYPE_BYTES))
    rng.field.append(_field("limit", 3, _T.TYPE_INT64))
    rng.field.append(_field("revision", 4, _T.TYPE_INT64))

    rngr = rpc.message_type.add(name="RangeResponse")
    rngr.field.append(_field(
        "header", 1, _T.TYPE_MESSAGE, type_name=".etcdserverpb.ResponseHeader"))
    rngr.field.append(_field(
        "kvs", 2, _T.TYPE_MESSAGE, _T.LABEL_REPEATED, ".mvccpb.KeyValue"))
    rngr.field.append(_field("more", 3, _T.TYPE_BOOL))
    rngr.field.append(_field("count", 4, _T.TYPE_INT64))

    put = rpc.message_type.add(name="PutRequest")
    put.field.append(_field("key", 1, _T.TYPE_BYTES))
    put.field.append(_field("value", 2, _T.TYPE_BYTES))

    putr = rpc.message_type.add(name="PutResponse")
    putr.field.append(_field(
        "header", 1, _T.TYPE_MESSAGE, type_name=".etcdserverpb.ResponseHeader"))

    wcreate = rpc.message_type.add(name="WatchCreateRequest")
    wcreate.field.append(_field("key", 1, _T.TYPE_BYTES))
    wcreate.field.append(_field("range_end", 2, _T.TYPE_BYTES))
    wcreate.field.append(_field("start_revision", 3, _T.TYPE_INT64))

    wcancel = rpc.message_type.add(name="WatchCancelRequest")
    wcancel.field.append(_field("watch_id", 1, _T.TYPE_INT64))

    wreq = rpc.message_type.add(name="WatchRequest")
    wreq.field.append(_field(
        "create_request", 1, _T.TYPE_MESSAGE,
        type_name=".etcdserverpb.WatchCreateRequest"))
    wreq.field.append(_field(
        "cancel_request", 2, _T.TYPE_MESSAGE,
        type_name=".etcdserverpb.WatchCancelRequest"))

    wresp = rpc.message_type.add(name="WatchResponse")
    wresp.field.append(_field(
        "header", 1, _T.TYPE_MESSAGE, type_name=".etcdserverpb.ResponseHeader"))
    wresp.field.append(_field("watch_id", 2, _T.TYPE_INT64))
    wresp.field.append(_field("created", 3, _T.TYPE_BOOL))
    wresp.field.append(_field("canceled", 4, _T.TYPE_BOOL))
    wresp.field.append(_field("compact_revision", 5, _T.TYPE_INT64))
    wresp.field.append(_field(
        "events", 11, _T.TYPE_MESSAGE, _T.LABEL_REPEATED, ".mvccpb.Event"))
    pool.Add(rpc)
    return pool


_pool = _build_pool()


def _cls(full_name: str):
    return message_factory.GetMessageClass(
        _pool.FindMessageTypeByName(full_name))


KeyValue = _cls("mvccpb.KeyValue")
Event = _cls("mvccpb.Event")
RangeRequest = _cls("etcdserverpb.RangeRequest")
RangeResponse = _cls("etcdserverpb.RangeResponse")
PutRequest = _cls("etcdserverpb.PutRequest")
PutResponse = _cls("etcdserverpb.PutResponse")
WatchRequest = _cls("etcdserverpb.WatchRequest")
WatchResponse = _cls("etcdserverpb.WatchResponse")

KV_SERVICE = "etcdserverpb.KV"
WATCH_SERVICE = "etcdserverpb.Watch"


class EtcdDataSource(ReconnectingWatchMixin, AbstractDataSource[bytes, T]):
    """Initial Range + revision-keyed Watch stream, with reconnect.

    Revision bookkeeping follows etcd's contract: the header revision of
    the last observed state is remembered, and every (re)created watch
    asks for ``start_revision = seen + 1`` — so an update that landed
    while the watcher was down arrives as the first replayed event (and
    each reconnect's fresh Range read covers even compacted history).
    """

    _watch_thread_name = "sentinel-etcd-watch"

    def __init__(self, endpoint: str, key: str, converter: Converter,
                 reconnect_backoff_ms: Tuple[int, int] = (50, 2000)):
        super().__init__(converter)
        self.endpoint = endpoint
        self.key = key.encode("utf-8") if isinstance(key, str) else key
        self._revision = 0      # last header revision observed
        self._applied: Optional[bytes] = None
        self._channel = None
        self._init_watch(reconnect_backoff_ms)

    # -- plumbing ----------------------------------------------------------

    def _open(self):
        import grpc

        channel = grpc.insecure_channel(self.endpoint)
        range_rpc = channel.unary_unary(
            f"/{KV_SERVICE}/Range",
            request_serializer=RangeRequest.SerializeToString,
            response_deserializer=RangeResponse.FromString)
        watch_rpc = channel.stream_stream(
            f"/{WATCH_SERVICE}/Watch",
            request_serializer=WatchRequest.SerializeToString,
            response_deserializer=WatchResponse.FromString)
        return channel, range_rpc, watch_rpc

    # -- ReadableDataSource ------------------------------------------------

    def read_source(self) -> Optional[bytes]:
        channel, range_rpc, _ = self._open()
        try:
            resp = range_rpc(RangeRequest(key=self.key), timeout=5.0)
            if resp.header.revision > self._revision:
                self._revision = resp.header.revision
            return resp.kvs[0].value if resp.kvs else None
        finally:
            channel.close()

    def start(self) -> "EtcdDataSource":
        try:
            self._apply(self.read_source())
        except Exception as ex:  # grpc.RpcError etc.
            _log_warn("etcd datasource initial load failed: %r", ex)
        self._start_watching()
        return self

    def close(self) -> None:
        self._join_watch()

    def _interrupt_watch(self) -> None:
        channel = self._channel
        if channel is not None:
            # close() aborts the in-flight watch stream, waking the thread.
            channel.close()

    # -- internals ---------------------------------------------------------

    def _apply(self, raw: Optional[bytes]) -> None:
        if raw is None or self._stop.is_set():
            return
        if raw == self._applied:
            return  # replayed catch-up of a value already live
        try:
            value = self.converter(raw.decode("utf-8"))
        except Exception as ex:  # keep last good rules
            _log_warn("etcd datasource bad payload: %r", ex)
            return
        if value is not None:
            self._property.update_value(value)
            self._applied = raw

    def _watch_round(self) -> None:
        """One connect → catch-up Range → watch-until-error cycle.

        ``grpc.RpcError`` is re-raised as ``ConnectionError`` so the
        mixin's exception tuple stays free of the (lazily imported) grpc
        module.
        """
        import grpc

        channel = None
        try:
            channel, range_rpc, watch_rpc = self._open()
            self._channel = channel
            # State-based catch-up BEFORE watching (the Consul/Redis
            # reconnect discipline): a put that landed while the watcher
            # was down — including one the server compacted past, which a
            # start_revision replay can NEVER deliver — is recovered by
            # this read; the watch then covers everything after it.
            cur = range_rpc(RangeRequest(key=self.key), timeout=5.0)
            if cur.header.revision > self._revision:
                self._revision = cur.header.revision
            if cur.kvs:
                self._apply(cur.kvs[0].value)
            create = WatchRequest()
            create.create_request.key = self.key
            create.create_request.start_revision = self._revision + 1
            responses = watch_rpc(iter([create]))
            for resp in responses:
                if self._stop.is_set():
                    return
                if resp.canceled:
                    # e.g. compaction past our start revision — the next
                    # round's Range read re-syncs state.
                    raise ConnectionError(
                        f"watch canceled (compact_revision="
                        f"{resp.compact_revision})")
                if resp.header.revision > self._revision:
                    self._revision = resp.header.revision
                for ev in resp.events:
                    if ev.type == EVENT_PUT:
                        self._apply(ev.kv.value)
                    # DELETE keeps the last good rules (the reference
                    # datasources' stance on removal).
                if resp.created:
                    self._healthy()
            if not self._stop.is_set():
                raise ConnectionError("watch stream ended")
        except grpc.RpcError as ex:
            raise ConnectionError(f"grpc: {ex}") from ex
        finally:
            self._channel = None
            if channel is not None:
                channel.close()


class EtcdWritableDataSource(WritableDataSource[T]):
    """Publish via ``KV/Put`` (the reference writer's shape)."""

    def __init__(self, endpoint: str, key: str, encoder: Converter):
        self.endpoint = endpoint
        self.key = key.encode("utf-8") if isinstance(key, str) else key
        self.encoder = encoder

    def write(self, value: T) -> None:
        import grpc

        channel = grpc.insecure_channel(self.endpoint)
        try:
            put_rpc = channel.unary_unary(
                f"/{KV_SERVICE}/Put",
                request_serializer=PutRequest.SerializeToString,
                response_deserializer=PutResponse.FromString)
            put_rpc(PutRequest(
                key=self.key,
                value=self.encoder(value).encode("utf-8")), timeout=5.0)
        finally:
            channel.close()


# -- in-repo fake server ------------------------------------------------------


class MiniEtcdServer:
    """etcd3 Range/Put/Watch subset over a real grpcio server.

    ``stop()`` + ``start()`` rebinds the same port for reconnect tests;
    the KV store and revision counter survive (a real etcd's raft log
    would too). A watch created with ``start_revision`` at or before the
    watched key's mod_revision replays the current value first — etcd's
    historical-replay contract, which is what makes reconnect lossless.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host, self.port = host, port
        self._kv: Dict[bytes, Tuple[bytes, int, int, int]] = (
            {})  # key -> (value, create_rev, mod_rev, version)
        self._revision = 0
        self._lock = threading.Lock()
        self._watchers: List[Tuple[bytes, "queue.Queue"]] = []
        self._server = None
        self.watch_count = 0  # test hook

    # -- handlers ----------------------------------------------------------

    def _range(self, request, context):
        resp = RangeResponse()
        with self._lock:
            resp.header.revision = self._revision
            entry = self._kv.get(bytes(request.key))
            if entry is not None:
                value, crev, mrev, ver = entry
                kv = resp.kvs.add()
                kv.key = bytes(request.key)
                kv.value = value
                kv.create_revision = crev
                kv.mod_revision = mrev
                kv.version = ver
                resp.count = 1
        return resp

    def _put(self, request, context):
        key, value = bytes(request.key), bytes(request.value)
        with self._lock:
            self._revision += 1
            old = self._kv.get(key)
            crev = old[1] if old else self._revision
            ver = (old[3] + 1) if old else 1
            self._kv[key] = (value, crev, self._revision, ver)
            mrev = self._revision
            watchers = list(self._watchers)
        for wkey, q in watchers:
            if wkey == key:
                q.put((EVENT_PUT, key, value, crev, mrev, ver))
        resp = PutResponse()
        resp.header.revision = mrev
        return resp

    def _watch(self, request_iterator, context):
        create = None
        for req in request_iterator:
            if req.HasField("create_request"):
                create = req.create_request
                break
            if req.HasField("cancel_request"):
                return
        if create is None:
            return
        key = bytes(create.key)
        q: "queue.Queue" = queue.Queue()
        with self._lock:
            self._watchers.append((key, q))
            self.watch_count += 1
            entry = self._kv.get(key)
            rev = self._revision
        try:
            created = WatchResponse()
            created.created = True
            created.header.revision = rev
            yield created
            # Historical replay: a start_revision at or before the
            # current mod_revision means the watcher missed that put.
            if (entry is not None and create.start_revision
                    and create.start_revision <= entry[2]):
                q.put((EVENT_PUT, key, entry[0], entry[1], entry[2],
                       entry[3]))
            while context.is_active():
                try:
                    etype, k, v, crev, mrev, ver = q.get(timeout=0.2)
                except queue.Empty:
                    continue
                resp = WatchResponse()
                resp.header.revision = mrev
                ev = resp.events.add()
                ev.type = etype
                ev.kv.key = k
                ev.kv.value = v
                ev.kv.create_revision = crev
                ev.kv.mod_revision = mrev
                ev.kv.version = ver
                yield resp
        finally:
            with self._lock:
                try:
                    self._watchers.remove((key, q))
                except ValueError:
                    pass

    # -- lifecycle ---------------------------------------------------------

    @property
    def endpoint(self) -> str:
        return f"127.0.0.1:{self.port}"

    def start(self) -> "MiniEtcdServer":
        import concurrent.futures

        import grpc

        server = grpc.server(
            concurrent.futures.ThreadPoolExecutor(max_workers=8))
        server.add_generic_rpc_handlers((
            grpc.method_handlers_generic_handler(KV_SERVICE, {
                "Range": grpc.unary_unary_rpc_method_handler(
                    self._range,
                    request_deserializer=RangeRequest.FromString,
                    response_serializer=RangeResponse.SerializeToString),
                "Put": grpc.unary_unary_rpc_method_handler(
                    self._put,
                    request_deserializer=PutRequest.FromString,
                    response_serializer=PutResponse.SerializeToString),
            }),
            grpc.method_handlers_generic_handler(WATCH_SERVICE, {
                "Watch": grpc.stream_stream_rpc_method_handler(
                    self._watch,
                    request_deserializer=WatchRequest.FromString,
                    response_serializer=WatchResponse.SerializeToString),
            }),
        ))
        bound = server.add_insecure_port(f"{self.host}:{self.port}")
        if self.port == 0:
            self.port = bound  # pin for restarts
        server.start()
        self._server = server
        return self

    def stop(self) -> None:
        if self._server is not None:
            self._server.stop(grace=0.2).wait(timeout=2.0)
            self._server = None
        with self._lock:
            self._watchers.clear()

    def put(self, key: str, value: str) -> None:
        self._put(PutRequest(key=key.encode("utf-8"),
                             value=value.encode("utf-8")), None)

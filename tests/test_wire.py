"""Wire-path reactor tests (ISSUE 11): zero-copy frame scanning, the
coalescing/pipelining reactor's edge cases (partial frames, slow
consumers, mid-harvest connection death), byte-identical wire compat
between the reactor and the legacy thread-per-connection frontend, the
pipelined client, the allocation-free shed paths, and the batched RLS
mode."""

import socket
import threading
import time

import pytest

import sentinel_tpu as st
from sentinel_tpu.cluster import codec
from sentinel_tpu.cluster.client import ClusterTokenClient
from sentinel_tpu.cluster.constants import (
    MSG_ENTRY,
    MSG_EXIT,
    MSG_FLOW,
    MSG_PARAM_FLOW,
    MSG_PING,
    TokenResultStatus,
)
from sentinel_tpu.cluster.rules import ClusterFlowRuleManager
from sentinel_tpu.cluster.server import ClusterTokenServer, _Batcher
from sentinel_tpu.cluster.token_service import DefaultTokenService

FLOW_ID = 8100


def _rules(count=1e9):
    rules = ClusterFlowRuleManager()
    rules.load_rules("default", [st.FlowRule(
        resource="wire-res", count=count, cluster_mode=True,
        cluster_config={"flowId": FLOW_ID, "thresholdType": 1})])
    return rules


def _recv_frames(sock, n, timeout_s=15.0):
    """Read exactly n reply frames; -> (raw_bytes, [Response])."""
    sock.settimeout(timeout_s)
    reader = codec.FrameReader()
    raw = bytearray()
    out = []
    while len(out) < n:
        data = sock.recv(65536)
        if not data:
            break
        raw.extend(data)
        for body in reader.feed(data):
            out.append(codec.decode_response(body))
    return bytes(raw), out


# -- FrameScanner (zero-copy parse) -------------------------------------------


def test_frame_scanner_matches_reader_on_every_split():
    """Differential: FrameScanner == FrameReader over one multi-frame
    byte string split at EVERY boundary into two feeds (the partial-
    frame-across-reads cases), plus byte-by-byte delivery."""
    bodies = [b"a", b"bb" * 7, b"", b"x" * 300, b"tail"]
    stream = b"".join(codec.frame(b) for b in bodies)
    for cut in range(len(stream) + 1):
        scanner = codec.FrameScanner()
        got = [bytes(f) for f in scanner.feed(stream[:cut])]
        got += [bytes(f) for f in scanner.feed(stream[cut:])]
        assert got == bodies, f"split at {cut}"
    scanner = codec.FrameScanner()
    got = []
    for i in range(len(stream)):
        got += [bytes(f) for f in scanner.feed(stream[i:i + 1])]
    assert got == bodies


def test_frame_scanner_whole_frames_are_zero_copy_views():
    """Frames wholly inside a chunk come back as memoryviews ALIASING
    the chunk — no per-frame bytes copy (the FrameReader behavior the
    reactor path replaces)."""
    bodies = [b"hello", b"world" * 10]
    chunk = b"".join(codec.frame(b) for b in bodies)
    frames = codec.FrameScanner().feed(chunk)
    assert [bytes(f) for f in frames] == bodies
    for f in frames:
        assert isinstance(f, memoryview)
        assert f.obj is chunk  # view into the fed chunk, not a copy


# -- reactor edge cases over real sockets -------------------------------------


@pytest.fixture()
def wire_server(frozen_time):
    svc = DefaultTokenService(_rules())
    svc.request_tokens([(FLOW_ID, 1, False)])  # absorb width-1 compile
    server = ClusterTokenServer(svc, host="127.0.0.1", port=0).start()
    assert server.reactor_enabled
    yield server
    server.stop()


def test_partial_frames_split_across_reads(wire_server):
    """A pipelined burst delivered in 3-byte slices (every frame spans
    reads) still answers completely and in order."""
    n = 8
    frames = b"".join(
        codec.encode_request(xid, MSG_FLOW,
                             codec.encode_flow_request(FLOW_ID, 1, False))
        for xid in range(1, n + 1))
    with socket.create_connection(
            ("127.0.0.1", wire_server.bound_port), timeout=10) as sock:
        for i in range(0, len(frames), 3):
            sock.sendall(frames[i:i + 3])
            time.sleep(0.002)
        _raw, resps = _recv_frames(sock, n)
    assert [r.xid for r in resps] == list(range(1, n + 1))
    assert all(r.status == TokenResultStatus.OK for r in resps)


def test_slow_consumer_outbuf_bounded_and_sheds(frozen_time):
    """A client that writes a flood but never reads: the per-connection
    reply backlog stays bounded (reading stops at the bound), requests
    parsed past the bound shed OVERLOADED, and once the client drains,
    every request has exactly one reply."""
    from sentinel_tpu.core.config import config

    config.set("csp.sentinel.wire.outbuf.max.bytes", "4096")
    try:
        svc = DefaultTokenService(_rules())
        svc.request_tokens([(FLOW_ID, 1, False)] * 256)
        server = ClusterTokenServer(svc, host="127.0.0.1", port=0).start()
        try:
            n = 4000
            frames = b"".join(
                codec.encode_request(
                    xid, MSG_FLOW,
                    codec.encode_flow_request(FLOW_ID, 1, False))
                for xid in range(1, n + 1))
            with socket.create_connection(
                    ("127.0.0.1", server.bound_port), timeout=10) as sock:
                sock.sendall(frames)
                deadline = time.monotonic() + 10
                while time.monotonic() < deadline:
                    wire = server.wire_stats()
                    if wire["outbufShed"] > 0:
                        break
                    time.sleep(0.05)
                wire = server.wire_stats()
                assert wire["outbufShed"] > 0, wire
                # Bounded: the backlog never exceeds the configured bound
                # plus one read-chunk's worth of replies.
                reactor = server._reactor
                for conn in list(reactor._conns.values()):
                    assert conn.out_bytes <= 4096 + reactor.read_chunk * 2
                _raw, resps = _recv_frames(sock, n, timeout_s=30.0)
            assert len(resps) == n  # zero silent drops
            statuses = {int(r.status) for r in resps}
            assert statuses <= {int(TokenResultStatus.OK),
                                int(TokenResultStatus.OVERLOADED)}
            assert int(TokenResultStatus.OVERLOADED) in statuses
        finally:
            server.stop()
    finally:
        config.set("csp.sentinel.wire.outbuf.max.bytes", "0")  # -> default


def test_mid_harvest_connection_death_drops_verdict_no_strand(frozen_time):
    """A connection that dies while its fused batch is on the device:
    the verdict is dropped (counted), the reactor keeps serving other
    connections, and nothing strands."""
    svc = DefaultTokenService(_rules())
    svc.request_tokens([(FLOW_ID, 1, False)] * 4)
    real = svc.request_tokens
    svc.request_tokens = lambda reqs, now_ms=None: (
        time.sleep(0.3), real(reqs, now_ms))[1]
    server = ClusterTokenServer(svc, host="127.0.0.1", port=0).start()
    try:
        doomed = socket.create_connection(
            ("127.0.0.1", server.bound_port), timeout=10)
        doomed.sendall(codec.encode_request(
            1, MSG_FLOW, codec.encode_flow_request(FLOW_ID, 1, False)))
        time.sleep(0.05)  # let the request stage + dispatch
        doomed.close()    # dies mid-harvest
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if server.wire_stats()["droppedReplies"] >= 1:
                break
            time.sleep(0.05)
        assert server.wire_stats()["droppedReplies"] >= 1
        # the reactor is still healthy for everyone else
        with socket.create_connection(
                ("127.0.0.1", server.bound_port), timeout=10) as sock:
            sock.sendall(codec.encode_request(
                2, MSG_FLOW, codec.encode_flow_request(FLOW_ID, 1, False)))
            _raw, resps = _recv_frames(sock, 1)
        assert resps and resps[0].status == TokenResultStatus.OK
    finally:
        server.stop()


def test_per_connection_fifo_preserved_across_mixed_types(wire_server):
    """FLOW (harvested off-thread) interleaved with PING (filled
    inline): reply BYTES still leave in request order — the slot ring
    contract (docs/SEMANTICS.md "Coalescing ordering")."""
    msgs = []
    for xid in range(1, 9):
        if xid % 2:
            msgs.append(codec.encode_request(
                xid, MSG_FLOW, codec.encode_flow_request(FLOW_ID, 1, False)))
        else:
            msgs.append(codec.encode_request(
                xid, MSG_PING, codec.encode_ping("default")))
    with socket.create_connection(
            ("127.0.0.1", wire_server.bound_port), timeout=10) as sock:
        sock.sendall(b"".join(msgs))
        _raw, resps = _recv_frames(sock, 8)
    assert [r.xid for r in resps] == list(range(1, 9))


# -- wire compat: reactor <-> legacy byte-identical ---------------------------


def _scripted_replies(engine, reactor: bool, epoch: int):
    """Run the full scripted message sequence against a fresh server on
    the given frontend; -> the raw concatenated reply bytes."""
    svc = DefaultTokenService(_rules(), epoch=epoch)
    svc.request_tokens([(FLOW_ID, 1, False)] * 2)  # absorb width compiles
    svc.request_tokens([(999, 1, False)])
    server = ClusterTokenServer(svc, host="127.0.0.1", port=0,
                                engine=engine, reactor=reactor).start()
    script = [
        codec.encode_request(1, MSG_PING, codec.encode_ping("default")),
        codec.encode_request(2, MSG_FLOW,
                             codec.encode_flow_request(FLOW_ID, 2, False)),
        codec.encode_request(3, MSG_FLOW,
                             codec.encode_flow_request(999, 1, False)),
        codec.encode_request(4, MSG_PARAM_FLOW,
                             codec.encode_param_flow_request(
                                 FLOW_ID, 1, ["k", 7])),
        codec.encode_request(5, MSG_ENTRY, codec.encode_entry_request(
            "wire-compat-res", "origin-a", 1, 0, False)),
        codec.encode_request(6, MSG_EXIT, codec.encode_exit_request(1, False)),
        codec.encode_request(7, MSG_EXIT, codec.encode_exit_request(99, False)),
        codec.encode_request(8, 42, b"junk"),  # unknown type -> BAD_REQUEST
    ]
    try:
        with socket.create_connection(
                ("127.0.0.1", server.bound_port), timeout=15) as sock:
            sock.sendall(b"".join(script))
            raw, resps = _recv_frames(sock, len(script))
        assert len(resps) == len(script)
        return raw
    finally:
        server.stop()


@pytest.mark.parametrize("epoch", [0, 5])
def test_wire_compat_reactor_and_legacy_byte_identical(engine, epoch):
    """THE compat pin: the same scripted request stream (every message
    type, incl. the epoch-TLV-stamped variants) answers byte-for-byte
    identically on the reactor and the legacy thread-per-connection
    frontend — an old client cannot tell the frontends apart."""
    legacy = _scripted_replies(engine, reactor=False, epoch=epoch)
    reactor = _scripted_replies(engine, reactor=True, epoch=epoch)
    assert reactor == legacy


@pytest.mark.parametrize("reactor", [False, True])
def test_new_client_pipelined_against_both_frontends(engine, reactor,
                                                     frozen_time):
    """The pipelined client (new-client half of the compat matrix):
    xid-correlated batch acquires work identically against the legacy
    (old-server) and reactor frontends, epoch fencing included."""
    svc = DefaultTokenService(_rules(), epoch=3)
    svc.request_tokens([(FLOW_ID, 1, False)] * 16)
    server = ClusterTokenServer(svc, host="127.0.0.1", port=0,
                                engine=engine, reactor=reactor).start()
    c = ClusterTokenClient("127.0.0.1", server.bound_port,
                           request_timeout_s=10.0)
    try:
        c.start()
        deadline = time.monotonic() + 5
        while not c.is_connected() and time.monotonic() < deadline:
            time.sleep(0.01)
        out = c.request_tokens_pipelined(
            [(FLOW_ID, 1, False)] * 15 + [(999, 1, False)])
        assert [int(r.status) for r in out[:15]] == [0] * 15
        assert out[15].status == TokenResultStatus.NO_RULE_EXISTS
    finally:
        c.stop()
        server.stop()


def test_pipelined_client_overloaded_semantics(frozen_time):
    """OVERLOADED reaches pipelined callers exactly as it reaches
    per-request callers: status + retry-after, breaker neutral-success
    (the wire round-tripped)."""
    svc = DefaultTokenService(_rules())
    svc.request_tokens([(FLOW_ID, 1, False)])
    server = ClusterTokenServer(svc, host="127.0.0.1", port=0).start()

    def shed(requests, budget=None):
        done = threading.Event()
        box = {"shed_retry_after_ms": 40}
        done.set()
        return done, box

    server.batcher.submit_many = shed
    c = ClusterTokenClient("127.0.0.1", server.bound_port,
                           request_timeout_s=5.0)
    try:
        c.start()
        deadline = time.monotonic() + 5
        while not c.is_connected() and time.monotonic() < deadline:
            time.sleep(0.01)
        out = c.request_tokens_pipelined([(FLOW_ID, 1, False)] * 4)
        assert all(r.status == TokenResultStatus.OVERLOADED for r in out)
        assert all(r.wait_ms == 40 for r in out)
        assert c.health_gate.snapshot()["state"] == "CLOSED"
    finally:
        c.stop()
        server.stop()


# -- allocation-free shed paths + coalescing granularity ----------------------


class _StubService:
    def request_tokens(self, requests, now_ms=None):
        from sentinel_tpu.cluster.token_service import TokenResult

        return [TokenResult(TokenResultStatus.OK, remaining=1)
                for _ in requests]


def test_batcher_shed_paths_allocate_nothing():
    """Submit-time sheds return the SHARED pre-set event + immutable box
    — zero allocations per shed request or group (the ISSUE 11
    allocation-count pin), and admitted groups allocate exactly one
    event+box per GROUP, never per request."""
    b = _Batcher(_StubService(), 0.0, 256, max_queue_groups=10,
                 watermark_pct=20, retry_after_ms=77)
    # not started: submissions park in the queue -> watermark engages
    admitted = [b.submit_many([(FLOW_ID, 1, False)] * 32) for _ in range(2)]
    assert b.groups_allocated == 2  # one pair per 32-request group
    s1 = b.submit_many([(FLOW_ID, 1, False)] * 500)
    s2 = b.submit_many([(FLOW_ID, 1, False)])
    assert s1[0] is s2[0] and s1[1] is s2[1]  # the shared shed pair
    assert s1[0].is_set()
    assert s1[1]["shed_retry_after_ms"] == 77
    assert b.groups_allocated == 2  # sheds allocated nothing
    assert b.shed_requests == 501
    # admitted groups kept their own (distinct) pairs
    assert admitted[0][0] is not admitted[1][0]


def test_reactor_coalesces_connections_into_shared_groups(frozen_time):
    """N pipelined single-connection bursts coalesce into O(cycles)
    fused groups — not one group (nor one Event) per request: the
    per-request wakeup storm the reactor removes."""
    svc = DefaultTokenService(_rules())
    for w in (64, 128, 192, 256):
        svc.request_tokens([(FLOW_ID, 1, False)] * w)
    server = ClusterTokenServer(svc, host="127.0.0.1", port=0).start()
    sizes = []
    orig = server.batcher.submit_many

    def spying(requests, budget=None):
        reqs = list(requests)
        sizes.append(len(reqs))
        return orig(reqs, budget)

    server.batcher.submit_many = spying
    try:
        n_conns, burst = 4, 64
        socks = [socket.create_connection(
            ("127.0.0.1", server.bound_port), timeout=10)
            for _ in range(n_conns)]
        frames = b"".join(
            codec.encode_request(xid, MSG_FLOW,
                                 codec.encode_flow_request(FLOW_ID, 1, False))
            for xid in range(1, burst + 1))
        for s in socks:
            s.sendall(frames)
        for s in socks:
            _raw, resps = _recv_frames(s, burst)
            assert len(resps) == burst
            s.close()
        total = n_conns * burst
        assert sum(sizes) == total
        # far fewer groups than requests: coalescing actually engaged
        assert len(sizes) <= total // 8
        assert server.batcher.groups_allocated <= len(sizes)
    finally:
        server.stop()


# -- batched RLS mode ---------------------------------------------------------


def test_rls_batched_mode_coalesces_descriptor_sets(frozen_time):
    from sentinel_tpu.envoy_rls import (
        EnvoyRlsRule,
        KeyValueResource,
        ResourceDescriptor,
        proto,
    )
    from sentinel_tpu.envoy_rls.service import SentinelEnvoyRlsService

    rls = SentinelEnvoyRlsService(batched=True)
    rls.rules.load_rules([EnvoyRlsRule("web", [ResourceDescriptor(
        [KeyValueResource("path", "/api")], 3)])])
    try:
        codes = []
        for _ in range(5):
            overall, statuses = rls.should_rate_limit(
                "web", [[("path", "/api")]])
            codes.append(overall)
            assert len(statuses) == 1
        assert codes.count(proto.CODE_OK) == 3
        assert codes.count(proto.CODE_OVER_LIMIT) == 2
        assert rls.overload_stats()["batched"] is True
        assert rls.overload_stats()["batcher"]["admittedGroups"] >= 1
    finally:
        rls.close()


# -- telemetry surface --------------------------------------------------------


def test_wire_families_exported(engine):
    from sentinel_tpu.telemetry.exporter import render_engine_metrics

    text = render_engine_metrics(engine)
    assert "sentinel_tpu_wire_connections -1" in text  # not a server
    assert "sentinel_tpu_wire_coalesced_batch" in text
    try:
        engine.cluster.set_to_server(host="127.0.0.1", port=0)
        text = render_engine_metrics(engine)
        assert "sentinel_tpu_wire_connections 0" in text
        wire = engine.resilience_stats()["wire"]
        assert wire is not None and wire["connections"] == 0
    finally:
        engine.cluster.stop()

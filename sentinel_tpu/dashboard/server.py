"""The dashboard web server: discovery + metrics + rule CRUD + cluster ops.

Reference: ``sentinel-dashboard`` (SURVEY.md §2.6) — Spring Boot +
AngularJS there; here a stdlib HTTP server exposing the same capability
set as a small JSON API plus one static page:

  * ``POST /registry/machine``                heartbeat receiver
    (``MachineRegistryController``)
  * ``GET  /app/names.json``                  app list (``AppController``)
  * ``GET  /app/machines.json?app=``          machine list + health
  * ``GET  /v1/rules?app=&type=``             rule CRUD, V1 style: read from
  * ``POST /v1/rules?app=&type=``             the machines, push to ALL
    (``FlowControllerV1`` et al. via ``SentinelApiClient``)
  * ``GET/POST /v2/rules?app=&type=``         rule CRUD, V2 style: through a
    registered config-source provider/publisher pair
    (``FlowControllerV2`` + ``DynamicRuleProvider``/``Publisher``;
    see :meth:`DashboardServer.register_rule_source`)
  * ``GET/POST /gateway/rules?app=``          gateway flow rules, V1 style
  * ``GET/POST /gateway/apis?app=``           custom API groups
    (``GatewayFlowRuleController`` / ``GatewayApiController`` via the
    machines' ``gateway/*`` commands)
  * ``GET  /metric/queryTopResourceMetric.json?app=``    live QPS series
  * ``GET  /metric/queryByAppAndResource.json?app=&identity=``
    (``MetricController`` over ``InMemoryMetricsRepository``)
  * ``GET  /resource/machineResource.json?ip=&port=``    clusterNode proxy
  * ``GET  /rollout/status.json?app=``        staged-rollout state
  * ``GET  /rollout/diff.json?app=``          shadow-vs-live outcome deltas
  * ``GET  /metrics``                         dashboard aggregates as
    OpenMetrics text (fleet view; each engine serves its own /metrics)
  * ``GET  /telemetry/summary.json?app=``     engine telemetry snapshot
  * ``GET  /telemetry/traces.json?app=``      sampled decision traces
    (both proxy the machines' ``telemetry`` / ``traces`` commands)
  * ``GET  /telemetry/stream?app=``           Server-Sent Events: one
    ``event: second`` per new complete flight-recorder second plus one
    ``event: alert`` per SLO/anomaly alert transition (proxies the
    machines' ``timeseries`` + ``alerts`` commands on a ~1s cadence;
    fetch failures surface as ``event: error`` frames, the stream stays
    up; ``Last-Event-ID`` resumes both cursors after a reconnect)
  * ``GET  /adaptive.json?app=``              adaptive-loop state: enabled/
    frozen, in-flight candidate, targets, senses, decision counters
  * ``GET  /alerts.json?app=``                SLO/anomaly alerts: active
    set + transition log (proxies the machines' ``alerts`` command)
  * ``GET  /sim.json?app=``                   trace-replay simulator: last
    policy-lab report / scenario catalog (proxies the ``sim`` command)
  * ``GET  /rebalance.json?app=``             shard rebalancer: freeze state,
    plan history (op=status) or slice-load fold (op=sense)
  * ``GET  /waterfall.json?app=``             wire-to-device latency
    waterfall: per-stage budget, RTT reconciliation, exemplars + sentry
    (proxies the machines' ``waterfall`` command, op=status)
  * ``GET  /population.json?app=``            namespace telescope:
    cardinality, top-k with error bars, churn, slot-budget projection
    (proxies the machines' ``population`` command; op=status/report/
    curve/fleet)
  * ``GET  /fleet.json?app=``                 fleet observability: federated
    per-leader staleness/skew/health + exact fleet series (proxies the
    machines' ``fleet`` command; ``op=series`` for the per-second sums,
    ``op=why&resource=&stampMs=`` routes the forensic ``why`` join,
    ``op=journal`` the audit-journal tail)
  * ``POST /rollout/command?app=&op=``        stage/canary/promote/abort/tick
    (no reference twin — proxies the engines' ``rollout`` command)
  * ``POST /cluster/assign?app=&ip=&port=``   token-server assignment
    (``ClusterConfigController.assign``: chosen machine -> SERVER, every
    other healthy machine -> CLIENT of it)
  * ``GET  /``                                the UI (static/index.html)
  * ``POST /auth/login`` / ``/auth/logout``, ``GET /auth/check``
    (``auth.AuthService``; enabled only when
    ``sentinel.dashboard.auth.username`` is configured)

Rules are owned by the engines (and their writable datasources); the
dashboard holds no rule store — matching the reference's V1 controllers,
whose in-memory repository is a display cache, not a source of truth.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from typing import Dict, Optional

from sentinel_tpu.dashboard.auth import COOKIE_NAME, AuthService
from sentinel_tpu.dashboard.client import ApiError, SentinelApiClient
from sentinel_tpu.dashboard.discovery import AppManagement, MachineInfo
from sentinel_tpu.dashboard.metrics import InMemoryMetricsRepository, MetricFetcher

RULE_TYPES = ("flow", "degrade", "system", "authority", "paramFlow")
_STATIC_DIR = Path(__file__).parent / "static"
# LoginAuthenticationFilter exemptions: login itself, the UI shell, the
# heartbeat receiver (engines are not logged-in browsers), and the
# OpenMetrics endpoint (scrapers are not logged-in browsers either; it
# exposes aggregate numbers only, no rule mutation).
_PUBLIC_PATHS = ("/", "/index.html", "/auth/login", "/auth/check",
                 "/registry/machine", "/metrics")


def _flat_qs(qs: str) -> Dict[str, str]:
    """query-string / form body → first-value-wins flat dict."""
    return {k: v[0] for k, v in urllib.parse.parse_qs(qs).items()}


class DashboardServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 8080,
                 fetch_interval_s: float = 1.0,
                 auth: Optional[AuthService] = None,
                 heartbeat_token: Optional[str] = None):
        from sentinel_tpu.core.config import HEARTBEAT_TOKEN
        from sentinel_tpu.core.config import config as _cfg

        self.host = host
        self.port = port
        self.auth = auth if auth is not None else AuthService()
        # Optional shared secret for /registry/machine (auth-exempt by
        # reference parity): without it, any network peer can register a
        # rogue machine the dashboard will then poll and trust.
        self.heartbeat_token = (
            heartbeat_token if heartbeat_token is not None
            else (_cfg.get(HEARTBEAT_TOKEN, "") or ""))
        self.apps = AppManagement()
        self.api = SentinelApiClient()
        # (app, rule_type) -> (provider, publisher) — the V2 pipeline.
        self.rule_sources: Dict = {}
        self.repository = InMemoryMetricsRepository()
        self.fetcher = MetricFetcher(self.apps, self.repository,
                                     interval_s=fetch_interval_s)
        # SSE (/telemetry/stream): poll cadence against the machines'
        # `timeseries` command, and the live consumer gauge the
        # dashboard /metrics exposition reports.
        self.stream_interval_s = 1.0
        self.sse_clients = 0
        self._sse_lock = threading.Lock()
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def bound_port(self) -> int:
        return self._server.server_address[1] if self._server else self.port

    def start(self, fetch: bool = True) -> "DashboardServer":
        """``fetch=False`` skips the metric poll thread (tests drive
        ``fetcher.fetch_once`` deterministically)."""
        self._server = ThreadingHTTPServer((self.host, self.port), _Handler)
        self._server.dashboard = self
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="sentinel-dashboard",
            daemon=True)
        self._thread.start()
        if fetch:
            self.fetcher.start()
        return self

    def stop(self) -> None:
        self.fetcher.stop()
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    # -- operations (handlers delegate here; also usable programmatically) --

    def register_machine(self, params: Dict[str, str]) -> None:
        self.apps.register(MachineInfo(
            app=params.get("app", "unknown"),
            ip=params.get("ip", "127.0.0.1"),
            port=int(params.get("port", "8719")),
            hostname=params.get("hostname", ""),
            app_type=int(params.get("app_type", "0") or 0),
            version=params.get("v", ""),
            pid=int(params.get("pid", "0") or 0),
        ))

    def _first_healthy(self, app: str) -> MachineInfo:
        ms = self.apps.healthy_machines(app)
        if not ms:
            raise ApiError(f"no healthy machine for app {app!r}")
        return ms[0]

    def register_rule_source(self, app: str, rule_type: str,
                             provider, publisher) -> None:
        """V2 rule pipeline (reference ``FlowControllerV2`` +
        ``DynamicRuleProvider``/``DynamicRulePublisher``): rules for
        (app, type) are read from and published to a CONFIG SOURCE (e.g.
        a broker key the engines' push datasources listen on) instead of
        the machines' command API — the dashboard writes config, engines
        converge via their own datasource bindings.

        ``provider()`` returns the current rule list (dicts);
        ``publisher(rules)`` persists it to the source."""
        if rule_type not in RULE_TYPES:
            raise ValueError(f"invalid rule type {rule_type!r}")
        self.rule_sources[(app, rule_type)] = (provider, publisher)

    def get_rules_v2(self, app: str, rule_type: str):
        src = self.rule_sources.get((app, rule_type))
        if src is None:
            raise ApiError(
                f"no v2 rule source registered for ({app}, {rule_type})")
        return src[0]()

    def set_rules_v2(self, app: str, rule_type: str, rules) -> str:
        src = self.rule_sources.get((app, rule_type))
        if src is None:
            raise ApiError(
                f"no v2 rule source registered for ({app}, {rule_type})")
        src[1](rules)
        return "published"

    def get_rules(self, app: str, rule_type: str):
        m = self._first_healthy(app)
        return self.api.fetch_rules(m.ip, m.port, rule_type)

    def set_rules(self, app: str, rule_type: str, rules) -> Dict[str, bool]:
        """Push wholesale to every healthy machine (V1 publish semantics)."""
        out = {}
        for m in self.apps.healthy_machines(app):
            try:
                self.api.set_rules(m.ip, m.port, rule_type, rules)
                out[m.key] = True
            except ApiError:
                out[m.key] = False
        if not out:
            raise ApiError(f"no healthy machine for app {app!r}")
        return out

    def get_gateway(self, app: str, kind: str):
        m = self._first_healthy(app)
        if kind == "apis":
            return self.api.fetch_api_definitions(m.ip, m.port)
        return self.api.fetch_gateway_rules(m.ip, m.port)

    def set_gateway(self, app: str, kind: str, payload) -> Dict[str, bool]:
        """Wholesale push to every healthy machine (V1 semantics), for
        gateway rules (kind='rules') or custom API groups (kind='apis')."""
        out = {}
        for m in self.apps.healthy_machines(app):
            try:
                if kind == "apis":
                    self.api.set_api_definitions(m.ip, m.port, payload)
                else:
                    self.api.set_gateway_rules(m.ip, m.port, payload)
                out[m.key] = True
            except ApiError:
                out[m.key] = False
        if not out:
            raise ApiError(f"no healthy machine for app {app!r}")
        return out

    def get_rollout(self, app: str, op: str = "status"):
        """Staged-rollout read path (status / shadow-vs-live diff) from
        the first healthy machine — like the V1 rule read path."""
        m = self._first_healthy(app)
        return self.api.fetch_rollout(m.ip, m.port, op)

    def get_adaptive(self, app: str, op: str = "status",
                     since_seq: Optional[int] = None,
                     limit: Optional[int] = None):
        """Adaptive-loop read path (``adaptive`` command status or
        history) from the first healthy machine — the Adaptive panel's
        source. Read-only: enable/freeze/set go through the machines'
        command plane directly (the runbook's drill)."""
        if op not in ("status", "history"):
            raise ValueError(f"unsupported adaptive op {op!r}")
        m = self._first_healthy(app)
        return self.api.fetch_adaptive(m.ip, m.port, op=op,
                                       since_seq=since_seq, limit=limit)

    def get_fleet(self, app: str, op: str = "status",
                  params: Optional[Dict[str, str]] = None):
        """Fleet observability read path: the machines' ``fleet``
        command (status/series), the ``journal`` tail, or the ``why``
        forensic join — one dashboard proxy for the whole plane."""
        m = self._first_healthy(app)
        if op == "journal":
            return self.api.fetch_journal(m.ip, m.port,
                                          params=params or {})
        if op == "why":
            return self.api.fetch_why(m.ip, m.port, params=params or {})
        if op not in ("status", "series", "poll"):
            raise ValueError(f"unsupported fleet op {op!r}")
        return self.api.fetch_fleet(m.ip, m.port, op=op,
                                    params=params or {})

    def get_rebalance(self, app: str, op: str = "status",
                      params: Optional[Dict[str, str]] = None):
        """Rebalancer read path (``rebalance`` command status/sense)
        from the first healthy machine. Read-only: plan/certify/apply/
        rollback are governed actions and go through the machines'
        command plane directly."""
        if op not in ("status", "sense"):
            raise ValueError(f"unsupported rebalance op {op!r}")
        m = self._first_healthy(app)
        return self.api.fetch_rebalance(m.ip, m.port, op=op,
                                        params=params or {})

    def get_waterfall(self, app: str,
                      params: Optional[Dict[str, str]] = None):
        """Latency-waterfall read path (``waterfall`` command,
        op=status) from the first healthy machine — the Waterfall
        panel's source. Read-only: budget overrides and saturation
        probes go through the machines' command plane directly."""
        m = self._first_healthy(app)
        return self.api.fetch_waterfall(m.ip, m.port,
                                        params=params or {})

    def get_population(self, app: str, op: str = "status",
                       params: Optional[Dict[str, str]] = None):
        """Namespace-telescope read path (``population`` command) from
        the first healthy machine — the Namespace population panel's
        source. Read-only ops only (the tracker has no mutating ops)."""
        if op not in ("status", "report", "curve", "fleet"):
            raise ValueError(f"unsupported population op {op!r}")
        m = self._first_healthy(app)
        return self.api.fetch_population(m.ip, m.port, op=op,
                                         params=params or {})

    def get_sim(self, app: str, op: str = "report"):
        """Simulator read path (``sim`` command report/scenarios) from
        the first healthy machine — the Simulator panel's source.
        Read-only: drill replays and lab runs go through the machines'
        command plane / the offline lab directly."""
        if op not in ("report", "scenarios"):
            raise ValueError(f"unsupported sim op {op!r}")
        m = self._first_healthy(app)
        return self.api.fetch_sim(m.ip, m.port, op=op)

    def get_telemetry(self, app: str, kind: str = "summary",
                      limit: Optional[int] = None):
        """Engine telemetry read path: attribution/histogram snapshot
        (kind='summary') or sampled decision traces (kind='traces') from
        the first healthy machine."""
        m = self._first_healthy(app)
        if kind == "traces":
            return self.api.fetch_traces(m.ip, m.port, limit=limit)
        return self.api.fetch_telemetry(m.ip, m.port)

    def rollout_command(self, app: str, params: Dict[str, str],
                        body: str = "") -> Dict:
        """Staged-rollout mutation (load/stage/promote/abort/tick) pushed
        to EVERY healthy machine, V1 publish semantics: each engine runs
        its own shadow/canary/guardrail over its own traffic slice."""
        out = {}
        for m in self.apps.healthy_machines(app):
            try:
                out[m.key] = self.api.rollout_command(m.ip, m.port, params,
                                                      body=body)
            except ApiError as ex:
                out[m.key] = {"error": str(ex)}
        if not out:
            raise ApiError(f"no healthy machine for app {app!r}")
        return out

    def assign_token_server(self, app: str, ip: str, port: int,
                            token_port: int = 0) -> Dict:
        """Reference ``ClusterConfigController`` assign flow: flip the chosen
        machine to SERVER, then point every other healthy machine at it."""
        self.api.modify_cluster_server_config(ip, port, token_port)
        self.api.set_cluster_mode(ip, port, 1)
        bound = self.api.fetch_cluster_server_config(ip, port).get("boundPort")
        if bound is None:
            raise ApiError(
                f"{ip}:{port} flipped to server but reports no bound token port")
        clients = {}
        for m in self.apps.healthy_machines(app):
            if m.ip == ip and m.port == port:
                continue
            try:
                self.api.modify_cluster_client_config(m.ip, m.port, ip, int(bound))
                self.api.set_cluster_mode(m.ip, m.port, 0)
                clients[m.key] = True
            except ApiError:
                clients[m.key] = False
        return {"server": f"{ip}:{port}", "tokenPort": bound, "clients": clients}


class _Handler(BaseHTTPRequestHandler):
    server_version = "sentinel-tpu-dashboard"

    def log_message(self, fmt, *args):
        pass

    # -- plumbing ----------------------------------------------------------

    def _json(self, obj, code: int = 200, headers=()):
        data = json.dumps(obj).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json; charset=utf-8")
        self.send_header("Content-Length", str(len(data)))
        for k, v in headers:
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)

    def _ok(self, result, headers=()):
        # reference dashboard Result<T> envelope: {success, code, msg, data}
        self._json({"success": True, "code": 0, "msg": None, "data": result},
                   headers=headers)

    def _fail(self, msg: str, code: int = 400):
        self._json({"success": False, "code": code, "msg": msg, "data": None},
                   code=code)

    def _text(self, text: str, ctype: str, code: int = 200):
        data = text.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _static(self, name: str):
        path = _STATIC_DIR / name
        if not path.is_file():
            self._fail("not found", 404)
            return
        data = path.read_bytes()
        ctype = "text/html; charset=utf-8" if name.endswith(".html") else \
            "application/javascript" if name.endswith(".js") else "text/css"
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    # -- auth --------------------------------------------------------------

    def _session_token(self) -> Optional[str]:
        authz = self.headers.get("Authorization", "")
        if authz.startswith("Bearer "):
            return authz[len("Bearer "):].strip()
        for part in self.headers.get("Cookie", "").split(";"):
            k, _, v = part.strip().partition("=")
            if k == COOKIE_NAME and v:
                return v
        return None

    def _auth_routes(self, d: DashboardServer, path: str, body: str) -> bool:
        """Handle /auth/*; returns True when the request was consumed."""
        if path == "/auth/login":
            if self.command != "POST":
                self._fail("POST required", 405)
                return True
            form = _flat_qs(body)
            token = d.auth.login(form.get("username", ""),
                                 form.get("password", ""))
            if token is None:
                self._fail("invalid username or password", 401)
            else:
                self._ok({"username": form.get("username", "")},
                         headers=[("Set-Cookie",
                                   f"{COOKIE_NAME}={token}; HttpOnly; "
                                   f"Path=/; SameSite=Strict")])
            return True
        if path == "/auth/logout":
            if self.command != "POST":
                self._fail("POST required", 405)
                return True
            d.auth.logout(self._session_token())
            self._ok("logged out")
            return True
        if path == "/auth/check":
            user = d.auth.validate(self._session_token())
            if user is None and d.auth.enabled:
                self._fail("not logged in", 401)
            else:
                self._ok({"username": user.username if user else "",
                          "authRequired": d.auth.enabled})
            return True
        return False

    # -- routing -----------------------------------------------------------

    def do_GET(self):
        self._route("")

    def do_POST(self):
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length).decode("utf-8") if length else ""
        self._route(body)

    def _route(self, body: str):
        d: DashboardServer = self.server.dashboard
        parsed = urllib.parse.urlparse(self.path)
        path = parsed.path
        q = _flat_qs(parsed.query)
        try:
            if self._auth_routes(d, path, body):
                return
            if d.auth.enabled and path not in _PUBLIC_PATHS \
                    and d.auth.validate(self._session_token()) is None:
                return self._fail("not logged in", 401)
            if path in ("/", "/index.html"):
                return self._static("index.html")
            # Mutating routes are POST-only: a crawler or <img> prefetch must
            # not reassign a cluster or register phantom machines via GET.
            if path in ("/registry/machine", "/cluster/assign") \
                    and self.command != "POST":
                return self._fail("POST required", 405)
            if path == "/registry/machine":
                if d.heartbeat_token:
                    import hmac

                    # Compare as bytes: compare_digest raises TypeError on
                    # non-ASCII str, and header bytes arrive latin-1-decoded.
                    got = self.headers.get("X-Sentinel-Heartbeat-Token", "")
                    if not hmac.compare_digest(
                            got.encode("utf-8"),
                            d.heartbeat_token.encode("utf-8")):
                        return self._fail("bad heartbeat token", 403)
                form = _flat_qs(body)
                form.update(q)
                d.register_machine(form)
                return self._ok("registered")
            if path == "/app/names.json":
                return self._ok(d.apps.app_names())
            if path == "/app/machines.json":
                return self._ok([m.to_dict()
                                 for m in d.apps.machines(q.get("app", ""))])
            if path in ("/gateway/rules", "/gateway/apis"):
                # reference: GatewayFlowRuleController / GatewayApiController
                app = q.get("app", "")
                kind = "apis" if path.endswith("apis") else "rules"
                if self.command == "GET":
                    return self._ok(d.get_gateway(app, kind))
                payload = json.loads(body or "[]")
                if not isinstance(payload, list):
                    return self._fail("expected a JSON list")
                return self._ok(d.set_gateway(app, kind, payload))
            if path in ("/v1/rules", "/v2/rules"):
                app, rtype = q.get("app", ""), q.get("type", "flow")
                if rtype not in RULE_TYPES:
                    return self._fail(f"invalid type {rtype!r}")
                v2 = path == "/v2/rules"
                if self.command == "GET":
                    return self._ok(d.get_rules_v2(app, rtype) if v2
                                    else d.get_rules(app, rtype))
                rules = json.loads(body or "[]")
                if not isinstance(rules, list):
                    return self._fail("expected a JSON list")
                return self._ok(d.set_rules_v2(app, rtype, rules) if v2
                                else d.set_rules(app, rtype, rules))
            if path == "/metric/queryTopResourceMetric.json":
                return self._metric_top(d, q)
            if path == "/metric/queryByAppAndResource.json":
                app = q.get("app", "")
                res = q.get("identity", "")
                start, end = self._range(q)
                return self._ok(d.repository.query(app, res, start, end))
            if path == "/resource/machineResource.json":
                return self._ok(d.api.fetch_cluster_node(
                    q.get("ip", ""), int(q.get("port", "8719"))))
            if path == "/cluster/assign":
                return self._ok(d.assign_token_server(
                    q.get("app", ""), q.get("ip", ""),
                    int(q.get("port", "8719")),
                    int(q.get("tokenPort", "0"))))
            if path in ("/rollout/status.json", "/rollout/diff.json"):
                op = "diff" if path.endswith("diff.json") else "status"
                return self._ok(d.get_rollout(q.get("app", ""), op))
            if path == "/metrics":
                from sentinel_tpu.telemetry.exporter import (
                    render_dashboard_metrics)
                from sentinel_tpu.telemetry.openmetrics import (
                    OPENMETRICS_CONTENT_TYPE)

                return self._text(render_dashboard_metrics(d),
                                  OPENMETRICS_CONTENT_TYPE)
            if path == "/telemetry/stream":
                return self._sse_stream(d, q)
            if path == "/adaptive.json":
                since = q.get("sinceSeq")
                limit = q.get("limit")
                return self._ok(d.get_adaptive(
                    q.get("app", ""), op=q.get("op", "status"),
                    since_seq=int(since) if since else None,
                    limit=int(limit) if limit else None))
            if path == "/sim.json":
                return self._ok(d.get_sim(
                    q.get("app", ""), op=q.get("op", "report")))
            if path == "/fleet.json":
                op = q.get("op", "status")
                params = {k: v for k, v in q.items()
                          if k not in ("app", "op")}
                return self._ok(d.get_fleet(q.get("app", ""), op=op,
                                            params=params))
            if path == "/rebalance.json":
                op = q.get("op", "status")
                params = {k: v for k, v in q.items()
                          if k not in ("app", "op")}
                return self._ok(d.get_rebalance(q.get("app", ""), op=op,
                                                params=params))
            if path == "/waterfall.json":
                params = {k: v for k, v in q.items() if k != "app"}
                return self._ok(d.get_waterfall(q.get("app", ""),
                                                params=params))
            if path == "/population.json":
                op = q.get("op", "status")
                params = {k: v for k, v in q.items()
                          if k not in ("app", "op")}
                return self._ok(d.get_population(q.get("app", ""), op=op,
                                                 params=params))
            if path == "/alerts.json":
                m = d._first_healthy(q.get("app", ""))
                since = q.get("sinceSeq")
                return self._ok(d.api.fetch_alerts(
                    m.ip, m.port,
                    since_seq=int(since) if since else None))
            if path in ("/telemetry/summary.json", "/telemetry/traces.json"):
                kind = "traces" if path.endswith("traces.json") else "summary"
                limit = q.get("limit")
                return self._ok(d.get_telemetry(
                    q.get("app", ""), kind,
                    limit=int(limit) if limit else None))
            if path == "/rollout/command":
                # Mutating: POST-only, like /cluster/assign above.
                if self.command != "POST":
                    return self._fail("POST required", 405)
                params = {k: v for k, v in q.items() if k != "app"}
                return self._ok(d.rollout_command(
                    q.get("app", ""), params, body=body))
            if path == "/cluster/state.json":
                out = []
                for m in d.apps.healthy_machines(q.get("app", "")):
                    try:
                        out.append({**m.to_dict(),
                                    **d.api.fetch_cluster_mode(m.ip, m.port)})
                    except ApiError:
                        pass
                return self._ok(out)
            return self._fail(f"unknown path {path}", 404)
        except ApiError as ex:
            return self._fail(str(ex), 502)
        except (ValueError, KeyError) as ex:
            return self._fail(f"bad request: {ex}")
        except BrokenPipeError:
            pass

    def _sse_stream(self, d: DashboardServer, q):
        """``/telemetry/stream``: Server-Sent Events pushing each new
        complete flight-recorder second of the app's first healthy
        machine (``event: second``, data = the `timeseries` command's
        per-second JSON) plus each SLO/anomaly alert transition
        (``event: alert``, data = one `alerts` command event). A failed
        upstream fetch emits ``event: error`` with a structured body and
        the stream keeps polling — a machine restart mid-stream degrades
        to error frames, not a dropped connection. ``maxEvents=`` closes
        the stream after N data frames (second + alert — bounded
        consumption for tests/tools).

        Resume: every data frame carries ``id: <secondStamp>:<alertSeq>``
        (both cursors, whatever the frame type). A reconnecting
        EventSource replays its ``Last-Event-ID`` header here, and the
        stream resumes from BOTH cursors — the missed complete seconds
        replay from the machine's bounded host history and the missed
        alert transitions from its bounded event log, instead of being
        silently lost across a reconnect."""
        app = q.get("app", "")
        try:
            max_events = int(q.get("maxEvents", "0") or 0)
        except ValueError:
            return self._fail("bad request: maxEvents")
        cursor = None   # newest streamed second stamp (ms)
        alert_seq = 0   # newest streamed alert transition seq
        last_id = (self.headers.get("Last-Event-ID") or "").strip()
        if last_id:
            sec_part, _, seq_part = last_id.partition(":")
            try:
                cursor = int(sec_part) if int(sec_part) > 0 else None
                alert_seq = max(0, int(seq_part or "0"))
            except ValueError:
                cursor, alert_seq = None, 0  # foreign id: fresh stream
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream; charset=utf-8")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Connection", "close")
        self.end_headers()

        def emit(event: str, payload) -> None:
            self.wfile.write(
                f"id: {cursor or 0}:{alert_seq}\nevent: {event}\n"
                f"data: {json.dumps(payload)}\n\n".encode("utf-8"))
            self.wfile.flush()

        with d._sse_lock:
            d.sse_clients += 1
        sent = 0
        try:
            # stop() nulls _server; without this check a connected
            # stream would keep polling engines ~1/s forever after the
            # server is stopped (ThreadingHTTPServer's server_close
            # only closes the LISTENING socket, never handler threads).
            while d._server is not None:
                try:
                    m = d._first_healthy(app)
                    # First poll: only the newest 60 (a fresh consumer
                    # wants recent context, not the whole history).
                    # Cursor polls (including a Last-Event-ID resume):
                    # EVERYTHING after the cursor — a capped catch-up
                    # would silently skip the seconds beyond the cap
                    # while the cursor jumped past them.
                    out = d.api.fetch_timeseries(
                        m.ip, m.port, since_ms=cursor,
                        limit=60 if cursor is None else 1_000_000)
                    for sec in out.get("seconds", []):
                        cursor = max(cursor or 0, int(sec["timestamp"]))
                        emit("second", sec)
                        sent += 1
                        if max_events and sent >= max_events:
                            return
                    alerts = d.api.fetch_alerts(m.ip, m.port,
                                                since_seq=alert_seq)
                    for ev in alerts.get("events", []):
                        alert_seq = max(alert_seq, int(ev["seq"]))
                        emit("alert", ev)
                        sent += 1
                        if max_events and sent >= max_events:
                            return
                except (ApiError, ValueError, KeyError) as ex:
                    # Structured failure INSIDE the stream: consumers see
                    # what broke instead of a silent stall.
                    emit("error", {"error": str(ex)})
                if max_events and sent >= max_events:
                    return
                # Comment frame doubles as the disconnect probe: a gone
                # client surfaces as BrokenPipe here, ending the loop.
                self.wfile.write(b": keepalive\n\n")
                self.wfile.flush()
                time.sleep(d.stream_interval_s)
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            with d._sse_lock:
                d.sse_clients -= 1

    def _range(self, q):
        now = int(time.time() * 1000)
        start = int(q.get("startTime", now - 5 * 60_000))
        end = int(q.get("endTime", now))
        return start, end

    def _metric_top(self, d: DashboardServer, q):
        app = q.get("app", "")
        start, end = self._range(q)
        top = d.repository.top_resources(app, start, end,
                                         int(q.get("pageSize", "30")))
        return self._ok({
            "resource": {r: d.repository.query(app, r, start, end)
                         for r in top},
        })

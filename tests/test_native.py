"""Native shim tests: build the C++ library, then prove wire compatibility
by acquiring tokens from the Python token server through the C client.
"""

import time

import pytest

import sentinel_tpu as st
from sentinel_tpu.cluster.constants import THRESHOLD_GLOBAL, TokenResultStatus
from sentinel_tpu.cluster.rules import ClusterFlowRuleManager
from sentinel_tpu.cluster.server import ClusterTokenServer
from sentinel_tpu.cluster.token_service import DefaultTokenService
from sentinel_tpu.native import NativeTokenClient, load_shim, native_now_ms

pytestmark = pytest.mark.skipif(load_shim() is None,
                                reason="native toolchain unavailable")


@pytest.fixture()
def token_server(frozen_time):
    rules = ClusterFlowRuleManager()
    rules.load_rules("default", [st.FlowRule(
        resource="native-res", count=3, cluster_mode=True,
        cluster_config={"flowId": 4242, "thresholdType": THRESHOLD_GLOBAL})])
    server = ClusterTokenServer(
        DefaultTokenService(rules), host="127.0.0.1", port=0).start()
    yield server
    server.stop()


def test_native_client_acquires_tokens(token_server):
    with NativeTokenClient("127.0.0.1", token_server.bound_port) as client:
        got = [client.request_token(4242).status for _ in range(5)]
    assert got.count(TokenResultStatus.OK) == 3
    assert got.count(TokenResultStatus.BLOCKED) == 2


def test_native_client_unknown_flow(token_server):
    with NativeTokenClient("127.0.0.1", token_server.bound_port) as client:
        assert client.request_token(999).status == TokenResultStatus.NO_RULE_EXISTS


def test_native_client_registers_namespace(token_server):
    with NativeTokenClient("127.0.0.1", token_server.bound_port, "nsZ"):
        deadline = time.time() + 2
        while (token_server.service.connections.connected_count("nsZ") == 0
               and time.time() < deadline):
            time.sleep(0.02)
        assert token_server.service.connections.connected_count("nsZ") == 1


def test_native_connect_failure_raises():
    with pytest.raises((ConnectionError, RuntimeError)):
        NativeTokenClient("127.0.0.1", 1, timeout_ms=300)


def test_native_clock_reasonable():
    now = native_now_ms()
    assert now is not None
    assert abs(now - time.time() * 1000) < 5000


@pytest.fixture()
def param_server(frozen_time):
    """Token server with a THRESHOLD_GLOBAL param rule: 2 tokens/s/value."""
    rules = ClusterFlowRuleManager()
    rules.load_rules("default", [st.FlowRule(
        resource="native-param", count=2, cluster_mode=True,
        cluster_config={"flowId": 7100, "thresholdType": THRESHOLD_GLOBAL})])
    server = ClusterTokenServer(
        DefaultTokenService(rules), host="127.0.0.1", port=0).start()
    yield server
    server.stop()


def test_native_param_token_acquire(param_server):
    """PARAM_FLOW through the C shim: per-value buckets enforced."""
    with NativeTokenClient("127.0.0.1", param_server.bound_port) as client:
        got = [client.request_param_token(7100, 1, ["hotKey"]).status
               for _ in range(4)]
        assert got.count(TokenResultStatus.OK) == 2
        assert got.count(TokenResultStatus.BLOCKED) == 2
        # a different value has its own bucket
        assert client.request_param_token(7100, 1, ["coldKey"]).status \
            == TokenResultStatus.OK
        # unknown flowId -> NO_RULE_EXISTS (client falls back to local)
        assert client.request_param_token(999, 1, ["x"]).status \
            == TokenResultStatus.NO_RULE_EXISTS


def test_native_param_buckets_shared_with_python_client(param_server):
    """Typed wire params hash identically from C and Python, so both
    clients drain the SAME (flowId, value) bucket — incl. int vs str
    distinction (42 and "42" are different buckets in both languages)."""
    from sentinel_tpu.cluster.client import ClusterTokenClient

    py = ClusterTokenClient("127.0.0.1", param_server.bound_port).start()
    try:
        with NativeTokenClient("127.0.0.1", param_server.bound_port) as c:
            assert c.request_param_token(7100, 1, [42]).status \
                == TokenResultStatus.OK
            assert py.request_param_token(7100, 1, [42]).status \
                == TokenResultStatus.OK
            # bucket for int 42 is now full (2/2) from both sides
            assert c.request_param_token(7100, 1, [42]).status \
                == TokenResultStatus.BLOCKED
            assert py.request_param_token(7100, 1, [42]).status \
                == TokenResultStatus.BLOCKED
            # "42" (string) is a distinct typed bucket, still open
            assert c.request_param_token(7100, 1, ["42"]).status \
                == TokenResultStatus.OK
        # mixed types in one request: bool + float + str
        with NativeTokenClient("127.0.0.1", param_server.bound_port) as c:
            assert c.request_param_token(7100, 1, [True, 1.5, "u"]).status \
                == TokenResultStatus.OK
    finally:
        py.stop()

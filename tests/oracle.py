"""Pure-Python serial oracle of the reference sliding window + controllers.

A faithful scalar re-implementation of the reference semantics (LeapArray
lazy rotation, DefaultController, leaky bucket, warm-up token bucket) used
as ground truth in property tests: the device kernels must agree with this
oracle on any event sequence (SURVEY.md §4 takeaways: "device results == a
serial oracle").
"""

from __future__ import annotations

from typing import Dict, List


class OracleLeapArray:
    """Scalar LeapArray: B buckets of ``bucket_ms`` each, lazy reset."""

    def __init__(self, interval_ms: int, buckets: int, channels: int):
        self.interval_ms = interval_ms
        self.buckets = buckets
        self.bucket_ms = interval_ms // buckets
        self.starts = [-interval_ms] * buckets
        self.data = [[0] * channels for _ in range(buckets)]
        self.channels = channels

    def _idx(self, now: int) -> int:
        return (now // self.bucket_ms) % self.buckets

    def _window_start(self, now: int) -> int:
        return now - now % self.bucket_ms

    def current(self, now: int) -> List[int]:
        i = self._idx(now)
        ws = self._window_start(now)
        if self.starts[i] != ws:
            self.data[i] = [0] * self.channels
            self.starts[i] = ws
        return self.data[i]

    def add(self, now: int, channel: int, value: int) -> None:
        self.current(now)[channel] += value

    def total(self, now: int, channel: int) -> int:
        """Sum over non-deprecated buckets (reference ``values()``)."""
        tot = 0
        for b in range(self.buckets):
            exp = self._expected_start(now, b)
            if self.starts[b] == exp:
                tot += self.data[b][channel]
        return tot

    def previous_bucket(self, now: int, channel: int) -> int:
        prev = now - self.bucket_ms
        b = self._idx(prev)
        if self.starts[b] == self._window_start(prev):
            return self.data[b][channel]
        return 0

    def _expected_start(self, now: int, b: int) -> int:
        cur = self._window_start(now)
        offset = (self._idx(now) - b) % self.buckets
        return cur - offset * self.bucket_ms


PASS, BLOCK, EXCEPTION, SUCCESS, RT, OCCUPIED = range(6)


class OracleNode:
    """StatisticNode: 1s/2-bucket + 60s/60-bucket windows + thread gauge."""

    def __init__(self):
        self.w1 = OracleLeapArray(1000, 2, 6)
        self.w60 = OracleLeapArray(60000, 60, 6)
        self.threads = 0

    def add(self, now, channel, value):
        self.w1.add(now, channel, value)
        self.w60.add(now, channel, value)

    def pass_qps(self, now) -> float:
        return self.w1.total(now, PASS)


class OracleFlowChecker:
    """DefaultController over one resource (QPS or thread grade)."""

    def __init__(self, count: float, grade_qps: bool = True):
        self.count = count
        self.grade_qps = grade_qps

    def can_pass(self, node: OracleNode, now: int, acquire: int = 1) -> bool:
        used = node.pass_qps(now) if self.grade_qps else node.threads
        return used + acquire <= self.count


class OracleRateLimiter:
    """RateLimiterController: leaky bucket in µs."""

    def __init__(self, count: float, max_queue_ms: int):
        self.cost_us = int(round(1_000_000.0 / count))
        self.max_queue_us = max_queue_ms * 1000
        self.latest_us = 0

    def try_pass(self, now_ms: int, acquire: int = 1):
        """Returns (ok, wait_us)."""
        now_us = now_ms * 1000
        expected = self.latest_us + acquire * self.cost_us
        if expected <= now_us:
            self.latest_us = now_us
            return True, 0
        wait = expected - now_us
        if wait > self.max_queue_us:
            return False, 0
        self.latest_us += acquire * self.cost_us
        return True, wait

"""SPI / extension mechanism (reference: ``core:init/InitFunc`` +
``@InitOrder`` + ``spi/SpiLoader`` + the ``SlotChainBuilder`` seam that
lets the param-flow module splice ``ParamFlowSlot`` into the chain —
SURVEY.md §2.1 "Init & SPI", §1 L3).

Three extension seams, Python-native:

  * **Init funcs** — ``@init_func(order=...)`` callables (plus anything on
    the ``sentinel_tpu.init_funcs`` entry-point group) run exactly once at
    first engine construction, mirroring ``InitExecutor.doInit`` firing on
    the first ``SphU.entry``.
  * **Host slots** — :class:`ProcessorSlot` objects with ``on_entry`` /
    ``on_exit`` hooks wrapped around every ``engine.entry()`` call.
    ``on_entry`` may raise a ``BlockException`` subclass to reject the
    request; the engine commits the block to statistics (the reference's
    StatisticSlot records custom-slot rejections the same way) before the
    exception reaches the caller. Discovered from the
    ``sentinel_tpu.slots`` entry-point group or registered directly.
  * **Device checkers** — pure JAX functions spliced INTO the fused
    admission step between the param-flow and flow slots (the reference's
    SPI splice point): ``fn(state, rules, batch, now_ms, candidate) ->
    blocked bool[N]``. Registration bumps a version; the engine re-jits
    its step on the next entry, the same recompile semantics as a rule
    push. Verdicts surface as ``BlockReason.CUSTOM`` /
    :class:`~sentinel_tpu.core.exceptions.BlockException`.

The jitted chain can't host arbitrary Python mid-kernel, so the reference's
single linked-slot abstraction splits into the host pair (arbitrary code,
per-entry) and the device seam (pure array code, fused); together they
cover what custom ``ProcessorSlot``s do upstream.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence, Tuple

_lock = threading.RLock()


def _entry_points(group: str):
    try:
        from importlib.metadata import entry_points

        return list(entry_points(group=group))
    except Exception:
        return []


# ---------------------------------------------------------------------------
# Init funcs
# ---------------------------------------------------------------------------

_init_funcs: List[Tuple[int, Callable[[], None]]] = []
_init_done = False
_init_complete = threading.Event()
_init_thread: Optional[threading.Thread] = None


def init_func(order: int = 0):
    """Register a one-shot boot hook (reference: ``@InitOrder`` +
    ``InitFunc``). Runs at first engine construction; registering after
    boot runs the hook immediately (late-loaded extension modules)."""

    def deco(fn: Callable[[], None]):
        with _lock:
            if _init_done:
                fn()
            else:
                _init_funcs.append((order, fn))
        return fn

    return deco


def run_init_funcs() -> None:
    """Idempotent ``InitExecutor.doInit``: entry-point group first, then
    registered funcs, ordered.

    Losers of the boot race WAIT until the winner's hooks finish, so no
    thread can use a half-initialized engine (hooks calling back into this
    module from the boot thread return immediately instead of
    deadlocking).
    """
    global _init_done, _init_thread
    with _lock:
        if _init_done:
            runner = False
        else:
            _init_done = True
            _init_thread = threading.current_thread()
            runner = True
            for ep in _entry_points("sentinel_tpu.init_funcs"):
                try:
                    fn = ep.load()
                    _init_funcs.append((getattr(fn, "__init_order__", 0), fn))
                except Exception:
                    from sentinel_tpu.log.record_log import record_log

                    record_log.warn("init entry point %s failed to load", ep)
            funcs = sorted(_init_funcs, key=lambda t: t[0])
    if not runner:
        if threading.current_thread() is _init_thread:
            return  # re-entrant call from inside an init func
        _init_complete.wait(timeout=60)
        return
    try:
        for _, fn in funcs:
            try:
                fn()
            except Exception as ex:
                from sentinel_tpu.log.record_log import record_log

                record_log.warn("init func %r failed: %r", fn, ex)
    finally:
        _init_complete.set()


def reset_spi_for_tests() -> None:
    global _init_done, _slots_loaded
    with _lock:
        _init_done = False
        _init_complete.clear()
        _init_funcs.clear()
        _slots.clear()
        _slots_loaded = False  # entry-point slots reload like init funcs do
        _rebuild_slot_cache()
        _device_checkers.clear()
        bump_device_version()


# ---------------------------------------------------------------------------
# Host slots
# ---------------------------------------------------------------------------


@dataclass
class EntryInfo:
    """What a host slot sees (reference: the slot-chain arguments)."""

    resource: str
    origin: str
    count: int
    entry_type: int
    prioritized: bool
    args: Sequence
    context_name: str


class ProcessorSlot:
    """Host-side custom slot. Subclass and override either hook."""

    def on_entry(self, info: EntryInfo) -> None:
        """Raise a BlockException subclass to reject the entry."""

    def on_exit(self, info: EntryInfo, rt_ms: int, error: bool) -> None:
        pass


_slots: List[Tuple[int, ProcessorSlot]] = []
_slots_loaded = False
# Immutable snapshot read lock-free on the hot path (GIL-atomic attribute
# read; rebuilt under the lock on every mutation). The common zero-slot
# deployment costs one tuple read per entry/exit, no lock.
_slots_cache: Tuple[ProcessorSlot, ...] = ()


def _rebuild_slot_cache() -> None:
    global _slots_cache
    _slots.sort(key=lambda t: t[0])
    _slots_cache = tuple(s for _, s in _slots)


def register_slot(slot: ProcessorSlot, order: int = 0) -> None:
    with _lock:
        _slots.append((order, slot))
        _rebuild_slot_cache()


def unregister_slot(slot: ProcessorSlot) -> None:
    with _lock:
        _slots[:] = [(o, s) for o, s in _slots if s is not slot]
        _rebuild_slot_cache()


def _load_slot_entry_points() -> None:
    global _slots_loaded
    with _lock:
        if _slots_loaded:
            return
        _slots_loaded = True
        for ep in _entry_points("sentinel_tpu.slots"):
            try:
                slot = ep.load()()
                # order comes from the LOADED slot (EntryPoint objects
                # carry no such attribute), like __init_order__ for inits.
                _slots.append((getattr(slot, "__slot_order__", 0), slot))
            except Exception:
                from sentinel_tpu.log.record_log import record_log

                record_log.warn("slot entry point %s failed to load", ep)
        _rebuild_slot_cache()


def host_slots() -> Tuple[ProcessorSlot, ...]:
    if not _slots_loaded:
        _load_slot_entry_points()
    return _slots_cache


# ---------------------------------------------------------------------------
# Device checkers
# ---------------------------------------------------------------------------

# fn(state, rules, batch, now_ms, candidate) -> blocked bool[N]; must be a
# pure traceable JAX function (it runs inside the fused jitted step).
DeviceChecker = Callable

_device_checkers: List[Tuple[int, str, DeviceChecker]] = []
_device_checkers_cache: Tuple[DeviceChecker, ...] = ()
_device_version = 0


def bump_device_version() -> None:
    global _device_version
    _device_version += 1


def _rebuild_checker_cache() -> None:
    global _device_checkers_cache
    _device_checkers_cache = tuple(fn for _, _, fn in _device_checkers)


def register_device_checker(fn: DeviceChecker, order: int = 0,
                            name: Optional[str] = None) -> None:
    """Splice a pure-JAX verdict into the fused step (before the flow
    slot — the reference's ParamFlowSlot splice point). Engines re-jit on
    their next entry."""
    with _lock:
        _device_checkers.append((order, name or getattr(fn, "__name__", "custom"), fn))
        _device_checkers.sort(key=lambda t: t[0])
        _rebuild_checker_cache()
        bump_device_version()


def unregister_device_checker(fn: DeviceChecker) -> None:
    with _lock:
        _device_checkers[:] = [t for t in _device_checkers if t[2] is not fn]
        _rebuild_checker_cache()
        bump_device_version()


def device_checkers() -> Tuple[DeviceChecker, ...]:
    # Lock-free read of a prebuilt tuple: this sits on the per-entry fast
    # path (engine.entry's fast_ok gate), where the per-call lock+rebuild
    # measured ~2.6µs vs ~0.16µs cached. The tuple swap under ``_lock``
    # on (un)registration is GIL-atomic for readers.
    return _device_checkers_cache


def device_version() -> int:
    with _lock:
        return _device_version

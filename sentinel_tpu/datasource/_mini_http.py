"""Shared plumbing for the HTTP-speaking connectors and their in-repo
fake servers (Nacos / Consul): base-URL normalization and a
``ThreadingHTTPServer`` that can stop and rebind the SAME port, so
reconnect paths are testable against a "restarted" server.
"""

from __future__ import annotations

import socket
import threading
from http.server import ThreadingHTTPServer
from typing import Optional


class JsonResponderMixin:
    """``_send_json`` for fake-server handlers that speak plain JSON
    (mix in ahead of ``BaseHTTPRequestHandler``)."""

    def _send_json(self, code: int, doc) -> None:
        import json

        body = json.dumps(doc).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def normalize_base(addr: str) -> str:
    """``host:port`` or URL → scheme-ful base with no trailing slash."""
    base = addr.rstrip("/")
    if not base.startswith(("http://", "https://")):
        # Full-scheme check: a bare hostname like "httpd-gw:8848" must
        # still get a scheme, or urllib parses "httpd-gw" as one.
        base = "http://" + base
    return base


class RestartableHTTPServer(ThreadingHTTPServer):
    """Fake-server base: background serve thread, condition-variable state
    for long-poll parking, and ``stop()``/``start()`` cycles that rebind
    the same resolved port (pinned in ``server_address`` by the first
    bind) — state held in subclass fields survives, like a real config
    server's backing store would.
    """

    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, host: str, port: int, handler) -> None:
        super().__init__((host, port), handler)
        self._cond = threading.Condition()
        self._stopping = False
        self._thread: Optional[threading.Thread] = None
        self.poll_rounds = 0  # long-poll/blocking-query rounds served

    @property
    def addr(self) -> str:
        return f"http://127.0.0.1:{self.server_address[1]}"

    def start(self) -> "RestartableHTTPServer":
        self._stopping = False
        if self.socket.fileno() == -1:
            # Restart after stop(): fresh socket, same pinned port.
            self.socket = socket.socket(self.address_family,
                                        self.socket_type)
            self.server_bind()
            self.server_activate()
        self._thread = threading.Thread(
            target=self.serve_forever,
            name=f"mini-{type(self).__name__.lower()}", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        with self._cond:
            self._stopping = True
            self._cond.notify_all()  # release parked long-polls promptly
        self.shutdown()
        self.server_close()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

"""Cross-pod namespace sharding tests (SURVEY §2.10) on the virtual
8-device topology arranged as a 2x4 (dcn, ici) mesh: two "pods" of four
devices. Pod-scope cluster rules enforce per-slice quotas; global-scope
rules enforce ONE quota across both pods (the psum's outer reduction is
the DCN hop on real hardware). Host side: the namespace router."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sentinel_tpu.core import constants as C
from sentinel_tpu.core.batch import EntryBatch, make_entry_batch_np
from sentinel_tpu.core.registry import NodeRegistry
from sentinel_tpu.models import authority as A
from sentinel_tpu.models import degrade as D_
from sentinel_tpu.models import flow as F
from sentinel_tpu.models import param_flow as PF
from sentinel_tpu.models import system as Y
from sentinel_tpu.ops import step as S
from sentinel_tpu.parallel import namespaces as NS

NOW0 = 1_700_000_000_000
CAPACITY = 128
SLICES, PER_SLICE = 2, 4
NDEV = SLICES * PER_SLICE


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= NDEV
    return NS.make_dcn_mesh(SLICES, PER_SLICE)


_ENTRY = {}


def _entry_fn(mesh):
    if id(mesh) not in _ENTRY:
        entry, exit_ = NS.make_dcn_pod_steps(mesh)
        _ENTRY[id(mesh)] = (jax.jit(entry), jax.jit(exit_))
    return _ENTRY[id(mesh)][0]


def _exit_fn(mesh):
    _entry_fn(mesh)
    return _ENTRY[id(mesh)][1]


def _build(rules):
    reg = NodeRegistry(CAPACITY)
    row = reg.cluster_row("shared")
    ft, _ = F.compile_flow_rules(rules, reg, CAPACITY)
    dt, di = D_.compile_degrade_rules([], reg, CAPACITY)
    pt = PF.compile_param_rules([], reg, CAPACITY)
    pack = S.RulePack(
        flow=ft, degrade=dt,
        authority=A.compile_authority_rules([], reg, CAPACITY),
        system=Y.compile_system_rules([]),
        param=pt)
    one = S.make_state(CAPACITY, ft.num_rules, NOW0,
                       degrade=D_.make_degrade_state(dt, di),
                       param=PF.make_param_state(pt.num_rules))
    return row, pack, NS.make_dcn_pod_state(SLICES, PER_SLICE, one)


def _batch(row, per_dev):
    buf = make_entry_batch_np(NDEV * per_dev)
    buf["cluster_row"][:] = row
    buf["dn_row"][:] = -1
    buf["count"][:] = 1
    return EntryBatch(**{k: jnp.asarray(v) for k, v in buf.items()})


def _admitted_per_slice(dec, per_dev):
    r = np.asarray(dec.reason).reshape(SLICES, PER_SLICE * per_dev)
    return [(row == C.BlockReason.PASS).sum() for row in r]


def test_pod_scope_rule_is_per_slice(mesh):
    """Default cluster scope: EACH pod enforces the quota independently —
    the sharded-namespace case (a namespace lives on one slice)."""
    thr, per_dev = 6, 3
    row, pack, pod = _build([F.FlowRule(resource="shared", count=thr,
                                        cluster_mode=True)])
    entry = _entry_fn(mesh)
    pod, dec1 = entry(pod, pack, _batch(row, per_dev), jnp.asarray(NOW0, jnp.int64))
    a1 = _admitted_per_slice(dec1, per_dev)
    for a in a1:  # each slice within its own bound, no cross-pod coupling
        assert thr <= a <= thr + (PER_SLICE - 1) * per_dev
    pod, dec2 = entry(pod, pack, _batch(row, per_dev), jnp.asarray(NOW0 + 1, jnp.int64))
    assert _admitted_per_slice(dec2, per_dev) == [0, 0]


def test_global_scope_rule_spans_pods(mesh):
    """scope='global': ONE quota across both pods. Saturate it entirely
    from pod 0; pod 1 must see the usage through the DCN-axis psum."""
    thr = 8
    row, pack, pod = _build([F.FlowRule(
        resource="shared", count=thr, cluster_mode=True,
        cluster_config={"scope": "global"})])
    entry = _entry_fn(mesh)

    per_dev = thr
    buf = make_entry_batch_np(NDEV * per_dev)
    buf["cluster_row"][:] = -1
    buf["cluster_row"][:thr] = row  # device 0 of pod 0 only
    buf["dn_row"][:] = buf["cluster_row"]
    buf["count"][:] = 1
    pod, dec1 = entry(pod, pack,
                      EntryBatch(**{k: jnp.asarray(v) for k, v in buf.items()}),
                      jnp.asarray(NOW0, jnp.int64))
    assert sum(_admitted_per_slice(dec1, per_dev)) == thr

    # Pod 1 (and pod 0) now see the world window as full.
    pod, dec2 = entry(pod, pack, _batch(row, 2), jnp.asarray(NOW0 + 1, jnp.int64))
    assert _admitted_per_slice(dec2, 2) == [0, 0]


def test_global_scope_bounded_overshoot_then_stop(mesh):
    thr, per_dev = 10, 2
    row, pack, pod = _build([F.FlowRule(
        resource="shared", count=thr, cluster_mode=True,
        cluster_config={"scope": "global"})])
    entry = _entry_fn(mesh)
    pod, dec1 = entry(pod, pack, _batch(row, per_dev), jnp.asarray(NOW0, jnp.int64))
    total1 = sum(_admitted_per_slice(dec1, per_dev))
    assert thr <= total1 <= thr + (NDEV - 1) * per_dev
    pod, dec2 = entry(pod, pack, _batch(row, per_dev), jnp.asarray(NOW0 + 1, jnp.int64))
    assert sum(_admitted_per_slice(dec2, per_dev)) == 0


# -- host layer --------------------------------------------------------------


def test_namespace_router_stable_and_pinnable():
    m = NS.NamespaceShardMap(4)
    a = m.slice_of("payments")
    assert a == m.slice_of("payments")  # stable
    assert 0 <= a < 4
    m.pin("payments", 3)
    assert m.slice_of("payments") == 3
    spread = {m.slice_of(f"ns{i}") for i in range(64)}
    assert len(spread) > 1  # hashing actually spreads


def test_namespace_router_fails_over_and_recovers():
    m = NS.NamespaceShardMap(3)
    m.pin("orders", 1)
    m.mark_down(1)
    fallback = m.slice_of("orders")
    assert fallback != 1 and 0 <= fallback < 3
    assert m.slice_of("orders") == fallback  # deterministic failover
    m.mark_up(1)
    assert m.slice_of("orders") == 1  # pinned home restored
    m.mark_down(0)
    m.mark_down(1)
    m.mark_down(2)
    with pytest.raises(RuntimeError):
        m.slice_of("orders")


def test_dcn_exit_step_balances_gauges(mesh):
    """Entries then exits over the 2x4 mesh: every replica's concurrency
    gauge returns to zero (no exit path = permanently blocked THREAD
    rules)."""
    from sentinel_tpu.core.batch import ExitBatch, make_exit_batch_np

    row, pack, pod = _build([F.FlowRule(resource="shared", count=1e9,
                                        cluster_mode=True)])
    entry, exit_ = _entry_fn(mesh), _exit_fn(mesh)
    per_dev = 2
    pod, dec = entry(pod, pack, _batch(row, per_dev),
                     jnp.asarray(NOW0, jnp.int64))
    assert sum(_admitted_per_slice(dec, per_dev)) == NDEV * per_dev
    gauges = np.asarray(pod.cur_threads)[..., row]
    assert (gauges == per_dev).all()

    buf = make_exit_batch_np(NDEV * per_dev)
    buf["cluster_row"][:] = row
    buf["dn_row"][:] = -1
    buf["count"][:] = 1
    buf["success"][:] = True
    pod = exit_(pod, pack,
                ExitBatch(**{k: jnp.asarray(v) for k, v in buf.items()}),
                jnp.asarray(NOW0 + 5, jnp.int64))
    assert (np.asarray(pod.cur_threads)[..., row] == 0).all()

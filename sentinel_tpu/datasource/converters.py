"""JSON rule converters: the reference's wire schema <-> rule dataclasses.

The JSON field names are the reference's (camelCase POJO properties as
serialized by fastjson in the dashboard / datasource demos), so rule files
and dashboard payloads written for the reference parse unchanged.
"""

from __future__ import annotations

import json
from typing import List, Optional

from sentinel_tpu.core import constants as C
from sentinel_tpu.models.authority import AuthorityRule
from sentinel_tpu.models.degrade import DegradeRule
from sentinel_tpu.models.flow import FlowRule
from sentinel_tpu.models.param_flow import ParamFlowItem, ParamFlowRule
from sentinel_tpu.models.system import SystemRule


def _loads(source) -> list:
    if source is None:
        return []
    data = json.loads(source) if isinstance(source, str) else source
    if data is None:
        return []
    if not isinstance(data, list):
        raise ValueError("expected a JSON array of rules")
    return data


# -- staged rollout tags (sentinel_tpu/rollout/) ----------------------------
# Any rule may carry ``candidateSet`` (a named candidate ruleset evaluated
# in shadow lanes instead of enforced) and ``rolloutStage`` ("shadow" |
# "canary" — the initial stage a datasource-tagged candidate starts in).
# Absent fields keep the reference wire schema byte-identical.

def _rollout_fields(d: dict) -> dict:
    out = {}
    cs = d.get("candidateSet")
    if cs:
        out["candidate_set"] = str(cs)
    rs = d.get("rolloutStage")
    if rs:
        out["rollout_stage"] = str(rs)
    return out


def _emit_rollout(d: dict, r) -> dict:
    if getattr(r, "candidate_set", None):
        d["candidateSet"] = r.candidate_set
    if getattr(r, "rollout_stage", None):
        d["rolloutStage"] = r.rollout_stage
    return d


# -- flow -------------------------------------------------------------------

def flow_rule_from_dict(d: dict) -> FlowRule:
    return FlowRule(
        resource=d.get("resource", ""),
        count=float(d.get("count", 0)),
        grade=int(d.get("grade", C.FLOW_GRADE_QPS)),
        limit_app=d.get("limitApp") or C.LIMIT_APP_DEFAULT,
        strategy=int(d.get("strategy", C.FLOW_STRATEGY_DIRECT)),
        ref_resource=d.get("refResource"),
        control_behavior=int(d.get("controlBehavior", C.CONTROL_BEHAVIOR_DEFAULT)),
        warm_up_period_sec=int(d.get("warmUpPeriodSec", 10)),
        max_queueing_time_ms=int(d.get("maxQueueingTimeMs", 500)),
        cluster_mode=bool(d.get("clusterMode", False)),
        cluster_config=d.get("clusterConfig"),
        derived_from=d.get("derivedFrom"),
        **_rollout_fields(d),
    )


def flow_rule_to_dict(r: FlowRule) -> dict:
    d = {
        "resource": r.resource, "limitApp": r.limit_app, "grade": r.grade,
        "count": r.count, "strategy": r.strategy,
        "controlBehavior": r.control_behavior,
        "warmUpPeriodSec": r.warm_up_period_sec,
        "maxQueueingTimeMs": r.max_queueing_time_ms,
        "clusterMode": r.cluster_mode,
    }
    if r.ref_resource:
        d["refResource"] = r.ref_resource
    if r.cluster_config:
        d["clusterConfig"] = r.cluster_config
    if getattr(r, "derived_from", None):
        d["derivedFrom"] = r.derived_from
    return _emit_rollout(d, r)


def flow_rules_from_json(source) -> List[FlowRule]:
    return [flow_rule_from_dict(d) for d in _loads(source)]


def flow_rules_to_json(rules: List[FlowRule]) -> str:
    return json.dumps([flow_rule_to_dict(r) for r in rules])


# -- tps (sentinel_tpu/llm/ — LLM token-budget admission) -------------------
# Fourth rule family: per-(model, tenant) tokens-per-second budgets with
# optional burst headroom and a concurrent-stream cap. Hot-reloadable
# through any datasource exactly like the families above; the engine
# lowers loads onto flow rules (llm/rules.py).

def tps_rule_from_dict(d: dict):
    from sentinel_tpu.llm.rules import TpsRule

    return TpsRule(
        model=d.get("model", ""),
        tokens_per_second=float(d.get("tokensPerSecond", 0)),
        burst_tokens=float(d.get("burstTokens", 0)),
        tenant=d.get("tenant") or C.LIMIT_APP_DEFAULT,
        max_concurrent_streams=int(d.get("maxConcurrentStreams", 0)),
        cluster_mode=bool(d.get("clusterMode", False)),
        cluster_config=d.get("clusterConfig"),
        **_rollout_fields(d),
    )


def tps_rule_to_dict(r) -> dict:
    d = {
        "model": r.model, "tenant": r.tenant,
        "tokensPerSecond": r.tokens_per_second,
        "burstTokens": r.burst_tokens,
        "maxConcurrentStreams": r.max_concurrent_streams,
        "clusterMode": r.cluster_mode,
    }
    if r.cluster_config:
        d["clusterConfig"] = r.cluster_config
    return _emit_rollout(d, r)


def tps_rules_from_json(source) -> list:
    return [tps_rule_from_dict(d) for d in _loads(source)]


def tps_rules_to_json(rules) -> str:
    return json.dumps([tps_rule_to_dict(r) for r in rules])


# -- degrade ----------------------------------------------------------------

def degrade_rule_from_dict(d: dict) -> DegradeRule:
    return DegradeRule(
        resource=d.get("resource", ""),
        count=float(d.get("count", 0)),
        grade=int(d.get("grade", C.DEGRADE_GRADE_RT)),
        time_window=int(d.get("timeWindow", 0)),
        slow_ratio_threshold=float(
            d.get("slowRatioThreshold", C.DEGRADE_DEFAULT_SLOW_RATIO_THRESHOLD)),
        min_request_amount=int(
            d.get("minRequestAmount", C.DEGRADE_DEFAULT_MIN_REQUEST_AMOUNT)),
        stat_interval_ms=int(
            d.get("statIntervalMs", C.DEGRADE_DEFAULT_STAT_INTERVAL_MS)),
        limit_app=d.get("limitApp") or C.LIMIT_APP_DEFAULT,
        **_rollout_fields(d),
    )


def degrade_rule_to_dict(r: DegradeRule) -> dict:
    return _emit_rollout({
        "resource": r.resource, "limitApp": r.limit_app, "grade": r.grade,
        "count": r.count, "timeWindow": r.time_window,
        "slowRatioThreshold": r.slow_ratio_threshold,
        "minRequestAmount": r.min_request_amount,
        "statIntervalMs": r.stat_interval_ms,
    }, r)


def degrade_rules_from_json(source) -> List[DegradeRule]:
    return [degrade_rule_from_dict(d) for d in _loads(source)]


def degrade_rules_to_json(rules: List[DegradeRule]) -> str:
    return json.dumps([degrade_rule_to_dict(r) for r in rules])


# -- system -----------------------------------------------------------------

def system_rule_from_dict(d: dict) -> SystemRule:
    def g(key):
        v = d.get(key, -1)
        return float(v) if v is not None else -1.0

    return SystemRule(
        highest_system_load=g("highestSystemLoad"),
        highest_cpu_usage=g("highestCpuUsage"),
        qps=g("qps"),
        max_thread=g("maxThread"),
        avg_rt=g("avgRt"),
        **_rollout_fields(d),
    )


def system_rule_to_dict(r: SystemRule) -> dict:
    return _emit_rollout({
        "highestSystemLoad": r.highest_system_load,
        "highestCpuUsage": r.highest_cpu_usage,
        "qps": r.qps, "maxThread": r.max_thread, "avgRt": r.avg_rt,
    }, r)


def system_rules_from_json(source) -> List[SystemRule]:
    return [system_rule_from_dict(d) for d in _loads(source)]


def system_rules_to_json(rules: List[SystemRule]) -> str:
    return json.dumps([system_rule_to_dict(r) for r in rules])


# -- authority --------------------------------------------------------------

def authority_rule_from_dict(d: dict) -> AuthorityRule:
    return AuthorityRule(
        resource=d.get("resource", ""),
        limit_app=d.get("limitApp", ""),
        strategy=int(d.get("strategy", C.AUTHORITY_WHITE)),
        **_rollout_fields(d),
    )


def authority_rule_to_dict(r: AuthorityRule) -> dict:
    return _emit_rollout({"resource": r.resource, "limitApp": r.limit_app,
                          "strategy": r.strategy}, r)


def authority_rules_from_json(source) -> List[AuthorityRule]:
    return [authority_rule_from_dict(d) for d in _loads(source)]


def authority_rules_to_json(rules: List[AuthorityRule]) -> str:
    return json.dumps([authority_rule_to_dict(r) for r in rules])


# -- cluster map (cluster/ha.py — datasource-driven leader assignment) ------
#
# The HA analog of the reference's cluster-assign config: one JSON object
# naming the leadership epoch, the ordered token-server seats (leader
# first) and the client membership that sizes the degraded-quota share.
#
#     {"epoch": 3, "namespace": "default",
#      "servers": [{"machineId": "node-a", "host": "10.0.0.1", "port": 18730},
#                  {"machineId": "node-b", "host": "10.0.0.2", "port": 18730}],
#      "clients": ["node-c", "node-d"],
#      "leader": "node-a",            // optional; default servers[0]
#      "requestTimeoutMs": 2000}      // optional
#
# Push it through any datasource with ``cluster_map_from_json`` as the
# converter and hand the property to ``ClusterHAManager.watch``.


def cluster_map_from_dict(d: dict) -> "object":
    from sentinel_tpu.cluster.ha import ClusterMap, ClusterServerSpec

    if not isinstance(d, dict):
        raise ValueError("cluster map must be a JSON object")
    try:
        epoch = int(d.get("epoch", 0))
    except (TypeError, ValueError):
        raise ValueError(f"cluster map epoch {d.get('epoch')!r} not an int")
    raw_servers = d.get("servers")
    if not isinstance(raw_servers, list) or not raw_servers:
        raise ValueError("cluster map needs a non-empty 'servers' list")
    servers = []
    for s in raw_servers:
        if not isinstance(s, dict) or not s.get("machineId") \
                or not s.get("host"):
            raise ValueError(f"bad cluster map server entry: {s!r}")
        try:
            port = int(s["port"])
        except (KeyError, TypeError, ValueError):
            raise ValueError(f"bad cluster map server port in: {s!r}")
        servers.append(ClusterServerSpec(str(s["machineId"]),
                                         str(s["host"]), port))
    leader = d.get("leader")
    if leader:
        ordered = [s for s in servers if s.machine_id == str(leader)]
        if not ordered:
            raise ValueError(
                f"cluster map leader {leader!r} not in the servers list")
        ordered += [s for s in servers if s.machine_id != str(leader)]
        servers = ordered
    raw_clients = d.get("clients") or []
    if not isinstance(raw_clients, (list, tuple)):
        # A bare string would iterate character-wise into a silently
        # wrong degraded-quota divisor — reject like every other field.
        raise ValueError(
            f"cluster map 'clients' must be a list, got {raw_clients!r}")
    clients = tuple(str(c) for c in raw_clients)
    try:
        timeout_ms = int(d.get("requestTimeoutMs", 2000))
    except (TypeError, ValueError):
        timeout_ms = 2000
    return ClusterMap(epoch=epoch, servers=tuple(servers), clients=clients,
                      namespace=str(d.get("namespace") or "default"),
                      request_timeout_ms=max(1, timeout_ms))


def cluster_map_from_json(source) -> "object":
    data = json.loads(source) if isinstance(source, str) else source
    return cluster_map_from_dict(data)


def cluster_map_to_dict(m) -> dict:
    return {
        "epoch": m.epoch,
        "namespace": m.namespace,
        "servers": [{"machineId": s.machine_id, "host": s.host,
                     "port": s.port} for s in m.servers],
        "clients": list(m.clients),
        "requestTimeoutMs": m.request_timeout_ms,
    }


# -- shard maps (cluster/sharding.py — ISSUE 12 sharded multi-leader) ------
#
#     {"version": 4, "nSlices": 64, "namespace": "default",
#      "servers": [{"machineId": "a", "host": "10.0.0.1", "port": 18730},
#                  {"machineId": "b", "host": "10.0.0.2", "port": 18730}],
#      "sliceOwners": {"a": [0, 1, ...], "b": [32, 33, ...]},
#      "sliceEpochs": {"0": 4, "32": 7},   // optional; absent -> version
#      "clients": ["node-c"],
#      "requestTimeoutMs": 2000}
#
# ``sliceOwners`` must cover every slice exactly once; ``sliceEpochs``
# defaults each slice's fencing term to the map version (correct but
# coarse — a rebalance SHOULD bump only the moved slices' epochs so
# standing leaders' in-flight replies stay honest). Push through any
# datasource with ``shard_map_from_json`` and hand the property to
# ``ClusterHAManager.watch`` — apply_map dispatches on the map type.


def shard_map_from_dict(d: dict) -> "object":
    from sentinel_tpu.cluster.ha import ClusterServerSpec
    from sentinel_tpu.cluster.sharding import ShardMap
    from sentinel_tpu.core.config import config as _cfg

    if not isinstance(d, dict):
        raise ValueError("shard map must be a JSON object")
    try:
        version = int(d.get("version", 0))
    except (TypeError, ValueError):
        raise ValueError(f"shard map version {d.get('version')!r} not an int")
    try:
        n_slices = int(d.get("nSlices", _cfg.cluster_shard_slices()))
    except (TypeError, ValueError):
        raise ValueError(f"shard map nSlices {d.get('nSlices')!r} not an int")
    if n_slices <= 0:
        raise ValueError(f"shard map nSlices must be positive: {n_slices}")
    raw_servers = d.get("servers")
    if not isinstance(raw_servers, list) or not raw_servers:
        raise ValueError("shard map needs a non-empty 'servers' list")
    servers = []
    for s in raw_servers:
        if not isinstance(s, dict) or not s.get("machineId") \
                or not s.get("host"):
            raise ValueError(f"bad shard map server entry: {s!r}")
        try:
            port = int(s["port"])
        except (KeyError, TypeError, ValueError):
            raise ValueError(f"bad shard map server port in: {s!r}")
        servers.append(ClusterServerSpec(str(s["machineId"]),
                                         str(s["host"]), port))
    known = {s.machine_id for s in servers}
    raw_owners = d.get("sliceOwners")
    owner = [None] * n_slices
    if isinstance(raw_owners, dict):
        for mid, slist in raw_owners.items():
            if str(mid) not in known:
                raise ValueError(
                    f"sliceOwners names unknown server {mid!r}")
            if not isinstance(slist, (list, tuple)):
                raise ValueError(
                    f"sliceOwners[{mid!r}] must be a list of slice ids")
            for sl in slist:
                try:
                    sl = int(sl)
                except (TypeError, ValueError):
                    raise ValueError(f"bad slice id {sl!r} for {mid!r}")
                if not 0 <= sl < n_slices:
                    raise ValueError(
                        f"slice {sl} out of ring [0, {n_slices})")
                if owner[sl] is not None:
                    raise ValueError(f"slice {sl} assigned twice")
                owner[sl] = str(mid)
    elif isinstance(raw_owners, list):
        if len(raw_owners) != n_slices:
            raise ValueError(
                f"sliceOwners list has {len(raw_owners)} entries, "
                f"ring has {n_slices}")
        for sl, mid in enumerate(raw_owners):
            if str(mid) not in known:
                raise ValueError(
                    f"sliceOwners[{sl}] names unknown server {mid!r}")
            owner[sl] = str(mid)
    else:
        raise ValueError("shard map needs 'sliceOwners' (dict or list)")
    missing = [i for i, m in enumerate(owner) if m is None]
    if missing:
        raise ValueError(
            f"{len(missing)} slice(s) unowned (first: {missing[:5]}) — "
            "every slice needs exactly one owner")
    raw_epochs = d.get("sliceEpochs")
    epochs = [version] * n_slices
    if raw_epochs is not None:
        if isinstance(raw_epochs, dict):
            for sl, ep in raw_epochs.items():
                try:
                    sl, ep = int(sl), int(ep)
                except (TypeError, ValueError):
                    raise ValueError(
                        f"bad sliceEpochs entry {sl!r}: {ep!r}")
                if not 0 <= sl < n_slices:
                    raise ValueError(
                        f"sliceEpochs slice {sl} out of ring [0, {n_slices})")
                epochs[sl] = ep
        elif isinstance(raw_epochs, list):
            if len(raw_epochs) != n_slices:
                raise ValueError(
                    f"sliceEpochs list has {len(raw_epochs)} entries, "
                    f"ring has {n_slices}")
            try:
                epochs = [int(e) for e in raw_epochs]
            except (TypeError, ValueError):
                raise ValueError("sliceEpochs entries must be ints")
        else:
            raise ValueError("'sliceEpochs' must be a dict or list")
    raw_clients = d.get("clients") or []
    if not isinstance(raw_clients, (list, tuple)):
        raise ValueError(
            f"shard map 'clients' must be a list, got {raw_clients!r}")
    try:
        timeout_ms = int(d.get("requestTimeoutMs", 2000))
    except (TypeError, ValueError):
        timeout_ms = 2000
    return ShardMap(
        version=version, n_slices=n_slices, servers=tuple(servers),
        slice_owner=tuple(owner), slice_epoch=tuple(epochs),
        clients=tuple(str(c) for c in raw_clients),
        namespace=str(d.get("namespace") or "default"),
        request_timeout_ms=max(1, timeout_ms))


def shard_map_from_json(source) -> "object":
    data = json.loads(source) if isinstance(source, str) else source
    return shard_map_from_dict(data)


def shard_map_to_dict(m) -> dict:
    owners: dict = {}
    for sl, mid in enumerate(m.slice_owner):
        owners.setdefault(mid, []).append(sl)
    return {
        "version": m.version,
        "nSlices": m.n_slices,
        "namespace": m.namespace,
        "servers": [{"machineId": s.machine_id, "host": s.host,
                     "port": s.port} for s in m.servers],
        "sliceOwners": owners,
        "sliceEpochs": {str(i): int(e)
                        for i, e in enumerate(m.slice_epoch)},
        "clients": list(m.clients),
        "requestTimeoutMs": m.request_timeout_ms,
    }


def any_cluster_map_from_json(source) -> "object":
    """Converter accepting EITHER map flavor (the standalone
    participant's file watcher): a ``sliceOwners`` key selects the
    shard-map schema, anything else parses as a plain cluster map."""
    data = json.loads(source) if isinstance(source, str) else source
    if isinstance(data, dict) and "sliceOwners" in data:
        return shard_map_from_dict(data)
    return cluster_map_from_dict(data)


# -- SLO objectives (sentinel_tpu/slo/ — datasource-driven judgement) -------
#
# The ``sloRules`` converter: one JSON array of objective objects, pushed
# through any datasource (file/Redis/HTTP/push) with
# ``slo_objectives_from_json`` as the converter and
# ``engine.slo.load_objectives`` as the sink, so objectives hot-reload
# exactly like flow rules. Absent fields take the shipped defaults
# (docs/OPERATIONS.md "SLOs & alerting" has the full schema + window
# table):
#
#     [{"resource": "getUser", "sli": "availability", "objective": 0.999,
#       "minEvents": 10,
#       "windows": [{"longSeconds": 60, "shortSeconds": 5,
#                    "burnRate": 14.4, "severity": "page"},
#                   {"longSeconds": 300, "shortSeconds": 60,
#                    "burnRate": 6, "severity": "ticket"}]},
#      {"resource": "getUser", "sli": "latency", "objective": 0.99,
#       "latencyMs": 64, "name": "getUser-rt"}]


def slo_objective_from_dict(d: dict) -> "object":
    from sentinel_tpu.slo.objectives import (
        BurnWindow, DEFAULT_BURN_WINDOWS, DEFAULT_MIN_EVENTS, SloObjective)

    if not isinstance(d, dict):
        raise ValueError(f"SLO objective must be a JSON object, got {d!r}")
    raw_windows = d.get("windows")
    if raw_windows is None:
        windows = DEFAULT_BURN_WINDOWS
    else:
        if not isinstance(raw_windows, list) or not raw_windows:
            raise ValueError(
                f"'windows' must be a non-empty list, got {raw_windows!r}")
        windows = tuple(
            BurnWindow(
                long_s=int(w.get("longSeconds", 0)),
                short_s=int(w.get("shortSeconds", 0)),
                burn=float(w.get("burnRate", 0)),
                severity=str(w.get("severity", "page")),
            )
            for w in raw_windows
        )
    return SloObjective(
        resource=str(d.get("resource", "")),
        sli=str(d.get("sli", "availability")),
        objective=float(d.get("objective", 0.99)),
        latency_ms=int(d.get("latencyMs", 256)),
        min_events=int(d.get("minEvents", DEFAULT_MIN_EVENTS)),
        windows=windows,
        name=str(d.get("name", "")),
    ).validate()


def slo_objective_to_dict(o) -> dict:
    d = {
        "resource": o.resource,
        "sli": o.sli,
        "objective": o.objective,
        "minEvents": o.min_events,
        "windows": [{"longSeconds": w.long_s, "shortSeconds": w.short_s,
                     "burnRate": w.burn, "severity": w.severity}
                    for w in o.windows],
    }
    if o.sli == "latency":
        d["latencyMs"] = o.latency_ms
        # What the RT histogram actually enforces (log2 bucket edges).
        d["effectiveLatencyMs"] = o.snapped_latency_ms
    if o.name:
        d["name"] = o.name
    return d


def slo_objectives_from_json(source) -> List["object"]:
    return [slo_objective_from_dict(d) for d in _loads(source)]


def slo_objectives_to_json(objectives) -> str:
    return json.dumps([slo_objective_to_dict(o) for o in objectives])


# -- adaptive targets (sentinel_tpu/adaptive/ — closed-loop limiting) -------
#
# The ``adaptiveTargets`` converter: one JSON array of target objects,
# pushed through any datasource with ``adaptive_targets_from_json`` as
# the converter and ``engine.adaptive.load_targets`` as the sink (the
# ``adaptive`` command's ``op=set`` shares the schema). Absent fields
# take the dataclass defaults (docs/OPERATIONS.md "Adaptive limiting"):
#
#     [{"resource": "getUser", "maxBlockRate": 0.05, "rtP99Ms": 250,
#       "floor": 50, "ceiling": 5000, "minEntries": 32}]


def adaptive_target_from_dict(d: dict) -> "object":
    from sentinel_tpu.adaptive.controller import (
        DEFAULT_MIN_ENTRIES, AdaptiveTarget)

    if not isinstance(d, dict):
        raise ValueError(f"adaptive target must be a JSON object, got {d!r}")
    defaults = AdaptiveTarget(resource="_")
    return AdaptiveTarget(
        resource=str(d.get("resource", "")),
        max_block_rate=float(d.get("maxBlockRate",
                                   defaults.max_block_rate)),
        rt_p99_ms=float(d.get("rtP99Ms", defaults.rt_p99_ms)),
        floor=float(d.get("floor", defaults.floor)),
        ceiling=float(d.get("ceiling", defaults.ceiling)),
        min_entries=int(d.get("minEntries", DEFAULT_MIN_ENTRIES)),
    ).validate()


def adaptive_target_to_dict(t) -> dict:
    return {
        "resource": t.resource,
        "maxBlockRate": t.max_block_rate,
        "rtP99Ms": t.rt_p99_ms,
        "floor": t.floor,
        "ceiling": t.ceiling,
        "minEntries": t.min_entries,
    }


def adaptive_targets_from_json(source) -> List["object"]:
    return [adaptive_target_from_dict(d) for d in _loads(source)]


def adaptive_targets_to_json(targets) -> str:
    return json.dumps([adaptive_target_to_dict(t) for t in targets])


# -- param flow -------------------------------------------------------------

_CLASS_TYPES = {
    "int": int, "Integer": int, "long": int, "Long": int,
    "double": float, "Double": float, "float": float, "Float": float,
    "String": str, "java.lang.String": str, "boolean": bool, "Boolean": bool,
}


def _java_class_type(obj) -> str:
    """Emit the reference's classType names so round-trips (and reference
    tooling) re-type item objects correctly. bool before int: Python bools
    are ints."""
    if isinstance(obj, bool):
        return "boolean"
    if isinstance(obj, int):
        return "long"
    if isinstance(obj, float):
        return "double"
    return "String"


def _coerce_item_object(obj, class_type: Optional[str]):
    """Reference items carry (object-as-string, classType); re-type here so
    the host param hash matches values seen at entry time."""
    if class_type is None:
        return obj
    py = _CLASS_TYPES.get(class_type)
    if py is None:
        return obj
    if py is bool and isinstance(obj, str):
        return obj.lower() == "true"
    try:
        return py(obj)
    except (TypeError, ValueError):
        return obj


def param_rule_from_dict(d: dict) -> ParamFlowRule:
    items = []
    for it in d.get("paramFlowItemList") or []:
        items.append(ParamFlowItem(
            object=_coerce_item_object(it.get("object"), it.get("classType")),
            count=float(it.get("count", 0)),
        ))
    return ParamFlowRule(
        resource=d.get("resource", ""),
        param_idx=int(d.get("paramIdx", 0)),
        count=float(d.get("count", 0)),
        grade=int(d.get("grade", C.PARAM_FLOW_GRADE_QPS)),
        duration_in_sec=int(d.get("durationInSec", 1)),
        burst_count=int(d.get("burstCount", 0)),
        control_behavior=int(d.get("controlBehavior", C.CONTROL_BEHAVIOR_DEFAULT)),
        max_queueing_time_ms=int(d.get("maxQueueingTimeMs", 0)),
        items=items,
        cluster_mode=bool(d.get("clusterMode", False)),
        cluster_config=d.get("clusterConfig"),
        **_rollout_fields(d),
    )


def param_rule_to_dict(r: ParamFlowRule) -> dict:
    d = {
        "resource": r.resource, "paramIdx": r.param_idx, "grade": r.grade,
        "count": r.count, "durationInSec": r.duration_in_sec,
        "burstCount": r.burst_count, "controlBehavior": r.control_behavior,
        "maxQueueingTimeMs": r.max_queueing_time_ms,
        "clusterMode": r.cluster_mode,
    }
    if r.items:
        d["paramFlowItemList"] = [
            {
                "object": str(it.object),
                "classType": _java_class_type(it.object),
                "count": it.count,
            }
            for it in r.items
        ]
    if r.cluster_config:
        d["clusterConfig"] = r.cluster_config
    return _emit_rollout(d, r)


def param_rules_from_json(source) -> List[ParamFlowRule]:
    return [param_rule_from_dict(d) for d in _loads(source)]


def param_rules_to_json(rules: List[ParamFlowRule]) -> str:
    return json.dumps([param_rule_to_dict(r) for r in rules])

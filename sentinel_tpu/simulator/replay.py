"""The replay engine: a real ``SentinelEngine`` on a program clock.

Verdicts are produced by the PRODUCTION kernels — each simulated
second's demand is expanded into ``EntryBatch`` rows and driven through
``engine.check_batch`` (the same fused step live traffic rides), exits
through ``engine.complete_batch``, with ``now`` always the injected
:class:`~sentinel_tpu.simulator.clock.SimClock`. The once-per-second
flight-recorder fold, SLO judgement, rollout guardrail windows, and the
adaptive loop all run in-sim unmodified, riding the same
``_spill_flight`` cadence they ride live — just at whatever wall speed
the host can step.

Determinism by construction: one clock (never wall), one fixed demand
expansion order (sorted resources, trace pair order), one fixed batch
chunking, exits drained before entries each second (the production
cycle order), and the only async machinery (trace-ring sampling) torn
down at engine birth. Two runs of the same trace produce bit-identical
verdict streams and identical adaptive decision logs — the tier-1
determinism oracle pins this.

The retry-storm closed loop (``trace.meta["retry"]``) is the one
feedback edge recorded traces cannot carry: blocked entries re-offer
after a backoff at a decay factor, so admission decisions feed back
into future demand exactly like impatient clients do.
"""

from __future__ import annotations

import hashlib
import json
from typing import Dict, List, Optional

import numpy as np

from sentinel_tpu.core import constants as C
from sentinel_tpu.core.batch import (
    BATCH_WIDTHS,
    EntryBatch,
    ExitBatch,
    make_entry_batch_np,
    make_exit_batch_np,
)
from sentinel_tpu.simulator.clock import SimClock
from sentinel_tpu.simulator.trace import Trace
from sentinel_tpu.telemetry.attribution import (
    NUM_RT_BUCKETS,
    RT_BUCKET_EDGES_MS,
    histogram_quantile,
)

# Smallest int rt landing in each device histogram bucket (bucket b
# counts rt in (edge[b-1], edge[b]]): replaying a recorded bucket with
# its representative re-buckets identically on device, so a recorded RT
# histogram round-trips bit-exact.
_RT_REP = tuple(RT_BUCKET_EDGES_MS) + (RT_BUCKET_EDGES_MS[-1] + 1,)

_SIM_CONTEXT = "sim"

# Drill-speed adaptive knobs for in-sim closed-loop runs; override per
# key via ReplayEngine(adaptive={...}). Real-time defaults would spend
# most of a short scenario soaking.
DEFAULT_ADAPTIVE_KNOBS = {
    "intervalS": 2, "shadowS": 2, "canaryS": 2, "canaryBps": 2000,
    "cooldownS": 4, "stepPct": 0.5, "backoffS": 20, "minWindowEntries": 8,
}


def _pad_width(n: int, cap: int) -> int:
    for w in BATCH_WIDTHS:
        if w >= n and w <= cap:
            return w
    return cap


def _rt_bucket(rt_ms: int) -> int:
    b = 0
    for edge in RT_BUCKET_EDGES_MS:
        if rt_ms > edge:
            b += 1
    return b


class ReplayResult:
    """Everything one replay run observed, host-side and exact."""

    __slots__ = ("trace_meta", "seconds", "offered", "passed", "blocked",
                 "retried", "verdict_sha256", "series", "rt_hist",
                 "decisions", "counters", "final_counts", "band_violations",
                 "journal", "streams", "population", "replay_wall_s",
                 "total_wall_s")

    def __init__(self):
        self.trace_meta: Dict = {}
        self.seconds = 0
        self.offered = 0      # demand tokens offered (incl. retries)
        self.passed = 0       # tokens admitted
        self.blocked = 0      # tokens blocked
        self.retried = 0      # tokens re-offered by the retry model
        self.verdict_sha256 = ""
        self.series: List[Dict] = []   # per second: t / pass / block maps
        self.rt_hist = [0] * NUM_RT_BUCKETS
        self.decisions: List[Dict] = []  # adaptive decision log
        self.counters: Dict = {}         # adaptive monotone counters
        self.final_counts: Dict[str, float] = {}  # tunable rule counts
        self.band_violations = 0
        # The sim engine's control-plane audit journal (ISSUE 14):
        # memory-only (never file-backed — see _build_engine), stamped
        # in SIMULATED time, so two runs of one trace+seed produce
        # identical record streams — the journal-determinism oracle.
        self.journal: List[Dict] = []
        # Streamed-generation outcomes (ISSUE 17): what the trace's "g"
        # events did to the host-side reservation ledger. Empty unless
        # the scenario carries streams.
        self.streams: Dict[str, int] = {}
        # Namespace-telescope output (ISSUE 19): the sealed churn-window
        # series plus the final top-k — folded at SIMULATED time on the
        # same spill cadence as judgement, so two runs of one trace+seed
        # produce identical population series (the determinism oracle).
        self.population: Dict = {}
        # Wall timing (perf_counter, the one sanctioned wall read in
        # this package — it measures speed, it never drives replay):
        # replay_wall_s covers the second loop only (steady state, what
        # the >=100x acceptance measures); total_wall_s adds engine
        # build + rule compile + optional warmup.
        self.replay_wall_s = 0.0
        self.total_wall_s = 0.0

    @property
    def block_rate(self) -> float:
        total = self.passed + self.blocked
        return self.blocked / total if total else 0.0

    @property
    def utilization(self) -> float:
        """Admitted fraction of offered demand (goodput ratio)."""
        return self.passed / self.offered if self.offered else 0.0

    @property
    def rt_p99_ms(self) -> float:
        if not sum(self.rt_hist):
            return 0.0
        return float(histogram_quantile(self.rt_hist, 0.99))

    def objective_vector(self) -> Dict[str, float]:
        """The multi-objective score surface (block-rate, RT-p99,
        utilization) the policy lab ranks on."""
        return {"blockRate": round(self.block_rate, 6),
                "rtP99Ms": round(self.rt_p99_ms, 2),
                "utilization": round(self.utilization, 6)}

    def to_dict(self) -> Dict:
        return {
            "seconds": self.seconds,
            "offered": self.offered, "passed": self.passed,
            "blocked": self.blocked, "retried": self.retried,
            "verdictSha256": self.verdict_sha256,
            "objective": self.objective_vector(),
            "counters": self.counters,
            "finalCounts": self.final_counts,
            "bandViolations": self.band_violations,
            "decisions": len(self.decisions),
            "journalRecords": len(self.journal),
            "streams": dict(self.streams),
            "population": ({
                "observed": self.population.get("observed", 0),
                "distinct": self.population.get("distinct", 0.0),
                "windows": len(self.population.get("windows", ())),
            } if self.population else {}),
        }


class ReplayEngine:
    """One trace -> one fresh engine -> one deterministic run.

    ``run()`` builds everything from scratch (engine, clock, rule
    loads), so calling it twice IS the determinism oracle: no state
    survives between runs but the trace itself.
    """

    def __init__(self, trace: Trace, *,
                 rules: Optional[Dict[str, list]] = None,
                 capacity: Optional[int] = None,
                 max_batch: Optional[int] = None,
                 epoch_ms: Optional[int] = None,
                 spill_every_s: Optional[int] = None,
                 adaptive: Optional[Dict] = None,
                 policy=None,
                 targets: Optional[list] = None,
                 fixed_width: Optional[bool] = None):
        from sentinel_tpu.core.config import config as _cfg

        self.trace = trace
        self.rules = rules if rules is not None else trace.rules
        self.capacity = int(capacity) if capacity \
            else max(128, 4 * (len(trace.resources) + 4))
        cap = _cfg.sim_max_batch()
        self.max_batch = min(int(max_batch) if max_batch else cap,
                             BATCH_WIDTHS[-1])
        self.epoch_ms = int(epoch_ms) if epoch_ms is not None \
            else (trace.epoch_ms or _cfg.sim_epoch_ms())
        self.adaptive_knobs = (dict(DEFAULT_ADAPTIVE_KNOBS, **adaptive)
                               if adaptive is not None else None)
        self.policy = policy
        self.targets = targets
        # Adaptive needs every second spilled (interval gating, freeze
        # staleness); open-loop replay spills sparsely — each spill is a
        # device gather, and the ring holds well more than this.
        self.spill_every_s = int(spill_every_s) if spill_every_s else (
            1 if self.adaptive_knobs is not None else 32)
        # Closed-loop runs pad every chunk to ONE ladder width: each
        # candidate install/teardown retraces the fused step PER width,
        # so one shape per kind turns ~2N retraces per promotion into 2.
        # Open-loop runs (no retraces) keep the minimal-width ladder —
        # cheaper steps win when nothing ever recompiles.
        self.fixed_width = (self.adaptive_knobs is not None
                            if fixed_width is None else bool(fixed_width))

    # -- engine assembly ---------------------------------------------------

    def _build_engine(self, clock: SimClock):
        from sentinel_tpu.core.engine import SentinelEngine
        from sentinel_tpu.datasource import converters as CV

        # journal_path="" forces a memory-only journal whatever the
        # process config says: a shared file would leak one replay's
        # records into the next run's restore, breaking determinism.
        eng = SentinelEngine(self.capacity, clock=clock.now_ms,
                             journal_path="")
        # The trace ring's worker thread is the one async consumer on
        # the check_batch path; stopped, submit() is a pinned no-op —
        # zero nondeterministic host work rides the verdict stream.
        eng.traces.stop()
        loaders = {
            "flow": (eng.flow_rules, CV.flow_rules_from_json),
            "degrade": (eng.degrade_rules, CV.degrade_rules_from_json),
            "param": (eng.param_rules, CV.param_rules_from_json),
            "system": (eng.system_rules, CV.system_rules_from_json),
            "authority": (eng.authority_rules, CV.authority_rules_from_json),
            "tps": (eng.tps_rules, CV.tps_rules_from_json),
        }
        for fam, rules in (self.rules or {}).items():
            mgr, from_json = loaders[fam]
            parsed = from_json(json.dumps(list(rules)))
            if parsed:
                mgr.load_rules(parsed)
        if self.adaptive_knobs is not None:
            self._configure_adaptive(eng)
        return eng

    def _configure_adaptive(self, eng) -> None:
        from sentinel_tpu.adaptive.envelope import SafetyEnvelope

        k = self.adaptive_knobs
        loop = eng.adaptive
        loop.interval_s = int(k["intervalS"])
        loop.shadow_soak_s = int(k["shadowS"])
        loop.canary_soak_s = int(k["canaryS"])
        loop.canary_bps = int(k["canaryBps"])
        loop.backoff_s = int(k["backoffS"])
        loop.envelope = SafetyEnvelope(
            step_pct=float(k["stepPct"]),
            cooldown_ms=int(k["cooldownS"]) * 1000)
        eng.rollout.min_window_entries = int(k["minWindowEntries"])
        if self.policy is not None:
            loop.controller.policy = self.policy
        if self.targets is not None:
            loop.load_targets(self.targets)
        loop.enable()

    def _resolve_rows(self, eng) -> Dict[str, tuple]:
        reg = eng.registry
        ent_row = reg.entrance_row(_SIM_CONTEXT)
        rows = {}
        for res in self.trace.resources:
            c_row = reg.cluster_row(res)
            d_row = reg.default_row(_SIM_CONTEXT, res, ent_row)
            rows[res] = (c_row, d_row)
        return rows

    # -- batch builders ----------------------------------------------------

    def _dispatch_entries(self, eng, rows, entries, now, sha) -> List[tuple]:
        """Expand (res, count, n, attempt) demand into padded ladder
        batches, dispatch through the production step, fold verdicts.
        Returns per-row (res, count, attempt, passed) tuples in dispatch
        order — the attempt tag rides through so the retry model can
        bound each entry's chain independently (fresh blocked demand
        must not inherit a due retry's attempt number)."""
        flat = []
        for res, count, n, attempt in entries:
            flat.extend((res, count, attempt) for _ in range(n))
        out = []
        for lo in range(0, len(flat), self.max_batch):
            chunk = flat[lo:lo + self.max_batch]
            width = (self.max_batch if self.fixed_width
                     else _pad_width(len(chunk), self.max_batch))
            buf = make_entry_batch_np(width)
            for i, (res, count, _attempt) in enumerate(chunk):
                c_row, d_row = rows[res]
                buf["cluster_row"][i] = c_row
                buf["dn_row"][i] = d_row
                buf["count"][i] = count
            dec = eng.check_batch(EntryBatch(**buf), now_ms=now)
            reason = np.asarray(dec.reason)[:len(chunk)]
            wait = np.asarray(dec.wait_us)[:len(chunk)]
            slot = np.asarray(dec.rule_slot)[:len(chunk)]
            sha.update(reason.tobytes())
            sha.update(wait.tobytes())
            sha.update(slot.tobytes())
            for i, (res, count, attempt) in enumerate(chunk):
                passed = reason[i] == 0 or reason[i] == C.BlockReason.WAIT
                out.append((res, count, attempt, bool(passed)))
        return out

    def _dispatch_streams(self, eng, sec, now, sha,
                          result: ReplayResult) -> None:
        """Drive this second's streamed-generation events ("g" rows)
        through the production reservation path (stream_open / tick /
        close — ISSUE 17), folding each outcome into the verdict sha so
        a reservation-semantics change breaks replay determinism
        loudly. Blocked opens and blocked overflow ticks are outcomes,
        not errors: impatient clients simply go away."""
        from sentinel_tpu.core.exceptions import BlockException

        events = sec.get("g")
        if not events:
            return
        st = result.streams
        for ev in events:
            op = ev["op"]
            try:
                if op == "open":
                    lease = eng.stream_open(ev["id"], ev["model"],
                                            int(ev["est"]))
                    outcome, val = 0, int(lease.remaining)
                    st["opened"] = st.get("opened", 0) + 1
                elif op == "tick":
                    val = int(eng.stream_tick(ev["id"], int(ev["tok"])))
                    outcome = 0
                    st["ticks"] = st.get("ticks", 0) + 1
                    st["tokens"] = st.get("tokens", 0) + int(ev["tok"])
                else:  # close / abort
                    val = int(eng.stream_close(
                        ev["id"], aborted=op == "abort"))
                    outcome = 0
                    key = "aborted" if op == "abort" else "closed"
                    st[key] = st.get(key, 0) + 1
            except BlockException:
                outcome, val = 1, 0
                st["blocked"] = st.get("blocked", 0) + 1
            except KeyError:
                # The stream never opened (its open blocked): later
                # ticks/closes of the same id are no-ops by design.
                outcome, val = 2, 0
            sha.update(b"g%d:%s:%d:%d" % (
                outcome, ev["id"].encode(), now, val))

    def _dispatch_exits(self, eng, rows, exits, now) -> None:
        """(res, count, rt_ms, error) rows -> padded exit batches."""
        for lo in range(0, len(exits), self.max_batch):
            chunk = exits[lo:lo + self.max_batch]
            width = (self.max_batch if self.fixed_width
                     else _pad_width(len(chunk), self.max_batch))
            buf = make_exit_batch_np(width)
            for i, (res, count, rt_ms, error) in enumerate(chunk):
                c_row, d_row = rows[res]
                buf["cluster_row"][i] = c_row
                buf["dn_row"][i] = d_row
                buf["count"][i] = count
                buf["rt_ms"][i] = rt_ms
                buf["success"][i] = True
                buf["error"][i] = error
            eng.complete_batch(ExitBatch(**buf), now_ms=now)

    # -- exit models -------------------------------------------------------

    @staticmethod
    def _recorded_exits(sec: Dict) -> List[tuple]:
        """Live-trace mode: replay the recorded completion pattern of
        this second as-is (open loop — docs/SEMANTICS.md)."""
        out = []
        for res in sorted(sec.get("x", {})):
            cell = sec["x"][res]
            for b, n in enumerate(cell.get("rt", ())):
                for _ in range(int(n)):
                    out.append((res, 1, _RT_REP[b], False))
            for _ in range(int(cell.get("err", 0))):
                out.append((res, 1, 0, True))
        return out

    def _model_exits(self, passes: Dict[str, int], t: int,
                     pending: Dict[int, list], result) -> List[tuple]:
        """Synthetic mode: admitted tokens complete under the scenario's
        load-dependent RT profile — tokens beyond the knee see the
        loaded RT, so over-admission is visible in the scored p99."""
        profile = self.trace.meta.get("rtProfile", {})
        now_exits = []
        for res in sorted(passes):
            tokens = passes[res]
            prof = profile.get(res)
            if prof is None or tokens <= 0:
                continue
            base = int(prof.get("baseMs", 10))
            loaded = int(prof.get("loadedMs", base * 5))
            knee = int(prof.get("kneeTps", 1 << 30))
            for rt_ms, n in ((base, min(tokens, knee)),
                             (loaded, max(0, tokens - knee))):
                if n <= 0:
                    continue
                result.rt_hist[_rt_bucket(rt_ms)] += n
                row = (res, 1, rt_ms, False)
                if rt_ms < 1000:
                    now_exits.extend([row] * n)
                else:
                    pending.setdefault(t + rt_ms // 1000, []).extend(
                        [row] * n)
        return now_exits

    # -- the run -----------------------------------------------------------

    def warmup_widths(self) -> List[int]:
        """Ladder widths to pre-compile before a timed run so the
        measured replay absorbs zero XLA compiles. Every ladder width
        up to max_batch, not just the entry-demand-derived set: exit
        batches size by COMPLETION rows (recorded buckets, or tokens in
        model mode — count-16 entries fan out 16 exit rows each) and
        the retry model grows entry chunks past the trace's own demand,
        so a demand-only enumeration can leave a width to compile
        inside the timed loop."""
        if self.fixed_width:
            return [self.max_batch]
        return [w for w in BATCH_WIDTHS if w <= self.max_batch]

    def run(self, warmup: bool = False) -> ReplayResult:
        import time as _time

        t_total = _time.perf_counter()
        clock = SimClock(self.epoch_ms)
        eng = self._build_engine(clock)
        result = ReplayResult()
        result.trace_meta = dict(self.trace.meta)
        sha = hashlib.sha256()
        try:
            rows = self._resolve_rows(eng)
            if warmup:
                eng.warmup(self.warmup_widths())
            t_loop = _time.perf_counter()
            by_t = {sec["t"]: sec for sec in self.trace.seconds}
            retry = self.trace.meta.get("retry")
            pending_exits: Dict[int, list] = {}
            pending_retries: Dict[int, Dict[tuple, int]] = {}
            for t in range(self.trace.duration_s):
                now = clock.now_ms()
                sec = by_t.get(t, {"t": t, "d": {}})
                # 1. completions due from earlier seconds drain first
                #    (the production cycle order: exits before entries).
                due = pending_exits.pop(t, [])
                recorded = self._recorded_exits(sec)
                for res, _c, rt_ms, err in recorded:
                    if not err:
                        result.rt_hist[_rt_bucket(rt_ms)] += 1
                if due or recorded:
                    self._dispatch_exits(eng, rows, due + recorded, now)
                # 2. this second's demand (attempt 0) + due retries
                #    (their own attempt — chains are bounded per entry).
                entries = [(res, count, n, 0)
                           for res in sorted(sec["d"])
                           for count, n in sec["d"][res]]
                for (res, count, attempt), n in sorted(
                        pending_retries.pop(t, {}).items()):
                    entries.append((res, count, n, attempt))
                    result.retried += count * n
                verdicts = self._dispatch_entries(eng, rows, entries,
                                                  now, sha)
                # 2b. streamed-generation events ride the same second,
                #     after the batched demand (fixed order = replayable).
                self._dispatch_streams(eng, sec, now, sha, result)
                # 3. fold outcomes; blocked demand feeds the retry model.
                passes: Dict[str, int] = {}
                blocked_by: Dict[tuple, int] = {}
                sec_pass: Dict[str, int] = {}
                sec_block: Dict[str, int] = {}
                for res, count, attempt, passed in verdicts:
                    result.offered += count
                    if passed:
                        result.passed += count
                        passes[res] = passes.get(res, 0) + count
                        sec_pass[res] = sec_pass.get(res, 0) + count
                    else:
                        result.blocked += count
                        sec_block[res] = sec_block.get(res, 0) + count
                        blocked_by[(res, count, attempt)] = \
                            blocked_by.get((res, count, attempt), 0) + 1
                if retry:
                    for (res, count, attempt), n in sorted(
                            blocked_by.items()):
                        next_attempt = attempt + 1
                        if next_attempt > int(retry.get("maxAttempts", 0)):
                            continue
                        again = int(n * float(retry.get("factor", 0.5)))
                        if again <= 0:
                            continue
                        due_t = t + max(1, int(retry.get(
                            "backoffSeconds", 1)))
                        if due_t < self.trace.duration_s:
                            bucket = pending_retries.setdefault(due_t, {})
                            key = (res, count, next_attempt)
                            bucket[key] = bucket.get(key, 0) + again
                # 4. synthetic completions for this second's passes.
                if not sec.get("x"):
                    now_exits = self._model_exits(
                        passes, t, pending_exits, result)
                    if now_exits:
                        self._dispatch_exits(eng, rows, now_exits, now)
                if sec_pass or sec_block:
                    result.series.append(
                        {"t": t, "pass": sec_pass, "block": sec_block})
                # 5. the second completes; judgement + the adaptive loop
                #    ride the spill at simulated time.
                now = clock.advance(1000)
                if (t + 1) % self.spill_every_s == 0 \
                        or t + 1 == self.trace.duration_s:
                    eng._spill_flight(now)
                result.seconds += 1
            result.replay_wall_s = max(_time.perf_counter() - t_loop, 1e-9)
            result.verdict_sha256 = sha.hexdigest()
            self._finalize(eng, result)
        finally:
            eng.close()
        result.total_wall_s = max(_time.perf_counter() - t_total, 1e-9)
        return result

    def _finalize(self, eng, result: ReplayResult) -> None:
        from sentinel_tpu.adaptive.loop import _tunable

        loop = eng.adaptive
        hist = loop.history()
        result.decisions = hist["events"]
        result.counters = dict(loop._counters())
        # The full audit stream, simulated-time-stamped: rule loads at
        # build, every rollout transition and adaptive decision the run
        # produced. Deterministic given the trace + seed (the oracle in
        # tests/test_fleet.py pins it).
        result.journal = eng.journal.tail()
        if result.streams:
            # Ledger end-state: a drained run shows zero outstanding
            # reservation tokens (the gateway demo's acceptance gate).
            st = eng.streams.stats()
            result.streams["outstandingTokens"] = st["outstandingTokens"]
            result.streams["active"] = st["active"]
        for r in eng.flow_rules.get_rules():
            if _tunable(r):
                result.final_counts[r.resource] = float(r.count)
        # Population series (ISSUE 19): sealed churn windows + the
        # final top-k with error bars, all stamped in simulated time.
        population = getattr(eng, "population", None)
        if population is not None and population.enabled:
            result.population = {
                "windows": population.series(),
                "topk": [{"key": k, "count": c, "err": e}
                         for k, c, e in population._ss.top()],
                "observed": population.observed_total,
                "distinct": round(population._hll.estimate(), 2),
            }
        # Safety-envelope audit: every promoted change AND the final
        # live counts must sit inside the declared [floor, ceiling]
        # band. The envelope guarantees this by construction; the lab's
        # acceptance gate counts violations anyway (belt and braces).
        bands = {t.resource: (t.floor, t.ceiling)
                 for t in loop.controller.targets()}
        for ev in result.decisions:
            if ev.get("kind") != "promote":
                continue
            for ch in ev.get("changes", ()):
                band = bands.get(ch.get("resource"))
                if band and not band[0] <= ch["to"] <= band[1]:
                    result.band_violations += 1
        for res, count in result.final_counts.items():
            band = bands.get(res)
            if band and not band[0] <= count <= band[1]:
                result.band_violations += 1

"""asyncio adapter (reference: ``sentinel-reactor-adapter``'s
``SentinelReactorTransformer`` — SURVEY.md §2.5): guard coroutines the way
the reactor adapter guards subscriptions — the entry happens on
subscription (here: await), completion/cancellation exits it, and errors
feed exception metrics.

The engine's ``entry()`` performs a device dispatch (~ms); ``entry_async``
runs it in the default executor so the event loop never blocks, while the
engine's ContextVar-based context propagates into the coroutine (contexts
work per-task, matching the reference's per-subscription context).
"""

from __future__ import annotations

import asyncio
import functools
from typing import Callable, Optional

import sentinel_tpu as st
from sentinel_tpu.core import constants as C
from sentinel_tpu.core.exceptions import BlockException


async def entry_async(resource: str, entry_type: int = C.EntryType.OUT,
                      count: int = 1, args=()):
    """``await``-able ``SphU.entry``: raises BlockException when rejected.

    Returns the EntryHandle; exit via :func:`exit_async` (or use
    :class:`entry_scope`). ``asyncio.to_thread`` (not run_in_executor)
    so the task's ContextVar context — the engine's Context — propagates
    into the worker thread.

    Cancellation-safe: a worker thread cannot be interrupted, so if the
    awaiting task is cancelled mid-admission the entry may still COMMIT
    afterwards — shielded here, with an undo callback that exits the
    orphaned handle the moment the thread finishes (otherwise a cancelled
    task would leak a concurrency slot forever).
    """
    fut = asyncio.ensure_future(
        asyncio.to_thread(st.entry, resource, entry_type, count, list(args)))
    try:
        return await asyncio.shield(fut)
    except asyncio.CancelledError:
        def _undo(f):
            if not f.cancelled() and f.exception() is None:
                f.result().exit()

        fut.add_done_callback(_undo)
        raise


async def exit_async(handle) -> None:
    """``await``-able exit for explicit callers on uncancelled paths.

    The adapter's own cleanup paths exit SYNCHRONOUSLY instead: awaiting
    inside a cancelled task's ``finally``/``__aexit__`` raises
    CancelledError at the first suspension, which would leak the entry
    (a permanently-held concurrency slot). The sync commit is ~1ms —
    acceptable on completion paths; admission stays async.
    """
    await asyncio.to_thread(handle.exit)


class entry_scope:
    """``async with entry_scope("res"):`` — the async twin of
    ``with st.entry("res"):`` (auto-exit + business-exception tracing)."""

    def __init__(self, resource: str, entry_type: int = C.EntryType.OUT,
                 count: int = 1, args=()):
        self.resource = resource
        self.entry_type = entry_type
        self.count = count
        self.args = args
        self._handle = None

    async def __aenter__(self):
        self._handle = await entry_async(self.resource, self.entry_type,
                                         self.count, self.args)
        return self._handle

    async def __aexit__(self, exc_type, exc, tb):
        if self._handle is not None:
            if (exc is not None
                    and not BlockException.is_block_exception(exc)
                    and not isinstance(exc, asyncio.CancelledError)):
                # cancellation is not a service error (a wait_for timeout
                # must not feed an exception-ratio breaker) — same stance
                # as sentinel_coroutine's ignore list
                self._handle.trace(exc)
            self._handle.exit()  # sync: survives task cancellation
        return False


def sentinel_coroutine(value: Optional[str] = None,
                       entry_type: int = C.EntryType.OUT,
                       block_handler: Optional[Callable] = None,
                       fallback: Optional[Callable] = None,
                       default_fallback: Optional[Callable] = None,
                       exceptions_to_ignore=(),
                       args_from: Optional[Callable] = None):
    """The asyncio twin of :func:`~sentinel_tpu.adapters.annotation.
    sentinel_resource`, sharing its exact routing semantics (handlers get
    ``*args, ex=ex, **kwargs``; a nested BlockException routes to the
    block handler untraced) via the same router factory — the differences
    are that admission runs off-loop (``entry_async``) and exit is
    cancellation-proof. Cancellation propagates untraced (it is not a
    service error)."""
    from sentinel_tpu.adapters.annotation import make_routers

    def deco(fn):
        resource = value or f"{fn.__module__}:{fn.__qualname__}"
        on_blocked, on_error = make_routers(
            block_handler, fallback, default_fallback,
            tuple(exceptions_to_ignore) + (asyncio.CancelledError,))

        async def _maybe(out):
            import inspect

            if inspect.isawaitable(out):  # same test as sentinel_resource's
                out = await out
            return out

        @functools.wraps(fn)
        async def wrapper(*args, **kwargs):
            params = args_from(*args, **kwargs) if args_from else args
            try:
                handle = await entry_async(resource, entry_type, args=params)
            except BlockException as ex:
                return await _maybe(on_blocked(ex, args, kwargs))
            try:
                return await fn(*args, **kwargs)
            except BaseException as ex:
                return await _maybe(on_error(handle, ex, args, kwargs))
            finally:
                handle.exit()  # sync: survives task cancellation

        wrapper.__sentinel_resource__ = resource
        return wrapper

    return deco

"""End-to-end flow-rule tests through the public entry API.

Golden behavior parity with the reference demos (SURVEY.md §2.7 / BASELINE
config #1 ``FlowQpsDemo``): a QPS rule of N admits exactly N entries per
second window, then throws FlowException; window roll restores quota.
Deterministic via the frozen clock.
"""

import pytest

import sentinel_tpu as st
from sentinel_tpu.core import constants as C


def test_flow_qps_demo_golden(engine, frozen_time):
    """BASELINE config #1: single-resource QPS rule, count=20."""
    st.load_flow_rules([st.FlowRule(resource="demo", count=20)])
    passed = blocked = 0
    for _ in range(30):
        try:
            with st.entry("demo"):
                passed += 1
        except st.FlowException:
            blocked += 1
    assert passed == 20
    assert blocked == 10
    # next second: quota restored
    frozen_time.advance_time(1000)
    with st.entry("demo"):
        pass


def test_flow_blocks_register_block_qps(engine, frozen_time):
    st.load_flow_rules([st.FlowRule(resource="r", count=2)])
    results = []
    for _ in range(5):
        e = st.entry_ok("r")
        results.append(e is not None)
        if e:
            e.exit()
    snap = engine.node_snapshot()["r"]
    assert snap["passQps"] == 2
    assert snap["blockQps"] == 3


def test_thread_grade_counts_concurrency(engine, frozen_time):
    st.load_flow_rules([
        st.FlowRule(resource="t", count=2, grade=C.FLOW_GRADE_THREAD)
    ])
    e1 = st.entry("t")
    e2 = st.entry("t")
    with pytest.raises(st.FlowException):
        st.entry("t")
    e1.exit()  # concurrency drops -> admit again
    e3 = st.entry("t")
    e3.exit()
    e2.exit()


def test_no_rules_means_pass(engine, frozen_time):
    for _ in range(100):
        with st.entry("free"):
            pass
    assert engine.node_snapshot()["free"]["passQps"] == 100


def test_zero_count_blocks_everything(engine, frozen_time):
    st.load_flow_rules([st.FlowRule(resource="z", count=0)])
    with pytest.raises(st.FlowException):
        st.entry("z")


def test_rule_swap_wholesale(engine, frozen_time):
    st.load_flow_rules([st.FlowRule(resource="s", count=1)])
    with st.entry("s"):
        pass
    with pytest.raises(st.FlowException):
        st.entry("s")
    # raise the limit: new rule applies immediately; stats survive the push
    st.load_flow_rules([st.FlowRule(resource="s", count=10)])
    with st.entry("s"):
        pass
    assert engine.node_snapshot()["s"]["passQps"] == 2


def test_origin_specific_limit(engine, frozen_time):
    """limitApp=<origin> only throttles that caller (AuthoritySlot-adjacent
    origin selection in FlowRuleChecker)."""
    st.load_flow_rules([
        st.FlowRule(resource="o", count=1, limit_app="appA"),
        st.FlowRule(resource="o", count=100),
    ])
    st.context_enter("ctx_a", origin="appA")
    with st.entry("o"):
        pass
    with pytest.raises(st.FlowException):
        st.entry("o")
    # a different origin is not limited by the appA rule
    st.exit_context()
    st.context_enter("ctx_b", origin="appB")
    for _ in range(5):
        with st.entry("o"):
            pass
    st.exit_context()


def test_limit_app_other(engine, frozen_time):
    """limitApp="other" throttles only origins not explicitly named."""
    st.load_flow_rules([
        st.FlowRule(resource="w", count=100, limit_app="vip"),
        st.FlowRule(resource="w", count=1, limit_app=C.LIMIT_APP_OTHER),
    ])
    st.context_enter("cv", origin="vip")
    for _ in range(10):
        with st.entry("w"):
            pass
    st.exit_context()
    st.context_enter("cx", origin="riffraff")
    with st.entry("w"):
        pass
    with pytest.raises(st.FlowException):
        st.entry("w")
    st.exit_context()


def test_rate_limiter_paces(engine, frozen_time):
    """RateLimiterController: 10 QPS -> 100ms spacing, queue cap honored."""
    st.load_flow_rules([
        st.FlowRule(
            resource="p", count=10,
            control_behavior=C.CONTROL_BEHAVIOR_RATE_LIMITER,
            max_queueing_time_ms=500,
        )
    ])
    waits = []
    for _ in range(5):
        reason, wait_us = engine._submit_entry(
            "p", engine.registry.cluster_row("p"), -1, -1, -3, 0, 1, False,
            False, ())
        waits.append((reason, wait_us))
    # first fits immediately; then 100ms increments
    assert all(r == 0 or r == C.BlockReason.PASS for r, _ in waits)
    w = [wu for _, wu in waits]
    assert w[0] == 0
    assert w[1] == pytest.approx(100_000, abs=2000)
    assert w[4] == pytest.approx(400_000, abs=2000)
    # 6th: wait hits exactly the 500ms cap -> still admitted (reference
    # blocks only when wait strictly exceeds maxQueueingTimeMs)
    reason, wait_us = engine._submit_entry(
        "p", engine.registry.cluster_row("p"), -1, -1, -3, 0, 1, False, False, ())
    assert reason == 0 and wait_us == pytest.approx(500_000, abs=2000)
    # 7th: beyond the queue cap -> blocked
    reason, _ = engine._submit_entry(
        "p", engine.registry.cluster_row("p"), -1, -1, -3, 0, 1, False, False, ())
    assert reason == C.BlockReason.FLOW


def test_relate_strategy(engine, frozen_time):
    """RELATE: write_db is throttled when read_db is busy."""
    st.load_flow_rules([
        st.FlowRule(resource="write_db", count=3,
                    strategy=C.FLOW_STRATEGY_RELATE, ref_resource="read_db")
    ])
    # read_db idle: write passes
    with st.entry("write_db"):
        pass
    # read_db busy (4 passes in window): write blocked
    for _ in range(4):
        with st.entry("read_db"):
            pass
    with pytest.raises(st.FlowException):
        st.entry("write_db")


def test_warmup_cold_start_throttles(engine, frozen_time):
    """WarmUpController: cold system only admits ~count/coldFactor."""
    st.load_flow_rules([
        st.FlowRule(resource="wu", count=90,
                    control_behavior=C.CONTROL_BEHAVIOR_WARM_UP,
                    warm_up_period_sec=10)
    ])
    frozen_time.advance_time(3000)  # let the bucket fill to maxToken
    passed = 0
    for _ in range(90):
        if st.entry_ok("wu"):
            passed += 1
    # cold threshold is count/coldFactor = 30
    assert passed == pytest.approx(30, abs=1)


# -- dynamic window geometry (IntervalProperty / SampleCountProperty) -------

class TestWindowGeometry:
    def test_default_geometry_from_config(self, engine):
        assert engine._spec1.interval_ms == 1000
        assert engine._spec1.buckets == 2

    def test_invalid_geometry_rejected(self, engine):
        import pytest as _pytest

        with _pytest.raises(ValueError):
            engine.set_window_geometry(interval_ms=1000, sample_count=3)
        with _pytest.raises(ValueError):
            engine.set_window_geometry(interval_ms=0)

    def test_retune_resets_instant_window_and_keeps_quota_rate(
            self, engine, frozen_time):
        """After retuning to a 2s/4-bucket window the QPS threshold still
        means per-SECOND (passQps normalization), and the instant stats
        reset under the new geometry."""
        st.load_flow_rules([st.FlowRule(resource="geo", count=3)])
        assert sum(1 for _ in range(5) if st.entry_ok("geo")) == 3

        engine.set_window_geometry(interval_ms=2000, sample_count=4)
        assert engine._spec1.bucket_ms == 500
        # Stats reset + per-second normalization (passQps = window sum
        # * 1000/interval): the i-th burst entry sees used = i*0.5 QPS, so
        # i=0..4 satisfy used + 1 <= 3 and the 6th blocks — a 2s window
        # smooths the instantaneous burst to its per-second average,
        # exactly the reference's IntervalProperty behavior.
        got = [bool(st.entry_ok("geo")) for _ in range(7)]
        assert got == [True] * 5 + [False] * 2

    def test_retune_survives_minute_window_and_breakers(self, engine,
                                                        frozen_time):
        """Minute-window history and param/degrade state survive a retune;
        only the instant window resets."""
        st.load_flow_rules([st.FlowRule(resource="geo2", count=100)])
        for _ in range(4):
            h = st.entry_ok("geo2")
            if h:
                h.exit()
        frozen_time.advance_time(2_000)  # seal the second into w60
        lines = engine.seal_metrics()
        assert any("geo2" in ln for ln in map(str, lines))

        engine.set_window_geometry(interval_ms=500, sample_count=1)
        # minute window kept: sealing again right after the retune must not
        # lose the already-staged history (only the INSTANT window reset)
        snap = engine.node_snapshot()["geo2"]
        assert snap["passQps"] == 0.0  # instant window was reset
        assert snap["curThreadNum"] == 0
        # the engine still admits under the new geometry
        assert st.entry_ok("geo2")

    def test_sample_count_config_key(self, engine, monkeypatch):
        from sentinel_tpu.core.config import config

        monkeypatch.setenv("CSP_SENTINEL_STATISTIC_SAMPLE_COUNT", "4")
        config.reset_for_tests()
        try:
            eng = st.reset(capacity=256)
            assert eng._spec1.buckets == 4
        finally:
            monkeypatch.delenv("CSP_SENTINEL_STATISTIC_SAMPLE_COUNT")
            config.reset_for_tests()
            st.reset(capacity=256)

    def test_geometry_property_push(self, engine):
        """SampleCountProperty/IntervalProperty push form: a datasource can
        drive the geometry like any rule property."""
        engine.window_geometry_property.update_value(
            {"intervalMs": 2000, "sampleCount": 4})
        assert engine._spec1.interval_ms == 2000
        assert engine._spec1.buckets == 4

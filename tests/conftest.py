"""Test config: run JAX on a virtual 8-device CPU topology.

Per the build environment contract, tests run on CPU with
``xla_force_host_platform_device_count=8`` so multi-chip sharding logic is
exercised without TPU hardware; the bench runs on the real chip.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

# The image's sitecustomize registers the TPU backend and pins
# jax_platforms to it regardless of the env var; override via config
# (must happen before the backend initializes).
jax.config.update("jax_platforms", "cpu")

import pytest

import sentinel_tpu as st
from sentinel_tpu.utils import time_util


@pytest.fixture()
def frozen_time():
    """Pin the clock to a deterministic epoch; yield the controller."""
    time_util.freeze_time(1_700_000_000_000)
    yield time_util
    time_util.unfreeze_time()


@pytest.fixture()
def engine(frozen_time):
    """Fresh default engine with a pinned clock and a clean context."""
    from sentinel_tpu.core.context import replace_context

    replace_context(None)
    eng = st.reset(capacity=512)
    yield eng
    replace_context(None)
    st.reset(capacity=512)


# -- quick tier ---------------------------------------------------------------
# `pytest -m quick` (< ~2 min): one representative per engine path, chosen to
# cover the regression classes that shipped broken HEADs in rounds 2-3
# (engine/lease/checkpoint/retune interactions) plus a smoke per subsystem.
# Run it before EVERY commit; the full suite before the round's final one.

QUICK = (
    "test_flow.py::test_flow_qps_demo_golden",
    "test_flow.py::test_rule_swap_wholesale",
    "test_flow.py::TestWindowGeometry::test_retune_resets_instant_window_and_keeps_quota_rate",
    "test_lease.py::test_lease_admission_is_exact",
    "test_lease.py::test_lease_stats_reach_the_device",
    "test_lease.py::test_rule_push_does_not_regrant_spent_quota",
    "test_lease.py::test_retune_with_compiled_leased_engine",
    "test_checkpoint.py::test_stats_survive_restart",
    "test_checkpoint.py::test_restore_after_rule_load_seeds_lease_mirror",
    "test_checkpoint_scenarios.py::test_leased_traffic_checkpoint_crash_restore",
    "test_occupy.py::test_prioritized_borrows_once_bucket_expires",
    "test_degrade.py::test_exception_ratio_opens_and_recovers",
    "test_window.py::test_rotation_drops_old_buckets",
    "test_cluster.py::test_codec_flow_round_trip",
    "test_transport.py::test_get_set_rules_round_trip",
    "test_dashboard.py::test_discovery_from_heartbeats",
    "test_transport.py::test_gateway_rules_and_api_definitions_commands",
    "test_tlv_fixtures.py",     # whole file: 2.5s
    "test_redis_datasource.py",  # whole file: 2.5s
    # Differential-fuzz representatives (the FULL fuzz file has grown to
    # ~15 scenarios / several minutes — r5 added mixed-count, hot-key,
    # system, geometry, and warm-up regimes; the full set runs in the
    # suite, the quick tier keeps ONE seed of the core oracle scenario,
    # the trace regression, and ONE mixed-count pin — exact parametrized
    # ids, or the prefix match would drag in every seed including the
    # 150-step soak):
    "test_step_fuzz.py::test_fuzz_step_matches_serial_oracle[11-40]",
    "test_step_fuzz.py::test_width_zero_batches_trace_and_preserve_state",
    "test_step_fuzz.py::test_fuzz_mixed_acquire_counts[13-50]",
    "test_token_service_fuzz.py",  # token-service fuzz vs oracle: ~2s
)


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "quick: pre-commit smoke tier (pytest -m quick)")


def pytest_collection_modifyitems(config, items):
    for item in items:
        rel = item.nodeid.split("tests/")[-1]
        for q in QUICK:
            if rel == q or rel.startswith(q + "::") or rel.startswith(q + "["):
                item.add_marker(pytest.mark.quick)
                break

"""Within-batch semantics regressions (code-review findings).

Serial-reference invariants the vectorized checker must respect:
  1. a request blocked by one rule never inflates the usage that other
     requests in the same micro-batch are admitted against;
  2. a blocked request never consumes rate-limiter (leaky bucket) tokens;
  3. THREAD-grade checks count concurrency (1 per entry), not tokens.
"""

import numpy as np
import pytest

import sentinel_tpu as st
from sentinel_tpu.core import constants as C
from sentinel_tpu.core.batch import EntryBatch, make_entry_batch_np


def _batch(engine, rows):
    """Build an EntryBatch from a list of per-request field dicts."""
    buf = make_entry_batch_np(len(rows))
    for i, r in enumerate(rows):
        for k, v in r.items():
            buf[k][i] = v
    return EntryBatch(**buf)


def test_blocked_requests_do_not_inflate_prefix(engine, frozen_time):
    """10 appA requests blocked by a count=0 rule must not push the
    shared default rule over its threshold for the appB request."""
    st.load_flow_rules([
        st.FlowRule(resource="o", count=0, limit_app="appA"),
        st.FlowRule(resource="o", count=10),
    ])
    reg = engine.registry
    cl = reg.cluster_row("o")
    a_id = reg.origin_id("appA")
    b_id = reg.origin_id("appB")
    a_row = reg.origin_row("o", "appA")
    b_row = reg.origin_row("o", "appB")
    engine._ensure_compiled()
    rows = [
        dict(cluster_row=cl, dn_row=-1, origin_row=a_row, origin_id=a_id,
             origin_named=True, count=1)
        for _ in range(10)
    ] + [
        dict(cluster_row=cl, dn_row=-1, origin_row=b_row, origin_id=b_id,
             origin_named=False, count=1)
    ]
    dec = engine.check_batch(_batch(engine, rows))
    reasons = np.asarray(dec.reason)
    assert (reasons[:10] == C.BlockReason.FLOW).all()  # appA rule blocks
    assert reasons[10] == C.BlockReason.PASS  # appB unaffected by them


def test_blocked_requests_do_not_consume_rate_limiter(engine, frozen_time):
    """appA traffic rejected by its own rule must leave the leaky bucket
    untouched for appB."""
    st.load_flow_rules([
        st.FlowRule(resource="r", count=0, limit_app="appA"),
        st.FlowRule(resource="r", count=10,
                    control_behavior=C.CONTROL_BEHAVIOR_RATE_LIMITER,
                    max_queueing_time_ms=1000),
    ])
    reg = engine.registry
    cl = reg.cluster_row("r")
    a_id = reg.origin_id("appA")
    a_row = reg.origin_row("r", "appA")
    b_id = reg.origin_id("appB")
    b_row = reg.origin_row("r", "appB")
    engine._ensure_compiled()
    rows = [
        dict(cluster_row=cl, dn_row=-1, origin_row=a_row, origin_id=a_id,
             origin_named=True, count=1)
        for _ in range(8)
    ] + [
        dict(cluster_row=cl, dn_row=-1, origin_row=b_row, origin_id=b_id,
             origin_named=False, count=1)
    ]
    dec = engine.check_batch(_batch(engine, rows))
    reasons = np.asarray(dec.reason)
    waits = np.asarray(dec.wait_us)
    assert (reasons[:8] == C.BlockReason.FLOW).all()
    assert reasons[8] == C.BlockReason.PASS
    # first surviving request claims the very first bucket slot: no wait
    assert waits[8] == 0


def test_thread_grade_prefix_counts_entries_not_tokens(engine, frozen_time):
    """3 entries of count=5 against a THREAD limit of 4: concurrency moves
    by 1 per entry, so all three must pass."""
    st.load_flow_rules([
        st.FlowRule(resource="t", count=4, grade=C.FLOW_GRADE_THREAD)
    ])
    reg = engine.registry
    cl = reg.cluster_row("t")
    engine._ensure_compiled()
    rows = [
        dict(cluster_row=cl, dn_row=-1, origin_row=-1, origin_id=-3,
             origin_named=False, count=5)
        for _ in range(3)
    ]
    dec = engine.check_batch(_batch(engine, rows))
    assert (np.asarray(dec.reason) == C.BlockReason.PASS).all()


def test_rate_limiter_batch_paces_after_idle(engine, frozen_time):
    """After an idle gap a micro-batch must still be paced: the leaky-bucket
    base clamps to now - cost, so of 8 simultaneous requests at count=10
    (cost 100ms, queue cap 200ms) exactly 3 fit (waits 0/100/200ms)."""
    st.load_flow_rules([
        st.FlowRule(resource="rl", count=10,
                    control_behavior=C.CONTROL_BEHAVIOR_RATE_LIMITER,
                    max_queueing_time_ms=200),
    ])
    cl = engine.registry.cluster_row("rl")
    engine._ensure_compiled()
    rows = [dict(cluster_row=cl, dn_row=-1, origin_row=-1, count=1)
            for _ in range(8)]
    dec = engine.check_batch(_batch(engine, rows))
    reasons = np.asarray(dec.reason)
    waits = np.asarray(dec.wait_us)
    assert (reasons[:3] == C.BlockReason.PASS).all()
    assert (reasons[3:] == C.BlockReason.FLOW).all()
    assert list(waits[:3]) == [0, 100_000, 200_000]


def test_warmup_zero_count_rule_blocks_without_crash(engine, frozen_time):
    """count=0 is a valid block-everything config for every behavior; the
    warm-up slope math must not divide by zero."""
    st.load_flow_rules([
        st.FlowRule(resource="wz", count=0,
                    control_behavior=C.CONTROL_BEHAVIOR_WARM_UP),
    ])
    with pytest.raises(st.FlowException):
        st.entry("wz")


def test_param_hash_deterministic_and_typed():
    from sentinel_tpu.core.engine import _hash_param

    assert _hash_param("user-42") == 2811702807  # frozen cross-process value
    vals = [1, 1.0, "1", True, b"1", None]
    hashes = [_hash_param(v) for v in vals]
    assert len(set(hashes)) == len(vals)
    assert all(0 < h <= 0xFFFFFFFF for h in hashes)


def test_admission_totals_invariant_under_permutation(engine, frozen_time):
    """Race-detection analog (SURVEY §5): the device result must equal a
    serial oracle under permuted batches — for unit counts, per-resource
    admitted TOTALS are arrival-order invariant (which requests pass
    depends on order; how many never does)."""
    rng = np.random.default_rng(42)
    st.load_flow_rules([st.FlowRule(resource="pa", count=4),
                        st.FlowRule(resource="pb", count=7)])
    reg = engine.registry
    rows = {r: reg.cluster_row(r) for r in ("pa", "pb")}
    engine._ensure_compiled()
    base = (["pa"] * 9) + (["pb"] * 9)
    totals = []
    for trial in range(4):
        order = list(base)
        rng.shuffle(order)
        batch_rows = [dict(cluster_row=rows[r], dn_row=-1, origin_row=-1,
                           count=1) for r in order]
        dec = engine.check_batch(_batch(engine, batch_rows))
        admitted = np.asarray(dec.reason) == C.BlockReason.PASS
        per_res = {r: int(sum(a for a, o in zip(admitted, order) if o == r))
                   for r in rows}
        totals.append(per_res)
        st.load_flow_rules([st.FlowRule(resource="pa", count=4),
                            st.FlowRule(resource="pb", count=7)])
        frozen_time.advance_time(2_000)  # fresh window per trial
    assert all(t == {"pa": 4, "pb": 7} for t in totals), totals


def test_pre_passed_skips_slots_and_commits_pass(engine, frozen_time):
    """A host-leased (pre_passed) entry must commit PASS + thread even
    when every rule would block it, and must not consume any slot state
    that device-checked peers in the batch rely on."""
    st.load_flow_rules([st.FlowRule(resource="pp", count=0)])  # blocks all
    reg = engine.registry
    cl = reg.cluster_row("pp")
    engine._ensure_compiled()

    dec = engine.check_batch(_batch(engine, [
        {"cluster_row": cl, "dn_row": -1, "count": 1, "pre_passed": True},
        {"cluster_row": cl, "dn_row": -1, "count": 1},  # device-checked
    ]))
    reasons = np.asarray(dec.reason)
    assert reasons[0] == C.BlockReason.PASS   # slots skipped entirely
    assert reasons[1] == C.BlockReason.FLOW   # count=0 still blocks peers

    snap = engine.node_snapshot()["pp"]
    assert snap["passQps"] == 1
    assert snap["blockQps"] == 1
    assert snap["curThreadNum"] == 1  # pre_passed holds a concurrency slot


def test_pre_blocked_wins_over_pre_passed(engine, frozen_time):
    """Both flags set: the remote rejection wins (block committed)."""
    reg = engine.registry
    cl = reg.cluster_row("pb")
    engine._ensure_compiled()
    dec = engine.check_batch(_batch(engine, [
        {"cluster_row": cl, "dn_row": -1, "count": 1,
         "pre_passed": True, "pre_blocked": True},
    ]))
    assert np.asarray(dec.reason)[0] == C.BlockReason.FLOW
    assert engine.node_snapshot()["pb"]["blockQps"] == 1


def test_flow_plus_breaker_bound_within_one_batch(engine, frozen_time):
    """SEMANTICS.md bounded delta #1 (cross-family clause): flow's
    within-batch prefix counts entries the later degrade slot blocks, so
    on a flow+breaker resource the device may attribute some blocks to
    FLOW that the serial reference attributes to DEGRADE — but it NEVER
    admits more than serial, never admits fewer than serial minus the
    breaker-blocked count, and commits PASS only for actual admits."""
    st.load_flow_rules([st.FlowRule(resource="fb", count=2)])
    st.load_degrade_rules([st.DegradeRule(
        resource="fb", grade=C.DEGRADE_GRADE_EXCEPTION_COUNT, count=1,
        time_window=1, min_request_amount=1, stat_interval_ms=30_000)])
    reg = engine.registry
    cl = reg.cluster_row("fb")
    engine._ensure_compiled()

    # Trip the breaker: admit, fail, let the exception count trip it.
    h = st.entry("fb")
    h.trace(ValueError("boom"))
    h.exit()
    h2 = st.entry_ok("fb")
    assert h2 is not None  # second admit within count=2
    h2.trace(ValueError("boom"))
    h2.exit()
    # Verify OPEN via breaker state directly — a probe entry here would
    # be flow-blocked (window already at count) and prove nothing.
    assert int(np.asarray(engine._state.degrade.state)[0]) == C.BREAKER_OPEN

    # Retry due -> next batch carries exactly one probe.
    frozen_time.advance_time(1100)
    dec = engine.check_batch(_batch(engine, [
        {"cluster_row": cl, "dn_row": -1, "count": 1} for _ in range(5)
    ]))
    reasons = np.asarray(dec.reason)
    # Serial reference: entry 1 probes (PASS), entries 2-5 DEGRADE.
    # Device: one PASS; the rest blocked — some as FLOW (the documented
    # conservative attribution), none over-admitted.
    admitted = int((reasons == C.BlockReason.PASS).sum())
    assert admitted == 1
    assert set(np.unique(reasons)) <= {C.BlockReason.PASS,
                                       C.BlockReason.FLOW,
                                       C.BlockReason.DEGRADE}
    # State exactness: exactly the one admit committed PASS — the
    # instant window for "fb" carries 1 pass this second.
    row_pass = int(np.asarray(
        engine._state.w1.counts[:, C.MetricEvent.PASS, cl]).sum())
    assert row_pass == 1

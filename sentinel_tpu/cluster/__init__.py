"""Cluster flow control (reference: ``sentinel-cluster/`` — SURVEY.md §2.4,
§2.11, §3.3): a token server owning global sliding windows so N instances
share one quota, a binary-TLV TCP wire protocol, a token client with
reconnect + local fallback, and namespace-scoped cluster rule management.

TPU-native split: *within a pod* there is no server at all — cluster-mode
rules admit against a ``psum``'d global window (``parallel/cluster.py``).
This package is the *cross-process* surface: the token server batches
acquire requests from remote (non-pod) clients into jitted device steps over
one ``[flow_rules, buckets, events]`` window tensor, and the client side
plugs into the engine's flow checker with the reference's
``fallbackToLocalOrPass`` semantics.
"""

from sentinel_tpu.cluster.constants import (
    ClusterFlowEvent,
    MSG_FLOW,
    MSG_PARAM_FLOW,
    MSG_PING,
    THRESHOLD_AVG_LOCAL,
    THRESHOLD_GLOBAL,
    TokenResultStatus,
)
from sentinel_tpu.cluster.rules import ClusterFlowRuleManager
from sentinel_tpu.cluster.token_service import DefaultTokenService, TokenResult
from sentinel_tpu.cluster.client import ClusterTokenClient
from sentinel_tpu.cluster.server import ClusterTokenServer
from sentinel_tpu.cluster.state import ClusterStateManager, EpochFence
from sentinel_tpu.cluster.ha import (
    ClusterHAManager,
    ClusterMap,
    ClusterServerSpec,
    DegradedQuota,
    FailoverTokenClient,
)

__all__ = [
    "ClusterFlowEvent", "ClusterFlowRuleManager", "ClusterHAManager",
    "ClusterMap", "ClusterServerSpec", "ClusterStateManager",
    "ClusterTokenClient", "ClusterTokenServer", "DefaultTokenService",
    "DegradedQuota", "EpochFence", "FailoverTokenClient",
    "MSG_FLOW", "MSG_PARAM_FLOW", "MSG_PING", "THRESHOLD_AVG_LOCAL",
    "THRESHOLD_GLOBAL", "TokenResult", "TokenResultStatus",
]

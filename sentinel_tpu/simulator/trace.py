"""The versioned, portable trace format (capture half of the simulator).

One trace = one timebase (``epochMs``) + one record per flight-recorder
second: per-resource demand (acquire-count histogram), and the observed
exit pattern (success-RT bucket histogram + exception count). Traces
carry the rule sets that were live at capture so a replay reproduces the
admission world, and free-form ``meta`` the generators use for the two
models real recordings cannot carry — the closed-loop retry coupling and
the load-dependent RT profile (``scenarios.py``).

Capture paths:

* :func:`export_trace` — one-shot export of the engine's spilled
  flight-recorder history (the ``flightrec op=export`` command).
* :class:`TraceWriter` — a tee registered on the engine's spill
  (``engine.add_flight_tee``): every complete second is appended to a
  JSONL file as it spills (header line + one line per second), so a
  live incident can be captured continuously and replayed later
  (``flightrec op=tee`` / ``op=stop``).

Exactness contract (docs/SEMANTICS.md "Replay determinism"): the flight
recorder records token AGGREGATES per second — live export reconstructs
demand as count-1 acquires at the second boundary, which replays the
per-second pass/block series exactly for default-window rules driven at
second granularity; per-entry acquire-count structure and sub-second
arrival order are the two things a live trace does not carry (synthetic
scenario traces DO carry mixed counts explicitly).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

from sentinel_tpu.telemetry.attribution import NUM_RT_BUCKETS

TRACE_VERSION = 1
TRACE_KIND = "sentinel-tpu-trace"

# Rule families a trace may carry, in the converter vocabulary.
_RULE_FAMILIES = ("flow", "degrade", "param", "system", "authority", "tps")

# Streaming-reservation ops a trace second's "g" events may carry
# (ISSUE 17): deterministic stream lifecycles the replay drives through
# the engine's stream_open/stream_tick/stream_close calls.
_STREAM_OPS = ("open", "tick", "close", "abort")


def _validate_streams(events) -> list:
    out = []
    for ev in events or ():
        op = ev.get("op")
        if op not in _STREAM_OPS:
            raise ValueError(f"trace stream event op {op!r} invalid "
                             f"(one of {_STREAM_OPS})")
        sid = ev.get("id")
        if not isinstance(sid, str) or not sid:
            raise ValueError(f"trace stream event id {sid!r} invalid")
        clean = {"op": op, "id": sid}
        if op == "open":
            model = ev.get("model")
            if not isinstance(model, str) or not model:
                raise ValueError(
                    f"trace stream open {sid!r} needs a model")
            clean["model"] = model
            est = int(ev.get("est", 0))
            if est < 0:
                raise ValueError(
                    f"trace stream open {sid!r} estimate {est} < 0")
            clean["est"] = est
        elif op == "tick":
            tok = int(ev.get("tok", 0))
            if tok < 0:
                raise ValueError(
                    f"trace stream tick {sid!r} tokens {tok} < 0")
            clean["tok"] = tok
        out.append(clean)
    return out


def _validate_demand(d: Dict) -> Dict[str, list]:
    out = {}
    for res, pairs in (d or {}).items():
        if not isinstance(res, str) or not res:
            raise ValueError(f"trace demand resource {res!r} invalid")
        clean = []
        for pair in pairs:
            count, n = int(pair[0]), int(pair[1])
            if count <= 0 or n < 0:
                raise ValueError(
                    f"trace demand pair {pair!r} on {res!r} invalid "
                    "(count must be positive, n non-negative)")
            if n:
                clean.append([count, n])
        if clean:
            out[res] = clean
    return out


class Trace:
    """One replayable workload: metadata + rules + per-second records."""

    __slots__ = ("version", "epoch_ms", "duration_s", "meta", "resources",
                 "rules", "seconds")

    def __init__(self, epoch_ms: int, duration_s: int,
                 meta: Optional[Dict] = None,
                 resources: Optional[List[str]] = None,
                 rules: Optional[Dict[str, list]] = None,
                 seconds: Optional[List[Dict]] = None):
        self.version = TRACE_VERSION
        self.epoch_ms = int(epoch_ms)
        self.duration_s = int(duration_s)
        self.meta = dict(meta or {})
        self.resources = list(resources or [])
        self.rules = {f: list(rs) for f, rs in (rules or {}).items()}
        # Sparse by design: all-idle seconds are omitted (the recorder's
        # own skip-idle stance); duration_s preserves trailing idle.
        self.seconds = list(seconds or [])

    # -- (de)serialization -------------------------------------------------

    def to_dict(self) -> Dict:
        return {
            "version": self.version,
            "kind": TRACE_KIND,
            "epochMs": self.epoch_ms,
            "durationS": self.duration_s,
            "meta": self.meta,
            "resources": self.resources,
            "rules": self.rules,
            "seconds": self.seconds,
        }

    @classmethod
    def from_dict(cls, d: Dict) -> "Trace":
        if not isinstance(d, dict):
            raise ValueError("trace must be a JSON object")
        if d.get("kind") != TRACE_KIND:
            raise ValueError(f"not a {TRACE_KIND} document "
                             f"(kind={d.get('kind')!r})")
        version = int(d.get("version", -1))
        if version != TRACE_VERSION:
            # Versioned: a future writer's trace must fail loudly here,
            # never half-replay under old semantics.
            raise ValueError(
                f"trace version {version} unsupported (this build reads "
                f"version {TRACE_VERSION})")
        duration = int(d.get("durationS", 0))
        if duration <= 0:
            raise ValueError(f"trace durationS {duration} must be positive")
        seconds = []
        for sec in d.get("seconds", ()):
            t = int(sec["t"])
            if not 0 <= t < duration:
                raise ValueError(
                    f"trace second t={t} outside [0, {duration})")
            rec = {"t": t, "d": _validate_demand(sec.get("d", {}))}
            if sec.get("x"):
                exits = {}
                for res, cell in sec["x"].items():
                    rt = [int(v) for v in cell.get("rt", ())]
                    if len(rt) > NUM_RT_BUCKETS:
                        # Reject at load, not IndexError mid-replay:
                        # the bucket geometry is part of the format.
                        raise ValueError(
                            f"trace second t={t} resource {res!r} "
                            f"carries {len(rt)} rt buckets (format "
                            f"has {NUM_RT_BUCKETS})")
                    exits[res] = {"rt": rt,
                                  "err": int(cell.get("err", 0))}
                rec["x"] = exits
            if sec.get("g"):
                # Streamed-generation events (ISSUE 17) — preserved
                # through the round-trip, replayed in list order.
                rec["g"] = _validate_streams(sec["g"])
            seconds.append(rec)
        seconds.sort(key=lambda s: s["t"])
        stamps = [s["t"] for s in seconds]
        if len(set(stamps)) != len(stamps):
            raise ValueError("trace carries duplicate seconds")
        rules = d.get("rules") or {}
        unknown = sorted(set(rules) - set(_RULE_FAMILIES))
        if unknown:
            raise ValueError(f"trace carries unknown rule families "
                             f"{unknown}")
        # Resources = declared ∪ observed: a TraceWriter stream's header
        # is written before any second exists, so its declared list is
        # empty — the seconds themselves are authoritative (a replay
        # must resolve a row for every resource they reference).
        # Declared order is preserved (round-trip fidelity); observed
        # stragglers append sorted.
        declared = [str(r) for r in d.get("resources", ())]
        observed = set()
        for sec in seconds:
            observed.update(sec["d"])
            observed.update(sec.get("x", {}))
        return cls(
            epoch_ms=int(d.get("epochMs", 0)),
            duration_s=duration,
            meta=d.get("meta") or {},
            resources=declared + sorted(observed - set(declared)),
            rules=rules,
            seconds=seconds,
        )

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True)

    @classmethod
    def from_json(cls, source: str) -> "Trace":
        """Parse either shape a capture produces: one JSON object
        (``export_trace``/``save``) or the ``TraceWriter`` JSONL stream
        (header line + one line per second)."""
        source = source.strip()
        try:
            doc = json.loads(source)
        except ValueError:
            doc = None
        if isinstance(doc, dict):
            return cls.from_dict(doc)
        lines = [ln for ln in source.splitlines() if ln.strip()]
        if not lines:
            raise ValueError("empty trace document")
        head = json.loads(lines[0])
        body = lines[1:]
        # Crash-safety contract: a tee killed mid-write may leave ONE
        # torn trailing line — drop it, the complete seconds before it
        # are the capture. A torn line anywhere else is corruption and
        # still rejects loudly.
        if body:
            try:
                json.loads(body[-1])
            except ValueError:
                body = body[:-1]
        head["seconds"] = [json.loads(ln) for ln in body]
        # A mid-write tail may exceed the header's provisional duration:
        # the stream is authoritative for how long the capture ran.
        if head["seconds"]:
            head["durationS"] = max(int(head.get("durationS", 1)),
                                    head["seconds"][-1]["t"] + 1)
        return cls.from_dict(head)

    def save(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as f:
            f.write(self.to_json())
            f.write("\n")

    @classmethod
    def load(cls, path: str) -> "Trace":
        with open(path, "r", encoding="utf-8") as f:
            return cls.from_json(f.read())

    # -- accessors ---------------------------------------------------------

    def second(self, t: int) -> Optional[Dict]:
        for sec in self.seconds:
            if sec["t"] == t:
                return sec
        return None

    def total_offered(self) -> int:
        """Total demand tokens across the trace (utilization divisor)."""
        return sum(count * n
                   for sec in self.seconds
                   for pairs in sec["d"].values()
                   for count, n in pairs)


def _rules_snapshot(engine) -> Dict[str, list]:
    """Every family's live rules as converter dicts (what a replay needs
    to reproduce the admission world at capture time)."""
    from sentinel_tpu.datasource import converters as CV

    return {
        "flow": [CV.flow_rule_to_dict(r)
                 for r in engine.flow_rules.get_rules()],
        "degrade": [CV.degrade_rule_to_dict(r)
                    for r in engine.degrade_rules.get_rules()],
        "param": [CV.param_rule_to_dict(r)
                  for r in engine.param_rules.get_rules()],
        "system": [CV.system_rule_to_dict(r)
                   for r in engine.system_rules.get_rules()],
        "authority": [CV.authority_rule_to_dict(r)
                      for r in engine.authority_rules.get_rules()],
        "tps": [CV.tps_rule_to_dict(r)
                for r in engine.tps_rules.get_rules()],
    }


def _second_to_trace_record(sec_dict: Dict, epoch_ms: int) -> Dict:
    """``second_to_dict`` JSON shape -> one trace second (offset form)."""
    t = (int(sec_dict["timestamp"]) - epoch_ms) // 1000
    demand: Dict[str, list] = {}
    exits: Dict[str, Dict] = {}
    for res, cell in sec_dict.get("resources", {}).items():
        offered = int(cell.get("pass", 0)) + int(cell.get("block", 0))
        if offered:
            demand[res] = [[1, offered]]
        rt = cell.get("rtBuckets") or []
        err = int(cell.get("exception", 0))
        if any(rt) or err:
            exits[res] = {"rt": [int(v) for v in rt], "err": err}
    rec = {"t": t, "d": demand}
    if exits:
        rec["x"] = exits
    return rec


def export_trace(engine, start_ms: Optional[int] = None,
                 end_ms: Optional[int] = None,
                 limit: Optional[int] = None,
                 resource: Optional[str] = None,
                 meta: Optional[Dict] = None) -> Trace:
    """Build a trace from the engine's spilled flight-recorder history
    (the ``flightrec op=export`` surface). ``limit`` keeps the newest N
    complete seconds; ``start_ms``/``end_ms`` bound the window;
    ``resource`` filters to one resource's series."""
    view = engine.timeseries_view(resource=resource, start_ms=start_ms,
                                  end_ms=end_ms, limit=limit)
    secs = view["seconds"]
    if secs:
        epoch = int(secs[0]["timestamp"])
        duration = (int(secs[-1]["timestamp"]) - epoch) // 1000 + 1
    else:
        epoch, duration = engine.now_ms() - engine.now_ms() % 1000, 1
    records = [_second_to_trace_record(s, epoch) for s in secs]
    records = [r for r in records if r["d"] or r.get("x")]
    resources = sorted({res for r in records for res in r["d"]}
                       | {res for r in records for res in r.get("x", {})})
    base_meta = {
        "source": "flightrec",
        "capturedMs": engine.now_ms(),
        # Honesty markers the replay + SEMANTICS note key off: live
        # aggregates collapse acquire counts to 1-token acquires and
        # sub-second arrival to the second boundary.
        "demand": "token-aggregate",
        "openLoop": True,
    }
    base_meta.update(meta or {})
    return Trace(epoch_ms=epoch, duration_s=max(1, duration),
                 meta=base_meta, resources=resources,
                 rules=_rules_snapshot(engine), seconds=records)


class TraceWriter:
    """Continuous capture: tee every spilled second into a JSONL file.

    Register with ``engine.add_flight_tee(writer.on_second)`` (the
    ``flightrec op=tee`` command does both ends). The header line is
    written on the FIRST second (its stamp fixes the trace epoch), each
    subsequent second appends one line and flushes — a crash keeps every
    complete second written so far, and :meth:`Trace.from_json` reads
    the stream shape directly."""

    def __init__(self, path: str, engine, meta: Optional[Dict] = None):
        self.path = path
        self.engine = engine
        self.meta = dict(meta or {})
        self.epoch_ms: Optional[int] = None
        self.seconds_written = 0
        self._file = open(path, "w", encoding="utf-8")
        self._closed = False

    def on_second(self, sec_dict: Dict) -> None:
        if self._closed:
            return
        try:
            self._write_second(sec_dict)
        except OSError:
            # Disk full / file yanked: mark THIS writer dead before the
            # engine detaches the callback, so `flightrec op=status`
            # reports the truth (closed, count frozen) and a fresh
            # op=tee is not refused by a zombie "active" writer.
            self.close()
            raise

    def _write_second(self, sec_dict: Dict) -> None:
        stamp = int(sec_dict["timestamp"])
        if self.epoch_ms is None:
            self.epoch_ms = stamp
            head = Trace(
                epoch_ms=stamp, duration_s=1,
                meta={"source": "flightrec-tee", "streamed": True,
                      "demand": "token-aggregate", "openLoop": True,
                      **self.meta},
                resources=[], rules=_rules_snapshot(self.engine),
                seconds=[]).to_dict()
            del head["seconds"]
            self._file.write(json.dumps(head, sort_keys=True) + "\n")
        rec = _second_to_trace_record(sec_dict, self.epoch_ms)
        if not rec["d"] and not rec.get("x"):
            return
        self._file.write(json.dumps(rec, sort_keys=True) + "\n")
        self._file.flush()
        self.seconds_written += 1

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._file.close()

    def status(self) -> Dict:
        return {"path": self.path, "epochMs": self.epoch_ms,
                "secondsWritten": self.seconds_written,
                "closed": self._closed}

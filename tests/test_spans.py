"""Cross-process spans (telemetry/spans.py + the cluster trace TLV):
traceparent codec, wire compatibility with TLV-blind peers, the
end-to-end engine -> token-server stitch, and the OTLP export.

The load-bearing property is the CLUSTER test: one sampled entry's
trace carries one trace id across the wire — the client ring holds the
engine decision span, the token_request span, and the server-shipped
token-service span; the server's own collector holds the same
token-service span under the same trace id.
"""

import json
import time
import urllib.request

import pytest

import sentinel_tpu as st
from sentinel_tpu.cluster import codec
from sentinel_tpu.cluster.rules import ClusterFlowRuleManager
from sentinel_tpu.cluster.server import ClusterTokenServer
from sentinel_tpu.cluster.token_service import DefaultTokenService
from sentinel_tpu.cluster.constants import THRESHOLD_GLOBAL, TokenResultStatus
from sentinel_tpu.telemetry import spans as SP


def _rule(flow_id, count):
    return st.FlowRule(
        resource=f"res{flow_id}", count=count, cluster_mode=True,
        cluster_config={"flowId": flow_id,
                        "thresholdType": THRESHOLD_GLOBAL})


# -- trace context / codec ---------------------------------------------------

def test_traceparent_round_trip():
    ctx = SP.new_trace_context()
    assert len(ctx.trace_id) == 32 and len(ctx.span_id) == 16
    parsed = SP.parse_traceparent(ctx.traceparent())
    assert parsed == ctx
    child = ctx.child()
    assert child.trace_id == ctx.trace_id and child.span_id != ctx.span_id


@pytest.mark.parametrize("bad", [
    "", "00-abc-def-01", "zz-" + "0" * 32 + "-" + "1" * 16 + "-01",
    "00-" + "g" * 32 + "-" + "1" * 16 + "-01",          # non-hex trace
    "00-" + "0" * 32 + "-" + "1" * 16 + "-01",          # all-zero trace
    "00-" + "a" * 32 + "-" + "0" * 16 + "-01",          # all-zero span
    "00-" + "a" * 32 + "-" + "b" * 16,                  # missing flags
])
def test_traceparent_rejects_malformed(bad):
    assert SP.parse_traceparent(bad) is None


def test_trace_tlv_round_trip_and_wire_compat():
    """The TLV rides after the entity; TLV-blind decoders (old peers)
    read the same values, TLV-aware readers recover it exactly."""
    base = codec.encode_flow_request(900, 2, True)
    ctx = SP.new_trace_context()
    tagged = codec.append_trace_tlv(base, ctx.traceparent())
    # old decoder: identical result, trailing bytes ignored
    assert codec.decode_flow_request(tagged) == \
        codec.decode_flow_request(base) == (900, 2, True)
    # new reader: exact recovery at the entity's fixed size
    assert codec.read_trace_tlv(tagged, codec.FLOW_REQ_SIZE) \
        == ctx.traceparent()
    # absent / truncated / wrong-tag: None, never an exception
    assert codec.read_trace_tlv(base, codec.FLOW_REQ_SIZE) is None
    assert codec.read_trace_tlv(tagged[:-3], codec.FLOW_REQ_SIZE) is None
    assert codec.read_trace_tlv(b"\x00\x00\x05abc", 0) is None
    # param-flow entities are self-delimiting: offset helper finds the TLV
    p = codec.encode_param_flow_request(7, 1, ["k", 3, True])
    ptag = codec.append_trace_tlv(p, ctx.traceparent())
    assert codec.decode_param_flow_request(ptag) == \
        codec.decode_param_flow_request(p)
    assert codec.read_trace_tlv(
        ptag, codec.param_flow_request_size(ptag)) == ctx.traceparent()


def test_span_info_round_trip():
    s = codec.encode_span_info("ab" * 8, 1_700_000_000_123, 4567)
    assert codec.decode_span_info(s) == ("ab" * 8, 1_700_000_000_123, 4567)
    assert codec.decode_span_info("garbage") is None
    assert codec.decode_span_info("a:b:c") is None


# -- collector ---------------------------------------------------------------

def test_span_collector_sampling_capacity_and_pagination():
    col = SP.SpanCollector(sample_every=3, capacity=4)
    hits = [col.sample() for _ in range(9)]
    got = [h for h in hits if h is not None]
    assert len(got) == 3  # every 3rd
    for ctx in got:
        col.record(SP.Span("s", ctx).finish(duration_us=10))
    for k in range(6):
        col.record(SP.Span(f"extra{k}", SP.new_trace_context()).finish(0))
    snap = col.snapshot()
    assert snap["recorded"] == 9 and len(snap["spans"]) == 4  # capacity
    assert snap["spans"][0]["name"] == "extra5"  # newest first
    page = col.snapshot(limit=2, offset=1)["spans"]
    assert [s["name"] for s in page] == ["extra4", "extra3"]
    disabled = SP.SpanCollector(sample_every=0)
    assert disabled.sample() is None


def test_otlp_export_shape():
    col = SP.SpanCollector(sample_every=1)
    ctx = col.sample()
    root = SP.Span("root", ctx, attrs={"resource": "r", "count": 2,
                                       "ok": True, "ratio": 0.5})
    col.record(root.finish(duration_us=1500))
    out = SP.to_otlp(col.snapshot()["spans"], service_name="app1")
    scope = out["resourceSpans"][0]["scopeSpans"][0]
    sp = scope["spans"][0]
    assert sp["traceId"] == ctx.trace_id and sp["spanId"] == ctx.span_id
    start = int(sp["startTimeUnixNano"])
    assert int(sp["endTimeUnixNano"]) - start == 1_500_000
    attrs = {a["key"]: a["value"] for a in sp["attributes"]}
    assert attrs["resource"] == {"stringValue": "r"}
    assert attrs["count"] == {"intValue": "2"}
    assert attrs["ok"] == {"boolValue": True}
    assert attrs["ratio"] == {"doubleValue": 0.5}
    svc = {a["key"]: a["value"] for a in
           out["resourceSpans"][0]["resource"]["attributes"]}
    assert svc["service.name"] == {"stringValue": "app1"}
    assert json.dumps(out)  # JSON-serializable end to end


# -- cluster end-to-end ------------------------------------------------------

def _connect_client(engine, server):
    engine.cluster.set_to_client("127.0.0.1", server.bound_port)
    deadline = time.time() + 3
    while engine.cluster.client_if_active() is None \
            and time.time() < deadline:
        time.sleep(0.02)
    assert engine.cluster.client_if_active() is not None


def test_cluster_trace_stitches_across_the_wire(engine, frozen_time):
    """One sampled BLOCKED entry: the client ring holds engine decision
    + token_request + the server-shipped token-service span under ONE
    trace id; the server's own collector holds the same span id."""
    engine.spans.sample_every = 1  # sample every cluster-checked entry
    st.load_flow_rules([_rule(910, 0)])  # remote quota 0: always blocked

    server_rules = ClusterFlowRuleManager()
    server_rules.load_rules("default", [_rule(910, 0)])
    service = DefaultTokenService(server_rules)
    # Warm the acquire jit OUTSIDE the entry's deadline budget: the
    # first-compile stall would otherwise time the request out and
    # degrade this entry to the local check (a resilience behavior
    # covered elsewhere).
    service.request_token(910)
    server = ClusterTokenServer(service, host="127.0.0.1", port=0).start()
    try:
        _connect_client(engine, server)
        assert st.entry_ok("res910") is None  # remote BLOCKED pre-decides

        snap = engine.spans.snapshot()
        by_name = {s["name"]: s for s in snap["spans"]}
        assert set(by_name) == {"sentinel.entry", "cluster.token_request",
                                "cluster.token_service"}
        root = by_name["sentinel.entry"]
        reqsp = by_name["cluster.token_request"]
        srvsp = by_name["cluster.token_service"]
        # one shared trace id across all three hops
        assert root["traceId"] == reqsp["traceId"] == srvsp["traceId"]
        # parentage: entry -> token_request -> token_service
        assert reqsp["parentSpanId"] == root["spanId"]
        assert srvsp["parentSpanId"] == reqsp["spanId"]
        # verdict attribution on the hops
        assert root["attributes"]["resource"] == "res910"
        assert root["attributes"]["blocked"] is True
        assert root["attributes"]["preBlocked"] is True
        assert reqsp["attributes"]["status"] \
            == int(TokenResultStatus.BLOCKED)
        # per-hop timings: the wire+queue hop can never be cheaper than
        # the server-side step it contains
        assert reqsp["durationUs"] >= srvsp["durationUs"] >= 0

        # the SERVER recorded the same span under the same trace
        srv_snap = service.spans.snapshot()
        assert len(srv_snap["spans"]) == 1
        assert srv_snap["spans"][0]["traceId"] == root["traceId"]
        assert srv_snap["spans"][0]["spanId"] == srvsp["spanId"]
        assert srv_snap["spans"][0]["attributes"]["flowId"] == 910

        # grouped view: one trace with all three spans
        traces = engine.spans.traces()
        assert len(traces) == 1 and len(traces[0]["spans"]) == 3
    finally:
        server.stop()
        engine.cluster.stop()


def test_unsampled_entries_carry_no_trace(engine, frozen_time):
    """sample_every=0 disables span work entirely — nothing recorded on
    either side, requests still served."""
    engine.spans.sample_every = 0
    st.load_flow_rules([_rule(911, 100)])
    server_rules = ClusterFlowRuleManager()
    server_rules.load_rules("default", [_rule(911, 100)])
    service = DefaultTokenService(server_rules)
    server = ClusterTokenServer(service, host="127.0.0.1", port=0).start()
    try:
        _connect_client(engine, server)
        h = st.entry_ok("res911")
        assert h is not None
        h.exit()
        assert engine.spans.snapshot()["recorded"] == 0
        assert service.spans.snapshot()["recorded"] == 0
    finally:
        server.stop()
        engine.cluster.stop()


def test_traces_command_serves_spans_and_otlp(engine, frozen_time):
    """`traces?spans=true` adds the grouped span view; `format=otlp`
    returns the OTLP-flavored JSON document."""
    from sentinel_tpu.transport.command_center import CommandCenter

    engine.spans.sample_every = 1
    ctx = engine.spans.sample()
    engine.spans.record(SP.Span("sentinel.entry", ctx,
                                attrs={"resource": "r"}).finish(100))
    center = CommandCenter(engine, port=0).start()
    try:
        base = f"http://127.0.0.1:{center.bound_port}"
        with urllib.request.urlopen(f"{base}/traces?spans=true",
                                    timeout=5) as r:
            out = json.loads(r.read().decode())
        assert out["spanTraces"][0]["traceId"] == ctx.trace_id
        assert out["spanSampling"]["recorded"] == 1
        with urllib.request.urlopen(f"{base}/traces?format=otlp",
                                    timeout=5) as r:
            otlp = json.loads(r.read().decode())
        got = otlp["resourceSpans"][0]["scopeSpans"][0]["spans"]
        assert got[0]["traceId"] == ctx.trace_id
    finally:
        center.stop()

"""Pallas TPU kernel for the dense segmented prefix (ops/segment.py).

The XLA path (`segmented_prefix_dense_multi`) runs one `lax.scan` over
row blocks, generating each [block, N] comparison mask on the VPU and
contracting it on the MXU — with the mask and value operands bouncing
through HBM between scan steps. This kernel keeps everything in VMEM:
one grid step per row block, the mask generated tile-by-tile and fed
straight to the MXU, the accumulator never leaving the core. Measured
on the real chip at bench shapes (N=8192, M=2, 16-step scan):
0.303 ms/step vs 0.518 for the XLA scan — 1.71x.

Exactness: the mask is {0,1} f32 and values are f32, so results are
exact for integer counts < 2^24 — strictly wider than the XLA path's
bf16 (≤ 256) envelope.

Backend quirks (measured, this image's mosaic lowering):
- i64 anywhere in the kernel (or its index maps) sends lowering into
  infinite `_convert_helper` recursion. sentinel_tpu enables jax x64,
  under which python-int constants trace as i64 — so the call is traced
  under ``enable_x64(False)``; all kernel I/O is int32/f32, making
  that semantics-free.
- bool→bf16 converts recurse the same way (bool→f32 select is fine).
- A shape-free ``BlockSpec(memory_space=VMEM)`` recurses too; explicit
  full-array block shapes work.
"""

from __future__ import annotations

from typing import List, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import enable_x64 as _enable_x64
from jax.experimental import pallas as pl

_BLOCK = 512   # rows per grid step
_JTILE = 512   # mask tile width fed to the MXU per inner iteration


def _make_kernel(npad: int, m1: int):
    def kernel(ids_col_ref, ids_row_ref, vals_ref, out_ref):
        b = pl.program_id(0)
        my_ids = ids_col_ref[...]                          # [BLOCK, 1]
        my_pos = (b * _BLOCK
                  + jax.lax.broadcasted_iota(jnp.int32, (_BLOCK, 1), 0))

        def body(j, acc):
            jids = ids_row_ref[:, pl.ds(j * _JTILE, _JTILE)]
            jpos = (j * _JTILE
                    + jax.lax.broadcasted_iota(jnp.int32, (1, _JTILE), 1))
            mask = (my_ids == jids) & (jpos < my_pos)
            maskf = jnp.where(mask, jnp.float32(1), jnp.float32(0))
            v = vals_ref[pl.ds(j * _JTILE, _JTILE), :]
            return acc + jax.lax.dot_general(
                maskf, v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        out_ref[...] = jax.lax.fori_loop(
            0, npad // _JTILE, body,
            jnp.zeros((_BLOCK, m1), jnp.float32))

    return kernel


def prefix_pallas(ids: jnp.ndarray, values: jnp.ndarray,
                  interpret: bool = False) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One dense segmented exclusive prefix on the TPU (or interpreted).

    Same contract as ``segment.segmented_prefix_dense``: ``ids`` int[N]
    (< 0 forms a shared segment whose values callers keep at 0),
    ``values`` [N] or [N, M]; returns (prefix float32 like values,
    is_first bool[N]).
    """
    from sentinel_tpu.ops.segment import prep_prefix_pair

    n = ids.shape[0]
    npad = -(-n // _BLOCK) * _BLOCK
    squeeze, m, ids32, vals1 = prep_prefix_pair(ids, values, npad)
    # jax.enable_x64 was removed in jax 0.4.37; the experimental context
    # manager is the surviving spelling of the same switch.
    with _enable_x64(False):
        out = pl.pallas_call(
            _make_kernel(npad, m + 1),
            grid=(npad // _BLOCK,),
            in_specs=[
                pl.BlockSpec((_BLOCK, 1), lambda b: (b, 0)),
                pl.BlockSpec((1, npad), lambda b: (0, 0)),
                pl.BlockSpec((npad, m + 1), lambda b: (0, 0)),
            ],
            out_specs=pl.BlockSpec((_BLOCK, m + 1), lambda b: (b, 0)),
            out_shape=jax.ShapeDtypeStruct((npad, m + 1), jnp.float32),
            interpret=interpret,
        )(ids32[:, None], ids32[None, :], vals1)
    out = out[:n]
    prefix, earlier = out[:, :m], out[:, m]
    is_first = earlier == 0
    if squeeze:
        prefix = prefix[:, 0]
    return prefix, is_first


def prefix_pallas_multi(pairs: List[Tuple[jnp.ndarray, jnp.ndarray]],
                        interpret: bool = False):
    """K independent prefixes (the ``segmented_prefix_dense_multi``
    contract) as K kernel launches — each launch already saturates the
    MXU from VMEM, so unlike the XLA scans there is nothing to fuse."""
    return [prefix_pallas(ids, values, interpret=interpret)
            for ids, values in pairs]

"""Heartbeat sender (reference: ``SimpleHttpHeartbeatSender`` +
``HeartbeatSenderInitFunc`` — SURVEY.md §2.3, §3.4): periodic POST to the
dashboard's ``/registry/machine`` so it discovers this instance and marks it
healthy. Dashboard list comes from ``csp.sentinel.dashboard.server``
(comma-separated ``host:port``); failures rotate to the next address.
"""

from __future__ import annotations

import os
import socket
import threading
import urllib.parse
import urllib.request
from typing import List, Optional

from sentinel_tpu.core.config import config


def _local_ip() -> str:
    override = config.get("csp.sentinel.heartbeat.client.ip")
    if override:
        return override
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("10.255.255.255", 1))
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        return "127.0.0.1"


class HeartbeatSender:
    def __init__(self, dashboards: Optional[List[str]] = None,
                 interval_ms: Optional[int] = None,
                 api_port: Optional[int] = None):
        servers = dashboards
        if servers is None:
            raw = config.dashboard_server() or ""
            servers = [s.strip() for s in raw.split(",") if s.strip()]
        self.dashboards = servers
        self.interval_ms = interval_ms or config.heartbeat_interval_ms()
        self.api_port = api_port or config.api_port()
        self._idx = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def heartbeat_message(self) -> dict:
        import sentinel_tpu

        return {
            "app": config.app_name(),
            "app_type": str(config.app_type()),
            "v": sentinel_tpu.__version__,
            "version": str(int(__import__("time").time() * 1000)),
            "hostname": socket.gethostname(),
            "ip": _local_ip(),
            "port": str(self.api_port),
            "pid": str(os.getpid()),
        }

    def send_once(self) -> bool:
        """One POST to the current dashboard; rotate on failure."""
        if not self.dashboards:
            return False
        target = self.dashboards[self._idx % len(self.dashboards)]
        url = f"http://{target}/registry/machine"
        data = urllib.parse.urlencode(self.heartbeat_message()).encode("ascii")
        req = urllib.request.Request(url, data=data)
        # Optional shared secret: deployments that enable dashboard auth can
        # also close the (auth-exempt) registration endpoint to strangers.
        from sentinel_tpu.core.config import HEARTBEAT_TOKEN

        token = config.get(HEARTBEAT_TOKEN, "") or ""
        if token:
            req.add_header("X-Sentinel-Heartbeat-Token", token)
        try:
            with urllib.request.urlopen(req, timeout=3) as resp:
                return 200 <= resp.status < 300
        except OSError:
            self._idx += 1  # try the next dashboard next beat
            return False

    def start(self) -> "HeartbeatSender":
        if self._thread is None:
            self._stop.clear()  # allow start() after a stop()
            self._thread = threading.Thread(
                target=self._run, name="sentinel-heartbeat", daemon=True)
            self._thread.start()
        return self

    def _run(self):
        from sentinel_tpu.log.record_log import record_log

        while not self._stop.wait(self.interval_ms / 1000.0):
            try:
                self.send_once()
            except Exception as ex:
                record_log.warn("heartbeat failed: %r", ex)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

"""Pod-as-token-server tests on the virtual 8-device CPU mesh.

The single most load-bearing claim of the TPU-native design
(``parallel/cluster.py``): a mesh of devices jointly enforces ONE global
quota for cluster-mode rules via a ``psum`` over the pod axis, with
overshoot bounded by one micro-step of cross-device staleness — each device
admits against the other devices' pass counts as of the step start, so

    total admitted <= threshold + (D - 1) x (max per-device admission/step)

and once counts propagate (the next step), admission stops pod-wide.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jax.sharding import Mesh, PartitionSpec as P

import sentinel_tpu as st
from sentinel_tpu.core import constants as C
from sentinel_tpu.core.batch import EntryBatch, ExitBatch, make_entry_batch_np, make_exit_batch_np
from sentinel_tpu.core.registry import NodeRegistry
from sentinel_tpu.models import authority as A
from sentinel_tpu.models import degrade as D_
from sentinel_tpu.models import flow as F
from sentinel_tpu.models import param_flow as PF
from sentinel_tpu.models import system as Y
from sentinel_tpu.ops import step as S
from sentinel_tpu.parallel import cluster as PC

NOW0 = 1_700_000_000_000
CAPACITY = 128
NDEV = 8


@pytest.fixture(scope="module")
def mesh():
    devices = jax.devices()
    assert len(devices) >= NDEV, "conftest must force 8 CPU devices"
    return Mesh(np.asarray(devices[:NDEV]), (PC.AXIS,))


def _build(rules):
    reg = NodeRegistry(CAPACITY)
    row = reg.cluster_row("shared")
    ft, _ = F.compile_flow_rules(rules, reg, CAPACITY)
    dt, di = D_.compile_degrade_rules([], reg, CAPACITY)
    pt = PF.compile_param_rules([], reg, CAPACITY)
    pack = S.RulePack(
        flow=ft, degrade=dt,
        authority=A.compile_authority_rules([], reg, CAPACITY),
        system=Y.compile_system_rules([]),
        param=pt,
    )
    one = S.make_state(CAPACITY, ft.num_rules, NOW0,
                       degrade=D_.make_degrade_state(dt, di),
                       param=PF.make_param_state(pt.num_rules))
    return reg, row, pack, one


def _entry_batch(row, per_dev, count=1):
    """EntryBatch sharded over NDEV devices: [NDEV*per_dev] rows."""
    buf = make_entry_batch_np(NDEV * per_dev)
    buf["cluster_row"][:] = row
    buf["dn_row"][:] = -1  # keep the ruled row single-committed
    buf["count"][:] = count
    return EntryBatch(**{k: jnp.asarray(v) for k, v in buf.items()})


def _exit_batch(row, per_dev):
    buf = make_exit_batch_np(NDEV * per_dev)
    buf["cluster_row"][:] = row
    buf["dn_row"][:] = -1
    buf["count"][:] = 1
    buf["success"][:] = True
    return ExitBatch(**{k: jnp.asarray(v) for k, v in buf.items()})


_STEPS = {}


def _steps(mesh):
    """Jitted pod steps, built once per mesh (shard_map without jit would
    dispatch the whole step op-by-op)."""
    key = id(mesh)
    if key not in _STEPS:
        entry, exit_ = PC.make_pod_steps(mesh)
        _STEPS[key] = (jax.jit(entry), jax.jit(exit_))
    return _STEPS[key]


def _run(mesh, pack, pod_state, batch, now):
    entry, _ = _steps(mesh)
    return entry(pod_state, pack, batch, jnp.asarray(now, jnp.int64))


def _admitted(dec):
    return int((np.asarray(dec.reason) == C.BlockReason.PASS).sum())


def test_pod_respects_global_threshold_with_bounded_overshoot(mesh):
    """Step 1: every device admits locally (stale psum) within the bound;
    step 2: propagated counts stop admission pod-wide."""
    thr, per_dev = 10, 4
    _, row, pack, one = _build([F.FlowRule(resource="shared", count=thr,
                                           cluster_mode=True)])
    pod = PC.make_pod_state(NDEV, one)
    batch = _entry_batch(row, per_dev)

    pod, dec1 = _run(mesh, pack, pod, batch, NOW0)
    admitted1 = _admitted(dec1)
    # Each device alone could admit at most min(per_dev, thr).
    assert admitted1 <= thr + (NDEV - 1) * min(per_dev, thr)
    assert admitted1 >= thr  # the pod is not under-admitting either

    pod, dec2 = _run(mesh, pack, pod, batch, NOW0 + 1)
    # Global usage (>= thr) is now visible everywhere: nothing passes.
    assert _admitted(dec2) == 0


def test_pod_stops_when_one_device_exhausts_quota(mesh):
    """Quota consumed on device 0 only must block devices 1..7 next step."""
    thr = 6
    _, row, pack, one = _build([F.FlowRule(resource="shared", count=thr,
                                           cluster_mode=True)])
    pod = PC.make_pod_state(NDEV, one)

    # Device 0 sends `thr` requests, other devices idle (row -1 = no-op).
    buf = make_entry_batch_np(NDEV * thr)
    buf["cluster_row"][:] = -1
    buf["cluster_row"][:thr] = row  # shard 0 only
    buf["dn_row"][:] = buf["cluster_row"]
    buf["count"][:] = 1
    pod, dec1 = _run(mesh, pack, pod,
                     EntryBatch(**{k: jnp.asarray(v) for k, v in buf.items()}),
                     NOW0)
    assert _admitted(dec1) == thr

    # Now every device tries: all must see the global window as full.
    pod, dec2 = _run(mesh, pack, pod, _entry_batch(row, 2), NOW0 + 1)
    assert _admitted(dec2) == 0


def test_pod_quota_refreshes_across_window_rotation(mesh):
    thr = 8
    _, row, pack, one = _build([F.FlowRule(resource="shared", count=thr,
                                           cluster_mode=True)])
    pod = PC.make_pod_state(NDEV, one)
    pod, dec1 = _run(mesh, pack, pod, _entry_batch(row, 1), NOW0)
    assert _admitted(dec1) == NDEV  # 8 <= thr: all pass
    pod, dec2 = _run(mesh, pack, pod, _entry_batch(row, 1), NOW0 + 10)
    assert _admitted(dec2) == 0  # window holds 8 >= thr globally
    # A full window later the quota is back for the whole pod.
    pod, dec3 = _run(mesh, pack, pod, _entry_batch(row, 1), NOW0 + 1100)
    assert _admitted(dec3) == NDEV


def test_local_rules_stay_per_device(mesh):
    """A non-cluster rule is enforced per device replica, not pod-wide."""
    thr, per_dev = 3, 5
    _, row, pack, one = _build([F.FlowRule(resource="shared", count=thr,
                                           cluster_mode=False)])
    pod = PC.make_pod_state(NDEV, one)
    pod, dec = _run(mesh, pack, pod, _entry_batch(row, per_dev), NOW0)
    # Every device admits its own `thr` — D x thr total, proving no psum
    # coupling for local rules.
    assert _admitted(dec) == NDEV * thr
    reasons = np.asarray(dec.reason).reshape(NDEV, per_dev)
    for d in range(NDEV):
        assert (reasons[d] == C.BlockReason.PASS).sum() == thr


def test_exit_path_balances_thread_gauges_across_devices(mesh):
    """Entries then exits on every device: each replica's concurrency gauge
    returns to zero (the pod analog of StatisticSlot.exit)."""
    _, row, pack, one = _build([F.FlowRule(resource="shared", count=1e9,
                                           cluster_mode=True)])
    pod = PC.make_pod_state(NDEV, one)
    entry, exit_ = _steps(mesh)
    per_dev = 3
    pod, dec = entry(pod, pack, _entry_batch(row, per_dev),
                     jnp.asarray(NOW0, jnp.int64))
    assert _admitted(dec) == NDEV * per_dev
    gauges = np.asarray(pod.cur_threads)[:, row]
    assert (gauges == per_dev).all()  # [D] replicas each carry their own

    pod = exit_(pod, pack, _exit_batch(row, per_dev),
                jnp.asarray(NOW0 + 5, jnp.int64))
    gauges = np.asarray(pod.cur_threads)[:, row]
    assert (gauges == 0).all()


def test_pod_admission_matches_single_server_totals_over_steps(mesh):
    """Multi-step conservation: the pod never admits more per window than a
    single token server with the same threshold would, beyond the documented
    one-step staleness bound."""
    thr, per_dev, steps = 12, 2, 6
    _, row, pack, one = _build([F.FlowRule(resource="shared", count=thr,
                                           cluster_mode=True)])
    pod = PC.make_pod_state(NDEV, one)
    batch = _entry_batch(row, per_dev)
    total = 0
    for k in range(steps):
        pod, dec = _run(mesh, pack, pod, batch, NOW0 + k)
        total += _admitted(dec)
    bound = thr + (NDEV - 1) * min(per_dev, thr)
    assert total <= bound
    # and the pod-global window agrees with what was admitted
    w1_total = int(np.asarray(pod.w1.counts)[:, :, C.MetricEvent.PASS, row].sum())
    assert w1_total == total


def test_pod_occupy_borrows_respect_global_next_window(mesh):
    """Prioritized occupy grants admit against the POD-global next window:
    wave 1 lends within the one-step staleness bound, and once the borrows
    propagate (next step) the whole pod stops lending."""
    thr, per_dev = 10, 4
    _, row, pack, one = _build([F.FlowRule(resource="shared", count=thr,
                                           cluster_mode=True)])
    pod = PC.make_pod_state(NDEV, one)

    # Saturate the window from device 0 in the first bucket.
    buf = make_entry_batch_np(NDEV * thr)
    buf["cluster_row"][:] = -1
    buf["cluster_row"][:thr] = row  # shard 0 only
    buf["dn_row"][:] = buf["cluster_row"]
    buf["count"][:] = 1
    pod, dec0 = _run(mesh, pack, pod,
                     EntryBatch(**{k: jnp.asarray(v) for k, v in buf.items()}),
                     NOW0)
    assert _admitted(dec0) == thr

    # Next bucket: the quota sits in the expiring bucket, so the global
    # next window has `thr` of room. Every device sends prioritized traffic.
    buf = make_entry_batch_np(NDEV * per_dev)
    buf["cluster_row"][:] = row
    buf["dn_row"][:] = -1
    buf["count"][:] = 1
    buf["prioritized"][:] = True
    pbatch = EntryBatch(**{k: jnp.asarray(v) for k, v in buf.items()})

    pod, dec1 = _run(mesh, pack, pod, pbatch, NOW0 + 600)
    r1, w1_ = np.asarray(dec1.reason), np.asarray(dec1.wait_us)
    granted1 = int(((r1 == C.BlockReason.PASS) & (w1_ > 0)).sum())
    borrows = int(np.asarray(pod.occupied_next).sum())
    assert granted1 == borrows
    assert 1 <= granted1 <= thr + (NDEV - 1) * per_dev

    # Same bucket, one step later: pending borrows are psum-visible, the
    # global next window is full — zero further grants anywhere.
    pod, dec2 = _run(mesh, pack, pod, pbatch, NOW0 + 610)
    assert _admitted(dec2) == 0
    assert int(np.asarray(pod.occupied_next).sum()) == borrows


def _build_param(rules, param_rules):
    reg = NodeRegistry(CAPACITY)
    row = reg.cluster_row("shared")
    ft, _ = F.compile_flow_rules(rules, reg, CAPACITY)
    dt, di = D_.compile_degrade_rules([], reg, CAPACITY)
    pt = PF.compile_param_rules(param_rules, reg, CAPACITY)
    pack = S.RulePack(
        flow=ft, degrade=dt,
        authority=A.compile_authority_rules([], reg, CAPACITY),
        system=Y.compile_system_rules([]),
        param=pt,
    )
    one = S.make_state(CAPACITY, ft.num_rules, NOW0,
                       degrade=D_.make_degrade_state(dt, di),
                       param=PF.make_param_state(pt.num_rules))
    return reg, row, pack, one


def test_pod_cluster_param_rule_enforces_global_per_value_quota(mesh):
    """Cluster-mode param rule: one hot value hammered from EVERY device is
    jointly limited via the psum'd sketch — step 1 within the staleness
    bound, step 2 fully stopped; a different value still has quota."""
    thr, per_dev = 6, 3
    _, row, pack, one = _build_param(
        [], [PF.ParamFlowRule("shared", param_idx=0, count=thr,
                              cluster_mode=True)])
    pod = PC.make_pod_state(NDEV, one)

    buf = make_entry_batch_np(NDEV * per_dev)
    buf["cluster_row"][:] = row
    buf["dn_row"][:] = -1
    buf["count"][:] = 1
    buf["param_hash"][:, 0] = 0xBEEF
    buf["param_present"][:, 0] = True
    hot_batch = EntryBatch(**{k: jnp.asarray(v) for k, v in buf.items()})

    pod, dec1 = _run(mesh, pack, pod, hot_batch, NOW0)
    admitted1 = _admitted(dec1)
    # each device alone admits <= min(per_dev, thr); global <= bound
    assert thr <= admitted1 <= thr + (NDEV - 1) * min(per_dev, thr)

    # One step later the sketches are psum-visible: value exhausted pod-wide.
    pod, dec2 = _run(mesh, pack, pod, hot_batch, NOW0 + 1)
    assert _admitted(dec2) == 0

    # An unrelated value is untouched by the hot value's exhaustion.
    buf["param_hash"][:, 0] = 0xCAFE
    cold_batch = EntryBatch(**{k: jnp.asarray(v) for k, v in buf.items()})
    pod, dec3 = _run(mesh, pack, pod, cold_batch, NOW0 + 2)
    assert _admitted(dec3) >= thr


def test_pod_local_param_rule_stays_per_device(mesh):
    """A local (non-cluster) param rule must NOT couple across devices."""
    thr, per_dev = 2, 4
    _, row, pack, one = _build_param(
        [], [PF.ParamFlowRule("shared", param_idx=0, count=thr)])
    pod = PC.make_pod_state(NDEV, one)
    buf = make_entry_batch_np(NDEV * per_dev)
    buf["cluster_row"][:] = row
    buf["dn_row"][:] = -1
    buf["count"][:] = 1
    buf["param_hash"][:, 0] = 0xF00D
    buf["param_present"][:, 0] = True
    pod, dec = _run(mesh, pack, pod,
                    EntryBatch(**{k: jnp.asarray(v) for k, v in buf.items()}),
                    NOW0)
    reasons = np.asarray(dec.reason).reshape(NDEV, per_dev)
    for d in range(NDEV):  # every device admits its own thr for the value
        assert (reasons[d] == C.BlockReason.PASS).sum() == thr


def test_pod_uneven_real_traffic_across_shards(mesh):
    """Real requests distributed unevenly (13 across 8 shards, rest padding
    rows) — totals must match the global quota exactly like an even batch."""
    thr = 5
    _, row, pack, one = _build([F.FlowRule(resource="shared", count=thr,
                                           cluster_mode=True)])
    pod = PC.make_pod_state(NDEV, one)
    per_dev = 4
    buf = make_entry_batch_np(NDEV * per_dev)
    buf["cluster_row"][:] = -1  # padding
    # 13 real requests: shard 0 rows 0-3 + shard 1 rows 4-9 + shard 7
    # rows 28-30 (each shard's slice is per_dev=4 consecutive rows)
    placements = [0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 28, 29, 30]
    for i in placements:
        buf["cluster_row"][i] = row
    buf["dn_row"][:] = buf["cluster_row"]
    buf["count"][:] = 1
    pod, dec = _run(mesh, pack, pod,
                    EntryBatch(**{k: jnp.asarray(v) for k, v in buf.items()}),
                    NOW0)
    admitted = _admitted(dec)
    # 3 active shards: bound = thr + 2 x per-shard max
    assert thr <= admitted <= thr + 2 * per_dev
    # padding rows never produce verdicts
    reasons = np.asarray(dec.reason)
    pad = np.ones(len(reasons), bool)
    pad[placements] = False
    assert (reasons[pad] == -1).all()
    # step 2: propagated -> stop
    pod, dec2 = _run(mesh, pack, pod,
                     EntryBatch(**{k: jnp.asarray(v) for k, v in buf.items()}),
                     NOW0 + 1)
    assert _admitted(dec2) == 0


def test_pod_steps_safe_under_donation(mesh):
    """jit(donate_argnums=0) over the shard_mapped step: results identical
    to the undonated path and the donated buffer is actually consumed."""
    thr = 4
    _, row, pack, one = _build([F.FlowRule(resource="shared", count=thr,
                                           cluster_mode=True)])
    entry, _ = PC.make_pod_steps(mesh)
    donating = jax.jit(entry, donate_argnums=(0,))

    pod_a = PC.make_pod_state(NDEV, one)
    pod_b = PC.make_pod_state(NDEV, one)
    batch = _entry_batch(row, 1)
    now = jnp.asarray(NOW0, jnp.int64)

    pod_a2, dec_a = _steps(mesh)[0](pod_a, pack, batch, now)
    pod_b2, dec_b = donating(pod_b, pack, batch, now)
    assert (np.asarray(dec_a.reason) == np.asarray(dec_b.reason)).all()
    np.testing.assert_array_equal(np.asarray(pod_a2.w1.counts),
                                  np.asarray(pod_b2.w1.counts))
    # (CPU ignores donation rather than deleting the input, so buffer
    # deletion is not asserted — correctness under the donating jit is.)

    # second donated step continues correctly from the new state
    pod_b3, dec_b2 = donating(pod_b2, pack, batch, jnp.asarray(NOW0 + 1, jnp.int64))
    assert _admitted(dec_b2) <= max(0, thr - _admitted(dec_b))


def test_pod_cluster_param_full_quota_every_window(mesh):
    """Regression: a sustained cluster-mode value must receive its FULL
    quota in every window — the admission sketch hard-resets at rolls (a
    decayed carryover would halve steady-state throughput forever)."""
    thr = 8
    _, row, pack, one = _build_param(
        [], [PF.ParamFlowRule("shared", param_idx=0, count=thr,
                              cluster_mode=True)])
    pod = PC.make_pod_state(NDEV, one)
    buf = make_entry_batch_np(NDEV * 2)  # 16 offered/window vs quota 8
    buf["cluster_row"][:] = row
    buf["dn_row"][:] = -1
    buf["count"][:] = 1
    buf["param_hash"][:, 0] = 0xD00D
    buf["param_present"][:, 0] = True
    batch = EntryBatch(**{k: jnp.asarray(v) for k, v in buf.items()})
    for w in range(3):
        t = NOW0 + w * 1000
        pod, dec1 = _run(mesh, pack, pod, batch, t)
        a1 = _admitted(dec1)
        pod, dec2 = _run(mesh, pack, pod, batch, t + 1)
        a2 = _admitted(dec2)
        # full quota available each window (within one-step staleness up),
        # and the second step proves global stop once counts propagate
        assert a1 >= thr, (w, a1)
        assert a1 + a2 <= thr + (NDEV - 1) * 2, (w, a1, a2)


def test_pod_degrade_breaker_per_device_instance_semantics(mesh):
    """Circuit breakers are PER-INSTANCE in the reference (no cluster mode
    for degrade); on the pod each device is an instance: a device whose
    local exit stream crosses the threshold opens ITS breaker; devices
    that saw no failures stay CLOSED."""
    reg = NodeRegistry(CAPACITY)
    row = reg.cluster_row("shared")
    ft, _ = F.compile_flow_rules([], reg, CAPACITY)
    dt, di = D_.compile_degrade_rules(
        [D_.DegradeRule(resource="shared", grade=C.DEGRADE_GRADE_EXCEPTION_COUNT,
                        count=3, time_window=5, min_request_amount=1)],
        reg, CAPACITY)
    pt = PF.compile_param_rules([], reg, CAPACITY)
    pack = S.RulePack(flow=ft, degrade=dt,
                      authority=A.compile_authority_rules([], reg, CAPACITY),
                      system=Y.compile_system_rules([]), param=pt)
    one = S.make_state(CAPACITY, ft.num_rules, NOW0,
                       degrade=D_.make_degrade_state(dt, di),
                       param=PF.make_param_state(pt.num_rules))
    pod = PC.make_pod_state(NDEV, one)
    entry_fn, exit_fn = _steps(mesh)

    per_dev = 4
    # admit everywhere first
    pod, dec = entry_fn(pod, pack, _entry_batch(row, per_dev),
                        jnp.asarray(NOW0, jnp.int64))
    assert _admitted(dec) == NDEV * per_dev

    # device 0's lanes fail (4 errors >= count=3); all other devices succeed
    buf = make_exit_batch_np(NDEV * per_dev)
    buf["cluster_row"][:] = row
    buf["dn_row"][:] = -1
    buf["count"][:] = 1
    fail_lane = np.zeros(NDEV * per_dev, bool)
    fail_lane[:per_dev] = True  # shard 0 (first per_dev lanes)
    buf["success"][:] = ~fail_lane
    buf["error"][:] = fail_lane
    xbatch = ExitBatch(**{k: jnp.asarray(v) for k, v in buf.items()})
    pod = exit_fn(pod, pack, xbatch, jnp.asarray(NOW0 + 10, jnp.int64))

    # next entries: shard 0 OPEN (DEGRADE), other shards still CLOSED
    pod, dec = entry_fn(pod, pack, _entry_batch(row, per_dev),
                        jnp.asarray(NOW0 + 20, jnp.int64))
    reasons = np.asarray(dec.reason).reshape(NDEV, per_dev)
    assert (reasons[0] == C.BlockReason.DEGRADE).all()
    assert (reasons[1:] == C.BlockReason.PASS).all()

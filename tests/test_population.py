"""Namespace telescope (ISSUE 19): differential oracles for the
sketches, bit-exact merge semantics, churn/alarm behaviour, the
engine/fleet wiring, and the Zipf admission-readiness acceptance.

The sketch tests are DIFFERENTIAL: every randomized stream is counted
twice — once by the sketch under test, once by a plain dict — and the
published error bound is checked against the exact answer. Determinism
is structural (blake2b hashing, insertion-order folds), so the same
seeds always exercise the same cells.
"""

import hashlib
import json
import math
import random

import pytest

import sentinel_tpu as st
from sentinel_tpu.core.config import config
from sentinel_tpu.core.context import replace_context
from sentinel_tpu.telemetry.population import (
    CountMinSketch,
    HyperLogLog,
    PopulationTracker,
    SpaceSaving,
    _hll_b64_estimate,
    merge_pages,
    page_summary,
    report_from_page,
    sketch_hash,
)
from sentinel_tpu.transport.command_center import CommandRequest
from sentinel_tpu.transport.handlers import cmd_population
from sentinel_tpu.utils import time_util
from tests.test_telemetry import _batch

BASE_MS = 1_700_000_000_000
WIN_MS = 10_000  # csp.sentinel.population.window.seconds default


def _res(out):
    return json.loads(out.result)


# -- hashing: pinned and seed-independent ---------------------------------


def test_sketch_hash_is_pinned_and_seed_independent():
    """The sketch hash is a WIRE contract (fleet merge identity): pin
    the construction AND a literal value so a silent swap fails here
    before it mis-merges a mixed fleet."""
    expect = int.from_bytes(
        hashlib.blake2b(b"ns#1234", digest_size=8).digest(), "big")
    assert sketch_hash("ns#1234") == expect
    assert sketch_hash("ns#1234") == 0xB01304D4E2C7A057


# -- Space-Saving vs exact oracle -----------------------------------------


@pytest.mark.parametrize("seed,n_keys,k", [(3, 300, 50), (17, 120, 32)])
def test_space_saving_guarantee_vs_exact_oracle(seed, n_keys, k):
    rng = random.Random(seed)
    ss = SpaceSaving(k)
    truth = {}
    keys = [f"key{i}" for i in range(n_keys)]
    weights = [1.0 / (i + 1) ** 1.05 for i in range(n_keys)]
    for key in rng.choices(keys, weights, k=6000):
        inc = rng.randint(1, 4)
        ss.update(key, inc)
        truth[key] = truth.get(key, 0) + inc
    total = sum(truth.values())
    entries = {key: (cnt, err) for key, cnt, err in ss.top()}
    # (a) any key heavier than total/k is guaranteed present
    for key, true in truth.items():
        if true > total / k:
            assert key in entries, f"heavy hitter {key} evicted"
    # (b) per-entry bracket: count - err <= true <= count
    for key, (cnt, err) in entries.items():
        true = truth.get(key, 0)
        assert cnt - err <= true <= cnt, (key, cnt, err, true)
    # (c) the floor bounds every ABSENT key's true count
    floor = ss.floor()
    for key, true in truth.items():
        if key not in entries:
            assert true <= floor, (key, true, floor)


# -- count-min vs exact oracle --------------------------------------------


def test_cms_overestimates_only_and_within_epsilon():
    rng = random.Random(29)
    cms = CountMinSketch(4, 512)
    truth = {}
    for i in rng.choices(range(2000), k=8000):
        h = sketch_hash(f"cms{i}")
        cms.update(h, 1)
        truth[h] = truth.get(h, 0) + 1
    total = sum(truth.values())
    bound = cms.epsilon_total(total)
    violations = 0
    for h, true in truth.items():
        got = cms.query(h)
        assert got >= true, "count-min must never undercount"
        if got - true > bound:
            violations += 1
    # The (e/width)*total bound holds per query with confidence
    # 1 - e^-depth (~98% at depth 4); allow the tail its due.
    assert violations / len(truth) < 0.05, (violations, len(truth), bound)


# -- HyperLogLog vs exact oracle ------------------------------------------


@pytest.mark.parametrize("card", [100, 1000, 5000])
def test_hll_within_standard_error(card):
    hll = HyperLogLog(11)
    for i in range(card):
        hll.add(sketch_hash(f"hll{card}:{i}"))
    est = hll.estimate()
    # stderr = 1.04/sqrt(2^11) ~ 2.3%; allow ~3.5 sigma
    assert abs(est - card) / card < 0.08, (est, card)


# -- standalone tracker: fold, windows, churn -----------------------------


def _tracker(transition=None):
    return PopulationTracker(now_ms=lambda: BASE_MS, transition=transition)


def test_tracker_fold_windows_and_churn_series():
    tr = _tracker()
    tr.observe_pairs([("a", 6), ("b", 4)])
    tr.roll(BASE_MS)
    tr.observe("a", 2)
    tr.roll(BASE_MS + 1000)           # same window: no seal yet
    assert tr.windows_sealed == 0
    tr.observe("c", 1)
    tr.roll(BASE_MS + WIN_MS)         # seals window 0, folds c into w1
    tr.roll(BASE_MS + 2 * WIN_MS)     # seals window 1
    series = tr.series()
    assert [w["windowMs"] for w in series] == [BASE_MS, BASE_MS + WIN_MS]
    assert series[0]["observed"] == 12 and series[0]["entered"] == 2
    assert series[1]["observed"] == 1
    assert series[1]["entered"] == 1 and series[1]["exited"] == 0
    assert tr.observed_total == 13 and tr.folded_keys == 4
    snap = tr.snapshot()
    assert snap["topk"][0] == {"key": "a", "count": 8, "err": 0}
    assert snap["ssFloor"] == 0      # below capacity: summary is exact
    assert 2.5 < snap["distinct"] < 3.5


def test_cardinality_baseline_alarm_fires_and_resolves():
    fired = []
    tr = _tracker(transition=lambda *a: fired.append(a))
    steady = [(f"s{i}", 1) for i in range(6)]
    now = BASE_MS
    for i in range(13):               # 12 sealed steady windows (> warmup)
        tr.observe_pairs(steady[:5 + i % 2])  # tiny jitter: variance > 0
        tr.roll(now)
        now += WIN_MS
    assert not any(f[1] for f in fired)
    tr.observe_pairs([(f"blow{i}", 1) for i in range(400)])
    tr.roll(now)                      # folds the blowup into the open window
    tr.roll(now + WIN_MS)             # seals it -> alarm
    assert tr.alarm is True
    firing = [f for f in fired if f[1]]
    assert firing and firing[-1][0] == PopulationTracker.ALERT_KEY
    fields = firing[-1][3]
    assert fields["kind"] == "population" and fields["z"] > 4.0
    tr.observe_pairs(steady[:5])
    tr.roll(now + 2 * WIN_MS)         # a calm window seals -> resolve
    assert tr.alarm is False
    assert any(not f[1] for f in fired[len(firing):] or fired)


def test_no_observation_when_disabled():
    config.set("csp.sentinel.population.enabled", "false")
    try:
        tr = _tracker()
        assert tr.enabled is False
        tr.observe("x", 5)
        tr.observe_pairs([("y", 1)])
        tr.roll(BASE_MS)
        assert tr.observed_total == 0 and tr.fold_count == 0
    finally:
        config.set("csp.sentinel.population.enabled", "")


# -- merge semantics: exact, associative, commutative ---------------------


def _page_from(stream, windows=2):
    """A page from a standalone tracker fed ``stream`` across
    ``windows`` churn windows."""
    tr = _tracker()
    per = max(1, len(stream) // windows)
    now = BASE_MS
    for i in range(0, len(stream), per):
        tr.observe_pairs(stream[i:i + per])
        tr.roll(now)
        now += WIN_MS
    tr.roll(now)                      # seal the last window
    return tr.page()


def _canon(page):
    return json.dumps(page, sort_keys=True, separators=(",", ":"))


def test_merge_is_associative_and_commutative_bit_exact():
    rng = random.Random(77)
    pool = [f"f{i}" for i in range(160)]
    pages = [
        _page_from([(k, rng.randint(1, 5))
                    for k in rng.choices(pool[:120], k=400)]),
        _page_from([(k, rng.randint(1, 5))
                    for k in rng.choices(pool[40:], k=300)]),
        _page_from([(k, 2) for k in rng.choices(pool, k=200)]),
    ]
    a, b, c = pages
    left = merge_pages([merge_pages([a, b]), c])
    right = merge_pages([a, merge_pages([b, c])])
    flat = merge_pages([a, b, c])
    shuffled = merge_pages([c, a, b])
    assert _canon(left) == _canon(right) == _canon(flat) == _canon(shuffled)
    # conservation: exact totals sum
    assert flat["observed"] == sum(p["observed"] for p in pages)
    assert flat["leaders"] == 3


def test_merge_identity_and_error_bound_summation():
    rng = random.Random(5)
    stream = [(f"q{i}", rng.randint(1, 3))
              for i in rng.choices(range(40), k=200)]
    page = _page_from(stream)
    solo = merge_pages([page])
    assert solo["observed"] == page["observed"]
    assert solo["ss"]["floor"] == page["ss"]["floor"]
    assert {e[0]: e[1] for e in solo["ss"]["entries"]} == \
        {e[0]: e[1] for e in page["ss"]["entries"]}
    s1, s2 = page_summary(page), page_summary(solo)
    assert (s1["observed"], s1["distinct"], s1["hotMass"]) == \
        (s2["observed"], s2["distinct"], s2["hotMass"])
    # The SEMANTICS asymmetry: a key absent from one page widens its
    # merged bracket by that page's floor — never below the truth.
    other = _page_from([(f"other{i}", 4) for i in range(70)])
    merged = merge_pages([page, other])
    assert merged["ss"]["floor"] == \
        page["ss"]["floor"] + other["ss"]["floor"]
    ent = {e[0]: (e[1], e[2]) for e in merged["ss"]["entries"]}
    truth = {}
    for k, c in stream:
        truth[k] = truth.get(k, 0) + c
    for key, true in truth.items():
        if key in ent:
            cnt, err = ent[key]
            assert cnt - err <= true <= cnt


def test_merge_rejects_geometry_mismatch():
    page = _page_from([("a", 1)])
    bad = _page_from([("b", 1)])
    bad["geom"] = dict(bad["geom"], cmsWidth=128)
    with pytest.raises(ValueError, match="geometry mismatch"):
        merge_pages([page, bad])


def test_page_shrinks_loudly_under_byte_cap():
    tr = _tracker()
    tr.observe_pairs([(f"pp{i}", 1) for i in range(300)])
    tr.roll(BASE_MS)
    full = len(json.dumps(tr.page(), separators=(",", ":")))
    small = tr.page(max_bytes=9000)
    assert full > 9000, "stream too small to force a shrink (test rot)"
    assert len(json.dumps(small, separators=(",", ":"))) <= 9000
    assert "sliceHll" in small["truncated"]
    assert small["observed"] == 300       # totals survive truncation
    assert len(small["ss"]["entries"]) >= 8  # the top-k head is kept


# -- fleet federation: stub leaders, bit-exact merged view ----------------


class _PopClient:
    def __init__(self, page):
        self._page = page

    def request_population_page(self, timeout_s=None):
        return json.loads(json.dumps(self._page)) \
            if self._page is not None else None

    def is_connected(self):
        return True

    def stop(self):
        pass


def test_fleet_population_merges_bit_exactly_and_latches_unsupported():
    from sentinel_tpu.telemetry.fleet import FleetView

    rng = random.Random(42)
    pa = _page_from([(f"x{i}", rng.randint(1, 6))
                     for i in rng.choices(range(90), k=300)])
    pb = _page_from([(f"x{i}", 1) for i in rng.choices(range(150), k=250)])
    clients = {1: _PopClient(pa), 2: _PopClient(pb),
               3: _PopClient({"unsupported": True})}
    fv = FleetView([("LA", "h", 1), ("LB", "h", 2), ("LOLD", "h", 3)],
                   clock=lambda: BASE_MS,
                   client_factory=lambda h, p: clients[p])
    try:
        ok = fv.poll_population()
        assert ok == {"LA": True, "LB": True, "LOLD": False}
        view = fv.fleet_population(slot_budget=8, budgets=[4, 16])
        assert view["pagesMerged"] == 2
        assert view["leaders"]["LOLD"]["unsupported"] is True
        # read-time merge == a direct merge of the same pages, bit-exact
        assert _canon(view["merged"]) == _canon(merge_pages([pa, pb]))
        assert view["report"]["slotBudget"] == 8
        assert [c["slotBudget"] for c in view["curve"]] == [4, 16]
        # unsupported leaders are never polled again
        fv.poll_population()
        assert fv._leaders["LOLD"].population_polls == 1
    finally:
        fv.stop()


# -- engine wiring: A/B device-work guard, report, ops command ------------


def _drive_second(eng, lanes, now):
    time_util.freeze_time(now)
    eng._run_entry_batch(_batch(eng, lanes))
    eng.slo_refresh(now_ms=now)


def test_population_fold_adds_no_device_work():
    """A/B guard (acceptance): the same stream with the telescope on
    and off dispatches the SAME device programs — observation stages
    host-side pairs and the fold is host arithmetic on the spill."""

    def run(enabled):
        replace_context(None)
        config.set("csp.sentinel.population.enabled",
                   "" if enabled else "false")
        eng = st.reset(capacity=256)
        st.load_flow_rules([st.FlowRule(resource="ab", count=100)])
        now = BASE_MS
        for _ in range(5):
            _drive_second(eng, [("ab", "", None)] * 4, now)
            now += 1000
        time_util.freeze_time(now)
        eng.slo_refresh(now_ms=now)
        dispatches = {k: v["dispatches"]
                      for k, v in eng.step_timer.snapshot().items()}
        return dispatches, eng.population.observed_total

    time_util.freeze_time(BASE_MS)
    try:
        off_dispatches, off_observed = run(False)
        on_dispatches, on_observed = run(True)
    finally:
        config.set("csp.sentinel.population.enabled", "")
        time_util.unfreeze_time()
        replace_context(None)
        st.reset(capacity=512)
    assert off_observed == 0
    assert on_observed == 20, "the A/B run never exercised the telescope"
    assert on_dispatches == off_dispatches


def test_zipf_replay_hit_rate_projection_within_5pct(engine):
    """Acceptance: seeded Zipf stream through the REAL engine; the
    admission-readiness report predicts the measured hot-set hit rate
    within 5% absolute for three slot budgets."""
    rng = random.Random(1234)
    n_res = 150
    resources = [f"z{i:03d}" for i in range(n_res)]
    weights = [1.0 / (r + 1) ** 1.1 for r in range(n_res)]
    truth = {}
    now = BASE_MS
    for _ in range(25):
        draws = rng.choices(resources, weights, k=200)
        for res in draws:
            truth[res] = truth.get(res, 0) + 1
        time_util.freeze_time(now)
        for i in range(0, len(draws), 100):
            engine._run_entry_batch(_batch(
                engine, [(res, "", None) for res in draws[i:i + 100]]))
        engine.slo_refresh(now_ms=now)
        now += 1000
    time_util.freeze_time(now)
    total = sum(truth.values())
    ranked = sorted(truth.values(), reverse=True)
    for budget in (4, 12, 32):
        rep = engine.population_report(slot_budget=budget, now_ms=now)
        measured = sum(ranked[:budget]) / total
        assert abs(rep["hitRate"] - measured) <= 0.05, (budget, rep, measured)
        assert rep["hitRateGuaranteed"] <= rep["hitRate"] \
            <= rep["hitRateUpper"] + 1e-9
    assert engine.population.observed_total == total
    # beyond-k budgets extrapolate and say so
    wide = engine.population_report(slot_budget=4096, now_ms=now)
    assert wide["extrapolated"] is True and wide["hitRate"] <= 1.0


def test_population_command_surface(engine):
    now = BASE_MS
    for _ in range(3):
        _drive_second(engine, [("cmdA", "", None)] * 3
                      + [("cmdB", "", None)], now)
        now += 1000
    time_util.freeze_time(now)
    engine.slo_refresh(now_ms=now)
    out = _res(cmd_population(CommandRequest(
        parameters={"op": "status"}, engine=engine)))
    assert out["enabled"] is True and out["observed"] == 12
    assert out["topk"][0]["key"] == "cmdA"
    rep = _res(cmd_population(CommandRequest(
        parameters={"op": "report", "budget": "1"}, engine=engine)))
    assert rep["slotBudget"] == 1 and rep["hitRate"] == 0.75
    curve = _res(cmd_population(CommandRequest(
        parameters={"op": "curve", "budgets": "1,2"}, engine=engine)))
    assert [c["slotBudget"] for c in curve["curve"]] == [1, 2]
    page = _res(cmd_population(CommandRequest(
        parameters={"op": "page"}, engine=engine)))
    assert page["observed"] == 12 and "cms" in page
    bad = cmd_population(CommandRequest(
        parameters={"op": "report", "budget": "wat"}, engine=engine))
    assert not bad.success


def test_exporter_ships_population_families(engine):
    from sentinel_tpu.telemetry.exporter import render_engine_metrics

    _drive_second(engine, [("exp", "", None)] * 2, BASE_MS)
    time_util.freeze_time(BASE_MS + 1000)
    engine.slo_refresh(now_ms=BASE_MS + 1000)
    text = render_engine_metrics(engine)
    assert "sentinel_tpu_population_enabled 1" in text
    assert "sentinel_tpu_population_observed_total 2" in text
    for fam in ("sentinel_tpu_population_distinct",
                "sentinel_tpu_population_ss_floor",
                "sentinel_tpu_population_cardinality_alarm",
                "sentinel_tpu_population_fold_ms_total"):
        assert fam in text, fam


# -- replay determinism ----------------------------------------------------


def test_replay_population_series_deterministic():
    from sentinel_tpu.simulator import ReplayEngine, build_scenario

    tr = build_scenario("flash_crowd", seconds=20, seed=7)
    # spill every simulated second (the live cadence) so churn windows
    # seal inside a 20s trace — the open-loop default spills sparsely.
    r1 = ReplayEngine(tr, spill_every_s=1).run()
    r2 = ReplayEngine(tr, spill_every_s=1).run()
    assert r1.population == r2.population
    assert r1.population["observed"] > 0
    assert r1.population["windows"], "no churn window sealed in 20s"
    assert r1.population["topk"]

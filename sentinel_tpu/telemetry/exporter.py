"""Render one engine's full telemetry as OpenMetrics text.

Everything an operator previously had to collect from four surfaces —
Sentinel metric log files, the ``resilience`` / ``rollout`` / ``profile``
JSON ops commands — plus the new device-resident attribution counters
and RT histograms, under stable ``sentinel_tpu_*`` names any Prometheus
scraper ingests. Served by the ``metrics`` command
(``GET /metrics`` on the command center); the ``telemetry`` command is
the JSON-parity view of the same numbers.

Per-resource series cover ClusterNode rows with any recorded traffic
(cardinality = active resources, the same set the metric log writes).
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from sentinel_tpu.core import constants as C
from sentinel_tpu.telemetry.attribution import (
    ATTR_REASON_NAMES,
    RT_BUCKET_EDGES_MS,
    SLOT_BIN_LABELS,
)
from sentinel_tpu.telemetry.openmetrics import OpenMetricsBuilder

_EVENT_FAMILIES = (
    # (family name, MetricEvent, help)
    ("sentinel_tpu_pass", C.MetricEvent.PASS,
     "Admitted entries per resource since engine start"),
    ("sentinel_tpu_block", C.MetricEvent.BLOCK,
     "Blocked entries per resource since engine start"),
    ("sentinel_tpu_success", C.MetricEvent.SUCCESS,
     "Successful completions per resource since engine start"),
    ("sentinel_tpu_exception", C.MetricEvent.EXCEPTION,
     "Business-exception completions per resource since engine start"),
)


def _active_rows(engine, counts: Dict[str, np.ndarray]) -> Dict[str, int]:
    """resource -> ClusterNode row, for rows with any telemetry signal."""
    from sentinel_tpu.core.registry import KIND_CLUSTER

    totals = counts["totals"]
    by_reason = counts["blockByReason"]
    active = (totals.any(axis=0) | by_reason.any(axis=0))
    out: Dict[str, int] = {}
    for row, meta in enumerate(engine._device_metas()):
        if meta.kind == KIND_CLUSTER and row < active.shape[0] \
                and active[row]:
            out[meta.resource] = row
    return out


def render_engine_metrics(engine) -> str:
    counts = engine.telemetry_counts()
    rows = _active_rows(engine, counts)
    totals = counts["totals"]
    by_reason = counts["blockByReason"]
    rt_hist = counts["rtHist"]

    b = OpenMetricsBuilder()

    for name, ev, help_text in _EVENT_FAMILIES:
        b.family(name, "counter", help_text)
        for res, row in rows.items():
            b.sample(name + "_total", {"resource": res},
                     int(totals[int(ev), row]))

    b.family("sentinel_tpu_block_reason", "counter",
             "Blocked entries per (resource, first-blocking rule family) "
             "— device-exact attribution from the fused step")
    for res, row in rows.items():
        for ch, reason in enumerate(ATTR_REASON_NAMES):
            v = int(by_reason[ch, row])
            if v:
                b.sample("sentinel_tpu_block_reason_total",
                         {"resource": res, "reason": reason}, v)

    b.family("sentinel_tpu_block_slot", "counter",
             "Blocked entries per (rule family, first-blocking rule-slot "
             "bin) — engine-global; 'unknown' = remote/pre-decided "
             "verdicts with no local rule identity")
    by_slot = counts["blockBySlot"]
    for ch, reason in enumerate(ATTR_REASON_NAMES):
        for bin_i, label in enumerate(SLOT_BIN_LABELS):
            v = int(by_slot[ch, bin_i])
            if v:
                b.sample("sentinel_tpu_block_slot_total",
                         {"reason": reason, "slot": label}, v)

    b.family("sentinel_tpu_rt_ms", "histogram",
             "Response time of successful completions, device-bucketed "
             "(log2 edges, ms)")
    for res, row in rows.items():
        buckets = rt_hist[:, row]
        if not buckets.any():
            continue
        b.histogram("sentinel_tpu_rt_ms", {"resource": res},
                    [float(e) for e in RT_BUCKET_EDGES_MS],
                    [float(x) for x in buckets],
                    float(totals[int(C.MetricEvent.RT), row]))

    # -- degradation channels (resilience_stats parity) -------------------
    res_stats = engine.resilience_stats()
    b.counter("sentinel_tpu_fail_open",
              "Entries passed unguarded because no verdict could be "
              "produced", res_stats["failOpenCount"])
    b.counter("sentinel_tpu_cluster_fallback",
              "Cluster-mode rule evaluations degraded to the local check",
              res_stats["clusterFallbackCount"])
    b.counter("sentinel_tpu_cluster_budget_exhausted",
              "Entries whose remote-wait deadline budget ran out",
              res_stats["clusterBudgetExhaustedCount"])
    breaker = res_stats.get("tokenClientBreaker")
    b.family("sentinel_tpu_token_client_breaker_state", "gauge",
             "Token client health gate: 0=closed 1=open 2=half-open "
             "(-1: no client)")
    _BRK = {"CLOSED": 0, "OPEN": 1, "HALF_OPEN": 2}
    b.sample("sentinel_tpu_token_client_breaker_state", None,
             _BRK.get((breaker or {}).get("state"), -1))
    b.family("sentinel_tpu_probe_last_success_age_ms", "gauge",
             "Age of each registered health probe's last success")
    for probe, snap in sorted(res_stats.get("probes", {}).items()):
        age = snap.get("lastSuccessAgeMs")
        if age is not None:
            b.sample("sentinel_tpu_probe_last_success_age_ms",
                     {"probe": probe}, age)

    # -- cluster HA (cluster/ha.py) ---------------------------------------
    ha = res_stats.get("clusterHA") or {}
    b.family("sentinel_tpu_cluster_ha_role", "gauge",
             "Cluster role of this instance: -1=not started 0=token "
             "client 1=token server")
    b.sample("sentinel_tpu_cluster_ha_role", None, ha.get("role", -1))
    b.family("sentinel_tpu_cluster_ha_epoch", "gauge",
             "Highest leadership epoch this instance has applied or "
             "observed (0: pre-HA / never clustered)")
    b.sample("sentinel_tpu_cluster_ha_epoch", None, ha.get("epoch", 0))
    b.counter("sentinel_tpu_cluster_ha_failovers",
              "Token-client failovers to a different server in the map "
              "order", ha.get("failoverCount", 0))
    b.counter("sentinel_tpu_cluster_ha_stale_epoch_rejected",
              "Responses rejected by the epoch fence (deposed-leader "
              "replies)", ha.get("staleEpochRejected", 0))
    b.family("sentinel_tpu_cluster_ha_degraded", "gauge",
             "1 while the token client serves per-client-share degraded "
             "verdicts (no leader reachable)")
    b.sample("sentinel_tpu_cluster_ha_degraded", None,
             1 if ha.get("degraded") else 0)
    b.counter("sentinel_tpu_cluster_ha_degraded_seconds",
              "Cumulative seconds spent in degraded-quota mode",
              ha.get("degradedSeconds", 0.0))

    # -- sharded multi-leader cluster (cluster/sharding.py — ISSUE 12) ----
    # One family set for both roles: a LEADER reports slice ownership
    # and per-slice epochs; a routing CLIENT reports the degraded blast
    # radius. Absent (unsharded) instances render zeros so one scrape
    # config fits every role.
    shard = ha.get("shard") or {}
    mgr = ha.get("manager") or {}
    b.family("sentinel_tpu_shard_slices_owned", "gauge",
             "Hash slices this leader currently owns (0: not a sharded "
             "leader)")
    b.sample("sentinel_tpu_shard_slices_owned", None,
             shard.get("slicesOwned", 0))
    b.family("sentinel_tpu_shard_slice_epoch", "gauge",
             "Per-slice leadership epoch of each OWNED slice (the fence "
             "term stamped into that slice's verdicts)")
    for sl, ep in sorted(shard.get("sliceEpochs", {}).items(),
                         key=lambda kv: int(kv[0])):
        b.sample("sentinel_tpu_shard_slice_epoch", {"slice": str(sl)}, ep)
    b.counter("sentinel_tpu_shard_wrong_slice_rejected",
              "Requests answered (server) or observed (client) "
              "WRONG_SLICE: the flow hashed outside the reached "
              "leader's owned slices",
              shard.get("wrongSliceRejected", 0))
    b.counter("sentinel_tpu_shard_handoffs",
              "Slice handoffs this seat completed (donor publishes + "
              "recipient warm-starts through the checkpoint graft)",
              mgr.get("handoffs", 0))
    b.family("sentinel_tpu_shard_degraded_slices", "gauge",
             "Slices currently served from the per-client degraded "
             "share because their owning leader is unreachable")
    b.sample("sentinel_tpu_shard_degraded_slices", None,
             shard.get("degradedSlices", 0))

    # -- frontend overload (bounded ingestion — ISSUE 6) ------------------
    # Server-side families render -1 / nothing while this instance is
    # not a token server, so one scrape config fits every role.
    ov = res_stats.get("overload")
    b.counter("sentinel_tpu_overload_client_shed",
              "Entries whose cluster acquire came back OVERLOADED and "
              "were served via the local lease/fallback path",
              res_stats.get("clusterOverloadCount", 0))
    b.counter("sentinel_tpu_overload_client_responses",
              "OVERLOADED responses the failover token client observed "
              "(each opens a per-target retry-after backoff window)",
              ha.get("overloadedCount", 0))
    b.family("sentinel_tpu_overload_targets_backed_off", "gauge",
             "Token-server targets currently inside an overload-backoff "
             "window")
    b.sample("sentinel_tpu_overload_targets_backed_off", None,
             ha.get("targetsBackedOff", 0))
    b.family("sentinel_tpu_overload_queue_depth", "gauge",
             "Token-server admission queue depth in groups (-1: not a "
             "server)")
    b.sample("sentinel_tpu_overload_queue_depth", None,
             ov["queueDepth"] if ov else -1)
    b.family("sentinel_tpu_overload_queue_limit", "gauge",
             "Configured admission queue bound in groups (-1: not a "
             "server)")
    b.sample("sentinel_tpu_overload_queue_limit", None,
             ov["queueLimitGroups"] if ov else -1)
    b.family("sentinel_tpu_overload_queue_depth_max", "gauge",
             "High-water mark of the admission queue since server start "
             "(-1: not a server)")
    b.sample("sentinel_tpu_overload_queue_depth_max", None,
             ov["queueDepthMax"] if ov else -1)
    b.family("sentinel_tpu_overload_shed", "counter",
             "Request groups shed by the token-server frontend, by cause "
             "(watermark / queue_full / deadline_expired)")
    if ov:
        for cause, key in (("watermark", "shedWatermark"),
                           ("queue_full", "shedQueueFull"),
                           ("deadline_expired", "shedDeadlineExpired")):
            b.sample("sentinel_tpu_overload_shed_total", {"cause": cause},
                     ov[key])
    b.family("sentinel_tpu_overload_shed_requests", "counter",
             "Individual requests inside shed groups (every one received "
             "an explicit OVERLOADED reply)")
    if ov:
        b.sample("sentinel_tpu_overload_shed_requests_total", None,
                 ov["shedRequests"])

    # -- wire path (reactor ingestion — ISSUE 11) -------------------------
    # Families render -1 / nothing while this instance is not a reactor
    # token server, so one scrape config fits every role.
    wire = res_stats.get("wire")
    b.family("sentinel_tpu_wire_connections", "gauge",
             "Live connections multiplexed by the wire reactor (-1: not "
             "a reactor server)")
    b.sample("sentinel_tpu_wire_connections", None,
             wire["connections"] if wire else -1)
    b.family("sentinel_tpu_wire_coalesced_batch", "gauge",
             "Requests folded per fused wire batch (p50 over the recent "
             "window; -1: not a reactor server)")
    b.sample("sentinel_tpu_wire_coalesced_batch", None,
             wire["coalescedBatchP50"] if wire else -1)
    b.family("sentinel_tpu_wire_rtt_ms", "gauge",
             "Server-side request RTT (arrival to reply built), recent "
             "percentiles in ms")
    if wire:
        b.sample("sentinel_tpu_wire_rtt_ms", {"quantile": "0.50"},
                 wire["rttP50Ms"])
        b.sample("sentinel_tpu_wire_rtt_ms", {"quantile": "0.99"},
                 wire["rttP99Ms"])
    b.family("sentinel_tpu_wire_outbuf_shed", "counter",
             "Requests shed OVERLOADED because the connection's bounded "
             "reply backlog was full (slow consumer)")
    if wire:
        b.sample("sentinel_tpu_wire_outbuf_shed_total", None,
                 wire["outbufShed"])

    # -- staged rollout guardrail ----------------------------------------
    guard = res_stats.get("rollout") or {}
    b.family("sentinel_tpu_rollout_active", "gauge",
             "1 while a candidate ruleset holds the device")
    active = guard.get("activeCandidateSet")
    b.sample("sentinel_tpu_rollout_active", None, 1 if active else 0)
    if active:
        b.family("sentinel_tpu_rollout", "info",
                 "Active candidate set and stage")
        b.sample("sentinel_tpu_rollout_info",
                 {"name": active, "stage": guard.get("stage") or ""}, 1)
    b.family("sentinel_tpu_rollout_breach_streak", "gauge",
             "Consecutive guardrail windows over the block-rate delta")
    b.sample("sentinel_tpu_rollout_breach_streak", None,
             guard.get("breachStreak", 0))
    b.family("sentinel_tpu_rollout_promotion_epoch", "gauge",
             "Promotions since engine start")
    b.sample("sentinel_tpu_rollout_promotion_epoch", None,
             guard.get("promotionEpoch", 0))

    # -- step timing (profile parity) ------------------------------------
    timer = engine.step_timer.snapshot()
    b.family("sentinel_tpu_step_dispatches", "counter",
             "Device step dispatches per kind")
    for kind, row in sorted(timer.items()):
        b.sample("sentinel_tpu_step_dispatches_total", {"kind": kind},
                 row["dispatches"])
    b.family("sentinel_tpu_step_entries", "counter",
             "Entries carried by device dispatches per kind")
    for kind, row in sorted(timer.items()):
        b.sample("sentinel_tpu_step_entries_total", {"kind": kind},
                 row["entries"])
    b.family("sentinel_tpu_step_ms", "gauge",
             "Sampled synchronous step wall time percentiles (ms)")
    for kind, row in sorted(timer.items()):
        for q in ("50", "95", "99"):
            v = row.get(f"stepP{q}Ms")
            if v is not None:
                b.sample("sentinel_tpu_step_ms",
                         {"kind": kind, "quantile": f"0.{q}"}, v)
    b.family("sentinel_tpu_enqueue_ms", "gauge",
             "Dispatch enqueue wall time percentiles (ms)")
    for kind, row in sorted(timer.items()):
        for q in ("50", "95", "99"):
            v = row.get(f"enqueueP{q}Ms")
            if v is not None:
                b.sample("sentinel_tpu_enqueue_ms",
                         {"kind": kind, "quantile": f"0.{q}"}, v)

    # -- pipelined admission (core/pipeline.py — ISSUE 8) ------------------
    # Cycle/entry counters are monotone across pipeline start/stop
    # generations (engine._pipeline_totals); depth + wait splits answer
    # "is pipelined latency queue wait or device wait" at a glance.
    pl = engine.pipeline_stats()
    b.family("sentinel_tpu_pipeline_active", "gauge",
             "1 while the micro-batch collector owns admission")
    b.sample("sentinel_tpu_pipeline_active", None, 1 if pl["active"] else 0)
    b.family("sentinel_tpu_pipeline_inflight_depth", "gauge",
             "Entry cycles currently in flight on the device stream")
    b.sample("sentinel_tpu_pipeline_inflight_depth", None,
             pl["inflightDepth"])
    b.family("sentinel_tpu_pipeline_inflight_depth_max", "gauge",
             "High-water mark of overlapped entry cycles since engine "
             "start (2+ = double buffering engaged)")
    b.sample("sentinel_tpu_pipeline_inflight_depth_max", None,
             pl["inflightDepthMax"])
    b.counter("sentinel_tpu_pipeline_cycles",
              "Dispatched pipelined entry cycles", pl["cycles"])
    b.counter("sentinel_tpu_pipeline_entries",
              "Entries batched through the pipeline", pl["batched"])
    b.counter("sentinel_tpu_pipeline_fail_open_cycles",
              "Pipeline cycles whose tickets failed open (dispatch or "
              "harvest death)", pl["failOpenCycles"])
    b.counter("sentinel_tpu_pipeline_pool_allocated",
              "Staging buffers the pipeline pool allocated fresh",
              pl["poolAllocated"])
    b.counter("sentinel_tpu_pipeline_pool_reused",
              "Staging-buffer acquisitions served from the pool",
              pl["poolReused"])
    b.family("sentinel_tpu_pipeline_queue_wait_ms", "gauge",
             "Oldest-ticket submit-to-dispatch wait per harvested cycle "
             "(rolling percentiles, ms)")
    for q in ("50", "95"):
        b.sample("sentinel_tpu_pipeline_queue_wait_ms",
                 {"quantile": f"0.{q}"}, pl[f"queueWaitP{q}Ms"])
    b.family("sentinel_tpu_pipeline_device_wait_ms", "gauge",
             "Harvest block on the materialized verdicts per cycle "
             "(rolling percentiles, ms)")
    for q in ("50", "95"):
        b.sample("sentinel_tpu_pipeline_device_wait_ms",
                 {"quantile": f"0.{q}"}, pl[f"deviceWaitP{q}Ms"])

    # -- step duration (continuous, SLO-targetable) ------------------------
    # Cumulative histogram of the sampled synchronous step walls: unlike
    # the rolling sentinel_tpu_step_ms quantile gauges above (post-hoc,
    # cleared on profile reset), these counters are monotone for the
    # engine's lifetime, so a scraper can rate() them and a step-latency
    # SLO can burn against them.
    from sentinel_tpu.metrics.profiling import STEP_DURATION_EDGES_MS

    b.family("sentinel_tpu_step_duration_ms", "histogram",
             "Sampled synchronous device step wall time (ms, log2 "
             "buckets, cumulative since engine start)")
    for kind, row in sorted(engine.step_timer.duration_histogram().items()):
        b.histogram("sentinel_tpu_step_duration_ms", {"kind": kind},
                    [float(e) for e in STEP_DURATION_EDGES_MS],
                    [float(x) for x in row["buckets"]], row["sumMs"])

    # -- latency waterfall (telemetry/waterfall.py — ISSUE 18) -------------
    # Per-stage wire/pipeline latency on the shared log2 ladder
    # (cumulative since engine start), the end-to-end RTT histogram with
    # OpenMetrics exemplars joining slow buckets to stitched trace ids,
    # the last sealed second's derived queueing gauges (-1 = no sealed
    # second yet, the exporter's absent convention), and the regression
    # sentry's committed stage budgets.
    wf = getattr(engine, "waterfall", None)
    if wf is not None:
        from sentinel_tpu.telemetry.attribution import WF_BUCKET_EDGES_MS

        wstate = wf.export_state()
        wf_edges = [float(e) for e in WF_BUCKET_EDGES_MS]
        b.family("sentinel_tpu_waterfall_stage_ms", "histogram",
                 "Per-stage wire/pipeline latency (ms, shared log2 "
                 "buckets, cumulative since engine start)")
        for lane in sorted(wstate["hist"]):
            for stage, (buckets, total) in wstate["hist"][lane].items():
                b.histogram("sentinel_tpu_waterfall_stage_ms",
                            {"lane": lane, "stage": stage}, wf_edges,
                            [float(x) for x in buckets], total)
        rtt_buckets, rtt_sum = wstate["rtt"]
        wf_exemplars = {
            bi: ({"trace_id": ex["traceId"]}, ex["valueMs"],
                 ex["timestampMs"] / 1000.0)
            for bi, ex in wstate["rttExemplars"].items()}
        b.family("sentinel_tpu_waterfall_rtt_ms", "histogram",
                 "End-to-end wire RTT, arrival to flush (ms, log2 "
                 "buckets) with trace-id exemplars on sampled slow "
                 "requests")
        b.histogram("sentinel_tpu_waterfall_rtt_ms", {}, wf_edges,
                    [float(x) for x in rtt_buckets], rtt_sum,
                    exemplars=wf_exemplars)
        last_wf = wstate["last"]
        b.family("sentinel_tpu_waterfall_stage_concurrency", "gauge",
                 "Little's-law inferred in-stage concurrency over the "
                 "last sealed second, per lane/stage")
        if last_wf is not None:
            for lane, stages in sorted(last_wf["lanes"].items()):
                for stage, cell in stages.items():
                    b.sample("sentinel_tpu_waterfall_stage_concurrency",
                             {"lane": lane, "stage": stage},
                             cell["concurrency"])
        b.family("sentinel_tpu_waterfall_device_utilization", "gauge",
                 "Fused-batch device busy fraction of the last sealed "
                 "second (-1 = none sealed yet)")
        b.sample("sentinel_tpu_waterfall_device_utilization", None,
                 last_wf["deviceUtilization"] if last_wf is not None else -1)
        b.family("sentinel_tpu_waterfall_coalesce_efficiency", "gauge",
                 "Requests per fused batch in the last sealed second "
                 "(-1 = none sealed yet)")
        b.sample("sentinel_tpu_waterfall_coalesce_efficiency", None,
                 last_wf["coalesce"]["efficiency"]
                 if last_wf is not None else -1)
        b.counter("sentinel_tpu_waterfall_seconds",
                  "Sealed waterfall seconds", wstate["sealedSeconds"])
        b.counter("sentinel_tpu_waterfall_exemplars",
                  "Exemplars captured from traced slow requests",
                  wstate["exemplarsCaptured"])
        b.family("sentinel_tpu_waterfall_budget_ms", "gauge",
                 "Committed per-stage latency budget the regression "
                 "sentry burns against (ms)")
        for key, budget in sorted(wstate["budgetsMs"].items()):
            b.sample("sentinel_tpu_waterfall_budget_ms", {"stage": key},
                     budget)

    # -- flight recorder (per-second series) ------------------------------
    # The LAST complete second per resource as gauges: scrapers that
    # cannot ingest the `timeseries` command still get a per-second
    # trajectory at 1 Hz scrape cadence (cumulative counters above give
    # totals; these give the derivative, device-exact).
    ts = engine.timeseries_view(limit=1)
    last = ts["seconds"][-1] if ts["seconds"] else None
    b.family("sentinel_tpu_second_pass", "gauge",
             "Admitted entries in the last complete flight-recorder "
             "second, per resource")
    if last is not None:
        for res, vals in sorted(last["resources"].items()):
            b.sample("sentinel_tpu_second_pass", {"resource": res},
                     vals["pass"])
    b.family("sentinel_tpu_second_block", "gauge",
             "Blocked entries in the last complete flight-recorder "
             "second, per resource")
    if last is not None:
        for res, vals in sorted(last["resources"].items()):
            b.sample("sentinel_tpu_second_block", {"resource": res},
                     vals["block"])
    b.family("sentinel_tpu_timeseries_last_second", "gauge",
             "Stamp (ms) of the newest complete flight-recorder second "
             "(-1: none recorded yet)")
    b.sample("sentinel_tpu_timeseries_last_second", None,
             last["timestamp"] if last is not None else -1)
    b.family("sentinel_tpu_timeseries_retained_seconds", "gauge",
             "Complete seconds retained in the host-side history")
    b.sample("sentinel_tpu_timeseries_retained_seconds", None,
             ts["retainedSeconds"])

    # -- namespace telescope (telemetry/population.py — ISSUE 19) ---------
    # (AFTER the timeseries_view read: its fold rolled the tracker,
    # so the snapshot is current through the newest complete second.)
    # Population sensing as gauges/counters: cardinality (global HLL),
    # hot-set mass and the Space-Saving floor (the exact-vs-bounded
    # seam), churn turnover, the cardinality-growth alarm, and the
    # fold-overhead self-measurement the bench phase trends.
    population = getattr(engine, "population", None)
    if population is not None:
        pstate = population.snapshot(windows=1)
        b.family("sentinel_tpu_population_enabled", "gauge",
                 "Namespace telescope enabled (0/1)")
        b.sample("sentinel_tpu_population_enabled", None,
                 1 if pstate["enabled"] else 0)
        b.counter("sentinel_tpu_population_observed",
                  "Total (key, count) traffic folded into the "
                  "population sketches", pstate["observed"])
        b.family("sentinel_tpu_population_distinct", "gauge",
                 "HyperLogLog distinct-key estimate since engine start "
                 "(stderr 1.04/sqrt(2^p))")
        b.sample("sentinel_tpu_population_distinct", None,
                 pstate["distinct"])
        b.family("sentinel_tpu_population_window_distinct", "gauge",
                 "Distinct-key estimate of the last sealed churn "
                 "window (-1 = none sealed yet)")
        b.sample("sentinel_tpu_population_window_distinct", None,
                 pstate["churn"][-1]["distinct"] if pstate["churn"] else -1)
        b.family("sentinel_tpu_population_ss_floor", "gauge",
                 "Space-Saving eviction floor: upper bound on any "
                 "absent key's true count (0 = summary unsaturated, "
                 "every entry exact)")
        b.sample("sentinel_tpu_population_ss_floor", None,
                 pstate["ssFloor"])
        b.family("sentinel_tpu_population_hot_mass", "gauge",
                 "Fraction of observed traffic held by the top-k "
                 "summary (upper estimates)")
        total_obs = pstate["observed"]
        hot = sum(e["count"] for e in pstate["topk"])
        b.sample("sentinel_tpu_population_hot_mass", None,
                 round(hot / total_obs, 6) if total_obs else 0.0)
        b.counter("sentinel_tpu_population_churn_entered",
                  "Cumulative top-k ring entries across sealed churn "
                  "windows", pstate["enteredTotal"])
        b.counter("sentinel_tpu_population_churn_exited",
                  "Cumulative top-k ring exits across sealed churn "
                  "windows", pstate["exitedTotal"])
        b.family("sentinel_tpu_population_cardinality_z", "gauge",
                 "Last churn window's cardinality z-score against the "
                 "EWMA baseline")
        b.sample("sentinel_tpu_population_cardinality_z", None,
                 pstate["baseline"]["lastZ"])
        b.family("sentinel_tpu_population_cardinality_alarm", "gauge",
                 "Cardinality-growth alarm firing (0/1)")
        b.sample("sentinel_tpu_population_cardinality_alarm", None,
                 1 if pstate["alarm"] else 0)
        b.counter("sentinel_tpu_population_fold_ms",
                  "Cumulative host milliseconds spent folding staged "
                  "pairs into the sketches", pstate["foldMsTotal"])

    # -- slot-table admission (core/slots.py — ISSUE 20) ------------------
    # Registry overflow is loud in BOTH modes (classic interning can
    # saturate too); the slot families render only in slot mode.
    b.counter("sentinel_tpu_registry_overflow",
              "Node registrations refused at registry capacity and "
              "degraded to pass-through rows", engine.registry.overflow_count)
    slots = getattr(engine, "slots", None)
    if slots is not None:
        sstate = slots.status()
        b.family("sentinel_tpu_slots_budget", "gauge",
                 "Device slot-table budget (rows, incl. the 2 reserved)")
        b.sample("sentinel_tpu_slots_budget", None, sstate["budget"])
        b.family("sentinel_tpu_slots_hot", "gauge",
                 "Resources currently holding a device slot")
        b.sample("sentinel_tpu_slots_hot", None, sstate["hot"])
        b.family("sentinel_tpu_slots_free", "gauge",
                 "Unoccupied device slots")
        b.sample("sentinel_tpu_slots_free", None, sstate["free"])
        b.family("sentinel_tpu_slots_pinned", "gauge",
                 "Resources pinned hot by compiled rules (never stolen)")
        b.sample("sentinel_tpu_slots_pinned", None, sstate["pinnedNow"])
        b.family("sentinel_tpu_slots_frozen", "gauge",
                 "Manual steal freeze in force (0/1; churn-alarm and "
                 "telemetry-stale freezes are visible in `slots` status)")
        b.sample("sentinel_tpu_slots_frozen", None,
                 1 if sstate["frozen"] else 0)
        b.counter("sentinel_tpu_slots_admits",
                  "Resources admitted into a device slot",
                  sstate["admitsTotal"])
        b.counter("sentinel_tpu_slots_evictions",
                  "Occupants evicted from a device slot (spilled "
                  "host-side)", sstate["evictionsTotal"])
        b.counter("sentinel_tpu_slots_rehydrations",
                  "Admissions that grafted (or cold-started) a "
                  "previously spilled resource", sstate["rehydrationsTotal"])
        b.counter("sentinel_tpu_slots_rehydrations_cold",
                  "Rehydrations with NO usable spill record (torn, "
                  "dropped, or first touch)", sstate["rehydrationsColdTotal"])
        b.counter("sentinel_tpu_slots_steals",
                  "Slots stolen from a colder occupant by a "
                  "telescope-ranked challenger", sstate["stealsTotal"])
        b.counter("sentinel_tpu_slots_storms",
                  "Chaos eviction storms executed (slots.evict.storm)",
                  sstate["stormsTotal"])
        b.counter("sentinel_tpu_slots_hot_hits",
                  "Entries admitted through a device slot or hot lease",
                  sstate["hotHitsTotal"])
        b.counter("sentinel_tpu_slots_cold_pass",
                  "Cold-tail entries passed on the host lease path",
                  sstate["coldPassTotal"])
        b.counter("sentinel_tpu_slots_cold_block",
                  "Cold-tail entries blocked host-exact by their lease",
                  sstate["coldBlockTotal"])
        b.counter("sentinel_tpu_slots_cold_unenforced",
                  "Cold-tail passes whose GUARDED rules could not be "
                  "enforced off-device (the loud degradation)",
                  sstate["coldUnenforcedTotal"])
        b.counter("sentinel_tpu_slots_spill_torn",
                  "Spill records torn in flight (victim rehydrates cold)",
                  sstate["spillTornTotal"])
        b.counter("sentinel_tpu_slots_spill_dropped",
                  "Spill records dropped at the LRU retention cap",
                  sstate["spillDroppedTotal"])
        b.counter("sentinel_tpu_slots_late_exits",
                  "Exits landing after their slot tenancy was evicted "
                  "(reconciled host-side)", sstate["lateExitsTotal"])
        b.counter("sentinel_tpu_slots_pin_overflow",
                  "Rule-pinned resources that exceeded the slot budget "
                  "(rule enforced cold, loudly)", sstate["pinOverflowTotal"])
        b.family("sentinel_tpu_slots_hit_rate", "gauge",
                 "Hot-set hit rate since start: hot admissions over all "
                 "admissions")
        b.sample("sentinel_tpu_slots_hit_rate", None, sstate["hitRate"])
        b.family("sentinel_tpu_slots_spill_records", "gauge",
                 "Spill records currently retained host-side")
        b.sample("sentinel_tpu_slots_spill_records", None,
                 sstate["spillRecords"])

    # -- SLO engine + alerting (sentinel_tpu/slo/) ------------------------
    # The timeseries_view read above already refreshed judgement (spill
    # feeds the SLO manager and re-evaluates burn rules), so these render
    # current through the newest complete second.
    slo = engine.slo
    slo_status = slo.status()
    health = slo_status["health"]
    b.family("sentinel_tpu_slo_objectives", "gauge",
             "Configured SLO objectives")
    b.sample("sentinel_tpu_slo_objectives", None,
             len(slo_status["objectives"]))
    b.family("sentinel_tpu_slo_burn_rate", "gauge",
             "Multi-window burn rate per (objective, window side): "
             "error rate over the window divided by the error budget; "
             ">= the rule's threshold on BOTH sides fires the alert")
    for key, snap in sorted(slo_status["burn"].items()):
        for rule in snap["rules"]:
            labels = {"objective": key, "resource": snap["resource"],
                      "sli": snap["sli"], "severity": rule["severity"]}
            b.sample("sentinel_tpu_slo_burn_rate",
                     {**labels, "window": f"{rule['longSeconds']}s"},
                     round(rule["burnLong"], 6))
            b.sample("sentinel_tpu_slo_burn_rate",
                     {**labels, "window": f"{rule['shortSeconds']}s"},
                     round(rule["burnShort"], 6))
    b.family("sentinel_tpu_slo_baseline_zscore", "gauge",
             "Latest z-score of each objective-less resource's signal "
             "against its own EWMA baseline")
    for res, signals in sorted(slo_status["baselines"].items()):
        for sig, snap in sorted(signals.items()):
            if snap["warmedUp"]:
                b.sample("sentinel_tpu_slo_baseline_zscore",
                         {"resource": res, "signal": sig}, snap["lastZ"])
    b.family("sentinel_tpu_slo_health_score", "gauge",
             "Composite health per resource (100 = healthy; page -40, "
             "ticket -20, anomaly -15 per active alert)")
    for res, score in sorted(health["resources"].items()):
        b.sample("sentinel_tpu_slo_health_score", {"resource": res}, score)
    b.family("sentinel_tpu_slo_instance_health", "gauge",
             "Composite instance health: worst resource score minus the "
             "overload shed-rate penalty")
    b.sample("sentinel_tpu_slo_instance_health", None, health["instance"])
    b.family("sentinel_tpu_slo_shed_rate", "gauge",
             "Token-server admission shed fraction since the previous "
             "evaluation (health-score input; 0 while not a server)")
    b.sample("sentinel_tpu_slo_shed_rate", None, health["shedRate"])
    alerts = slo.alerts_snapshot(limit=0)
    by_sev: Dict[str, int] = {}
    for a in alerts["active"]:
        by_sev[a["severity"]] = by_sev.get(a["severity"], 0) + 1
    b.family("sentinel_tpu_alert_active", "gauge",
             "Currently firing alerts per severity")
    for sev in ("page", "ticket", "anomaly"):
        b.sample("sentinel_tpu_alert_active", {"severity": sev},
                 by_sev.get(sev, 0))
    b.counter("sentinel_tpu_alert_fired",
              "Alert fire transitions since engine start",
              alerts["counters"]["fired"])
    b.counter("sentinel_tpu_alert_resolved",
              "Alert resolve transitions since engine start",
              alerts["counters"]["resolved"])
    wh = alerts["webhook"]
    b.counter("sentinel_tpu_alert_webhook_delivered",
              "Alert events delivered to a webhook endpoint (2xx)",
              wh["delivered"])
    b.counter("sentinel_tpu_alert_webhook_failed",
              "Alert events that exhausted their webhook retry budget",
              wh["failed"])
    b.counter("sentinel_tpu_alert_webhook_dropped",
              "Alert events dropped from the full webhook queue",
              wh["dropped"])

    # -- closed-loop adaptive limiting (sentinel_tpu/adaptive/) ----------
    ad = engine.adaptive.guardrail_state()
    b.family("sentinel_tpu_adaptive_enabled", "gauge",
             "1 while the adaptive loop may propose rule retunes")
    b.sample("sentinel_tpu_adaptive_enabled", None,
             1 if ad["enabled"] else 0)
    b.family("sentinel_tpu_adaptive_frozen", "gauge",
             "1 while the safety envelope holds the loop read-only "
             "(manual freeze, stale/faulted telemetry, abort backoff)")
    b.sample("sentinel_tpu_adaptive_frozen", None, 1 if ad["frozen"] else 0)
    b.counter("sentinel_tpu_adaptive_proposals",
              "Per-resource rule retunes proposed into a rollout "
              "candidate since engine start",
              ad["proposals"])
    b.counter("sentinel_tpu_adaptive_promotions",
              "Adaptive candidates promoted into the live rules "
              "(always through the rollout manager)",
              ad["promotions"])
    b.counter("sentinel_tpu_adaptive_aborts",
              "Adaptive candidates aborted (guardrail, SLO breach, "
              "freeze, or operator) — each starts the backoff window",
              ad["aborts"])
    b.counter("sentinel_tpu_adaptive_clamped",
              "Policy asks the envelope clamped (step/floor/ceiling) "
              "or rejected as band-edge no-ops",
              ad["clamped"])
    b.family("sentinel_tpu_adaptive_target_delta", "gauge",
             "Latest sensed block rate minus the target per adaptive "
             "resource (positive = still blocking above target)")
    for res, delta in sorted(engine.adaptive.target_deltas().items()):
        b.sample("sentinel_tpu_adaptive_target_delta",
                 {"resource": res}, delta)

    # -- LLM admission & streaming reservations (sentinel_tpu/llm/) ------
    st = engine.streams.stats()
    b.family("sentinel_tpu_llm_rules", "gauge",
             "Live TPS rules (per-model token budgets lowered onto the "
             "flow family)")
    b.sample("sentinel_tpu_llm_rules", None,
             len(engine.tps_rules.get_rules()))
    b.family("sentinel_tpu_llm_streams_active", "gauge",
             "Streaming reservations currently open in the ledger")
    b.sample("sentinel_tpu_llm_streams_active", None, st["active"])
    b.counter("sentinel_tpu_llm_streams_opened",
              "Streaming reservations admitted since engine start",
              st["opened"])
    b.counter("sentinel_tpu_llm_streams_blocked",
              "Stream opens rejected (window, concurrency cap, or "
              "ledger capacity)",
              st["openBlocked"])
    b.counter("sentinel_tpu_llm_streams_aborted",
              "Streams closed by abort (the remainder returned as "
              "expiring credit)",
              st["aborted"])
    b.counter("sentinel_tpu_llm_streams_evicted",
              "Idle streams evicted by the spill-cadence sweep "
              "(abandoned generations)",
              st["evicted"])
    b.counter("sentinel_tpu_llm_tokens_debited",
              "Tokens debited into TPS windows (reservations + "
              "overflow ticks)",
              st["tokensDebited"])
    b.counter("sentinel_tpu_llm_tokens_streamed",
              "Actual output tokens reconciled through stream ticks",
              st["tokensStreamed"])
    b.counter("sentinel_tpu_llm_tokens_released",
              "Unconsumed reservation tokens released at "
              "close/abort/evict",
              st["tokensReleased"])
    b.family("sentinel_tpu_llm_reservation_outstanding", "gauge",
             "Reserved-but-unstreamed tokens across open leases (the "
             "reconciliation backlog; drains to zero when idle)")
    b.sample("sentinel_tpu_llm_reservation_outstanding", None,
             st["outstandingTokens"])
    b.family("sentinel_tpu_llm_credit_tokens", "gauge",
             "Released tokens still reusable before their window "
             "rolls off")
    b.sample("sentinel_tpu_llm_credit_tokens", None, st["creditTokens"])

    # -- trace-replay simulator (sentinel_tpu/simulator/) ----------------
    # Process-wide, not per-engine: the offline lab runs on its own sim
    # engines; this exposition is where its last verdict lands for
    # scrapers and the dashboard Simulator panel.
    from sentinel_tpu.simulator.lab import counters as sim_counters
    from sentinel_tpu.simulator.lab import last_report as sim_last_report

    simc = sim_counters()
    b.counter("sentinel_tpu_sim_lab_runs",
              "Policy-lab comparison runs completed in this process",
              simc["labRuns"])
    b.counter("sentinel_tpu_sim_replayed_seconds",
              "Simulated seconds replayed through the policy lab",
              simc["replayedSeconds"])
    report = sim_last_report()
    b.family("sentinel_tpu_sim_replay_rate", "gauge",
             "Last lab run's simulated seconds per wall second "
             "(accelerated-clock speedup; 0 until a lab run completes)")
    b.sample("sentinel_tpu_sim_replay_rate", None,
             (report or {}).get("secondsPerWallSecond", 0))
    b.family("sentinel_tpu_sim_policy_score", "gauge",
             "Last lab run's scalarized objective score per "
             "(scenario, policy) — higher is better; see the `sim` "
             "command for the full objective vectors")
    for scen, cell in sorted((report or {}).get("results", {}).items()):
        for pol, run in sorted(cell.items()):
            b.sample("sentinel_tpu_sim_policy_score",
                     {"scenario": scen, "policy": pol}, run["score"])

    # -- chaos campaign engine (sentinel_tpu/chaos/) -----------------------
    # Process-wide like the simulator's: campaigns run on their own
    # throwaway meshes; the counters land here for scrapers and CI. A
    # deployment that strips the chaos tooling (the mode cluster/ha.py's
    # regression guard supports) reports zeroed families, never a dead
    # /metrics surface.
    try:
        from sentinel_tpu.chaos import counters as chaos_counters

        chc = chaos_counters()
    except ImportError:
        chc = {"episodes": 0, "violations": 0, "faultsFired": 0,
               "shrinkSteps": 0}
    b.counter("sentinel_tpu_chaos_episodes",
              "Chaos-campaign episodes completed in this process",
              chc["episodes"])
    b.counter("sentinel_tpu_chaos_violations",
              "Invariant violations detected by chaos campaigns "
              "(any growth is a finding, not noise)",
              chc["violations"])
    b.counter("sentinel_tpu_chaos_faults_fired",
              "Faults fired / chaos actions executed across campaigns",
              chc["faultsFired"])
    b.counter("sentinel_tpu_chaos_shrink_steps",
              "Delta-debugging re-runs spent minimizing violating "
              "fault schedules",
              chc["shrinkSteps"])

    # -- control-plane audit journal (telemetry/journal.py) ---------------
    jstats = engine.journal.stats()
    b.family("sentinel_tpu_journal_last_seq", "gauge",
             "Highest audit-journal seq (monotone across restarts when "
             "a file backs the journal)")
    b.sample("sentinel_tpu_journal_last_seq", None, jstats["lastSeq"])
    b.counter("sentinel_tpu_journal_records",
              "Audit records appended by this process",
              jstats["appended"])
    b.counter("sentinel_tpu_journal_dropped_partial",
              "Torn tail records dropped (loudly) during crash recovery",
              jstats["droppedPartial"])
    b.counter("sentinel_tpu_journal_rotations",
              "Journal file segment rotations", jstats["rotations"])
    b.family("sentinel_tpu_journal_durable", "gauge",
             "1 while a file backs the journal (0: in-memory tail only)")
    b.sample("sentinel_tpu_journal_durable", None,
             1 if jstats["durable"] else 0)

    # -- fleet federation (telemetry/fleet.py) ----------------------------
    # Families render -1 / nothing while no FleetView collector is
    # attached, so one scrape config fits every role.
    fleet = engine.fleet
    fstatus = fleet.status() if fleet is not None else None
    b.family("sentinel_tpu_fleet_leaders", "gauge",
             "Leaders the attached FleetView federates (-1: no "
             "collector attached)")
    b.sample("sentinel_tpu_fleet_leaders", None,
             fstatus["leaderCount"] if fstatus else -1)
    b.family("sentinel_tpu_fleet_stale_leaders", "gauge",
             "Leaders whose newest complete second is older than the "
             "staleness bound")
    b.sample("sentinel_tpu_fleet_stale_leaders", None,
             fstatus["staleLeaders"] if fstatus else -1)
    b.family("sentinel_tpu_fleet_health", "gauge",
             "Fleet health: min of the federated leaders' instance "
             "health scores (-1: no collector / no data)")
    fh = (fstatus or {}).get("fleetHealth")
    b.sample("sentinel_tpu_fleet_health", None, fh if fh is not None else -1)
    b.family("sentinel_tpu_fleet_retained_seconds", "gauge",
             "Fleet-wide per-second records the collector retains")
    b.sample("sentinel_tpu_fleet_retained_seconds", None,
             fstatus["retainedSeconds"] if fstatus else -1)
    b.family("sentinel_tpu_fleet_skew_ms", "gauge",
             "Signed clock skew per federated leader (leader nowMs "
             "minus collector clock at receive)")
    if fstatus:
        for name, row in sorted(fstatus["leaders"].items()):
            if row["skewMs"] is not None:
                b.sample("sentinel_tpu_fleet_skew_ms", {"leader": name},
                         row["skewMs"])
    b.counter("sentinel_tpu_fleet_polls",
              "FleetView scrape cycles completed",
              fstatus["polls"] if fstatus else 0)
    b.counter("sentinel_tpu_fleet_poll_errors",
              "Leader page pulls that returned no payload",
              fstatus["pollErrors"] if fstatus else 0)

    # -- governed shard rebalancing (cluster/rebalance.py) ----------------
    rb = getattr(engine, "rebalancer", None)
    rstate = rb.metrics_state() if rb is not None else None
    b.counter("sentinel_tpu_rebalance_plans",
              "Rebalance plans proposed (skew / join / leave)",
              rstate["plans"] if rstate else 0)
    b.counter("sentinel_tpu_rebalance_applies",
              "Certified plans applied through the HA map path",
              rstate["applies"] if rstate else 0)
    b.counter("sentinel_tpu_rebalance_rollbacks",
              "Last-known-good ownership restores",
              rstate["rollbacks"] if rstate else 0)
    b.counter("sentinel_tpu_rebalance_vetoes",
              "Plans/applies refused by the safety envelope (freeze, "
              "cooldown, certification, stale plan)",
              rstate["vetoes"] if rstate else 0)
    b.counter("sentinel_tpu_rebalance_slices_moved",
              "Slices whose owner changed via applied rebalance plans",
              rstate["slices_moved"] if rstate else 0)
    b.family("sentinel_tpu_rebalance_frozen", "gauge",
             "1 while the freeze gate blocks new plans (manual, stale "
             "telemetry, degraded leader, or abort backoff)")
    b.sample("sentinel_tpu_rebalance_frozen", None,
             rstate["frozen"] if rstate else 0)
    b.family("sentinel_tpu_rebalance_skew", "gauge",
             "Last sensed leader-load skew ((max-min)/mean over the "
             "slice-granular fleet fold)")
    b.sample("sentinel_tpu_rebalance_skew", None,
             rstate["skew"] if rstate else 0)

    # -- span sampling health --------------------------------------------
    ssnap = engine.spans.snapshot(limit=0)
    b.counter("sentinel_tpu_spans_seen",
              "Cluster-checked entries observed by the span sampler",
              ssnap["seen"])
    b.counter("sentinel_tpu_spans_recorded",
              "Cross-process spans retained in the host ring",
              ssnap["recorded"])

    # -- trace sampling health -------------------------------------------
    tsnap = engine.traces.snapshot(limit=0)
    b.counter("sentinel_tpu_traces_seen_blocked",
              "Blocked entries observed by the trace sampler",
              tsnap["seenBlocked"])
    b.counter("sentinel_tpu_traces_recorded",
              "Decision traces retained in the host ring",
              tsnap["recorded"])
    b.counter("sentinel_tpu_traces_dropped_batches",
              "Dispatched batches the sampler dropped (hand-off queue "
              "full) — sampling degradation signal",
              tsnap["droppedBatches"])
    b.counter("sentinel_tpu_traces_errors",
              "Queued batches the trace worker failed to process",
              tsnap["errors"])

    return b.render()


def render_dashboard_metrics(dashboard) -> str:
    """Dashboard-side aggregates (its repository + discovery state) as
    OpenMetrics — the fleet view beside each engine's own ``/metrics``."""
    import time as _time

    b = OpenMetricsBuilder()
    apps = dashboard.apps
    b.family("sentinel_tpu_dashboard_machines", "gauge",
             "Machines registered per app (healthy only)")
    for app in sorted(apps.app_names()):
        b.sample("sentinel_tpu_dashboard_machines", {"app": app},
                 len(apps.healthy_machines(app)))
    now_ms = int(_time.time() * 1000)
    rows = []
    for app in dashboard.repository.apps():
        for res in dashboard.repository.resources_of(app):
            series = dashboard.repository.query(
                app, res, now_ms - 120_000, now_ms)
            if series:
                rows.append((app, res, series[-1]))
    b.family("sentinel_tpu_dashboard_resource_pass_qps", "gauge",
             "Latest aggregated pass QPS per (app, resource)")
    for app, res, latest in rows:
        b.sample("sentinel_tpu_dashboard_resource_pass_qps",
                 {"app": app, "resource": res}, latest["passQps"])
    b.family("sentinel_tpu_dashboard_resource_block_qps", "gauge",
             "Latest aggregated block QPS per (app, resource)")
    for app, res, latest in rows:
        b.sample("sentinel_tpu_dashboard_resource_block_qps",
                 {"app": app, "resource": res}, latest["blockQps"])
    b.family("sentinel_tpu_dashboard_sse_clients", "gauge",
             "Live /telemetry/stream consumers currently connected")
    b.sample("sentinel_tpu_dashboard_sse_clients", None,
             getattr(dashboard, "sse_clients", 0))
    return b.render()

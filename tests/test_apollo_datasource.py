"""Apollo datasource connector tests (SURVEY.md §2.2, reference
``sentinel-datasource-apollo``): notifications/v2 long-poll over real
HTTP — initial config fetch, change notification → re-fetch, releaseKey
304 suppression, open-api item+release writable two-step, working-copy
invisibility until release, auth token, bad payloads, and reconnect
catch-up across a server restart.
"""

import json
import time

import pytest

import sentinel_tpu as st
from sentinel_tpu.datasource import bind
from sentinel_tpu.datasource.converters import (
    flow_rules_from_json,
    flow_rules_to_json,
)
from sentinel_tpu.datasource.apollo import (
    ApolloDataSource,
    ApolloWritableDataSource,
    MiniApolloServer,
)

APP, NS, KEY = "demo-app", "application", "flowRules"


@pytest.fixture()
def server():
    s = MiniApolloServer(max_hold_ms=400).start()
    yield s
    s.stop()


def _wait_for(pred, timeout_s: float = 5.0) -> bool:
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return pred()


def _rules_json(*resources, count=5.0) -> str:
    return json.dumps([{"resource": r, "count": count} for r in resources])


def _source(server, **kw):
    kw.setdefault("poll_timeout_ms", 400)
    return ApolloDataSource(server.addr, APP, NS, KEY,
                            flow_rules_from_json, **kw)


def test_initial_fetch_loads_rules(server, engine):
    server.publish(APP, NS, KEY, _rules_json("pre"))
    src = _source(server).start()
    try:
        bind(src, st.load_flow_rules)
        assert [r.resource for r in engine.flow_rules.get_rules()] == ["pre"]
    finally:
        src.close()


def test_notification_pushes_rules(server, engine):
    src = _source(server).start()
    try:
        bind(src, st.load_flow_rules)
        server.publish(APP, NS, KEY, _rules_json("pushed"))
        assert _wait_for(lambda: [r.resource for r in
                                  engine.flow_rules.get_rules()] == ["pushed"])
        server.publish(APP, NS, KEY, _rules_json("again"))
        assert _wait_for(lambda: [r.resource for r in
                                  engine.flow_rules.get_rules()] == ["again"])
    finally:
        src.close()


def test_release_key_suppresses_requery(server, engine):
    """A 304 on an unchanged releaseKey proves the echo bookkeeping: the
    connector does not re-download an unchanged namespace."""
    server.publish(APP, NS, KEY, _rules_json("r1"))
    src = _source(server).start()
    try:
        bind(src, st.load_flow_rules)
        assert src._release_key  # adopted from the fetch
        # direct re-fetch with the adopted key → 304 → None
        assert src._fetch_config() is None
    finally:
        src.close()


def test_other_keys_in_namespace_ignored(server, engine):
    server.publish(APP, NS, KEY, _rules_json("mine"))
    src = _source(server).start()
    try:
        bind(src, st.load_flow_rules)
        assert [r.resource for r in engine.flow_rules.get_rules()] == ["mine"]
        # a release touching only OTHER keys keeps rules untouched
        server.publish(APP, NS, "unrelated.key", "whatever")
        time.sleep(0.3)
        assert [r.resource for r in engine.flow_rules.get_rules()] == ["mine"]
    finally:
        src.close()


def test_writable_two_step_and_working_copy_invisible(server, engine):
    src = _source(server).start()
    writer = ApolloWritableDataSource(server.addr, APP, NS, KEY,
                                      flow_rules_to_json)
    try:
        bind(src, st.load_flow_rules)
        writer.write([st.FlowRule(resource="created", count=7)])
        assert _wait_for(lambda: [r.resource for r in
                                  engine.flow_rules.get_rules()]
                         == ["created"])
        writer.write([st.FlowRule(resource="updated", count=8)])  # PUT path
        assert _wait_for(lambda: [r.resource for r in
                                  engine.flow_rules.get_rules()]
                         == ["updated"])
        # an item written WITHOUT a release stays invisible (Apollo's
        # actual durability model)
        with server._cond:
            server._working[(APP, "default", NS)][KEY] = _rules_json("draft")
        time.sleep(0.3)
        assert [r.resource for r in engine.flow_rules.get_rules()] \
            == ["updated"]
    finally:
        src.close()


def test_open_api_token_enforced(engine):
    server = MiniApolloServer(max_hold_ms=400, token="secret-token").start()
    try:
        bad = ApolloWritableDataSource(server.addr, APP, NS, KEY,
                                       flow_rules_to_json)
        with pytest.raises(OSError):
            bad.write([st.FlowRule(resource="x", count=1)])
        good = ApolloWritableDataSource(server.addr, APP, NS, KEY,
                                        flow_rules_to_json,
                                        token="secret-token")
        good.write([st.FlowRule(resource="x", count=1)])
        src = ApolloDataSource(server.addr, APP, NS, KEY,
                               flow_rules_from_json, poll_timeout_ms=400)
        assert b"x" in json.dumps(src.read_source()).encode() or \
            "x" in src.read_source()
    finally:
        server.stop()


def test_bad_payload_keeps_last_good(server, engine):
    src = _source(server).start()
    try:
        bind(src, st.load_flow_rules)
        server.publish(APP, NS, KEY, _rules_json("good"))
        assert _wait_for(lambda: [r.resource for r in
                                  engine.flow_rules.get_rules()] == ["good"])
        server.publish(APP, NS, KEY, "{not json!")
        time.sleep(0.3)
        assert [r.resource for r in engine.flow_rules.get_rules()] == ["good"]
    finally:
        src.close()


def test_server_restart_reconnects_and_catches_up(server, engine):
    src = _source(server, reconnect_backoff_ms=(20, 100)).start()
    try:
        bind(src, st.load_flow_rules)
        server.publish(APP, NS, KEY, _rules_json("before"))
        assert _wait_for(lambda: [r.resource for r in
                                  engine.flow_rules.get_rules()] == ["before"])
        server.stop()
        # a release lands while the connector is down (state survives the
        # restart, as a real Apollo's would)
        server.publish(APP, NS, KEY, _rules_json("during"))
        time.sleep(0.2)
        server.start()
        assert _wait_for(lambda: [r.resource for r in
                                  engine.flow_rules.get_rules()] == ["during"])
        server.publish(APP, NS, KEY, _rules_json("after"))
        assert _wait_for(lambda: [r.resource for r in
                                  engine.flow_rules.get_rules()] == ["after"])
    finally:
        src.close()

"""Pod-as-one-rate-limiter: mesh-parallel admission over ICI.

Reference architecture being replaced (SURVEY.md §2.4, §2.11, §3.3): the
``sentinel-cluster`` token server — a Netty TCP server owning the global
sliding window, with every client paying one RTT per ``requestToken`` and
degrading to local checks on failure (``FlowRuleChecker.passClusterCheck`` /
``fallbackToLocalOrPass``).

TPU-native design: there is no server process. Each device in the mesh holds
a full-capacity replica of the stats tensors carrying *its own* admitted
traffic (the reference's "every JVM holds its own full stats" replication,
§2.10), and the request stream is sharded over the device axis. Cluster-mode
flow rules admit against the POD-GLOBAL window: a ``psum`` over the mesh
axis folds every device's pass counts into one view, so the whole pod acts
as a single token server with zero RTTs — the collective rides ICI inside
one XLA program.

Exactness: within one micro-step a device sees other devices' counts as of
the step start, so overshoot is bounded by (devices − 1) × max per-device
batch admission for one rule — the quantified semantics delta of SURVEY.md
§7 (hard part #5). The reference's own cluster mode has an analogous window
(client-side batching + RTT staleness).

Multi-host pods work unchanged: ``jax.make_mesh`` over all devices spans
hosts, and XLA routes the same ``psum`` over ICI within a slice and DCN
across slices.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from sentinel_tpu.core import constants as C
from sentinel_tpu.core.batch import Decisions, EntryBatch, ExitBatch
from sentinel_tpu.ops import step as S
from sentinel_tpu.ops import window as W

try:  # jax >= 0.4.35 exposes shard_map at the top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

from jax.sharding import Mesh, PartitionSpec as P

AXIS = "pod"


def _squeeze0(tree):
    return jax.tree.map(lambda x: jnp.squeeze(x, 0), tree)


def _expand0(tree):
    return jax.tree.map(lambda x: x[None], tree)


def make_pod_state(n_devices: int, one: S.SentinelState) -> S.SentinelState:
    """Per-device replicated-structure state: leaves shaped [D, ...].

    ``one`` is a freshly built single-device state whose geometry matches
    the rule pack (same capacity / rule counts on every device).
    """
    return jax.tree.map(
        lambda x: jnp.broadcast_to(x[None], (n_devices,) + x.shape), one
    )


def global_pass_counts(w1: W.Window, axis: str) -> Tuple[jax.Array, jax.Array]:
    """(extra_pass[R], local_pass[R]): other-device / own pass totals."""
    local = W.all_totals(w1)[:, C.MetricEvent.PASS]
    total = jax.lax.psum(local, axis)
    return total - local, local


def global_next_window(w1: W.Window, occupied_next: jax.Array, now_ms: jax.Array,
                       axis: str) -> jax.Array:
    """extra_next[R]: other devices' NEXT-window usage (occupy borrows).

    A device's next-window usage is its window pass minus the bucket about
    to expire, plus its pending borrows. psum'd so prioritized occupy
    grants admit against the pod-global next window, not just the local
    slice (otherwise every device would lend up to the global threshold).
    """
    spec = S.SPEC_1S
    oldest_idx = jnp.mod(W.current_index(now_ms, spec) + 1, spec.buckets)
    oldest = w1.counts[oldest_idx, C.MetricEvent.PASS, :]
    local = (W.all_totals(w1)[:, C.MetricEvent.PASS] - oldest
             + occupied_next)
    return jax.lax.psum(local, axis) - local


def _pod_entry(state: S.SentinelState, rules: S.RulePack, batch: EntryBatch,
               now_ms: jax.Array, *, axis: str, cluster_param: bool,
               extra_checkers: tuple = (),
               occupy_timeout_ms: int = C.DEFAULT_OCCUPY_TIMEOUT_MS,
               shadow_rules=None, canary_bps=None, canary_salt=None,
               ) -> Tuple[S.SentinelState, Decisions]:
    local = _squeeze0(state)
    now_ms = jnp.asarray(now_ms, jnp.int64)
    w1 = W.rotate(local.w1, now_ms, S.SPEC_1S)
    extra_pass, _ = global_pass_counts(w1, axis)
    extra_next = global_next_window(w1, local.occupied_next, now_ms, axis)
    extra_cms = None
    if cluster_param:
        # Cluster-mode param rules admit against the pod-global sketch.
        # Roll the local sketch windows BEFORE the psum: every device
        # rolls at the same per-rule boundary, so the cross-device extra
        # never carries a stale window (which would zero the first step
        # of each fresh window).
        from sentinel_tpu.models import param_flow as PF

        local = local._replace(param=PF.roll_sketch_windows(
            rules.param, local.param, now_ms))
        extra_cms = jax.lax.psum(local.param.cms, axis) - local.param.cms
    shadow_extra_pass = None
    shadow_extra_cms = None
    if shadow_rules is not None and local.shadow is not None:
        # Shadow counters ride the same psum: the candidate's cluster-mode
        # rules admit against the POD-GLOBAL shadow window (other devices'
        # candidate-passed counts), so shadow-vs-live deltas are pod-exact
        # rather than per-slice. Rotate before the psum, same discipline
        # as the live window above.
        sh_w1 = W.rotate(local.shadow.w1, now_ms, S.SPEC_1S)
        shadow_extra_pass, _ = global_pass_counts(sh_w1, axis)
        local = local._replace(shadow=local.shadow._replace(w1=sh_w1))
        if cluster_param:
            sh_param = PF.roll_sketch_windows(
                shadow_rules.param, local.shadow.param, now_ms)
            local = local._replace(
                shadow=local.shadow._replace(param=sh_param))
            shadow_extra_cms = (jax.lax.psum(sh_param.cms, axis)
                                - sh_param.cms)
    # Hand the rotated window through so entry_step's own rotate hits the
    # cheap restamp branch instead of re-sweeping the counts tensor.
    new_local, dec = S.entry_step(local._replace(w1=w1), rules, batch, now_ms,
                                  extra_pass=extra_pass, extra_next=extra_next,
                                  extra_cms=extra_cms,
                                  extra_checkers=extra_checkers,
                                  occupy_timeout_ms=occupy_timeout_ms,
                                  shadow_rules=shadow_rules,
                                  canary_bps=canary_bps,
                                  canary_salt=canary_salt,
                                  shadow_extra_pass=shadow_extra_pass,
                                  shadow_extra_cms=shadow_extra_cms)
    return _expand0(new_local), dec


def _pod_exit(state: S.SentinelState, rules: S.RulePack, batch: ExitBatch,
              now_ms: jax.Array, *, axis: str,
              shadow_rules=None) -> S.SentinelState:
    del axis
    return _expand0(S.exit_step(_squeeze0(state), rules, batch, now_ms,
                                shadow_rules=shadow_rules))


def global_shadow_counts(state: S.SentinelState) -> Optional[jax.Array]:
    """Pod-global rollout counters from a [D, ...] pod state: the shadow
    counter tensor summed over the device axis (host-side read — every
    device accumulated only its own shard's lanes)."""
    if state.shadow is None:
        return None
    return jnp.sum(state.shadow.counts, axis=0)


def global_telemetry_counts(state: S.SentinelState) -> S.TelemetryState:
    """Pod-global decision attribution / RT histograms / totals from a
    [D, ...] pod state: each device's step accumulated only its own
    shard's lanes (the telemetry columns ride each device's local
    bincount), so the pod view is the device-axis sum — the same
    reduction the in-step psum applies to the shared window, applied at
    read time because cumulative counters are only read host-side
    (keeping every device's steady-state step free of an extra
    collective). The live staged second is folded in
    (``S.telemetry_view``), so the read is exact at any instant."""
    return jax.tree.map(lambda x: jnp.sum(x, axis=0),
                        S.telemetry_view(state))


def global_flight_recorder(state: S.SentinelState) -> Optional[S.FlightRecorder]:
    """Pod-global flight recorder from a [D, ...] pod state: per-slot
    stamps are clock-derived and identical on every device, so the
    global per-second deltas are the device-axis sum of the ring tensors
    (same read-time reduction as :func:`global_telemetry_counts`).
    None when recording is disabled."""
    fl = state.flight
    if fl is None:
        return None
    return S.FlightRecorder(
        stamps=fl.stamps[0],
        events=jnp.sum(fl.events, axis=0),
        attr=jnp.sum(fl.attr, axis=0),
        hist=jnp.sum(fl.hist, axis=0),
        slot_attr=jnp.sum(fl.slot_attr, axis=0),
    )


def make_pod_steps(mesh: Mesh, axis: str = AXIS, cluster_param: bool = True,
                   occupy_timeout_ms: int = C.DEFAULT_OCCUPY_TIMEOUT_MS,
                   shadow_rules=None, canary_bps=None, canary_salt=None):
    """Build (entry_step, exit_step) shard_mapped over ``mesh[axis]``.

    State leaves carry a leading device axis (sharded); batches are sharded
    over the request axis; rules and ``now_ms`` are replicated. The returned
    functions are jittable; callers wrap them in ``jax.jit`` with state
    donation.

    ``cluster_param=False`` drops the param-sketch all-reduce (a
    [PR, 4, 2048] f32 psum per step) for deployments with no cluster-mode
    param rules — a static choice, like rule compilation itself.
    ``occupy_timeout_ms`` is likewise build-static here (pod callers own
    their jit lifecycle); the single-engine paths take it as a traced
    runtime knob.

    SPI device checkers (core/spi.py) registered at BUILD time are spliced
    into the pod step like the single-device engine's; later registrations
    need a fresh ``make_pod_steps`` (pod callers own their jit lifecycle —
    watch ``spi.device_version()`` the way the engine does).

    ``shadow_rules`` / ``canary_bps`` / ``canary_salt`` stage a candidate
    ruleset pod-wide (sentinel_tpu/rollout/), build-static like the SPI
    splice: the pod state must carry a matching shadow world
    (``S.make_shadow_state`` broadcast by ``make_pod_state``), and the
    candidate's cluster-mode rules admit against the psum'd shadow
    window, so would-verdicts are pod-global like live verdicts.
    """
    from sentinel_tpu.core import spi as _spi

    entry = _shard_map(
        functools.partial(_pod_entry, axis=axis, cluster_param=cluster_param,
                          extra_checkers=_spi.device_checkers(),
                          occupy_timeout_ms=occupy_timeout_ms,
                          shadow_rules=shadow_rules, canary_bps=canary_bps,
                          canary_salt=canary_salt),
        mesh=mesh,
        in_specs=(P(axis), P(), P(axis), P()),
        out_specs=(P(axis), P(axis)),
        # The r5 survivor-fixpoint (ops/fixpoint.py) is a lax.while_loop;
        # jax's shard_map replication checker has no while rule yet
        # (mixed-acquire batches crashed with "No replication rule for
        # while"), so the static rep check is off. Collective correctness
        # is unaffected — psums are explicit in the step body.
        check_rep=False,
    )
    exit_ = _shard_map(
        functools.partial(_pod_exit, axis=axis, shadow_rules=shadow_rules),
        mesh=mesh,
        in_specs=(P(axis), P(), P(axis), P()),
        out_specs=P(axis),
        check_rep=False,
    )
    return entry, exit_

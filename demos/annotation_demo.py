"""@SentinelResource demo (reference: ``sentinel-demo-annotation-spring-aop``):
decorate a function, route blocks to a blockHandler and business errors to
a fallback."""

import _demo_env  # noqa: F401

import sentinel_tpu as st
from sentinel_tpu.adapters.annotation import sentinel_resource


def on_block(name, ex):
    return f"degraded({name})"


def on_error(name, ex):
    return f"fallback({ex})"


@sentinel_resource("greet", block_handler=on_block, fallback=on_error)
def greet(who: str) -> str:
    if who == "oops":
        raise ValueError("bad input")
    return f"hello {who}"


st.load_flow_rules([st.FlowRule(resource="greet", count=3)])

# Absorb the XLA compile so the calls below share one 1s window.
h = st.entry_ok("warmup")
if h:
    h.exit()

# 'oops' passes admission, raises inside -> fallback; ada + grace pass;
# linus is the 4th acquire in the window -> blockHandler.
for who in ["oops", "ada", "grace", "linus"]:
    print(f"greet({who!r}) -> {greet(who)!r}")

"""SLO manager: per-second evaluation, alert store, health scoring.

One :class:`SloManager` rides each engine. It consumes the flight
recorder's COMPLETE seconds exactly as the host history renders them
(``second_to_dict`` — the same JSON every other surface shares) and
turns them into judgement:

* **Burn-rate rules** — every objective keeps a bounded per-second
  series of (bad, total) events; ``evaluate(now)`` computes each rule's
  long/short-window burn rates at the newest complete second boundary
  and drives the alert state machine. Idle seconds are implicit zeros
  (stamp arithmetic), so burn decays exactly as traffic stops.
* **Anomaly baselines** — resources with NO explicit objective get one
  :class:`~sentinel_tpu.slo.baseline.EwmaBaseline` per signal (per-
  second block rate, per-second RT p99 from the device histogram);
  z-score breaches fire ``anomaly`` alerts through the same machinery.
* **Health scores** — active alerts and the overload batcher's shed
  rate compose into a 0-100 score per resource and per instance
  (formula in docs/OPERATIONS.md; deliberately simple and monotone:
  page -40, ticket -20, anomaly -15, shed-rate up to -50 instance-wide).

Cadence contract: ``ingest``/``evaluate`` are driven by the engine's
flight-recorder spill (``engine._spill_flight`` — the once-per-second
fold's read side), so SLO evaluation adds ZERO per-step device work and
no background thread. Readers (the ``alerts``/``slo`` commands, the
exporter, the dashboard SSE pump) refresh it at their own cadence.

All mutation runs under one manager lock; alert fan-out (webhook) is
queue-decoupled and never blocks evaluation.
"""

from __future__ import annotations

import threading
from collections import OrderedDict, deque
from typing import Deque, Dict, List, Optional, Set, Tuple

from sentinel_tpu.slo.baseline import EwmaBaseline
from sentinel_tpu.slo.objectives import (
    SEVERITY_PAGE,
    SloObjective,
    max_window_seconds,
)
from sentinel_tpu.slo.webhook import AlertWebhook
from sentinel_tpu.telemetry.attribution import histogram_quantile

# Health-score penalties per active alert (docs/OPERATIONS.md).
PENALTY = {"page": 40, "ticket": 20, "anomaly": 15}
SHED_PENALTY_CAP = 50

BASELINE_SIGNALS = ("blockRate", "rtP99Ms")


class SloManager:
    """Objectives + baselines + alert store for one engine."""

    def __init__(self, engine=None):
        from sentinel_tpu.core.config import config as _cfg

        self.engine = engine
        self._lock = threading.RLock()
        self._objectives: "OrderedDict[str, SloObjective]" = OrderedDict()
        # objective key -> deque[(stamp_ms, bad, total)] of traffic
        # seconds inside the widest window (idle seconds are implicit).
        self._series: Dict[str, Deque[Tuple[int, int, int]]] = {}
        self._retain_ms = 0
        # resource -> {signal: EwmaBaseline} for objective-less resources.
        self._baselines: Dict[str, Dict[str, EwmaBaseline]] = {}
        self.baseline_alpha = _cfg.slo_baseline_alpha()
        self.baseline_zscore = _cfg.slo_baseline_zscore()
        self.baseline_warmup = _cfg.slo_baseline_warmup_seconds()
        self.baseline_min_events = _cfg.slo_baseline_min_events()
        self.rollout_abort_enabled = _cfg.slo_rollout_abort()
        # Alert store: active alerts by key + a bounded transition log
        # (each fired/resolved transition is one seq-numbered event —
        # the SSE pump's and webhook's shared cursor space).
        self._active: "OrderedDict[str, Dict]" = OrderedDict()
        self._events: Deque[Dict] = deque(maxlen=_cfg.alert_history_capacity())
        self._seq = 0
        self.fired_count = 0
        self.resolved_count = 0
        # Control-plane audit journal (ISSUE 14): every transition
        # mirrors into it (a resolve carries causeSeq -> its fire), and
        # — the restart fix — a file-backed journal re-seeds the
        # transition log + seq cursor here, so `alerts sinceSeq=`
        # cursors held by external consumers survive a process restart
        # instead of silently replaying from 1.
        self.journal = getattr(engine, "journal", None) \
            if engine is not None else None
        self._fired_jseq: Dict[str, int] = {}
        if self.journal is not None:
            for rec in self.journal.replay(kind="sloTransition"):
                ev = rec.get("event")
                if isinstance(ev, dict) and "seq" in ev:
                    self._events.append(ev)
                    self._seq = max(self._seq, int(ev["seq"]))
        self.webhook = AlertWebhook()
        # Evaluation cursors + last burn snapshot per objective.
        self._last_ingest_ms = -1
        self._eval_end_ms = -1
        self._burn: Dict[str, Dict] = {}
        # Overload shed-rate (health input): deltas of the batcher's
        # cumulative counters, windowed per NEW complete second (not per
        # evaluate() call — concurrent readers would otherwise shrink
        # the delta window to milliseconds and hide real shedding).
        self._shed_last: Optional[Tuple[int, int]] = None
        self._shed_end_ms = -1
        self.shed_rate = 0.0

    # -- objectives --------------------------------------------------------

    def load_objectives(self, objectives: List[SloObjective]) -> None:
        """Wholesale replacement (the same §3.2 semantics every rule
        family uses — datasource pushes and the ``slo`` command both land
        here). Series survive for objectives whose definition is
        unchanged; removed objectives resolve their alerts."""
        validated = [o.validate() for o in objectives]
        with self._lock:
            new: "OrderedDict[str, SloObjective]" = OrderedDict()
            for o in validated:
                if o.key in new:
                    raise ValueError(f"duplicate objective key {o.key!r}")
                new[o.key] = o
            old = self._objectives
            self._objectives = new
            self._retain_ms = max_window_seconds(new.values()) * 1000
            self._series = {
                k: (self._series.get(k, deque())
                    if old.get(k) == new[k] else deque())
                for k in new
            }
            self._burn = {k: v for k, v in self._burn.items() if k in new}
            # Resources that now carry an objective leave baseline
            # jurisdiction; their anomaly alerts resolve.
            covered = {o.resource for o in new.values()}
            for res in list(self._baselines):
                if res in covered:
                    del self._baselines[res]
            now = self._now_ms()
            for key, alert in list(self._active.items()):
                gone = (alert["kind"] == "burn_rate"
                        and alert["objective"] not in new) or \
                       (alert["kind"] == "anomaly"
                        and alert["resource"] in covered)
                if gone:
                    self._transition(key, False, now, alert)
        if self.journal is not None:
            from sentinel_tpu.datasource.converters import (
                slo_objective_to_dict)
            from sentinel_tpu.telemetry.journal import MAX_RULES_PER_RECORD

            self.journal.record(
                "sloLoad", count=len(validated),
                objectives=[slo_objective_to_dict(o)
                            for o in validated[:MAX_RULES_PER_RECORD]])

    def objectives(self) -> List[SloObjective]:
        with self._lock:
            return list(self._objectives.values())

    # -- ingestion (flight-recorder spill feed) ----------------------------

    def ingest(self, stamp_ms: int, resources: Dict[str, Dict]) -> None:
        """Feed one rendered COMPLETE second (``second_to_dict`` shape).
        Stamps must arrive monotonically (the spill guarantees it);
        replays are ignored, first wins."""
        with self._lock:
            if stamp_ms <= self._last_ingest_ms:
                return
            self._last_ingest_ms = stamp_ms
            for key, obj in self._objectives.items():
                cell = resources.get(obj.resource)
                if not cell:
                    continue
                bad, total = obj.bad_total(cell)
                if total <= 0 and bad <= 0:
                    continue
                series = self._series[key]
                series.append((stamp_ms, bad, total))
                floor = stamp_ms - self._retain_ms
                while series and series[0][0] < floor:
                    series.popleft()
            covered = {o.resource for o in self._objectives.values()}
            for res, cell in resources.items():
                if res in covered:
                    continue
                self._ingest_baseline(res, cell, stamp_ms)

    def _ingest_baseline(self, res: str, cell: Dict, stamp_ms: int) -> None:
        bls = self._baselines.get(res)
        if bls is None:
            bls = self._baselines[res] = {
                sig: EwmaBaseline(self.baseline_alpha, self.baseline_zscore,
                                  self.baseline_warmup)
                for sig in BASELINE_SIGNALS
            }
        events = int(cell.get("pass", 0)) + int(cell.get("block", 0))
        if events > 0:
            x = float(cell.get("block", 0)) / float(events)
            breach = bls["blockRate"].update(x) \
                and events >= self.baseline_min_events
            self._anomaly_transition(res, "blockRate", breach,
                                     bls["blockRate"], x, stamp_ms)
        buckets = cell.get("rtBuckets") or []
        completions = int(sum(buckets))
        if completions > 0:
            x = float(histogram_quantile(buckets, 0.99))
            breach = bls["rtP99Ms"].update(x) \
                and completions >= self.baseline_min_events
            self._anomaly_transition(res, "rtP99Ms", breach,
                                     bls["rtP99Ms"], x, stamp_ms)

    def _anomaly_transition(self, res: str, signal: str, firing: bool,
                            bl: EwmaBaseline, value: float,
                            stamp_ms: int) -> None:
        key = f"anomaly:{res}:{signal}"
        self._transition(key, firing, stamp_ms, {
            "key": key,
            "kind": "anomaly",
            "severity": "anomaly",
            "resource": res,
            "signal": signal,
            "value": round(value, 6),
            "zscore": round(bl.last_z, 4),
            "threshold": self.baseline_zscore,
            "baselineMean": round(bl.mean, 6),
        })

    # -- evaluation --------------------------------------------------------

    def evaluate(self, now_ms: int) -> None:
        """Run every burn rule at the newest complete second boundary
        (``end = now - now % 1000``; the window is the ``long_s`` /
        ``short_s`` seconds strictly before it). Idempotent per
        boundary; host arithmetic only."""
        end = int(now_ms) - int(now_ms) % 1000
        with self._lock:
            if end < self._eval_end_ms:
                return
            self._eval_end_ms = end
            for key, obj in self._objectives.items():
                series = self._series[key]
                rules_out = []
                for w in obj.windows:
                    bad_l, tot_l = _window_sums(series, end, w.long_s)
                    bad_s, tot_s = _window_sums(series, end, w.short_s)
                    burn_l = _burn(bad_l, tot_l, obj.budget)
                    burn_s = _burn(bad_s, tot_s, obj.budget)
                    firing = (tot_l >= obj.min_events
                              and burn_l >= w.burn and burn_s >= w.burn)
                    rule_key = (f"burn:{key}:{w.long_s}s/{w.short_s}s"
                                f":{w.severity}")
                    self._transition(rule_key, firing, end, {
                        "key": rule_key,
                        "kind": "burn_rate",
                        "severity": w.severity,
                        "resource": obj.resource,
                        "sli": obj.sli,
                        "objective": key,
                        "target": obj.objective,
                        "windowLongS": w.long_s,
                        "windowShortS": w.short_s,
                        "burnThreshold": w.burn,
                        "burnLong": round(burn_l, 6),
                        "burnShort": round(burn_s, 6),
                        "eventsLong": tot_l,
                    })
                    rules_out.append({
                        "longSeconds": w.long_s,
                        "shortSeconds": w.short_s,
                        "severity": w.severity,
                        "burnThreshold": w.burn,
                        "burnLong": burn_l,
                        "burnShort": burn_s,
                        "badLong": bad_l,
                        "totalLong": tot_l,
                        "firing": firing,
                    })
                self._burn[key] = {
                    "resource": obj.resource,
                    "sli": obj.sli,
                    "target": obj.objective,
                    "rules": rules_out,
                    "evaluatedAtMs": end,
                }
            if end > self._shed_end_ms:
                self._shed_end_ms = end
                self._update_shed_rate()

    def _update_shed_rate(self) -> None:
        """Instance health input: the overload batcher's shed fraction
        since the previous evaluation (``shed_rate()`` — ISSUE 7 wires
        the batcher's counters into the health score). None while this
        instance is not a token server."""
        stats = None
        if self.engine is not None:
            cluster = getattr(self.engine, "cluster", None)
            if cluster is not None:
                stats = cluster.overload_stats()
        if not stats:
            self._shed_last = None
            self.shed_rate = 0.0
            return
        shed = int(stats.get("shedRequests", 0))
        admitted = int(stats.get("admittedRequests", 0))
        last, self._shed_last = self._shed_last, (shed, admitted)
        if last is None or shed < last[0] or admitted < last[1]:
            self.shed_rate = 0.0  # first read / server restarted
            return
        shed_d = shed - last[0]
        adm_d = admitted - last[1]
        self.shed_rate = (shed_d / float(shed_d + adm_d)
                          if shed_d + adm_d > 0 else 0.0)

    # -- alert state machine -----------------------------------------------

    def external_transition(self, key: str, firing: bool, now_ms: int,
                            fields: Dict) -> None:
        """Public fire/refresh/resolve seam for sibling evaluators (the
        waterfall regression sentry, ISSUE 18): alerts they judge land in
        the SAME store, transition log, journal mirror, and webhook as
        burn-rate rules — a wire-path budget breach pages exactly like an
        availability breach. ``fields`` must carry the burn-alert keys
        the read surfaces index (``key``/``kind``/``severity``/
        ``resource``)."""
        with self._lock:
            self._transition(key, firing, int(now_ms), fields)

    def _transition(self, key: str, firing: bool, now_ms: int,
                    fields: Dict) -> None:
        """Caller holds the lock. Fire/refresh/resolve one alert key;
        transitions append to the bounded event log and fan out."""
        active = self._active.get(key)
        if firing:
            if active is None:
                alert = dict(fields, sinceMs=now_ms, lastMs=now_ms)
                self._active[key] = alert
                self.fired_count += 1
                self._emit("fired", alert, now_ms)
            else:
                active.update(fields)
                active["lastMs"] = now_ms
        elif active is not None:
            del self._active[key]
            self.resolved_count += 1
            resolved = dict(active, resolvedMs=now_ms)
            self._emit("resolved", resolved, now_ms)

    def _emit(self, kind: str, alert: Dict, now_ms: int) -> None:
        self._seq += 1
        event = {"seq": self._seq, "type": kind, "timestamp": now_ms,
                 "alert": dict(alert)}
        self._events.append(event)
        if self.journal is not None:
            # A resolve is CAUSED by its fire: the back-pointer lets the
            # why-query's chain walk show an alert's full arc.
            key = alert.get("key")
            cause = self._fired_jseq.get(key) if kind == "resolved" else None
            jseq = self.journal.record("sloTransition", cause_seq=cause,
                                       event=dict(event))
            if kind == "fired":
                self._fired_jseq[key] = jseq
            else:
                self._fired_jseq.pop(key, None)
        if self.webhook.enabled:
            from sentinel_tpu.core.config import config as _cfg

            self.webhook.submit(dict(event, source=_cfg.app_name()))

    # -- read surfaces ------------------------------------------------------

    def alerts_snapshot(self, since_seq: int = 0,
                        resource: Optional[str] = None,
                        limit: Optional[int] = None) -> Dict:
        """Active alerts + the transition log after ``since_seq`` (the
        SSE pump's cursor; 0 = everything retained)."""
        with self._lock:
            active = [dict(a) for a in self._active.values()]
            events = [e for e in self._events if e["seq"] > since_seq]
            if resource is not None:
                active = [a for a in active if a["resource"] == resource]
                events = [e for e in events
                          if e["alert"]["resource"] == resource]
            if limit is not None and limit >= 0:
                # events[-0:] would be the WHOLE list — limit=0 means
                # "no transitions, just the active set and counters"
                # (the exporter's cheap read).
                events = events[-limit:] if limit > 0 else []
            return {
                "active": active,
                "events": events,
                "nextSeq": self._seq,
                "counters": {
                    "fired": self.fired_count,
                    "resolved": self.resolved_count,
                },
                "webhook": self.webhook.stats(),
                "health": self.health_scores(),
            }

    def status(self) -> Dict:
        """The ``slo`` command's view: objectives, burn snapshots,
        baselines, health."""
        from sentinel_tpu.datasource.converters import slo_objective_to_dict

        with self._lock:
            return {
                "objectives": [slo_objective_to_dict(o)
                               for o in self._objectives.values()],
                "burn": {k: dict(v) for k, v in self._burn.items()},
                "baselines": {
                    res: {sig: bl.snapshot() for sig, bl in bls.items()}
                    for res, bls in sorted(self._baselines.items())
                },
                "health": self.health_scores(),
                "evaluatedThroughMs": self._eval_end_ms,
                "activeAlerts": len(self._active),
                "rolloutAbortEnabled": self.rollout_abort_enabled,
            }

    def health_scores(self) -> Dict:
        """Composite 0-100 health per resource and per instance.

        Resource: 100 minus a penalty per active alert on it (page 40,
        ticket 20, anomaly 15), floored at 0. Instance: the worst
        resource score minus an overload penalty proportional to the
        batcher's recent shed fraction (capped at 50), floored at 0."""
        with self._lock:
            resources: Dict[str, int] = {}
            for o in self._objectives.values():
                resources.setdefault(o.resource, 100)
            for res in self._baselines:
                resources.setdefault(res, 100)
            for alert in self._active.values():
                res = alert["resource"]
                pen = PENALTY.get(alert["severity"], PENALTY["anomaly"])
                resources[res] = max(0, resources.get(res, 100) - pen)
            shed_penalty = min(SHED_PENALTY_CAP,
                               int(round(100 * self.shed_rate)))
            worst = min(resources.values(), default=100)
            return {
                "resources": resources,
                "instance": max(0, worst - shed_penalty),
                "shedRate": round(self.shed_rate, 6),
                "shedPenalty": shed_penalty,
            }

    def abort_signal(self, resources: Optional[Set[str]] = None) -> List[Dict]:
        """Active PAGE-severity burn alerts (optionally restricted to a
        resource set) — the rollout guardrail's additional auto-abort
        input. Anomaly alerts deliberately do not vote: a candidate
        ruleset CHANGES behavior, which is exactly what a self-baseline
        flags."""
        with self._lock:
            return [dict(a) for a in self._active.values()
                    if a["kind"] == "burn_rate"
                    and a["severity"] == SEVERITY_PAGE
                    and (resources is None or a["resource"] in resources)]

    def active_alerts_on(self, resources: Set[str]) -> List[Dict]:
        """EVERY active alert (any kind, any severity) touching the
        given resources — the adaptive loop's proposal gate. Unlike
        :meth:`abort_signal`, anomalies DO vote here: a proposal has no
        canary blast shield yet, so any sign the resource is behaving
        unusually is reason enough not to start retuning it."""
        with self._lock:
            return [dict(a) for a in self._active.values()
                    if a["resource"] in resources]

    def reset_timebase(self) -> None:
        """Forget every stamp-bearing cursor and series (the engine's
        ``set_clock`` seam): ingest/eval cursors, objective series,
        baselines, burn snapshots, and active alerts all carry absolute
        stamps of the OLD timebase — after a backward swap the ingest
        cursor would silently drop every new second as "already seen"
        and judgement would go dead with no error. Objectives and the
        seq-numbered transition LOG survive (config and history are not
        statistics); active alerts clear without transitions — their
        fire stamps belong to a timebase that no longer exists."""
        with self._lock:
            self._last_ingest_ms = -1
            self._eval_end_ms = -1
            self._series = {k: deque() for k in self._objectives}
            self._baselines.clear()
            self._burn.clear()
            self._active.clear()
            self._shed_end_ms = -1
            self._shed_last = None

    def stop(self) -> None:
        self.webhook.stop()

    def _now_ms(self) -> int:
        # Ride the owning engine's timebase (clock-injection seam,
        # ISSUE 13) so in-sim judgement stamps with simulated time; an
        # engine-less manager (unit tests) keeps the process clock.
        engine = self.engine
        if engine is not None:
            return engine.now_ms()
        from sentinel_tpu.utils import time_util

        return time_util.current_time_millis()


def _window_sums(series, end_ms: int, window_s: int) -> Tuple[int, int]:
    """Exact (bad, total) over stamps in [end - window_s*1000, end).
    The deque holds only retained traffic seconds; idle seconds are
    implicit zeros."""
    floor = end_ms - window_s * 1000
    bad = total = 0
    for stamp, b, t in reversed(series):
        if stamp < floor:
            break
        if stamp < end_ms:
            bad += b
            total += t
    return bad, total


def _burn(bad: int, total: int, budget: float) -> float:
    if total <= 0:
        return 0.0
    return (bad / float(total)) / budget

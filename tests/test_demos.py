"""Demo smoke tests: every advertised quickstart must actually run.

The reference treats ``sentinel-demo/`` as living documentation; these
run each SELF-TERMINATING demo as a real subprocess (fresh interpreter,
the exact command the README documents) and assert a clean exit. The
dashboard demo serves forever by design and is exercised through
``tests/test_dashboard.py`` instead.

Each subprocess clears PYTHONPATH (the demos' ``_demo_env`` puts the
repo root on sys.path themselves), which also keeps the smoke tests
alive when a host accelerator plugin is unreachable.
"""

import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent

SELF_TERMINATING = [
    # Redundant subprocess smoke slow-tier'd (ISSUE 18 tier-1 wall-time
    # trim, ~15s): the demo's exact admission scenario is pinned
    # in-process by tests/test_flow.py::test_flow_qps_demo_golden, so
    # the subprocess run only re-verifies interpreter startup; the full
    # demo sweep still runs with -m slow.
    pytest.param("flow_qps_demo.py", marks=pytest.mark.slow),
    "warm_up_demo.py",
    "degrade_demo.py",
    "param_flow_demo.py",
    "annotation_demo.py",
    "cluster_demo.py",
    "lease_demo.py",
    "datasource_demo.py",
    "remote_bridge_demo.py",
]


@pytest.mark.parametrize("script", SELF_TERMINATING)
def test_demo_runs_clean(script):
    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    env["SENTINEL_DEMO_PLATFORM"] = "cpu"
    out = subprocess.run(
        [sys.executable, str(REPO / "demos" / script)],
        capture_output=True, text=True, timeout=420, env=env,
        cwd=str(REPO))
    assert out.returncode == 0, (script, out.stdout[-800:], out.stderr[-800:])
    assert out.stdout.strip(), f"{script} printed nothing"

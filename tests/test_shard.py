"""Sharded multi-leader cluster suite (ISSUE 12 tentpole): fixed-ring
flowId hash slices with per-slice epoch-fenced ownership, client-side
slice routing with WRONG_SLICE self-healing, per-slice failover (only a
lost leader's slices degrade), and crash-safe rebalancing through the
slice-filtered checkpoint grafting path.

Determinism stance matches test_cluster_ha.py: host-side quota math and
degraded-mode state machines run on the frozen ``utils/time_util``
clock; socket scenarios use real time for connect/reconnect waits. The
multi-spell chaos drill is ``slow``-marked from the start (870s tier-1
discipline); one scaled-down seed of every invariant stays tier-1.
"""

from __future__ import annotations

import socket
import time
from collections import Counter

import pytest

import sentinel_tpu as st
from sentinel_tpu.cluster import codec
from sentinel_tpu.cluster.client import ClusterTokenClient
from sentinel_tpu.cluster.constants import THRESHOLD_GLOBAL, TokenResultStatus
from sentinel_tpu.cluster.ha import (
    ClusterHAManager,
    ClusterMap,
    ClusterServerSpec,
    DegradedQuota,
)
from sentinel_tpu.cluster.rules import ClusterFlowRuleManager
from sentinel_tpu.cluster.server import ClusterTokenServer
from sentinel_tpu.cluster.sharding import (
    ShardedTokenClient,
    ShardMap,
    ShardState,
    slice_of,
)
from sentinel_tpu.cluster.state import (
    CLUSTER_CLIENT,
    CLUSTER_SERVER,
    ClusterStateManager,
    SliceEpochFence,
)
from sentinel_tpu.cluster.token_service import DefaultTokenService
from sentinel_tpu.core import checkpoint as ckpt
from sentinel_tpu.datasource.converters import (
    any_cluster_map_from_json,
    shard_map_from_json,
    shard_map_to_dict,
)
from sentinel_tpu.resilience import FaultInjector
from sentinel_tpu.utils import time_util

pytestmark = pytest.mark.chaos

N = 8  # scaled-down ring (the shipped default is 64; the math is size-free)

# Three flowIds landing in three DISTINCT slices of the 8-ring (pinned
# below by test_slice_of_pinned_and_stable, so these stay honest).
FID_A, FID_B, FID_C = 9003, 9001, 9000   # slices 0, 4, 6
SL_A, SL_B, SL_C = 0, 4, 6


@pytest.fixture()
def injector():
    with FaultInjector(seed=4242) as inj:
        yield inj


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _wait(pred, timeout_s: float = 5.0) -> bool:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return pred()


def _rule(flow_id, count, **cc):
    return st.FlowRule(
        resource=f"res-{flow_id}", count=count, cluster_mode=True,
        cluster_config={"flowId": flow_id, "thresholdType": THRESHOLD_GLOBAL,
                        **cc})


def _rules(*pairs):
    mgr = ClusterFlowRuleManager()
    mgr.load_rules("default", [_rule(fid, cnt) for fid, cnt in pairs])
    return mgr


def _owner_map(assign, version=1, epochs=None, servers=None, clients=("X",)):
    """assign: {machine_id: [slices]}; unlisted slices go to the first
    machine. epochs: {slice: epoch} overrides (default = version)."""
    owner = [None] * N
    for mid, sls in assign.items():
        for sl in sls:
            owner[sl] = mid
    first = next(iter(assign))
    owner = [m if m is not None else first for m in owner]
    eps = [version] * N
    for sl, ep in (epochs or {}).items():
        eps[sl] = ep
    return ShardMap(version=version, n_slices=N, servers=tuple(servers),
                    slice_owner=tuple(owner), slice_epoch=tuple(eps),
                    clients=tuple(clients))


def _seats(tmp_path, machine_ids, rule_pairs):
    base = str(tmp_path / "shard.ck")
    out = {}
    for mid in machine_ids:
        state = ClusterStateManager()
        state.server_rules().load_rules(
            "default", [_rule(fid, cnt) for fid, cnt in rule_pairs])
        out[mid] = ClusterHAManager(
            state=state, machine_id=mid, checkpoint_path=base,
            checkpoint_period_s=3600.0, server_host="127.0.0.1")
    return out


# -- routing helper + fence (no sockets) --------------------------------------


def test_slice_of_pinned_and_stable():
    """The flowId→slice mapping is a WIRE contract (client and server
    recompute it independently): pin concrete values so any drift in
    the shared helper fails loudly, and sanity-check spread."""
    # Pins for the shipped 64-ring and the test 8-ring.
    assert slice_of(6000, 64) == 30
    assert slice_of(6001, 64) == 36
    assert slice_of(123456789, 64) == 48
    assert [slice_of(f, N) for f in (FID_A, FID_B, FID_C)] \
        == [SL_A, SL_B, SL_C]
    # Full range + non-degenerate spread over sequential ids (the
    # common flowId allocation pattern a bare modulus would stripe).
    counts = Counter(slice_of(i, N) for i in range(10_000))
    assert set(counts) <= set(range(N))
    assert len(counts) == N
    assert max(counts.values()) < 10_000 // N * 3
    # Deterministic (no process-seeded hash()).
    assert slice_of(2**63 - 1, 64) == slice_of(2**63 - 1, 64)


def test_slice_epoch_fence_lanes_independent():
    f = SliceEpochFence()
    assert f.observe(5, scope=3)
    # Slice 7's lane is untouched by slice 3's term.
    assert f.observe(1, scope=7)
    assert not f.observe(4, scope=3)       # stale in lane 3
    assert f.stale_rejected_count == 1
    assert f.observe(5, scope=3)           # equal epoch passes
    assert f.observe(2, scope=None)        # global lane independent too
    assert not f.observe(1, scope=None)
    assert f.highest_seen == 5
    assert f.snapshot() == {3: 5, 7: 1, None: 2}


# -- converter ----------------------------------------------------------------


def _map_json(owners, version=3, n=N, epochs=None):
    d = {
        "version": version, "nSlices": n,
        "servers": [{"machineId": "a", "host": "10.0.0.1", "port": 1871},
                    {"machineId": "b", "host": "10.0.0.2", "port": 1871}],
        "sliceOwners": owners,
        "clients": ["c1", "c2"],
    }
    if epochs is not None:
        d["sliceEpochs"] = epochs
    return d


def test_shard_map_converter_roundtrip():
    m = shard_map_from_json(_map_json(
        {"a": [0, 1, 2, 3], "b": [4, 5, 6, 7]}, epochs={"4": 9}))
    assert m.version == 3 and m.n_slices == N
    assert m.slice_owner == ("a",) * 4 + ("b",) * 4
    assert m.slice_epoch == (3, 3, 3, 3, 9, 3, 3, 3)  # default = version
    assert m.clients == ("c1", "c2")
    assert m.slices_of("b") == (4, 5, 6, 7)
    assert m.epochs_of("a") == {0: 3, 1: 3, 2: 3, 3: 3}
    # List form + roundtrip through to_dict.
    m2 = shard_map_from_json(shard_map_to_dict(m))
    assert m2 == m
    flat = dict(_map_json(list(m.slice_owner)))
    assert shard_map_from_json(flat).slice_owner == m.slice_owner
    # Dual-flavor converter dispatches on the sliceOwners key.
    assert isinstance(any_cluster_map_from_json(
        _map_json({"a": list(range(8))})), ShardMap)
    assert not isinstance(any_cluster_map_from_json(
        {"epoch": 1, "servers": [{"machineId": "a", "host": "h",
                                  "port": 1}]}), ShardMap)


def test_shard_map_converter_rejects_malformed():
    good = _map_json({"a": [0, 1, 2, 3], "b": [4, 5, 6, 7]})
    bad = [
        {**good, "sliceOwners": {"a": [0, 1], "b": [4, 5, 6, 7]}},  # gaps
        {**good, "sliceOwners": {"a": [0, 0, 1, 2, 3],
                                 "b": [4, 5, 6, 7]}},   # double-assigned
        {**good, "sliceOwners": {"zz": list(range(8))}},  # unknown owner
        {**good, "sliceOwners": {"a": [0, 1, 2, 99],
                                 "b": [3, 4, 5, 6, 7]}},  # out of ring
        {**good, "sliceOwners": ["a"] * 7},               # short list
        {**good, "nSlices": 0},                           # empty ring
        {**good, "version": "x"},                         # non-int version
        {**good, "servers": []},                          # no leaders
        {**good, "sliceEpochs": {"99": 2}},               # epoch off-ring
        {**good, "sliceEpochs": [1, 2]},                  # short epoch list
        {**good, "clients": "c1"},                        # bare string
        [],                                               # not an object
    ]
    for d in bad:
        with pytest.raises(ValueError):
            shard_map_from_json(d)


# -- server-side ownership (direct service, no sockets) -----------------------


def test_service_wrong_slice_is_pre_device_and_quota_free(frozen_time):
    svc = DefaultTokenService(_rules((FID_A, 4), (FID_C, 4)))
    svc.set_shard(ShardState(N, 7, {SL_A: 2}))
    # Unowned slice: WRONG_SLICE carrying the map version; repeated
    # requests consume NOTHING (checked before limiter + device step).
    for _ in range(6):
        r = svc.request_token(FID_C)
        assert r.status == TokenResultStatus.WRONG_SLICE
        assert r.wait_ms == 7
    assert svc.wrong_slice_count == 6
    # Owned slice serves its full quota, stamped with ITS slice epoch.
    got = [svc.request_token(FID_A) for _ in range(5)]
    assert [g.status for g in got] == [TokenResultStatus.OK] * 4 \
        + [TokenResultStatus.BLOCKED]
    assert all(g.epoch == 2 for g in got)
    # Param path: same ownership contract.
    r = svc.request_param_token(FID_C, 1, ["k"])
    assert r.status == TokenResultStatus.WRONG_SLICE and r.wait_ms == 7
    r = svc.request_param_token(FID_A, 1, ["k"])
    assert r.status == TokenResultStatus.OK and r.epoch == 2
    snap = svc.shard_snapshot()
    assert snap["slicesOwned"] == 1 and snap["sliceEpochs"] == {"0": 2}
    assert snap["wrongSliceRejected"] == 7


def test_wrong_slice_wire_roundtrip_and_fence_hygiene(frozen_time):
    """WRONG_SLICE on the real wire: status + map version through the
    dedicated TLV, and NO epoch TLV — an out-of-slice reply must never
    write into the requesting slice's fence lane (the replying leader
    holds no term there)."""
    svc = DefaultTokenService(_rules((FID_A, 100), (FID_C, 100)))
    svc.set_shard(ShardState(N, 5, {SL_A: 9}))
    server = ClusterTokenServer(svc, host="127.0.0.1", port=0).start()
    fence = SliceEpochFence()
    cli = ClusterTokenClient(
        "127.0.0.1", server.bound_port, request_timeout_s=10.0,
        epoch_fence=fence,
        fence_scope_fn=lambda fid: slice_of(int(fid), N)).start()
    try:
        assert _wait(cli.is_connected)
        r = cli.request_token(FID_C)
        assert r.status == TokenResultStatus.WRONG_SLICE
        assert r.wait_ms == 5                       # map version, not retry
        assert fence.snapshot() == {}               # lane untouched
        r = cli.request_token(FID_A)
        assert r.status == TokenResultStatus.OK
        assert fence.snapshot() == {SL_A: 9}        # per-slice epoch landed
        # Param flavor: version rides the TLV (no waitMs field).
        r = cli.request_param_token(FID_C, 1, ["k"])
        assert r.status == TokenResultStatus.WRONG_SLICE and r.wait_ms == 5
    finally:
        cli.stop()
        server.stop()


def test_stale_slice_epoch_rejected_per_lane(frozen_time):
    """A deposed donor's late replies carry its old slice epoch and are
    fence-rejected — while an UNRELATED slice's lower-epoch leader keeps
    serving (per-slice lanes, the tentpole's fencing contract)."""
    svc = DefaultTokenService(_rules((FID_A, 100), (FID_C, 100)))
    # Zombie view: still claims slice SL_A at epoch 2, and honestly
    # owns SL_C at epoch 1.
    svc.set_shard(ShardState(N, 1, {SL_A: 2, SL_C: 1}))
    server = ClusterTokenServer(svc, host="127.0.0.1", port=0).start()
    fence = SliceEpochFence()
    fence.observe(3, SL_A)   # the fleet has seen SL_A's epoch-3 owner
    cli = ClusterTokenClient(
        "127.0.0.1", server.bound_port, request_timeout_s=10.0,
        epoch_fence=fence,
        fence_scope_fn=lambda fid: slice_of(int(fid), N)).start()
    try:
        assert _wait(cli.is_connected)
        r = cli.request_token(FID_A)
        assert r.status == TokenResultStatus.FAIL   # stale term: rejected
        assert fence.stale_rejected_count == 1
        r = cli.request_token(FID_C)                # unrelated slice: fine
        assert r.status == TokenResultStatus.OK
        assert fence.snapshot()[SL_C] == 1
    finally:
        cli.stop()
        server.stop()


# -- sharded client routing ---------------------------------------------------


def _two_leader_wire(counts=((FID_A, 1000), (FID_B, 1000), (FID_C, 1000)),
                     a_slices=(SL_A,), version=1):
    """Two real leaders: A owning ``a_slices``, B the rest."""
    servers, specs = [], []
    for mid in ("A", "B"):
        owned = set(a_slices) if mid == "A" \
            else set(range(N)) - set(a_slices)
        svc = DefaultTokenService(_rules(*counts), max_allowed_qps=1e9)
        svc.set_shard(ShardState(N, version, {s: version for s in owned}))
        srv = ClusterTokenServer(svc, host="127.0.0.1", port=0).start()
        servers.append(srv)
        specs.append(ClusterServerSpec(mid, "127.0.0.1", srv.bound_port))
    return servers, specs


def test_sharded_client_routes_by_slice_and_pipelines(frozen_time):
    servers, specs = _two_leader_wire()
    smap = _owner_map({"A": [SL_A], "B": [s for s in range(N) if s != SL_A]},
                      servers=specs)
    cli = ShardedTokenClient(smap, request_timeout_s=10.0).start()
    try:
        assert _wait(cli.is_connected)
        for fid in (FID_A, FID_B, FID_C):
            assert cli.request_token(fid).status == TokenResultStatus.OK
        # Correct routing = zero wrong-slice traffic anywhere.
        assert servers[0].service.wrong_slice_count == 0
        assert servers[1].service.wrong_slice_count == 0
        # Pipelined: one batch splits per owning leader, results land
        # in request order.
        out = cli.request_tokens_pipelined(
            [(FID_A, 1, False), (FID_B, 1, False), (FID_C, 1, False),
             (FID_A, 1, False)])
        assert [r.status for r in out] == [TokenResultStatus.OK] * 4
        assert servers[0].service.wrong_slice_count == 0
        assert servers[1].service.wrong_slice_count == 0
    finally:
        cli.stop()
        for s in servers:
            s.stop()


def test_sharded_client_self_heals_on_stale_map(frozen_time):
    """A client whose map routes every slice to A walks to B on
    WRONG_SLICE, adopts B as the learned owner, and stops paying the
    mis-route on subsequent requests — no config push involved."""
    servers, specs = _two_leader_wire()
    stale = _owner_map({"A": list(range(N))}, servers=specs)
    cli = ShardedTokenClient(stale, request_timeout_s=10.0).start()
    try:
        assert _wait(cli.is_connected)
        for fid in (FID_A, FID_B, FID_C):
            assert cli.request_token(fid).status == TokenResultStatus.OK
        s = cli.failover_stats()["shard"]
        assert s["wrongSliceRejected"] == 2      # B's two slices healed
        assert s["learnedOverrides"] == 2
        assert s["staleMapVersionSeen"] == 1     # B's reply named its map
        assert cli.failover_count == 2
        w0 = cli.wrong_slice_count
        for fid in (FID_A, FID_B, FID_C):        # learned: direct now
            assert cli.request_token(fid).status == TokenResultStatus.OK
        assert cli.wrong_slice_count == w0
    finally:
        cli.stop()
        for s in servers:
            s.stop()


def test_per_slice_failover_only_victim_slices_degrade(frozen_time):
    """Killing leader B degrades ONLY B's slices: A's keep full-fidelity
    verdicts with zero degraded entries, B's serve the per-client share
    after the failover deadline — the blast-radius contract."""
    servers, specs = _two_leader_wire()
    smap = _owner_map({"A": [SL_A], "B": [s for s in range(N) if s != SL_A]},
                      servers=specs)
    cli = ShardedTokenClient(
        smap, request_timeout_s=0.3, failover_deadline_ms=400,
        degraded=DegradedQuota(divisor=2,
                               thresholds={FID_B: (8.0, 1000)})).start()
    try:
        assert _wait(cli.is_connected)
        assert cli.request_token(FID_A).status == TokenResultStatus.OK
        assert cli.request_token(FID_B).status == TokenResultStatus.OK
        servers[1].stop()                        # B dies (no drain)
        assert _wait(lambda: not cli._pool["B"].is_connected())
        # First verdict-free walk starts B's clock only.
        assert cli.request_token(FID_B).status == TokenResultStatus.FAIL
        time_util.advance_time(500)              # past the deadline
        r = cli.request_token(FID_B)             # degraded share: 8/2 = 4
        assert r.status == TokenResultStatus.OK
        got = [cli.request_token(FID_B).status for _ in range(4)]
        assert got == [TokenResultStatus.OK] * 3 \
            + [TokenResultStatus.BLOCKED]
        # A's slice: untouched, still full fidelity, zero degraded.
        assert cli.request_token(FID_A).status == TokenResultStatus.OK
        s = cli.failover_stats()
        assert s["degraded"] is True
        assert s["shard"]["degradedSlices"] == N - 1   # B's slices only
        assert s["shard"]["leaders"]["A"]["degraded"] is False
        assert s["shard"]["leaders"]["B"]["degraded"] is True
        assert cli.fence.stale_rejected_count == 0
        # B recovers -> its slices exit degraded on the next verdict.
        svc = DefaultTokenService(_rules((FID_B, 1000)), max_allowed_qps=1e9)
        svc.set_shard(ShardState(N, 1, {s: 1 for s in range(N)
                                        if s != SL_A}))
        revived = ClusterTokenServer(
            svc, host="127.0.0.1", port=specs[1].port).start()
        try:
            assert _wait(lambda: cli._pool["B"].is_connected(), 10.0)
            assert _wait(lambda: cli.request_token(FID_B).status
                         == TokenResultStatus.OK, 10.0)
            assert cli.failover_stats()["shard"]["degradedSlices"] == 0
        finally:
            revived.stop()
    finally:
        cli.stop()
        for s in servers:
            s.stop()


class _StatusStub:
    """Pool stand-in answering a fixed wire status (no sockets)."""

    def __init__(self, status, wait_ms=0, connected=True):
        from sentinel_tpu.cluster.token_service import TokenResult

        self._result = TokenResult(status, wait_ms=wait_ms)
        self._connected = connected
        self.calls = 0

    def is_connected(self):
        return self._connected

    def request_token(self, *a, **k):
        self.calls += 1
        return self._result

    def request_param_token(self, *a, **k):
        return self.request_token()

    def stop(self):
        pass


def test_survivor_overload_does_not_mask_victim_failover(frozen_time):
    """A survivor shedding OVERLOADED must not reset the dead owner's
    failover clock: a frontend sheds BEFORE its slice check, so it sheds
    for slices it does not even own — if that reply were credited to the
    owner, the victim's slices could never enter degraded mode for as
    long as any other leader is loaded. The owner's clock stops only
    when the owner ITSELF proves alive (its own OVERLOADED answer, or
    the backoff window such an answer opened)."""
    specs = (ClusterServerSpec("A", "127.0.0.1", _free_port()),
             ClusterServerSpec("B", "127.0.0.1", _free_port()))
    smap = _owner_map({"A": [SL_A], "B": [s for s in range(N) if s != SL_A]},
                      servers=specs)
    cli = ShardedTokenClient(
        smap, request_timeout_s=0.3, failover_deadline_ms=400,
        degraded=DegradedQuota(divisor=2, thresholds={FID_B: (8.0, 1000)}),
        health_gate=None)
    try:
        shedding_a = _StatusStub(TokenResultStatus.OVERLOADED, wait_ms=50)
        cli._pool = {"A": shedding_a, "B": _StatusStub(
            TokenResultStatus.FAIL, connected=False)}     # B is DOWN
        # Walk for B's slice: B dead, A sheds -> OVERLOADED surfaces
        # (safe local degradation) but B's clock STARTS.
        r = cli.request_token(FID_B)
        assert r.status == TokenResultStatus.OVERLOADED
        assert shedding_a.calls == 1
        time_util.advance_time(500)                       # past deadline
        # Still shedding elsewhere — B's slices now serve the per-client
        # degraded share regardless (8/2 = 4).
        got = [cli.request_token(FID_B).status for _ in range(5)]
        assert got == [TokenResultStatus.OK] * 4 \
            + [TokenResultStatus.BLOCKED]
        s = cli.failover_stats()
        assert s["shard"]["leaders"]["B"]["degraded"] is True
        assert s["shard"]["leaders"]["A"]["degraded"] is False
        # The owner ITSELF answering OVERLOADED is alive: its spell ends
        # and its slices return OVERLOADED, not degraded verdicts.
        time_util.advance_time(300)                       # A's backoff over
        cli._pool["B"] = _StatusStub(TokenResultStatus.OVERLOADED,
                                     wait_ms=50)
        r = cli.request_token(FID_B)
        assert r.status == TokenResultStatus.OVERLOADED
        assert cli.failover_stats()["shard"]["leaders"]["B"]["degraded"] \
            is False
    finally:
        cli.stop()


def test_map_change_reuses_live_sockets(frozen_time):
    """A rebalance that only moves slices keeps every unchanged leader's
    live socket (no reconnect storm): the PR 5 same-target-reuse pin
    extended to the per-leader pool."""
    servers, specs = _two_leader_wire()
    m1 = _owner_map({"A": [SL_A], "B": [s for s in range(N) if s != SL_A]},
                    servers=specs)
    cli = ShardedTokenClient(m1, request_timeout_s=10.0).start()
    try:
        assert _wait(cli.is_connected)
        inner_a, inner_b = cli._pool["A"], cli._pool["B"]
        assert cli.request_token(FID_A).status == TokenResultStatus.OK
        m2 = _owner_map({"A": [SL_A, SL_B],
                         "B": [s for s in range(N) if s not in (SL_A, SL_B)]},
                        version=2, servers=specs)
        assert cli.apply_map(m2)
        assert cli._pool["A"] is inner_a         # same sockets, no churn
        assert cli._pool["B"] is inner_b
        assert cli.socket_reuse_count == 2
        assert cli.map.version == 2
        # Stale and ring-resize maps are refused.
        assert not cli.apply_map(m1)
        assert not cli.apply_map(m2._replace(version=3, n_slices=N * 2))
        # A leader address CHANGE does rebuild that one client.
        specs2 = (specs[0],
                  ClusterServerSpec("B", "127.0.0.1", _free_port()))
        m3 = _owner_map({"A": [SL_A, SL_B],
                         "B": [s for s in range(N) if s not in (SL_A, SL_B)]},
                        version=3, servers=specs2)
        assert cli.apply_map(m3)
        assert cli._pool["A"] is inner_a
        assert cli._pool["B"] is not inner_b
    finally:
        cli.stop()
        for s in servers:
            s.stop()


# -- checkpoint slice filtering ----------------------------------------------


def test_checkpoint_slice_filter_roundtrip(frozen_time, tmp_path):
    path = str(tmp_path / "slice.ck")
    svc = DefaultTokenService(_rules((FID_A, 10), (FID_B, 10), (FID_C, 10)))
    for _ in range(3):
        assert svc.request_token(FID_A).status == TokenResultStatus.OK
    for _ in range(5):
        assert svc.request_token(FID_C).status == TokenResultStatus.OK
    ckpt.save_cluster_checkpoint(svc, path, slices=(SL_A,), n_slices=N,
                                 epoch=4)
    header, _arrays = ckpt._load_npz(path)
    assert set(header["flows"]) == {str(FID_A)}      # only SL_A's flows
    assert header["epoch"] == 4 and header["slices"] == [SL_A]
    # Restore into a fresh service: only the filtered slice grafts; a
    # filter EXCLUDING the file's slice grafts nothing.
    svc2 = DefaultTokenService(_rules((FID_A, 10), (FID_B, 10), (FID_C, 10)))
    assert ckpt.restore_cluster_checkpoint(svc2, path, slices=(SL_C,),
                                           n_slices=N) == 0
    assert ckpt.restore_cluster_checkpoint(svc2, path, slices=(SL_A,),
                                           n_slices=N) == 1
    got = [svc2.request_token(FID_A).status for _ in range(8)]
    assert got.count(TokenResultStatus.OK) == 7      # 3 carried + 7 = 10
    assert svc2.request_token(FID_C).status == TokenResultStatus.OK
    with pytest.raises(ValueError):
        ckpt.save_cluster_checkpoint(svc, path, slices=(SL_A,))  # no ring


def test_handoff_preserves_quota_bound(frozen_time, tmp_path):
    """Graceful rebalance: donor publishes the slice's rows then fences
    itself; the recipient warm-starts from them — total admissions for a
    flow across the handoff never exceed its threshold (margin 0 for a
    graceful handoff; a crash's margin is grants-since-last-publish,
    drilled in the 3-leader test)."""
    T = 6
    seats = _seats(tmp_path, ("A", "B"), [(FID_A, T), (FID_C, T)])
    specs = (ClusterServerSpec("A", "127.0.0.1", _free_port()),
             ClusterServerSpec("B", "127.0.0.1", _free_port()))
    m1 = _owner_map({"A": [SL_A], "B": [s for s in range(N) if s != SL_A]},
                    servers=specs)
    try:
        seats["A"].apply_map(m1)
        seats["B"].apply_map(m1)
        svc_a = seats["A"].state.token_server.service
        svc_b = seats["B"].state.token_server.service
        for _ in range(4):
            assert svc_a.request_token(FID_A).status == TokenResultStatus.OK
        # Move SL_A to B, bumping ONLY that slice's epoch (unchanged
        # slices keep term 1 — per-slice epochs, not a global term).
        m2 = _owner_map(
            {"B": list(range(N))}, version=2,
            epochs={**{s: 1 for s in range(N)}, SL_A: 2}, servers=specs)
        seats["A"].apply_map(m2)     # donor drains + flips to client
        assert seats["A"].state.mode == CLUSTER_CLIENT
        assert svc_a.shard.epochs == {SL_A: 1}  # old view, now fenced out
        seats["B"].apply_map(m2)
        assert seats["B"].rows_restored >= 1
        assert seats["B"].handoffs >= 1
        got = [svc_b.request_token(FID_A) for _ in range(4)]
        assert [g.status for g in got] \
            == [TokenResultStatus.OK, TokenResultStatus.OK,
                TokenResultStatus.BLOCKED, TokenResultStatus.BLOCKED]
        assert all(g.epoch == 2 for g in got)   # the bumped slice term
        # Unchanged slices kept epoch 1 — still fenced per-slice.
        assert svc_b.request_token(FID_C).epoch == 1
    finally:
        seats["A"].stop()
        seats["B"].stop()


def test_flat_leader_first_shard_map_publishes_whole_ring(frozen_time,
                                                          tmp_path):
    """A FLAT (PR 5) leader adopting its FIRST shard map owned the whole
    key space: the migration publishes EVERY ring slice from the live
    flat service before the sharded world restores — the slices this
    seat keeps graft on its own warm-start, and the moved ones graft on
    the recipients'. No flow cold-starts mid-window."""
    T = 6
    seats = _seats(tmp_path, ("A", "B"), [(FID_A, T), (FID_B, T)])
    specs = (ClusterServerSpec("A", "127.0.0.1", _free_port()),
             ClusterServerSpec("B", "127.0.0.1", _free_port()))
    flat = ClusterMap(epoch=3, servers=(
        ClusterServerSpec("A", "127.0.0.1", _free_port()),), clients=("X",))
    try:
        seats["A"].apply_map(flat)               # PR 5 flat leadership
        assert seats["A"].state.mode == CLUSTER_SERVER
        svc_flat = seats["A"].state.token_server.service
        assert svc_flat.shard is None
        for _ in range(4):
            assert svc_flat.request_token(FID_A).status \
                == TokenResultStatus.OK
        for _ in range(3):
            assert svc_flat.request_token(FID_B).status \
                == TokenResultStatus.OK
        # First shard map: A keeps FID_A's slice, B gains the rest
        # (including FID_B's).
        m = _owner_map({"A": [SL_A],
                        "B": [s for s in range(N) if s != SL_A]},
                       version=4, servers=specs)
        seats["A"].apply_map(m)
        seats["B"].apply_map(m)
        svc_a = seats["A"].state.token_server.service
        svc_b = seats["B"].state.token_server.service
        assert svc_a.shard is not None and svc_a is not svc_flat
        # A's retained slice kept its rows: 4 of T=6 carried over.
        got = [svc_a.request_token(FID_A).status for _ in range(3)]
        assert got == [TokenResultStatus.OK, TokenResultStatus.OK,
                       TokenResultStatus.BLOCKED]
        # B's gained slice grafted the flat rows: 3 of T=6 carried.
        got = [svc_b.request_token(FID_B).status for _ in range(4)]
        assert got == [TokenResultStatus.OK, TokenResultStatus.OK,
                       TokenResultStatus.OK, TokenResultStatus.BLOCKED]
    finally:
        seats["A"].stop()
        seats["B"].stop()


# -- chaos seams --------------------------------------------------------------


def test_handoff_stall_widens_margin_but_stays_bounded(frozen_time, tmp_path,
                                                       injector):
    """cluster.shard.handoff.stall (delay mode): the donor's publish is
    slow but completes — the handoff still lands and the quota bound
    still holds (a stall widens the margin only when a crash interrupts
    the publish; a slow graceful drain costs latency, not correctness)."""
    T = 5
    seats = _seats(tmp_path, ("A", "B"), [(FID_A, T)])
    specs = (ClusterServerSpec("A", "127.0.0.1", _free_port()),
             ClusterServerSpec("B", "127.0.0.1", _free_port()))
    m1 = _owner_map({"A": [SL_A], "B": [s for s in range(N) if s != SL_A]},
                    servers=specs)
    injector.arm("cluster.shard.handoff.stall", "delay", delay_ms=50,
                 times=64)
    try:
        seats["A"].apply_map(m1)
        seats["B"].apply_map(m1)
        svc_a = seats["A"].state.token_server.service
        for _ in range(3):
            assert svc_a.request_token(FID_A).status == TokenResultStatus.OK
        m2 = _owner_map({"B": list(range(N))}, version=2,
                        epochs={**{s: 1 for s in range(N)}, SL_A: 2},
                        servers=specs)
        t0 = time.monotonic()
        seats["A"].apply_map(m2)
        assert time.monotonic() - t0 >= 0.05     # the stall really fired
        seats["B"].apply_map(m2)
        assert seats["B"].rows_restored >= 1
        svc_b = seats["B"].state.token_server.service
        got = [svc_b.request_token(FID_A).status for _ in range(3)]
        assert got == [TokenResultStatus.OK, TokenResultStatus.OK,
                       TokenResultStatus.BLOCKED]   # 3 carried + 2 = T
    finally:
        seats["A"].stop()
        seats["B"].stop()


def test_map_split_seat_sits_out_push(frozen_time, tmp_path, injector):
    """cluster.shard.map.split: a seat the push cannot reach stays on
    its old map version — visible as a version split in stats — and
    rejoins on the next successful push."""
    seats = _seats(tmp_path, ("A",), [(FID_A, 5)])
    specs = (ClusterServerSpec("A", "127.0.0.1", _free_port()),)
    m1 = _owner_map({"A": list(range(N))}, servers=specs)
    try:
        seats["A"].apply_map(m1)
        assert seats["A"].shard_map.version == 1
        injector.arm("cluster.shard.map.split", "error", times=1)
        m2 = _owner_map({"A": list(range(N))}, version=2, servers=specs)
        seats["A"].apply_map(m2)
        assert seats["A"].shard_map.version == 1    # sat the push out
        assert seats["A"].stats()["shardMapVersion"] == 1
        seats["A"].apply_map(m2)                    # next push lands
        assert seats["A"].shard_map.version == 2
    finally:
        seats["A"].stop()


def test_donor_zombie_late_replies_fence_rejected(frozen_time, tmp_path,
                                                  injector):
    """cluster.shard.donor.zombie: the donor neither publishes nor
    fences — it keeps granting the moved slice at the old epoch. A
    client that saw the new map must fence-reject its late replies (no
    double-granting across the split)."""
    seats = _seats(tmp_path, ("A", "B"), [(FID_A, 100)])
    specs = [ClusterServerSpec(mid, "127.0.0.1", _free_port())
             for mid in seats]
    m1 = _owner_map({"A": [SL_A], "B": [s for s in range(N) if s != SL_A]},
                    servers=specs)
    try:
        seats["A"].apply_map(m1)
        seats["B"].apply_map(m1)
        m2 = _owner_map({"B": list(range(N))}, version=2,
                        epochs={**{s: 1 for s in range(N)}, SL_A: 2},
                        servers=specs)
        injector.arm("cluster.shard.donor.zombie", "error", times=1)
        seats["A"].apply_map(m2)                 # zombie: map unapplied
        assert seats["A"].shard_map.version == 1
        assert seats["A"].state.mode == CLUSTER_SERVER   # still serving!
        svc_a = seats["A"].state.token_server.service
        assert svc_a.shard.epochs == {SL_A: 1}
        seats["B"].apply_map(m2)                 # the fleet moves on
        # A fenced client (saw m2's epochs) rejects the zombie's grants.
        fence = SliceEpochFence()
        for sl, ep in enumerate(m2.slice_epoch):
            fence.observe(ep, sl)
        cli = ClusterTokenClient(
            "127.0.0.1", specs[0].port, request_timeout_s=10.0,
            epoch_fence=fence,
            fence_scope_fn=lambda fid: slice_of(int(fid), N)).start()
        try:
            assert _wait(cli.is_connected)
            r = cli.request_token(FID_A)
            assert r.status == TokenResultStatus.FAIL
            assert fence.stale_rejected_count == 1
        finally:
            cli.stop()
    finally:
        seats["A"].stop()
        seats["B"].stop()


# -- engine + ops surfaces ----------------------------------------------------


class _WrongSliceStub:
    serves_degraded = False

    def __init__(self):
        self.calls = 0

    def is_connected(self):
        return True

    def request_token(self, *a, **k):
        from sentinel_tpu.cluster.token_service import TokenResult

        self.calls += 1
        return TokenResult(TokenResultStatus.WRONG_SLICE, wait_ms=9)

    def request_param_token(self, *a, **k):
        return self.request_token()

    def stop(self):
        pass


@pytest.mark.slow
def test_engine_wrong_slice_degrades_to_local_check(engine):
    """An un-healed WRONG_SLICE reaching the engine (e.g. a plain
    client pointed at a sharded leader) degrades the rule to its local
    check — counted separately so a stale-map storm is visible.

    Slow-marked (ISSUE 15 tier-1 trim): ~9s measured, dominated by the
    full-engine fixture compile; the WRONG_SLICE wire/service/client
    contracts all keep tier-1 seeds above, and the chaos campaign
    drives the routing walk continuously."""
    st.load_flow_rules([st.FlowRule(
        resource="shard-res", count=3, cluster_mode=True,
        cluster_config={"flowId": 4242, "thresholdType": THRESHOLD_GLOBAL,
                        "fallbackToLocalWhenFail": True})])
    stub = _WrongSliceStub()
    engine.cluster.token_client = stub
    engine.cluster.mode = CLUSTER_CLIENT
    try:
        ok = blocked = 0
        for _ in range(5):
            try:
                engine.entry("shard-res").exit()
                ok += 1
            except st.BlockException:
                blocked = blocked + 1
        assert stub.calls == 5
        assert ok == 3 and blocked == 2      # the LOCAL check enforced
        rs = engine.resilience_stats()
        assert rs["clusterWrongSliceCount"] == 5
        assert rs["clusterFallbackCount"] >= 5
    finally:
        engine.cluster.token_client = None
        engine.cluster.mode = -1


def test_shard_stats_reach_exporter_and_ha_stats(engine, frozen_time):
    from sentinel_tpu.telemetry.exporter import render_engine_metrics

    engine.cluster.server_rules().load_rules("default", [_rule(FID_A, 50)])
    svc = DefaultTokenService(engine.cluster.server_rules())
    svc.set_shard(ShardState(N, 3, {SL_A: 4, SL_B: 2}))
    engine.cluster.set_to_server(host="127.0.0.1", port=0, service=svc,
                                 epoch=4)
    svc.set_shard(ShardState(N, 3, {SL_A: 4, SL_B: 2}))  # epoch reset above
    try:
        assert svc.request_token(FID_C).status \
            == TokenResultStatus.WRONG_SLICE
        ha = engine.cluster.ha_stats()
        assert ha["shard"]["slicesOwned"] == 2
        assert ha["shard"]["wrongSliceRejected"] == 1
        assert engine.cluster.shard_stats() == ha["shard"]
        text = render_engine_metrics(engine)
        assert "sentinel_tpu_shard_slices_owned 2" in text
        assert 'sentinel_tpu_shard_slice_epoch{slice="0"} 4' in text
        assert 'sentinel_tpu_shard_slice_epoch{slice="4"} 2' in text
        assert "sentinel_tpu_shard_wrong_slice_rejected_total 1" in text
        assert "sentinel_tpu_shard_handoffs_total" in text
        assert "sentinel_tpu_shard_degraded_slices 0" in text
    finally:
        engine.cluster.stop()


# -- the 3-leader drill -------------------------------------------------------


def _three_leader_cluster(tmp_path, T=6):
    """Three HA seats, one slice-distinct flow each, shared handoff
    files, and a sharded client with a static degraded share."""
    pairs = [(FID_A, T), (FID_B, T), (FID_C, T)]
    seats = _seats(tmp_path, ("A", "B", "C"), pairs)
    specs = tuple(ClusterServerSpec(mid, "127.0.0.1", _free_port())
                  for mid in ("A", "B", "C"))
    rest = [s for s in range(N) if s not in (SL_A, SL_B, SL_C)]
    m1 = _owner_map({"A": [SL_A], "B": [SL_B], "C": [SL_C] + rest},
                    servers=specs)
    for seat in seats.values():
        seat.apply_map(m1)
        # Absorb the per-width jit compiles up front (pad_width is exact
        # below 64, so widths 1..4 each compile separately): a first
        # compile landing mid-drill stalls EVERY seat's replies (shared
        # process GIL) past the client timeout — a latency artifact the
        # concurrent-traffic drill would misread as a lost leader.
        svc = seat.state.token_server.service
        for w in (1, 2, 3, 4):
            svc.request_tokens([(None, 0, False)] * w)
    # health_gate=None + a generous request timeout: the three leaders
    # share this process's GIL, and a checkpoint publish (fsync-heavy)
    # on one can stall another's reply thread past a tight timeout on a
    # loaded CI box — which would trip the per-leader breaker and turn
    # a latency hiccup into a FAIL cascade the drill would misread as a
    # shard-semantics violation. Breaker behavior has its own pins
    # (test_chaos / test_cluster_ha); these drills pin SLICE semantics.
    cli = ShardedTokenClient(
        m1, request_timeout_s=2.0, failover_deadline_ms=400,
        health_gate=None,
        degraded=DegradedQuota(
            divisor=1, thresholds={fid: (float(T), 1000)
                                   for fid, _ in pairs})).start()
    return seats, specs, m1, cli


def test_three_leader_crash_drill_scaled(frozen_time, tmp_path):
    """Tier-1-scaled ISSUE 12 acceptance seed: kill one of three leaders
    mid-traffic; only its slices degrade (zero degraded verdicts and
    zero fence violations on the survivors), and its slices recover via
    a checkpoint-grafted handoff with over-admission == grants since the
    victim's last publish."""
    T = 6
    seats, specs, m1, cli = _three_leader_cluster(tmp_path, T)
    try:
        assert _wait(lambda: all(c.is_connected()
                                 for c in cli._pool.values()))
        # Mid-traffic: C grants 3, publishes, grants 1 more (the margin).
        for _ in range(2):
            assert cli.request_token(FID_A).status == TokenResultStatus.OK
            assert cli.request_token(FID_B).status == TokenResultStatus.OK
        for _ in range(3):
            assert cli.request_token(FID_C).status == TokenResultStatus.OK
        seats["C"].publish_checkpoint()
        assert cli.request_token(FID_C).status == TokenResultStatus.OK
        ok_c_before = 4

        # Hard crash: listener + connections die, NO drain publish.
        seats["C"].state.token_server._fault_crash()
        assert _wait(lambda: not cli._pool["C"].is_connected())

        # Survivors: full fidelity, zero degraded, zero fence rejects.
        assert cli.request_token(FID_A).status == TokenResultStatus.OK
        assert cli.request_token(FID_B).status == TokenResultStatus.OK
        assert cli.failover_stats()["shard"]["degradedSlices"] == 0

        # The victim's slices degrade to the per-client share after the
        # deadline (share == T here: single client, divisor 1).
        assert cli.request_token(FID_C).status == TokenResultStatus.FAIL
        time_util.advance_time(500)
        assert cli.request_token(FID_C).status == TokenResultStatus.OK
        st_shard = cli.failover_stats()["shard"]
        assert st_shard["degradedSlices"] == len(m1.slices_of("C"))
        assert st_shard["leaders"]["A"]["degraded"] is False
        assert st_shard["leaders"]["B"]["degraded"] is False

        # Rebalance: C's slices move to B (epoch bump per moved slice);
        # B warm-starts from C's last publish.
        # Rebalance protocol (OPERATIONS): bump ONLY the moved slices'
        # epochs — standing leaders' in-flight replies stay honest.
        moved = m1.slices_of("C")
        m2 = _owner_map(
            {"A": [SL_A], "B": [s for s in range(N) if s != SL_A]},
            version=2,
            epochs={**{s: 1 for s in range(N)}, **{s: 2 for s in moved}},
            servers=specs)
        seats["A"].apply_map(m2)
        seats["B"].apply_map(m2)
        assert seats["B"].rows_restored >= 1
        assert cli.apply_map(m2)

        # Over-admission bound: C published at 3 grants, then granted 1
        # more (lost). B restored 3 -> T - 3 = 3 remain; total device
        # grants = 4 + 3 = T + 1 = T + grants-since-publish.
        time_util.advance_time(100)  # same window: bound must hold NOW
        post = [cli.request_token(FID_C).status for _ in range(4)]
        assert post == [TokenResultStatus.OK] * 3 \
            + [TokenResultStatus.BLOCKED]
        assert ok_c_before + post.count(TokenResultStatus.OK) == T + 1

        # Recovered: nothing degraded, still zero fence violations for
        # the survivors' lanes, and every leader answered in-slice.
        assert cli.failover_stats()["shard"]["degradedSlices"] == 0
        # Healed routing pays no further mis-route tax anywhere.
        wrong_before = (
            seats["A"].state.token_server.service.wrong_slice_count,
            seats["B"].state.token_server.service.wrong_slice_count)
        assert cli.request_token(FID_A).status == TokenResultStatus.OK
        assert cli.request_token(FID_B).status == TokenResultStatus.OK
        assert cli.request_token(FID_C).status == TokenResultStatus.BLOCKED
        assert wrong_before == (
            seats["A"].state.token_server.service.wrong_slice_count,
            seats["B"].state.token_server.service.wrong_slice_count)
    finally:
        cli.stop()
        for seat in seats.values():
            seat.stop()


@pytest.mark.slow
def test_three_leader_multi_spell_drill(frozen_time, tmp_path):
    """Multi-spell flavor of the crash drill: two successive victim
    crashes with rebalances in between, concurrent traffic on the
    survivors throughout — per-slice blast radius, fencing, and the
    per-slice over-admission bound hold across BOTH spells."""
    import threading

    T = 50
    seats, specs, m1, cli = _three_leader_cluster(tmp_path, T)
    stop = threading.Event()
    survivor_fail = []

    def hammer():
        # A is never a victim: its slice must serve a wire-grade
        # verdict (OK/BLOCKED, never FAIL/degraded) through BOTH spells.
        while not stop.is_set():
            r = cli.request_token(FID_A)
            if r.status not in (TokenResultStatus.OK,
                                TokenResultStatus.BLOCKED):
                survivor_fail.append(("A", r.status))
            time.sleep(0.01)

    t = threading.Thread(target=hammer, daemon=True)
    try:
        assert _wait(lambda: all(c.is_connected()
                                 for c in cli._pool.values()))
        t.start()
        # Spell 1: crash C, degrade, rebalance onto B.
        for _ in range(5):
            assert cli.request_token(FID_C).status == TokenResultStatus.OK
        seats["C"].publish_checkpoint()
        seats["C"].state.token_server._fault_crash()
        assert _wait(lambda: not cli._pool["C"].is_connected())
        cli.request_token(FID_C)
        time_util.advance_time(500)
        assert cli.request_token(FID_C).status == TokenResultStatus.OK
        # Bump ONLY the moved slices' epochs (the OPERATIONS rebalance
        # protocol): bumping a standing leader's lane would fence-reject
        # its own honest in-flight replies — exactly what the concurrent
        # hammer on A's slice is here to catch.
        moved = m1.slices_of("C")
        m2 = _owner_map(
            {"A": [SL_A], "B": [s for s in range(N) if s != SL_A]},
            version=2,
            epochs={**{s: 1 for s in range(N)}, **{s: 2 for s in moved}},
            servers=specs)
        seats["A"].apply_map(m2)
        seats["B"].apply_map(m2)
        assert cli.apply_map(m2)
        assert _wait(lambda: cli.request_token(FID_C).status
                     == TokenResultStatus.OK, 10.0)
        # Spell 2: crash B (now owning everything but SL_A); only A's
        # slice keeps serving wire verdicts.
        seats["B"].publish_checkpoint()
        seats["B"].state.token_server._fault_crash()
        assert _wait(lambda: not cli._pool["B"].is_connected())
        cli.request_token(FID_B)
        time_util.advance_time(500)
        assert cli.request_token(FID_B).status in (
            TokenResultStatus.OK, TokenResultStatus.BLOCKED)  # share
        assert cli.request_token(FID_A).status == TokenResultStatus.OK
        m3 = _owner_map({"A": list(range(N))}, version=3,
                        epochs={**{s: 3 for s in range(N)}, SL_A: 1},
                        servers=specs)
        seats["A"].apply_map(m3)
        assert cli.apply_map(m3)
        assert _wait(lambda: cli.request_token(FID_B).status
                     in (TokenResultStatus.OK, TokenResultStatus.BLOCKED),
                     10.0)
        stop.set()
        t.join(timeout=5)
        # The never-killed leader's lane saw no FAIL and no fence
        # violation across both spells. (A's service DOES answer
        # WRONG_SLICE probes while walks search for dead leaders'
        # slices — that's the healing path, not a violation.)
        assert survivor_fail == []
        assert cli.fence.stale_rejected_count == 0
    finally:
        stop.set()
        cli.stop()
        for seat in seats.values():
            seat.stop()

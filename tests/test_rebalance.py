"""Governed shard rebalancer (ISSUE 16 — ``cluster/rebalance.py``).

Tier-1: minimal-movement and envelope properties over synthetic load
folds, the freeze-gate precedence at rebalancer level, last-known-good
rollback exactness, the certification veto on a deliberately broken
plan (the ``slice_conservation`` acceptance), and join autoscaling
through the same propose→certify→apply pipeline.

Slow: bit-identical certification replay, and the scaled 3-leader LIVE
drill — real ``ClusterHAManager`` seats, induced hot leader, exactly
one certified journal-chained map apply.
"""

import pytest

from sentinel_tpu.adaptive.envelope import (
    FREEZE_BACKOFF,
    FREEZE_DEGRADED,
    FREEZE_MANUAL,
    FREEZE_STALE,
)
from sentinel_tpu.cluster.ha import ClusterServerSpec
from sentinel_tpu.cluster.rebalance import ShardRebalancer
from sentinel_tpu.cluster.sharding import ShardMap, slice_of
from sentinel_tpu.telemetry.journal import ControlPlaneJournal, current_cause

N_SLICES = 8
LEADERS = ("A", "B", "C")


def _mk_map(owner, version=5, epochs=None, leaders=LEADERS):
    specs = tuple(ClusterServerSpec(m, "127.0.0.1", 0) for m in leaders)
    return ShardMap(version=version, n_slices=len(owner), servers=specs,
                    slice_owner=tuple(owner),
                    slice_epoch=tuple(epochs or (version,) * len(owner)))


class _FakeHA:
    def __init__(self, smap):
        self.shard_map = smap
        self.applied = []
        self.pending = False

    def transition_pending(self):
        return self.pending

    def apply_map(self, smap):
        self.applied.append((smap, current_cause()))
        self.shard_map = smap


class _FakeFleet:
    """Slice loads + health the rebalancer senses; everything mutable
    so tests can induce staleness/degradation/skew."""

    def __init__(self, clock, loads, degraded=(), lag_ms=2000):
        self.clock = clock
        self.loads = dict(loads)          # slice -> load
        self.degraded = set(degraded)
        self.lag_ms = lag_ms

    def settled_through_ms(self):
        return self.clock() - self.lag_ms

    def status(self):
        return {"leaders": {
            m: {"stale": m in self.degraded, "epochRegressed": False}
            for m in LEADERS}}

    def slice_loads(self, flow_of, n, window_seconds=None,
                    settled_only=True):
        return {"nSlices": n, "seconds": 30,
                "settledThroughMs": self.settled_through_ms(),
                "slices": dict(self.loads), "observedByLeader": {},
                "unattributed": 0}


def _mk(loads=None, owner=None, degraded=(), lag_ms=2000, now=10_000_000):
    clock_now = [now]
    clock = lambda: clock_now[0]  # noqa: E731
    owner = owner or ["A"] * 5 + ["B", "C", "C"]
    loads = loads if loads is not None else {
        sl: (1000 if owner[sl] == "A" else 50) for sl in range(len(owner))}
    smap = _mk_map(owner)
    ha = _FakeHA(smap)
    fleet = _FakeFleet(clock, loads, degraded=degraded, lag_ms=lag_ms)
    journal = ControlPlaneJournal(clock, path=None)
    rb = ShardRebalancer(ha=ha, fleet=fleet, journal=journal,
                         flow_of=lambda r: None, clock=clock)
    return rb, ha, fleet, journal, clock_now


# -- minimal movement ------------------------------------------------------


@pytest.mark.parametrize("seed", range(6))
def test_moves_bounded_by_cap_and_improve_skew(seed):
    """Property: over randomized load shapes, a plan never moves more
    than the cap, only moved slices differ from the base map, only
    moved slices' epochs bump, and the projected skew never worsens."""
    import random

    rng = random.Random(seed)
    owner = [rng.choice(LEADERS) for _ in range(N_SLICES)]
    # Ensure every leader holds a seat in the map even if it owns none.
    loads = {sl: rng.randrange(0, 2000) for sl in range(N_SLICES)}
    rb, ha, _fleet, _j, _now = _mk(loads=loads, owner=owner)
    from sentinel_tpu.core.config import config

    cap = config.rebalance_max_slices_per_epoch()
    r = rb.propose()
    if not r["ok"]:
        assert r["veto"] in ("deadband",)
        return
    plan = rb.plans[r["plan"]["planId"]]
    assert 0 < len(plan.moves) <= cap
    base = ha.shard_map
    for sl in range(N_SLICES):
        if sl in plan.moves:
            assert plan.proposed.slice_owner[sl] != base.slice_owner[sl]
            assert plan.proposed.slice_owner[sl] == plan.moves[sl][1]
            assert plan.proposed.slice_epoch[sl] == plan.proposed.version
        else:
            assert plan.proposed.slice_owner[sl] == base.slice_owner[sl]
            assert plan.proposed.slice_epoch[sl] == base.slice_epoch[sl]
    assert plan.proposed.version == base.version + 1
    assert plan.skew_after <= plan.skew_before


def test_deadband_vetoes_balanced_cluster():
    """No plan while skew is inside the deadband: a balanced cluster
    must be left alone (movement is never free)."""
    loads = {sl: 100 for sl in range(N_SLICES)}
    owner = ["A", "A", "A", "B", "B", "B", "C", "C"]
    rb, _ha, _f, _j, _now = _mk(loads=loads, owner=owner)
    r = rb.propose()
    assert not r["ok"] and r["veto"] == "deadband"
    assert rb.plans_total == 0


# -- envelope invariants ---------------------------------------------------


def test_cooldown_vetoes_remove_after_apply():
    """Cooldowns stamp at APPLY: a just-moved slice cannot move again
    inside the cooldown window, and can after it expires."""
    rb, ha, fleet, _j, now = _mk()
    r = rb.propose()
    pid = r["plan"]["planId"]
    plan = rb.plans[pid]
    plan.certified = True  # envelope test: skip the mesh episode
    assert rb.apply(pid)["ok"]
    moved = set(plan.moves)
    # Re-skew so the SAME slices would want to move back.
    fleet.loads = {sl: (2000 if sl in moved else 10)
                   for sl in range(N_SLICES)}
    now[0] += 1000
    r2 = rb.propose()
    if r2["ok"]:
        assert not moved & set(rb.plans[r2["plan"]["planId"]].moves)
    from sentinel_tpu.core.config import config

    now[0] += 2 * config.rebalance_cooldown_ms() + 1000
    assert rb.ledger.check(next(iter(moved)), "A", now[0]) is None


def test_flip_hysteresis_outlasts_plain_cooldown():
    """Moving a slice BACK (direction flip) waits the longer flip
    window even after the plain cooldown has expired."""
    rb, _ha, _f, _j, now = _mk()
    sl = 3
    rb.ledger.stamp(sl, "B", now[0])
    after_plain = now[0] + rb.ledger.cooldown_ms + 1
    assert rb.ledger.check(sl, "B", after_plain) is None
    assert rb.ledger.check(sl, "A", after_plain) == "hysteresis"
    after_flip = now[0] + rb.ledger.flip_cooldown_ms + 1
    assert rb.ledger.check(sl, "A", after_flip) is None


def test_degraded_leader_freezes_skew_plans_but_not_leave():
    """Freeze precedence: a degraded leader freezes skew planning, but
    a fold-out plan for that leader proceeds (the sick seat is the
    reason to move)."""
    rb, _ha, _f, _j, _now = _mk(degraded=("A",))
    r = rb.propose()
    assert not r["ok"] and r["frozenBy"] == FREEZE_DEGRADED
    r2 = rb.plan_leave("A")
    assert r2["ok"], r2
    plan = rb.plans[r2["plan"]["planId"]]
    assert all(frm == "A" for _sl, (frm, _to) in plan.moves.items())
    assert "A" not in {to for _sl, (_frm, to) in plan.moves.items()}


def test_freeze_precedence_manual_stale_degraded_backoff():
    rb, _ha, fleet, _j, now = _mk(degraded=("B",))
    fleet.lag_ms = 60_000          # stale telemetry
    rb.backoff_until_ms = now[0] + 99_999
    rb.manual_frozen = True
    assert rb.status()["frozenBy"] == FREEZE_MANUAL
    rb.manual_frozen = False
    assert rb.status()["frozenBy"] == FREEZE_STALE
    fleet.lag_ms = 1000
    assert rb.status()["frozenBy"] == FREEZE_DEGRADED
    fleet.degraded = set()
    assert rb.status()["frozenBy"] == FREEZE_BACKOFF
    rb.backoff_until_ms = 0
    assert rb.status()["frozen"] is False


def test_mid_handoff_vetoes_all_movement():
    rb, ha, _f, _j, _now = _mk()
    ha.pending = True
    r = rb.propose()
    assert not r["ok"]
    assert rb.plans_total == 0


def test_apply_requires_certification_and_fresh_base():
    rb, ha, _f, _j, _now = _mk()
    pid = rb.propose()["plan"]["planId"]
    r = rb.apply(pid)
    assert not r["ok"] and r["veto"] == "certification"
    rb.plans[pid].certified = True
    ha.shard_map = ha.shard_map._replace(version=ha.shard_map.version + 1)
    r2 = rb.apply(pid)
    assert not r2["ok"] and r2["veto"] == "stale-plan"


# -- rollback --------------------------------------------------------------


def test_rollback_restores_exact_prior_ownership():
    """One-command rollback: ownership returns bit-identically to the
    retained map; version and moved-slice epochs bump (per-slice
    fencing forbids reviving old terms)."""
    rb, ha, _f, _j, _now = _mk()
    before = ha.shard_map
    pid = rb.propose()["plan"]["planId"]
    rb.plans[pid].certified = True
    assert rb.apply(pid)["ok"]
    assert ha.shard_map.slice_owner != before.slice_owner
    r = rb.rollback()
    assert r["ok"]
    assert ha.shard_map.slice_owner == before.slice_owner
    assert ha.shard_map.version > before.version
    assert rb.rollbacks_total == 1


# -- certification (the chaos-mesh dry-run) --------------------------------


def test_broken_plan_certification_fires_slice_conservation():
    """The acceptance veto: a plan that moves slices WITHOUT bumping
    their epochs must fail certification with ``slice_conservation``
    violations, journal the veto, and back planning off."""
    rb, ha, _f, journal, now = _mk()
    pid = rb.propose()["plan"]["planId"]
    plan = rb.plans[pid]
    plan.proposed = plan.proposed._replace(
        slice_epoch=ha.shard_map.slice_epoch)  # the bug under test
    r = rb.certify(pid, campaign_seed=7, seconds=6, max_faults=2)
    assert not r["ok"]
    invs = {v["invariant"] for v in r["cert"]["violations"]}
    assert "slice_conservation" in invs
    assert rb.backoff_until_ms > now[0]
    assert rb.status()["frozenBy"] == FREEZE_BACKOFF
    certs = journal.tail(kind="rebalanceCertify")
    assert certs and certs[-1]["ok"] is False
    assert certs[-1]["causeSeq"] == plan.propose_seq


def test_certified_plan_applies_with_full_journal_chain():
    """Happy path end-to-end: certify passes, apply actuates under
    ``causing(applySeq)``, and the journal chain walks apply →
    certify → propose with ``actor="rebalancer"`` throughout."""
    rb, ha, _f, journal, _now = _mk()
    pid = rb.propose()["plan"]["planId"]
    c = rb.certify(pid, campaign_seed=7, seconds=6, max_faults=2)
    assert c["ok"], c
    a = rb.apply(pid)
    assert a["ok"], a
    _smap, cause = ha.applied[-1]
    assert cause == a["applySeq"]
    chain = journal.chain(a["applySeq"])
    kinds = [rec["kind"] for rec in chain]
    assert kinds[:3] == ["rebalanceApply", "rebalanceCertify",
                        "rebalancePropose"]
    assert all(rec["actor"] == "rebalancer" for rec in chain[:3])


@pytest.mark.slow
def test_certification_replays_bit_identically():
    """Same seed + same plan → identical verdict AND fault sha256s
    (the campaign's replay discipline applied to certification)."""
    rb, _ha, _f, _j, _now = _mk()
    pid = rb.propose()["plan"]["planId"]
    c1 = rb.certify(pid, campaign_seed=11)
    rb.backoff_until_ms = 0
    c2 = rb.certify(pid, campaign_seed=11)
    assert c1["cert"]["verdictSha256"] == c2["cert"]["verdictSha256"]
    assert c1["cert"]["faultSha256"] == c2["cert"]["faultSha256"]
    c3 = rb.certify(pid, campaign_seed=12)
    assert c3["cert"]["verdictSha256"] != c1["cert"]["verdictSha256"] \
        or c3["cert"]["faultSha256"] != c1["cert"]["faultSha256"]


# -- autoscaling -----------------------------------------------------------


def test_join_folds_new_seat_through_same_pipeline():
    """Leader-join autoscaling: the new seat enters the server set,
    receives at most the cap of (heaviest) slices, and the plan rides
    the same certify → apply pipeline as a skew plan."""
    rb, ha, _f, journal, _now = _mk()
    r = rb.plan_join("D", "127.0.0.1", 0)
    assert r["ok"], r
    pid = r["plan"]["planId"]
    plan = rb.plans[pid]
    from sentinel_tpu.core.config import config

    assert 0 < len(plan.moves) <= config.rebalance_max_slices_per_epoch()
    assert all(to == "D" for _sl, (_frm, to) in plan.moves.items())
    assert plan.proposed.server_for("D") is not None
    c = rb.certify(pid, campaign_seed=3, seconds=6, max_faults=2)
    assert c["ok"], c
    a = rb.apply(pid)
    assert a["ok"], a
    assert ha.shard_map.server_for("D") is not None
    assert set(ha.shard_map.slices_of("D")) == set(plan.moves)
    kinds = [rec["kind"] for rec in journal.chain(a["applySeq"])]
    assert kinds[:3] == ["rebalanceApply", "rebalanceCertify",
                        "rebalancePropose"]


def test_leave_drains_cap_slices_and_drops_empty_seat():
    rb, ha, _f, _j, _now = _mk(owner=["A", "A", "A", "B", "B", "B",
                                      "C", "C"])
    r = rb.plan_leave("C")
    assert r["ok"], r
    plan = rb.plans[r["plan"]["planId"]]
    assert set(plan.moves) == {6, 7}
    assert plan.proposed.server_for("C") is None
    assert "C" not in plan.proposed.slice_owner


# -- the scaled live drill -------------------------------------------------


@pytest.mark.slow
def test_live_three_leader_drill_one_certified_apply():
    """Scaled drill on REAL seats: a 3-leader in-process mesh
    (``ClusterHAManager`` each, real journals/checkpoints), traffic
    induced hot on leader A, the rebalancer senses the skew from the
    actually-served verdicts, and EXACTLY ONE certified, journal-
    chained map apply moves load off A — the chain reaching from seat
    A's ``shardMapApply`` back to ``rebalancePropose`` in one walk."""
    import os
    import shutil
    import tempfile

    from sentinel_tpu.chaos.invariants import History
    from sentinel_tpu.chaos.mesh import ChaosMesh
    from sentinel_tpu.core.config import config
    from sentinel_tpu.simulator.clock import SimClock

    workdir = tempfile.mkdtemp(prefix="sentinel-rebalance-drill-")
    clock = SimClock(config.chaos_epoch_ms())
    history = History()
    n = 8
    # Flows chosen deterministically: 5 hot flows on distinct slices
    # all owned by A, 2 cool ones elsewhere.
    flows = {}
    seen = set()
    fid = 9000
    while len(flows) < 7 and fid < 60_000:
        sl = slice_of(fid, n)
        if sl not in seen:
            flows[fid] = 9.0
            seen.add(sl)
        fid += 1
    mesh = ChaosMesh(clock, history, workdir, leaders=LEADERS, n_slices=n,
                     flows=flows)
    try:
        slots = sorted(seen)
        hot, cool = set(slots[:5]), set(slots[5:])
        assign = {"A": sorted(hot),
                  "B": sorted(cool),
                  "C": [sl for sl in range(n) if sl not in seen]}
        mesh.rebalance(assign, {sl: 2 for sl in range(n)}, version=2)
        # Shared control-plane journal: the rebalancer and seat A write
        # the SAME journal so the causal chain is walkable end to end.
        journal = ControlPlaneJournal(
            clock.now_ms, path=os.path.join(workdir, "journal-ctl.jsonl"))
        mesh.hosts["A"].journal = journal
        mesh.seats["A"].state.journal = journal
        hot_flows = sorted(f for f in flows if slice_of(f, n) in hot)
        cool_flows = sorted(f for f in flows if slice_of(f, n) in cool)
        for sec in range(6):
            for f in hot_flows:
                for _ in range(4):
                    mesh.request(f, sec)
            for f in cool_flows:
                mesh.request(f, sec)
            clock.advance(1000)

        class _MeshFleet:
            def settled_through_ms(self):
                return clock.now_ms() - 1000

            def status(self):
                return {"leaders": {m: {"stale": False,
                                        "epochRegressed": False}
                                    for m in LEADERS}}

            def slice_loads(self, flow_of, n_slices, window_seconds=None,
                            settled_only=True):
                loads = {}
                for ev in history.of("verdict"):
                    if ev["status"] in ("pass", "block"):
                        sl = slice_of(ev["flow"], n_slices)
                        loads[sl] = loads.get(sl, 0) + 1
                return {"nSlices": n_slices, "seconds": 6,
                        "settledThroughMs": self.settled_through_ms(),
                        "slices": loads, "observedByLeader": {},
                        "unattributed": 0}

        def apply_all(smap):
            for mid in mesh.leader_order:
                mesh.seats[mid].apply_map(smap)
            mesh.router.apply_map(smap)

        rb = ShardRebalancer(ha=mesh.seats["A"], fleet=_MeshFleet(),
                             journal=journal, flow_of=lambda r: None,
                             clock=clock.now_ms, apply_via=apply_all)
        skew0 = rb.sense()["skew"]
        r = rb.propose()
        assert r["ok"], r
        pid = r["plan"]["planId"]
        plan = rb.plans[pid]
        assert all(frm == "A" for _sl, (frm, _to) in plan.moves.items())
        c = rb.certify(pid, campaign_seed=5, seconds=6, max_faults=2)
        assert c["ok"], c
        a = rb.apply(pid)
        assert a["ok"], a
        # Exactly one apply, and seat A really adopted the map.
        applies = journal.tail(kind="rebalanceApply")
        assert len(applies) == 1
        assert mesh.seats["A"].shard_map.version == plan.proposed.version
        moved = set(plan.moves)
        assert moved and not (moved & set(
            mesh.seats["A"].shard_map.slices_of("A")))
        # The causal chain walks seat A's shardMapApply back through
        # the rebalancer's apply/certify/propose — one journal, one why.
        smap_recs = [rec for rec in journal.tail(kind="shardMapApply")
                     if rec.get("version") == plan.proposed.version]
        assert smap_recs, "seat A recorded no shardMapApply for the plan"
        kinds = [rec["kind"] for rec in journal.chain(smap_recs[-1]["seq"])]
        assert kinds[:4] == ["shardMapApply", "rebalanceApply",
                             "rebalanceCertify", "rebalancePropose"]
        assert rb.sense()["skew"] < skew0
    finally:
        mesh.stop()
        shutil.rmtree(workdir, ignore_errors=True)


def test_rebalance_command_surface():
    """The ops handler's param plumbing (status/freeze round-trip) on a
    live engine — the governed actions themselves are covered above."""
    import json

    from sentinel_tpu import get_engine
    from sentinel_tpu.transport.command_center import CommandRequest
    from sentinel_tpu.transport.handlers import cmd_rebalance

    eng = get_engine()
    r = cmd_rebalance(CommandRequest(parameters={"op": "status"},
                                     engine=eng))
    assert r.success
    st = json.loads(r.result)
    assert "counters" in st and "frozen" in st
    assert json.loads(cmd_rebalance(CommandRequest(
        parameters={"op": "freeze"}, engine=eng)).result)["frozen"] is True
    assert json.loads(cmd_rebalance(CommandRequest(
        parameters={"op": "unfreeze"}, engine=eng)).result)["frozen"] is False
    bad = cmd_rebalance(CommandRequest(parameters={"op": "nope"},
                                       engine=eng))
    assert not bad.success

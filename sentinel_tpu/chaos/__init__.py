"""Deterministic chaos campaign engine (ISSUE 15 — the FoundationDB
move applied to the sharded mesh).

The repo's safety story lives in docs/SEMANTICS.md as prose proofs and
in hand-written chaos tests that explore exactly the schedules their
authors imagined. This package closes the gap with a GENERATOR:

* :mod:`~sentinel_tpu.chaos.mesh` — a real in-process multi-leader
  sharded mesh (``ClusterHAManager`` seats with loopback reactors,
  real checkpoint/journal files, the real ``ShardedTokenClient`` walk)
  driven single-threaded on a program-advanced clock, so every episode
  is a pure function of its inputs.
* :mod:`~sentinel_tpu.chaos.scheduler` — ``FaultScheduler``: composes
  randomized fault schedules over the ``resilience/faults.py`` seams
  plus the mesh-level actions (crash, rebalance, link loss, clock
  skew); each schedule is a pure function of
  ``(campaign_seed, episode_index)``.
* :mod:`~sentinel_tpu.chaos.invariants` — the SEMANTICS.md bounds as
  executable checkers over an episode's recorded history.
* :mod:`~sentinel_tpu.chaos.shrink` — delta-debugging: a violating
  schedule is minimized to the smallest still-failing subset.
* :mod:`~sentinel_tpu.chaos.campaign` — ties it together; violations
  come back as forensic bundles joined with the seats' audit journals.
* :mod:`~sentinel_tpu.chaos.regressions` — known-fixed bugs a test can
  deliberately put back (the shrinker's proof-of-life).

This module stays import-light: the exporter reads :func:`counters`
on every scrape, and the ops command reads :func:`last_report`.
"""

from __future__ import annotations

import threading

_lock = threading.Lock()
_counters = {
    "episodes": 0,       # episodes completed in this process
    "violations": 0,     # invariant violations detected
    "faultsFired": 0,    # injector fires + mesh actions executed
    "shrinkSteps": 0,    # shrinker re-runs spent minimizing schedules
}
_last_report = None


def counters() -> dict:
    """Process-wide chaos counters (the ``sentinel_tpu_chaos_*``
    exporter families' source)."""
    with _lock:
        return dict(_counters)


def _count(**deltas) -> None:
    with _lock:
        for k, v in deltas.items():
            _counters[k] += int(v)


def last_report():
    """The newest campaign report run in this process (ops surface)."""
    return _last_report


def _set_last_report(report) -> None:
    global _last_report
    _last_report = report


def run_campaign(*args, **kwargs):
    """Convenience: :class:`~sentinel_tpu.chaos.campaign.ChaosCampaign`
    built and run in one call (the bench / ops-command entry point)."""
    from sentinel_tpu.chaos.campaign import ChaosCampaign

    return ChaosCampaign(*args, **kwargs).run()

package com.alibaba.csp.sentinel.node;

/** Vendored signature stub (see vendored/README.md). Reference:
 * core:node/DefaultNode.java — opaque to the bridge (it forwards stats
 * to the backend instead of mutating local nodes). */
public class DefaultNode {
}

"""Break down the fused entry_step's on-chip cost at bench shapes.

Times jitted sub-stages in isolation (same shapes as bench_throughput:
capacity 32768, batch 8192) so optimization targets the measured hot
spot, not a guess. Run on the real chip; scratch tool, not a test.
"""

import time

import numpy as np

import jax
import jax.numpy as jnp


def timeit(fn, *args, iters=10, **kw):
    out = fn(*args, **kw)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args, **kw)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters * 1e3  # ms


def main():
    from sentinel_tpu.core.batch import EntryBatch, make_entry_batch_np
    from sentinel_tpu.core.registry import NodeRegistry
    from sentinel_tpu.models import authority as A
    from sentinel_tpu.models import degrade as D
    from sentinel_tpu.models import flow as F
    from sentinel_tpu.models import param_flow as P
    from sentinel_tpu.models import system as Y
    from sentinel_tpu.ops import segment as seg
    from sentinel_tpu.ops import step as S
    from sentinel_tpu.ops import window as W

    n_resources, capacity, batch_n = 10_000, 32_768, 8192
    now0 = 1_700_000_000_000
    reg = NodeRegistry(capacity)
    rules = [F.FlowRule(resource=f"res{i}", count=1e9, control_behavior=0)
             for i in range(0, n_resources, 10)]
    degrade_rules = [D.DegradeRule(resource=f"res{i}", count=100,
                                   grade=i % 3, time_window=10)
                     for i in range(0, n_resources, 20)]
    param_rules = [P.ParamFlowRule(f"res{i}", param_idx=0, count=1e9)
                   for i in range(0, n_resources, 40)]
    ctx = "sentinel_default_context"
    ent_row = reg.entrance_row(ctx)
    c_rows = np.asarray([reg.cluster_row(f"res{i}")
                         for i in range(n_resources)])
    d_rows = np.asarray([reg.default_row(ctx, f"res{i}", ent_row)
                         for i in range(n_resources)])
    ft, _ = F.compile_flow_rules(rules, reg, capacity)
    dt, di = D.compile_degrade_rules(degrade_rules, reg, capacity)
    pt = P.compile_param_rules(param_rules, reg, capacity)
    pack = S.RulePack(flow=ft, degrade=dt,
                      authority=A.compile_authority_rules([], reg, capacity),
                      system=Y.compile_system_rules([Y.SystemRule(qps=1e12)]),
                      param=pt)
    state = S.make_state(capacity, ft.num_rules, now0,
                         degrade=D.make_degrade_state(dt, di),
                         param=P.make_param_state(pt.num_rules))

    rng = np.random.default_rng(0)
    buf = make_entry_batch_np(batch_n)
    pick = rng.integers(0, n_resources, size=batch_n)
    buf["cluster_row"][:] = c_rows[pick]
    buf["dn_row"][:] = d_rows[pick]
    buf["count"][:] = 1
    buf["param_hash"][:, 0] = rng.integers(1, 1 << 31, size=batch_n)
    buf["param_present"][:, 0] = True
    batch = EntryBatch(**{k: jnp.asarray(v) for k, v in buf.items()})
    now = jnp.asarray(now0, jnp.int64)

    print(f"platform: {jax.devices()[0].platform}")

    # Full step (no donation, so reusable).
    full = jax.jit(lambda st_, b, t: S.entry_step(st_, pack, b, t))
    print(f"full entry_step:        {timeit(full, state, batch, now):8.3f} ms")

    # Stage: window rotate only.
    rot = jax.jit(lambda w, t: W.rotate(w, t, S.SPEC_1S))
    print(f"  w1 rotate:            {timeit(rot, state.w1, now):8.3f} ms")

    # Stage: the 4-row commit bincount.
    rows4 = jnp.stack([batch.dn_row, batch.cluster_row, batch.origin_row,
                       jnp.full_like(batch.cluster_row, -1)], axis=1)

    def commit(r4):
        pass4 = jnp.ones(r4.shape, jnp.int32)
        return seg.bincount_matmul(r4.reshape(-1),
                                   jnp.stack([pass4.reshape(-1)] * 3, axis=1),
                                   capacity)

    cj = jax.jit(commit)
    print(f"  bincount commit:      {timeit(cj, rows4):8.3f} ms")

    # Stage: flow check only.
    fj = jax.jit(lambda st_, b, t: F.check_flow(
        ft, st_.flow, st_.w1, st_.cur_threads, b, t,
        jnp.zeros((batch_n,), bool), occupied_next=st_.occupied_next))
    print(f"  flow check:           {timeit(fj, state, batch, now):8.3f} ms")

    # Stage: degrade check.
    dj = jax.jit(lambda st_, b, t: D.check_degrade(
        dt, st_.degrade, b, t, jnp.ones((batch_n,), bool)))
    print(f"  degrade check:        {timeit(dj, state, batch, now):8.3f} ms")

    # Stage: param check.
    pj = jax.jit(lambda st_, b, t: P.check_param_flow(
        pt, st_.param, b, t, jnp.ones((batch_n,), bool)))
    print(f"  param check:          {timeit(pj, state, batch, now):8.3f} ms")

    # Stage: system check.
    yj = jax.jit(lambda st_, b, t: Y.check_system(
        pack.system, st_.sys_signals, st_.w1, st_.w60, st_.sec.counts,
        st_.cur_threads, b, jnp.ones((batch_n,), bool), t))
    print(f"  system check:         {timeit(yj, state, batch, now):8.3f} ms")

    # Dense prefix at batch width (inside flow for ruled rows).
    ids = batch.cluster_row
    vals = jnp.ones((batch_n,), jnp.float32)
    sj = jax.jit(lambda i, v: seg.segmented_prefix_dense(i, v))
    print(f"  segmented prefix:     {timeit(sj, ids, vals):8.3f} ms")

    # Cost analysis of the full step.
    lowered = jax.jit(
        lambda st_, b, t: S.entry_step(st_, pack, b, t)
    ).lower(state, batch, now).compile()
    ca = lowered.cost_analysis()
    if isinstance(ca, list):
        ca = ca[0]
    if ca:
        print("cost_analysis: flops=%.3g bytes=%.3g" % (
            ca.get("flops", -1), ca.get("bytes accessed", -1)))


if __name__ == "__main__":
    main()

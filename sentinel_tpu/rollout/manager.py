"""Staged-rollout orchestration: shadow → canary → promote/abort.

No reference twin — the reference pushes a rule edit straight from
datasource to enforcement. This manager closes that gap with three
stages per named candidate ruleset:

  * **shadow** — the candidate is compiled beside the live pack and
    evaluated in extra non-enforcing lanes of the fused step
    (``ops/step.py``); would-pass/would-block counts accumulate per
    resource and per family with zero effect on verdicts.
  * **canary** — a deterministic hash of each request's (origin,
    context) key (``rollout/canary.py``) selects a stable
    ``canary_bps``/10000 slice of traffic that the candidate verdict
    ENFORCES; everyone else stays on the live rules. Shadow counting
    continues for all lanes, so the guardrail keeps comparing worlds.
  * **promote / abort** — promote merges the candidate into the live
    rule managers through the existing ``load_rules`` property path
    (one atomic swap at the next compile: the same §3.2 wholesale-push
    semantics every datasource uses) and bumps the promotion epoch;
    abort tears the shadow world down and keeps the live rules.

Guardrail: every :meth:`tick` (ops-plane cadence, typically 1 Hz or the
dashboard's fetch loop) diffs the cumulative shadow counters against
the previous tick and compares the candidate's block rate to the live
one. ``abort_windows`` consecutive windows with
``shadow_rate − live_rate > max_block_delta`` auto-abort the rollout —
a bad candidate can never graduate past the blast radius of its canary
slice.

Merging semantics (documented in docs/OPERATIONS.md): a candidate set
overrides the live ruleset per RESOURCE for the families it touches —
live rules on resources the candidate does not mention stay in force —
except system rules (resource-less), which replace wholesale when the
candidate carries any. The shadow pack compiles from this MERGED view,
so shadow counts answer exactly "what would the world after promote
have done".

Concurrency: all mutation runs under the engine's config lock (the
rule-push plane); the manager never takes the engine's dispatch lock
itself, so staging a rollout cannot stall admissions behind a compile.
"""

from __future__ import annotations

import zlib
from collections import deque
from dataclasses import dataclass, field, replace as dc_replace
from typing import Dict, List, Optional

from sentinel_tpu.datasource import converters as CV
from sentinel_tpu.ops import step as S
from sentinel_tpu.rollout.canary import CANARY_BPS_MAX

STAGE_SHADOW = "shadow"
STAGE_CANARY = "canary"
STAGE_PROMOTED = "promoted"
STAGE_ABORTED = "aborted"
ACTIVE_STAGES = (STAGE_SHADOW, STAGE_CANARY)

FAMILIES = ("flow", "degrade", "authority", "system", "param")

# family -> (engine manager attribute, dict-parser)
_FAMILY_BIND = {
    "flow": ("flow_rules", CV.flow_rule_from_dict),
    "degrade": ("degrade_rules", CV.degrade_rule_from_dict),
    "authority": ("authority_rules", CV.authority_rule_from_dict),
    "system": ("system_rules", CV.system_rule_from_dict),
    "param": ("param_rules", CV.param_rule_from_dict),
}
# Wire aliases accepted in rollout payloads (the command plane's
# ``paramFlow`` naming vs the model package's ``param``).
_FAMILY_ALIAS = {"paramFlow": "param"}

DEFAULT_MAX_BLOCK_DELTA = 0.05   # candidate may block ≤ 5pp more than live
DEFAULT_ABORT_WINDOWS = 3        # consecutive breached ticks before abort
DEFAULT_MIN_WINDOW_ENTRIES = 64  # ticks with less traffic don't vote
DEFAULT_CANARY_BPS = 100         # 1% of traffic when unspecified


def _salt_for(name: str) -> int:
    """Stable per-candidate canary salt: different candidates sample
    different traffic slices, reruns of one candidate sample the same."""
    return zlib.crc32(name.encode("utf-8")) & 0x7FFFFFFF


@dataclass
class CandidateSet:
    """One named candidate ruleset moving through the rollout stages."""

    name: str
    stage: str = STAGE_SHADOW
    rules: Dict[str, list] = field(default_factory=dict)  # family -> rules
    canary_bps: int = 0
    source: str = "ops"  # "ops" (rollout command) | "datasource" (tagged)
    created_ms: int = 0
    stage_since_ms: int = 0
    ended_reason: Optional[str] = None
    # For datasource-tagged candidates: the stage the source's
    # ``rolloutStage`` tags last requested. Re-publishes with unchanged
    # tags must not clobber an ops-side escalation (see refresh_staged).
    source_stage: Optional[str] = None
    # Audit-journal seq of this candidate's staging record (ISSUE 14):
    # later transitions (stage flips, promote, abort) carry it as their
    # causeSeq, so the journal shows one linked lifecycle per candidate.
    journal_seq: Optional[int] = None

    def families(self) -> List[str]:
        return [f for f in FAMILIES if self.rules.get(f)]


class RolloutManager:
    """Owns candidate sets + the rollout guardrail for one engine."""

    def __init__(self, engine):
        from sentinel_tpu.core.config import config as _cfg

        self.engine = engine
        self._sets: Dict[str, CandidateSet] = {}
        self._active: Optional[str] = None
        self.promotion_epoch = 0
        self.max_block_delta = self._cfg_float(
            _cfg, "csp.sentinel.rollout.max.block.delta",
            DEFAULT_MAX_BLOCK_DELTA)
        self.abort_windows = _cfg.get_int(
            "csp.sentinel.rollout.abort.windows", DEFAULT_ABORT_WINDOWS)
        self.min_window_entries = _cfg.get_int(
            "csp.sentinel.rollout.min.window.entries",
            DEFAULT_MIN_WINDOW_ENTRIES)
        self._breach_streak = 0
        self._last_sample = None  # np.int64[NUM_SHADOW_COUNTERS] totals
        self._history: deque = deque(maxlen=60)
        # Lifecycle listeners: fn(event, candidate, reason) fired on
        # every promote ("promoted") and abort ("aborted") — the
        # adaptive loop's channel for endings it didn't drive itself.
        # Fired under the engine config lock: listeners must be
        # lock-light and NEVER call back into this manager.
        self._listeners: List = []

    @staticmethod
    def _cfg_float(cfg, key: str, default: float) -> float:
        v = cfg.get(key)
        try:
            return float(v) if v is not None else default
        except ValueError:
            return default

    # -- introspection -----------------------------------------------------

    @property
    def active_name(self) -> Optional[str]:
        return self._active

    def active_set(self) -> Optional[CandidateSet]:
        return self._sets.get(self._active) if self._active else None

    def candidate(self, name: Optional[str]) -> Optional[CandidateSet]:
        """Any known candidate set by name, active or ended (the
        adaptive loop reads ended stages/reasons through this)."""
        return self._sets.get(name) if name else None

    def add_lifecycle_listener(self, fn) -> None:
        self._listeners.append(fn)

    def _fire(self, event: str, cand: CandidateSet,
              reason: Optional[str]) -> None:
        for fn in self._listeners:
            try:
                fn(event, cand, reason)
            except Exception as ex:  # noqa: BLE001 — a buggy listener
                # must not break promote/abort (the rule plane).
                from sentinel_tpu.log.record_log import record_log

                record_log.warn("rollout lifecycle listener failed: %r", ex)

    def device_active(self) -> bool:
        """True while a candidate is installed on device (shadow/canary) —
        the lease fast path stands down so every entry reaches the step
        the shadow lanes ride (core/lease.py gating)."""
        cand = self.active_set()
        return cand is not None and cand.stage in ACTIVE_STAGES

    def canary_config(self):
        """(canary_bps | None, salt) for the engine's dispatch plumbing."""
        cand = self.active_set()
        if cand is None or cand.stage != STAGE_CANARY:
            return None, 0
        return cand.canary_bps, _salt_for(cand.name)

    # -- candidate lifecycle (all under the engine config lock) ------------

    def _lock(self):
        return self.engine._config_lock

    def load_candidate(self, name: str, rules, stage: str = STAGE_SHADOW,
                       canary_bps: Optional[int] = None,
                       source: str = "ops") -> CandidateSet:
        """Register (or replace) a candidate set and install its shadow.

        ``rules``: {family: [rule dicts or rule objects]} — family keys
        accept the command plane's aliases (``paramFlow``). Only one
        candidate may hold the device at a time: staging a second while
        another is in shadow/canary raises (promote or abort first).
        """
        if stage not in ACTIVE_STAGES:
            raise ValueError(f"initial stage must be one of {ACTIVE_STAGES}")
        parsed = self._parse_rules(rules)
        if not any(parsed.values()):
            raise ValueError("candidate set carries no valid rules")
        with self._lock():
            cur = self.active_set()
            if cur is not None and cur.stage in ACTIVE_STAGES \
                    and cur.name != name:
                raise ValueError(
                    f"candidate {cur.name!r} is already {cur.stage}; "
                    "promote or abort it first")
            now = self.engine.now_ms()
            cand = CandidateSet(
                name=name, stage=stage, rules=parsed, source=source,
                created_ms=now, stage_since_ms=now,
                canary_bps=self._clamp_bps(
                    canary_bps if canary_bps is not None
                    else (DEFAULT_CANARY_BPS if stage == STAGE_CANARY else 0)))
            self._sets[name] = cand
            self._active = name
            self._reset_guardrail()
            self._notify()
            j = getattr(self.engine, "journal", None)
            if j is not None:
                cand.journal_seq = j.record(
                    "rolloutStage", name=name, stage=stage, source=source,
                    canaryBps=cand.canary_bps,
                    families={f: len(cand.rules[f])
                              for f in cand.families()})
            return cand

    @staticmethod
    def _clamp_bps(bps) -> int:
        # CANARY_BPS_MAX is the hash bucket modulus (canary.py): clamping
        # to the same constant keeps every clamped value selectable.
        return max(0, min(CANARY_BPS_MAX, int(bps)))

    def _parse_rules(self, rules) -> Dict[str, list]:
        out: Dict[str, list] = {}
        for fam_raw, items in (rules or {}).items():
            fam = _FAMILY_ALIAS.get(fam_raw, fam_raw)
            if fam not in _FAMILY_BIND:
                raise ValueError(f"unknown rule family {fam_raw!r}")
            _, from_dict = _FAMILY_BIND[fam]
            parsed = [from_dict(r) if isinstance(r, dict) else r
                      for r in (items or [])]
            out[fam] = [r for r in parsed if r.is_valid()]
        return out

    def set_stage(self, name: str, stage: str,
                  canary_bps: Optional[int] = None) -> CandidateSet:
        """shadow ↔ canary transitions (+ canary percentage tuning)."""
        if stage not in ACTIVE_STAGES:
            raise ValueError(
                f"set_stage handles {ACTIVE_STAGES}; use promote()/abort()")
        with self._lock():
            cand = self._require_active(name)
            cand.stage = stage
            cand.stage_since_ms = self.engine.now_ms()
            if stage == STAGE_CANARY:
                cand.canary_bps = self._clamp_bps(
                    canary_bps if canary_bps is not None
                    else (cand.canary_bps or DEFAULT_CANARY_BPS))
            # Stage flips tune the traced canary scalars only — the
            # shadow world (counters, controller state) carries over.
            self.engine._set_canary(*self.canary_config())
            j = getattr(self.engine, "journal", None)
            if j is not None:
                j.record("rolloutStage", name=cand.name, stage=stage,
                         canaryBps=cand.canary_bps,
                         cause_seq=cand.journal_seq)
            return cand

    def promote(self, name: str) -> Dict:
        """Atomic swap into the live rule tensors: for every family the
        candidate touches, load the MERGED ruleset through the family
        manager (the same property path datasources push through), then
        tear the shadow world down."""
        import contextlib as _ctxlib

        from sentinel_tpu.telemetry import journal as journal_mod

        with self._lock():
            cand = self._require_active(name)
            # The promote record lands BEFORE the rule loads it fires,
            # and the loads run under causing(seq): the resulting
            # ruleLoad records carry causeSeq -> this promote — the
            # causality the why-query's chain walk follows back through
            # the candidate's staging record.
            j = getattr(self.engine, "journal", None)
            jseq = j.record("rolloutPromote", name=cand.name,
                            cause_seq=cand.journal_seq) if j else None
            loaded = {}
            with (journal_mod.causing(jseq) if j is not None
                  else _ctxlib.nullcontext()):
                for fam in cand.families():
                    merged = self.merged_rules(fam, cand)
                    detagged = [self._detag(r) for r in merged]
                    attr, _ = _FAMILY_BIND[fam]
                    getattr(self.engine, attr).load_rules(detagged)
                    loaded[fam] = len(detagged)
            cand.stage = STAGE_PROMOTED
            cand.stage_since_ms = self.engine.now_ms()
            cand.ended_reason = "promoted"
            self._active = None
            self.promotion_epoch += 1
            self._reset_guardrail()
            self._notify()
            self._fire("promoted", cand, None)
            return {"promoted": name, "epoch": self.promotion_epoch,
                    "rulesLoaded": loaded}

    def abort(self, name: Optional[str] = None,
              reason: str = "manual") -> Dict:
        """Tear the candidate down; live rules were never touched."""
        with self._lock():
            cand = self._require_active(name)
            cand.stage = STAGE_ABORTED
            cand.stage_since_ms = self.engine.now_ms()
            cand.ended_reason = reason
            self._active = None
            self._reset_guardrail()
            self._notify()
            j = getattr(self.engine, "journal", None)
            if j is not None:
                j.record("rolloutAbort", name=cand.name, reason=reason,
                         cause_seq=cand.journal_seq)
            self._fire("aborted", cand, reason)
            return {"aborted": cand.name, "reason": reason}

    def _require_active(self, name: Optional[str]) -> CandidateSet:
        cand = self.active_set()
        if cand is None:
            raise ValueError("no active candidate set")
        if name is not None and name != cand.name:
            raise ValueError(
                f"candidate {name!r} is not active ({cand.name!r} is)")
        return cand

    @staticmethod
    def _detag(rule):
        if getattr(rule, "candidate_set", None) or \
                getattr(rule, "rollout_stage", None):
            return dc_replace(rule, candidate_set=None, rollout_stage=None)
        return rule

    def _reset_guardrail(self) -> None:
        self._breach_streak = 0
        self._last_sample = None
        self._history.clear()

    def _notify(self) -> None:
        """Mark the device-side rollout artifacts dirty (compiled shadow
        pack + shadow state + lease gating). Caller holds the config lock."""
        eng = self.engine
        eng._dirty["rollout"] = True
        eng._set_canary(*self.canary_config())
        eng._rebuild_leases()

    # -- staged sources (datasource-tagged rules) --------------------------

    def refresh_staged(self) -> None:
        """Adopt rules that arrived through the normal datasource path
        carrying a ``candidateSet`` tag (core/rule_manager.py splits them
        out of the live partition). Called from the engine's rule-change
        listeners, under the config lock.

        One datasource-defined set becomes/updates the active candidate
        only when no OTHER candidate holds the device (first writer
        wins); its initial stage honors the rules' ``rolloutStage``.
        """
        staged: Dict[str, Dict[str, list]] = {}
        for fam, (attr, _) in _FAMILY_BIND.items():
            mgr = getattr(self.engine, attr)
            get_staged = getattr(mgr, "get_staged", None)
            if get_staged is None:
                continue
            for set_name, rules in get_staged().items():
                staged.setdefault(set_name, {})[fam] = rules
        cand = self.active_set()
        if cand is not None and cand.source == "datasource" \
                and cand.name not in staged:
            # The source dropped the tagged rules: the candidate is gone.
            self.abort(cand.name, reason="staged rules removed at source")
            cand = None
        for set_name, fam_rules in staged.items():
            if cand is None or cand.name == set_name:
                stage = STAGE_SHADOW
                for rules in fam_rules.values():
                    for r in rules:
                        rs = getattr(r, "rollout_stage", None)
                        if rs in ACTIVE_STAGES:
                            stage = rs
                if cand is not None:
                    cand.rules = {f: list(rs) for f, rs in fam_rules.items()}
                    # The tag-derived stage applies only when the SOURCE
                    # changed it since the last refresh: a re-publish with
                    # unchanged tags (or any unrelated rule push firing
                    # this listener) must not demote an ops-escalated
                    # canary back to the tags' stage.
                    if stage != cand.source_stage:
                        cand.source_stage = stage
                        if stage != cand.stage:
                            # set_stage: canary flips pick up the default
                            # slice when the bps was never configured.
                            self.set_stage(cand.name, stage)
                else:
                    adopted = self.load_candidate(
                        set_name, fam_rules, stage=stage,
                        source="datasource")
                    adopted.source_stage = stage
                break  # only one candidate may hold the device

    # -- merged view / device spec -----------------------------------------

    def merged_rules(self, family: str,
                     cand: Optional[CandidateSet] = None) -> list:
        """Live rules with the candidate's per-resource overrides applied
        — the ruleset the world would run after promote."""
        if cand is None:
            cand = self.active_set()
        attr, _ = _FAMILY_BIND[family]
        live = getattr(self.engine, attr).get_rules()
        crules = list((cand.rules if cand else {}).get(family, ()))
        if not crules:
            return live
        if family == "system":
            return crules  # resource-less: wholesale replacement
        covered = {r.resource for r in crules}
        return [r for r in live if r.resource not in covered] + crules

    def device_spec(self) -> Optional[Dict[str, list]]:
        """{family: merged rules} for the shadow pack compile, or None
        when nothing should be on device."""
        cand = self.active_set()
        if cand is None or cand.stage not in ACTIVE_STAGES:
            return None
        return {fam: self.merged_rules(fam, cand) for fam in FAMILIES}

    # -- guardrail ----------------------------------------------------------

    def tick(self, now_ms: Optional[int] = None) -> Dict:
        """One guardrail window: diff cumulative shadow counters against
        the previous tick, compare block rates, auto-abort on a streak.

        Drive it from any ops-plane cadence (the ``rollout`` command's
        ``op=tick``, a dashboard fetch loop, or a cron); tests call it
        directly with a pinned clock. Idempotence is per-call: each call
        IS one window.
        """
        now = now_ms if now_ms is not None else self.engine.now_ms()
        cand = self.active_set()
        if cand is None or cand.stage not in ACTIVE_STAGES:
            return {"active": None}
        # SLO breach gate (sentinel_tpu/slo/): an active PAGE-severity
        # burn alert on a resource the candidate touches aborts
        # IMMEDIATELY — no streak. The block-rate-delta guardrail below
        # compares candidate vs live on the same traffic; this one
        # catches the live world burning its error budget WHILE a canary
        # is enforcing (whatever the cause, a rollout must not ride
        # through a page). Opt out via csp.sentinel.slo.rollout.abort.
        slo = getattr(self.engine, "slo", None)
        if slo is not None and slo.rollout_abort_enabled:
            # Judgement only advances on reads (the spill ride) — a tick
            # driven from a cron with no scraper attached must refresh
            # itself, or a live page never transitions to active (and a
            # long-resolved one never transitions out).
            self.engine.slo_refresh(now_ms=now)
            touched = {r.resource for fam, rules in cand.rules.items()
                       if fam != "system" for r in rules}
            breaches = slo.abort_signal(touched or None)
            if breaches:
                worst = breaches[0]
                reason = (f"slo: {worst['objective']} burning at "
                          f"{worst['burnLong']}x over "
                          f"{worst['windowLongS']}s")
                if len(breaches) > 1:
                    reason += f" (+{len(breaches) - 1} more)"
                self.abort(cand.name, reason=reason)
                return {"active": cand.name, "stage": cand.stage,
                        "status": "aborted", "timestamp": now,
                        "sloBreaches": breaches}
        counts = self.engine.shadow_counts()
        if counts is None:
            return {"active": cand.name, "status": "no-device-state"}
        totals = counts.sum(axis=1)
        last, self._last_sample = self._last_sample, totals
        if last is None or bool((totals < last).any()):
            # First window after install, or the counters were reset
            # under us (rule push re-created the shadow world): baseline.
            return {"active": cand.name, "status": "baseline"}
        delta = totals - last
        live_total = int(delta[S.SH_LIVE_PASS] + delta[S.SH_LIVE_BLOCK])
        shadow_total = int(delta[S.SH_WOULD_PASS] + delta[S.SH_WOULD_BLOCK])
        if live_total < self.min_window_entries:
            return {"active": cand.name, "status": "idle",
                    "entries": live_total}
        # max(..., 1): min_window_entries may legitimately be configured
        # to 0, and an idle window must read as rate 0, not divide by it.
        live_rate = float(delta[S.SH_LIVE_BLOCK]) / max(live_total, 1)
        shadow_rate = float(delta[S.SH_WOULD_BLOCK]) / max(shadow_total, 1)
        block_delta = shadow_rate - live_rate
        breach = block_delta > self.max_block_delta
        self._breach_streak = self._breach_streak + 1 if breach else 0
        out = {
            "active": cand.name, "stage": cand.stage, "status": "ok",
            "timestamp": now, "entries": live_total,
            "liveBlockRate": round(live_rate, 6),
            "shadowBlockRate": round(shadow_rate, 6),
            "blockRateDelta": round(block_delta, 6),
            "breach": breach,
            "breachStreak": self._breach_streak,
            "windowsToAbort": max(0, self.abort_windows - self._breach_streak),
        }
        self._history.append(out)
        if breach and self._breach_streak >= self.abort_windows:
            self.abort(cand.name, reason=(
                f"guardrail: block-rate delta {block_delta:.4f} > "
                f"{self.max_block_delta} for {self._breach_streak} windows"))
            out["status"] = "aborted"
        return out

    # -- ops snapshots -------------------------------------------------------

    def guardrail_state(self) -> Dict:
        """Compact slice for ``resilience_stats()`` — one unified
        degradation picture beside the breaker/fallback channels."""
        cand = self.active_set()
        return {
            "activeCandidateSet": cand.name if cand else None,
            "stage": cand.stage if cand else None,
            "canaryBps": cand.canary_bps if cand else 0,
            "breachStreak": self._breach_streak,
            "windowsToAbort": (max(0, self.abort_windows - self._breach_streak)
                               if cand else None),
            "maxBlockRateDelta": self.max_block_delta,
            "promotionEpoch": self.promotion_epoch,
        }

    def snapshot(self) -> Dict:
        cand = self.active_set()
        return {
            "active": cand.name if cand else None,
            "stage": cand.stage if cand else None,
            "canaryBps": cand.canary_bps if cand else 0,
            "promotionEpoch": self.promotion_epoch,
            "guardrail": {
                "maxBlockRateDelta": self.max_block_delta,
                "abortWindows": self.abort_windows,
                "minWindowEntries": self.min_window_entries,
                "breachStreak": self._breach_streak,
                "history": list(self._history)[-10:],
            },
            "sets": {
                name: {
                    "stage": c.stage,
                    "families": {f: len(c.rules.get(f, ()))
                                 for f in c.families()},
                    "canaryBps": c.canary_bps,
                    "source": c.source,
                    "createdMs": c.created_ms,
                    "stageSinceMs": c.stage_since_ms,
                    "endedReason": c.ended_reason,
                }
                for name, c in self._sets.items()
            },
        }

    def diff(self) -> Dict:
        """Per-resource shadow-vs-live outcome deltas (dashboard view)."""
        counts = self.engine.shadow_counts()
        cand = self.active_set()
        if counts is None or cand is None:
            return {"active": cand.name if cand else None, "resources": {}}
        rows = self.engine._device_resources()
        out = {}
        for res, row in rows.items():
            c = counts[:, row]
            live_total = int(c[S.SH_LIVE_PASS] + c[S.SH_LIVE_BLOCK])
            shadow_total = int(c[S.SH_WOULD_PASS] + c[S.SH_WOULD_BLOCK])
            if live_total == 0 and shadow_total == 0:
                continue
            out[res] = {
                "wouldPass": int(c[S.SH_WOULD_PASS]),
                "wouldBlock": int(c[S.SH_WOULD_BLOCK]),
                "livePass": int(c[S.SH_LIVE_PASS]),
                "liveBlock": int(c[S.SH_LIVE_BLOCK]),
                "wouldBlockByFamily": {
                    "authority": int(c[S.SH_WB_AUTHORITY]),
                    "system": int(c[S.SH_WB_SYSTEM]),
                    "paramFlow": int(c[S.SH_WB_PARAM]),
                    "flow": int(c[S.SH_WB_FLOW]),
                    "degrade": int(c[S.SH_WB_DEGRADE]),
                },
                "blockRateDelta": round(
                    (int(c[S.SH_WOULD_BLOCK]) / max(shadow_total, 1))
                    - (int(c[S.SH_LIVE_BLOCK]) / max(live_total, 1)), 6),
            }
        return {"active": cand.name, "stage": cand.stage, "resources": out}

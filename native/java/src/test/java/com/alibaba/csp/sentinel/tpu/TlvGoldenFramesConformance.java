package com.alibaba.csp.sentinel.tpu;

import java.io.ByteArrayOutputStream;
import java.io.InputStream;
import java.io.OutputStream;
import java.net.ServerSocket;
import java.net.Socket;
import java.nio.charset.StandardCharsets;
import java.nio.file.Files;
import java.nio.file.Paths;
import java.util.ArrayList;
import java.util.HashMap;
import java.util.List;
import java.util.Map;
import java.util.regex.Matcher;
import java.util.regex.Pattern;

/**
 * Wire-format conformance for the Java bridge against the repo's golden
 * TLV frames ({@code tests/fixtures/tlv/fixtures.json}) — the same bytes
 * the Python codec and the C shim are pinned to in
 * {@code tests/test_tlv_fixtures.py}. Run it the day a JVM is available
 * (see {@code native/java/BUILD.md}, "Wire-format conformance"):
 *
 * <pre>
 *   java -cp out:jna-5.x.jar:sentinel-core-1.8.x.jar \
 *        -Djna.library.path=native \
 *        com.alibaba.csp.sentinel.tpu.TlvGoldenFramesConformance \
 *        tests/fixtures/tlv/fixtures.json
 * </pre>
 *
 * <p>No JUnit / JSON-library dependency on purpose: the fixture file is
 * repo-controlled, so a two-field regex extraction is sufficient and
 * keeps this runnable with nothing but the bridge's own classpath.
 * Exit code 0 = every frame matched byte-for-byte and every scripted
 * status surfaced through {@code requestToken}.
 *
 * <p>PROVENANCE: written without a JVM in the build sandbox — never
 * compiled here; validate signatures against the fork before use.
 */
public final class TlvGoldenFramesConformance {

    public static void main(String[] args) throws Exception {
        String path = args.length > 0 ? args[0]
                : "tests/fixtures/tlv/fixtures.json";
        Map<String, byte[]> fx = loadFixtures(path);

        CaptureServer server = new CaptureServer(new byte[][] {
                fx.get("ping_response_ok"),
                fx.get("flow_response_should_wait_350ms"),
                withXid(fx.get("param_response_blocked"), 3),
        });

        // The bridge reads its server from ClusterClientConfigManager
        // (the dashboard's cluster-assign flow); point it at the capture
        // server. Signature per documented 1.8 SPI — re-verify on first
        // compile, like the rest of the bridge.
        com.alibaba.csp.sentinel.cluster.client.config.ClusterClientConfigManager
                .applyNewAssignConfig(
                        new com.alibaba.csp.sentinel.cluster.client.config
                                .ClusterClientAssignConfig(
                                        "127.0.0.1", server.port()));
        TpuClusterTokenClient client = new TpuClusterTokenClient();
        client.start();
        com.alibaba.csp.sentinel.cluster.TokenResult r1 =
                client.requestToken(4242L, 1, false);
        expect(r1.getStatus() == 2 /* SHOULD_WAIT */,
                "flow status SHOULD_WAIT, got " + r1.getStatus());
        expect(r1.getWaitInMs() == 350,
                "waitInMs 350, got " + r1.getWaitInMs());
        Object[] params = new Object[] {7L, "user-1", Boolean.TRUE, 2.5d};
        com.alibaba.csp.sentinel.cluster.TokenResult r2 =
                client.requestParamToken(7100L, 1,
                        java.util.Arrays.asList(params));
        expect(r2.getStatus() == 1 /* BLOCKED */,
                "param status BLOCKED, got " + r2.getStatus());
        client.stop();
        server.join();

        // Frames the bridge actually emitted must BE the golden ones.
        List<byte[]> got = server.frames();
        expect(got.size() == 3, "expected 3 frames, got " + got.size());
        expectBytes(got.get(0), body(fx.get("ping_request_default")),
                "PING-on-connect frame");
        expectBytes(got.get(1), body(fx.get("flow_request_basic")),
                "FLOW acquire frame");
        byte[] goldenParam = body(fx.get("param_request_every_type"));
        goldenParam[3] = 3; // xid 2 -> 3: third request on the connection
        expectBytes(got.get(2), goldenParam, "PARAM_FLOW acquire frame");

        System.out.println("TLV conformance OK: 3 frames byte-identical, "
                + "2 scripted statuses surfaced");
    }

    // -- fixture plumbing ---------------------------------------------------

    private static Map<String, byte[]> loadFixtures(String path)
            throws Exception {
        String json = new String(Files.readAllBytes(Paths.get(path)),
                StandardCharsets.UTF_8);
        Map<String, byte[]> out = new HashMap<>();
        Pattern p = Pattern.compile(
                "\"name\":\\s*\"([^\"]+)\"[^}]*?\"hex\":\\s*\"([0-9a-f]+)\"",
                Pattern.DOTALL);
        Matcher m = p.matcher(json);
        while (m.find()) {
            out.put(m.group(1), unhex(m.group(2)));
        }
        if (out.isEmpty()) {
            throw new IllegalStateException("no fixtures parsed from " + path);
        }
        return out;
    }

    private static byte[] unhex(String hex) {
        byte[] out = new byte[hex.length() / 2];
        for (int i = 0; i < out.length; i++) {
            out[i] = (byte) Integer.parseInt(
                    hex.substring(2 * i, 2 * i + 2), 16);
        }
        return out;
    }

    /** Strip the u16 length prefix: compare bodies like the Python test. */
    private static byte[] body(byte[] frame) {
        byte[] out = new byte[frame.length - 2];
        System.arraycopy(frame, 2, out, 0, out.length);
        return out;
    }

    /** Patch the xid's low byte inside a full frame (offset 2+3). */
    private static byte[] withXid(byte[] frame, int xid) {
        byte[] out = frame.clone();
        out[5] = (byte) xid;
        return out;
    }

    private static void expect(boolean ok, String what) {
        if (!ok) {
            throw new AssertionError("conformance failure: " + what);
        }
    }

    private static void expectBytes(byte[] got, byte[] want, String what) {
        if (!java.util.Arrays.equals(got, want)) {
            throw new AssertionError("conformance failure: " + what
                    + "\n  got  " + hex(got) + "\n  want " + hex(want));
        }
    }

    private static String hex(byte[] b) {
        StringBuilder sb = new StringBuilder();
        for (byte x : b) {
            sb.append(String.format("%02x", x));
        }
        return sb.toString();
    }

    /**
     * Raw TCP capture server: records each length-framed request body the
     * bridge sends and replies with the scripted golden frame — the Java
     * twin of {@code tests/test_tlv_fixtures.py}'s {@code _CaptureServer}.
     */
    private static final class CaptureServer {
        private final ServerSocket listener;
        private final byte[][] script;
        private final List<byte[]> frames = new ArrayList<>();
        private final Thread thread;

        CaptureServer(byte[][] script) throws Exception {
            this.script = script;
            this.listener = new ServerSocket(0);
            this.thread = new Thread(this::run, "tlv-capture");
            this.thread.setDaemon(true);
            this.thread.start();
        }

        int port() {
            return listener.getLocalPort();
        }

        List<byte[]> frames() {
            return frames;
        }

        void join() throws InterruptedException {
            thread.join(5000);
        }

        private void run() {
            try (Socket conn = listener.accept()) {
                InputStream in = conn.getInputStream();
                OutputStream os = conn.getOutputStream();
                ByteArrayOutputStream buf = new ByteArrayOutputStream();
                int served = 0;
                byte[] chunk = new byte[4096];
                while (served < script.length) {
                    int n = in.read(chunk);
                    if (n < 0) {
                        return;
                    }
                    buf.write(chunk, 0, n);
                    byte[] all = buf.toByteArray();
                    int off = 0;
                    while (all.length - off >= 2 && served < script.length) {
                        int len = ((all[off] & 0xff) << 8)
                                | (all[off + 1] & 0xff);
                        if (all.length - off - 2 < len) {
                            break;
                        }
                        byte[] body = new byte[len];
                        System.arraycopy(all, off + 2, body, 0, len);
                        frames.add(body);
                        os.write(script[served++]);
                        os.flush();
                        off += 2 + len;
                    }
                    buf.reset();
                    buf.write(all, off, all.length - off);
                }
            } catch (Exception ex) {
                throw new RuntimeException(ex);
            } finally {
                try {
                    listener.close();
                } catch (Exception ignored) {
                }
            }
        }
    }
}

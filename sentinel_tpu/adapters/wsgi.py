"""WSGI middleware (reference: ``sentinel-web-servlet``'s ``CommonFilter`` +
``WebCallbackManager`` — SURVEY.md §2.5): each request enters a web context
with the parsed caller origin and an entry named by the (cleaned) URL path;
blocked requests get a 429 by default.
"""

from __future__ import annotations

from typing import Callable, Optional

import sentinel_tpu as st
from sentinel_tpu.core import constants as C
from sentinel_tpu.core.exceptions import BlockException

WEB_CONTEXT_NAME = "sentinel_web_context"
DEFAULT_BLOCK_BODY = b"Blocked by Sentinel (flow limiting)"


class SentinelWSGIMiddleware:
    def __init__(
        self,
        app,
        url_cleaner: Optional[Callable[[str], str]] = None,
        origin_parser: Optional[Callable[[dict], str]] = None,
        block_handler: Optional[Callable] = None,
        total_resource: Optional[str] = None,
    ):
        """``url_cleaner`` maps raw paths to resource names (UrlCleaner);
        ``origin_parser(environ)`` extracts the caller origin
        (RequestOriginParser); ``block_handler(environ, start_response, ex)``
        overrides the 429 response (UrlBlockHandler). ``total_resource``
        adds a CommonTotalFilter-style aggregate entry when set."""
        self.app = app
        self.url_cleaner = url_cleaner or (lambda p: p)
        self.origin_parser = origin_parser or (lambda environ: "")
        self.block_handler = block_handler
        self.total_resource = total_resource

    def __call__(self, environ, start_response):
        path = environ.get("PATH_INFO", "/")
        resource = self.url_cleaner(path)
        origin = self.origin_parser(environ)
        st.context_enter(WEB_CONTEXT_NAME, origin)
        entries = []

        def cleanup():
            for e in reversed(entries):
                e.exit()
            st.exit_context()

        try:
            try:
                if self.total_resource:
                    entries.append(st.entry(self.total_resource,
                                            entry_type=C.EntryType.IN))
                if resource:
                    entries.append(st.entry(resource, entry_type=C.EntryType.IN))
            except BlockException as ex:
                cleanup()  # an earlier entry (total resource) may be live
                if self.block_handler is not None:
                    return self.block_handler(environ, start_response, ex)
                start_response("429 Too Many Requests",
                               [("Content-Type", "text/plain")])
                return [DEFAULT_BLOCK_BODY]
            result = self.app(environ, start_response)
        except BaseException as ex:
            for e in entries:
                e.trace(ex)
            cleanup()
            raise
        else:
            # Entries stay live while the (possibly streaming) body is
            # consumed — RT covers body generation and mid-stream errors
            # are traced (reference CommonFilter completes after the chain).
            return _GuardedIterable(result, entries, cleanup)
        finally:
            if not entries:
                st.exit_context()


class _GuardedIterable:
    """Wraps the app's response iterable; exits entries on exhaustion/close."""

    def __init__(self, result, entries, cleanup):
        self._result = result
        self._entries = entries
        self._cleanup = cleanup
        self._done = False

    def _finish(self):
        if not self._done:
            self._done = True
            self._cleanup()

    def __iter__(self):
        try:
            for chunk in self._result:
                yield chunk
        except BaseException as ex:
            for e in self._entries:
                e.trace(ex)
            raise
        finally:
            self._finish()

    def close(self):
        try:
            close = getattr(self._result, "close", None)
            if close is not None:
                close()
        finally:
            self._finish()

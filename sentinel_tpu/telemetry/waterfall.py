"""Wire-to-device latency waterfall (ISSUE 18).

Every admitted wire request carries a compact stage-stamp record —
``perf_counter`` marks taken at seams that already exist in the reactor,
the batcher, and the pipeline — and the deltas land here as one
8-stage budget per request:

========  ==============================================================
stage     interval
========  ==============================================================
read      socket readable -> frame parsed + staged (reactor thread)
coalesce  staged -> coalesced submit into the batcher queue
queue     submit -> batcher drain (``_Batcher._run``'s ``queue.get``)
dispatch  drain -> device dispatch (linger + flatten + pad + enqueue)
device    dispatch -> harvest materialization (device wall, amortized)
harvest   harvest -> reply slot filled + encoded (``_resolve``)
reply     slot filled -> flush picks the slot (head-of-line wait)
flush     flush pick -> reply bytes handed to the socket layer
========  ==============================================================

The eight deltas chain: their sum is EXACTLY the request's arrival ->
flush RTT (no gaps, no overlaps), which is the reconciliation invariant
the ``waterfall`` command reports. The pipeline lane (``queue`` /
``device`` from :meth:`Pipeline wait split <record_pipeline>`) rides the
same geometry so wire and in-process stages share one histogram family.

Fold cadence: observations accumulate into per-second staging cells
stamped with the ENGINE timebase (``engine.now_ms()`` — inert under
injected clocks, ISSUE 13) and are sealed once per second by
``roll(now)`` riding the flight recorder's ``_spill_flight`` fold —
zero new per-step device work, zero background threads. ``perf_counter``
appears in this module ONLY as a duration/speed source (deltas, probe
windows), never as a timestamp; the lint gate pins that.

Exactness contract (docs/SEMANTICS.md): sealed per-second stage
histograms and sums are EXACT over the requests whose flush landed in
that second. Exemplars are SAMPLED (top-of-histogram outliers plus an
every-Nth cadence among traced requests) — forensic pointers, not
statistics.

The :class:`RegressionSentry` turns committed per-stage budgets (derived
from the BENCH_17 capture) into burn-rate alerts through the SLO
machinery's own window pairs: a wire-path regression pages exactly like
an availability breach.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Sequence, Tuple

from sentinel_tpu.telemetry.attribution import (
    NUM_WF_BUCKETS,
    WF_BUCKET_EDGES_MS,
    bucket_index_of,
    histogram_quantile_edges,
)

WIRE_STAGES: Tuple[str, ...] = (
    "read", "coalesce", "queue", "dispatch", "device", "harvest", "reply",
    "flush")
PIPELINE_STAGES: Tuple[str, ...] = ("queue", "device")
LANE_STAGES: Dict[str, Tuple[str, ...]] = {
    "wire": WIRE_STAGES,
    "pipeline": PIPELINE_STAGES,
}

# Exemplars retained per second (the slowest traced requests win).
_EXEMPLARS_PER_SECOND = 4

# Allowed over-budget fraction per stage: the sentry's objective is
# "99% of requests inside the stage budget", so burn 1.0 == 1% breaching.
SENTRY_ALLOWED_BREACH = 0.01

# Committed per-stage budgets in ms, derived from the BENCH_17
# waterfall_probe capture (890k requests through the saturated loopback
# mesh, depths 1/2/4 x up to 32 conns): each stage's p99 at saturation
# rounded up to the next log2 edge (queue 6.6 -> 8, dispatch 13.1 -> 16,
# device 15.2 -> 16, reply 31.8 -> 32), then one extra doubling of
# headroom on the stages that breathe with box load (queue, device,
# reply — reply's p99 sat ON its edge). A sustained breach of these is
# a wire-path regression, not noise.
DEFAULT_STAGE_BUDGETS_MS: Dict[str, float] = {
    "wire.queue": 16.0,
    "wire.dispatch": 16.0,
    "wire.device": 32.0,
    "wire.reply": 64.0,
}

_LOG2_LO = -6  # WF_BUCKET_EDGES_MS[0] == 2^-6


def _fast_bucket(value_ms: float) -> int:
    """O(1) log2 bucket index (``le`` semantics, +Inf overflow). The
    differential test pins this against the linear-scan oracle in
    :mod:`~sentinel_tpu.telemetry.attribution`."""
    if value_ms <= WF_BUCKET_EDGES_MS[0]:
        return 0
    b = max(0, int(math.ceil(math.log2(value_ms))) - _LOG2_LO)
    # Float fuzz at an exact edge can land one bucket high/low; settle
    # against the real edges (at most one step either way).
    if b >= NUM_WF_BUCKETS - 1:
        return NUM_WF_BUCKETS - 1
    if b > 0 and value_ms <= WF_BUCKET_EDGES_MS[b - 1]:
        return b - 1
    if value_ms > WF_BUCKET_EDGES_MS[b]:
        return b + 1 if b + 1 < NUM_WF_BUCKETS else NUM_WF_BUCKETS - 1
    return b


class _SecondAcc:
    """One staged (not yet sealed) second of observations."""

    __slots__ = ("counts", "sums", "rtt_counts", "rtt_sum", "busy_ms",
                 "batches", "batch_requests", "exemplars", "max_total")

    def __init__(self) -> None:
        self.counts: Dict[str, List[List[int]]] = {}
        self.sums: Dict[str, List[float]] = {}
        self.rtt_counts: List[int] = [0] * NUM_WF_BUCKETS
        self.rtt_sum = 0.0
        self.busy_ms = 0.0
        self.batches = 0
        self.batch_requests = 0
        # [(total_ms, trace_id, bucket)] — bounded, slowest retained.
        self.exemplars: List[Tuple[float, str, int]] = []
        self.max_total = 0.0

    def lane(self, name: str) -> Tuple[List[List[int]], List[float]]:
        counts = self.counts.get(name)
        if counts is None:
            n = len(LANE_STAGES[name])
            counts = self.counts[name] = [
                [0] * NUM_WF_BUCKETS for _ in range(n)]
            self.sums[name] = [0.0] * n
        return counts, self.sums[name]


class WaterfallRecorder:
    """Per-second per-stage latency histograms + exemplars + sentry.

    One rides each engine (``engine.waterfall``); engine-less instances
    (unit tests, oracles) inject ``now_ms`` — with neither, timestamps
    ride a ``perf_counter``-derived millisecond counter so the module
    never reads the wall clock.
    """

    def __init__(self, engine=None, now_ms: Optional[Callable[[], int]] = None,
                 transition: Optional[Callable] = None):
        from sentinel_tpu.core.config import config as _cfg

        self._engine = engine
        if engine is not None:
            self._now_ms: Callable[[], int] = engine.now_ms
        elif now_ms is not None:
            self._now_ms = now_ms
        else:
            self._now_ms = lambda: int(time.perf_counter() * 1000)
        self.enabled = _cfg.waterfall_enabled()
        self.exemplar_every = _cfg.waterfall_exemplar_every()
        self._lock = threading.Lock()
        self._staged: Dict[int, _SecondAcc] = {}
        self._sealed: Deque[Dict] = deque(
            maxlen=max(1, _cfg.waterfall_history_seconds()))
        self._sealed_floor = -1
        # Cumulative (since construction / timebase reset survives):
        self._cum_counts: Dict[str, List[List[int]]] = {
            lane: [[0] * NUM_WF_BUCKETS for _ in stages]
            for lane, stages in LANE_STAGES.items()}
        self._cum_sums: Dict[str, List[float]] = {
            lane: [0.0] * len(stages)
            for lane, stages in LANE_STAGES.items()}
        self._cum_rtt: List[int] = [0] * NUM_WF_BUCKETS
        self._cum_rtt_sum = 0.0
        # rtt bucket -> latest exemplar {"traceId","valueMs","timestampMs"}.
        self._rtt_exemplars: Dict[int, Dict] = {}
        self._n_traced = 0
        self.sealed_seconds = 0
        self.late_drops = 0
        self.observed_requests = 0
        self.exemplars_captured = 0
        self.sentry = RegressionSentry(self, engine=engine,
                                       transition=transition)

    # -- write side (hot paths) ---------------------------------------------

    def observe_wire(self, durations_ms: Sequence[float],
                     trace_id: Optional[str] = None) -> None:
        """One admitted wire request's eight stage deltas (ms), in
        :data:`WIRE_STAGES` order. Their sum is the request RTT."""
        if not self.enabled:
            return
        sec = self._now_ms() // 1000 * 1000
        total = 0.0
        with self._lock:
            if sec <= self._sealed_floor - 1000:
                self.late_drops += 1
                return
            acc = self._staged.get(sec)
            if acc is None:
                acc = self._staged[sec] = _SecondAcc()
            counts, sums = acc.lane("wire")
            for i, d in enumerate(durations_ms):
                d = d if d > 0.0 else 0.0
                counts[i][_fast_bucket(d)] += 1
                sums[i] += d
                total += d
            acc.rtt_counts[_fast_bucket(total)] += 1
            acc.rtt_sum += total
            self.observed_requests += 1
            if trace_id:
                self._n_traced += 1
                if (total >= acc.max_total
                        or self._n_traced % self.exemplar_every == 0):
                    acc.max_total = max(acc.max_total, total)
                    ex = acc.exemplars
                    ex.append((total, trace_id, _fast_bucket(total)))
                    if len(ex) > _EXEMPLARS_PER_SECOND:
                        ex.remove(min(ex, key=lambda e: e[0]))

    def observe_pipeline(self, queue_wait_ms: float,
                         device_wait_ms: float) -> None:
        """One pipeline harvest's queue/device wait split (ms)."""
        if not self.enabled:
            return
        sec = self._now_ms() // 1000 * 1000
        with self._lock:
            if sec <= self._sealed_floor - 1000:
                self.late_drops += 1
                return
            acc = self._staged.get(sec)
            if acc is None:
                acc = self._staged[sec] = _SecondAcc()
            counts, sums = acc.lane("pipeline")
            for i, d in enumerate((queue_wait_ms, device_wait_ms)):
                d = d if d > 0.0 else 0.0
                counts[i][_fast_bucket(d)] += 1
                sums[i] += d

    def observe_batch(self, device_busy_ms: float, n_requests: int) -> None:
        """One fused device batch: device wall (ms) + coalesced width —
        the utilization / coalesce-efficiency denominators."""
        if not self.enabled:
            return
        sec = self._now_ms() // 1000 * 1000
        with self._lock:
            acc = self._staged.get(sec)
            if acc is None:
                acc = self._staged[sec] = _SecondAcc()
            acc.busy_ms += device_busy_ms if device_busy_ms > 0.0 else 0.0
            acc.batches += 1
            acc.batch_requests += int(n_requests)

    # -- fold (rides _spill_flight) -----------------------------------------

    def roll(self, now_ms: int) -> None:
        """Seal every staged second strictly before the current one.
        Idempotent; host arithmetic only. Sentry evaluation rides the
        same call, outside the recorder lock."""
        cur = int(now_ms) - int(now_ms) % 1000
        new_recs: List[Dict] = []
        with self._lock:
            for sec in sorted(s for s in self._staged if s < cur):
                rec = self._seal(sec, self._staged.pop(sec))
                self._sealed.append(rec)
                self.sealed_seconds += 1
                new_recs.append(rec)
            if new_recs:
                self._sealed_floor = max(self._sealed_floor, cur)
        for rec in new_recs:
            self.sentry.ingest(rec)
        self.sentry.evaluate(now_ms)

    def _seal(self, sec: int, acc: _SecondAcc) -> Dict:
        # Caller holds the lock.
        lanes: Dict[str, Dict] = {}
        for lane, counts in acc.counts.items():
            sums = acc.sums[lane]
            cum_c, cum_s = self._cum_counts[lane], self._cum_sums[lane]
            stages: Dict[str, Dict] = {}
            for i, name in enumerate(LANE_STAGES[lane]):
                row, s = counts[i], sums[i]
                n = sum(row)
                for b in range(NUM_WF_BUCKETS):
                    cum_c[i][b] += row[b]
                cum_s[i] += s
                stages[name] = {
                    "count": n,
                    "sumMs": round(s, 4),
                    "p50Ms": round(histogram_quantile_edges(
                        row, 0.5, WF_BUCKET_EDGES_MS), 4),
                    "p99Ms": round(histogram_quantile_edges(
                        row, 0.99, WF_BUCKET_EDGES_MS), 4),
                    # Little's law at a 1s window: L = (sum of time
                    # spent in stage) / window — inferred concurrency.
                    "concurrency": round(s / 1000.0, 4),
                    "buckets": list(row),
                }
            lanes[lane] = stages
        for b in range(NUM_WF_BUCKETS):
            self._cum_rtt[b] += acc.rtt_counts[b]
        self._cum_rtt_sum += acc.rtt_sum
        exemplars = []
        # Ascending, so within one second the SLOWEST same-bucket
        # exemplar is the one the cumulative per-bucket map retains.
        for total, trace_id, bucket in sorted(acc.exemplars):
            ex = {"traceId": trace_id, "valueMs": round(total, 4),
                  "bucket": bucket, "timestampMs": sec}
            exemplars.append(ex)
            self._rtt_exemplars[bucket] = ex
            self.exemplars_captured += 1
        exemplars.reverse()  # slowest first for display
        n_rtt = sum(acc.rtt_counts)
        return {
            "timestamp": sec,
            "lanes": lanes,
            "rtt": {
                "count": n_rtt,
                "sumMs": round(acc.rtt_sum, 4),
                "p50Ms": round(histogram_quantile_edges(
                    acc.rtt_counts, 0.5, WF_BUCKET_EDGES_MS), 4),
                "p99Ms": round(histogram_quantile_edges(
                    acc.rtt_counts, 0.99, WF_BUCKET_EDGES_MS), 4),
                "buckets": list(acc.rtt_counts),
            },
            "coalesce": {
                "batches": acc.batches,
                "requests": acc.batch_requests,
                "efficiency": round(acc.batch_requests / acc.batches, 4)
                if acc.batches else 0.0,
            },
            "deviceUtilization": round(min(1.0, acc.busy_ms / 1000.0), 4),
            "exemplars": exemplars,
        }

    def reset_timebase(self) -> None:
        """The engine's ``set_clock`` seam: staged cells, history, and
        cursors carry absolute stamps of the OLD timebase — drop them so
        in-sim seconds start clean (cumulative totals survive: they are
        counters, not stamps)."""
        with self._lock:
            self._staged.clear()
            self._sealed.clear()
            self._sealed_floor = -1
        self.sentry.reset_timebase()

    # -- read surfaces ------------------------------------------------------

    def snapshot(self, limit: int = 60) -> Dict:
        """The ``waterfall`` command / dashboard view."""
        with self._lock:
            recent = list(self._sealed)[-max(0, int(limit)):]
            cumulative: Dict[str, Dict] = {}
            wire_stage_total = 0.0
            for lane, stages in LANE_STAGES.items():
                out: Dict[str, Dict] = {}
                for i, name in enumerate(stages):
                    row = self._cum_counts[lane][i]
                    s = self._cum_sums[lane][i]
                    if lane == "wire":
                        wire_stage_total += s
                    out[name] = {
                        "count": sum(row),
                        "sumMs": round(s, 4),
                        "p50Ms": round(histogram_quantile_edges(
                            row, 0.5, WF_BUCKET_EDGES_MS), 4),
                        "p99Ms": round(histogram_quantile_edges(
                            row, 0.99, WF_BUCKET_EDGES_MS), 4),
                    }
                cumulative[lane] = out
            rtt_sum = self._cum_rtt_sum
            snap = {
                "enabled": self.enabled,
                "stages": {k: list(v) for k, v in LANE_STAGES.items()},
                "edgesMs": list(WF_BUCKET_EDGES_MS),
                "sealedSeconds": self.sealed_seconds,
                "stagedSeconds": len(self._staged),
                "observedRequests": self.observed_requests,
                "lateDrops": self.late_drops,
                "exemplarsCaptured": self.exemplars_captured,
                "cumulative": cumulative,
                "rtt": {
                    "count": sum(self._cum_rtt),
                    "sumMs": round(rtt_sum, 4),
                    "p50Ms": round(histogram_quantile_edges(
                        self._cum_rtt, 0.5, WF_BUCKET_EDGES_MS), 4),
                    "p99Ms": round(histogram_quantile_edges(
                        self._cum_rtt, 0.99, WF_BUCKET_EDGES_MS), 4),
                },
                # The exactness invariant: the eight wire stages chain,
                # so their summed time equals the summed RTT (both over
                # SEALED seconds only; staged cells are excluded from
                # both sides, so the delta is float fuzz, not sampling).
                "reconciliation": {
                    "wireStageSumMs": round(wire_stage_total, 4),
                    "rttSumMs": round(rtt_sum, 4),
                    "relativeError": round(
                        abs(wire_stage_total - rtt_sum) / rtt_sum, 9)
                    if rtt_sum > 0 else 0.0,
                },
                "exemplars": [dict(self._rtt_exemplars[b])
                              for b in sorted(self._rtt_exemplars)],
                "recent": recent,
            }
        snap["sentry"] = self.sentry.snapshot()
        return snap

    def export_state(self) -> Dict:
        """The OpenMetrics exporter's read: cumulative histograms +
        per-bucket exemplars + last sealed second's derived gauges."""
        with self._lock:
            hist = {
                lane: {
                    name: (list(self._cum_counts[lane][i]),
                           self._cum_sums[lane][i])
                    for i, name in enumerate(stages)}
                for lane, stages in LANE_STAGES.items()}
            last = self._sealed[-1] if self._sealed else None
            return {
                "hist": hist,
                "rtt": (list(self._cum_rtt), self._cum_rtt_sum),
                "rttExemplars": {b: dict(ex)
                                 for b, ex in self._rtt_exemplars.items()},
                "last": last,
                "sealedSeconds": self.sealed_seconds,
                "exemplarsCaptured": self.exemplars_captured,
                "budgetsMs": dict(self.sentry.budgets),
            }


class RegressionSentry:
    """Committed per-stage budgets judged by the SLO burn-window pairs.

    Each sealed second contributes one (bad, total) sample per budgeted
    stage — ``bad`` counted EXACTLY from the sealed histogram with the
    budget snapped UP to its log2 edge (same convention as
    ``snap_latency_ms``). Alerts land through
    :meth:`SloManager.external_transition`, so a wire-path regression
    shares the availability machinery's store, journal, and webhook.
    """

    def __init__(self, recorder: WaterfallRecorder, engine=None,
                 transition: Optional[Callable] = None):
        from sentinel_tpu.core.config import config as _cfg
        from sentinel_tpu.slo.objectives import DEFAULT_BURN_WINDOWS

        self._recorder = recorder
        self._engine = engine
        self._transition = transition
        self.enabled = _cfg.waterfall_sentry_enabled()
        self.min_events = _cfg.waterfall_sentry_min_events()
        self.windows = DEFAULT_BURN_WINDOWS
        self.budgets: Dict[str, float] = dict(DEFAULT_STAGE_BUDGETS_MS)
        self._lock = threading.Lock()
        self._series: Dict[str, Deque[Tuple[int, int, int]]] = {}
        self._retain_ms = (max(w.long_s for w in self.windows) + 60) * 1000
        self._eval_end = -1
        self._burn: Dict[str, List[Dict]] = {}

    def _sink(self) -> Optional[Callable]:
        if self._transition is not None:
            return self._transition
        slo = getattr(self._engine, "slo", None) \
            if self._engine is not None else None
        return slo.external_transition if slo is not None else None

    def set_budgets(self, budgets: Dict[str, float]) -> Dict[str, float]:
        """Merge operator overrides (``{"lane.stage": ms}``); a budget
        <= 0 removes the key. Unknown stages are rejected. Removing a
        budget resolves any alert it fired — ``evaluate`` stops
        iterating the key, so without an explicit resolve here a fired
        alert would sit active in the SLO store forever."""
        resolves = []
        with self._lock:
            for key, val in budgets.items():
                lane, _, stage = str(key).partition(".")
                if stage not in LANE_STAGES.get(lane, ()):
                    raise ValueError(f"unknown waterfall stage: {key!r}")
                val = float(val)
                if val <= 0:
                    removed = self.budgets.pop(key, None)
                    self._series.pop(key, None)
                    self._burn.pop(key, None)
                    if removed is not None:
                        resolves.extend(
                            f"waterfall:{key}:{w.long_s}s/{w.short_s}s"
                            f":{w.severity}" for w in self.windows)
                else:
                    self.budgets[key] = val
            out = dict(self.budgets)
            end = max(self._eval_end, 0)
        sink = self._sink()
        if sink is not None:
            for rule_key in resolves:
                sink(rule_key, False, end, {"key": rule_key,
                                            "kind": "waterfall_budget"})
        return out

    def ingest(self, rec: Dict) -> None:
        if not self.enabled:
            return
        stamp = rec["timestamp"]
        with self._lock:
            for key, budget in self.budgets.items():
                lane, _, stage = key.partition(".")
                cell = rec["lanes"].get(lane, {}).get(stage)
                if not cell or not cell["count"]:
                    continue
                buckets = cell["buckets"]
                edge_b = bucket_index_of(budget)
                good = sum(buckets[:edge_b + 1])
                total = cell["count"]
                series = self._series.get(key)
                if series is None:
                    series = self._series[key] = deque()
                series.append((stamp, total - good, total))
                floor = stamp - self._retain_ms
                while series and series[0][0] < floor:
                    series.popleft()

    def evaluate(self, now_ms: int) -> None:
        if not self.enabled:
            return
        sink = self._sink()
        if sink is None:
            return
        from sentinel_tpu.slo.manager import _burn, _window_sums

        end = int(now_ms) - int(now_ms) % 1000
        transitions = []
        with self._lock:
            if end < self._eval_end:
                return
            self._eval_end = end
            for key, budget in self.budgets.items():
                series = self._series.get(key)
                if series is None:
                    continue
                rules_out = []
                for w in self.windows:
                    bad_l, tot_l = _window_sums(series, end, w.long_s)
                    bad_s, tot_s = _window_sums(series, end, w.short_s)
                    burn_l = _burn(bad_l, tot_l, SENTRY_ALLOWED_BREACH)
                    burn_s = _burn(bad_s, tot_s, SENTRY_ALLOWED_BREACH)
                    firing = (tot_l >= self.min_events
                              and burn_l >= w.burn and burn_s >= w.burn)
                    rule_key = (f"waterfall:{key}:{w.long_s}s/{w.short_s}s"
                                f":{w.severity}")
                    rules_out.append({
                        "window": f"{w.long_s}s/{w.short_s}s",
                        "severity": w.severity,
                        "burnLong": round(burn_l, 4),
                        "burnShort": round(burn_s, 4),
                        "events": tot_l,
                        "firing": firing,
                    })
                    transitions.append((rule_key, firing, {
                        "key": rule_key,
                        "kind": "waterfall_budget",
                        "severity": w.severity,
                        "resource": f"waterfall:{key}",
                        "stage": key,
                        "budgetMs": budget,
                        "burnLong": round(burn_l, 4),
                        "burnShort": round(burn_s, 4),
                        "windowLongS": w.long_s,
                        "windowShortS": w.short_s,
                        "allowedBreachFraction": SENTRY_ALLOWED_BREACH,
                    }))
                self._burn[key] = rules_out
        for rule_key, firing, fields in transitions:
            sink(rule_key, firing, end, fields)

    def reset_timebase(self) -> None:
        with self._lock:
            self._series.clear()
            self._burn.clear()
            self._eval_end = -1

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "minEvents": self.min_events,
                "allowedBreachFraction": SENTRY_ALLOWED_BREACH,
                "budgetsMs": dict(self.budgets),
                "burn": {k: [dict(r) for r in v]
                         for k, v in self._burn.items()},
            }


# ---------------------------------------------------------------------------
# Saturation probe: drive the in-process loopback mesh across a
# (pipeline depth x connection count) grid and record the acquires/s
# curve — the instrument ROADMAP item 5 asks for before sharding the
# reactor. perf_counter is used for window timing only (speed, not
# timestamps).
# ---------------------------------------------------------------------------

def saturation_probe(depths: Sequence[int] = (1, 2, 4),
                     conns_grid: Sequence[int] = (2, 8, 32),
                     window_s: float = 2.0,
                     settle_s: float = 1.0,
                     burst: int = 64,
                     n_flows: int = 32,
                     max_cells: int = 16) -> Dict:
    """Measure acquires/s per (inflight depth, connection count) cell on
    a fresh loopback :class:`ClusterTokenServer` per depth. Returns the
    raw grid plus, per depth, the peak rate and the FIRST connection
    count reaching >= 90% of it (the saturation knee)."""
    import socket as _socket

    import sentinel_tpu as st
    from sentinel_tpu.cluster import codec
    from sentinel_tpu.cluster.constants import MSG_FLOW
    from sentinel_tpu.cluster.rules import ClusterFlowRuleManager
    from sentinel_tpu.cluster.server import ClusterTokenServer
    from sentinel_tpu.cluster.token_service import DefaultTokenService

    grid = [(d, c) for d in depths for c in conns_grid][:max(1, max_cells)]
    cells: List[Dict] = []
    for depth in sorted({d for d, _ in grid}):
        rules = ClusterFlowRuleManager()
        rules.load_rules("default", [
            st.FlowRule(resource=f"wf{i}", count=1e9, cluster_mode=True,
                        cluster_config={"flowId": 6000 + i,
                                        "thresholdType": 1})
            for i in range(n_flows)
        ])
        svc = DefaultTokenService(rules, max_allowed_qps=1e12)
        for w in (burst, 256, 1024, 4096):  # absorb the coalesce-width jits
            svc.request_tokens([(6000, 1, False)] * w)
        server = ClusterTokenServer(svc, host="127.0.0.1", port=0)
        server.batcher.inflight_depth = depth
        server.start()
        try:
            for d, n_conns in grid:
                if d != depth:
                    continue
                rate = _drive_cell(_socket, codec, MSG_FLOW,
                                   server.bound_port, n_conns, burst,
                                   n_flows, window_s, settle_s)
                cells.append({"depth": depth, "connections": n_conns,
                              "acquiresPerSec": round(rate, 1)})
        finally:
            server.stop()
    per_depth: Dict[str, Dict] = {}
    for depth in sorted({d for d, _ in grid}):
        row = [c for c in cells if c["depth"] == depth]
        peak = max((c["acquiresPerSec"] for c in row), default=0.0)
        knee = next((c["connections"] for c in row
                     if peak > 0 and c["acquiresPerSec"] >= 0.9 * peak), 0)
        per_depth[str(depth)] = {"peakAcquiresPerSec": peak,
                                 "saturationConnections": knee}
    return {"grid": cells, "perDepth": per_depth,
            "pipelinedPerConn": burst, "windowS": window_s}


def _drive_cell(_socket, codec, msg_flow: int, port: int, n_conns: int,
                burst: int, n_flows: int, window_s: float,
                settle_s: float) -> float:
    """One probe cell: ``n_conns`` pipelined TLV connections, each
    keeping ``burst`` requests in flight; returns replies/s over the
    measurement window (frames pre-encoded — server cost only)."""
    n_threads = min(8, n_conns)
    stop = threading.Event()
    replies = [0] * n_threads
    barrier = threading.Barrier(n_threads + 1)
    per_thread = [n_conns // n_threads + (1 if t < n_conns % n_threads else 0)
                  for t in range(n_threads)]

    def worker(tid: int) -> None:
        conns = []
        try:
            for _ in range(per_thread[tid]):
                s = _socket.create_connection(("127.0.0.1", port), timeout=10)
                s.settimeout(10)
                s.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
                conns.append((s, codec.FrameReader()))
            frames = b"".join(
                codec.encode_request(
                    xid + 1, msg_flow,
                    codec.encode_flow_request(
                        6000 + (tid + xid) % n_flows, 1, False))
                for xid in range(burst))
            barrier.wait()
            while not stop.is_set():
                for s, _ in conns:
                    s.sendall(frames)
                for s, reader in conns:
                    got = 0
                    while got < burst:
                        data = s.recv(65536)
                        if not data:
                            return
                        for body in reader.feed(data):
                            codec.decode_response(body)
                            got += 1
                            replies[tid] += 1
        except (OSError, threading.BrokenBarrierError):
            pass
        finally:
            for s, _ in conns:
                try:
                    s.close()
                except OSError:
                    pass

    threads = [threading.Thread(target=worker, args=(t,), daemon=True)
               for t in range(n_threads)]
    for t in threads:
        t.start()
    try:
        barrier.wait(timeout=30)
    except threading.BrokenBarrierError:
        stop.set()
        return 0.0
    time.sleep(max(0.0, settle_s))
    base = sum(replies)
    t0 = time.perf_counter()
    time.sleep(max(0.1, window_s))
    dt = time.perf_counter() - t0
    got = sum(replies) - base
    stop.set()
    for t in threads:
        t.join(timeout=15)
    return got / dt if dt > 0 else 0.0

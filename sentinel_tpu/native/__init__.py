"""ctypes bindings for the native shim (``native/sentinel_shim.cpp``).

The shim is the language-neutral client path to the token server (C ABI:
JNI / FFI / ctypes all bind it — the reference-parity "SPI shim" of
SURVEY.md §7 M4) plus the cached-tick clock. Built on demand with ``make``
(g++); everything degrades gracefully when the toolchain or library is
unavailable — ``load_shim()`` returns None and callers fall back to the
pure-Python client.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_LIB_NAME = "libsentinel_shim.so"

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _build() -> Optional[str]:
    path = os.path.abspath(os.path.join(_NATIVE_DIR, _LIB_NAME))
    src = os.path.abspath(os.path.join(_NATIVE_DIR, "sentinel_shim.cpp"))
    if not os.path.exists(src):
        # No source (e.g. trimmed install): a prebuilt .so is all we have.
        return path if os.path.exists(path) else None
    # Source present: ALWAYS go through make, whose own mtime check rebuilds
    # strictly-stale outputs. An equal-mtime prebuilt never shadows source.
    try:
        subprocess.run(["make", "-s", _LIB_NAME],
                       cwd=os.path.abspath(_NATIVE_DIR),
                       check=True, capture_output=True, timeout=120)
    except (OSError, subprocess.SubprocessError):
        return None
    return path if os.path.exists(path) else None


def load_shim() -> Optional[ctypes.CDLL]:
    """The shim library, built+loaded lazily; None when unavailable."""
    global _lib, _load_failed
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        path = _build()
        if path is None:
            _load_failed = True
            return None
        try:
            lib = ctypes.CDLL(path)
            _declare(lib)
        except (OSError, AttributeError):
            # AttributeError: a stale prebuilt .so missing newer symbols
            # (no source to rebuild from) — fall back like any other miss.
            _load_failed = True
            return None
        _lib = lib
        return _lib


def _declare(lib: ctypes.CDLL) -> None:
    lib.st_client_connect.restype = ctypes.c_void_p
    lib.st_client_connect.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int]
    lib.st_request_token.restype = ctypes.c_int
    lib.st_request_token.argtypes = [
        ctypes.c_void_p, ctypes.c_longlong, ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_int)]
    lib.st_request_param_token.restype = ctypes.c_int
    lib.st_request_param_token.argtypes = [
        ctypes.c_void_p, ctypes.c_longlong, ctypes.c_int,
        ctypes.POINTER(StParam), ctypes.c_int]
    lib.st_request_tokens_batch.restype = ctypes.c_int
    lib.st_request_tokens_batch.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_longlong),
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
        ctypes.c_int, ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_int)]
    lib.st_remote_entry.restype = ctypes.c_int
    lib.st_remote_entry.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.POINTER(StParam), ctypes.c_int,
        ctypes.POINTER(ctypes.c_longlong), ctypes.POINTER(ctypes.c_int)]
    lib.st_remote_exit.restype = ctypes.c_int
    lib.st_remote_exit.argtypes = [
        ctypes.c_void_p, ctypes.c_longlong, ctypes.c_int, ctypes.c_int]
    lib.st_client_close.argtypes = [ctypes.c_void_p]
    lib.st_now_ms.restype = ctypes.c_longlong


class StParam(ctypes.Structure):
    """Mirror of ``st_param`` in native/sentinel_shim.h."""

    _fields_ = [("tag", ctypes.c_ubyte), ("i", ctypes.c_longlong),
                ("d", ctypes.c_double), ("s", ctypes.c_char_p)]


def _pack_params(params):
    arr = (StParam * len(params))()
    keepalive = []
    for k, p in enumerate(params):
        if isinstance(p, bool):
            arr[k].tag, arr[k].i = 2, int(p)
        elif isinstance(p, int):
            arr[k].tag, arr[k].i = 0, p
        elif isinstance(p, float):
            arr[k].tag, arr[k].d = 3, p
        else:
            raw = str(p).encode("utf-8")
            keepalive.append(raw)
            arr[k].tag, arr[k].s = 1, raw
    return arr, keepalive


class NativeTokenClient:
    """Token client backed by the C++ shim (wire-compatible with the
    Python ``ClusterTokenClient``). Multi-in-flight: N threads may call
    concurrently on one instance — responses demux by xid inside the
    shim. ``close`` must not race new requests (shim close contract)."""

    def __init__(self, host: str, port: int, namespace: str = "default",
                 timeout_ms: int = 3000):
        lib = load_shim()
        if lib is None:
            raise RuntimeError("native shim unavailable (no g++/make?)")
        self._lib = lib
        self._handle = lib.st_client_connect(
            host.encode(), port, namespace.encode(), timeout_ms)
        if not self._handle:
            raise ConnectionError(f"shim could not connect to {host}:{port}")

    def request_token(self, flow_id: int, count: int = 1,
                      prioritized: bool = False):
        from sentinel_tpu.cluster.token_service import TokenResult

        extra = ctypes.c_int(0)
        status = self._lib.st_request_token(
            self._handle, flow_id, count, 1 if prioritized else 0,
            ctypes.byref(extra))
        if status == 2:  # SHOULD_WAIT
            return TokenResult(status, wait_ms=extra.value)
        return TokenResult(status, remaining=extra.value)

    def request_param_token(self, flow_id: int, count: int, params):
        """Hot-param acquire through the shim (typed params hash-compatible
        with the Python client's)."""
        from sentinel_tpu.cluster.token_service import TokenResult

        arr, keepalive = _pack_params(list(params))
        status = self._lib.st_request_param_token(
            self._handle, flow_id, count, arr, len(arr))
        del keepalive
        return TokenResult(status)

    def request_tokens_batch(self, requests):
        """Pipelined batch acquire: ``requests`` is a sequence of
        ``(flow_id, count, prioritized)``; all frames are sent before any
        response is awaited — one RTT per batch, and the server's
        micro-batcher folds them into one device step. Returns a list of
        TokenResult (status -1 entries mark transport loss)."""
        from sentinel_tpu.cluster.token_service import TokenResult

        n = len(requests)
        if n == 0:
            return []
        flow_ids = (ctypes.c_longlong * n)(*[int(r[0]) for r in requests])
        counts = (ctypes.c_int * n)(*[int(r[1]) for r in requests])
        prios = (ctypes.c_int * n)(*[1 if r[2] else 0 for r in requests])
        statuses = (ctypes.c_int * n)()
        extras = (ctypes.c_int * n)()
        self._lib.st_request_tokens_batch(
            self._handle, flow_ids, counts, prios, n, statuses, extras)
        out = []
        for k in range(n):
            if statuses[k] == 2:  # SHOULD_WAIT
                out.append(TokenResult(statuses[k], wait_ms=extras[k]))
            else:
                out.append(TokenResult(statuses[k], remaining=extras[k]))
        return out

    def remote_entry(self, resource: str, origin: str = "", count: int = 1,
                     entry_type: int = 0, prioritized: bool = False,
                     params=()):
        """M4 bridge: full backend slot-chain check + stats commit.
        Returns ``(status, entry_id, reason)``."""
        arr, keepalive = _pack_params(list(params))
        entry_id = ctypes.c_longlong(0)
        reason = ctypes.c_int(0)
        status = self._lib.st_remote_entry(
            self._handle, resource.encode(), origin.encode(), count,
            entry_type, 1 if prioritized else 0, arr, len(arr),
            ctypes.byref(entry_id), ctypes.byref(reason))
        del keepalive
        return status, entry_id.value, reason.value

    def remote_exit(self, entry_id: int, error: bool = False,
                    count: int = -1) -> int:
        return self._lib.st_remote_exit(
            self._handle, entry_id, 1 if error else 0, count)

    def close(self) -> None:
        if self._handle:
            self._lib.st_client_close(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def native_now_ms() -> Optional[int]:
    """Cached-tick clock read; None when the shim is unavailable."""
    lib = load_shim()
    if lib is None:
        return None
    return int(lib.st_now_ms())


_lease_ext = None
_lease_ext_failed = False


def load_lease_ext():
    """The ``sentinel_lease_ext`` CPython extension (the token-lease
    admission ring at C speed — see ``native/lease_ext.c`` for why an
    extension and not the shim's ctypes surface). Built on demand like
    the shim; None when the toolchain or headers are unavailable."""
    global _lease_ext, _lease_ext_failed
    with _lock:
        if _lease_ext is not None or _lease_ext_failed:
            return _lease_ext
        so = os.path.abspath(os.path.join(_NATIVE_DIR,
                                          "sentinel_lease_ext.so"))
        src = os.path.abspath(os.path.join(_NATIVE_DIR, "lease_ext.c"))
        if os.path.exists(src):
            # PY_INCLUDE must come from THE RUNNING interpreter, not
            # whatever python3 is on PATH: the untagged .so name carries
            # no ABI tag, so a cross-version build would import anyway
            # and crash in the admission hot path instead of falling
            # back cleanly.
            import sysconfig

            try:
                subprocess.run(
                    ["make", "-s", "sentinel_lease_ext.so",
                     f"PY_INCLUDE={sysconfig.get_paths()['include']}"],
                    cwd=os.path.abspath(_NATIVE_DIR),
                    check=True, capture_output=True, timeout=120)
            except (OSError, subprocess.SubprocessError):
                _lease_ext_failed = True
                return None
        if not os.path.exists(so):
            _lease_ext_failed = True
            return None
        try:
            import importlib.util

            spec = importlib.util.spec_from_file_location(
                "sentinel_lease_ext", so)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
        except (OSError, ImportError):
            _lease_ext_failed = True
            return None
        _lease_ext = mod
        return _lease_ext

"""HTTP-polling datasource: the Eureka / Spring-Cloud-Config / Apollo
shape (reference: ``sentinel-datasource-eureka`` /
``…-spring-cloud-config`` — SURVEY.md §2.2): periodically GET a config
URL, push on change. Change detection is conditional-request native:
``ETag``/``If-None-Match`` first, ``Last-Modified``/``If-Modified-Since``
second, so an unchanged poll costs one 304 round-trip and no conversion.

``MiniConfigHTTPServer`` is the in-repo fake — a minimal config endpoint
serving one document with proper ETag/304 semantics — used by tests and
demos; point the datasource at any real HTTP config endpoint and nothing
changes.
"""

from __future__ import annotations

import hashlib
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler
from typing import Optional

from sentinel_tpu.datasource._mini_http import RestartableHTTPServer
from sentinel_tpu.datasource.base import (
    AutoRefreshDataSource,
    Converter,
    T,
)


class HttpRefreshableDataSource(AutoRefreshDataSource[str, T]):
    """GET ``url`` every ``recommend_refresh_ms``; convert + push on 200,
    skip cheaply on 304. Network errors keep the last good rules and the
    poll loop alive (the reference's AutoRefresh stance)."""

    def __init__(self, url: str, converter: Converter,
                 recommend_refresh_ms: int = 3000,
                 timeout_s: float = 5.0,
                 headers: Optional[dict] = None,
                 retry_policy=None):
        super().__init__(converter, recommend_refresh_ms,
                         retry_policy=retry_policy)
        self.url = url
        self.timeout_s = timeout_s
        self.headers = dict(headers or {})
        self._etag: Optional[str] = None
        self._last_modified: Optional[str] = None
        self._pending: Optional[tuple] = None
        self._not_modified = False

    def read_source(self) -> Optional[str]:
        req = urllib.request.Request(self.url, headers=dict(self.headers))
        if self._etag:
            req.add_header("If-None-Match", self._etag)
        elif self._last_modified:
            req.add_header("If-Modified-Since", self._last_modified)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                body = resp.read().decode(
                    resp.headers.get_content_charset() or "utf-8")
                # Stage the validators; load_config commits them only
                # after the CONVERTER succeeds too — recording them any
                # earlier turns a mid-body or bad-document failure into a
                # poisoned cache (every later poll 304s against a document
                # that was never actually applied).
                self._pending = (resp.headers.get("ETag"),
                                 resp.headers.get("Last-Modified"))
                self._not_modified = False
                return body
        except urllib.error.HTTPError as ex:
            if ex.code == 304:
                self._not_modified = True
                return None  # unchanged: load_config pushes nothing
            raise

    def load_config(self):
        raw = self.read_source()
        if raw is None and self._not_modified:
            return None
        value = self.converter(raw)
        if self._pending is not None:
            self._etag, self._last_modified = self._pending
            self._pending = None
        return value


class _ConfigHandler(BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802 — http.server API
        server: "MiniConfigHTTPServer" = self.server  # type: ignore
        with server._lock:
            body, etag = server._body, server._etag
            server.request_count += 1
            if self.headers.get("If-None-Match") == etag:
                server.not_modified_count += 1
                self.send_response(304)
                self.send_header("ETag", etag)
                self.end_headers()
                return
        self.send_response(200)
        self.send_header("Content-Type", "application/json; charset=utf-8")
        self.send_header("ETag", etag)
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # quiet
        pass


class MiniConfigHTTPServer(RestartableHTTPServer):
    """One-document config endpoint with real ETag/304 semantics (the
    shared base adds stop()+start() same-port restartability)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        super().__init__(host, port, _ConfigHandler)
        self._lock = threading.Lock()
        self._body = b"[]"
        self._etag = '"empty"'
        self.request_count = 0
        self.not_modified_count = 0

    @property
    def url(self) -> str:
        return f"{self.addr}/config"

    def set_document(self, text: str) -> None:
        raw = text.encode("utf-8")
        with self._lock:
            self._body = raw
            self._etag = '"%s"' % hashlib.sha1(raw).hexdigest()[:16]

"""Authority rules: per-resource origin allow/deny lists.

Reference surface (SURVEY.md §2.1 "AuthoritySlot"): ``AuthorityRule``
(resource, limitApp = comma-separated origin list, strategy WHITE/BLACK),
``AuthorityRuleManager``, ``AuthorityRuleChecker.passCheck`` — requests with
an empty origin always pass; WHITE passes iff the origin is listed, BLACK
passes iff it is not. Upstream paths: ``core:slots/block/authority/``
(reference mount was empty; citations are upstream-layout paths).

TPU-native design: origins are interned to int ids host-side (the registry
already does this for per-origin stats rows), so the device check is a
vectorized membership test of ``batch.origin_id`` against a padded
``int32[AR, MAX_ORIGINS]`` id table — no strings on device.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from sentinel_tpu.core import constants as C
from sentinel_tpu.core.rule_manager import RuleManager
from sentinel_tpu.core.batch import EntryBatch
from sentinel_tpu.core.registry import NodeRegistry
from sentinel_tpu.ops import window as W
from sentinel_tpu.utils.shapes import round_up as _round_up

# Origins beyond this many per rule are kept host-side valid but ignored on
# device; compile_authority_rules widens the table to fit, so this is only
# the floor.
MIN_ORIGIN_SLOTS = 4

_NO_ORIGIN = -100  # padding id that never equals a real interned origin


@dataclass
class AuthorityRule:
    resource: str
    limit_app: str  # comma-separated origin names
    strategy: int = C.AUTHORITY_WHITE
    # Staged rollout (sentinel_tpu/rollout/): see FlowRule.candidate_set.
    candidate_set: Optional[str] = None
    rollout_stage: Optional[str] = None

    def is_valid(self) -> bool:
        return bool(self.resource) and bool(self.limit_app) and self.strategy in (
            C.AUTHORITY_WHITE,
            C.AUTHORITY_BLACK,
        )

    def origins(self) -> List[str]:
        return [o.strip() for o in self.limit_app.split(",") if o.strip()]


class AuthorityRuleTensors(NamedTuple):
    resource_row: jax.Array  # int32[AR]
    strategy: jax.Array      # int32[AR]
    origin_ids: jax.Array    # int32[AR, K] padded with _NO_ORIGIN
    rules_by_row: jax.Array  # int32[R, S] rule ids per ClusterNode row

    @property
    def num_rules(self) -> int:
        return self.resource_row.shape[0]

    @property
    def slots(self) -> int:
        return self.rules_by_row.shape[1]


def compile_authority_rules(
    rules: List[AuthorityRule],
    registry: NodeRegistry,
    num_rows: int,
    min_slots: int = 0,
) -> AuthorityRuleTensors:
    valid = [r for r in rules if r.is_valid()]
    ar = _round_up(len(valid), 8)
    k = max(
        MIN_ORIGIN_SLOTS,
        _round_up(max((len(r.origins()) for r in valid), default=1), 4),
    )
    res_row = np.full(ar, -1, np.int32)
    strategy = np.zeros(ar, np.int32)
    origin_ids = np.full((ar, k), _NO_ORIGIN, np.int32)
    by_row: Dict[int, List[int]] = {}

    for i, r in enumerate(valid):
        row = registry.cluster_row(r.resource)
        res_row[i] = row
        strategy[i] = r.strategy
        for j, origin in enumerate(r.origins()[:k]):
            origin_ids[i, j] = registry.origin_id(origin)
        if row >= 0:
            by_row.setdefault(row, []).append(i)

    # 0 when no rules: the per-slot loop then vanishes at trace time,
    # so rule-free deployments pay nothing for this family (the
    # dropped-index scatters of an empty table still cost ~0.1ms/step
    # per scatter at batch 8192 on TPU). ``min_slots`` is the engine's
    # ratchet: crossing 0 -> 1 slots is a SHAPE change that retraces the
    # fused step, so the engine floors this at the widest slot count it
    # has ever compiled — one retrace when a family is first used, none
    # on later pushes (including dropping back to zero rules).
    s = max(min_slots, max((len(v) for v in by_row.values()), default=0))
    rules_by_row = np.full((num_rows, s), -1, np.int32)
    for row, ids in by_row.items():
        rules_by_row[row, : len(ids)] = ids

    return AuthorityRuleTensors(
        resource_row=jnp.asarray(res_row),
        strategy=jnp.asarray(strategy),
        origin_ids=jnp.asarray(origin_ids),
        rules_by_row=jnp.asarray(rules_by_row),
    )


class AuthorityRuleManager(RuleManager):
    """Wholesale-swap registry (reference: ``AuthorityRuleManager``)."""


class AuthorityVerdict(NamedTuple):
    blocked: jax.Array  # bool[N]
    slot: jax.Array  # int32[N] first-blocking rule slot (-1 = not blocked)


def check_authority(
    rt: AuthorityRuleTensors,
    batch: EntryBatch,
    candidate: jax.Array,  # bool[N]
) -> AuthorityVerdict:
    """Vectorized ``AuthorityRuleChecker.passCheck``."""
    n = batch.size
    blocked = jnp.zeros((n,), bool)
    # First blocking rule slot per request (sequential chain's throw
    # site) for decision attribution; -1 while unblocked.
    first_slot = jnp.full((n,), -1, jnp.int32)
    has_origin = batch.origin_id >= 0

    for k in range(rt.slots):
        rule_id = rt.rules_by_row.at[
            W.oob(batch.cluster_row, rt.rules_by_row.shape[0]), jnp.full((n,), k)
        ].get(mode="fill", fill_value=-1)
        has_rule = rule_id >= 0
        ids = rt.origin_ids.at[W.oob(rule_id, rt.num_rules)].get(
            mode="fill", fill_value=_NO_ORIGIN
        )  # [N, K]
        member = jnp.any(ids == batch.origin_id[:, None], axis=1) & has_origin
        strat = rt.strategy.at[W.oob(rule_id, rt.num_rules)].get(
            mode="fill", fill_value=C.AUTHORITY_WHITE
        )
        ok = jnp.where(strat == C.AUTHORITY_WHITE, member, ~member)
        # Empty-origin requests always pass (reference checker's early out).
        applicable = has_rule & candidate & has_origin
        slot_blocked = applicable & (~ok)
        first_slot = jnp.where(slot_blocked & (~blocked), k, first_slot)
        blocked = blocked | slot_blocked

    return AuthorityVerdict(blocked=blocked, slot=first_slot)

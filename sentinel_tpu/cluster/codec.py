"""Binary TLV wire protocol (reference: ``cluster-common:`` request/response
entities + ``codec/`` writer/decoder registries — SURVEY.md §2.11).

Frame: big-endian ``u16`` length prefix, then the body.
Request body:  ``xid:i32 | type:u8 | entity``.
Response body: ``xid:i32 | type:u8 | status:i8 | entity``.

Entities:
  * PING request: ``u8 len | namespace utf-8``; response: empty.
  * FLOW request: ``flowId:i64 | count:i32 | priority:u8``;
    response: ``remaining:i32 | waitMs:i32`` (``FlowTokenResponseData``).
  * PARAM_FLOW request: ``flowId:i64 | count:i32 | nparams:u16 | params``
    with each param type-tagged (``u8``: 0=int/1=str/2=bool/3=float);
    response: empty.
"""

from __future__ import annotations

import struct
from typing import List, NamedTuple, Optional, Sequence, Tuple

from sentinel_tpu.cluster.constants import MSG_FLOW, MSG_PARAM_FLOW, MSG_PING

_LEN = struct.Struct(">H")
_REQ_HEAD = struct.Struct(">iB")
_RESP_HEAD = struct.Struct(">iBb")
_FLOW_REQ = struct.Struct(">qiB")
_FLOW_RESP = struct.Struct(">ii")

PARAM_INT = 0
PARAM_STR = 1
PARAM_BOOL = 2
PARAM_FLOAT = 3


class Request(NamedTuple):
    xid: int
    msg_type: int
    entity: bytes


class Response(NamedTuple):
    xid: int
    msg_type: int
    status: int
    entity: bytes


def frame(body: bytes) -> bytes:
    if len(body) > 0xFFFF:
        raise ValueError(f"frame body too large: {len(body)} bytes")
    return _LEN.pack(len(body)) + body


def encode_request(xid: int, msg_type: int, entity: bytes) -> bytes:
    return frame(_REQ_HEAD.pack(xid, msg_type) + entity)


def encode_response(xid: int, msg_type: int, status: int, entity: bytes = b"") -> bytes:
    return frame(_RESP_HEAD.pack(xid, msg_type, status) + entity)


def decode_request(body: bytes) -> Request:
    xid, msg_type = _REQ_HEAD.unpack_from(body)
    return Request(xid, msg_type, body[_REQ_HEAD.size:])


def decode_response(body: bytes) -> Response:
    xid, msg_type, status = _RESP_HEAD.unpack_from(body)
    return Response(xid, msg_type, status, body[_RESP_HEAD.size:])


class FrameReader:
    """Incremental length-field frame splitter (Netty frame decoder analog)."""

    def __init__(self):
        self._buf = bytearray()

    def feed(self, data: bytes) -> List[bytes]:
        self._buf.extend(data)
        frames = []
        while True:
            if len(self._buf) < _LEN.size:
                break
            (length,) = _LEN.unpack_from(self._buf)
            if len(self._buf) < _LEN.size + length:
                break
            frames.append(bytes(self._buf[_LEN.size:_LEN.size + length]))
            del self._buf[:_LEN.size + length]
        return frames


# -- entities -----------------------------------------------------------------


def encode_ping(namespace: str) -> bytes:
    raw = namespace.encode("utf-8")[:255]
    return bytes([len(raw)]) + raw


def decode_ping(entity: bytes) -> str:
    n = entity[0] if entity else 0
    return entity[1:1 + n].decode("utf-8")


def encode_flow_request(flow_id: int, count: int, prioritized: bool) -> bytes:
    return _FLOW_REQ.pack(flow_id, count, 1 if prioritized else 0)


def decode_flow_request(entity: bytes) -> Tuple[int, int, bool]:
    flow_id, count, prio = _FLOW_REQ.unpack_from(entity)
    return flow_id, count, bool(prio)


def encode_flow_response(remaining: int, wait_ms: int) -> bytes:
    return _FLOW_RESP.pack(remaining, wait_ms)


def decode_flow_response(entity: bytes) -> Tuple[int, int]:
    if len(entity) < _FLOW_RESP.size:
        return 0, 0
    return _FLOW_RESP.unpack_from(entity)


def encode_params(params: Sequence) -> bytes:
    out = [struct.pack(">H", len(params))]
    for p in params:
        if isinstance(p, bool):
            out.append(struct.pack(">BB", PARAM_BOOL, 1 if p else 0))
        elif isinstance(p, int):
            out.append(struct.pack(">Bq", PARAM_INT, p))
        elif isinstance(p, float):
            out.append(struct.pack(">Bd", PARAM_FLOAT, p))
        else:
            # u16 length field: clamp pathological values (identity of a
            # >64KB param value degrades to its prefix, which is the same
            # bounded-key-space stance the param tables already take).
            raw = str(p).encode("utf-8")[:0xFFF0]
            out.append(struct.pack(">BH", PARAM_STR, len(raw)) + raw)
    return b"".join(out)


def decode_params(entity: bytes, offset: int = 0) -> Tuple[list, int]:
    (n,) = struct.unpack_from(">H", entity, offset)
    offset += 2
    params: list = []
    for _ in range(n):
        (tag,) = struct.unpack_from(">B", entity, offset)
        offset += 1
        if tag == PARAM_BOOL:
            (v,) = struct.unpack_from(">B", entity, offset)
            params.append(bool(v))
            offset += 1
        elif tag == PARAM_INT:
            (v,) = struct.unpack_from(">q", entity, offset)
            params.append(v)
            offset += 8
        elif tag == PARAM_FLOAT:
            (v,) = struct.unpack_from(">d", entity, offset)
            params.append(v)
            offset += 8
        else:
            (length,) = struct.unpack_from(">H", entity, offset)
            offset += 2
            params.append(entity[offset:offset + length].decode("utf-8"))
            offset += length
    return params, offset


def encode_param_flow_request(flow_id: int, count: int, params: Sequence) -> bytes:
    return struct.pack(">qi", flow_id, count) + encode_params(params)


def decode_param_flow_request(entity: bytes) -> Tuple[int, int, list]:
    flow_id, count = struct.unpack_from(">qi", entity)
    params, _ = decode_params(entity, 12)
    return flow_id, count, params

"""The fused admission/commit step — sentinel-tpu's "forward pass".

This is the TPU-native analog of the reference's slot-chain walk
(SURVEY.md §3.1): one jitted pure function
``(state, rules, batch, now) -> (state', decisions)`` that

  1. rotates the shared sliding windows to ``now`` (lazy bucket reset,
     branchless — ``ops/window.py``),
  2. runs the rule slots (authority → system → param → flow → degrade, same
     order as the reference chain; M0 wires flow, the rest join in M1),
  3. commits statistics exactly like ``StatisticSlot``: thread-count + pass
     on admit, block counts on reject — *after* the rule verdicts, which is
     the reference's crucial control-flow inversion ("statistics slot wraps
     the rule slots").

Every entry commits to up to four node rows (DefaultNode, ClusterNode,
origin StatisticNode, global ENTRY_NODE for inbound traffic), matching the
reference's node fan-out.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from sentinel_tpu.core import constants as C
from sentinel_tpu.core.batch import Decisions, EntryBatch, ExitBatch
from sentinel_tpu.core.registry import ENTRY_ROW
from sentinel_tpu.models import authority as A
from sentinel_tpu.models import degrade as D
from sentinel_tpu.models import flow as F
from sentinel_tpu.models import param_flow as P
from sentinel_tpu.models import system as Y
from sentinel_tpu.ops import window as W

SPEC_1S = W.WindowSpec(C.SECOND_WINDOW_MS, C.SECOND_BUCKETS)
SPEC_60S = W.WindowSpec(C.MINUTE_WINDOW_MS, C.MINUTE_BUCKETS)


class SentinelState(NamedTuple):
    """All mutable device state. One pytree, donated every step."""

    w1: W.Window          # 1s / 2-bucket window over all node rows
    w60: W.Window         # 60s / 60-bucket window (metric log source)
    cur_threads: jax.Array  # int32[R] live concurrency gauge per row
    flow: F.FlowState
    degrade: D.DegradeState
    param: P.ParamFlowState
    sys_signals: jax.Array  # f32[2] host-sampled [load1, cpu_usage]


class RulePack(NamedTuple):
    """All compiled rule tensors (host-rebuilt wholesale on config push)."""

    flow: F.FlowRuleTensors
    degrade: D.DegradeRuleTensors
    authority: A.AuthorityRuleTensors
    system: Y.SystemRuleTensors
    param: P.ParamRuleTensors


def make_state(num_rows: int, flow_rules: int, now_ms: int,
               degrade: D.DegradeState = None,
               param: P.ParamFlowState = None) -> SentinelState:
    if degrade is None:
        dt, di = D.compile_degrade_rules([], None, num_rows)
        degrade = D.make_degrade_state(dt, di)
    if param is None:
        param = P.make_param_state(0)
    return SentinelState(
        w1=W.make_window(num_rows, SPEC_1S),
        w60=W.make_window(num_rows, SPEC_60S),
        cur_threads=jnp.zeros((num_rows,), jnp.int32),
        flow=F.make_flow_state(flow_rules, now_ms),
        degrade=degrade,
        param=param,
        sys_signals=jnp.full((Y.NUM_SIGNALS,), -1.0, jnp.float32),
    )


def _target_rows(cluster_row, dn_row, origin_row, entry_in):
    """[N, 4] node rows each request commits to (−1 entries are dropped)."""
    entry_row = jnp.where(entry_in, ENTRY_ROW, -1)
    return jnp.stack([dn_row, cluster_row, origin_row, entry_row], axis=1)


def _commit(win: W.Window, now_ms, rows4, event, values4, spec) -> W.Window:
    n4 = rows4.reshape(-1)
    v4 = values4.reshape(-1)
    ev = jnp.full_like(n4, event)
    return W.add_events(win, now_ms, n4, ev, v4, spec)


def entry_step(
    state: SentinelState,
    rules: RulePack,
    batch: EntryBatch,
    now_ms: jax.Array,
    extra_pass=None,
) -> Tuple[SentinelState, Decisions]:
    """One admission step. ``extra_pass`` (int32[R], optional) is the other
    devices' pass-count contribution for cluster-mode rules — supplied by
    the pod-parallel wrapper (``parallel/cluster.py``) from a ``psum``."""
    now_ms = jnp.asarray(now_ms, jnp.int64)
    w1 = W.rotate(state.w1, now_ms, SPEC_1S)
    # The minute window only needs its CURRENT bucket fresh for commits;
    # readers (BBR check below, host metric sealing) mask staleness
    # themselves. Full rotation would sweep 60x the bytes per step.
    w60 = W.rotate_current(state.w60, now_ms, SPEC_60S)

    valid = batch.cluster_row >= 0
    reason = jnp.where(valid, C.BlockReason.PASS, -1).astype(jnp.int32)
    # Remote token-server rejections arrive pre-decided: record the block
    # (StatisticSlot catches the cluster FlowException the same way) and
    # skip every local slot.
    blocked = valid & batch.pre_blocked
    reason = jnp.where(blocked, C.BlockReason.FLOW, reason)

    # --- rule slots (order mirrors the reference chain: authority →
    # system → param-flow → flow → degrade) --------------------------------
    auth_blocked = A.check_authority(rules.authority, batch, valid & (~blocked))
    reason = jnp.where(valid & (~blocked) & auth_blocked, C.BlockReason.AUTHORITY, reason)
    blocked = blocked | auth_blocked

    cand = valid & (~blocked)
    sys_blocked = Y.check_system(rules.system, state.sys_signals, w1, w60,
                                 state.cur_threads, batch, cand, now_ms)
    reason = jnp.where(cand & sys_blocked, C.BlockReason.SYSTEM, reason)
    blocked = blocked | sys_blocked

    cand = valid & (~blocked)
    pv = P.check_param_flow(rules.param, state.param, batch, now_ms, cand)
    reason = jnp.where(cand & pv.blocked, C.BlockReason.PARAM_FLOW, reason)
    blocked = blocked | pv.blocked

    fv = F.check_flow(rules.flow, state.flow, w1, state.cur_threads, batch, now_ms, blocked,
                      extra_pass=extra_pass)
    reason = jnp.where(valid & (~blocked) & fv.blocked, C.BlockReason.FLOW, reason)
    blocked = blocked | fv.blocked

    dv = D.check_degrade(rules.degrade, state.degrade, batch, now_ms, valid & (~blocked))
    reason = jnp.where(valid & (~blocked) & dv.blocked, C.BlockReason.DEGRADE, reason)
    blocked = blocked | dv.blocked

    # --- StatisticSlot commit --------------------------------------------
    rows4 = _target_rows(batch.cluster_row, batch.dn_row, batch.origin_row, batch.entry_in)
    admit = valid & (~blocked)
    pass_counts = jnp.where(admit, batch.count, 0)
    block_counts = jnp.where(valid & blocked, batch.count, 0)
    pass4 = jnp.broadcast_to(pass_counts[:, None], rows4.shape)
    block4 = jnp.broadcast_to(block_counts[:, None], rows4.shape)

    w1 = _commit(w1, now_ms, rows4, C.MetricEvent.PASS, pass4, SPEC_1S)
    w1 = _commit(w1, now_ms, rows4, C.MetricEvent.BLOCK, block4, SPEC_1S)
    w60 = _commit(w60, now_ms, rows4, C.MetricEvent.PASS, pass4, SPEC_60S)
    w60 = _commit(w60, now_ms, rows4, C.MetricEvent.BLOCK, block4, SPEC_60S)

    thread_inc = jnp.broadcast_to(jnp.where(admit, 1, 0)[:, None], rows4.shape).reshape(-1)
    cur_threads = state.cur_threads.at[
        W.oob(rows4.reshape(-1), state.cur_threads.shape[0])
    ].add(thread_inc, mode="drop")

    wait_us = jnp.where(admit, jnp.maximum(fv.wait_us, pv.wait_us), 0)

    new_state = SentinelState(w1=w1, w60=w60, cur_threads=cur_threads,
                              flow=fv.state, degrade=dv.state, param=pv.state,
                              sys_signals=state.sys_signals)
    return new_state, Decisions(reason=reason, wait_us=wait_us)


def exit_step(
    state: SentinelState,
    rules: RulePack,
    batch: ExitBatch,
    now_ms: jax.Array,
) -> SentinelState:
    """Completion commit: RT + success/exception, thread decrement.

    Mirrors ``StatisticSlot.exit`` + ``Tracer`` exception accounting
    (SURVEY.md §3.1 "LeapArray write #2").
    """
    now_ms = jnp.asarray(now_ms, jnp.int64)
    w1 = W.rotate(state.w1, now_ms, SPEC_1S)
    w60 = W.rotate_current(state.w60, now_ms, SPEC_60S)

    valid = batch.cluster_row >= 0
    rows4 = _target_rows(batch.cluster_row, batch.dn_row, batch.origin_row, batch.entry_in)

    succ = jnp.where(valid & batch.success, batch.count, 0)
    exc = jnp.where(valid & batch.error, batch.count, 0)
    rt = jnp.where(valid & batch.success, batch.rt_ms, 0)
    succ4 = jnp.broadcast_to(succ[:, None], rows4.shape)
    exc4 = jnp.broadcast_to(exc[:, None], rows4.shape)
    rt4 = jnp.broadcast_to(rt[:, None], rows4.shape)

    for win, spec, name in ((w1, SPEC_1S, "w1"), (w60, SPEC_60S, "w60")):
        win = _commit(win, now_ms, rows4, C.MetricEvent.SUCCESS, succ4, spec)
        win = _commit(win, now_ms, rows4, C.MetricEvent.EXCEPTION, exc4, spec)
        win = _commit(win, now_ms, rows4, C.MetricEvent.RT, rt4, spec)
        win = W.add_min_rt(win, now_ms, rows4.reshape(-1),
                           jnp.where((valid & batch.success)[:, None], rt4, W.MIN_RT_EMPTY).reshape(-1),
                           spec)
        if name == "w1":
            w1 = win
        else:
            w60 = win

    thread_dec = jnp.broadcast_to(jnp.where(valid, -1, 0)[:, None], rows4.shape).reshape(-1)
    cur_threads = state.cur_threads.at[
        W.oob(rows4.reshape(-1), state.cur_threads.shape[0])
    ].add(thread_dec, mode="drop")

    degrade = D.feed_degrade(rules.degrade, state.degrade, batch, now_ms)
    param = P.feed_param_exit(rules.param, state.param, batch)

    return state._replace(w1=w1, w60=w60, cur_threads=cur_threads,
                          degrade=degrade, param=param)

"""Cluster role management (reference: ``core:cluster/ClusterStateManager.java``
— SURVEY.md §2.4): an instance is NOT_STARTED, a token CLIENT, or an
(embedded) token SERVER; the ops plane can flip roles at runtime.
"""

from __future__ import annotations

import threading
from typing import Optional

CLUSTER_NOT_STARTED = -1
CLUSTER_CLIENT = 0
CLUSTER_SERVER = 1


class ClusterStateManager:
    def __init__(self):
        self._lock = threading.RLock()
        self.mode = CLUSTER_NOT_STARTED
        self.token_client = None
        self.token_server = None
        self.last_modified = 0
        # Ops-plane staged configs (reference: ClusterClientConfigManager /
        # ClusterServerConfigManager — dynamic properties the dashboard
        # writes BEFORE flipping the mode via setClusterMode).
        # requestTimeout is in MILLISECONDS (reference units).
        self.client_config = {"serverHost": None, "serverPort": None,
                              "requestTimeout": 200, "namespace": "default"}
        self.server_config = {"port": 0, "maxAllowedQps": 30000.0}
        # Cluster rules survive server re-applies (config changes rebuild
        # the service, not the rule set — reference rule managers are
        # namespace-keyed properties independent of the transport).
        self._server_rules = None

    def server_rules(self):
        from sentinel_tpu.cluster.rules import ClusterFlowRuleManager

        with self._lock:
            if self._server_rules is None:
                self._server_rules = ClusterFlowRuleManager()
            return self._server_rules

    def apply_mode(self, mode: int) -> None:
        """Flip role from the staged configs (``setClusterMode`` handler).

        Reference: ``ModifyClusterModeCommandHandler`` →
        ``ClusterStateManager.applyState``.
        """
        import time as _time

        with self._lock:
            if mode == CLUSTER_CLIENT:
                host = self.client_config.get("serverHost")
                port = self.client_config.get("serverPort")
                if not host or not port:
                    raise ValueError(
                        "client config not set: POST cluster/client/modifyConfig first")
                tv = self.client_config.get("requestTimeout")
                timeout_s = (200.0 if tv is None else float(tv)) / 1000.0
                self.set_to_client(str(host), int(port),
                                   str(self.client_config.get("namespace")
                                       or "default"),
                                   request_timeout_s=timeout_s)
            elif mode == CLUSTER_SERVER:
                from sentinel_tpu.cluster.token_service import DefaultTokenService

                service = DefaultTokenService(
                    rules=self.server_rules(),
                    max_allowed_qps=float(self.server_config["maxAllowedQps"]))
                self.set_to_server(port=int(self.server_config["port"]),
                                   service=service)
            elif mode == CLUSTER_NOT_STARTED:
                self.stop()
            else:
                raise ValueError(f"invalid mode {mode}")
            self.last_modified = int(_time.time() * 1000)

    def set_to_client(self, host: str, port: int,
                      namespace: str = "default",
                      request_timeout_s: float = 2.0) -> None:
        """Flip to CLIENT: connect to a remote token server.

        The old role is torn down first (a staticly-configured port must be
        free for re-binds); if starting the new role fails the manager drops
        to NOT_STARTED rather than reporting a role that isn't running.
        """
        from sentinel_tpu.cluster.client import ClusterTokenClient

        with self._lock:
            self._teardown()
            self.mode = CLUSTER_NOT_STARTED
            self.token_client = ClusterTokenClient(
                host, port, namespace,
                request_timeout_s=request_timeout_s).start()
            self.mode = CLUSTER_CLIENT

    def set_to_server(self, host: str = "0.0.0.0", port: int = 0,
                      service=None) -> "object":
        """Flip to SERVER: run the embedded token server; returns it.

        Failure semantics mirror :meth:`set_to_client`: a failed bind leaves
        the manager honestly NOT_STARTED, never claiming a dead role.
        """
        from sentinel_tpu.cluster.server import ClusterTokenServer

        with self._lock:
            self._teardown()
            self.mode = CLUSTER_NOT_STARTED
            self.token_server = ClusterTokenServer(
                service=service, host=host, port=port).start()
            self.mode = CLUSTER_SERVER
            return self.token_server

    def _teardown(self):
        if self.token_client is not None:
            self.token_client.stop()
            self.token_client = None
        if self.token_server is not None:
            self.token_server.stop()
            self.token_server = None

    def stop(self) -> None:
        with self._lock:
            self._teardown()
            self.mode = CLUSTER_NOT_STARTED

    def client_if_active(self):
        """The connected token client, or None (drives the fallback path)."""
        with self._lock:
            if (self.mode == CLUSTER_CLIENT and self.token_client is not None
                    and self.token_client.is_connected()):
                return self.token_client
        return None

"""LLM inference gateway adapter (ISSUE 17): fronts a (mock)
SSE-streaming inference backend with the TPS admission family.

The choreography is the one every real token-metered gateway runs:

1. ``complete()`` opens a **streaming reservation**
   (``engine.stream_open``) for the request's estimated output budget —
   a blocked open is the 429, returned before a single backend token is
   generated.
2. Each generated chunk ticks the reservation down
   (``engine.stream_tick``) — output beyond the reserved window budget
   pays live, so a runaway generation feels backpressure mid-stream
   instead of after the fact.
3. ``close`` (or client abandonment -> ``abort``) reconciles: the
   unstreamed remainder of the reservation is released as expiring
   credit, so estimates never leak budget past the window they were
   debited into (docs/SEMANTICS.md "Streaming-reservation bound").

``MockInferenceServer`` is the deterministic stand-in backend: one
(seed, request_id) pair names one SSE event stream forever, so the demo
and its tests replay bit-identically. ``run_demo`` drives the gateway
shape end-to-end in-sim: ``hetero_cost`` streamed-generation load
through the production engine with the adaptive loop retuning per-model
``tokensPerSecond`` (shadow -> canary -> promote), then asserts the
ledger drained and nothing was silently dropped.
"""

from __future__ import annotations

import json
import zlib
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional

from sentinel_tpu.core.exceptions import BlockException

SSE_DATA_PREFIX = "data: "
SSE_DONE = "data: [DONE]"


def _sse(payload: Dict) -> str:
    return SSE_DATA_PREFIX + json.dumps(payload, sort_keys=True)


class MockInferenceServer:
    """Deterministic SSE-style mock backend.

    ``stream(request_id, model, max_tokens)`` yields chunked SSE data
    lines; the generation length is a pure function of (seed,
    request_id, model) via crc32 — no RNG object, no wall clock — so a
    replayed demo sees byte-identical backend behavior."""

    def __init__(self, seed: int = 0, chunk_tokens: int = 8):
        self.seed = int(seed)
        self.chunk_tokens = max(1, int(chunk_tokens))

    def generation_tokens(self, request_id: str, model: str,
                          max_tokens: int) -> int:
        """How many tokens this request actually generates: 50%..100%
        of ``max_tokens``, deterministic per (seed, request, model)."""
        h = zlib.crc32(f"{self.seed}:{request_id}:{model}".encode())
        frac = 0.5 + (h % 1000) / 2000.0
        return max(1, int(max_tokens * frac))

    def stream(self, request_id: str, model: str,
               max_tokens: int) -> Iterator[str]:
        total = self.generation_tokens(request_id, model, max_tokens)
        sent = 0
        while sent < total:
            n = min(self.chunk_tokens, total - sent)
            sent += n
            yield _sse({"id": request_id, "model": model, "tokens": n,
                        "index": sent})
        yield SSE_DONE


@dataclass
class CompletionResult:
    """One gateway request's outcome — the reconciliation receipt."""

    request_id: str
    model: str
    admitted: bool
    blocked_reason: str = ""
    streamed_tokens: int = 0
    released_tokens: int = 0   # unreconciled reservation given back
    aborted: bool = False
    events: List[str] = field(default_factory=list)


class LLMGateway:
    """The admission front for a streaming inference backend.

    Every request is a reservation lifecycle against the engine's TPS
    family; the gateway never drops a stream silently — every open
    either blocks (counted) or ends in exactly one close/abort
    (reconciled)."""

    def __init__(self, engine=None, server: Optional[
            MockInferenceServer] = None, tick_tokens: int = 0):
        if engine is None:
            import sentinel_tpu as st
            engine = st.get_engine()
        self.engine = engine
        self.server = server or MockInferenceServer()
        # 0 = tick per backend chunk (the honest cadence); >0 batches
        # ticks to amortize host calls on very chatty backends.
        self.tick_tokens = max(0, int(tick_tokens))

    def complete(self, request_id: str, model: str,
                 max_tokens: int = 0,
                 tenant: str = "default",
                 abandon_after_tokens: Optional[int] = None,
                 collect_events: bool = False) -> CompletionResult:
        """Run one streamed completion under admission.

        ``abandon_after_tokens`` models the impatient client: the
        stream aborts once that many tokens have streamed, leaving the
        rest of the reservation for ``stream_close(aborted=True)`` to
        reconcile — the over-admission-bound path."""
        eng = self.engine
        result = CompletionResult(request_id=request_id, model=model,
                                  admitted=False)
        try:
            eng.stream_open(request_id, model,
                            max_tokens if max_tokens > 0 else None,
                            tenant=tenant)
        except BlockException as ex:
            result.blocked_reason = type(ex).__name__
            return result
        result.admitted = True
        pending = 0
        try:
            for line in self.server.stream(request_id, model,
                                           max_tokens or 128):
                if collect_events:
                    result.events.append(line)
                if line == SSE_DONE:
                    break
                tokens = json.loads(line[len(SSE_DATA_PREFIX):])["tokens"]
                pending += int(tokens)
                if self.tick_tokens and pending < self.tick_tokens:
                    continue
                try:
                    eng.stream_tick(request_id, pending)
                finally:
                    result.streamed_tokens += pending
                    pending = 0
                if abandon_after_tokens is not None \
                        and result.streamed_tokens >= abandon_after_tokens:
                    result.aborted = True
                    break
        except BlockException:
            # Mid-stream backpressure: the window refused the overflow
            # tokens — surface it as an abort, reconciling what DID
            # stream. (A real gateway would retry-after instead.)
            result.aborted = True
        finally:
            if pending and not result.aborted:
                try:
                    eng.stream_tick(request_id, pending)
                    result.streamed_tokens += pending
                except BlockException:
                    result.aborted = True
            result.released_tokens = eng.stream_close(
                request_id, aborted=result.aborted)
        return result


def run_demo(seconds: int = 120, seed: int = 0,
             streams_per_s: float = 0.4,
             abandon_rate: float = 0.2) -> Dict:
    """The end-to-end acceptance drill (ISSUE 17): hetero_cost-shaped
    streamed-generation load through the production engine in-sim, the
    adaptive loop retuning per-model ``tokensPerSecond``
    (shadow -> canary -> promote). Returns a summary dict whose
    invariants the tests pin:

    * ``ledgerDrained`` — zero outstanding reservation tokens at end.
    * ``silentDrops`` — opened - closed - aborted - active == 0 always.
    * ``tpsPromotes`` — >= 1 promoted per-model TPS retune in-sim.
    """
    from sentinel_tpu.simulator.lab import default_targets
    from sentinel_tpu.simulator.replay import (
        DEFAULT_ADAPTIVE_KNOBS,
        ReplayEngine,
    )
    from sentinel_tpu.simulator.scenarios import hetero_cost

    trace = hetero_cost(seconds=seconds, seed=seed,
                        streams_per_s=streams_per_s,
                        abandon_rate=abandon_rate)
    result = ReplayEngine(
        trace,
        adaptive=dict(DEFAULT_ADAPTIVE_KNOBS),
        targets=[t for t in default_targets(trace)
                 if t.resource.startswith("llm:")],
    ).run()
    st = result.streams
    opened = st.get("opened", 0)
    accounted = (st.get("closed", 0) + st.get("aborted", 0)
                 + st.get("active", 0))
    tps_promotes = [
        ev for ev in result.decisions if ev.get("kind") == "promote"
        and any(ch.get("resource", "").startswith("llm:")
                for ch in ev.get("changes", ()))]
    return {
        "seconds": result.seconds,
        "verdictSha256": result.verdict_sha256,
        "objective": result.objective_vector(),
        "streams": dict(st),
        "ledgerDrained": st.get("outstandingTokens", 0) == 0
        and st.get("active", 0) == 0,
        "silentDrops": opened - accounted,
        "tpsPromotes": len(tps_promotes),
        "finalCounts": {res: cnt
                        for res, cnt in result.final_counts.items()
                        if res.startswith("llm:")},
    }

"""Dynamic slot-table admission: a bounded device hot set (ROADMAP 1).

The fused step is sized for ONE fixed HBM tensor — ``capacity`` rows,
compiled once. The reference answers unbounded namespaces by refusing
registrations past the cap; PR 19's registry overflow made that refusal
loud, but a refused resource still loses ALL protection. This module
makes a million-resource namespace *survivable*: the device tensor
shrinks to a small slot BUDGET holding only the live hot set, and the
host-side :class:`SlotTable` maps resources into it dynamically —

* **admit**: a cold resource claims a free slot on first touch (and on
  rebalance, when the population telescope ranks it above an
  incumbent). Admission grafts any previously spilled window rows back
  EXACTLY (the flowId-row idiom of ``restore_cluster_checkpoint``,
  generalized from cluster flow windows to every per-resource row).
* **evict**: a slot steal spills the victim's per-row columns host-side
  into a :class:`SpillRecord` — 1s/60s windows, staged second,
  concurrency gauge, occupy borrows, cumulative telemetry — then zeroes
  the columns and bumps the slot's GENERATION stamp, so a reused slot
  can never leak the evicted resource's series.
* **cold tail**: resources past the budget degrade LOUDLY, never raise:
  leaseable-ruled resources keep HOST-EXACT admission through their
  existing ``LocalLease``/``WideLease`` (eviction costs stats
  continuity, never verdict fidelity); device-only-ruled cold resources
  pass unenforced behind a counter; unruled cold resources pass behind
  a counter. Cold pass/block/exit tallies fold back into the device
  totals at rehydration — exact counter conservation.
* **pins**: resources named by any compiled rule (and a rollout
  candidate's device spec) are PINNED hot — the compiled rule tensors
  target slot indices, so evicting a ruled resource would apply its
  rule to the slot's successor. Only unruled resources churn.

Steal/admit decisions ride the once-per-second spill fold
(:meth:`on_spill`), fed by the telescope's top-k/churn feed, behind the
standard freeze-gate envelope (manual > churn-alarm > telemetry-stale).
Chaos seams ``slots.evict.storm`` (evict every unpinned occupant this
cycle) and ``slots.spill.torn`` (tear the spill record: the victim
rehydrates cold, loudly) certify the machinery; every transition emits
through ``event_sink`` for the ``slot_conservation`` invariant checker
(chaos/invariants.py).

Concurrency protocol (the one that matters):

* ``gate`` (a plain mutex) owns the resource->(slot, generation) map.
  The map dict is replaced WHOLESALE under ``gate``; lock-free readers
  (entry() translation) see either the old or the new mapping, never a
  torn one. Leased-path committer enqueues re-translate UNDER ``gate``
  immediately before enqueue, so a commit can never be queued for a
  slot whose tenancy already changed.
* a steal runs: swap the map under ``gate`` (victims out, targets
  reserved) -> flush the stats committer WITHOUT any engine lock (a
  flush under ``engine._lock`` deadlocks against the background flush
  thread) -> state surgery under ``engine._lock`` (spill, zero, graft)
  -> publish the admits under ``gate``.
* lock ORDER is ``engine._lock`` -> ``gate``; never the reverse.
* evicted slots DRAIN (``_draining``) until the surgery zeroes them;
  first-touch admission only ever claims slots from ``_free``, so an
  entry can never commit into a column still carrying the victim's
  data.

No wall-clock reads in this module (test_lint gate): every timestamp is
the engine timebase, passed in by the caller.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Set, Tuple

import numpy as np

from sentinel_tpu.core import constants as C
from sentinel_tpu.core.registry import (
    ENTRY_ROW,
    KIND_CLUSTER,
    ROOT_ROW,
    NodeMeta,
)

# Slots 0/1 mirror the registry's fixed rows (machine-root, the global
# ENTRY_NODE — ops/step.py hardcodes ENTRY_ROW for inbound commits), so
# dynamic tenancy starts at 2.
FIRST_SLOT = 2

# Synthetic meta kind for an unoccupied slot: never matches KIND_*, so
# every consumer's ``kind != KIND_CLUSTER`` skip naturally drops it.
KIND_FREE = -1

# EntryHandle.slot_gen sentinel: the entry was served on the COLD path
# (no device commit — its exit tallies host-side, never on-device).
COLD_GEN = -2


class SpillRecord:
    """One evicted resource's per-row state, host-side, numpy.

    Geometry stamps (window bucket starts, second/occupy stamps) are
    captured WITH the data so rehydration can graft each bucket exactly
    iff it is still current — the ``restore_cluster_checkpoint`` idiom:
    ``old_starts[i] != new_starts[i]`` means the bucket rotated while
    the resource was cold, and its grants expired with it (that natural
    expiry is the "grants-since-spill" conservation margin
    docs/SEMANTICS.md proves)."""

    __slots__ = (
        "resource", "generation", "evicted_ms",
        "w1_counts", "w1_min_rt", "w1_starts",
        "w60_counts", "w60_min_rt", "w60_starts",
        "sec_counts", "sec_min_rt", "sec_stamp",
        "cur_threads", "occupied_next", "occupied_stamp",
        "tel_block", "tel_hist", "tel_totals",
        "spilled_pass",
    )

    def __init__(self, resource: str, generation: int, evicted_ms: int):
        self.resource = resource
        self.generation = generation
        self.evicted_ms = evicted_ms


class SlotTable:
    """Host-side admission cache: live hot set -> bounded device slots."""

    def __init__(self, engine, budget: int):
        from sentinel_tpu.core.config import config as _cfg

        if budget < FIRST_SLOT + 1:
            raise ValueError(
                f"slot budget {budget} leaves no dynamic slots "
                f"(rows 0..{FIRST_SLOT - 1} are reserved)")
        self.engine = engine
        self.budget = int(budget)
        self.max_steals = _cfg.slots_max_steals()
        self.hysteresis_pct = _cfg.slots_hysteresis_pct()
        self.spill_max = _cfg.slots_spill_max()
        self.stale_seconds = _cfg.slots_stale_seconds()
        # The commit gate. See the module docstring's protocol.
        self.gate = threading.Lock()
        # resource -> (slot, generation). Replaced wholesale under gate;
        # read lock-free (GIL-atomic attribute + dict get).
        self._hot: Dict[str, Tuple[int, int]] = {}
        self._occupant: List[Optional[str]] = [None] * self.budget
        self._generation: List[int] = [0] * self.budget
        self._free: Set[int] = set(range(FIRST_SLOT, self.budget))
        # Evicted slots awaiting surgery's zeroing — NOT claimable.
        self._draining: Set[int] = set()
        # Resources mid-admission (reserved, mapping not yet published).
        self._admitting: Set[str] = set()
        # Spill store: resource -> SpillRecord, LRU-capped. A dropped
        # record is a bounded, counted loss (the resource rehydrates
        # cold) — never an error.
        self._spill: "OrderedDict[str, SpillRecord]" = OrderedDict()
        # Cold-tail tallies: resource -> int64[NUM_EVENTS] event deltas
        # served host-side while cold; folded into the device totals at
        # rehydration (exact counter conservation). Guarded by ``gate``.
        self._cold: Dict[str, np.ndarray] = {}
        # Freeze envelope (manual > churn-alarm > telemetry-stale).
        self._manual_freeze: Optional[str] = None
        self._observed_last = -1
        self._observed_changed_ms = -1
        self._rebalanced_ms = -1
        # Device-metas cache: rebuilt when occupancy changes; the LIST
        # OBJECT is immutable once built, so a reference captured at a
        # flight-recorder spill is a true tenancy snapshot.
        self._metas_cache: Optional[List[NodeMeta]] = None
        self._metas_version = -1
        self._version = 0
        # stamp_ms -> the device-metas list in force when that flight
        # second spilled: the timeseries history renders PAST seconds
        # with PAST tenancy, so a reused slot's old seconds can never
        # re-attribute to the successor (the generation-leak pin).
        self._stamp_metas: "OrderedDict[int, List[NodeMeta]]" = OrderedDict()
        # Chaos observability: callable(dict) invoked with every
        # admit/evict/rehydrate/late-exit transition (slot_storm wires a
        # History in; None in production — zero overhead).
        self.event_sink: Optional[Callable[[dict], None]] = None
        # Counters (exported as sentinel_tpu_slots_*).
        self.admits_total = 0
        self.evictions_total = 0
        self.rehydrations_total = 0
        self.rehydrations_cold_total = 0
        self.steals_total = 0
        self.storms_total = 0
        self.hot_hits_total = 0
        self.cold_pass_total = 0
        self.cold_block_total = 0
        self.cold_unenforced_total = 0
        self.spill_torn_total = 0
        self.spill_dropped_total = 0
        self.late_exits_total = 0
        self.pin_overflow_total = 0
        self.freezes_total = 0

    # -- translation (the ONLY resource->slot map in the tree) ------------

    def device_row(self, resource: str) -> Optional[int]:
        """The resource's current device slot, or None while cold. The
        single sanctioned translation implementation (test_lint pins
        that no second resource->slot map exists outside this module)."""
        cur = self._hot.get(resource)
        return cur[0] if cur is not None else None

    def current(self, resource: str) -> Optional[Tuple[int, int]]:
        """(slot, generation) of the resource's live tenancy, or None."""
        return self._hot.get(resource)

    def resources(self) -> Dict[str, int]:
        """resource -> slot of the current hot set (ops-plane shape
        parity with ``NodeRegistry.resources``)."""
        return {res: sg[0] for res, sg in self._hot.items()}

    def hot_count(self) -> int:
        return len(self._hot)

    def device_metas(self) -> List[NodeMeta]:
        """Slot-indexed meta view mirroring ``registry.meta``'s shape:
        rows 0/1 are the registry's fixed rows, occupied slots render as
        ClusterNodes of their occupant, free slots as inert KIND_FREE
        rows. Cached per occupancy version; the returned list is never
        mutated after build."""
        cache, ver = self._metas_cache, self._metas_version
        if cache is not None and ver == self._version:
            return cache
        with self.gate:
            if self._metas_cache is not None \
                    and self._metas_version == self._version:
                return self._metas_cache
            reg = self.engine.registry
            root = NodeMeta(row=ROOT_ROW, kind=reg.meta[ROOT_ROW].kind,
                            resource=reg.meta[ROOT_ROW].resource)
            entry = NodeMeta(row=ENTRY_ROW, kind=reg.meta[ENTRY_ROW].kind,
                             resource=reg.meta[ENTRY_ROW].resource,
                             parent_row=ROOT_ROW)
            metas: List[NodeMeta] = [root, entry]
            for slot in range(FIRST_SLOT, self.budget):
                res = self._occupant[slot]
                if res is None:
                    metas.append(NodeMeta(row=slot, kind=KIND_FREE))
                    continue
                src = reg.get_cluster_row(res)
                src_meta = reg.meta[src] if src is not None else None
                metas.append(NodeMeta(
                    row=slot, kind=KIND_CLUSTER, resource=res,
                    parent_row=ROOT_ROW,
                    entry_type=(src_meta.entry_type if src_meta
                                else int(C.EntryType.OUT)),
                    resource_type=(src_meta.resource_type if src_meta
                                   else int(C.ResourceType.COMMON))))
                root.children.append(slot)
            self._metas_cache = metas
            self._metas_version = self._version
            return metas

    def rule_registry_view(self) -> "_RuleRegistryView":
        """The registry facade handed to the rule compilers: resource
        rows resolve through THIS table (a cold resource compiles to row
        -1 = inert rule slot), id interning passes through to the real
        registry. Pins keep ruled resources hot, so inert compiles only
        happen past a pin overflow — which is counted and logged."""
        return _RuleRegistryView(self)

    # -- flight-second tenancy snapshots (generation-leak defense) --------

    def remember_metas(self, stamp_ms: int, metas: List[NodeMeta]) -> None:
        """Pin the tenancy view a flight second spilled under, keyed by
        its stamp; the timeseries history renders with it forever after."""
        ts = getattr(self.engine, "timeseries", None)
        keep = max(64, getattr(ts, "retention_seconds", 0) or 64)
        with self.gate:
            self._stamp_metas[int(stamp_ms)] = metas
            while len(self._stamp_metas) > keep:
                self._stamp_metas.popitem(last=False)

    def recall_metas(self, stamp_ms: int) -> Optional[List[NodeMeta]]:
        return self._stamp_metas.get(int(stamp_ms))

    # -- freeze envelope ---------------------------------------------------

    def freeze(self, reason: str) -> None:
        """Manual steal freeze (ops ``slots op=freeze``): rebalance
        steals stop; first-touch free-slot admits continue (freezing
        those would turn a drill into an outage for new resources)."""
        self._manual_freeze = str(reason) or "manual"
        self.freezes_total += 1

    def thaw(self) -> None:
        self._manual_freeze = None

    def freeze_reason(self, now_ms: int) -> Optional[str]:
        """Why steals are frozen right now, else None. Precedence:
        manual > churn-alarm > telemetry-stale (the standard envelope —
        an operator hold beats automation, a firing cardinality alarm
        means the top-k feed is churning too fast to trust for steals,
        and a stale telescope means the feed itself stopped moving)."""
        if self._manual_freeze is not None:
            return f"manual: {self._manual_freeze}"
        population = getattr(self.engine, "population", None)
        if population is None or not population.enabled:
            return "telemetry-stale: population telescope disabled"
        if population.alarm:
            return "churn-alarm: cardinality alarm firing"
        observed = population.observed_total
        if observed != self._observed_last:
            self._observed_last = observed
            self._observed_changed_ms = now_ms
        elif self._observed_changed_ms >= 0 and now_ms \
                - self._observed_changed_ms > self.stale_seconds * 1000:
            return ("telemetry-stale: population feed unchanged for "
                    f"{(now_ms - self._observed_changed_ms) // 1000}s")
        return None

    # -- cold-tail accounting ---------------------------------------------

    def _cold_tally_locked(self, resource: str, event: int,
                           count: int) -> None:
        vec = self._cold.get(resource)
        if vec is None:
            vec = self._cold[resource] = np.zeros(C.NUM_EVENTS, np.int64)
        vec[event] += count

    def cold_pass(self, resource: str, count: int,
                  unenforced: bool = False) -> None:
        with self.gate:
            self._cold_tally_locked(resource, int(C.MetricEvent.PASS), count)
            self.cold_pass_total += 1
            if unenforced:
                self.cold_unenforced_total += 1

    def cold_block(self, resource: str, count: int) -> None:
        with self.gate:
            self._cold_tally_locked(resource, int(C.MetricEvent.BLOCK), count)
            self.cold_block_total += 1

    def cold_exit(self, resource: str, count: int, rt_ms: int,
                  error: bool) -> None:
        """Completion of a COLD-path entry: SUCCESS/EXCEPTION/RT tally
        host-side (there is no device row to commit to)."""
        with self.gate:
            self._cold_tally_locked(resource,
                                    int(C.MetricEvent.SUCCESS), count)
            self._cold_tally_locked(resource, int(C.MetricEvent.RT), rt_ms)
            if error:
                self._cold_tally_locked(resource,
                                        int(C.MetricEvent.EXCEPTION), count)

    def evicted_exit(self, resource: str, count: int, rt_ms: int,
                     error: bool, now_ms: int) -> None:
        """Completion of a DEVICE-committed entry whose resource was
        evicted (and not re-admitted) before it exited: the entry's
        thread count is standing in the spill record — decrement it
        there so rehydration cannot leak phantom concurrency — and its
        completion stats tally cold (they fold back on rehydrate)."""
        with self.gate:
            rec = self._spill.get(resource)
            if rec is not None:
                rec.cur_threads = max(0, int(rec.cur_threads) - count)
            self._cold_tally_locked(resource,
                                    int(C.MetricEvent.SUCCESS), count)
            self._cold_tally_locked(resource, int(C.MetricEvent.RT), rt_ms)
            if error:
                self._cold_tally_locked(resource,
                                        int(C.MetricEvent.EXCEPTION), count)
            self.late_exits_total += 1
        self._emit({"e": "slotLateExit", "resource": resource,
                    "count": count, "ms": now_ms})

    # -- admission ---------------------------------------------------------

    def try_admit(self, resource: str, now_ms: int) -> Optional[Tuple[int, int]]:
        """First-touch admission into a FREE slot (never a steal): the
        fast path for a cold resource while the table is under budget.
        Returns the published (slot, generation), or None when no free
        slot exists / the resource is mid-admission elsewhere. Pays a
        rehydration graft iff a spill record survives."""
        with self.gate:
            cur = self._hot.get(resource)
            if cur is not None:
                return cur
            if resource in self._admitting or not self._free:
                return None
            slot = min(self._free)  # deterministic choice (replay oracles)
            self._free.discard(slot)
            self._occupant[slot] = resource
            self._admitting.add(resource)
            self._version += 1
        self._execute([], [(resource, slot)], now_ms)
        return self._hot.get(resource)

    def ensure_pinned(self, pinned: Set[str], now_ms: int) -> None:
        """Make every ruled resource hot BEFORE its rules compile (the
        config-plane hook on each rule push): compiled rule tensors
        target slot indices, so a cold ruled resource would compile to
        an inert rule. Steals unpinned incumbents when the free list
        runs dry; past that, the remaining pins overflow LOUDLY (the
        rule stays unenforced-while-cold, counted + logged)."""
        missing = [res for res in sorted(pinned)
                   if res not in self._hot and res not in self._admitting]
        if not missing:
            return
        evicts: List[Tuple[str, int, int]] = []
        admits: List[Tuple[str, int]] = []
        overflowed = 0
        with self.gate:
            hot = dict(self._hot)
            # Victim pool: unpinned occupants, coldest-first by the
            # telescope's current ranking (absent from top-k = 0).
            counts = self._population_counts()
            victims = sorted(
                (res for res in hot if res not in pinned),
                key=lambda r: (counts.get(r, 0), r))
            for res in missing:
                if res in hot or res in self._admitting:
                    continue
                if self._free:
                    slot = min(self._free)
                    self._free.discard(slot)
                elif victims:
                    victim = victims.pop(0)
                    slot, gen = hot.pop(victim)
                    self._generation[slot] = gen + 1
                    self._occupant[slot] = None
                    self._draining.add(slot)
                    evicts.append((victim, slot, gen))
                else:
                    self.pin_overflow_total += 1
                    overflowed += 1
                    continue
                self._occupant[slot] = res
                self._admitting.add(res)
                admits.append((res, slot))
            self._hot = hot
            self._version += 1
        if overflowed:
            self._log_pin_overflow(pinned)
        if evicts or admits:
            self._execute(evicts, admits, now_ms)

    def _log_pin_overflow(self, pinned: Set[str]) -> None:
        from sentinel_tpu.log.record_log import record_log

        record_log.warn(
            "slot table cannot pin every ruled resource (budget=%d, "
            "ruled=%d): overflowed rules stay UNENFORCED while cold; "
            "pin_overflow_total=%d", self.budget, len(pinned),
            self.pin_overflow_total)

    # -- rebalance (rides the spill fold) ----------------------------------

    def _population_counts(self) -> Dict[str, int]:
        population = getattr(self.engine, "population", None)
        if population is None or not population.enabled:
            return {}
        snap = population.snapshot(topk=max(2 * self.budget, 16), windows=1)
        return {e["key"]: int(e["count"]) for e in snap["topk"]}

    def on_spill(self, now_ms: int) -> None:
        """Rebalance tick, riding ``_spill_flight``'s once-per-second
        fold: sweep stale cold tallies of hot resources, then (at most
        once per second, outside any freeze) steal the coldest unpinned
        slots for telescope-ranked challengers under the hysteresis and
        ``max.steals`` bounds. The ``slots.evict.storm`` seam sits ABOVE
        the freeze gate — chaos must be able to exercise eviction even
        mid-freeze, exactly like a real operator drill."""
        from sentinel_tpu.resilience import faults

        if now_ms - self._rebalanced_ms < 1000 and self._rebalanced_ms >= 0:
            return
        self._rebalanced_ms = now_ms
        self._sweep_hot_tallies(now_ms)

        storm = False
        try:
            faults.fire("slots.evict.storm")
        except faults.FaultInjected:
            storm = True
        if storm:
            self.storms_total += 1
            self._evict_storm(now_ms)
            return

        reason = self.freeze_reason(now_ms)
        if reason is not None:
            return

        counts = self._population_counts()
        if not counts:
            return
        pinned = self.engine._slot_pinned_resources()
        hot = self._hot
        challengers = sorted(
            ((cnt, res) for res, cnt in counts.items()
             if res not in hot and res not in self._admitting),
            reverse=True)
        if not challengers:
            return
        victims = sorted(
            ((counts.get(res, 0), res) for res in hot if res not in pinned))
        scale = 1.0 + self.hysteresis_pct / 100.0
        evicts: List[Tuple[str, int, int]] = []
        admits: List[Tuple[str, int]] = []
        with self.gate:
            hot_map = dict(self._hot)
            free = sorted(self._free)
            for cnt, res in challengers:
                if len(evicts) + len(admits) >= self.max_steals:
                    break
                if res in hot_map or res in self._admitting:
                    continue
                if free:
                    slot = free.pop(0)
                    self._free.discard(slot)
                elif victims and cnt > victims[0][0] * scale:
                    vcnt, victim = victims.pop(0)
                    if victim not in hot_map:
                        continue
                    slot, gen = hot_map.pop(victim)
                    self._generation[slot] = gen + 1
                    self._occupant[slot] = None
                    self._draining.add(slot)
                    evicts.append((victim, slot, gen))
                    self.steals_total += 1
                else:
                    break  # sorted feeds: nothing below can qualify
                self._occupant[slot] = res
                self._admitting.add(res)
                admits.append((res, slot))
            self._hot = hot_map
            self._version += 1
        if evicts or admits:
            self._execute(evicts, admits, now_ms)

    def _evict_storm(self, now_ms: int) -> None:
        """Chaos storm: evict EVERY unpinned occupant this cycle (the
        worst-case churn the conservation invariant must survive)."""
        pinned = self.engine._slot_pinned_resources()
        evicts: List[Tuple[str, int, int]] = []
        with self.gate:
            hot_map = dict(self._hot)
            for res in sorted(hot_map):
                if res in pinned:
                    continue
                slot, gen = hot_map.pop(res)
                self._generation[slot] = gen + 1
                self._occupant[slot] = None
                self._draining.add(slot)
                evicts.append((res, slot, gen))
            self._hot = hot_map
            self._version += 1
        if evicts:
            self._execute(evicts, [], now_ms)

    def _sweep_hot_tallies(self, now_ms: int) -> None:
        """Fold any cold tallies standing for resources that are HOT
        (an in-flight cold entry can tally after its resource was
        re-admitted): a tiny device update keeps total conservation
        exact without waiting for the next evict/rehydrate cycle."""
        with self.gate:
            stale = {res: self._cold.pop(res)
                     for res in [r for r in self._cold if r in self._hot]}
        if not stale:
            return
        import jax.numpy as jnp

        eng = self.engine
        with eng._lock:
            eng._ensure_compiled()
            state = eng._state
            totals = state.telemetry.totals
            for res, vec in stale.items():
                cur = self._hot.get(res)
                if cur is None:
                    with self.gate:  # went cold again mid-sweep: put back
                        prev = self._cold.get(res)
                        self._cold[res] = vec if prev is None else prev + vec
                    continue
                totals = totals.at[:, cur[0]].add(jnp.asarray(vec))
            eng._state = state._replace(
                telemetry=state.telemetry._replace(totals=totals))

    # -- the steal/graft surgery ------------------------------------------

    def _execute(self, evicts: List[Tuple[str, int, int]],
                 admits: List[Tuple[str, int]], now_ms: int) -> None:
        """Spill ``evicts``' columns, zero them, graft ``admits``' spill
        records back, publish. Caller has ALREADY swapped the hot map
        (victims unpublished, targets reserved) under ``gate`` and holds
        NO locks here. See the module docstring for why the committer
        flush must happen outside ``engine._lock``."""
        from sentinel_tpu.ops import window as W

        eng = self.engine
        # Everything enqueued under the victims' tenancy lands on device
        # before the surgery reads it (enqueues after the map swap were
        # re-translated under the gate and went cold instead).
        eng._flush_committer()
        records: List[Optional[SpillRecord]] = []
        grafted: List[dict] = []
        with eng._lock:
            eng._ensure_compiled()
            state = eng._state
            w1c = np.array(state.w1.counts)
            w1m = np.array(state.w1.min_rt)
            w1s = np.array(state.w1.starts)
            w60c = np.array(state.w60.counts)
            w60m = np.array(state.w60.min_rt)
            w60s = np.array(state.w60.starts)
            secc = np.array(state.sec.counts)
            secm = np.array(state.sec.min_rt)
            sec_stamp = int(state.sec.stamp)
            thr = np.array(state.cur_threads)
            occ = np.array(state.occupied_next)
            occ_stamp = int(state.occupied_stamp)
            tel = state.telemetry
            tb = np.array(tel.block_by_reason)
            th = np.array(tel.rt_hist)
            tt = np.array(tel.totals)
            sa = np.array(tel.stage_attr)
            sh = np.array(tel.stage_hist)
            touched = [s for _, s, _ in evicts] + [s for _, s in admits]

            for res, slot, gen in evicts:
                rec = self._spill_slot(
                    res, slot, gen, now_ms, w1c, w1m, w1s, w60c, w60m, w60s,
                    secc, secm, sec_stamp, thr, occ, occ_stamp, tb, th, tt,
                    sa, sh)
                records.append(rec)
                # Zero the victim's columns — the generation firewall:
                # whatever the successor commits, none of this survives.
                w1c[:, :, slot] = 0
                w1m[:, slot] = int(W.MIN_RT_EMPTY)
                w60c[:, :, slot] = 0
                w60m[:, slot] = int(W.MIN_RT_EMPTY)
                secc[:, slot] = 0
                secm[slot] = int(W.MIN_RT_EMPTY)
                thr[slot] = 0
                occ[slot] = 0
                tb[:, slot] = 0
                th[:, slot] = 0
                tt[:, slot] = 0
                sa[:, slot] = 0
                sh[:, slot] = 0

            for res, slot in admits:
                with self.gate:
                    rec = self._spill.pop(res, None)
                    cold = self._cold.pop(res, None)
                info = self._graft_slot(
                    res, slot, rec, cold, w1c, w1m, w1s, w60c, w60m, w60s,
                    secc, secm, sec_stamp, thr, occ, occ_stamp, tb, th, tt)
                grafted.append(info)

            import jax.numpy as jnp

            new_state = state._replace(
                w1=state.w1._replace(counts=jnp.asarray(w1c),
                                     min_rt=jnp.asarray(w1m)),
                w60=state.w60._replace(counts=jnp.asarray(w60c),
                                       min_rt=jnp.asarray(w60m)),
                sec=state.sec._replace(counts=jnp.asarray(secc),
                                       min_rt=jnp.asarray(secm)),
                cur_threads=jnp.asarray(thr),
                occupied_next=jnp.asarray(occ),
                telemetry=tel._replace(
                    block_by_reason=jnp.asarray(tb),
                    rt_hist=jnp.asarray(th),
                    totals=jnp.asarray(tt),
                    stage_attr=jnp.asarray(sa),
                    stage_hist=jnp.asarray(sh)),
            )
            # Shadow lanes + flight ring: zeroed, never grafted — the
            # rollout guardrail re-baselines, and a ring slot must not
            # carry a prior tenancy's second into the next spill.
            if state.shadow is not None and touched:
                idx = jnp.asarray(touched, jnp.int32)
                shadow = state.shadow
                new_state = new_state._replace(shadow=shadow._replace(
                    counts=shadow.counts.at[:, idx].set(0),
                    w1=shadow.w1._replace(
                        counts=shadow.w1.counts.at[:, :, idx].set(0),
                        min_rt=shadow.w1.min_rt.at[:, idx].set(
                            W.MIN_RT_EMPTY))))
            if state.flight is not None and touched:
                idx = jnp.asarray(touched, jnp.int32)
                flight = state.flight
                new_state = new_state._replace(flight=flight._replace(
                    events=flight.events.at[:, :, idx].set(0),
                    attr=flight.attr.at[:, :, idx].set(0),
                    hist=flight.hist.at[:, :, idx].set(0)))
            eng._state = new_state

        # Publish: store spill records, free fully-drained slots, map
        # the admits in at their slots' CURRENT generation.
        with self.gate:
            for rec in records:
                if rec is None:
                    continue
                self._spill[rec.resource] = rec
                self._spill.move_to_end(rec.resource)
                while len(self._spill) > self.spill_max:
                    self._spill.popitem(last=False)
                    self.spill_dropped_total += 1
            for _, slot, _ in evicts:
                self._draining.discard(slot)
                if self._occupant[slot] is None:
                    self._free.add(slot)
            hot_map = dict(self._hot)
            for res, slot in admits:
                hot_map[res] = (slot, self._generation[slot])
                self._admitting.discard(res)
            self._hot = hot_map
            self._version += 1
            self.evictions_total += len(evicts)
            self.admits_total += len(admits)

        for (res, slot, gen), rec in zip(evicts, records):
            self._emit({"e": "slotEvict", "resource": res, "slot": slot,
                        "gen": gen, "torn": rec is None,
                        "spilledPass": (int(rec.spilled_pass)
                                        if rec is not None else 0),
                        "ms": now_ms})
        for info in grafted:
            self.rehydrations_total += 1
            if not info["fromRecord"]:
                self.rehydrations_cold_total += 1
            info.update(e="slotRehydrate", ms=now_ms,
                        gen=self._generation[info["slot"]])
            self._emit(info)
            self._emit({"e": "slotAdmit", "resource": info["resource"],
                        "slot": info["slot"], "gen": info["gen"],
                        "ms": now_ms})

    def _spill_slot(self, res, slot, gen, now_ms, w1c, w1m, w1s, w60c, w60m,
                    w60s, secc, secm, sec_stamp, thr, occ, occ_stamp, tb,
                    th, tt, sa, sh) -> Optional[SpillRecord]:
        """Extract one victim's columns into a SpillRecord — unless the
        ``slots.spill.torn`` seam tears it (error OR garbage mode), in
        which case the victim's state is dropped on the floor, counted:
        it rehydrates cold, the documented bounded-loud loss."""
        from sentinel_tpu.resilience import faults

        try:
            torn = faults.mutate("slots.spill.torn", b"\x01") != b"\x01"
        except faults.FaultInjected:
            torn = True
        if torn:
            self.spill_torn_total += 1
            return None
        rec = SpillRecord(res, gen, now_ms)
        rec.w1_counts = w1c[:, :, slot].copy()
        rec.w1_min_rt = w1m[:, slot].copy()
        rec.w1_starts = w1s.copy()
        rec.w60_counts = w60c[:, :, slot].copy()
        rec.w60_min_rt = w60m[:, slot].copy()
        rec.w60_starts = w60s.copy()
        rec.sec_counts = secc[:, slot].copy()
        rec.sec_min_rt = int(secm[slot])
        rec.sec_stamp = sec_stamp
        rec.cur_threads = int(thr[slot])
        rec.occupied_next = int(occ[slot])
        rec.occupied_stamp = occ_stamp
        # Cumulative telemetry spills with the live staged second folded
        # in (the staging would otherwise be zeroed un-folded).
        rec.tel_block = tb[:, slot] + sa[:, slot].astype(np.int64)
        rec.tel_hist = th[:, slot] + sh[:, slot].astype(np.int64)
        rec.tel_totals = tt[:, slot].copy()
        rec.spilled_pass = int(tt[int(C.MetricEvent.PASS), slot]
                               + secc[int(C.MetricEvent.PASS), slot])
        return rec

    def _graft_slot(self, res, slot, rec: Optional[SpillRecord], cold,
                    w1c, w1m, w1s, w60c, w60m, w60s, secc, secm, sec_stamp,
                    thr, occ, occ_stamp, tb, th, tt) -> dict:
        """Graft a spill record into a freshly zeroed slot, bucket by
        geometry-checked bucket; fold the resource's cold-tail tallies
        into the totals (exact counter conservation across the cold
        spell). Returns the rehydrate event payload."""
        info = {"resource": res, "slot": slot, "fromRecord": rec is not None,
                "graftedPass": 0, "stalePass": 0,
                "coldPass": int(cold[int(C.MetricEvent.PASS)])
                if cold is not None else 0}
        if rec is not None:
            grafted_pass = 0
            stale_pass = 0
            for i in range(min(len(rec.w1_starts), w1s.shape[0])):
                if rec.w1_starts[i] == w1s[i]:
                    w1c[i, :, slot] = rec.w1_counts[i]
                    w1m[i, slot] = rec.w1_min_rt[i]
                    grafted_pass += int(
                        rec.w1_counts[i][int(C.MetricEvent.PASS)])
                else:
                    stale_pass += int(
                        rec.w1_counts[i][int(C.MetricEvent.PASS)])
            for i in range(min(len(rec.w60_starts), w60s.shape[0])):
                if rec.w60_starts[i] == w60s[i]:
                    w60c[i, :, slot] = rec.w60_counts[i]
                    w60m[i, slot] = rec.w60_min_rt[i]
            if rec.sec_stamp == sec_stamp:
                # The staged second never rolled: restore it — it folds
                # into w60/telemetry on the normal cadence.
                secc[:, slot] = rec.sec_counts
                secm[slot] = rec.sec_min_rt
            else:
                # Its second completed while cold: the minute-window
                # bucket may have rotated, but the COUNTERS must not
                # lose it — fold straight into the cumulative totals.
                tt[:, slot] += rec.sec_counts.astype(np.int64)
            thr[slot] = rec.cur_threads
            if rec.occupied_stamp == occ_stamp:
                occ[slot] = rec.occupied_next
            tb[:, slot] = rec.tel_block
            th[:, slot] = rec.tel_hist
            tt[:, slot] += rec.tel_totals
            info["graftedPass"] = grafted_pass
            info["stalePass"] = stale_pass
        if cold is not None:
            tt[:, slot] += cold
        return info

    # -- checkpoint support ------------------------------------------------

    def checkpoint_dict(self) -> dict:
        """Slot assignment + generations for the checkpoint header. The
        saved device arrays are slot-indexed, so restore needs exactly
        this map to re-bind them. Spill records and cold tallies are
        NOT persisted — the cold tail restarts cold across a process
        restart, the reference's own "restart = cold stats" stance,
        bounded to resources outside the hot set."""
        with self.gate:
            return {
                "budget": self.budget,
                "hot": {res: [sg[0], sg[1]] for res, sg in self._hot.items()},
                "generations": list(self._generation),
            }

    def restore_assignment(self, d: dict) -> None:
        """Re-bind a checkpoint's slot assignment (boot-time only, under
        ``restore_checkpoint``'s fresh-engine guard)."""
        if int(d.get("budget", -1)) != self.budget:
            raise ValueError(
                f"checkpoint slot budget {d.get('budget')} != engine "
                f"slot budget {self.budget}")
        gens = [int(g) for g in d.get("generations", [])]
        if len(gens) != self.budget:
            raise ValueError("checkpoint slot generations length mismatch")
        with self.gate:
            self._generation = gens
            hot: Dict[str, Tuple[int, int]] = {}
            occupant: List[Optional[str]] = [None] * self.budget
            for res, sg in (d.get("hot") or {}).items():
                slot, gen = int(sg[0]), int(sg[1])
                if not FIRST_SLOT <= slot < self.budget \
                        or occupant[slot] is not None:
                    raise ValueError(
                        f"checkpoint slot assignment corrupt at {res!r}")
                hot[res] = (slot, gen)
                occupant[slot] = res
            self._hot = hot
            self._occupant = occupant
            self._free = {s for s in range(FIRST_SLOT, self.budget)
                          if occupant[s] is None}
            self._draining.clear()
            self._admitting.clear()
            self._version += 1

    # -- ops plane ---------------------------------------------------------

    def status(self) -> dict:
        with self.gate:
            cold_mass = {str(k): int(v.sum()) for k, v in
                         list(self._cold.items())[:16]}
            return {
                "budget": self.budget,
                "hot": len(self._hot),
                "free": len(self._free),
                "draining": len(self._draining),
                "pinnedNow": len(self.engine._slot_pinned_resources()),
                "frozen": self._manual_freeze,
                "admitsTotal": self.admits_total,
                "evictionsTotal": self.evictions_total,
                "rehydrationsTotal": self.rehydrations_total,
                "rehydrationsColdTotal": self.rehydrations_cold_total,
                "stealsTotal": self.steals_total,
                "stormsTotal": self.storms_total,
                "hotHitsTotal": self.hot_hits_total,
                "coldPassTotal": self.cold_pass_total,
                "coldBlockTotal": self.cold_block_total,
                "coldUnenforcedTotal": self.cold_unenforced_total,
                "spillTornTotal": self.spill_torn_total,
                "spillDroppedTotal": self.spill_dropped_total,
                "spillRecords": len(self._spill),
                "lateExitsTotal": self.late_exits_total,
                "pinOverflowTotal": self.pin_overflow_total,
                "freezesTotal": self.freezes_total,
                "coldTallyResources": len(self._cold),
                "coldTallySample": cold_mass,
                "hitRate": self.hit_rate(),
            }

    def hit_rate(self) -> float:
        """Measured hot-set hit rate since start: device/lease-hot
        admissions over ALL admissions (the BENCH_19 comparand for the
        telescope's ``population_report`` projection)."""
        hits = self.hot_hits_total
        total = hits + self.cold_pass_total + self.cold_block_total
        return round(hits / total, 6) if total else 1.0

    def note_verdict(self, resource: str, slot: int, gen: int, sec: int,
                     verdict: str, reason: int = 0) -> None:
        """Per-verdict attribution event for the ``slot_conservation``
        invariant (every verdict must land on exactly one live
        (resource, generation) tenancy). No-op without a sink — the hot
        path pays one attribute read."""
        if self.event_sink is None:
            return
        self._emit({"e": "slotVerdict", "resource": resource, "slot": slot,
                    "gen": gen, "sec": sec, "verdict": verdict,
                    "reason": reason})

    def _emit(self, event: dict) -> None:
        sink = self.event_sink
        if sink is not None:
            try:
                sink(event)
            except Exception:  # noqa: BLE001 — observability can't break admission
                pass


class _RuleRegistryView:
    """Duck-typed ``NodeRegistry`` facade for the rule compilers in slot
    mode: resource rows resolve through the slot table (cold -> -1 =
    inert rule slot, which the pin machinery makes a counted anomaly,
    never the steady state); id interning passes through to the real
    registry; the per-(context, resource) / per-origin row kinds have no
    device rows under a slot budget (-1 — CHAIN warm-up sync and
    per-origin statistic rows degrade to the cluster aggregate,
    docs/SEMANTICS.md "Eviction conservation bound")."""

    __slots__ = ("_slots", "_registry")

    def __init__(self, slots: SlotTable):
        self._slots = slots
        self._registry = slots.engine.registry

    def cluster_row(self, resource: str, entry_type: int = 0,
                    resource_type: int = 0) -> int:
        row = self._slots.device_row(resource)
        return row if row is not None else -1

    def origin_id(self, origin: str) -> int:
        return self._registry.origin_id(origin)

    def context_id(self, context: str) -> int:
        return self._registry.context_id(context)

    def default_row(self, context: str, resource: str,
                    parent_row: int) -> int:
        return -1

    def entrance_row(self, context: str) -> int:
        return -1

    def origin_row(self, resource: str, origin: str) -> int:
        return -1

"""Django-style middleware (reference: ``sentinel-spring-webmvc-adapter``'s
``SentinelWebInterceptor`` / ``AbstractSentinelInterceptor`` —
SURVEY.md §2.5).

Duck-typed against Django's middleware protocol, so it imports no Django:
construct with ``get_response``, call with a request object exposing
``.path`` and ``.META`` / ``.headers``, return the downstream response or
a 429. Register as usual::

    MIDDLEWARE = ["sentinel_tpu.adapters.django_mw.SentinelMiddleware", ...]

Configuration mirrors the webmvc adapter's ``SentinelWebMvcConfig``: set
class attributes (or subclass) for ``url_cleaner`` / ``origin_parser`` /
``block_handler``, matching the WSGI middleware's callbacks.
"""

from __future__ import annotations

from typing import Callable, Optional

from sentinel_tpu.adapters.wsgi import _GuardedIterable, enter_web_entries
from sentinel_tpu.core.exceptions import BlockException

DEFAULT_BLOCK_STATUS = 429
DEFAULT_BLOCK_BODY = b"Blocked by Sentinel (flow limiting)"


class _PlainResponse:
    """Minimal response stand-in used when Django isn't importable (tests,
    non-Django callers). Real deployments get a django HttpResponse."""

    def __init__(self, content: bytes, status: int):
        self.content = content
        self.status_code = status


def _make_response(content: bytes, status: int):
    try:  # pragma: no cover - exercised only with Django installed
        from django.http import HttpResponse

        return HttpResponse(content, status=status)
    except ImportError:
        return _PlainResponse(content, status)


class SentinelMiddleware:
    """``__init__(get_response)`` + ``__call__(request)`` — the modern
    Django middleware shape."""

    url_cleaner: Optional[Callable[[str], str]] = None
    origin_parser: Optional[Callable] = None
    block_handler: Optional[Callable] = None
    total_resource: Optional[str] = None

    def __init__(self, get_response):
        self.get_response = get_response

    def __call__(self, request):
        clean = type(self).url_cleaner or (lambda p: p)
        parse_origin = type(self).origin_parser or (lambda req: "")
        resource = clean(getattr(request, "path", "/"))
        origin = parse_origin(request)
        try:
            entries, cleanup = enter_web_entries(resource, origin,
                                                 type(self).total_resource)
        except BlockException as ex:
            if type(self).block_handler is not None:
                return type(self).block_handler(request, ex)
            return _make_response(DEFAULT_BLOCK_BODY, DEFAULT_BLOCK_STATUS)
        try:
            response = self.get_response(request)
        except BaseException as ex:
            for e in entries:
                e.trace(ex)
            cleanup()
            raise
        # Streaming responses keep their entries live until the body is
        # exhausted — RT covers generation, mid-stream errors are traced
        # (same stance as the WSGI middleware's _GuardedIterable).
        streaming = getattr(response, "streaming_content", None)
        if streaming is not None:
            response.streaming_content = _GuardedIterable(
                streaming, entries, cleanup)
            return response
        cleanup()
        return response

"""A REAL in-process multi-leader sharded mesh, driven deterministically.

Every load-bearing component is the production one: ``ClusterHAManager``
seats flip roles and publish/restore REAL checkpoint files, each seat's
control-plane mutations land in a REAL crash-safe ``ControlPlaneJournal``
file, admission runs through REAL ``DefaultTokenService`` device steps,
and routing/fencing/degraded-mode decisions are the REAL
``ShardedTokenClient`` walk over the real ``SliceEpochFence`` and
``DegradedQuota``. Leaders run their loopback wire reactors (listeners
bound on ephemeral ports), but the campaign's request path replaces the
router's socket pool with :class:`LoopbackConn` — a deterministic
in-process conduit that calls each leader's service directly, fires the
same chaos seams the wire path fires, and judges reply epochs exactly
like ``ClusterTokenClient._epoch_stale``.

Determinism: the mesh is driven by ONE thread on a program-advanced
``SimClock``, injected into every timing-sensitive component — the
router via ``ShardedTokenClient(clock=)``, the degraded quota via its
``now_ms`` parameter, the journals via their clock callable, the
services via per-request ``now_ms`` — so the verdict stream and fault
firing sequence are a pure function of ``(campaign_seed,
episode_index)`` WITHOUT touching the process clock (a campaign may run
beside a live engine; nothing global is frozen). test_lint pins that
nothing in this package reads a wall clock.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

from sentinel_tpu.chaos.invariants import History
from sentinel_tpu.cluster.constants import THRESHOLD_GLOBAL, TokenResultStatus
from sentinel_tpu.cluster.ha import (
    ClusterHAManager,
    ClusterServerSpec,
    DegradedQuota,
)
from sentinel_tpu.cluster.sharding import ShardedTokenClient, ShardMap, slice_of
from sentinel_tpu.cluster.state import CLUSTER_SERVER, ClusterStateManager
from sentinel_tpu.cluster.token_service import TokenResult
from sentinel_tpu.models.flow import FlowRule
from sentinel_tpu.resilience import faults
from sentinel_tpu.telemetry.journal import ControlPlaneJournal

_FAIL = TokenResult(TokenResultStatus.FAIL)

# Terminal category per wire status (the conservation columns).
_SHED = (TokenResultStatus.OVERLOADED, TokenResultStatus.TOO_MANY_REQUEST)

# The default campaign flow set: three flows whose slices land distinct
# on the default 8-ring (slices 6, 4, 0). ONE definition — the
# scheduler's plan simulation, the mesh, and the campaign must plan,
# build, and drive the same flows or initial_assignment diverges.
DEFAULT_FLOWS = {9000: 6.0, 9001: 6.0, 9003: 6.0}


def initial_assignment(leaders, flows, n_slices) -> Dict[str, List[int]]:
    """The episode's starting slice ownership: flows' slices round-robin
    across the leaders, spare slices to the LAST leader (so it alone can
    donate voluntarily). Two flows hashing into the SAME slice place it
    once (first flow's leader keeps it) — every slice has exactly one
    owner, whatever flow set a campaign is built with. One
    implementation shared by the mesh and the scheduler's plan
    simulation — they must never diverge."""
    leaders = tuple(leaders)
    assign: Dict[str, List[int]] = {m: [] for m in leaders}
    placed: set = set()
    for fid in sorted(flows):
        sl = slice_of(fid, n_slices)
        if sl in placed:
            continue
        assign[leaders[len(placed) % len(leaders)]].append(sl)
        placed.add(sl)
    for sl in range(n_slices):
        if sl not in placed:
            assign[leaders[-1]].append(sl)
    return {m: sorted(set(s)) for m, s in assign.items()}


class _SeatHost:
    """The engine stand-in a seat's HA manager needs: an audit journal
    riding the campaign clock, degraded thresholds, no span collector."""

    def __init__(self, journal, thresholds_fn):
        self.journal = journal
        self.cluster_degraded_thresholds = thresholds_fn
        self.spans = None


class _RecordingQuota(DegradedQuota):
    """The real per-client share math, with every degraded grant
    recorded into the episode history (the degraded-bound checker's
    evidence)."""

    def __init__(self, mesh, **kw):
        super().__init__(**kw)
        self._mesh = mesh

    def acquire(self, flow_id, count: int = 1, now_ms=None):
        mesh = self._mesh
        if now_ms is None:
            now_ms = mesh.clock.now_ms()  # campaign timebase, no freeze
        r = super().acquire(flow_id, count, now_ms)
        if r is not None:
            now = mesh.clock.now_ms()
            interval = mesh.interval_of(int(flow_id))
            if r.status == TokenResultStatus.OK:
                mesh.history.add("degradedGrant", op=mesh.current_op,
                                 flow=int(flow_id),
                                 win=now - now % interval)
            mesh.served_by = "degraded"
        return r


class LoopbackConn:
    """Deterministic loopback conduit to one seat's token service —
    the token-client protocol the ``ShardedTokenClient`` walk expects,
    minus the socket. Fires the wire path's chaos seams
    (``cluster.reactor.conn.{drop,stall}``, ``cluster.ha.halfopen``,
    ``cluster.ha.stale.epoch``) and judges reply epochs against the
    router's shared per-slice fence exactly like the real client."""

    def __init__(self, mesh: "ChaosMesh", mid: str, spec: ClusterServerSpec):
        self.mesh = mesh
        self.mid = mid
        self.host = spec.host
        self.port = spec.port
        self.request_timeout_s = 2.0

    # -- token-client protocol (pool duck type) ---------------------------

    def start(self):
        return self

    def stop(self) -> None:
        pass

    def is_connected(self) -> bool:
        mesh = self.mesh
        if not mesh.link_up.get(self.mid, True):
            return False
        state = mesh.seats[self.mid].state
        srv = state.token_server
        return (srv is not None and not srv.crashed
                and state.mode == CLUSTER_SERVER)

    def request_token(self, flow_id, count: int = 1,
                      prioritized: bool = False, timeout_s=None,
                      gate_neutral: bool = False, trace=None) -> TokenResult:
        mesh = self.mesh
        try:
            fid = int(flow_id)
        except (TypeError, ValueError):
            return _FAIL
        op = mesh.current_op
        sl = slice_of(fid, mesh.n_slices)
        try:
            mesh.fire_targeted("cluster.reactor.conn.stall", self.mid)
        except OSError:
            mesh.log_fault("conn.stall", self.mid, op=op)
            return _FAIL
        try:
            mesh.fire_targeted("cluster.reactor.conn.drop", self.mid)
        except OSError:
            mesh.log_fault("conn.drop", self.mid, op=op)
            return _FAIL
        srv = mesh.seats[self.mid].state.token_server
        if srv is None or srv.crashed:
            return _FAIL
        now = mesh.clock.now_ms() + mesh.skew_ms.get(self.mid, 0)
        r = srv.service.request_token(fid, count, prioritized, now_ms=now)
        granted = r.status == TokenResultStatus.OK
        win = now - now % mesh.interval_of(fid)
        # Stale-epoch replay seam: the armed garbage payload REPLACES
        # this reply's epoch stamp (a deposed term replayed on the wire).
        replayed = mesh.mutate_targeted("cluster.ha.stale.epoch",
                                        self.mid, b"\x01")
        epoch = r.epoch
        if replayed != b"\x01":
            epoch = int.from_bytes(replayed[:8], "big") if replayed else 0
            mesh.log_fault("stale.epoch", self.mid, op=op)
        # Half-open swallow: the server did the work (and consumed quota
        # on OK) but the reply never lands — the client sees a timeout.
        swallowed = mesh.mutate_targeted("cluster.ha.halfopen",
                                         self.mid, b"\x01") != b"\x01"
        if swallowed:
            mesh.log_fault("halfopen", self.mid, op=op)
            if granted:
                mesh.history.add("grantVoid", op=op, flow=fid,
                                 leader=self.mid, win=win)
            return _FAIL
        # Per-slice fence, exactly the client's stance: unstamped
        # replies pass unfenced; a stamped reply below the lane's
        # high-water mark is a deposed term — reject it as FAIL.
        if epoch is not None and int(epoch) > 0 \
                and r.status != TokenResultStatus.WRONG_SLICE:
            ok = mesh.fence.observe(int(epoch), sl)
            mesh.history.add("fence", scope=sl, epoch=int(epoch),
                             accepted=bool(ok))
            if not ok:
                if granted:
                    mesh.history.add("grantVoid", op=op, flow=fid,
                                     leader=self.mid, win=win)
                return _FAIL
        if granted:
            mesh.history.add("grant", op=op, flow=fid, leader=self.mid,
                             win=win)
        if r.status in _SHED:
            mesh.history.add("shedBy", op=op, flow=fid, leader=self.mid)
        if r.status != TokenResultStatus.FAIL:
            mesh.served_by = self.mid
        return r

    def request_param_token(self, flow_id, count, params, timeout_s=None,
                            gate_neutral: bool = False, trace=None):
        return self.request_token(flow_id, count)

    def request_tokens_pipelined(self, requests, timeout_s=None,
                                 gate_neutral: bool = False):
        return [self.request_token(*req[:3]) for req in requests]


class ChaosMesh:
    """N HA seats + one sharded router, built fresh per episode."""

    def __init__(self, clock, history: History, workdir: str,
                 leaders=("A", "B", "C"), n_slices: int = 8,
                 flows: Optional[Dict[int, float]] = None,
                 interval_ms: int = 1000,
                 failover_deadline_ms: int = 1500,
                 clients=("chaos-c1", "chaos-c2")):
        self.clock = clock
        self.history = history
        self.workdir = workdir
        self.leader_order = tuple(leaders)
        self.n_slices = int(n_slices)
        self.flows = dict(flows) if flows else dict(DEFAULT_FLOWS)
        self.interval_ms = int(interval_ms)
        self.clients = tuple(clients)
        self.thresholds = {fid: (thr, self.interval_ms)
                           for fid, thr in self.flows.items()}
        self.divisor = len(self.clients)
        # -- driver state ---------------------------------------------------
        self.current_op: Optional[int] = None
        self.served_by: Optional[str] = None
        self.skew_ms: Dict[str, int] = {}
        self.link_up: Dict[str, bool] = {m: True for m in leaders}
        self.crashed: set = set()
        self.fault_target: Dict[str, str] = {}
        self.fault_log: List[tuple] = []
        self._next_op = 0
        self._router_skip = 0
        # -- seats ----------------------------------------------------------
        rules = [FlowRule(resource=f"res-{fid}", count=thr,
                          cluster_mode=True,
                          cluster_config={"flowId": fid,
                                          "thresholdType": THRESHOLD_GLOBAL})
                 for fid, thr in sorted(self.flows.items())]
        # Specs carry port 0: every promotion binds an EPHEMERAL loopback
        # listener (the reactor runs; nothing routes traffic through it),
        # so episodes can never collide on ports and a seat that flips to
        # client mode dials a dead port instead of another seat's wire.
        self.specs = {m: ClusterServerSpec(m, "127.0.0.1", 0)
                      for m in leaders}
        self.seats: Dict[str, ClusterHAManager] = {}
        self.hosts: Dict[str, _SeatHost] = {}
        base = os.path.join(workdir, "handoff.ck")
        for mid in leaders:
            state = ClusterStateManager()
            state.server_rules().load_rules("default", rules)
            journal = ControlPlaneJournal(
                self.clock.now_ms,
                path=os.path.join(workdir, f"journal-{mid}.jsonl"))
            host = _SeatHost(journal, state.server_rules().thresholds)
            state.journal = journal
            mgr = ClusterHAManager(engine=host, state=state, machine_id=mid,
                                   checkpoint_path=base,
                                   checkpoint_period_s=3600.0,
                                   server_host="127.0.0.1")
            # A failed transition must never retry mid-episode on a wall
            # timer (nondeterministic); episodes are short and newer maps
            # win anyway.
            mgr.retry_delay_s = 3600.0
            self.seats[mid] = mgr
            self.hosts[mid] = host
        # -- initial map + router -------------------------------------------
        self.assignment = initial_assignment(self.leader_order, self.flows,
                                             self.n_slices)
        self.slice_epochs = {sl: 1 for sl in range(self.n_slices)}
        self.map_version = 1
        self.current_map = self._build_map()
        self._record_map_event()
        for mid in self.leader_order:
            self.seats[mid].apply_map(self.current_map)
        quota = _RecordingQuota(self, divisor=self.divisor,
                                thresholds=dict(self.thresholds))
        self.router = ShardedTokenClient(
            self.current_map, failover_deadline_ms=failover_deadline_ms,
            degraded=quota, health_gate=None, clock=self.clock.now_ms)
        self.fence = self.router.fence
        self.router._pool = {
            mid: LoopbackConn(self, mid, self.specs[mid])
            for mid in self.leader_order}

    # -- helpers -----------------------------------------------------------

    def interval_of(self, fid: int) -> int:
        return int(self.thresholds.get(fid, (0, self.interval_ms))[1])

    def _build_map(self) -> ShardMap:
        owner = [self.leader_order[-1]] * self.n_slices
        for mid, sls in self.assignment.items():
            for sl in sls:
                owner[sl] = mid
        return ShardMap(
            version=self.map_version, n_slices=self.n_slices,
            servers=tuple(self.specs[m] for m in self.leader_order),
            slice_owner=tuple(owner),
            slice_epoch=tuple(self.slice_epochs[sl]
                              for sl in range(self.n_slices)),
            clients=self.clients)

    def _record_map_event(self) -> None:
        """Evidence for the ``slice_conservation`` checker: the full
        ownership/epoch picture at every map adoption, plus the flowId →
        slice attribution (via the one ``slice_of``) so per-slice
        over-admission can be folded from the grant stream."""
        self.history.add(
            "shardMap", version=int(self.map_version), n=int(self.n_slices),
            owners={m: list(sls) for m, sls in self.assignment.items()},
            epochs={int(sl): int(ep)
                    for sl, ep in self.slice_epochs.items()},
            flows={int(fid): slice_of(fid, self.n_slices)
                   for fid in sorted(self.flows)})

    def fire_targeted(self, point: str, mid: str) -> None:
        if self.fault_target.get(point) in (None, mid):
            faults.fire(point)

    def mutate_targeted(self, point: str, mid: str, data: bytes) -> bytes:
        if self.fault_target.get(point) in (None, mid):
            return faults.mutate(point, data)
        return data

    def log_fault(self, kind: str, *args, **kw) -> None:
        self.fault_log.append((kind, args, tuple(sorted(kw.items()))))

    # -- the driven request path -------------------------------------------

    def request(self, fid: int, sec: int) -> str:
        op = self._next_op
        self._next_op += 1
        self.current_op = op
        self.served_by = None
        self.history.add("offered", op=op, flow=fid, sec=sec)
        r = self.router.request_token(fid)
        if r.status == TokenResultStatus.OK:
            status = "pass"
        elif r.status == TokenResultStatus.BLOCKED:
            status = "block"
        elif r.status in _SHED:
            status = "shed"
        else:
            status = "dropped"
        self.history.add("verdict", op=op, flow=fid, status=status,
                         by=self.served_by, sec=sec, wire=int(r.status))
        return status

    # -- scheduled actions -------------------------------------------------

    def apply_action(self, action: dict, injector, sec: int) -> Optional[int]:
        """Execute one schedule item; returns a link-restore second for
        ``link.down`` (the campaign re-raises the link), else None."""
        kind = action["kind"]
        mid = action.get("leader")
        self.log_fault("act:" + kind, mid or "", sec=sec)
        if kind == "conn.drop":
            self.fault_target["cluster.reactor.conn.drop"] = mid
            injector.arm("cluster.reactor.conn.drop", "error",
                         times=action.get("times", 1))
        elif kind == "conn.stall":
            self.fault_target["cluster.reactor.conn.stall"] = mid
            injector.arm("cluster.reactor.conn.stall", "error",
                         times=action.get("times", 1))
        elif kind == "halfopen":
            self.fault_target["cluster.ha.halfopen"] = mid
            injector.arm("cluster.ha.halfopen", "garbage", garbage=b"",
                         times=action.get("times", 1))
        elif kind == "stale.epoch":
            self.fault_target["cluster.ha.stale.epoch"] = mid
            injector.arm("cluster.ha.stale.epoch", "garbage",
                         garbage=(1).to_bytes(8, "big"),
                         times=action.get("times", 1))
        elif kind == "link.down":
            self.link_up[mid] = False
            return sec + int(action.get("secs", 1))
        elif kind == "crash":
            seat = self.seats[mid]
            srv = seat.state.token_server
            if srv is not None and not srv.crashed \
                    and seat.state.mode == CLUSTER_SERVER:
                srv._fault_crash()
                self.crashed.add(mid)
        elif kind == "publish":
            try:
                self.seats[mid].publish_checkpoint()
            except Exception:  # noqa: BLE001 — torn/fenced publish: logged
                self.log_fault("publish.failed", mid, sec=sec)
        elif kind == "torn.publish":
            injector.arm("checkpoint.torn.write", "garbage", times=1)
        elif kind == "ckpt.crash":
            injector.arm("checkpoint.torn.write", "error", times=1)
        elif kind == "journal.full":
            injector.arm("journal.disk.full", "error",
                         times=action.get("times", 1))
        elif kind == "journal.restart":
            host = self.hosts[mid]
            host.journal.close()
            host.journal = ControlPlaneJournal(
                self.clock.now_ms,
                path=os.path.join(self.workdir, f"journal-{mid}.jsonl"))
            self.seats[mid].state.journal = host.journal
        elif kind == "flap":
            self.fault_target["datasource.flap"] = mid
            injector.arm("datasource.flap", "error",
                         times=action.get("times", 1))
        elif kind == "map.split":
            injector.arm("cluster.shard.map.split", "error",
                         after=action.get("after", 0), times=1)
        elif kind == "zombie":
            injector.arm("cluster.shard.donor.zombie", "error", times=1)
        elif kind == "router.stale":
            self._router_skip += 1
        elif kind == "skew":
            try:
                faults.fire("cluster.leader.clock.skew")
            except OSError:
                self.log_fault("skew.vetoed", mid, sec=sec)
            else:
                self.skew_ms[mid] = int(action.get("ms", 0))
        elif kind == "overload":
            srv = self.seats[mid].state.token_server
            if srv is not None and not srv.crashed:
                srv.service.limiter.max_allowed_qps = float(
                    action.get("qps", 2))
        elif kind == "rebalance":
            self.rebalance(action["assignment"], action["epochs"],
                           action["version"])
        else:
            raise ValueError(f"unknown chaos action kind {kind!r}")
        return None

    def rebalance(self, assignment: Dict[str, List[int]],
                  epochs: Dict[int, int], version: int) -> None:
        """Adopt a FULL new assignment (the action is self-contained so
        any shrunken subset of a schedule stays executable): push to
        every live seat (flap/split/zombie seams apply), then to the
        router unless a ``router.stale`` action is pending — recording
        one ``transfer`` event per flow whose slice changed hands."""
        new_assign = {m: sorted(int(s) for s in sls)
                      for m, sls in assignment.items()}
        old_owner = {sl: mid for mid, sls in self.assignment.items()
                     for sl in sls}
        new_owner = {sl: mid for mid, sls in new_assign.items()
                     for sl in sls}
        now = self.clock.now_ms()
        for fid in sorted(self.flows):
            sl = slice_of(fid, self.n_slices)
            if old_owner.get(sl) != new_owner.get(sl):
                self.history.add(
                    "transfer", flow=fid, slice=sl,
                    frm=old_owner.get(sl), to=new_owner.get(sl),
                    win=now - now % self.interval_of(fid))
        self.assignment = new_assign
        self.slice_epochs.update({int(s): int(e) for s, e in epochs.items()})
        self.map_version = max(self.map_version + 1, int(version))
        self.current_map = self._build_map()
        self._record_map_event()
        for mid in self.leader_order:
            if mid in self.crashed:
                continue  # a dead seat gets no pushes (it is dead)
            try:
                self.fire_targeted("datasource.flap", mid)
            except OSError:
                self.log_fault("flap", mid)
                continue
            self.seats[mid].apply_map(self.current_map)
        if self._router_skip > 0:
            self._router_skip -= 1
            self.log_fault("router.stale", "")
        else:
            self.router.apply_map(self.current_map)

    # -- episode-end surfaces ----------------------------------------------

    def collect_journals(self) -> None:
        """Append each seat's DURABLE seq stream to the history (the
        journal-monotonicity checker's evidence; replay() reads the file
        set, so records from before a mid-episode restart are covered)."""
        for mid in self.leader_order:
            seqs = [int(r.get("seq", 0))
                    for r in self.hosts[mid].journal.replay()]
            self.history.add("journal", leader=mid, seqs=seqs)

    def journal_snapshot(self, stamp_ms: int) -> Dict[str, dict]:
        """The forensic join (ISSUE 15): per seat, the journal tail, the
        causeSeq walk from its newest record, and the shard map in force
        at the violation stamp — the PR 13 ``why`` discipline applied to
        a chaos verdict."""
        out = {}
        for mid in self.leader_order:
            j = self.hosts[mid].journal
            out[mid] = {
                "lastSeq": j.last_seq,
                "tail": j.tail(limit=16),
                "chain": j.chain(j.last_seq) if j.last_seq else [],
                "mapInForce": j.in_force(
                    stamp_ms, ("shardMapApply", "clusterMapApply")),
            }
        return out

    def stop(self) -> None:
        self.router.stop()
        for mid in self.leader_order:
            try:
                self.seats[mid].stop()
            except Exception:  # noqa: BLE001 — teardown is best-effort
                pass
            self.hosts[mid].journal.close()

"""The SEMANTICS.md safety bounds as executable invariant checkers.

Every checker is a pure function over an episode's recorded
:class:`History` — no live mesh access — so the same checkers run (a)
continuously during a campaign episode, (b) after it, and (c) in unit
tests against HAND-BUILT violating histories (a checker that cannot
fire is decoration; tests/test_chaos_campaign.py proves each one can).

The catalogue (docs/SEMANTICS.md "Invariant catalogue" maps each to
its prose proof):

* ``conservation`` — pass + block + shed + dropped == offered, per flow
* ``no_stranded`` — every offered op gets exactly ONE terminal verdict
  (no stranded tickets/replies after connection death)
* ``shed_not_half_admitted`` — a leader that shed an op consumed
  nothing for it (shed is pre-admission)
* ``overadmission`` — per (flow, window): effective wire grants <=
  threshold + the handoff margin (grants already standing in the
  window at each ownership transfer) — the per-slice fencing bound
* ``degraded_bound`` — per (flow, window): degraded grants <= the
  per-client share (threshold / divisor)
* ``epoch_monotone`` — the client fence never ACCEPTS an epoch below
  one it already accepted for the same slice lane
* ``journal_monotone`` — each seat's durable journal seq stream is
  strictly increasing, including across crash/restart recovery
* ``slice_conservation`` — every slice has exactly one owner at every
  fence epoch (a move without an epoch bump cannot fence the donor),
  and per-slice over-admission stays within the summed grants-since-
  last-publish bound — the invariant the shard rebalancer (ISSUE 16)
  certifies a plan against before apply
* ``slot_conservation`` — the slot-table admission ledger (ISSUE 20):
  per device slot, admits and evicts strictly alternate at strictly
  increasing generations; every ``slotVerdict`` is attributed to
  exactly ONE (resource, generation) — the slot's standing tenant at
  that point in the stream, never a stale or future occupant of a
  reused slot; and every evict→rehydrate round trip conserves window
  state (grafted + stale window passes never exceed the pass count
  spilled at eviction, and a TORN spill can only rehydrate cold)

Deliberate asymmetries (also in SEMANTICS.md): a verdict granted
server-side whose reply is lost (half-open swallow, fence rejection)
is recorded as ``grantVoid`` — quota was consumed but no request was
admitted, so it counts toward NEITHER conservation's pass column NOR
the over-admission bound (the PR 6 lost-reply double-count stance).
"""

from __future__ import annotations

from collections import Counter, defaultdict
from typing import Dict, List, NamedTuple, Tuple


class Violation(NamedTuple):
    invariant: str
    detail: str
    flow: object = None
    second: object = None

    def to_dict(self) -> dict:
        return {"invariant": self.invariant, "detail": self.detail,
                "flow": self.flow, "second": self.second}


class History:
    """An episode's ordered event log. Events are plain dicts with an
    ``e`` kind tag — hand-buildable in tests, hashable for replay
    oracles, and cheap to scan."""

    __slots__ = ("events",)

    # Terminal verdict categories (the conservation columns).
    TERMINAL = ("pass", "block", "shed", "dropped")

    def __init__(self):
        self.events: List[dict] = []

    def add(self, e: str, **fields) -> dict:
        fields["e"] = e
        self.events.append(fields)
        return fields

    def of(self, kind: str) -> List[dict]:
        return [ev for ev in self.events if ev["e"] == kind]


def check_conservation(history: History, thresholds, divisor) \
        -> List[Violation]:
    offered = Counter(ev["flow"] for ev in history.of("offered"))
    out: List[Violation] = []
    verdicts = history.of("verdict")
    by_flow: Dict[object, Counter] = defaultdict(Counter)
    for ev in verdicts:
        if ev["status"] not in History.TERMINAL:
            out.append(Violation(
                "conservation",
                f"op {ev.get('op')} carries unknown terminal status "
                f"{ev['status']!r}", flow=ev.get("flow"),
                second=ev.get("sec")))
            continue
        by_flow[ev["flow"]][ev["status"]] += 1
    for flow, n_offered in sorted(offered.items(), key=lambda kv: str(kv[0])):
        got = sum(by_flow[flow].values())
        if got != n_offered:
            out.append(Violation(
                "conservation",
                f"flow {flow}: offered {n_offered} != "
                f"pass+block+shed+dropped {got} ({dict(by_flow[flow])})",
                flow=flow))
    return out


def check_no_stranded(history: History, thresholds, divisor) \
        -> List[Violation]:
    offered = [ev["op"] for ev in history.of("offered")]
    verdict_ops = Counter(ev["op"] for ev in history.of("verdict"))
    out: List[Violation] = []
    for op in offered:
        n = verdict_ops.get(op, 0)
        if n == 0:
            out.append(Violation(
                "no_stranded", f"op {op} never received a terminal "
                "verdict (stranded ticket/reply)"))
        elif n > 1:
            out.append(Violation(
                "no_stranded", f"op {op} received {n} terminal verdicts"))
    return out


def check_shed_not_half_admitted(history: History, thresholds, divisor) \
        -> List[Violation]:
    granted_at = {(ev["op"], ev["leader"])
                  for ev in history.events
                  if ev["e"] in ("grant", "grantVoid")}
    out: List[Violation] = []
    for ev in history.of("shedBy"):
        if (ev["op"], ev["leader"]) in granted_at:
            out.append(Violation(
                "shed_not_half_admitted",
                f"leader {ev['leader']} shed op {ev['op']} AND consumed "
                "quota for it (half-admitted shed)", flow=ev.get("flow")))
    return out


def check_overadmission(history: History,
                        thresholds: Dict[int, Tuple[float, int]],
                        divisor) -> List[Violation]:
    """Per (flow, window): effective wire grants <= threshold + margin.

    The margin is credited at each ownership TRANSFER of the flow's
    slice: everything already granted in the transfer's window (and the
    one before it — restored stale rows rotate across the boundary) may
    be re-admitted by the recipient up to the grants-since-last-publish
    bound, so the allowance grows by the standing count. This is a
    deliberately LOOSE (sound) version of the SEMANTICS.md per-slice
    fencing bound: correct code can never exceed it, and an unfenced
    double-granting donor blows through it within one window."""
    counts: Dict[tuple, int] = defaultdict(int)
    margins: Dict[tuple, float] = defaultdict(float)
    for ev in history.events:
        if ev["e"] == "grant":
            counts[(ev["flow"], ev["win"])] += 1
        elif ev["e"] == "transfer":
            flow, win = ev["flow"], ev["win"]
            interval = max(1, int(thresholds.get(flow, (0, 1000))[1]))
            standing = counts[(flow, win)] + counts[(flow, win - interval)]
            for w in (win, win + interval):
                margins[(flow, w)] += standing
    out: List[Violation] = []
    for (flow, win), n in sorted(counts.items(), key=str):
        info = thresholds.get(flow)
        if info is None:
            continue
        allowed = float(info[0]) + margins.get((flow, win), 0.0)
        if n > allowed + 1e-9:
            out.append(Violation(
                "overadmission",
                f"flow {flow} window {win}: {n} wire grants > "
                f"threshold {info[0]} + margin "
                f"{margins.get((flow, win), 0.0)}", flow=flow))
    return out


def check_degraded_bound(history: History,
                         thresholds: Dict[int, Tuple[float, int]],
                         divisor: int) -> List[Violation]:
    counts: Dict[tuple, int] = defaultdict(int)
    for ev in history.of("degradedGrant"):
        counts[(ev["flow"], ev["win"])] += 1
    out: List[Violation] = []
    for (flow, win), n in sorted(counts.items(), key=str):
        info = thresholds.get(flow)
        if info is None:
            continue
        share = float(info[0]) / max(1, int(divisor))
        if n > share + 1e-9:
            out.append(Violation(
                "degraded_bound",
                f"flow {flow} window {win}: {n} degraded grants > "
                f"per-client share {share} (threshold {info[0]} / "
                f"divisor {divisor})", flow=flow))
    return out


def check_epoch_monotone(history: History, thresholds, divisor) \
        -> List[Violation]:
    hi: Dict[object, int] = {}
    out: List[Violation] = []
    for ev in history.of("fence"):
        if not ev.get("accepted"):
            continue
        scope, epoch = ev.get("scope"), int(ev["epoch"])
        if epoch < hi.get(scope, 0):
            out.append(Violation(
                "epoch_monotone",
                f"slice {scope}: accepted epoch {epoch} below the "
                f"lane's high-water mark {hi[scope]} (fence regression)"))
        hi[scope] = max(hi.get(scope, 0), epoch)
    return out


def check_journal_monotone(history: History, thresholds, divisor) \
        -> List[Violation]:
    out: List[Violation] = []
    for ev in history.of("journal"):
        seqs = list(ev.get("seqs") or ())
        for a, b in zip(seqs, seqs[1:]):
            if b <= a:
                out.append(Violation(
                    "journal_monotone",
                    f"seat {ev.get('leader')}: durable journal seq "
                    f"{b} after {a} (non-monotone across "
                    "crash/restart)"))
                break
    return out


def check_slice_conservation(history: History,
                             thresholds: Dict[int, Tuple[float, int]],
                             divisor) -> List[Violation]:
    """Every slice has exactly one owner at every fence epoch, and
    per-slice over-admission stays within the summed grants-since-
    last-publish bound (ISSUE 16 — the invariant the shard rebalancer
    certifies a plan against before it may touch the live mesh).

    Evidence is the ``shardMap`` events each map application records
    (full ownership + per-slice epochs + the flow->slice attribution):

    * structurally, each map must assign every slice to exactly one
      leader — a plan that drops or double-assigns a slice fires here
      before a single request is even driven;
    * across maps, one (slice, fence epoch) pair never names two
      different owners — a move that reuses the standing epoch cannot
      fence the donor (both seats grant at a fence the client
      accepts), which is exactly the broken-plan shape certification
      exists to veto;
    * per (slice, window): total wire grants <= the sum of the slice's
      per-flow allowances (threshold + transfer margin, the same
      arithmetic as ``overadmission``) — the per-slice fold of the
      SEMANTICS.md fencing bound, keyed by window interval so flows on
      different cadences never share a window key."""
    out: List[Violation] = []
    maps = history.of("shardMap")
    flow_slice: Dict[int, int] = {}
    owner_at: Dict[tuple, object] = {}  # (slice, epoch) -> owner
    for ev in maps:
        n = int(ev.get("n", 0))
        owners = ev.get("owners") or {}
        claimed: Dict[int, list] = defaultdict(list)
        for mid in sorted(owners):
            for sl in owners[mid]:
                claimed[int(sl)].append(mid)
        for sl in range(n):
            mids = claimed.get(sl, [])
            if len(mids) != 1:
                out.append(Violation(
                    "slice_conservation",
                    f"map v{ev.get('version')}: slice {sl} has "
                    f"{len(mids)} owners ({mids if mids else 'none'})",
                    second=ev.get("sec")))
        epochs = {int(k): int(v)
                  for k, v in (ev.get("epochs") or {}).items()}
        for sl, mids in sorted(claimed.items()):
            if len(mids) != 1 or sl not in epochs:
                continue
            key = (sl, epochs[sl])
            prev = owner_at.setdefault(key, mids[0])
            if prev != mids[0]:
                out.append(Violation(
                    "slice_conservation",
                    f"slice {sl} changed owner {prev} -> {mids[0]} at "
                    f"the SAME fence epoch {epochs[sl]} (a move without "
                    "an epoch bump cannot fence the donor)",
                    second=ev.get("sec")))
        for f, sl in (ev.get("flows") or {}).items():
            flow_slice[int(f)] = int(sl)
    if not flow_slice:
        return out
    counts: Dict[tuple, int] = defaultdict(int)
    margins: Dict[tuple, float] = defaultdict(float)
    for ev in history.events:
        if ev["e"] == "grant":
            counts[(ev["flow"], ev["win"])] += 1
        elif ev["e"] == "transfer":
            flow, win = ev["flow"], ev["win"]
            interval = max(1, int(thresholds.get(flow, (0, 1000))[1]))
            standing = counts[(flow, win)] + counts[(flow, win - interval)]
            for w in (win, win + interval):
                margins[(flow, w)] += standing
    got: Dict[tuple, int] = defaultdict(int)
    allowed: Dict[tuple, float] = defaultdict(float)
    for (flow, win), n_grants in counts.items():
        info = thresholds.get(flow)
        sl = flow_slice.get(int(flow))
        if info is None or sl is None:
            continue
        key = (sl, int(info[1]), win)
        got[key] += n_grants
        allowed[key] += float(info[0]) + margins.get((flow, win), 0.0)
    for key in sorted(got, key=str):
        if got[key] > allowed[key] + 1e-9:
            sl, _interval, win = key
            out.append(Violation(
                "slice_conservation",
                f"slice {sl} window {win}: {got[key]} wire grants > "
                f"summed per-flow allowance {allowed[key]} (per-slice "
                "grants-since-last-publish bound)"))
    return out


def check_slot_conservation(history: History, thresholds, divisor) \
        -> List[Violation]:
    """The slot-table admission ledger (core/slots.py, ISSUE 20).

    Scans the ordered event stream once, replaying tenancy:

    * ``slotAdmit``/``slotEvict`` strictly alternate per slot, the
      evict names the standing tenant's exact (resource, generation),
      and admit generations are strictly increasing per slot.
    * ``slotVerdict`` attribution: the verdict's (resource, slot, gen)
      must equal the slot's standing tenant — a reused slot must never
      book a verdict against the evicted resource's series (the
      generation-leak defense made executable).
    * ``slotRehydrate`` conservation: ``graftedPass + stalePass`` never
      exceeds the ``spilledPass`` recorded by that resource's most
      recent untorn evict (window passes are a subset of the cumulative
      passes spilled), a from-record graft requires such an evict to
      exist, and a TORN evict forces the next rehydrate cold
      (``fromRecord`` false, nothing grafted).
    """
    out: List[Violation] = []
    standing: Dict[int, Tuple[object, int]] = {}   # slot -> (resource, gen)
    last_gen: Dict[int, int] = {}                  # slot -> last admit gen
    last_evict: Dict[object, dict] = {}            # resource -> evict event
    pending_graft: Dict[int, dict] = {}            # slot -> rehydrate event
    for ev in history.events:
        kind = ev["e"]
        if kind == "slotAdmit":
            slot, gen, res = int(ev["slot"]), int(ev["gen"]), ev["resource"]
            if slot in standing:
                out.append(Violation(
                    "slot_conservation",
                    f"slot {slot}: admit of {res!r}@g{gen} while "
                    f"{standing[slot][0]!r}@g{standing[slot][1]} still "
                    "standing (admits/evicts must alternate)",
                    second=ev.get("sec")))
            if slot in last_gen and gen <= last_gen[slot]:
                out.append(Violation(
                    "slot_conservation",
                    f"slot {slot}: admit generation g{gen} not above the "
                    f"previous admit g{last_gen[slot]} (generations must "
                    "strictly increase per slot)"))
            graft = pending_graft.pop(slot, None)
            if graft is not None and (graft["resource"] != res
                                      or int(graft["gen"]) != gen):
                out.append(Violation(
                    "slot_conservation",
                    f"slot {slot}: rehydrate of {graft['resource']!r}"
                    f"@g{graft['gen']} not claimed by the admit that "
                    f"followed it ({res!r}@g{gen})"))
            standing[slot] = (res, gen)
            last_gen[slot] = gen
        elif kind == "slotEvict":
            slot, gen, res = int(ev["slot"]), int(ev["gen"]), ev["resource"]
            cur = standing.pop(slot, None)
            if cur is None:
                out.append(Violation(
                    "slot_conservation",
                    f"slot {slot}: evict of {res!r}@g{gen} from an "
                    "unoccupied slot"))
            elif cur != (res, gen):
                out.append(Violation(
                    "slot_conservation",
                    f"slot {slot}: evict names {res!r}@g{gen} but the "
                    f"standing tenant is {cur[0]!r}@g{cur[1]}"))
            last_evict[res] = ev
        elif kind == "slotRehydrate":
            slot, res = int(ev["slot"]), ev["resource"]
            grafted = int(ev.get("graftedPass", 0))
            stale = int(ev.get("stalePass", 0))
            prior = last_evict.get(res)
            if ev.get("fromRecord"):
                if prior is None:
                    out.append(Violation(
                        "slot_conservation",
                        f"{res!r}: rehydrate claims a spill record but no "
                        "evict of that resource precedes it"))
                elif prior.get("torn"):
                    out.append(Violation(
                        "slot_conservation",
                        f"{res!r}: rehydrate claims a spill record but the "
                        "most recent evict was TORN (a torn spill must "
                        "rehydrate cold)"))
                elif grafted + stale > int(prior.get("spilledPass", 0)):
                    out.append(Violation(
                        "slot_conservation",
                        f"{res!r}: rehydrate grafted {grafted}+{stale} "
                        f"window passes > {prior.get('spilledPass')} "
                        "passes spilled at eviction (round-trip must "
                        "conserve window state)"))
            elif grafted or stale:
                out.append(Violation(
                    "slot_conservation",
                    f"{res!r}: cold rehydrate (no record) reports "
                    f"grafted={grafted} stale={stale} — nothing may be "
                    "grafted without a spill record"))
            pending_graft[slot] = ev
        elif kind == "slotVerdict":
            slot, gen, res = int(ev["slot"]), int(ev["gen"]), ev["resource"]
            if slot < 0:
                # Cold-lane verdict: attributed to the COLD generation,
                # never to device-slot tenancy — but it must SAY so.
                if gen >= 0:
                    out.append(Violation(
                        "slot_conservation",
                        f"{res!r}: cold-lane verdict (slot {slot}) claims "
                        f"device generation g{gen}", second=ev.get("sec")))
                continue
            cur = standing.get(slot)
            if cur is None:
                out.append(Violation(
                    "slot_conservation",
                    f"slot {slot}: verdict for {res!r}@g{gen} booked "
                    "against an unoccupied slot", second=ev.get("sec")))
            elif cur != (res, gen):
                out.append(Violation(
                    "slot_conservation",
                    f"slot {slot}: verdict for {res!r}@g{gen} but the "
                    f"standing tenant is {cur[0]!r}@g{cur[1]} (every "
                    "verdict must attribute to exactly one "
                    "(resource, generation))", second=ev.get("sec")))
    return out


CHECKERS = (
    ("conservation", check_conservation),
    ("no_stranded", check_no_stranded),
    ("shed_not_half_admitted", check_shed_not_half_admitted),
    ("overadmission", check_overadmission),
    ("degraded_bound", check_degraded_bound),
    ("epoch_monotone", check_epoch_monotone),
    ("journal_monotone", check_journal_monotone),
    ("slice_conservation", check_slice_conservation),
    ("slot_conservation", check_slot_conservation),
)


def check_all(history: History, thresholds: Dict[int, Tuple[float, int]],
              divisor: int) -> List[Violation]:
    out: List[Violation] = []
    for _name, fn in CHECKERS:
        out.extend(fn(history, thresholds, divisor))
    return out

"""Closed-loop adaptive limiting (no reference twin — the reference's
rules are static until an operator or datasource pushes new ones).

The loop senses from the SLO engine + flight recorder
(``controller.py``), bounds every ask with hard safety envelopes
(``envelope.py``), and actuates EXCLUSIVELY through the staged-rollout
lifecycle (``loop.py`` -> ``rollout/manager.py``), so the block-rate
guardrail and SLO auto-abort shield every autonomous change. See
docs/OPERATIONS.md "Adaptive limiting" and docs/SEMANTICS.md
"Actuation safety envelope".
"""

from sentinel_tpu.adaptive.controller import (  # noqa: F401
    AdaptiveController,
    AdaptiveTarget,
    AimdPolicy,
    Policy,
    ResourceSense,
)
from sentinel_tpu.adaptive.envelope import (  # noqa: F401
    EnvelopeDecision,
    FreezeGate,
    FreezeState,
    SafetyEnvelope,
)
from sentinel_tpu.adaptive.loop import AdaptiveLoop  # noqa: F401

package com.alibaba.csp.sentinel.log;

/** Vendored signature stub (see vendored/README.md). Reference:
 * core:log/RecordLog.java. */
public class RecordLog {

    public static void info(String format, Object... args) {
    }

    public static void warn(String format, Object... args) {
    }
}

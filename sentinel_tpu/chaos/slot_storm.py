"""Slot-table eviction-storm chaos campaign (ISSUE 20).

The mesh campaign (``campaign.py``) certifies the SHARDED control
plane; this one certifies the single-engine SLOT TABLE: a real
``SentinelEngine`` in slot mode (bounded device hot set,
evict/rehydrate, cold-tail lease degradation) driven single-threaded
on a :class:`SimClock`, with the two ``slots.*`` fault seams armed:

* ``slots.evict.storm`` — the once-per-second rebalance tick evicts
  EVERY unpinned occupant (worst-case churn, fired above the freeze
  gate exactly like an operator drill);
* ``slots.spill.torn`` — a victim's spill record is torn in flight;
  it must rehydrate COLD, loudly counted, never half-grafted.

Every admit/evict/rehydrate/verdict transition the table emits lands
in a :class:`~sentinel_tpu.chaos.invariants.History`, checked by
``check_slot_conservation`` after each episode: admits/evicts
alternate per slot at strictly increasing generations, every verdict
attributes to exactly one live (resource, generation), and each
evict→rehydrate round trip conserves window state.

An episode is a pure function of ``(campaign_seed, index)``: seeded
Zipf-ish workload over a namespace several times the slot budget,
leaseable-only flow rules (host-exact verdicts — no device timing in
the oracle), program-advanced clock, thread-scoped injector. The
verdict stream and the tenancy transition stream each hash to a
sha256 that replays BIT-IDENTICALLY (tests/test_slots.py pins it).
"""

from __future__ import annotations

import random
from typing import Dict, List, NamedTuple, Optional

from sentinel_tpu import chaos as _pkg
from sentinel_tpu.chaos.campaign import _sha
from sentinel_tpu.chaos.invariants import History, Violation, check_all
from sentinel_tpu.chaos.scheduler import episode_seed
from sentinel_tpu.core.config import config
from sentinel_tpu.core.exceptions import BlockException
from sentinel_tpu.resilience import FaultInjector
from sentinel_tpu.simulator.clock import SimClock


class SlotStormResult(NamedTuple):
    index: int
    seed: int
    verdict_sha256: str
    tenancy_sha256: str
    violations: List[Violation]
    entries: int
    status: Dict

    def to_dict(self) -> dict:
        return {
            "episode": self.index, "episodeSeed": self.seed,
            "verdictSha256": self.verdict_sha256,
            "tenancySha256": self.tenancy_sha256,
            "violations": [v.to_dict() for v in self.violations],
            "entries": self.entries,
            "evictions": self.status.get("evictionsTotal"),
            "rehydrations": self.status.get("rehydrationsTotal"),
            "storms": self.status.get("stormsTotal"),
            "spillTorn": self.status.get("spillTornTotal"),
        }


class SlotStormCampaign:
    """N seed-replayable eviction-storm episodes over one slot table."""

    def __init__(self, campaign_seed: int = 0, episodes: int = 100,
                 seconds: int = 10, per_second: int = 12,
                 slot_budget: int = 8, resources: int = 30,
                 ruled_every: int = 10, ruled_count: int = 4,
                 storm_after: int = 3, torn_probability: float = 0.35):
        self.campaign_seed = int(campaign_seed)
        self.episodes = int(episodes)
        self.seconds = int(seconds)
        self.per_second = max(1, int(per_second))
        self.slot_budget = int(slot_budget)
        self.resources = int(resources)
        self.ruled_every = max(1, int(ruled_every))
        self.ruled_count = int(ruled_count)
        self.storm_after = int(storm_after)
        self.torn_probability = float(torn_probability)
        self.epoch_ms = config.chaos_epoch_ms()

    # -- one episode -------------------------------------------------------

    def run_episode(self, index: int) -> SlotStormResult:
        from sentinel_tpu.core.engine import SentinelEngine
        from sentinel_tpu.models.flow import FlowRule

        seed = episode_seed(self.campaign_seed, index)
        clock = SimClock(self.epoch_ms)
        history = History()
        rng = random.Random(seed)
        names = [f"storm-res-{i}" for i in range(self.resources)]
        # Zipf-ish popularity: deterministic weights, seeded draws — the
        # hot head churns with the cold tail exactly as the telescope
        # expects, and two runs of one seed draw the identical stream.
        weights = [1.0 / (i + 1) ** 1.2 for i in range(self.resources)]
        eng = None
        entries = 0
        try:
            # scope_thread: the storm/torn seams fire ONLY on this
            # driver thread — a live host engine in the same process
            # neither eats the episode's fault budget nor suffers it.
            with FaultInjector(seed=seed, scope_thread=True) as injector:
                injector.arm("slots.evict.storm", mode="error",
                             after=self.storm_after, times=2)
                injector.arm("slots.spill.torn", mode="error",
                             probability=self.torn_probability)
                eng = SentinelEngine(clock=clock.now_ms, journal_path="",
                                     slot_budget=self.slot_budget)
                eng.slots.event_sink = history.events.append
                # Leaseable-only rules: host-exact verdicts, so the
                # oracle stream is a pure function of the draw sequence.
                eng.flow_rules.load_rules([
                    FlowRule(resource=names[i], count=self.ruled_count)
                    for i in range(0, self.resources, self.ruled_every)])
                for _ in range(self.seconds):
                    for _ in range(self.per_second):
                        res = rng.choices(names, weights=weights)[0]
                        entries += 1
                        try:
                            eng.entry(res).exit()
                        except BlockException:
                            pass
                    clock.advance(1000)
                    # Land leased commits + run the rebalance tick (the
                    # storm seam fires inside on_spill).
                    eng.slo_refresh(clock.now_ms())
                status = eng.slots.status()
        finally:
            if eng is not None:
                eng.close()
        violations = check_all(history, {}, 1)
        verdict_sha = _sha(
            f"{ev['sec']}:{ev['resource']}:{ev['verdict']}:{ev['reason']}"
            for ev in history.of("slotVerdict"))
        tenancy_sha = _sha(
            f"{ev['e']}:{ev['resource']}:{ev['slot']}:{ev['gen']}"
            for ev in history.events
            if ev["e"] in ("slotAdmit", "slotEvict", "slotRehydrate"))
        _pkg._count(episodes=1, violations=len(violations),
                    faultsFired=int(status.get("stormsTotal", 0))
                    + int(status.get("spillTornTotal", 0)))
        return SlotStormResult(index, seed, verdict_sha, tenancy_sha,
                               violations, entries, status)

    # -- the campaign ------------------------------------------------------

    def run(self) -> dict:
        import time

        t0 = time.perf_counter()  # duration only, never a timestamp
        results: List[SlotStormResult] = []
        first_violation: Optional[dict] = None
        for index in range(self.episodes):
            result = self.run_episode(index)
            results.append(result)
            if result.violations and first_violation is None:
                first_violation = result.to_dict()
        wall = max(time.perf_counter() - t0, 1e-9)
        return {
            "campaignSeed": self.campaign_seed,
            "episodes": len(results),
            "entries": sum(r.entries for r in results),
            "evictions": sum(int(r.status.get("evictionsTotal", 0))
                             for r in results),
            "rehydrations": sum(int(r.status.get("rehydrationsTotal", 0))
                                for r in results),
            "storms": sum(int(r.status.get("stormsTotal", 0))
                          for r in results),
            "spillTorn": sum(int(r.status.get("spillTornTotal", 0))
                             for r in results),
            "violations": sum(len(r.violations) for r in results),
            "firstViolation": first_violation,
            "episodesPerSec": round(len(results) / wall, 3),
            "verdictSha256": _sha(r.verdict_sha256 for r in results),
            "tenancySha256": _sha(r.tenancy_sha256 for r in results),
        }

"""Event-driven wire frontend for the token server (ISSUE 11).

One ``selectors``-based I/O loop multiplexes every client connection
(thousands of sockets, zero threads parked on reads), a zero-copy
``FrameScanner`` (cluster/codec.py) parses TLV frames as memoryview
slices straight off each recv chunk, and a coalescing collector drains
ALL ready connections per loop cycle into ONE fused-step group through
the server's bounded, deadline-tagged admission batcher — which itself
pipelines up to ``csp.sentinel.wire.inflight.depth`` fused batches on
the device stream via the token service's enqueue-only dispatch/harvest
split (the PR 8 pattern applied to the wire path).

Replies multiplex back per connection with COALESCED writes: every
request gets an ordered reply slot at parse time; a harvester thread
fills slots as fused batches resolve; the reactor flushes each
connection's contiguous filled prefix as one buffer per flush (never a
write per request), preserving per-connection FIFO regardless of which
worker or harvest filled which slot (docs/SEMANTICS.md "Coalescing
ordering"). Non-FLOW frames (ENTRY/EXIT/PARAM_FLOW — engine work) run
on a small compute-only worker pool so the I/O loop never blocks.

Backpressure: a slow consumer's reply backlog is bounded by
``csp.sentinel.wire.outbuf.max.bytes`` — past it the connection stops
being read (TCP backpressure upstream) and requests already parsed shed
OVERLOADED (``outbufShed`` counts them); reply bytes never grow
unboundedly. A connection that dies mid-harvest simply drops its
verdicts (``droppedReplies``) — no strand, no stalled batch.

Chaos parity: reply bytes pass the same ``cluster.server.frame`` /
``cluster.ha.halfopen`` mutate seams as the legacy frontend
(server.mutate_reply), and epoch stamping rides the shared
``build_flow_reply`` encoder, so the wire stays byte-identical between
the two frontends (pinned by tests/test_wire.py).
"""

from __future__ import annotations

import queue
import selectors
import socket
import threading
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from sentinel_tpu.cluster import codec
from sentinel_tpu.cluster.constants import (
    MSG_FLOW,
    MSG_PING,
    TokenResultStatus,
)
from sentinel_tpu.cluster.server import (
    build_flow_reply,
    mutate_reply,
    process_control_frame,
)
from sentinel_tpu.resilience import faults
_LISTEN_BACKLOG = 256  # the legacy frontend's reconnect-storm headroom

# Estimated bytes per PROMISED reply (an unfilled slot): the backlog
# bound must count replies the connection is owed, not just bytes
# already encoded — replies materialize only at harvest, so a flood
# parsed in one chunk would otherwise sail past the bound before a
# single byte of it is queued. A FLOW reply is 16-40 bytes on the wire.
_REPLY_EST_BYTES = 24


class _Conn:
    """Per-connection reactor state. ``replies`` is the ordered slot
    ring: one single-element list per in-flight request, filled (from
    any thread) with the encoded reply bytes; the reactor pops and
    writes only the contiguous filled prefix, so the byte stream always
    answers requests in arrival order."""

    __slots__ = ("sock", "fd", "scanner", "namespace", "remote_entries",
                 "replies", "outq", "out_off", "out_bytes", "last_active",
                 "paused", "closed", "tasks", "task_running", "task_lock")

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.fd = sock.fileno()
        self.scanner = codec.FrameScanner()
        self.namespace: Optional[str] = None
        self.remote_entries: Dict[int, object] = {}
        self.replies: deque = deque()
        self.outq: deque = deque()
        self.out_off = 0
        self.out_bytes = 0
        self.last_active = time.monotonic()
        self.paused = False
        self.closed = False
        self.tasks: deque = deque()
        self.task_running = False
        self.task_lock = threading.Lock()


class WireReactor:
    """The selectors loop + harvester + compute pool behind
    :class:`~sentinel_tpu.cluster.server.ClusterTokenServer`."""

    def __init__(self, server):
        from sentinel_tpu.core.config import config

        self.server = server
        self.coalesce_max = config.wire_coalesce_max_batch()
        self.outbuf_max = config.wire_outbuf_max_bytes()
        self.read_chunk = config.wire_read_chunk_bytes()
        self.n_workers = config.wire_workers()
        self._sel = selectors.DefaultSelector()
        self._listener: Optional[socket.socket] = None
        self._waker_r: Optional[socket.socket] = None
        self._waker_w: Optional[socket.socket] = None
        self._conns: Dict[int, _Conn] = {}
        # (conn, xid, slot, req, t_arrival, t_staged)
        self._staged: List[tuple] = []
        # Latency-waterfall recorder (ISSUE 18): resolved by the owning
        # server before it constructs us (engine-attached servers only —
        # never boots the engine). None => per-request stamp work is
        # skipped entirely; the A/B dispatch-count guard pins that the
        # enabled path adds zero device work either way.
        self._wf = getattr(server.batcher, "waterfall", None)
        self._dirty_lock = threading.Lock()
        self._dirty: set = set()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._harvester: Optional[threading.Thread] = None
        self._pool = None
        # Bounded hand-off to the harvester: items exist only for groups
        # the bounded admission queue ADMITTED, so this can never grow
        # past (queue bound + in-flight depth); the margin is headroom.
        cap = server.batcher.max_queue_groups * 2 + 16
        self._harvest_q: "queue.Queue" = queue.Queue(maxsize=cap)
        # -- wire stats (sentinel_tpu_wire_* source) ----------------------
        self._stats_lock = threading.Lock()
        self.connections_total = 0
        self.outbuf_shed = 0
        self.dropped_replies = 0
        self.fused_batches = 0
        self.fused_requests = 0
        self._batch_sizes: deque = deque(maxlen=512)
        self._rtt_ms: deque = deque(maxlen=2048)       # arrival -> reply built
        self._coalesce_wait_ms: deque = deque(maxlen=2048)  # arrival -> submit
        self._queue_wait_ms: deque = deque(maxlen=2048)     # submit -> harvest

    # -- lifecycle ---------------------------------------------------------

    @property
    def bound_port(self) -> int:
        return self._listener.getsockname()[1] if self._listener else 0

    def attach_waterfall(self, recorder) -> None:
        """Late attach (engine booted after server start): subsequent
        requests start carrying stage-stamp records."""
        self._wf = recorder

    def start(self) -> "WireReactor":
        import concurrent.futures

        # Bind synchronously so an EADDRINUSE surfaces to the caller
        # (role flips must fail honestly, cluster/state.py semantics).
        lst = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        lst.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            lst.bind((self.server.host, self.server.port))
            lst.listen(_LISTEN_BACKLOG)
        except OSError:
            lst.close()
            raise
        lst.setblocking(False)
        self._listener = lst
        self._waker_r, self._waker_w = socket.socketpair()
        self._waker_r.setblocking(False)
        # Non-blocking writes too: a full waker buffer means a wake is
        # already pending — the send's only job is edge-triggering, and
        # a blocking write could park a harvester/worker against a
        # reactor that is busy (or stopping).
        self._waker_w.setblocking(False)
        self._sel.register(lst, selectors.EVENT_READ, "accept")
        self._sel.register(self._waker_r, selectors.EVENT_READ, "wake")
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=self.n_workers,
            thread_name_prefix="sentinel-wire-worker")
        self._harvester = threading.Thread(
            target=self._harvest_loop, name="sentinel-wire-harvester",
            daemon=True)
        self._harvester.start()
        self._thread = threading.Thread(
            target=self._loop, name="sentinel-wire-reactor", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._wake()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        if self._harvester is not None:
            self._harvester.join(timeout=2.0)
            self._harvester = None
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None

    def _wake(self) -> None:
        try:
            if self._waker_w is not None:
                self._waker_w.send(b"\0")
        except OSError:
            pass

    # -- the I/O loop ------------------------------------------------------

    def _loop(self) -> None:
        last_sweep = time.monotonic()
        try:
            while not self._stop.is_set():
                events = self._sel.select(timeout=0.05)
                for key, mask in events:
                    kind = key.data
                    if kind == "accept":
                        self._accept()
                    elif kind == "wake":
                        try:
                            while self._waker_r.recv(4096):
                                pass
                        except (BlockingIOError, OSError):
                            pass
                    else:
                        conn = kind
                        if mask & selectors.EVENT_READ:
                            self._read(conn)
                        if mask & selectors.EVENT_WRITE and not conn.closed:
                            self._try_send(conn)
                # Coalesce: everything staged this cycle goes out as
                # fused-step group(s) through the bounded batcher.
                if self._staged:
                    self._submit_staged()
                # Flush connections whose slots got filled off-loop.
                if self._dirty:
                    with self._dirty_lock:
                        dirty, self._dirty = self._dirty, set()
                    for conn in dirty:
                        if not conn.closed:
                            self._flush(conn)
                now = time.monotonic()
                if now - last_sweep >= 0.5:
                    last_sweep = now
                    self._sweep_idle(now)
        finally:
            for conn in list(self._conns.values()):
                self._close(conn)
            for sock in (self._listener, self._waker_r, self._waker_w):
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
            self._listener = None
            self._sel.close()

    def _accept(self) -> None:
        while True:
            try:
                sock, _addr = self._listener.accept()
            except (BlockingIOError, OSError):
                return
            sock.setblocking(False)
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            conn = _Conn(sock)
            self._conns[conn.fd] = conn
            with self._stats_lock:
                self.connections_total += 1
            try:
                self._sel.register(sock, selectors.EVENT_READ, conn)
            except (ValueError, OSError):
                self._close(conn)

    def _interest(self, conn: _Conn) -> None:
        """Recompute a live connection's selector interest set."""
        events = 0
        if not conn.paused:
            events |= selectors.EVENT_READ
        if conn.outq:
            events |= selectors.EVENT_WRITE
        try:
            if events:
                self._sel.modify(conn.sock, events, conn)
            else:
                # Fully quiesced (paused, nothing to write): parked until
                # a flush or resume re-registers it.
                self._sel.unregister(conn.sock)
        except (KeyError, ValueError, OSError):
            if events:
                try:
                    self._sel.register(conn.sock, events, conn)
                except (KeyError, ValueError, OSError):
                    pass

    @staticmethod
    def _backlog(conn: _Conn) -> int:
        """The connection's reply backlog: bytes queued for the socket
        plus an estimate for every reply still OWED (unfilled or
        unflushed slots) — the quantity the outbuf bound actually
        limits."""
        return conn.out_bytes + len(conn.replies) * _REPLY_EST_BYTES

    def _read(self, conn: _Conn) -> None:
        # Chaos seams (resilience/faults.py — ISSUE 15): conn.stall in
        # delay mode wedges this read (a saturated loop / stuck peer);
        # conn.drop in error mode kills the connection mid-stream — the
        # peer sees a clean drop and the close path must strand nothing
        # (remote entries exited, reply slots discarded).
        try:
            faults.fire("cluster.reactor.conn.stall")
            faults.fire("cluster.reactor.conn.drop")
        except OSError:
            self._close(conn)
            return
        try:
            chunk = conn.sock.recv(self.read_chunk)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._close(conn)
            return
        if not chunk:
            self._close(conn)
            return
        conn.last_active = time.monotonic()
        t_arrival = time.perf_counter()
        shed_retry = self.server.batcher.retry_after_ms
        wf = self._wf
        for frame in conn.scanner.feed(chunk):
            try:
                req = codec.decode_request(frame)
            except Exception:  # noqa: BLE001 — garbled frame: drop the conn
                self._close(conn)
                return
            # Slot ring cell: [reply_bytes, waterfall_stamp_record].
            # _flush keys on [0]; [1] stays None for control frames,
            # sheds, and stamp-disabled runs.
            slot = [None, None]
            conn.replies.append(slot)
            if req.msg_type == MSG_FLOW:
                if self._backlog(conn) > self.outbuf_max:
                    # Slow-consumer shed: the reply backlog is over its
                    # bound — answer OVERLOADED without device work
                    # instead of growing the backlog further.
                    with self._stats_lock:
                        self.outbuf_shed += 1
                    slot[0] = build_flow_reply(
                        self.server, req.xid, None, shed_retry)
                    continue
                try:
                    r = codec.decode_flow_request(req.entity)
                    if len(req.entity) > codec.FLOW_REQ_SIZE:
                        tp = codec.read_trace_tlv(
                            req.entity, codec.FLOW_REQ_SIZE)
                        if tp:
                            from sentinel_tpu.telemetry.spans import (
                                parse_traceparent,
                            )

                            ctx = parse_traceparent(tp)
                            if ctx is not None:
                                r = r + (ctx,)
                except Exception:  # noqa: BLE001 — undecodable entity
                    slot[0] = codec.encode_response(
                        req.xid, MSG_FLOW, TokenResultStatus.BAD_REQUEST)
                    continue
                # Waterfall "read" stage boundary: parse+stage done for
                # THIS frame (per-frame stamp only while capturing).
                t_staged = time.perf_counter() if wf is not None \
                    else t_arrival
                self._staged.append(
                    (conn, req.xid, slot, r, t_arrival, t_staged))
            elif req.msg_type == MSG_PING and not conn.task_running \
                    and not conn.tasks:
                # Cheap + ordering-safe inline (no compute work queued).
                self._fill_control(conn, req.materialized(), slot)
            else:
                self._enqueue_task(conn, req.materialized(), slot)
        self._flush(conn)
        # Read-side backpressure: past the outbuf bound, stop reading —
        # the kernel's socket buffers push back on the sender.
        if self._backlog(conn) > self.outbuf_max and not conn.paused:
            conn.paused = True
            self._interest(conn)

    # -- coalescing submit + harvest --------------------------------------

    def _submit_staged(self) -> None:
        staged, self._staged = self._staged, []
        batcher = self.server.batcher
        burst_cap = self.server.conn_max_burst
        while staged:
            reqs: List[tuple] = []
            routing: List[tuple] = []
            rest: List[tuple] = []
            per_conn: Dict[int, int] = {}
            t_first = staged[0][4]
            for item in staged:
                fd = item[0].fd
                if (len(reqs) >= self.coalesce_max
                        or per_conn.get(fd, 0) >= burst_cap):
                    rest.append(item)
                    continue
                per_conn[fd] = per_conn.get(fd, 0) + 1
                reqs.append(item[3])
                routing.append(item)
            t_submit = time.perf_counter()
            # No explicit budget: submit_many builds its own AFTER the
            # watermark check, so shed groups allocate nothing.
            done, box = batcher.submit_many(reqs)
            with self._stats_lock:
                self.fused_batches += 1
                self.fused_requests += len(reqs)
                self._batch_sizes.append(len(reqs))
                self._coalesce_wait_ms.append((t_submit - t_first) * 1e3)
            if done.is_set():
                # Shed (or an already-resolved stub): reply inline.
                self._resolve(done, box, routing, t_submit)
            else:
                try:
                    self._harvest_q.put_nowait((done, box, routing, t_submit))
                except queue.Full:
                    # Harvester stalled far behind (the cap bounds
                    # admission-queue residents, not drained-but-
                    # unresolved items): the group is ADMITTED — its
                    # tokens will be granted — so resolve it inline
                    # with its REAL box rather than faking a FAIL for
                    # verdicts the device is about to (or did) commit.
                    done.wait(timeout=max(
                        5.0, batcher.deadline_ms / 1000.0 + 1.0))
                    self._resolve(done, box, routing, t_submit)
            staged = rest

    def _harvest_loop(self) -> None:
        batcher = self.server.batcher
        wait_s = max(5.0, batcher.deadline_ms / 1000.0 + 1.0)
        while not self._stop.is_set():
            try:
                done, box, routing, t_submit = self._harvest_q.get(
                    timeout=0.1)
            except queue.Empty:
                continue
            done.wait(timeout=wait_s + len(routing) * 0.01)
            self._resolve(done, box, routing, t_submit)

    def _resolve(self, done, box, routing, t_submit) -> None:
        """Fill every routed reply slot from a completed (or failed)
        group; runs on the harvester thread or, for pre-set groups,
        inline on the reactor thread."""
        results = box.get("results")
        shed_retry = box.get("shed_retry_after_ms")
        t_done = time.perf_counter()
        # Waterfall stamps (ISSUE 18): admitted groups carry the
        # batcher's drain/dispatch/device marks; together with the
        # reactor-side marks they chain gap-free into the 8-stage
        # record _flush observes. Sheds/fails carry no stamps.
        wf_stamps = box.get("wfStamps") if self._wf is not None else None
        dirty = set()
        dropped = 0
        for k, item in enumerate(routing):
            conn, xid, slot, _req, t_arrival = item[0], item[1], item[2], \
                item[3], item[4]
            result = results[k] if results else None
            slot[0] = build_flow_reply(self.server, xid, result, shed_retry)
            if wf_stamps is not None:
                ctx = _req[3] if len(_req) > 3 else None
                slot[1] = (t_arrival, item[5], t_submit, wf_stamps, t_done,
                           ctx.trace_id if ctx is not None else None)
            if conn.closed:
                dropped += 1
            else:
                dirty.add(conn)
            self._rtt_ms.append((t_done - t_arrival) * 1e3)
        self._queue_wait_ms.append((t_done - t_submit) * 1e3)
        if dropped:
            with self._stats_lock:
                self.dropped_replies += dropped
        if dirty:
            with self._dirty_lock:
                self._dirty.update(dirty)
            self._wake()

    # -- non-FLOW compute (worker pool) ------------------------------------

    def _fill_control(self, conn: _Conn, req: codec.Request, slot) -> None:
        try:
            reply, conn.namespace = process_control_frame(
                self.server, req, conn.remote_entries, conn.namespace)
        except Exception:  # noqa: BLE001 — engine death must not kill I/O
            reply = codec.encode_response(
                req.xid, req.msg_type, TokenResultStatus.FAIL)
        slot[0] = reply

    def _enqueue_task(self, conn: _Conn, req: codec.Request, slot) -> None:
        with conn.task_lock:
            conn.tasks.append((req, slot))
            if not conn.task_running:
                conn.task_running = True
                self._pool.submit(self._run_conn_tasks, conn)

    def _run_conn_tasks(self, conn: _Conn) -> None:
        """Drain one connection's control-frame queue sequentially: a
        connection's ENTRY/EXIT stream keeps its order (the slot ring
        already keeps the REPLY order) while different connections run
        in parallel across the pool."""
        while True:
            with conn.task_lock:
                if not conn.tasks:
                    conn.task_running = False
                    break
                req, slot = conn.tasks.popleft()
            self._fill_control(conn, req, slot)
        with self._dirty_lock:
            self._dirty.add(conn)
        self._wake()

    # -- writes ------------------------------------------------------------

    def _flush(self, conn: _Conn) -> None:
        """Coalesce the contiguous filled reply prefix into ONE buffer
        (never a write per request) and push it down the socket. Slots
        carrying a waterfall stamp record complete their 8-stage chain
        here (reply-slot wait ends at the pick, flush ends after the
        bytes are handed to the socket layer) and land in the recorder."""
        wf = self._wf
        t_pick = time.perf_counter() if wf is not None else 0.0
        chunks = []
        recs = None
        while conn.replies and conn.replies[0][0] is not None:
            slot = conn.replies.popleft()
            chunks.append(slot[0])
            if slot[1] is not None:
                if recs is None:
                    recs = []
                recs.append(slot[1])
        if chunks:
            data = mutate_reply(b"".join(chunks))
            if data:
                conn.outq.append(data)
                conn.out_bytes += len(data)
        self._try_send(conn)
        if recs and wf is not None:
            t_sent = time.perf_counter()
            for (t_arr, t_stg, t_sub, (t_drn, t_dsp, t_dev), t_fill,
                 trace_id) in recs:
                wf.observe_wire((
                    (t_stg - t_arr) * 1e3,   # read: recv -> parse+stage
                    (t_sub - t_stg) * 1e3,   # coalesce: stage -> submit
                    (t_drn - t_sub) * 1e3,   # queue: submit -> drain
                    (t_dsp - t_drn) * 1e3,   # dispatch: drain -> device
                    (t_dev - t_dsp) * 1e3,   # device: dispatch -> harvest
                    (t_fill - t_dev) * 1e3,  # harvest: wake -> slot fill
                    (t_pick - t_fill) * 1e3,  # reply: fill -> flush pick
                    (t_sent - t_pick) * 1e3), trace_id)  # flush

    def _try_send(self, conn: _Conn) -> None:
        while conn.outq:
            head = conn.outq[0]
            try:
                sent = conn.sock.send(
                    memoryview(head)[conn.out_off:])
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                self._close(conn)
                return
            conn.out_bytes -= sent
            conn.out_off += sent
            if conn.out_off >= len(head):
                conn.outq.popleft()
                conn.out_off = 0
            elif sent == 0:
                break
        if conn.paused and self._backlog(conn) <= self.outbuf_max // 2:
            conn.paused = False
        self._interest(conn)

    # -- cleanup -----------------------------------------------------------

    def _sweep_idle(self, now: float) -> None:
        limit = self.server.idle_timeout_s
        for conn in list(self._conns.values()):
            if now - conn.last_active > limit:
                self._close(conn)

    def _close(self, conn: _Conn) -> None:
        if conn.closed:
            return
        conn.closed = True
        self._conns.pop(conn.fd, None)
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError, OSError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass
        if conn.namespace is not None:
            self.server.service.connections.disconnect(conn.namespace)
            conn.namespace = None
        # A dead peer must not leak thread counts: exit whatever its
        # connection still holds (the legacy handler's finally-block
        # semantics — a dropped link is not a biz exception).
        for handle in conn.remote_entries.values():
            try:
                handle.exit()
            except Exception:  # noqa: BLE001 — best-effort drain
                pass
        conn.remote_entries.clear()
        # Unsent slots are simply discarded; droppedReplies counts ONLY
        # verdicts resolved after their connection died (_resolve sees
        # conn.closed) — counting unfilled slots here too would tally
        # the same dropped verdict twice once its harvest lands.
        conn.replies.clear()

    # -- introspection -----------------------------------------------------

    def wire_stats(self) -> dict:
        """Snapshot for the ``sentinel_tpu_wire_*`` families and the
        ``getClusterMode``/dashboard surfaces. Lock-light: deque
        snapshots + plain counters."""
        def pct(ring, q):
            if not ring:
                return 0.0
            return round(float(np.percentile(np.asarray(ring), q)), 3)

        sizes = list(self._batch_sizes)
        return {
            "connections": len(self._conns),
            "connectionsTotal": self.connections_total,
            "fusedBatches": self.fused_batches,
            "fusedRequests": self.fused_requests,
            "coalescedBatchP50": pct(sizes, 50),
            "coalescedBatchMax": max(sizes) if sizes else 0,
            "rttP50Ms": pct(list(self._rtt_ms), 50),
            "rttP99Ms": pct(list(self._rtt_ms), 99),
            "coalesceWaitP50Ms": pct(list(self._coalesce_wait_ms), 50),
            "queueWaitP50Ms": pct(list(self._queue_wait_ms), 50),
            "outbufShed": self.outbuf_shed,
            "droppedReplies": self.dropped_replies,
            "outbufMaxBytes": self.outbuf_max,
            "coalesceMaxBatch": self.coalesce_max,
            "inflightDepth": self.server.batcher.inflight_depth,
        }

"""Circuit-breaker demo (reference: ``sentinel-demo-basic`` degrade demos):
an exception-ratio breaker OPENs under failures, rejects while open, then
HALF_OPENs a probe and CLOSEs when the service recovers."""

import _demo_env  # noqa: F401

import time

import sentinel_tpu as st
from sentinel_tpu.core import constants as C

st.load_degrade_rules([st.DegradeRule(
    resource="svc", grade=C.DEGRADE_GRADE_EXCEPTION_RATIO, count=0.5,
    time_window=2, min_request_amount=5, stat_interval_ms=1000)])

broken = True


def call_service():
    with st.entry("svc") as h:
        if broken:
            h.trace(RuntimeError("backend down"))
            return "error"
        return "ok"


st.entry_ok("_warmup")  # absorb the XLA compile before the timed loop

phase = "failing"
for i in range(40):
    if i == 15:
        broken = False
        phase = "recovered"
    try:
        result = call_service()
        print(f"{i:2d} [{phase}] call -> {result}")
    except st.DegradeException:
        print(f"{i:2d} [{phase}] SHORT-CIRCUITED (breaker open)")
    time.sleep(0.2)

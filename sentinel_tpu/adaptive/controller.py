"""Sensing + policy layer of the closed adaptive loop.

"Multi-Objective Adaptive Rate Limiting in Microservices Using Deep
Reinforcement Learning" (PAPERS.md) motivates limits that track load
instead of static QPS. This module is the half that DECIDES what a
better limit would be; it never touches the engine's rules — the loop
(``loop.py``) carries every decision through the staged-rollout
lifecycle, and the envelope (``envelope.py``) bounds it first.

Pieces:

* :class:`AdaptiveTarget` — the per-resource objective an operator
  declares: keep the block rate at/below ``max_block_rate`` (and,
  optionally, RT p99 at/below ``rt_p99_ms``) by tuning the resource's
  simple QPS flow rule within ``[floor, ceiling]``.
* :class:`ResourceSense` — what one evaluation window actually saw:
  pass/block totals and the RT p99 estimate, folded from the flight
  recorder's exact per-second series (``engine.timeseries_view``).
* :class:`Policy` — the narrow protocol a controller implements:
  ``propose(sense, target, current) -> new threshold | None``. One
  pure function of explicit inputs, so learned controllers (the DRL
  direction) plug in without touching loop or envelope code.
* :class:`AimdPolicy` — the shipped default: additive-flavored
  multiplicative increase while blocking exceeds the target with
  healthy RT, multiplicative decrease when RT p99 breaches (the
  congestion signal), deadband around both targets so an on-target
  resource proposes nothing.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Protocol

from sentinel_tpu.telemetry.attribution import histogram_quantile

DEFAULT_MIN_ENTRIES = 32


@dataclass(frozen=True)
class AdaptiveTarget:
    """One resource's adaptive objective + hard actuation band."""

    resource: str
    max_block_rate: float = 0.05   # keep block/(pass+block) at/below this
    rt_p99_ms: float = 0.0         # 0 = no RT target (availability only)
    floor: float = 1.0             # hard band: tuned count never leaves
    ceiling: float = 1_000_000.0   # [floor, ceiling], whatever the policy
    min_entries: int = DEFAULT_MIN_ENTRIES  # quieter windows don't vote

    def validate(self) -> "AdaptiveTarget":
        if not self.resource:
            raise ValueError("adaptive target needs a resource")
        if not 0.0 <= self.max_block_rate < 1.0:
            raise ValueError(
                f"maxBlockRate {self.max_block_rate} not in [0, 1)")
        if self.rt_p99_ms < 0:
            raise ValueError(f"rtP99Ms {self.rt_p99_ms} negative")
        if self.floor <= 0:
            raise ValueError(f"floor {self.floor} must be positive")
        if self.ceiling < self.floor:
            raise ValueError(
                f"ceiling {self.ceiling} below floor {self.floor}")
        if self.min_entries < 0:
            raise ValueError(f"minEntries {self.min_entries} negative")
        return self


@dataclass(frozen=True)
class ResourceSense:
    """One sense window's exact observation for one resource."""

    resource: str
    seconds: int         # complete seconds with traffic in the window
    passed: int
    blocked: int
    completions: int     # successful exits (RT histogram mass)
    block_rate: float    # blocked / (passed + blocked), 0 when idle
    rt_p99_ms: float     # histogram-estimated p99, 0 when no completions

    @property
    def entries(self) -> int:
        return self.passed + self.blocked


class Policy(Protocol):
    """The pluggable brain: desired new threshold for ONE resource.

    Implementations must be pure (no engine access, no clock reads —
    everything arrives in the arguments) and return ``None`` when no
    change is warranted. The envelope clamps whatever comes back, so a
    policy cannot escape the floor/ceiling/step bounds however wrong
    its output is.
    """

    name: str

    def propose(self, sense: ResourceSense, target: AdaptiveTarget,
                current: float) -> Optional[float]:
        ...  # pragma: no cover - protocol signature


class AimdPolicy:
    """AIMD on the block-rate target, gated by the RT-p99 target.

    * RT p99 above target (outside the deadband) -> multiplicative
      DECREASE (``x (1 - decrease_pct)``): the resource is congested;
      admitting less is the only lever a limiter has.
    * Block rate above target (outside the deadband) with RT healthy ->
      increase (``x (1 + increase_pct)``): demand exceeds the limit and
      the backend has headroom, so the limit is what's hurting.
    * Inside both deadbands -> ``None``. The deadband is the policy half
      of the no-flapping story (the envelope's flip cooldown is the
      other): a sense sitting ON the target proposes nothing in either
      direction.

    Block rate never triggers a decrease: blocking BELOW target means
    the limit is simply not binding, and shrinking an idle resource's
    limit buys nothing but a worse cold start when traffic returns
    (documented in docs/OPERATIONS.md "Adaptive limiting").
    """

    name = "aimd"

    def __init__(self, increase_pct: float, decrease_pct: float,
                 hysteresis_pct: float):
        self.increase_pct = float(increase_pct)
        self.decrease_pct = float(decrease_pct)
        self.hysteresis_pct = float(hysteresis_pct)

    def propose(self, sense: ResourceSense, target: AdaptiveTarget,
                current: float) -> Optional[float]:
        if sense.entries < max(target.min_entries, 1):
            return None
        if target.rt_p99_ms > 0 and sense.completions > 0 \
                and sense.rt_p99_ms \
                > target.rt_p99_ms * (1.0 + self.hysteresis_pct):
            return current * (1.0 - self.decrease_pct)
        # Deadband floor of 0.01 absolute: a 0-target (block nothing,
        # ever) still needs a non-empty band to not flap on a single
        # blocked entry in a million.
        band = max(target.max_block_rate * self.hysteresis_pct, 0.01)
        if sense.block_rate > target.max_block_rate + band:
            return current * (1.0 + self.increase_pct)
        return None


class AdaptiveController:
    """Targets + policy + sense folding for one engine's loop."""

    def __init__(self, policy):
        self.policy = policy
        self._targets: Dict[str, AdaptiveTarget] = {}

    # -- targets (wholesale load, the same §3.2 stance as rule families) --

    def load_targets(self, targets: List[AdaptiveTarget]) -> None:
        validated = [t.validate() for t in targets]
        new: Dict[str, AdaptiveTarget] = {}
        for t in validated:
            if t.resource in new:
                raise ValueError(
                    f"duplicate adaptive target for {t.resource!r}")
            new[t.resource] = t
        self._targets = new

    def targets(self) -> List[AdaptiveTarget]:
        return list(self._targets.values())

    def target_for(self, resource: str) -> Optional[AdaptiveTarget]:
        return self._targets.get(resource)

    # -- sensing -----------------------------------------------------------

    def fold_senses(self, seconds: List[Dict]) -> Dict[str, ResourceSense]:
        """Fold a ``timeseries_view`` page (chronological ``seconds``
        list, ``second_to_dict`` shape) into one sense per targeted
        resource. Host arithmetic over already-rendered dicts — the
        sense window costs zero device work beyond the spill that
        already rode the once-per-second fold."""
        out: Dict[str, ResourceSense] = {}
        for res in self._targets:
            passed = blocked = secs = 0
            buckets: Optional[List[int]] = None
            for sec in seconds:
                cell = sec["resources"].get(res)
                if not cell:
                    continue
                secs += 1
                passed += int(cell.get("pass", 0))
                blocked += int(cell.get("block", 0))
                rtb = cell.get("rtBuckets")
                if rtb:
                    if buckets is None:
                        buckets = [0] * len(rtb)
                    for i, v in enumerate(rtb):
                        buckets[i] += int(v)
            completions = int(sum(buckets)) if buckets else 0
            entries = passed + blocked
            out[res] = ResourceSense(
                resource=res, seconds=secs, passed=passed, blocked=blocked,
                completions=completions,
                block_rate=(blocked / float(entries) if entries else 0.0),
                rt_p99_ms=(float(histogram_quantile(buckets, 0.99))
                           if completions else 0.0),
            )
        return out

    # -- deciding ----------------------------------------------------------

    def desired(self, senses: Dict[str, ResourceSense],
                currents: Dict[str, float]) -> List[Dict]:
        """Raw policy asks, BEFORE the envelope: one dict per resource
        whose policy wants a change and which has a live simple-QPS rule
        to tune (``currents``: resource -> live rule count)."""
        out = []
        for res, target in self._targets.items():
            current = currents.get(res)
            if current is None:
                continue  # nothing to tune (documented: adaptive tunes
                # EXISTING simple QPS rules, it never creates rules)
            sense = senses.get(res)
            if sense is None:
                continue
            proposed = self.policy.propose(sense, target, current)
            if proposed is None:
                continue
            out.append({
                "resource": res,
                "current": float(current),
                "proposed": float(proposed),
                "sense": sense,
                "target": target,
            })
        return out

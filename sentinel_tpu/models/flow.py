"""Flow rules: QPS / concurrency limiting with four shaping behaviors.

Reference surface being reproduced (SURVEY.md §2.1 "FlowSlot + flow engine"):
``FlowRule`` (grade, count, strategy, refResource, controlBehavior, warm-up &
queueing params, limitApp), ``FlowRuleManager`` (wholesale rule swap via the
property system), ``FlowRuleChecker`` (node selection by requester origin and
relation strategy), and the ``TrafficShapingController`` family:

  * ``DefaultController``       — fast-fail:  pass iff used + acquire <= count
  * ``WarmUpController``        — Guava-SmoothWarmingUp-derived token bucket
                                  (coldFactor 3, warning zone, slope math)
  * ``RateLimiterController``   — leaky bucket, queue up to maxQueueingTimeMs
  * ``WarmUpRateLimiter``       — combination

TPU-native design: rules are compiled host-side into struct-of-arrays
tensors; the checker is one vectorized pure function over the entry
micro-batch — every request × every rule slot of its resource evaluated with
``where``-selects instead of virtual dispatch. Arrival-order exactness inside
a batch is preserved for unit acquires by segmented prefix sums over the
node rows each request will commit PASS to (see ``ops/segment.py``); for
cross-resource RELATE rules the within-batch contribution of *other*
resources' requests is not counted (bounded by one micro-batch; documented
semantics delta, SURVEY.md §7 hard part #2).

Warm-up state (storedTokens / lastFilledTime) and rate-limiter state
(latestPassedTime) are per-rule device tensors; like the reference, loading
new rules re-creates controller state (§3.2: "WarmUp state re-created!").
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, NamedTuple, Optional, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from sentinel_tpu.core import constants as C
from sentinel_tpu.core.batch import EntryBatch
from sentinel_tpu.core.registry import NodeRegistry, ORIGIN_ID_NONE
from sentinel_tpu.core.rule_manager import RuleManager
from sentinel_tpu.ops import fixpoint as FX
from sentinel_tpu.ops import window as W
from sentinel_tpu.ops.segment import (
    segmented_prefix_dense,
    segmented_prefix_dense_multi,
)
from sentinel_tpu.utils.shapes import round_up as _round_up


# ---------------------------------------------------------------------------
# Rule POJO + manager (host side)
# ---------------------------------------------------------------------------


@dataclass
class FlowRule:
    resource: str
    count: float
    grade: int = C.FLOW_GRADE_QPS
    limit_app: str = C.LIMIT_APP_DEFAULT
    strategy: int = C.FLOW_STRATEGY_DIRECT
    ref_resource: Optional[str] = None
    control_behavior: int = C.CONTROL_BEHAVIOR_DEFAULT
    warm_up_period_sec: int = 10
    max_queueing_time_ms: int = 500
    cluster_mode: bool = False
    cluster_config: Optional[dict] = None
    # Staged rollout (sentinel_tpu/rollout/): a named rule is part of a
    # CANDIDATE set — excluded from live enforcement, compiled into the
    # shadow pack instead. ``rollout_stage`` hints the initial stage for
    # datasource-tagged candidates ("shadow" default, "canary").
    candidate_set: Optional[str] = None
    rollout_stage: Optional[str] = None
    # LLM admission (sentinel_tpu/llm/): a rule lowered from another family
    # carries the family tag here ("tps"). Lowered rules are live and
    # enforced like any operator rule, but the lowering listener owns them:
    # each TPS load strips previously-derived rules before re-injecting.
    derived_from: Optional[str] = None

    def is_valid(self) -> bool:
        if not self.resource or self.count < 0:
            return False
        if self.grade not in (C.FLOW_GRADE_QPS, C.FLOW_GRADE_THREAD):
            return False
        if self.strategy in (C.FLOW_STRATEGY_RELATE, C.FLOW_STRATEGY_CHAIN) and not self.ref_resource:
            return False
        if self.control_behavior == C.CONTROL_BEHAVIOR_WARM_UP and self.warm_up_period_sec <= 0:
            return False
        return True


class FlowRuleTensors(NamedTuple):
    """Compiled SoA rule tensors + the per-resource-row rule index."""

    resource_row: jax.Array   # int32[FR] ClusterNode row of rule.resource
    sync_row: jax.Array       # int32[FR] node row warm-up token sync reads
    grade: jax.Array          # int32[FR]
    threshold: jax.Array      # float32[FR]
    strategy: jax.Array       # int32[FR]
    limit_origin: jax.Array   # int32[FR] origin id | ORIGIN_ID_{DEFAULT,OTHER}
    ref_row: jax.Array        # int32[FR] RELATE target ClusterNode row, -1
    ref_context: jax.Array    # int32[FR] CHAIN context id, -1
    behavior: jax.Array       # int32[FR]
    max_queue_us: jax.Array   # int64[FR] rate-limiter max queueing time (µs)
    cost_us: jax.Array        # int64[FR] rate-limiter cost per token (µs)
    warning_token: jax.Array  # float32[FR] warm-up params
    max_token: jax.Array      # float32[FR]
    slope: jax.Array          # float32[FR]
    cluster_mode: jax.Array   # bool[FR]
    remote_mode: jax.Array    # bool[FR] cluster rule WITH a flowId: enforced
                              # by a remote token server when one is active
    dcn_mode: jax.Array       # bool[FR] cluster rule with scope="global":
                              # admits against the CROSS-POD window (psum
                              # over the dcn axis too — SURVEY §2.10
                              # namespace sharding); default pod scope
    rules_by_row: jax.Array   # int32[R, K] rule ids per ClusterNode row, -1 pad

    @property
    def num_rules(self) -> int:
        return self.resource_row.shape[0]

    @property
    def slots(self) -> int:
        return self.rules_by_row.shape[1]


class FlowState(NamedTuple):
    """Per-rule mutable device state (re-created on rule load)."""

    stored_tokens: jax.Array    # float32[FR] warm-up bucket
    last_filled_ms: jax.Array   # int64[FR]
    latest_passed_us: jax.Array  # int64[FR] rate-limiter leaky bucket head


def make_flow_state(num_rules: int, now_ms: int) -> FlowState:
    del now_ms  # kept in the signature for callers that log creation time
    return FlowState(
        # lastFilledTime starts at epoch 0 so the first sync refills the
        # bucket to maxToken — the reference's cold-start state (a cold
        # system is *throttled* to count/coldFactor until tokens drain).
        stored_tokens=jnp.zeros((num_rules,), jnp.float32),
        last_filled_ms=jnp.zeros((num_rules,), jnp.int64),
        latest_passed_us=jnp.zeros((num_rules,), jnp.int64),
    )


def named_origin_map(rules: List[FlowRule], registry: NodeRegistry) -> Dict[str, Set[int]]:
    """resource -> origin ids explicitly named by valid rules' limitApp.

    The single source of the ``origin_named`` classification: used at
    compile time AND eagerly at rule load (entry() reads it pre-compile).
    """
    named: Dict[str, Set[int]] = {}
    for r in rules:
        if r.is_valid() and r.limit_app not in (C.LIMIT_APP_DEFAULT, C.LIMIT_APP_OTHER):
            named.setdefault(r.resource, set()).add(registry.origin_id(r.limit_app))
    return named


def compile_flow_rules(
    rules: List[FlowRule],
    registry: NodeRegistry,
    num_rows: int,
    min_slots: int = 1,
) -> Tuple[FlowRuleTensors, Dict[str, Set[int]]]:
    """Host-side rule build (reference: ``FlowRuleUtil.buildFlowRuleMap``).

    Returns the tensors plus the per-resource set of origin ids explicitly
    named by rules (for ``limitApp="other"`` matching).
    """
    valid = [r for r in rules if r.is_valid()]
    fr = _round_up(len(valid), 8)
    res_row = np.full(fr, -1, np.int32)
    sync_row = np.full(fr, -1, np.int32)
    grade = np.zeros(fr, np.int32)
    threshold = np.zeros(fr, np.float32)
    strategy = np.zeros(fr, np.int32)
    limit_origin = np.full(fr, C.ORIGIN_ID_DEFAULT, np.int32)
    ref_row = np.full(fr, -1, np.int32)
    ref_context = np.full(fr, -1, np.int32)
    behavior = np.zeros(fr, np.int32)
    max_queue_us = np.zeros(fr, np.int64)
    cost_us = np.zeros(fr, np.int64)
    warning_token = np.zeros(fr, np.float32)
    max_token = np.zeros(fr, np.float32)
    slope = np.zeros(fr, np.float32)
    cluster_mode = np.zeros(fr, bool)
    remote_mode = np.zeros(fr, bool)
    dcn_mode = np.zeros(fr, bool)

    named_origins = named_origin_map(valid, registry)
    by_row: Dict[int, List[int]] = {}

    for i, r in enumerate(valid):
        row = registry.cluster_row(r.resource)
        res_row[i] = row
        grade[i] = r.grade
        threshold[i] = r.count
        strategy[i] = r.strategy
        behavior[i] = r.control_behavior
        cluster_mode[i] = r.cluster_mode
        remote_mode[i] = (r.cluster_mode
                          and (r.cluster_config or {}).get("flowId") is not None)
        dcn_mode[i] = (r.cluster_mode
                       and (r.cluster_config or {}).get("scope") == "global")
        if r.limit_app == C.LIMIT_APP_DEFAULT:
            limit_origin[i] = C.ORIGIN_ID_DEFAULT
        elif r.limit_app == C.LIMIT_APP_OTHER:
            limit_origin[i] = C.ORIGIN_ID_OTHER
        else:
            limit_origin[i] = registry.origin_id(r.limit_app)
        if r.strategy == C.FLOW_STRATEGY_RELATE:
            ref_row[i] = registry.cluster_row(r.ref_resource)
        elif r.strategy == C.FLOW_STRATEGY_CHAIN:
            ref_context[i] = registry.context_id(r.ref_resource)
        # Warm-up token sync reads the same node admission checks against
        # (reference: canPass(node).syncToken(node.previousPassQps())):
        # RELATE -> the referenced resource's ClusterNode; CHAIN -> the
        # (context, resource) DefaultNode; a named limit_app -> that
        # origin's StatisticNode; default/"other" -> the ClusterNode
        # ("other" spans many origins — cluster row is the aggregate).
        if r.strategy == C.FLOW_STRATEGY_RELATE:
            sync_row[i] = ref_row[i]
        elif r.strategy == C.FLOW_STRATEGY_CHAIN:
            sync_row[i] = registry.default_row(
                r.ref_resource, r.resource, registry.entrance_row(r.ref_resource)
            )
        elif r.limit_app not in (C.LIMIT_APP_DEFAULT, C.LIMIT_APP_OTHER):
            sync_row[i] = registry.origin_row(r.resource, r.limit_app)
        else:
            sync_row[i] = row
        if r.control_behavior in (C.CONTROL_BEHAVIOR_RATE_LIMITER, C.CONTROL_BEHAVIOR_WARM_UP_RATE_LIMITER):
            # cost of one token in µs (reference uses ms: round(1/count*1000))
            cost_us[i] = int(round(1_000_000.0 / max(r.count, 1e-9)))
            max_queue_us[i] = r.max_queueing_time_ms * 1000
        if r.control_behavior in (C.CONTROL_BEHAVIOR_WARM_UP, C.CONTROL_BEHAVIOR_WARM_UP_RATE_LIMITER):
            # Guava SmoothWarmingUp-derived params (WarmUpController ctor).
            # count=0 is a valid block-everything rule; epsilon keeps the
            # slope math finite (warning_qps then collapses to ~0).
            cnt = max(r.count, 1e-9)
            wp, cold = r.warm_up_period_sec, C.COLD_FACTOR
            wt = (wp * cnt) / (cold - 1)
            mt = wt + 2.0 * wp * cnt / (1 + cold)
            warning_token[i] = wt
            max_token[i] = mt
            slope[i] = (cold - 1.0) / cnt / max(mt - wt, 1e-9)
        if row >= 0:
            by_row.setdefault(row, []).append(i)

    k = max(min_slots, max((len(v) for v in by_row.values()), default=1))
    rules_by_row = np.full((num_rows, k), -1, np.int32)
    for row, ids in by_row.items():
        rules_by_row[row, : len(ids)] = ids

    t = FlowRuleTensors(
        resource_row=jnp.asarray(res_row),
        sync_row=jnp.asarray(sync_row),
        grade=jnp.asarray(grade),
        threshold=jnp.asarray(threshold),
        strategy=jnp.asarray(strategy),
        limit_origin=jnp.asarray(limit_origin),
        ref_row=jnp.asarray(ref_row),
        ref_context=jnp.asarray(ref_context),
        behavior=jnp.asarray(behavior),
        max_queue_us=jnp.asarray(max_queue_us),
        cost_us=jnp.asarray(cost_us),
        warning_token=jnp.asarray(warning_token),
        max_token=jnp.asarray(max_token),
        slope=jnp.asarray(slope),
        cluster_mode=jnp.asarray(cluster_mode),
        remote_mode=jnp.asarray(remote_mode),
        dcn_mode=jnp.asarray(dcn_mode),
        rules_by_row=jnp.asarray(rules_by_row),
    )
    return t, named_origins


class FlowRuleManager(RuleManager):
    """Registry of flow rules; wholesale swap semantics (§3.2)."""

    def has_origin_rules(self) -> bool:
        with self._lock:
            return any(r.limit_app != C.LIMIT_APP_DEFAULT for r in self._rules)


# ---------------------------------------------------------------------------
# Vectorized checker (device side)
# ---------------------------------------------------------------------------


class FlowVerdict(NamedTuple):
    blocked: jax.Array  # bool[N]
    wait_us: jax.Array  # int64[N] sleep-then-pass (rate limiter / occupy)
    occupied: jax.Array  # bool[N] prioritized grant borrowing the next bucket
    occ_add: jax.Array  # int32[R] borrow counts granted this step, per node row
    state: FlowState
    slot: jax.Array  # int32[N] first-blocking rule slot (-1 = not blocked)


def _gather(arr, idx, fill):
    return arr.at[W.oob(idx, arr.shape[0])].get(mode="fill", fill_value=fill)


def _sync_warmup(rt: FlowRuleTensors, fs: FlowState, prev_bucket_pass: jax.Array, now_ms: jax.Array) -> FlowState:
    """Vectorized ``WarmUpController.syncToken`` over all rules, 1 Hz/rule.

    ``prev_bucket_pass``: float32[FR] previous-window pass count of each
    rule's resource (reference passes ``node.previousPassQps()``).
    """
    now_sec = (now_ms.astype(jnp.int64) // 1000) * 1000
    due = now_sec > fs.last_filled_ms
    is_warm = (rt.behavior == C.CONTROL_BEHAVIOR_WARM_UP) | (
        rt.behavior == C.CONTROL_BEHAVIOR_WARM_UP_RATE_LIMITER
    )
    active = due & is_warm & (rt.resource_row >= 0)

    elapsed_s = (now_sec - fs.last_filled_ms).astype(jnp.float32) / 1000.0
    refill = fs.stored_tokens + elapsed_s * rt.threshold
    below = fs.stored_tokens < rt.warning_token
    above = fs.stored_tokens > rt.warning_token
    low_qps = prev_bucket_pass < (rt.threshold / C.COLD_FACTOR)
    new_tokens = jnp.where(below | (above & low_qps), refill, fs.stored_tokens)
    new_tokens = jnp.minimum(new_tokens, rt.max_token)
    new_tokens = jnp.maximum(new_tokens - prev_bucket_pass, 0.0)

    return fs._replace(
        stored_tokens=jnp.where(active, new_tokens, fs.stored_tokens),
        last_filled_ms=jnp.where(active, now_sec, fs.last_filled_ms),
    )


def check_flow(
    rt: FlowRuleTensors,
    fs: FlowState,
    w1: W.Window,
    cur_threads: jax.Array,  # int32[R]
    batch: EntryBatch,
    now_ms: jax.Array,
    already_blocked: jax.Array,  # bool[N] blocked by an earlier slot
    extra_pass: Optional[jax.Array] = None,  # int32[R] other-device pass counts
    occupied_next: Optional[jax.Array] = None,  # int32[R] borrows on next bucket
    extra_next: Optional[jax.Array] = None,  # int32[R] other-device next-window use
    extra_pass_global: Optional[jax.Array] = None,  # int32[R] cross-POD passes
    extra_next_global: Optional[jax.Array] = None,  # int32[R] cross-POD next use
    spec: Optional[W.WindowSpec] = None,  # w1 geometry (engine may retune)
    occupy_timeout_ms: int = C.DEFAULT_OCCUPY_TIMEOUT_MS,
) -> FlowVerdict:
    """Vectorized ``FlowRuleChecker.checkFlow`` over the micro-batch.

    Evaluates every rule slot of each request's resource; a request is
    flow-blocked if any applicable rule rejects it. Rate-limiter rules
    return a wait instead (host sleeps), unless wait exceeds the queue cap.

    Two evaluation passes reproduce the serial rule "blocked requests never
    increment pass counters": pass 1 computes verdicts with every candidate
    counted in the prefixes; pass 2 re-evaluates with prefixes restricted to
    pass-1 survivors, so a request rejected by one rule no longer inflates
    the usage other requests see (nor consumes leaky-bucket tokens). For a
    single rule per node with UNIFORM acquire counts this is exactly the
    serial semantics (the serial-admitted set is then a prefix of the
    candidates, which two passes recover); with interacting rules the
    residual error is second-order and bounded by one micro-batch
    (documented delta, SURVEY.md §7 hard part #2).

    MIXED acquire counts within one batch break the prefix property (a
    small request can be serially admitted after a large one blocks), and
    a fixed second pass could then over-admit without bound — its prefixes
    never see the entries the second pass itself admits (r5 fuzz found
    batches admitting 30 tokens against a 9-token rule this way). Such
    batches take a fixpoint loop instead: ``survivors`` is iterated to
    ``S_{k+1} = candidate & ~blocked(S_k)``. The serial outcome is a
    fixpoint of that map, the map is antitone in S (more survivors ->
    stricter prefixes), so odd iterates under-approximate and even
    iterates over-approximate the serial set, sandwiching it; on
    convergence the result IS serial, and at the iteration cap the last
    EVEN iterate is handed to the final evaluation — whose one further
    map application makes the shipped decisions an ODD iterate, which
    can only UNDER-admit (safe direction). The loop is gated on a
    per-batch uniformity check, so uniform batches (every shipped
    reference call site acquires 1) pay exactly the two passes they
    always did.
    """
    if spec is None:
        spec = W.WindowSpec(C.SECOND_WINDOW_MS, C.SECOND_BUCKETS)
    candidate = (~already_blocked) & (batch.cluster_row >= 0)

    # Warm-up token sync (per rule, once per second) against the node the
    # rule admits on (sync_row), not blindly the resource ClusterNode.
    prev_idx = jnp.mod(W.current_index(now_ms, spec) - 1, spec.buckets)
    prev_pass_all = jnp.take(w1.counts[:, C.MetricEvent.PASS, :], prev_idx, axis=0)
    rule_prev_pass = _gather(prev_pass_all, rt.sync_row, 0).astype(jnp.float32)
    fs = _sync_warmup(rt, fs, rule_prev_pass, now_ms)

    def _blocked_for(survivors):
        out = _eval_flow_slots(
            rt, fs, w1, cur_threads, batch, now_ms, candidate,
            survivors=survivors, extra_pass=extra_pass,
            occupied_next=occupied_next, extra_next=extra_next,
            extra_pass_global=extra_pass_global,
            extra_next_global=extra_next_global,
            spec=spec, occupy_timeout_ms=occupy_timeout_ms,
        )
        return out[0]

    survivors = FX.survivor_fixpoint(candidate, _blocked_for, batch.count)

    (blocked, wait_us, consumed, rl_cmax, occupied, occ_add,
     first_slot) = _eval_flow_slots(
        rt, fs, w1, cur_threads, batch, now_ms, candidate,
        survivors=survivors, extra_pass=extra_pass,
        occupied_next=occupied_next, extra_next=extra_next,
        extra_pass_global=extra_pass_global, extra_next_global=extra_next_global,
        spec=spec, occupy_timeout_ms=occupy_timeout_ms,
    )

    # Advance leaky buckets: latest' = max(latest, now - acquire·cost) +
    # consumed·cost — the idle clamp scales with the acquire size (the
    # reference's whole-acquire-free-after-idle; see the verdict-side
    # comment). rl_cmax is the per-rule admitted acquire count (uniform
    # within a batch in the serially-exact regime).
    now_us = now_ms.astype(jnp.int64) * 1000
    new_latest = (jnp.maximum(fs.latest_passed_us,
                              now_us - rt.cost_us * jnp.maximum(rl_cmax, 1))
                  + consumed * rt.cost_us)
    fs = fs._replace(
        latest_passed_us=jnp.where(consumed > 0, new_latest, fs.latest_passed_us)
    )
    return FlowVerdict(blocked=blocked, wait_us=wait_us, occupied=occupied,
                       occ_add=occ_add, state=fs, slot=first_slot)


def _eval_flow_slots(
    rt: FlowRuleTensors,
    fs: FlowState,
    w1: W.Window,
    cur_threads: jax.Array,
    batch: EntryBatch,
    now_ms: jax.Array,
    candidate: jax.Array,
    survivors: Optional[jax.Array] = None,
    extra_pass: Optional[jax.Array] = None,
    occupied_next: Optional[jax.Array] = None,
    extra_next: Optional[jax.Array] = None,
    extra_pass_global: Optional[jax.Array] = None,
    extra_next_global: Optional[jax.Array] = None,
    spec: Optional[W.WindowSpec] = None,
    occupy_timeout_ms: int = C.DEFAULT_OCCUPY_TIMEOUT_MS,
):
    """One vectorized sweep over all rule slots.

    ``survivors`` (defaults to ``candidate``) selects which requests count
    toward within-batch prefixes — i.e. which are presumed to commit PASS.
    Verdicts are still produced for every candidate.
    """
    n = batch.size
    if survivors is None:
        survivors = candidate
    token_count = jnp.where(survivors, batch.count, 0)
    entry_count = jnp.where(survivors, 1, 0)  # thread gauge moves 1/entry

    # Within-batch arrival-order prefixes over the rows each request commits
    # PASS to. Node rows of different kinds never collide (the registry
    # allocates every node from one shared row space), so cluster/dn/origin
    # are three independent segment spaces — three dense prefixes, each
    # sharing one mask matmul for the token (QPS) and entry (THREAD) value
    # columns (``ops/segment.py`` — the MXU path; sorts blew scoped VMEM).
    vals2 = jnp.stack([token_count, entry_count], axis=1).astype(jnp.float32)
    cols = [p for p, _ in segmented_prefix_dense_multi(
        [(rows, vals2)
         for rows in (batch.cluster_row, batch.dn_row, batch.origin_row)])]
    tok3 = jnp.stack([c[:, 0] for c in cols], axis=1)  # [:, (cluster, dn, origin)]
    ent3 = jnp.stack([c[:, 1] for c in cols], axis=1)

    blocked = jnp.zeros((n,), bool)
    # First rule slot (per-resource load order) that blocked each request
    # — the sequential chain's throw site, surfaced for decision
    # attribution (telemetry/attribution.py). -1 while unblocked.
    first_slot = jnp.full((n,), -1, jnp.int32)
    # Cond-gated accumulators: varying-typed seeds (W.varying_zeros) so
    # the no-traffic branches type-check under shard_map.
    wait_us = W.varying_zeros(batch.count, (n,), jnp.int64)
    occupied = W.varying_zeros(batch.count, (n,), bool)
    occ_add = W.varying_zeros(batch.count, (w1.num_rows,),
                              jnp.int32)  # granted borrows per row
    consumed = W.varying_zeros(batch.count, (rt.num_rules,),
                               jnp.int64)  # rate-limiter tokens
    # Per-rule max admitted acquire count: the state advance clamps the
    # idle bucket head by acquire·cost (see the verdict-side comment).
    rl_cmax = W.varying_zeros(batch.count, (rt.num_rules,), jnp.int64)

    # Occupy-next-window geometry (DefaultController.tryOccupyNext): at the
    # next bucket boundary the OLDEST bucket's counts leave the window, so
    # next-window usage = window pass − oldest-bucket pass + already-borrowed.
    if spec is None:
        spec = W.WindowSpec(C.SECOND_WINDOW_MS, C.SECOND_BUCKETS)
    cur_idx = W.current_index(now_ms, spec)
    oldest_idx = jnp.mod(cur_idx + 1, spec.buckets)
    oldest_pass_all = jnp.take(w1.counts[:, C.MetricEvent.PASS, :], oldest_idx, axis=0)  # [R]
    occ_wait_us = (spec.bucket_ms - jnp.mod(now_ms.astype(jnp.int64), spec.bucket_ms)) * 1000

    for k in range(rt.slots):
        rule_id = rt.rules_by_row.at[
            W.oob(batch.cluster_row, rt.rules_by_row.shape[0]), jnp.full((n,), k)
        ].get(mode="fill", fill_value=-1)
        has_rule = rule_id >= 0
        g = lambda a, fill=0: _gather(a, rule_id, fill)

        strat = g(rt.strategy)
        lim_o = g(rt.limit_origin, C.ORIGIN_ID_DEFAULT)
        behavior = g(rt.behavior)
        grade = g(rt.grade)
        thr = g(rt.threshold, 0.0)

        # --- node selection (FlowRuleChecker.selectNodeByRequesterAndStrategy)
        has_origin = batch.origin_id >= 0
        direct = strat == C.FLOW_STRATEGY_DIRECT
        sel_specific = direct & (lim_o >= 0) & (batch.origin_id == lim_o)
        sel_default = direct & (lim_o == C.ORIGIN_ID_DEFAULT)
        sel_other = direct & (lim_o == C.ORIGIN_ID_OTHER) & has_origin & (~batch.origin_named)
        relate = strat == C.FLOW_STRATEGY_RELATE
        chain = (strat == C.FLOW_STRATEGY_CHAIN) & (batch.context_id == g(rt.ref_context, -1))

        # A request already granted an occupy borrow by an earlier slot has
        # left the chain (reference: PriorityWaitException short-circuits the
        # remaining rules), so later slots never see it.
        applicable = has_rule & candidate & (~occupied) & (sel_specific | sel_default | sel_other | relate | chain)
        # Requests whose remote-enforced rules (cluster mode + flowId) were
        # already checked by a token server skip those rules locally
        # (reference: passClusterCheck replaces the local check; fallback
        # requests keep skip_cluster False, which IS fallbackToLocalOrPass's
        # local branch). Pod-psum cluster rules (no flowId) stay live.
        applicable = applicable & ~(g(rt.remote_mode, False) & batch.skip_cluster)
        sel_row = jnp.where(sel_default, batch.cluster_row, -1)
        sel_row = jnp.where(sel_specific | sel_other, batch.origin_row, sel_row)
        sel_row = jnp.where(relate, g(rt.ref_row, -1), sel_row)
        sel_row = jnp.where(chain, batch.dn_row, sel_row)
        applicable = applicable & (sel_row >= 0)

        # cluster=[:,0], dn=[:,1], origin=[:,2]; RELATE rows get no
        # within-batch credit (cross-resource, bounded by one micro-batch).
        def _sel(prefixes):
            p = jnp.where(sel_default, prefixes[:, 0], jnp.float32(0))
            p = jnp.where(sel_specific | sel_other, prefixes[:, 2], p)
            return jnp.where(chain, prefixes[:, 1], p)

        tok_prefix = _sel(tok3)
        ent_prefix = _sel(ent3)

        # --- current usage of the selected node
        totals = W.row_totals(w1, sel_row)  # [N, E]
        pass_1s = totals[:, C.MetricEvent.PASS].astype(jnp.float32)
        used_qps = pass_1s + tok_prefix.astype(jnp.float32)
        if extra_pass is not None:
            # Cluster-mode rules admit against the POD-global window: add
            # the psum'd pass counts of the other devices (the TPU-native
            # token server — SURVEY.md §2.11). scope="global" rules admit
            # against the CROSS-POD window instead (psum over the dcn axis
            # too — namespace sharding, SURVEY §2.10). Local rules stay
            # local.
            cm = g(rt.cluster_mode, False)
            extra = _gather(extra_pass, sel_row, 0).astype(jnp.float32)
            if extra_pass_global is not None:
                extra = jnp.where(
                    g(rt.dcn_mode, False),
                    _gather(extra_pass_global, sel_row, 0).astype(jnp.float32),
                    extra)
            used_qps = used_qps + jnp.where(cm, extra, 0.0)
        # Normalize window sums to per-second QPS (reference
        # StatisticNode.passQps divides by the interval in seconds) — a
        # constant 1.0 under the default 1s geometry, load-bearing when
        # the engine retunes the window (set_window_geometry).
        qps_scale = jnp.float32(1000.0 / spec.interval_ms)
        used_qps = used_qps * qps_scale
        used_thr = (
            _gather(cur_threads, sel_row, 0).astype(jnp.float32)
            + ent_prefix.astype(jnp.float32)
        )
        used = jnp.where(grade == C.FLOW_GRADE_QPS, used_qps, used_thr)
        acq = jnp.where(grade == C.FLOW_GRADE_QPS, batch.count, 1).astype(jnp.float32)

        # --- DefaultController
        dflt_ok = used + acq <= thr

        # --- WarmUpController admission (tokens already synced)
        stored = g(fs.stored_tokens, 0.0)
        wtok = g(rt.warning_token, 0.0)
        above_warn = stored >= wtok
        warning_qps = 1.0 / ((stored - wtok) * g(rt.slope, 0.0) + 1.0 / jnp.maximum(thr, 1e-9))
        warm_thr = jnp.where(above_warn, warning_qps, thr)
        warm_ok = used + acq <= warm_thr

        # --- RateLimiterController: leaky-bucket wait. Only survivors
        # reserve bucket slots in the within-batch prefix.
        cost = g(rt.cost_us, 0)
        is_rl = (behavior == C.CONTROL_BEHAVIOR_RATE_LIMITER) | (
            behavior == C.CONTROL_BEHAVIOR_WARM_UP_RATE_LIMITER
        )
        any_rl = jnp.any(applicable & is_rl)

        # The prefix is a full masked-matmul scan; with no rate-limited
        # traffic in the batch every gid is -1 and rl_prefix is unused
        # downstream (``ok`` never selects the rl branch), so the cond
        # skips the scan (same no-traffic gating as param_flow's commit).
        def _rl_prefix(_):
            return segmented_prefix_dense(
                jnp.where(applicable & is_rl, rule_id, -1),
                jnp.where(applicable & survivors, batch.count, 0)
                .astype(jnp.float32),
            )[0]

        rl_prefix = jax.lax.cond(
            any_rl, _rl_prefix,
            lambda _: W.varying_zeros(batch.count, (n,), jnp.float32), 0)
        now_us = now_ms.astype(jnp.int64) * 1000
        # Clamp the bucket head the same way the state advance does: the
        # reference sets latestPassedTime = NOW for the first pass after
        # an idle period (not latest + cost), i.e. the effective base is
        # max(latest, now - acquire·cost) — the WHOLE multi-token acquire
        # is free after idle (RateLimiterController: expected ≤ now →
        # latest = now), not just one token; found by the differential
        # fuzz at count>1. Using the raw stale head here would let a
        # whole micro-batch through unpaced after any idle gap.
        latest = jnp.maximum(g(fs.latest_passed_us, 0),
                             now_us - cost * batch.count)
        expected = latest + (rl_prefix + batch.count).astype(jnp.int64) * cost
        rl_wait = jnp.maximum(expected - now_us, 0)
        rl_ok = rl_wait <= g(rt.max_queue_us, 0)

        ok = jnp.where(behavior == C.CONTROL_BEHAVIOR_DEFAULT, dflt_ok, True)
        ok = jnp.where(behavior == C.CONTROL_BEHAVIOR_WARM_UP, warm_ok, ok)
        ok = jnp.where(behavior == C.CONTROL_BEHAVIOR_RATE_LIMITER, rl_ok, ok)
        ok = jnp.where(behavior == C.CONTROL_BEHAVIOR_WARM_UP_RATE_LIMITER, warm_ok & rl_ok, ok)

        slot_blocked = applicable & (~ok)

        # --- prioritized occupy-next-window (DefaultController.tryOccupyNext
        # + OccupiableBucketLeapArray): a prioritized QPS request rejected by
        # the DEFAULT controller may borrow from the next bucket if the
        # next window (current − expiring bucket + borrows) has room and the
        # wait fits the occupy timeout. Granted requests pass with a wait;
        # their PASS lands in the bucket they borrowed (ops/step.py fold).
        # ``~blocked``: a request an EARLIER slot rejected already threw in
        # the serial reference — later slots must not hand it a borrow.
        occ_cand = (slot_blocked & (~blocked) & batch.prioritized
                    & (grade == C.FLOW_GRADE_QPS)
                    & (behavior == C.CONTROL_BEHAVIOR_DEFAULT))
        if occupied_next is not None:
            # The whole borrow evaluation — prefix scan, next-window
            # gathers, and the occ_add scatter — rides a cond on whether
            # the batch has ANY occupy candidate: prioritized traffic is
            # rare, and with none every grant is provably False and all
            # four outputs provably unchanged.
            def _occupy(args):
                occupied_, wait_us_, slot_blocked_, occ_add_ = args
                occ_prefix, _ = segmented_prefix_dense(
                    jnp.where(occ_cand, sel_row, -1),
                    jnp.where(occ_cand & survivors, batch.count, 0)
                    .astype(jnp.float32),
                )
                next_used = (
                    pass_1s
                    - _gather(oldest_pass_all, sel_row, 0).astype(jnp.float32)
                    + _gather(occupied_next, sel_row, 0).astype(jnp.float32)
                    + occ_prefix
                )
                if extra_next is not None:
                    # Cluster-mode rules borrow against the POD-global
                    # next window (global-scope rules: cross-pod): fold
                    # in the other devices' psum'd next-window usage, or
                    # every device would grant up to the full global
                    # threshold independently.
                    en = _gather(extra_next, sel_row, 0).astype(jnp.float32)
                    if extra_next_global is not None:
                        en = jnp.where(
                            g(rt.dcn_mode, False),
                            _gather(extra_next_global, sel_row,
                                    0).astype(jnp.float32),
                            en)
                    next_used = next_used + jnp.where(
                        g(rt.cluster_mode, False), en, 0.0)
                grant = occ_cand & (next_used * qps_scale + acq <= thr) & (
                    occ_wait_us <= occupy_timeout_ms * 1000
                )
                return (occupied_ | grant,
                        jnp.maximum(wait_us_,
                                    jnp.where(grant, occ_wait_us, 0)),
                        slot_blocked_ & (~grant),
                        occ_add_.at[W.oob(sel_row, w1.num_rows)].add(
                            jnp.where(grant, batch.count, 0)
                            .astype(jnp.int32), mode="drop"))

            occupied, wait_us, slot_blocked, occ_add = jax.lax.cond(
                jnp.any(occ_cand), _occupy, lambda args: args,
                (occupied, wait_us, slot_blocked, occ_add))

        first_slot = jnp.where(slot_blocked & (~blocked), k, first_slot)
        blocked = blocked | slot_blocked

        # Bucket tokens are consumed only by requests that survive every
        # slot (the serial reference never reaches the rate limiter for a
        # request an earlier rule rejected). The int64 scatter-add costs
        # ~0.5ms/step at batch 8192 even with every index dropped
        # (emulated hi/lo-u32 pairs), so it rides the same no-RL-traffic
        # cond as the prefix above.
        admitted_rl = applicable & is_rl & ok & survivors
        wait_us = jnp.maximum(wait_us, jnp.where(admitted_rl, rl_wait, 0))

        def _consume(args):
            c_, cmax_ = args
            ridx = W.oob(rule_id, rt.num_rules)
            admitted_counts = jnp.where(admitted_rl, batch.count,
                                        0).astype(jnp.int64)
            c_ = c_.at[ridx].add(admitted_counts, mode="drop")
            cmax_ = cmax_.at[ridx].max(admitted_counts, mode="drop")
            return c_, cmax_

        consumed, rl_cmax = jax.lax.cond(
            any_rl, _consume, lambda args: args, (consumed, rl_cmax))

    return blocked, wait_us, consumed, rl_cmax, occupied, occ_add, first_slot

"""Prioritized occupy-next-window tests.

Reference behavior being reproduced (SURVEY.md §2.1 "FlowSlot"):
``DefaultController.tryOccupyNext`` + ``OccupiableBucketLeapArray`` — a
prioritized QPS entry rejected by the default controller may *borrow* quota
from the next window bucket if that future window has room, waiting out the
remainder of the current bucket instead of failing. The borrowed pass lands
in the bucket it borrowed (the reference's ``resetWindowTo`` transfer), so
it counts against subsequent admissions there.

Clock geometry: the frozen epoch 1_700_000_000_000 is a whole-second
boundary; the 1s window has two 500ms buckets.
"""

import pytest

import sentinel_tpu as st


def _fill(resource, n):
    for _ in range(n):
        with st.entry(resource):
            pass


def _row(engine, resource):
    return engine.registry.cluster_row(resource)


def _occ(engine, row):
    """occupied_next[row]: flush the lease committer first (borrow landing
    runs inside a device step) and read under the engine lock (the
    committer thread donates state buffers on flush)."""
    import numpy as np

    engine._flush_committer()
    with engine._lock:
        return int(np.asarray(engine._state.occupied_next)[row])


def _sec_count(engine, event, row):
    import numpy as np

    engine._flush_committer()
    with engine._lock:
        return int(np.asarray(engine._state.sec.counts)[event, row])


def test_non_prioritized_never_borrows(engine, frozen_time):
    st.load_flow_rules([st.FlowRule(resource="occ", count=10)])
    _fill("occ", 10)
    frozen_time.advance_time(900)  # quota now sits in the expiring bucket
    with pytest.raises(st.FlowException):
        st.entry("occ")


def test_borrow_denied_while_next_window_is_full(engine, frozen_time):
    """Passes in the CURRENT bucket still occupy the next window."""
    st.load_flow_rules([st.FlowRule(resource="occ", count=10)])
    _fill("occ", 10)
    # Still inside the granting bucket: the next window keeps all 10 passes
    # (only the empty oldest bucket expires), so there is nothing to borrow.
    with pytest.raises(st.FlowException):
        st.entry("occ", prioritized=True)
    assert _occ(engine, _row(engine, "occ")) == 0


def test_prioritized_borrows_once_bucket_expires(engine, frozen_time):
    st.load_flow_rules([st.FlowRule(resource="occ", count=10)])
    _fill("occ", 10)
    frozen_time.advance_time(900)  # 10 passes now in the expiring bucket
    e = st.entry("occ", prioritized=True)  # sleeps ~100ms, then passes
    e.exit()
    row = _row(engine, "occ")
    assert _occ(engine, row) == 1
    # The granted pass is deferred to the borrowed bucket: the live window
    # still reads 10 passes, and no block was recorded.
    snap = engine.node_snapshot()["occ"]
    assert snap["passQps"] == 10
    assert snap["blockQps"] == 0


def test_borrow_capacity_is_the_rule_count(engine, frozen_time):
    st.load_flow_rules([st.FlowRule(resource="occ", count=2)])
    _fill("occ", 2)
    frozen_time.advance_time(900)
    st.entry("occ", prioritized=True).exit()
    st.entry("occ", prioritized=True).exit()
    with pytest.raises(st.FlowException):  # next window now full of borrows
        st.entry("occ", prioritized=True)
    assert _occ(engine, _row(engine, "occ")) == 2


def test_borrow_lands_as_pass_in_next_bucket(engine, frozen_time):
    """Folded borrows count against the window (the borrow is repaid)."""
    st.load_flow_rules([st.FlowRule(resource="occ", count=2)])
    _fill("occ", 2)
    frozen_time.advance_time(900)
    st.entry("occ", prioritized=True).exit()
    st.entry("occ", prioritized=True).exit()
    frozen_time.advance_time(100)  # enter the borrowed bucket
    # Window quota is consumed by the 2 landed borrows.
    with pytest.raises(st.FlowException):
        st.entry("occ")
    row = _row(engine, "occ")
    assert _occ(engine, row) == 0
    snap = engine.node_snapshot()["occ"]
    # 2 original passes expired with their bucket; the 2 borrows landed.
    assert snap["passQps"] == 2


def test_stale_borrows_deprecate_when_buckets_skip(engine, frozen_time):
    st.load_flow_rules([st.FlowRule(resource="occ", count=10)])
    _fill("occ", 10)
    frozen_time.advance_time(900)
    st.entry("occ", prioritized=True).exit()
    # Jump PAST the borrowed bucket: the borrow's target window expired
    # before anything rotated into it, so it is dropped, not landed.
    frozen_time.advance_time(1600)
    with st.entry("occ"):
        pass
    row = _row(engine, "occ")
    assert _occ(engine, row) == 0
    assert engine.node_snapshot()["occ"]["passQps"] == 1


def test_earlier_slot_block_denies_later_slot_borrow(engine, frozen_time):
    """A request rejected by an earlier rule slot must not collect a borrow
    from a later slot (the serial reference threw before reaching it)."""
    import numpy as np

    st.load_flow_rules([
        # Slot 0: origin-scoped, will block with a FULL next window.
        st.FlowRule(resource="r", count=2, limit_app="svcA"),
        # Slot 1: default, whose next window HAS room to lend.
        st.FlowRule(resource="r", count=10),
    ])
    st.context_enter("c1", origin="bulk")
    for _ in range(8):  # 8 passes on the cluster node, this bucket
        with st.entry("r"):
            pass
    st.exit_context()
    frozen_time.advance_time(900)  # those 8 now sit in the expiring bucket
    st.context_enter("c2", origin="svcA")
    for _ in range(2):  # svcA's origin quota, in the CURRENT bucket
        with st.entry("r"):
            pass
    # Slot 0 blocks (origin next-window full: its 2 passes don't expire);
    # slot 1 would lend (8 of its 10 expire) — but the request is dead.
    with pytest.raises(st.FlowException):
        st.entry("r", prioritized=True)
    st.exit_context()
    with engine._lock:
        assert int(np.asarray(engine._state.occupied_next).sum()) == 0


def test_occupied_pass_reaches_minute_metrics(engine, frozen_time):
    st.load_flow_rules([st.FlowRule(resource="occ", count=10)])
    _fill("occ", 10)
    frozen_time.advance_time(900)
    st.entry("occ", prioritized=True).exit()
    from sentinel_tpu.core import constants as C

    row = _row(engine, "occ")
    assert _sec_count(engine, C.MetricEvent.OCCUPIED_PASS, row) == 1
    # Minute staging records the grant's pass immediately (reference:
    # StatisticNode.addOccupiedPass hits the minute counter at grant time).
    assert _sec_count(engine, C.MetricEvent.PASS, row) == 11


def test_occupy_timeout_runtime_tunable(engine, frozen_time):
    """OccupyTimeoutProperty analog: shrinking the wait cap below the
    time-to-next-bucket denies borrows the default cap granted; restoring
    it grants again; out-of-range values are rejected."""
    st.load_flow_rules([st.FlowRule(resource="occ", count=10)])
    _fill("occ", 10)
    frozen_time.advance_time(700)  # next bucket is 300ms away

    engine.set_occupy_timeout(100)  # 300ms wait no longer fits
    with pytest.raises(st.FlowException):
        st.entry("occ", prioritized=True)

    engine.set_occupy_timeout(500)  # default again: borrow granted
    e = st.entry("occ", prioritized=True)
    e.exit()
    assert _occ(engine, _row(engine, "occ")) == 1

    with pytest.raises(ValueError):
        engine.set_occupy_timeout(-1)
    with pytest.raises(ValueError):
        engine.set_occupy_timeout(engine._spec1.interval_ms + 1)
    # push-property form
    engine.occupy_timeout_property.update_value(250)
    assert engine._occupy_timeout_ms == 250


def test_occupy_timeout_tune_is_free_and_geometry_clamps(engine,
                                                         frozen_time):
    """The cap is a TRACED step argument (tuning must not re-jit), and a
    geometry shrink below the active cap clamps it to one window."""
    st.load_flow_rules([st.FlowRule(resource="occ", count=10)])
    engine._ensure_compiled()
    jit_before = engine._entry_jit
    engine.set_occupy_timeout(123)
    assert engine._entry_jit is jit_before       # no rebuild on tune
    assert engine._occupy_timeout_ms == 123

    engine.set_window_geometry(interval_ms=100, sample_count=2)
    assert engine._occupy_timeout_ms == 100      # clamped to the window
    with pytest.raises(ValueError):
        engine.set_occupy_timeout(101)

"""Delta-debugging (ddmin) over fault schedules.

A violating episode's schedule is minimized to the smallest subset that
STILL violates, by re-running the (fully deterministic) episode with
candidate subsets: split into n chunks, try each chunk and each
complement, refine granularity when nothing smaller fails. Because the
predicate re-runs are bit-deterministic, the minimal schedule is a pure
function of the failing schedule — the shrinker-determinism oracle in
tests/test_chaos_campaign.py pins it.
"""

from __future__ import annotations

from typing import Callable, List, Tuple


def _chunks(items: List, n: int) -> List[List]:
    n = max(2, min(n, len(items)))
    size = len(items) / n
    out = []
    start = 0.0
    for _ in range(n):
        chunk = items[int(start):int(start + size)]
        if chunk:
            out.append(chunk)
        start += size
    return out


def ddmin(failing: Callable[[List], bool], items: List,
          max_runs: int = 96) -> Tuple[List, int]:
    """Minimize ``items`` to a small still-failing subset.

    ``failing(subset)`` re-runs the episode under ``subset`` and returns
    whether any invariant violated. ``items`` itself must be failing
    (the caller just observed it). Returns ``(minimal, runs_spent)``;
    ``max_runs`` bounds the shrink cost — on exhaustion the smallest
    failing subset found so far is returned (still a valid repro, just
    possibly not 1-minimal)."""
    items = list(items)
    runs = 0
    n = 2
    while len(items) >= 2 and runs < max_runs:
        chunks = _chunks(items, n)
        reduced = False
        candidates = [c for c in chunks if len(c) < len(items)]
        candidates += [
            [x for x in items if not any(x is y for y in c)]
            for c in chunks if 0 < len(c) < len(items)]
        for cand in candidates:
            if not cand:
                continue
            runs += 1
            if failing(cand):
                items = cand
                n = 2
                reduced = True
                break
            if runs >= max_runs:
                break
        if not reduced:
            if n >= len(items):
                break
            n = min(len(items), n * 2)
    return items, runs

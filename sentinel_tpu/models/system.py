"""System rules: whole-process adaptive protection (BBR-style).

Reference surface (SURVEY.md §2.1 "SystemSlot"): ``SystemRule`` (qps,
maxThread, avgRt, highestSystemLoad, highestCpuUsage), ``SystemRuleManager``
(merges all rules into one effective minimum per dimension;
``checkSystem``/``checkBbr``), ``SystemStatusListener`` (1 Hz OS poll).
Only inbound traffic (``EntryType.IN``) is guarded, against the global
``Constants.ENTRY_NODE`` aggregate. Upstream paths: ``core:slots/system/``
(reference mount was empty; citations are upstream-layout paths).

TPU-native design: the five effective thresholds compile to one small f32
tensor; load1/CPU are host-sampled at 1 Hz (``SystemStatusListener`` below,
reading ``/proc``) and carried in device state as a 2-element signal vector,
so the check itself is pure: ENTRY_NODE row stats + within-batch prefix +
signals → blocked mask. The BBR check uses the minute-window's per-second
max success count and the 1s window's min RT, mirroring
``maxSuccessQps() * minRt() / 1000``.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import List, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from sentinel_tpu.core import constants as C
from sentinel_tpu.core.rule_manager import RuleManager
from sentinel_tpu.core.batch import EntryBatch
from sentinel_tpu.core.registry import ENTRY_ROW
from sentinel_tpu.ops import fixpoint as FX
from sentinel_tpu.ops import window as W

NOT_SET = C.SYSTEM_RULE_NOT_SET  # -1.0

SIG_LOAD = 0
SIG_CPU = 1
NUM_SIGNALS = 2


@dataclass
class SystemRule:
    highest_system_load: float = NOT_SET
    highest_cpu_usage: float = NOT_SET
    qps: float = NOT_SET
    max_thread: float = NOT_SET
    avg_rt: float = NOT_SET
    # Staged rollout (sentinel_tpu/rollout/): see FlowRule.candidate_set.
    candidate_set: Optional[str] = None
    rollout_stage: Optional[str] = None

    def is_valid(self) -> bool:
        return any(
            v is not None and v >= 0
            for v in (
                self.highest_system_load,
                self.highest_cpu_usage,
                self.qps,
                self.max_thread,
                self.avg_rt,
            )
        )


class SystemRuleTensors(NamedTuple):
    """Effective thresholds (min across loaded rules; NOT_SET = unguarded)."""

    qps: jax.Array         # f32[] scalar
    max_thread: jax.Array  # f32[]
    avg_rt: jax.Array      # f32[]
    load: jax.Array        # f32[]
    cpu: jax.Array         # f32[]
    enabled: jax.Array     # bool[] any dimension set


def compile_system_rules(rules: List[SystemRule]) -> SystemRuleTensors:
    """Merge to one threshold per dimension (``SystemRuleManager.loadRules``)."""

    def eff(values: List[float]) -> float:
        vs = [v for v in values if v is not None and v >= 0]
        return min(vs) if vs else NOT_SET

    valid = [r for r in rules if r.is_valid()]
    qps = eff([r.qps for r in valid])
    max_thread = eff([r.max_thread for r in valid])
    avg_rt = eff([r.avg_rt for r in valid])
    load = eff([r.highest_system_load for r in valid])
    cpu = eff([r.highest_cpu_usage for r in valid])
    enabled = any(v >= 0 for v in (qps, max_thread, avg_rt, load, cpu))
    f = lambda v: jnp.asarray(v, jnp.float32)
    return SystemRuleTensors(
        qps=f(qps), max_thread=f(max_thread), avg_rt=f(avg_rt),
        load=f(load), cpu=f(cpu), enabled=jnp.asarray(enabled),
    )


class SystemRuleManager(RuleManager):
    """Wholesale-swap registry (reference: ``SystemRuleManager``)."""


def check_system(
    rt: SystemRuleTensors,
    signals: jax.Array,      # f32[NUM_SIGNALS] host-sampled [load1, cpu]
    w1: W.Window,
    w60: W.Window,
    sec_counts: jax.Array,   # int32[E, R] live current-second accumulator
    cur_threads: jax.Array,  # int32[R]
    batch: EntryBatch,
    candidate: jax.Array,    # bool[N]
    now_ms: jax.Array,
    spec1: Optional[W.WindowSpec] = None,  # w1 geometry (engine may retune)
) -> jax.Array:
    """Vectorized ``SystemRuleManager.checkSystem``: bool[N] blocked.

    ``w60`` holds only folded (completed) seconds; the live second lives in
    ``sec_counts`` (the step's staging accumulator). The BBR read masks
    stale buckets itself. Survivor resolution follows check_flow's
    convention (ops/fixpoint.py): uniform-count batches take the classic
    two passes reproducing the serial "blocked requests never count"
    rule exactly; MIXED acquire counts iterate to the fixpoint — the
    global IN prefix has the same truncated-second-pass over-admission
    class the flow and param sweeps had (r5).
    """

    def _blocked_for(survivors):
        return _eval_system(rt, signals, w1, w60, sec_counts, cur_threads,
                            batch, candidate, survivors=survivors,
                            now_ms=now_ms, spec1=spec1)

    # Only IN entries feed the global prefix: an OUT entry's odd count
    # must not push a uniform-IN batch off the exact two-pass hot path.
    survivors = FX.survivor_fixpoint(candidate, _blocked_for, batch.count,
                                     relevant=batch.entry_in)
    return _blocked_for(survivors)


def _eval_system(
    rt: SystemRuleTensors,
    signals: jax.Array,
    w1: W.Window,
    w60: W.Window,
    sec_counts: jax.Array,
    cur_threads: jax.Array,
    batch: EntryBatch,
    candidate: jax.Array,
    survivors: jax.Array,
    now_ms: jax.Array,
    spec1: Optional[W.WindowSpec] = None,
) -> jax.Array:
    n = batch.size
    applicable = candidate & batch.entry_in & rt.enabled

    # Within-batch arrival prefixes on the single ENTRY_NODE row: exclusive
    # cumsum over inbound survivors.
    contrib = jnp.where(survivors & batch.entry_in, batch.count, 0)
    tok_prefix = jnp.cumsum(contrib) - contrib
    ent_contrib = jnp.where(survivors & batch.entry_in, 1, 0)
    ent_prefix = jnp.cumsum(ent_contrib) - ent_contrib

    # Per-second normalization of window sums (reference passQps divides by
    # the interval seconds) — 1.0 under the default geometry.
    qps_scale = jnp.float32(
        1000.0 / (spec1.interval_ms if spec1 is not None
                  else C.SECOND_WINDOW_MS))
    totals = W.all_totals(w1)[ENTRY_ROW]  # [E]
    pass_qps = (totals[C.MetricEvent.PASS].astype(jnp.float32)
                + tok_prefix.astype(jnp.float32)) * qps_scale
    succ = jnp.maximum(totals[C.MetricEvent.SUCCESS].astype(jnp.float32), 1.0)
    cur_rt = totals[C.MetricEvent.RT].astype(jnp.float32) / succ
    threads = cur_threads[ENTRY_ROW].astype(jnp.float32) + ent_prefix.astype(jnp.float32)

    qps_ok = (rt.qps < 0) | (pass_qps + batch.count.astype(jnp.float32) <= rt.qps)
    thr_ok = (rt.max_thread < 0) | (threads <= rt.max_thread)
    rt_ok = (rt.avg_rt < 0) | (cur_rt <= rt.avg_rt)

    # BBR gate on load: estimated capacity = maxSuccessQps · minRt / 1000.
    # maxSuccessQps: the minute window's busiest 1s bucket — fresh folded
    # buckets (masked) plus the live staged second, exactly the reference's
    # "partial current bucket counts too" behavior.
    spec_60s = W.WindowSpec(C.MINUTE_WINDOW_MS, C.MINUTE_BUCKETS)
    fresh = W.staleness_mask(w60, now_ms, spec_60s)
    bucket_succ = jnp.where(
        fresh, w60.counts[:, C.MetricEvent.SUCCESS, ENTRY_ROW], 0
    ).astype(jnp.float32)
    max_succ_qps = jnp.maximum(
        jnp.max(bucket_succ),
        sec_counts[C.MetricEvent.SUCCESS, ENTRY_ROW].astype(jnp.float32),
    )
    min_rt = jnp.min(w1.min_rt[:, ENTRY_ROW]).astype(jnp.float32)
    min_rt = jnp.where(min_rt >= W.MIN_RT_EMPTY, 0.0, min_rt)
    bbr_ok = (threads <= 1.0) | (threads <= max_succ_qps * min_rt / 1000.0)
    load_ok = (rt.load < 0) | (signals[SIG_LOAD] <= rt.load) | bbr_ok

    cpu_ok = (rt.cpu < 0) | (signals[SIG_CPU] <= rt.cpu)

    ok = qps_ok & thr_ok & rt_ok & load_ok & cpu_ok
    return applicable & (~ok)


class SystemStatusListener:
    """1 Hz host sampler of load1 + process-visible CPU usage.

    Reference: ``SystemStatusListener`` polls ``OperatingSystemMXBean``.
    Here: ``/proc/loadavg`` and a ``/proc/stat`` delta. Thread-safe reads of
    the latest sample via :meth:`snapshot`.
    """

    def __init__(self, interval_s: float = 1.0):
        self.interval_s = interval_s
        self._lock = threading.Lock()
        self._load = -1.0
        self._cpu = -1.0
        self._prev_stat = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._sample()  # prime synchronously so the first check has data
        self._thread = threading.Thread(
            target=self._run, name="sentinel-system-status", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            # Join so a stop()-then-start() can't leave two samplers racing
            # on the cleared stop event.
            self._thread.join(timeout=self.interval_s + 1.0)
        self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self._sample()

    def _sample(self) -> None:
        load = self._read_load()
        cpu = self._read_cpu()
        with self._lock:
            if load is not None:
                self._load = load
            if cpu is not None:
                self._cpu = cpu

    def snapshot(self) -> np.ndarray:
        with self._lock:
            return np.asarray([self._load, self._cpu], np.float32)

    @staticmethod
    def _read_load() -> Optional[float]:
        try:
            with open("/proc/loadavg") as f:
                return float(f.read().split()[0])
        except (OSError, ValueError, IndexError):
            return None

    def _read_cpu(self) -> Optional[float]:
        try:
            with open("/proc/stat") as f:
                parts = f.readline().split()
            if parts[0] != "cpu":
                return None
            vals = [int(x) for x in parts[1:8]]
        except (OSError, ValueError, IndexError):
            return None
        idle = vals[3] + vals[4]  # idle + iowait
        total = sum(vals)
        prev = self._prev_stat
        self._prev_stat = (total, idle)
        if prev is None or total <= prev[0]:
            return None
        dt, di = total - prev[0], idle - prev[1]
        return max(0.0, min(1.0, 1.0 - di / dt)) if dt > 0 else None

"""Hard safety envelope for autonomous rule actuation.

"Designing Scalable Rate Limiting Systems" (PAPERS.md) warns that
adaptive limiters without bounded actuation oscillate; this module is
the bound. Every invariant lives here, first-class and separately
testable, so the controller/policy layer (``controller.py``) can be
swapped for a learned model without re-litigating safety:

* **Floor/ceiling clamps** — a proposed threshold never leaves the
  target's ``[floor, ceiling]`` band, whatever the policy says.
* **Bounded step size** — one actuation moves a threshold by at most
  ``step_pct`` of its current value (with a 1.0 absolute minimum so
  small integer-ish thresholds can still move at all).
* **Per-resource cooldown** — after a promoted change, the resource is
  untouchable for ``cooldown_ms``: the new setting's effect must show
  up in the flight recorder before it may be re-judged.
* **Hysteresis (no flapping across the target)** — a proposal that
  REVERSES the direction of the previous promoted change is rejected
  for ``flip_cooldown_ms`` (2x the plain cooldown by default): one
  boundary-straddling sense can never ping-pong a threshold.
* **Global freeze** (:class:`FreezeGate`) — stale or faulted telemetry,
  a manual ops freeze, or the post-abort backoff window turn the whole
  loop read-only: a controller must never actuate on senses it cannot
  trust, and never re-propose into the blast crater of an abort.

The envelope never talks to the engine or the rollout manager — it is
pure host arithmetic over explicit inputs, which is what makes the
invariants testable in isolation (tests/test_adaptive.py drives every
clause without a device).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, Optional

# EnvelopeDecision.reason values (stable strings — the decision log and
# the ops command surface them verbatim).
REASON_OK = "ok"
REASON_FLOOR = "floor"
REASON_CEILING = "ceiling"
REASON_STEP = "step"
REASON_COOLDOWN = "cooldown"
REASON_FLIP = "hysteresis"
REASON_NOOP = "no-op"

# FreezeGate reasons, in precedence order (manual beats everything:
# an operator's freeze must not be re-labelled by a coincident fault).
FREEZE_MANUAL = "manual"
FREEZE_DISABLED = "recorder-disabled"
FREEZE_STALE = "telemetry-stale"
FREEZE_FAULTED = "telemetry-faulted"
FREEZE_BACKOFF = "abort-backoff"
FREEZE_DEGRADED = "degraded-leader"


class CooldownLedger:
    """Per-key cooldown + direction-flip hysteresis — the shared
    actuation-pacing primitive (ISSUE 16 extracted it from
    :class:`SafetyEnvelope` so the shard rebalancer paces per-SLICE
    moves with the same clauses the adaptive loop paces per-resource
    threshold changes, instead of a second copy of the arithmetic).

    A key is whatever the caller actuates on (a resource name, a slice
    index); ``direction`` is any equality-comparable token (+1/-1 for
    thresholds, the destination leader for a slice move). After a
    :meth:`stamp`, the key is untouchable for ``cooldown_ms``, and a
    DIFFERENT direction stays rejected for ``flip_cooldown_ms`` (2x by
    default) — crossing back is where oscillation lives."""

    def __init__(self, cooldown_ms: int,
                 flip_cooldown_ms: Optional[int] = None):
        self.cooldown_ms = int(cooldown_ms)
        self.flip_cooldown_ms = (int(flip_cooldown_ms)
                                 if flip_cooldown_ms is not None
                                 else 2 * int(cooldown_ms))
        self._lock = threading.Lock()
        self._last: Dict = {}  # key -> (last stamped ms, direction)

    def check(self, key, direction, now_ms: int) -> Optional[str]:
        """REASON_COOLDOWN / REASON_FLIP when the key may not move
        (in that precedence), None when it may."""
        with self._lock:
            last = self._last.get(key)
        if last is None:
            return None
        last_ms, last_dir = last
        if now_ms - last_ms < self.cooldown_ms:
            return REASON_COOLDOWN
        if direction != last_dir \
                and now_ms - last_ms < self.flip_cooldown_ms:
            return REASON_FLIP
        return None

    def stamp(self, key, direction, now_ms: int) -> None:
        with self._lock:
            self._last[key] = (int(now_ms), direction)

    def state(self, now_ms: int) -> Dict:
        """Ops view: per-key cooldown remaining (keys inside only the
        longer flip window have served their plain cooldown and drop
        out, matching the adaptive ``cooldown_state`` shape)."""
        with self._lock:
            items = dict(self._last)
        out = {}
        for key, (last_ms, direction) in items.items():
            remaining = max(0, self.cooldown_ms - (now_ms - last_ms))
            if remaining > 0:
                out[key] = {"remainingMs": remaining,
                            "direction": direction}
        return out

    def reset(self) -> None:
        with self._lock:
            self._last.clear()


@dataclass(frozen=True)
class EnvelopeDecision:
    """Outcome of one :meth:`SafetyEnvelope.admit` call.

    ``allowed`` — the (possibly clamped) proposal may proceed;
    ``value`` — the threshold to actually stage (== ``current`` when
    rejected); ``clamped`` — a clamp changed the policy's ask;
    ``reason`` — which clause decided (one of the REASON_* constants).
    """

    allowed: bool
    value: float
    clamped: bool
    reason: str


class SafetyEnvelope:
    """Clamp + cooldown + hysteresis state for one adaptive loop."""

    def __init__(self, step_pct: float, cooldown_ms: int,
                 flip_cooldown_ms: Optional[int] = None):
        self.step_pct = float(step_pct)
        # Cooldown + direction-flip hysteresis live in the shared
        # ledger (the rebalancer paces slice moves through the same
        # primitive); direction here is +1/-1 relative to current.
        self._ledger = CooldownLedger(cooldown_ms, flip_cooldown_ms)

    @property
    def cooldown_ms(self) -> int:
        return self._ledger.cooldown_ms

    @property
    def flip_cooldown_ms(self) -> int:
        return self._ledger.flip_cooldown_ms

    def admit(self, resource: str, current: float, proposed: float,
              floor: float, ceiling: float, now_ms: int) -> EnvelopeDecision:
        """Run one proposal through every clause. Order matters and is
        part of the contract: cooldown/hysteresis (is actuation allowed
        AT ALL right now?) before clamps (how far may it go?), so a
        rejected resource never reports a misleading clamp reason."""
        direction = 1 if proposed > current else -1
        paced = self._ledger.check(resource, direction, now_ms)
        if paced is not None:
            return EnvelopeDecision(False, current, False, paced)
        if not floor <= current <= ceiling:
            # The LIVE value sits outside the band (an operator put it
            # there — e.g. an emergency clamp below the target's floor).
            # Admitting anything would either invert the ask's direction
            # (a congestion DECREASE clamped up to the floor is a limit
            # INCREASE) or stage a value the band forbids; both are
            # wrong, so the envelope refuses until the operator
            # reconciles the rule with the target (docs/OPERATIONS.md
            # "How to pin a resource static").
            return EnvelopeDecision(
                False, current, True,
                REASON_FLOOR if current < floor else REASON_CEILING)
        value, clamped, reason = proposed, False, REASON_OK
        # Bounded step first, band second: the band is the HARD invariant
        # (a floor/ceiling is never exceeded even when the step allows it).
        max_step = max(abs(current) * self.step_pct, 1.0)
        if abs(value - current) > max_step:
            value = current + max_step * direction
            clamped, reason = True, REASON_STEP
        if value < floor:
            value, clamped, reason = floor, True, REASON_FLOOR
        elif value > ceiling:
            value, clamped, reason = ceiling, True, REASON_CEILING
        if value == current:
            # Fully clamped back to where we already are (pinned at a
            # band edge, typically): not an actuation.
            return EnvelopeDecision(False, current, True, REASON_NOOP)
        return EnvelopeDecision(True, value, clamped, reason)

    def record_actuation(self, resource: str, current: float,
                         promoted: float, now_ms: int) -> None:
        """Stamp a PROMOTED change (cooldown + flip guard input).
        Proposals that die in shadow/canary don't stamp — the post-abort
        backoff (FreezeGate) covers that quiet period instead."""
        direction = 1 if promoted > current else -1
        self._ledger.stamp(resource, direction, now_ms)

    def cooldown_state(self, now_ms: int) -> Dict[str, Dict]:
        """Ops view: per-resource cooldown remaining."""
        return self._ledger.state(now_ms)

    def reset(self) -> None:
        self._ledger.reset()


@dataclass(frozen=True)
class FreezeState:
    frozen: bool
    reason: Optional[str]  # FREEZE_* constant, None when thawed


class FreezeGate:
    """Global actuation freeze: pure predicate over explicit inputs.

    The loop feeds it what it observed this tick; the gate only decides.
    Keeping it stateless (beyond nothing at all) means every clause is a
    one-line truth-table test.
    """

    def __init__(self, stale_after_ms: int):
        self.stale_after_ms = int(stale_after_ms)

    def evaluate(self, now_ms: int, *,
                 manual_frozen: bool,
                 recorder_enabled: bool,
                 last_second_ms: int,
                 fault_delta: int,
                 backoff_until_ms: int) -> FreezeState:
        """Precedence: manual > recorder-disabled > stale > faulted >
        backoff. ``last_second_ms`` is the newest COMPLETE second the
        flight recorder spilled (<= 0 means none yet — stale by
        definition); ``fault_delta`` counts fail-open / cluster-fallback
        events since the previous tick (any > 0 means the telemetry this
        tick judged may be missing the traffic that mattered most)."""
        if manual_frozen:
            return FreezeState(True, FREEZE_MANUAL)
        if not recorder_enabled:
            return FreezeState(True, FREEZE_DISABLED)
        if last_second_ms <= 0 \
                or now_ms - last_second_ms > self.stale_after_ms:
            return FreezeState(True, FREEZE_STALE)
        if fault_delta > 0:
            return FreezeState(True, FREEZE_FAULTED)
        if now_ms < backoff_until_ms:
            return FreezeState(True, FREEZE_BACKOFF)
        return FreezeState(False, None)


class RebalanceFreezeGate:
    """The shard rebalancer's freeze (ISSUE 16): same stateless-
    predicate discipline as :class:`FreezeGate`, with the clauses a
    PLACEMENT controller needs. Precedence: manual > stale-telemetry >
    degraded-leader > abort-backoff — an operator's freeze is never
    re-labelled, a skew computed from stale fleet series is never
    trusted, and nothing moves while any leader is degraded (moving
    slices around a sick leader amplifies the outage; fold-OUT plans
    evaluate with ``degraded_leaders=()`` because the sick leader is
    the reason to move, see cluster/rebalance.py)."""

    def __init__(self, stale_after_ms: int):
        self.stale_after_ms = int(stale_after_ms)

    def evaluate(self, now_ms: int, *,
                 manual_frozen: bool,
                 settled_through_ms: int,
                 degraded_leaders=(),
                 backoff_until_ms: int = 0) -> FreezeState:
        """``settled_through_ms`` is the newest second the fleet view
        has settled federation-wide (<= 0 means none — stale by
        definition); ``degraded_leaders`` the machine ids currently
        stale/regressed/unhealthy."""
        if manual_frozen:
            return FreezeState(True, FREEZE_MANUAL)
        if settled_through_ms <= 0 \
                or now_ms - settled_through_ms > self.stale_after_ms:
            return FreezeState(True, FREEZE_STALE)
        if degraded_leaders:
            return FreezeState(True, FREEZE_DEGRADED)
        if now_ms < backoff_until_ms:
            return FreezeState(True, FREEZE_BACKOFF)
        return FreezeState(False, None)

"""Hot-parameter flow tests.

Modeled on the reference's ``ParamFlowCheckerTest`` / demo behavior
(SURVEY.md §2.2): per-value QPS token buckets with burst, per-value
exception items, THREAD-grade concurrency, throttle behavior, and the
bounded-key-space eviction semantics.
"""

import pytest

import sentinel_tpu as st
from sentinel_tpu.core import constants as C


def hits(resource, value, n, **kw):
    """Attempt n entries with one hot param; return pass count."""
    passed = 0
    for _ in range(n):
        h = st.entry_ok(resource, args=(value,), **kw)
        if h is not None:
            passed += 1
            h.exit()
    return passed


class TestParamFlowQps:
    def test_per_value_isolation(self, engine):
        st.load_param_flow_rules([st.ParamFlowRule("hot", param_idx=0, count=3)])
        assert hits("hot", "keyA", 5) == 3
        # A different value has its own bucket.
        assert hits("hot", "keyB", 5) == 3

    def test_refill_after_duration(self, engine, frozen_time):
        st.load_param_flow_rules([st.ParamFlowRule("hot", param_idx=0, count=2)])
        assert hits("hot", 42, 4) == 2
        frozen_time.advance_time(1100)
        assert hits("hot", 42, 4) == 2

    def test_burst_capacity(self, engine, frozen_time):
        st.load_param_flow_rules([
            st.ParamFlowRule("hot", param_idx=0, count=2, burst_count=3)
        ])
        # Full bucket = count + burst on first touch.
        assert hits("hot", "k", 10) == 5
        # After one idle window only `count` tokens drip back in.
        frozen_time.advance_time(1100)
        assert hits("hot", "k", 10) == 2

    def test_duration_in_sec(self, engine, frozen_time):
        st.load_param_flow_rules([
            st.ParamFlowRule("hot", param_idx=0, count=2, duration_in_sec=2)
        ])
        assert hits("hot", "k", 4) == 2
        frozen_time.advance_time(1100)  # only half the window elapsed
        assert hits("hot", "k", 4) == 0
        frozen_time.advance_time(1000)
        assert hits("hot", "k", 4) == 2

    def test_item_exception_overrides(self, engine):
        st.load_param_flow_rules([
            st.ParamFlowRule(
                "hot", param_idx=0, count=1,
                items=[st.ParamFlowItem("vip", 5)],
            )
        ])
        assert hits("hot", "vip", 8) == 5
        assert hits("hot", "pleb", 8) == 1

    def test_zero_threshold_blocks_all(self, engine):
        st.load_param_flow_rules([st.ParamFlowRule("hot", param_idx=0, count=0)])
        assert hits("hot", "k", 3) == 0

    def test_param_idx_selects_argument(self, engine):
        st.load_param_flow_rules([st.ParamFlowRule("hot", param_idx=1, count=1)])
        # Same arg0, different arg1: separate buckets.
        assert st.entry_ok("hot", args=("x", "a")) is not None
        assert st.entry_ok("hot", args=("x", "b")) is not None
        assert st.entry_ok("hot", args=("y", "a")) is None

    def test_missing_param_passes(self, engine):
        st.load_param_flow_rules([st.ParamFlowRule("hot", param_idx=2, count=1)])
        # Entry carries no index-2 argument: the rule does not apply.
        passed = 0
        for _ in range(5):
            h = st.entry_ok("hot", args=("only0",))
            if h:
                passed += 1
                h.exit()
        assert passed == 5

    def test_count_acquires_tokens(self, engine):
        st.load_param_flow_rules([st.ParamFlowRule("hot", param_idx=0, count=5)])
        h = st.entry_ok("hot", count=4, args=("k",))
        assert h is not None
        h.exit()
        assert st.entry_ok("hot", count=4, args=("k",)) is None
        h = st.entry_ok("hot", count=1, args=("k",))
        assert h is not None
        h.exit()


class TestParamFlowThread:
    def test_concurrency_per_value(self, engine):
        st.load_param_flow_rules([
            st.ParamFlowRule("hot", param_idx=0, count=2,
                             grade=C.PARAM_FLOW_GRADE_THREAD)
        ])
        e1 = st.entry("hot", args=("k",))
        e2 = st.entry("hot", args=("k",))
        assert st.entry_ok("hot", args=("k",)) is None
        # Another value is free.
        e3 = st.entry("hot", args=("other",))
        e3.exit()
        e1.exit()
        # Slot released.
        e4 = st.entry("hot", args=("k",))
        e4.exit()
        e2.exit()


class TestParamFlowThrottle:
    def test_paced_admission_with_wait(self, engine, frozen_time):
        st.load_param_flow_rules([
            st.ParamFlowRule(
                "hot", param_idx=0, count=10,  # 100ms per token
                control_behavior=C.CONTROL_BEHAVIOR_RATE_LIMITER,
                max_queueing_time_ms=500,
            )
        ])
        # First passes immediately; next few pace out until the 500ms queue
        # cap rejects.
        got = [st.entry_ok("hot", args=("k",)) for _ in range(8)]
        passed = [h for h in got if h is not None]
        assert 5 <= len(passed) <= 6  # 500ms cap / 100ms cost (+head slack)
        for h in passed:
            h.exit()


class TestEviction:
    def test_distinct_values_beyond_table_conflate_bounded(self, engine):
        # Keys are hashed into a fixed table; a *new* key evicts its slot
        # and starts a fresh bucket (tensor analog of the reference's LRU
        # cap). Protection per hot value still holds.
        st.load_param_flow_rules([st.ParamFlowRule("hot", param_idx=0, count=1)])
        for i in range(50):
            h = st.entry_ok("hot", args=(f"key{i}",))
            assert h is not None
            h.exit()
        # The hot key within its bucket is still limited.
        assert hits("hot", "key0", 3) <= 1


def test_negative_burst_rule_is_dropped(engine):
    """Reference parity: malformed rules are discarded, traffic passes."""
    st.load_param_flow_rules([
        st.ParamFlowRule("hot", param_idx=0, count=5, burst_count=-10)
    ])
    for _ in range(3):
        h = st.entry_ok("hot", args=("k",))
        assert h is not None
        h.exit()


def test_empty_family_compiles_zero_slots_with_ratchet_floor():
    """Rule-free families compile to ZERO slots (their per-slot loop
    vanishes at trace time — a no-rules step measured ~4x cheaper), and
    ``min_slots`` restores the wider shape so the engine's ratchet can
    keep rule pushes retrace-free after a family's first use."""
    from sentinel_tpu.core.registry import NodeRegistry
    from sentinel_tpu.models import param_flow as P

    reg = NodeRegistry(64)
    assert P.compile_param_rules([], reg, 64).slots == 0
    pt = P.compile_param_rules(
        [st.ParamFlowRule("r", param_idx=0, count=5)], reg, 64)
    assert pt.slots == 1
    # The ratchet case: rules dropped back to zero keeps the shape.
    assert P.compile_param_rules([], reg, 64, min_slots=1).slots == 1


def test_engine_slot_floor_ratchets_across_pushes(engine, frozen_time):
    """Pushing a family's first rule widens its slot floor permanently:
    clearing the rules later compiles the SAME tensor shape, so the
    fused step is not retraced by the push cycle (the round-4
    'rule pushes don't recompile' guarantee, kept under zero-slot
    compiles of empty families)."""
    assert engine._slot_floor["param"] == 0
    st.load_param_flow_rules([st.ParamFlowRule("hot", param_idx=0, count=2)])
    h = st.entry_ok("hot", args=("k",))  # forces compile + dispatch
    if h:
        h.exit()
    assert engine._slot_floor["param"] == 1
    shape_with_rules = tuple(engine._rules.param.rules_by_row.shape)
    st.load_param_flow_rules([])  # clear the family
    h = st.entry_ok("hot", args=("k",))
    if h:
        h.exit()
    assert engine._slot_floor["param"] == 1
    assert tuple(engine._rules.param.rules_by_row.shape) == shape_with_rules


def test_reset_slot_floor_shrinks_after_transient_burst(engine, frozen_time):
    """The ratchet's escape hatch (r4 advisory): after a transient burst
    widens a family's loop, ``reset_slot_floor()`` (the ``resetSlotFloor``
    ops command) shrinks the compiled shapes back to what current rules
    need, at the documented cost of one retrace."""
    st.load_param_flow_rules([
        st.ParamFlowRule("hot", param_idx=0, count=2, duration_in_sec=i + 1)
        for i in range(4)  # 4 rules on ONE resource -> 4 slots
    ])
    h = st.entry_ok("hot", args=("k",))
    if h:
        h.exit()
    assert engine._slot_floor["param"] == 4
    st.load_param_flow_rules(
        [st.ParamFlowRule("hot", param_idx=0, count=2)])  # burst over
    h = st.entry_ok("hot", args=("k",))
    if h:
        h.exit()
    assert engine._slot_floor["param"] == 4  # ratchet held the wide shape
    wide = tuple(engine._rules.param.rules_by_row.shape)

    old = engine.reset_slot_floor()
    assert old["param"] == 4
    h = st.entry_ok("hot", args=("k",))  # forces the shrink recompile
    if h:
        h.exit()
    assert engine._slot_floor["param"] == 1
    narrow = tuple(engine._rules.param.rules_by_row.shape)
    assert narrow != wide and narrow[-1] == 1

    # still admits correctly after the shrink
    blocked = 0
    for _ in range(6):
        h = st.entry_ok("hot", args=("k",))
        if h:
            h.exit()
        else:
            blocked += 1
    assert blocked > 0  # count=2 rule still enforced post-reset


def test_reset_slot_floor_command(engine, frozen_time):
    import json

    from sentinel_tpu.transport.command_center import CommandRequest
    from sentinel_tpu.transport.handlers import cmd_reset_slot_floor

    st.load_param_flow_rules([
        st.ParamFlowRule("hot", param_idx=0, count=2, duration_in_sec=i + 1)
        for i in range(3)
    ])
    h = st.entry_ok("hot", args=("k",))
    if h:
        h.exit()
    st.load_param_flow_rules([])
    resp = cmd_reset_slot_floor(CommandRequest(engine=engine))
    assert resp.success
    body = json.loads(resp.result)
    assert body["previousFloor"]["param"] == 3
    assert body["floor"]["param"] == 0


def _jit_cache_size(jitted):
    """jax-private trace-cache probe; skip rather than fail if a jax
    bump renames it (the ratchet behavior itself is version-agnostic)."""
    probe = getattr(jitted, "_cache_size", None)
    if probe is None:
        pytest.skip("jax _cache_size API unavailable in this version")
    return probe()


def test_rule_push_cycle_never_retraces_after_first_use(engine, frozen_time):
    """The compile-count guarantee behind the ratchet: after a family's
    first use is compiled, pushing new rule VALUES, clearing the family,
    and re-pushing must all hit the same jit specialization — the
    entry jit's trace-cache size stays at 1."""
    st.load_flow_rules([st.FlowRule(resource="api", count=100)])
    st.load_param_flow_rules([st.ParamFlowRule("api", param_idx=0, count=50)])
    h = st.entry_ok("api", args=("k",))
    if h:
        h.exit()
    jit0 = engine._entry_jit  # identity-pin: a rebuilt jit would reset
    assert _jit_cache_size(jit0) == 1
    # Value-only push, family clear, and re-push: no new specialization.
    st.load_param_flow_rules([st.ParamFlowRule("api", param_idx=0, count=9)])
    h = st.entry_ok("api", args=("k",))
    if h:
        h.exit()
    st.load_param_flow_rules([])
    h = st.entry_ok("api", args=("k",))
    if h:
        h.exit()
    st.load_param_flow_rules([st.ParamFlowRule("api", param_idx=0, count=2)])
    h = st.entry_ok("api", args=("k",))
    if h:
        h.exit()
    assert engine._entry_jit is jit0  # not silently rebuilt per push
    assert _jit_cache_size(jit0) == 1

"""Fleet telemetry federation: N leaders -> one exact mesh-wide view.

Until now every telemetry layer (attribution, flight recorder, spans,
SLO health) was strictly per-process. This module is the mesh-wide
half (ISSUE 14):

* **Leader side** — :func:`leader_fleet_payload` renders one page of a
  leader's per-second flight-recorder spill (COMPLETE seconds strictly
  after the caller's cursor), its instance health, and its shard
  ownership as the ``fleetTelemetry`` wire reply (``MSG_FLEET`` —
  served by both frontends through ``process_control_frame``, so the
  reactor's zero-copy path carries it for free). Pages are bounded to
  fit the u16 frame; the cursor loops for more.
* **Collector side** — :class:`FleetView` polls N leaders over plain
  token-client sockets and federates their pages into an EXACT
  fleet-wide per-second series keyed by (stamp, resource, leader):
  per-leader cells are stored verbatim (bit-exact — federation never
  re-aggregates device numbers, it only sums them at read time), with
  per-leader staleness and clock-skew tracking, and fleet health as
  the composition (min) of the PR 7 instance healths.

Exactness contract (docs/SEMANTICS.md "Fleet-series exactness"): the
fleet sum for (resource, stamp) equals the arithmetic sum of each
leader's own ``timeseries_view`` cell for that second — COMPLETE
seconds only; a second is *settled* fleet-wide once every non-stale
leader's cursor has advanced past it (``settled_through_ms``).
In-progress seconds remain per-leader only — the one asymmetry.

Clocks: everything here rides an injected clock (the collector is
usually handed ``engine.now_ms``) — test_lint pins that no wall clock
is read in this module, the same determinism stance as the journal.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, NamedTuple, Optional

# Frame body budget for one fleetTelemetry reply entity: the TLV frame
# length field is u16; leave headroom for the response head + epoch TLV.
MAX_ENTITY_BYTES = 64_000

# Page-loop bound per poll cycle: a freshly attached collector catching
# up on a long-retained leader pulls at most this many pages per poll
# (the next poll continues from the cursor — bounded work per tick).
MAX_PAGES_PER_POLL = 8

_SUM_FIELDS = ("pass", "block", "success", "exception", "rtSumMs",
               "occupiedPass")


class LeaderSpec(NamedTuple):
    name: str
    host: str
    port: int


# -- leader side --------------------------------------------------------------


def leader_fleet_payload(server, since_ms: int, max_seconds: int) -> bytes:
    """One encoded ``fleetTelemetry`` reply entity for this leader:
    complete seconds strictly after ``since_ms`` (at most
    ``max_seconds``, further shrunk to fit the frame), instance health,
    and shard ownership. The caller stamps the epoch TLV behind it."""
    from sentinel_tpu.cluster import codec
    from sentinel_tpu.core.config import config as _cfg
    from sentinel_tpu.telemetry.timeseries import second_to_dict

    engine = server.engine
    cap = _cfg.fleet_max_seconds()
    k = max(1, min(int(max_seconds) if max_seconds > 0 else cap, cap))
    # Fold + spill first so the answer is current through the newest
    # complete second, then page on the COMPACT records and render only
    # the served page (a catching-up collector must not pay an
    # O(retention) JSON render per 16-second page).
    engine.slo_refresh()
    recs = engine.timeseries.query(start_ms=int(since_ms) + 1)
    metas = engine._device_metas()
    service = server.service
    shard = getattr(service, "shard", None)
    base = {
        "v": 1,
        "leader": _cfg.cluster_ha_machine_id() or _cfg.app_name(),
        "nowMs": engine.now_ms(),
        "epoch": int(getattr(service, "epoch", 0)),
        "shard": ({
            "mapVersion": int(shard.version),
            "nSlices": int(shard.n_slices),
            "slices": {str(sl): int(ep)
                       for sl, ep in sorted(shard.epochs.items())},
        } if shard is not None else None),
        "health": engine.slo.health_scores(),
        "lastStampMs": max(engine.timeseries.last_stamp_ms,
                           recs[-1].stamp_ms if recs else -1),
    }
    while True:
        page = [second_to_dict(r, metas) for r in recs[:k]]
        payload = dict(base)
        payload["seconds"] = page
        payload["moreAfterMs"] = (page[-1]["timestamp"]
                                  if len(recs) > len(page) and page
                                  else None)
        entity = codec.encode_json_entity(payload)
        if len(entity) <= MAX_ENTITY_BYTES:
            return entity
        if k > 1:
            k = k // 2
            continue
        # A SINGLE second too fat for the frame: skip it LOUDLY rather
        # than stall the cursor forever — the page names the skipped
        # stamp so the collector advances past it and counts the drop.
        payload["seconds"] = []
        payload["skippedSecondMs"] = recs[0].stamp_ms
        payload["moreAfterMs"] = (recs[0].stamp_ms if len(recs) > 1
                                  else None)
        return codec.encode_json_entity(payload)


def leader_population_payload(server) -> bytes:
    """One encoded population page (ISSUE 19) for this leader: the
    namespace telescope's mergeable sketches, current through the spill
    fold, sized to the same frame budget as a telemetry page. Served
    through the SAME ``MSG_FLEET`` message — a request with the
    ``max_seconds == -1`` sentinel selects this page, so a pre-telescope
    server transparently answers with a normal seconds page instead
    (the missing ``population`` key marks it unsupported client-side)."""
    from sentinel_tpu.cluster import codec
    from sentinel_tpu.core.config import config as _cfg

    engine = server.engine
    tracker = getattr(engine, "population", None) if engine is not None \
        else None
    if engine is not None:
        engine.slo_refresh()  # fold first: the page is current
    payload = {
        "v": 1,
        "leader": _cfg.cluster_ha_machine_id() or _cfg.app_name(),
        "nowMs": engine.now_ms() if engine is not None else 0,
        "epoch": int(getattr(server.service, "epoch", 0)),
        "population": (tracker.page(max_bytes=MAX_ENTITY_BYTES - 512)
                       if tracker is not None and tracker.enabled
                       else None),
    }
    return codec.encode_json_entity(payload)


# -- collector side -----------------------------------------------------------


class _LeaderState:
    __slots__ = ("spec", "client", "cursor_ms", "last_stamp_ms",
                 "last_ok_ms", "skew_ms", "polls", "errors", "unsupported",
                 "health", "shard", "epoch", "max_epoch", "epoch_regressed",
                 "seconds_ingested", "seconds_skipped", "remote_name",
                 "population", "population_at_ms", "population_polls",
                 "population_errors", "population_unsupported")

    def __init__(self, spec: LeaderSpec, client):
        self.spec = spec
        self.client = client
        self.cursor_ms = 0
        self.last_stamp_ms = -1
        self.last_ok_ms = -1   # collector clock at last successful payload
        self.skew_ms: Optional[int] = None
        self.polls = 0
        self.errors = 0
        self.unsupported = False
        self.health: Optional[Dict] = None
        self.shard: Optional[Dict] = None
        self.epoch = 0
        self.max_epoch = 0          # high-water epoch ever reported
        self.epoch_regressed = False
        self.seconds_ingested = 0
        self.seconds_skipped = 0   # fat seconds the leader couldn't frame
        self.remote_name: Optional[str] = None
        self.population: Optional[Dict] = None  # latest page, VERBATIM
        self.population_at_ms = -1
        self.population_polls = 0
        self.population_errors = 0
        self.population_unsupported = False


class FleetView:
    """Federates N leaders' fleetTelemetry pages into one exact view.

    ``leaders``: iterable of (name, host, port) tuples or dicts with
    those keys — ``name`` is the collector-side identity every series
    cell is keyed by (the wire payload's self-reported id is kept as
    ``remoteName`` for cross-checking). ``clock`` is a callable
    returning ms on the collector's timebase (``engine.now_ms``).
    """

    def __init__(self, leaders, clock,
                 stale_ms: Optional[int] = None,
                 history_seconds: Optional[int] = None,
                 max_seconds: Optional[int] = None,
                 client_factory=None):
        from sentinel_tpu.core.config import config as _cfg

        self._clock = clock
        self.stale_ms = int(stale_ms if stale_ms is not None
                            else _cfg.fleet_stale_ms())
        self.history_seconds = int(history_seconds if history_seconds
                                   is not None
                                   else _cfg.fleet_history_seconds())
        self.max_seconds = int(max_seconds if max_seconds is not None
                               else _cfg.fleet_max_seconds())
        if client_factory is None:
            client_factory = self._default_client
        self._lock = threading.Lock()
        # stamp -> resource -> leader name -> the leader's cell, stored
        # VERBATIM (bit-exactness: sums are computed at read time from
        # unmodified per-leader cells).
        self._store: "OrderedDict[int, Dict[str, Dict[str, Dict]]]" = \
            OrderedDict()
        self._leaders: "OrderedDict[str, _LeaderState]" = OrderedDict()
        self.poll_count = 0
        self.poll_errors = 0
        # Validate EVERY spec before starting ANY client: a bad spec
        # halfway through must not leak already-started reader threads
        # (the caller sees the raise and has nothing to stop).
        specs: List[LeaderSpec] = []
        for spec in leaders:
            if isinstance(spec, dict):
                spec = LeaderSpec(str(spec["name"]), str(spec["host"]),
                                  int(spec["port"]))
            else:
                spec = LeaderSpec(str(spec[0]), str(spec[1]), int(spec[2]))
            if any(s.name == spec.name for s in specs):
                raise ValueError(f"duplicate leader name {spec.name!r}")
            specs.append(spec)
        if not specs:
            raise ValueError("FleetView needs at least one leader")
        try:
            for spec in specs:
                self._leaders[spec.name] = _LeaderState(
                    spec, client_factory(spec.host, spec.port))
        except Exception:
            self.stop()  # a factory failure stops the clients it started
            raise

    @staticmethod
    def _default_client(host: str, port: int):
        from sentinel_tpu.cluster.client import ClusterTokenClient

        return ClusterTokenClient(host, port, namespace="fleet").start()

    def wait_connected(self, timeout_s: float = 5.0) -> bool:
        """Best-effort wait until every leader socket is up (drills).
        Event-wait only — no clock read (the bound is poll-counted)."""
        ev = threading.Event()
        for _ in range(max(1, int(timeout_s / 0.05))):
            if all(ls.client.is_connected()
                   for ls in self._leaders.values()):
                return True
            ev.wait(0.05)
        return all(ls.client.is_connected() for ls in self._leaders.values())

    # -- polling -----------------------------------------------------------

    def poll(self) -> Dict[str, int]:
        """One scrape cycle: pull every leader's unserved complete
        seconds (bounded pages per leader). Returns seconds ingested
        per leader name."""
        out: Dict[str, int] = {}
        for name, ls in list(self._leaders.items()):
            out[name] = self._poll_leader(ls)
        self.poll_count += 1
        return out

    def _poll_leader(self, ls: _LeaderState) -> int:
        if ls.unsupported:
            return 0
        ingested = 0
        for _ in range(MAX_PAGES_PER_POLL):
            payload = ls.client.request_fleet_telemetry(
                since_ms=ls.cursor_ms, max_seconds=self.max_seconds)
            ls.polls += 1
            if payload is None:
                ls.errors += 1
                self.poll_errors += 1
                return ingested
            if payload.get("unsupported"):
                # A stock (pre-fleet) server answered BAD_REQUEST: stop
                # asking — the leader row reports it instead of erroring
                # forever.
                ls.unsupported = True
                return ingested
            ingested += self._ingest(ls, payload)
            if payload.get("moreAfterMs") is None:
                break
        return ingested

    def poll_population(self) -> Dict[str, bool]:
        """One population scrape (ISSUE 19): pull every leader's current
        telescope page and store it VERBATIM (merging happens at read
        time from unmodified pages — the bit-exactness stance the
        telemetry cells already take). Returns per-leader success."""
        out: Dict[str, bool] = {}
        for name, ls in list(self._leaders.items()):
            if ls.population_unsupported:
                out[name] = False
                continue
            page = ls.client.request_population_page()
            ls.population_polls += 1
            if page is None:
                ls.population_errors += 1
                out[name] = False
                continue
            if page.get("unsupported"):
                ls.population_unsupported = True
                out[name] = False
                continue
            with self._lock:
                ls.population = page
                ls.population_at_ms = self._clock()
            out[name] = True
        return out

    def fleet_population(self, slot_budget: Optional[int] = None,
                         budgets: Optional[List[int]] = None) -> Dict:
        """The fleet-wide telescope: per-leader page summaries plus the
        EXACT merge of every stored page (CMS cell-wise add, HLL
        register max, Space-Saving union with summed floors — see
        docs/SEMANTICS.md). ``slot_budget`` adds an admission-readiness
        report over the merged page; ``budgets`` adds the projection
        curve the dashboard charts."""
        from sentinel_tpu.telemetry import population as pop

        with self._lock:
            pages = []
            leaders: Dict[str, Dict] = {}
            for name, ls in self._leaders.items():
                row: Dict = {
                    "polls": ls.population_polls,
                    "errors": ls.population_errors,
                    "unsupported": ls.population_unsupported,
                    "atMs": ls.population_at_ms,
                }
                if ls.population:
                    pages.append(ls.population)
                    row.update(pop.page_summary(ls.population))
                leaders[name] = row
        merged = pop.merge_pages(pages) if pages else {}
        win_s = max(1, int(merged.get("geom", {}).get("windowMs", 1000))
                    // 1000) if merged else 1
        out: Dict = {
            "leaders": leaders,
            "pagesMerged": len(pages),
            "merged": merged,
            "summary": pop.page_summary(merged) if merged else {},
        }
        if merged and slot_budget is not None:
            out["report"] = pop.report_from_page(merged, slot_budget, win_s)
        if merged and budgets:
            out["curve"] = pop.projection_curve(merged, budgets, win_s)
        return out

    def _ingest(self, ls: _LeaderState, payload: Dict) -> int:
        name = ls.spec.name
        now = int(self._clock())
        with self._lock:
            ls.last_ok_ms = now
            ls.remote_name = payload.get("leader")
            ls.epoch = int(payload.get("epoch") or 0)
            # Leader-restart blind spot (ISSUE 16 satellite): a leader
            # that restarted with a stale epoch looks exactly like an
            # idle-but-alive one on the series alone. Track the
            # high-water epoch so status() can say which it is; the
            # flag clears once the leader re-earns (or re-learns) an
            # epoch at least as new as any it ever reported.
            if ls.epoch < ls.max_epoch:
                ls.epoch_regressed = True
            else:
                ls.max_epoch = ls.epoch
                ls.epoch_regressed = False
            ls.health = payload.get("health")
            ls.shard = payload.get("shard")
            # Signed skew: positive = the leader's clock runs ahead of
            # the collector's (one-way latency rides inside it; the
            # bound is what matters for settling seconds, not the sign).
            ls.skew_ms = int(payload.get("nowMs", now)) - now
            last = payload.get("lastStampMs")
            if isinstance(last, int) and last > ls.last_stamp_ms:
                ls.last_stamp_ms = last
            skipped = payload.get("skippedSecondMs")
            if skipped is not None and int(skipped) > ls.cursor_ms:
                # The leader could not frame this second (too fat for
                # the wire page): advance past it LOUDLY rather than
                # stall the cursor on it forever.
                ls.cursor_ms = int(skipped)
                ls.seconds_skipped += 1
            n = 0
            for sec in payload.get("seconds") or ():
                stamp = int(sec["timestamp"])
                if stamp <= ls.cursor_ms:
                    continue  # replay: first ingest wins
                ls.cursor_ms = stamp
                if stamp > ls.last_stamp_ms:
                    ls.last_stamp_ms = stamp
                cell_map = self._store.setdefault(stamp, {})
                for res, cell in (sec.get("resources") or {}).items():
                    cell_map.setdefault(res, {})[name] = cell
                ls.seconds_ingested += 1
                n += 1
            # Sort BEFORE evicting: stamp order across leaders is not
            # insertion order, and a straggler older than the store's
            # front must be the one evicted — popping first under the
            # stale order would drop an in-window second and keep the
            # out-of-window straggler.
            if n:
                self._store = OrderedDict(sorted(self._store.items()))
            while len(self._store) > self.history_seconds:
                self._store.popitem(last=False)
        return n

    # -- read surfaces -----------------------------------------------------

    @staticmethod
    def _sum_cells(cells: Dict[str, Dict]) -> Dict:
        """The exact fleet cell: arithmetic sum of the per-leader cells
        (ints summed, RT-bucket vectors summed element-wise, per-reason
        maps merged by sum) — nothing re-derived, nothing rounded."""
        fleet: Dict = {f: 0 for f in _SUM_FIELDS}
        fleet["blockByReason"] = {}
        fleet["rtBuckets"] = []
        for cell in cells.values():
            for f in _SUM_FIELDS:
                fleet[f] += int(cell.get(f, 0))
            for reason, v in (cell.get("blockByReason") or {}).items():
                fleet["blockByReason"][reason] = \
                    fleet["blockByReason"].get(reason, 0) + int(v)
            buckets = cell.get("rtBuckets") or []
            if len(buckets) > len(fleet["rtBuckets"]):
                fleet["rtBuckets"].extend(
                    [0] * (len(buckets) - len(fleet["rtBuckets"])))
            for i, v in enumerate(buckets):
                fleet["rtBuckets"][i] += int(v)
        return fleet

    def series(self, resource: Optional[str] = None,
               limit: Optional[int] = None,
               since_ms: Optional[int] = None) -> List[Dict]:
        """The federated per-second series, chronological: each second
        carries the exact fleet sum AND the per-leader split per
        resource (keyed by (resource, leader); slice ownership rides
        ``status()``'s per-leader block)."""
        with self._lock:
            items = [(t, {res: dict(leaders)
                          for res, leaders in cell_map.items()})
                     for t, cell_map in self._store.items()]
        if since_ms is not None:
            items = [it for it in items if it[0] > since_ms]
        if limit is not None and limit >= 0:
            items = items[-limit:] if limit > 0 else []
        out = []
        for stamp, cell_map in items:
            resources = {}
            for res, leaders in cell_map.items():
                if resource is not None and res != resource:
                    continue
                resources[res] = {"fleet": self._sum_cells(leaders),
                                  "leaders": leaders}
            if resource is not None and not resources:
                continue
            out.append({"timestamp": stamp, "resources": resources})
        return out

    def slice_loads(self, flow_of, n_slices: int,
                    window_seconds: Optional[int] = None,
                    settled_only: bool = True) -> Dict:
        """Fold the federated series to SLICE granularity (ISSUE 16):
        per-slice offered load (pass + block) over the newest
        ``window_seconds`` settled seconds, attributed through
        ``flow_of(resource) -> flowId`` and the one ``slice_of``
        implementation — no second hash. Resources without a flowId
        (local-only rules) are counted in ``unattributed`` rather than
        silently dropped, so a skew computed from this fold can always
        be audited against the raw series. ``observedByLeader`` is the
        load each leader actually SERVED over the window (historical
        routing, not current ownership — the rebalancer recomputes
        leader loads from slice loads x the current map)."""
        from sentinel_tpu.cluster.sharding import slice_of

        n = int(n_slices)
        horizon = self.settled_through_ms() if settled_only else None
        secs = self.series()
        if horizon is not None and horizon >= 0:
            secs = [s for s in secs if s["timestamp"] <= horizon]
        if window_seconds is not None and window_seconds > 0:
            secs = secs[-int(window_seconds):]
        slices: Dict[int, int] = {}
        by_leader: Dict[str, int] = {}
        unattributed = 0
        for sec in secs:
            for res, cell in sec["resources"].items():
                fid = flow_of(res)
                sl = slice_of(int(fid), n) if fid is not None else None
                for mid, c in (cell.get("leaders") or {}).items():
                    load = int(c.get("pass", 0)) + int(c.get("block", 0))
                    by_leader[mid] = by_leader.get(mid, 0) + load
                    if sl is None:
                        unattributed += load
                    else:
                        slices[sl] = slices.get(sl, 0) + load
        return {
            "nSlices": n,
            "seconds": len(secs),
            "settledThroughMs": horizon if horizon is not None else -1,
            "slices": slices,
            "observedByLeader": by_leader,
            "unattributed": unattributed,
        }

    def _stale(self, ls: _LeaderState, now: int) -> bool:
        """Stale = out of CONTACT (no successful payload inside the
        bound) — an idle-but-alive leader answers every poll with zero
        new seconds and is NOT stale; a dead/partitioned one is. Data
        age rides beside it as ``stalenessMs``."""
        return ls.last_ok_ms < 0 or now - ls.last_ok_ms > self.stale_ms

    def settled_through_ms(self) -> int:
        """Newest stamp every non-stale leader's cursor has passed:
        fleet sums at or before it can no longer change (complete-
        seconds-only + per-leader monotone cursors). Stale leaders
        don't hold the frontier back — their staleness is reported
        instead (the blast-radius stance: a dead leader degrades ITS
        slices, not the whole fleet's visibility)."""
        now = int(self._clock())
        live = [ls.cursor_ms for ls in self._leaders.values()
                if not self._stale(ls, now)]
        return min(live) if live else -1

    def fleet_health(self) -> Optional[int]:
        """Composition of the PR 7 instance healths: the fleet is as
        healthy as its least healthy reporting leader."""
        scores = [int(ls.health["instance"])
                  for ls in self._leaders.values()
                  if ls.health and "instance" in ls.health]
        return min(scores) if scores else None

    def status(self) -> Dict:
        now = int(self._clock())
        with self._lock:
            leaders = {}
            for name, ls in self._leaders.items():
                leaders[name] = {
                    "host": ls.spec.host,
                    "port": ls.spec.port,
                    "connected": ls.client.is_connected(),
                    "remoteName": ls.remote_name,
                    "cursorMs": ls.cursor_ms,
                    "lastStampMs": ls.last_stamp_ms,
                    "stalenessMs": (now - ls.last_stamp_ms
                                    if ls.last_stamp_ms >= 0 else None),
                    "lastContactMs": ls.last_ok_ms,
                    # Age of last CONTACT (successful payload), not of
                    # data: "idle but alive" has a small contactAgeMs
                    # and an old lastStampMs; "dead" has both old.
                    "contactAgeMs": (now - ls.last_ok_ms
                                     if ls.last_ok_ms >= 0 else None),
                    "stale": self._stale(ls, now),
                    "skewMs": ls.skew_ms,
                    "polls": ls.polls,
                    "errors": ls.errors,
                    "unsupported": ls.unsupported,
                    "secondsIngested": ls.seconds_ingested,
                    "secondsSkipped": ls.seconds_skipped,
                    "epoch": ls.epoch,
                    "maxEpochSeen": ls.max_epoch,
                    "epochRegressed": ls.epoch_regressed,
                    "health": ls.health,
                    "slicesOwned": (sorted(int(s) for s in
                                           (ls.shard or {}).get("slices", {}))
                                    if ls.shard else []),
                    "mapVersion": (ls.shard or {}).get("mapVersion"),
                }
            retained = len(self._store)
        stale = sum(1 for v in leaders.values() if v["stale"])
        return {
            "leaders": leaders,
            "leaderCount": len(leaders),
            "staleLeaders": stale,
            "fleetHealth": self.fleet_health(),
            "retainedSeconds": retained,
            "settledThroughMs": self.settled_through_ms(),
            "staleAfterMs": self.stale_ms,
            "polls": self.poll_count,
            "pollErrors": self.poll_errors,
        }

    def stop(self) -> None:
        for ls in self._leaders.values():
            try:
                ls.client.stop()
            except Exception:  # noqa: BLE001 — teardown is best-effort
                pass

"""Nacos long-poll + Consul blocking-query connector tests (SURVEY.md
§2.2: ``sentinel-datasource-nacos`` / ``sentinel-datasource-consul``):
real wire protocols over real sockets — initial load, pushed updates via
the watch mechanism, writable publish, reconnect across a server
restart, and bad-payload resilience.
"""

import json
import time
import urllib.request

import pytest

import sentinel_tpu as st
from sentinel_tpu.datasource import bind
from sentinel_tpu.datasource.consul import (
    ConsulDataSource,
    ConsulWritableDataSource,
    MiniConsulServer,
    _parse_wait,
)
from sentinel_tpu.datasource.converters import (
    flow_rules_from_json,
    flow_rules_to_json,
)
from sentinel_tpu.datasource.nacos import (
    MiniNacosServer,
    NacosDataSource,
    NacosWritableDataSource,
    _md5_hex,
)


def _wait_for(pred, timeout_s: float = 5.0) -> bool:
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return pred()


def _rules_json(*resources, count=5.0) -> str:
    return json.dumps([{"resource": r, "count": count} for r in resources])


def _resources(prop):
    return {r.resource for r in (prop.value or [])}


# -- Nacos --------------------------------------------------------------------


@pytest.fixture()
def nacos():
    s = MiniNacosServer(max_hold_ms=400).start()
    yield s
    s.stop()


def _nacos_source(server, **kw) -> NacosDataSource:
    kw.setdefault("poll_timeout_ms", 300)
    kw.setdefault("reconnect_backoff_ms", (20, 100))
    return NacosDataSource(server.addr, "sentinel-flow", "DEFAULT_GROUP",
                           flow_rules_from_json, **kw)


def test_nacos_initial_load_and_push(nacos):
    nacos.publish("sentinel-flow", "DEFAULT_GROUP", _rules_json("api:a"))
    src = _nacos_source(nacos).start()
    try:
        assert _wait_for(lambda: _resources(src.property) == {"api:a"})
        # A publish lands through the long-poll listener, no restart.
        nacos.publish("sentinel-flow", "DEFAULT_GROUP",
                      _rules_json("api:a", "api:b"))
        assert _wait_for(
            lambda: _resources(src.property) == {"api:a", "api:b"})
    finally:
        src.close()


def test_nacos_absent_config_then_first_publish(nacos):
    src = _nacos_source(nacos).start()
    try:
        assert src.property.value is None  # 404 → nothing pushed
        nacos.publish("sentinel-flow", "DEFAULT_GROUP", _rules_json("late"))
        assert _wait_for(lambda: _resources(src.property) == {"late"})
    finally:
        src.close()


def test_nacos_writable_publish_roundtrip(nacos):
    writer = NacosWritableDataSource(nacos.addr, "sentinel-flow",
                                     "DEFAULT_GROUP", flow_rules_to_json)
    src = _nacos_source(nacos).start()
    try:
        writer.write([st.FlowRule(resource="via-writer", count=9.0)])
        assert _wait_for(lambda: _resources(src.property) == {"via-writer"})
    finally:
        src.close()


def test_nacos_bad_payload_keeps_last_good_without_spinning(nacos):
    nacos.publish("sentinel-flow", "DEFAULT_GROUP", _rules_json("good"))
    src = _nacos_source(nacos).start()
    try:
        assert _wait_for(lambda: _resources(src.property) == {"good"})
        nacos.publish("sentinel-flow", "DEFAULT_GROUP", "{not json]")
        # Receipt advances the listener md5 even though conversion failed,
        # so the long-poll PARKS again instead of busy-looping drift.
        assert _wait_for(lambda: src._md5 == _md5_hex("{not json]"))
        rounds_after_bad = nacos.poll_rounds
        time.sleep(0.7)
        assert _resources(src.property) == {"good"}
        # 0.7s / 300ms poll timeout ≈ 2-3 parked rounds; a busy loop would
        # rack up hundreds.
        assert nacos.poll_rounds - rounds_after_bad <= 6
        # And a later good payload still lands.
        nacos.publish("sentinel-flow", "DEFAULT_GROUP",
                      _rules_json("recovered"))
        assert _wait_for(lambda: _resources(src.property) == {"recovered"})
    finally:
        src.close()


def test_nacos_deleted_config_keeps_rules_without_spinning(nacos):
    nacos.publish("sentinel-flow", "DEFAULT_GROUP", _rules_json("good"))
    src = _nacos_source(nacos).start()
    try:
        assert _wait_for(lambda: _resources(src.property) == {"good"})
        nacos.delete("sentinel-flow", "DEFAULT_GROUP")
        # Deletion is recorded as md5 "" so the long-poll parks again.
        assert _wait_for(lambda: src._md5 == "")
        rounds_after_delete = nacos.poll_rounds
        time.sleep(0.7)
        assert _resources(src.property) == {"good"}  # last good kept
        assert nacos.poll_rounds - rounds_after_delete <= 6  # no busy loop
        nacos.publish("sentinel-flow", "DEFAULT_GROUP",
                      _rules_json("republished"))
        assert _wait_for(
            lambda: _resources(src.property) == {"republished"})
    finally:
        src.close()


def test_normalize_base_schemes():
    from sentinel_tpu.datasource._mini_http import normalize_base

    assert normalize_base("1.2.3.4:8848") == "http://1.2.3.4:8848"
    assert normalize_base("http://h:1/") == "http://h:1"
    assert normalize_base("https://h:1") == "https://h:1"
    # A bare hostname merely STARTING with "http" still gets a scheme.
    assert normalize_base("httpd-gw:8848") == "http://httpd-gw:8848"


def test_nacos_reconnect_after_server_restart(nacos):
    nacos.publish("sentinel-flow", "DEFAULT_GROUP", _rules_json("v1"))
    src = _nacos_source(nacos).start()
    try:
        assert _wait_for(lambda: _resources(src.property) == {"v1"})
        nacos.stop()
        assert _wait_for(lambda: src.reconnect_count > 0)
        # Publish while the connector is down, then restart on the SAME
        # port: the md5 drift is caught on the first listener round.
        nacos.publish("sentinel-flow", "DEFAULT_GROUP", _rules_json("v2"))
        nacos.start()
        assert _wait_for(lambda: _resources(src.property) == {"v2"})
    finally:
        src.close()


def test_nacos_tenant_isolation(nacos):
    nacos.publish("sentinel-flow", "DEFAULT_GROUP", _rules_json("t-a"),
                  tenant="a")
    src_a = _nacos_source(nacos, tenant="a").start()
    src_default = _nacos_source(nacos).start()
    try:
        assert _wait_for(lambda: _resources(src_a.property) == {"t-a"})
        assert src_default.property.value is None
    finally:
        src_a.close()
        src_default.close()


def test_nacos_bind_to_engine(nacos):
    eng = st.reset(capacity=64)
    try:
        src = _nacos_source(nacos).start()
        bind(src, st.load_flow_rules)
        nacos.publish("sentinel-flow", "DEFAULT_GROUP",
                      _rules_json("bound", count=0.0))
        try:
            def blocked():
                try:
                    with st.entry("bound"):
                        pass
                    return False
                except st.BlockException:
                    return True

            # Generous bound: the fresh engine's first entry() compiles
            # (tens of seconds on a contended 1-core box); _wait_for
            # returns the moment the push is enforced.
            assert _wait_for(blocked, timeout_s=90.0)
        finally:
            src.close()
    finally:
        eng.close()


# -- Consul -------------------------------------------------------------------


@pytest.fixture()
def consul():
    s = MiniConsulServer(max_hold_ms=400).start()
    yield s
    s.stop()


def _consul_source(server, **kw) -> ConsulDataSource:
    kw.setdefault("wait", "300ms")
    kw.setdefault("reconnect_backoff_ms", (20, 100))
    return ConsulDataSource(server.addr, "config/sentinel/flow-rules",
                            flow_rules_from_json, **kw)


def test_parse_wait_durations():
    assert _parse_wait("10s") == 10.0
    assert _parse_wait("1m") == 60.0
    assert _parse_wait("250ms") == 0.25
    assert _parse_wait("5") == 5.0
    with pytest.raises(ValueError):
        _parse_wait("soon")


def test_consul_bad_wait_fails_at_construction():
    # Must raise HERE — inside the watch loop it would be swallowed as an
    # endless silent reconnect.
    with pytest.raises(ValueError):
        ConsulDataSource("127.0.0.1:1", "k", flow_rules_from_json,
                         wait="5 minutes")


def test_consul_initial_load_and_watch(consul):
    consul.put("config/sentinel/flow-rules", _rules_json("api:a"))
    src = _consul_source(consul).start()
    try:
        assert _wait_for(lambda: _resources(src.property) == {"api:a"})
        consul.put("config/sentinel/flow-rules",
                   _rules_json("api:a", "api:b"))
        assert _wait_for(
            lambda: _resources(src.property) == {"api:a", "api:b"})
    finally:
        src.close()


def test_consul_absent_key_then_first_put(consul):
    src = _consul_source(consul).start()
    try:
        assert src.property.value is None
        consul.put("config/sentinel/flow-rules", _rules_json("late"))
        assert _wait_for(lambda: _resources(src.property) == {"late"})
    finally:
        src.close()


def test_consul_writable_put_roundtrip(consul):
    writer = ConsulWritableDataSource(consul.addr,
                                      "config/sentinel/flow-rules",
                                      flow_rules_to_json)
    src = _consul_source(consul).start()
    try:
        writer.write([st.FlowRule(resource="via-writer", count=9.0)])
        assert _wait_for(lambda: _resources(src.property) == {"via-writer"})
    finally:
        src.close()


def test_consul_bad_payload_keeps_last_good(consul):
    consul.put("config/sentinel/flow-rules", _rules_json("good"))
    src = _consul_source(consul).start()
    try:
        assert _wait_for(lambda: _resources(src.property) == {"good"})
        consul.put("config/sentinel/flow-rules", "{not json]")
        time.sleep(0.3)
        assert _resources(src.property) == {"good"}
        consul.put("config/sentinel/flow-rules", _rules_json("recovered"))
        assert _wait_for(lambda: _resources(src.property) == {"recovered"})
    finally:
        src.close()


def test_consul_reconnect_after_server_restart(consul):
    consul.put("config/sentinel/flow-rules", _rules_json("v1"))
    src = _consul_source(consul).start()
    try:
        assert _wait_for(lambda: _resources(src.property) == {"v1"})
        consul.stop()
        assert _wait_for(lambda: src.reconnect_count > 0)
        # State-based recovery: whatever was put while down is simply the
        # current state after reconnect.
        consul.put("config/sentinel/flow-rules", _rules_json("v2"))
        consul.start()
        assert _wait_for(lambda: _resources(src.property) == {"v2"})
    finally:
        src.close()


def test_consul_blocking_query_parks_when_idle(consul):
    """An idle blocking query must PARK (no busy spin): with a 300ms wait
    and no writes, a handful of rounds should elapse per second, not
    hundreds."""
    consul.put("config/sentinel/flow-rules", _rules_json("idle"))
    src = _consul_source(consul).start()
    try:
        assert _wait_for(lambda: _resources(src.property) == {"idle"})
        before_idx = consul._index
        before_rounds = consul.poll_rounds
        time.sleep(0.7)
        assert consul._index == before_idx  # no phantom writes
        assert src.reconnect_count == 0  # idle != error
        # 0.7s / 300ms wait ≈ 2-3 parked rounds; a busy-spinning watch
        # would rack up hundreds.
        assert consul.poll_rounds - before_rounds <= 6
    finally:
        src.close()


def test_consul_index_reset_restarts_watch(consul):
    consul.put("config/sentinel/flow-rules", _rules_json("v1"))
    src = _consul_source(consul).start()
    try:
        assert _wait_for(lambda: _resources(src.property) == {"v1"})
        # Simulate a leader change resetting the index space backwards.
        with consul._cond:
            consul._index = 0
            consul._kv["config/sentinel/flow-rules"] = (
                _rules_json("reset").encode("utf-8"), 1)
            consul._cond.notify_all()
        assert _wait_for(lambda: _resources(src.property) == {"reset"})
    finally:
        src.close()


def test_consul_raw_http_shape(consul):
    """The fake speaks recognizable Consul: base64 values + index header."""
    consul.put("k", "hello")
    with urllib.request.urlopen(f"{consul.addr}/v1/kv/k") as resp:
        assert resp.headers["X-Consul-Index"] == "1"
        (entry,) = json.loads(resp.read())
    assert entry["Key"] == "k"
    import base64

    assert base64.b64decode(entry["Value"]) == b"hello"

package com.alibaba.csp.sentinel.tpu;

import com.sun.jna.Library;
import com.sun.jna.Native;
import com.sun.jna.Pointer;
import com.sun.jna.Structure;
import com.sun.jna.ptr.IntByReference;
import com.sun.jna.ptr.LongByReference;

import java.util.Arrays;
import java.util.List;

/**
 * JNA binding to {@code libsentinel_shim.so} (C ABI declared in
 * {@code native/sentinel_shim.h}) — the bridge by which a JVM running the
 * reference slot chain acquires cluster tokens from the sentinel-tpu
 * backend (SURVEY.md §7 M4).
 *
 * <p>The shim speaks the same length-framed TLV protocol as the Python
 * {@code cluster/codec.py}: PING namespace registration on connect, FLOW
 * and PARAM_FLOW acquires, batched FLOW acquires, and the M4 remote
 * slot-chain bridge (MSG_ENTRY/MSG_EXIT). Handles are multi-in-flight:
 * N threads may issue requests on ONE handle concurrently — responses
 * demux by xid inside the shim (the Netty client's xid->promise map,
 * natively). Only {@code st_client_close} must not race new requests.
 *
 * <p>Build: see {@code native/java/BUILD.md}. No JNI glue is required —
 * JNA maps these declarations straight onto the C ABI, so the same
 * header also serves hand-written JNI if a zero-dependency build is
 * preferred.
 */
public interface SentinelTpuShim extends Library {

    SentinelTpuShim INSTANCE = Native.load("sentinel_shim", SentinelTpuShim.class);

    /** Mirror of {@code st_param} in sentinel_shim.h (tag selects field:
     * 0=int {@code i}, 1=string {@code s}, 2=bool {@code i},
     * 3=double {@code d}). */
    @Structure.FieldOrder({"tag", "i", "d", "s"})
    class StParam extends Structure {
        public byte tag;
        public long i;
        public double d;
        public String s;

        @Override
        protected List<String> getFieldOrder() {
            return Arrays.asList("tag", "i", "d", "s");
        }
    }

    Pointer st_client_connect(String host, int port, String ns, int timeoutMs);

    int st_request_token(Pointer handle, long flowId, int count,
                         int prioritized, IntByReference outExtra);

    int st_request_param_token(Pointer handle, long flowId, int count,
                               StParam[] params, int nparams);

    int st_request_tokens_batch(Pointer handle, long[] flowIds, int[] counts,
                                int[] prioritized, int n, int[] outStatuses,
                                int[] outExtras);

    int st_remote_entry(Pointer handle, String resource, String origin,
                        int count, int entryType, int prioritized,
                        StParam[] params, int nparams,
                        LongByReference outEntryId, IntByReference outReason);

    int st_remote_exit(Pointer handle, long entryId, int error, int count);

    void st_client_close(Pointer handle);

    void st_time_start();

    void st_time_stop();

    long st_now_ms();
}

"""Self-driving shard placement (ISSUE 16): a governed, chaos-certified
rebalancer with leader join/leave autoscaling.

The :class:`ShardRebalancer` closes the loop the fleet plane (PR 15)
opened: it **senses** per-slice load and per-leader health from
``FleetView`` folds, **proposes** minimal-movement :class:`ShardMap`
diffs under a hard safety envelope, optionally **certifies** the diff by
replaying the handoff as an in-process chaos-mesh episode under a seeded
fault schedule, and **applies** through the existing journal-audited HA
path — every stage an ``acting("rebalancer")`` journal record chained
``rebalancePropose -> rebalanceCertify -> rebalanceApply ->
shardMapApply -> haRoleFlip`` via causeSeq.

Safety envelope (all veto paths counted and journalled):

- at most ``csp.sentinel.rebalance.max.slices.per.epoch`` slices move
  per applied plan;
- per-slice cooldown + direction-flip hysteresis via the shared
  :class:`~sentinel_tpu.adaptive.envelope.CooldownLedger` (stamped at
  APPLY, not propose — an unapplied plan pins nothing);
- a slice whose owner is degraded or mid-handoff never moves;
- :class:`~sentinel_tpu.adaptive.envelope.RebalanceFreezeGate`
  precedence ``manual > stale-telemetry > degraded-leader >
  abort-backoff`` gates propose AND apply (fold-out plans evaluate with
  an empty degraded set: the sick leader is the REASON to move);
- the last-known-good map is retained for one-command rollback.

Certification replays a SYNTHETIC mesh — same topology, renumbered
epochs, loopback seats — not the live fleet; SEMANTICS.md "Movement
bound & slice conservation" names the asymmetry.  The episode is a pure
function of ``(campaign_seed, plan)``: its verdict/fault sha256 oracles
replay bit-identically, and a plan that violates ANY invariant
(including the ISSUE 16 ``slice_conservation`` checker) is vetoed and
backs the rebalancer off.

This module never mutates shard state directly: the ONLY actuation is
``ha.apply_map(...)`` (test_lint pins this), and it reads no wall
clock — time comes from the injected clock or the engine timebase.
"""

from __future__ import annotations

import shutil
import tempfile
import threading
from typing import Callable, Dict, List, Optional

from sentinel_tpu.adaptive.envelope import (
    CooldownLedger,
    RebalanceFreezeGate,
)
from sentinel_tpu.cluster.sharding import ShardMap, slice_of
from sentinel_tpu.core.config import config
from sentinel_tpu.telemetry.journal import acting, causing

VETO_DEADBAND = "deadband"
VETO_FROZEN = "frozen"
VETO_COOLDOWN = "cooldown"
VETO_DEGRADED = "degraded-owner"
VETO_HANDOFF = "mid-handoff"
VETO_CERTIFY = "certification"
VETO_NO_MAP = "no-map"
VETO_NO_SIGNAL = "no-signal"


def _sha(lines) -> str:
    import hashlib

    h = hashlib.sha256()
    for line in lines:
        h.update(line.encode("utf-8"))
        h.update(b"\n")
    return h.hexdigest()


class RebalancePlan:
    """One proposed map diff moving through propose -> certify -> apply.

    ``moves`` is {slice: (from_mid, to_mid)}; ``proposed`` is the full
    successor :class:`ShardMap` (minimal movement: only moved slices
    change owner, only moved slices' epochs bump)."""

    __slots__ = ("plan_id", "reason", "created_ms", "base_version",
                 "moves", "proposed", "skew_before", "skew_after",
                 "vetoed_slices", "propose_seq", "certify_seq",
                 "apply_seq", "certified", "cert", "applied_ms")

    def __init__(self, plan_id: int, reason: str, created_ms: int,
                 base_version: int, moves: Dict[int, tuple],
                 proposed: ShardMap, skew_before: float, skew_after: float,
                 vetoed_slices: Dict[int, str], propose_seq: Optional[int]):
        self.plan_id = int(plan_id)
        self.reason = str(reason)
        self.created_ms = int(created_ms)
        self.base_version = int(base_version)
        self.moves = dict(moves)
        self.proposed = proposed
        self.skew_before = float(skew_before)
        self.skew_after = float(skew_after)
        self.vetoed_slices = dict(vetoed_slices)
        self.propose_seq = propose_seq
        self.certify_seq: Optional[int] = None
        self.apply_seq: Optional[int] = None
        self.certified: Optional[bool] = None  # None = not yet run
        self.cert: Optional[dict] = None
        self.applied_ms: Optional[int] = None

    def to_dict(self) -> dict:
        return {
            "planId": self.plan_id, "reason": self.reason,
            "createdMs": self.created_ms, "baseVersion": self.base_version,
            "proposedVersion": int(self.proposed.version),
            "moves": {str(sl): {"from": frm, "to": to}
                      for sl, (frm, to) in sorted(self.moves.items())},
            "skewBefore": self.skew_before, "skewAfter": self.skew_after,
            "vetoedSlices": {str(sl): why for sl, why
                             in sorted(self.vetoed_slices.items())},
            "proposeSeq": self.propose_seq,
            "certifySeq": self.certify_seq, "applySeq": self.apply_seq,
            "certified": self.certified,
            "cert": ({k: self.cert[k] for k in
                      ("ok", "verdictSha256", "faultSha256", "seed",
                       "seconds", "violations", "transfers",
                       "handoffMarginGrants")}
                     if self.cert else None),
            "appliedMs": self.applied_ms,
        }


class ShardRebalancer:
    """The governed control loop over shard placement.

    Every collaborator is injectable for drills; defaults resolve from
    the engine AT CALL TIME (the HA seat and fleet poller both have
    lifecycles of their own)."""

    MAX_PLANS = 8  # bounded plan history (newest kept)

    def __init__(self, engine=None, ha=None, fleet=None, journal=None,
                 flow_of: Optional[Callable] = None,
                 clock: Optional[Callable[[], int]] = None,
                 apply_via: Optional[Callable] = None):
        self.engine = engine
        self._ha_override = ha
        self._fleet_override = fleet
        self._journal_override = journal
        self._flow_of_override = flow_of
        self._clock = clock
        self._apply_via = apply_via
        self._lock = threading.Lock()
        self.ledger = CooldownLedger(config.rebalance_cooldown_ms())
        self.gate = RebalanceFreezeGate(config.rebalance_stale_ms())
        self.manual_frozen = False
        self.backoff_until_ms = 0
        self.last_known_good: Optional[ShardMap] = None
        self.last_skew: float = 0.0
        self.plans: Dict[int, RebalancePlan] = {}
        self._next_plan = 1
        # Exporter counters (monotonic; gauges derived in metrics_state).
        self.plans_total = 0
        self.applies_total = 0
        self.rollbacks_total = 0
        self.vetoes_total = 0
        self.slices_moved_total = 0

    # -- collaborators (resolved at call time) -----------------------------

    def _now(self) -> int:
        if self._clock is not None:
            return int(self._clock())
        if self.engine is not None:
            return int(self.engine.now_ms())
        return 0

    def _ha(self):
        if self._ha_override is not None:
            return self._ha_override
        cluster = getattr(self.engine, "cluster", None)
        return getattr(cluster, "ha", None)

    def _fleet(self):
        if self._fleet_override is not None:
            return self._fleet_override
        return getattr(self.engine, "fleet", None)

    def _journal(self):
        if self._journal_override is not None:
            return self._journal_override
        return getattr(self.engine, "journal", None)

    def _record(self, kind: str, cause_seq=None, **fields) -> Optional[int]:
        j = self._journal()
        if j is None:
            return None
        with acting("rebalancer"):
            return j.record(kind, cause_seq=cause_seq, **fields)

    def _flow_of(self) -> Callable:
        """resource -> flowId attribution: injected, else folded from the
        engine's cluster-mode flow rules (the same ``cluster_config``
        flowIds the wire carries)."""
        if self._flow_of_override is not None:
            return self._flow_of_override
        table: Dict[str, int] = {}
        if self.engine is not None:
            for r in self.engine.flow_rules.get_rules():
                if getattr(r, "cluster_mode", False) and r.cluster_config:
                    fid = r.cluster_config.get("flowId")
                    if fid is not None:
                        table[r.resource] = int(fid)
        return table.get

    def current_map(self) -> Optional[ShardMap]:
        ha = self._ha()
        return getattr(ha, "shard_map", None)

    # -- sense -------------------------------------------------------------

    def degraded_leaders(self) -> List[str]:
        """Leaders the fleet plane marks unhealthy: stale (no contact
        inside the bound) or fencing-epoch regression (a resurrected
        stale seat)."""
        fleet = self._fleet()
        if fleet is None:
            return []
        out = []
        for mid, row in fleet.status().get("leaders", {}).items():
            if row.get("stale") or row.get("epochRegressed"):
                out.append(mid)
        return sorted(out)

    def sense(self, window_seconds: Optional[int] = None) -> dict:
        """Fold fleet telemetry to slice granularity and project it
        onto CURRENT ownership: per-leader load is slice loads x the
        map in force, NOT the historical serving leader."""
        smap = self.current_map()
        fleet = self._fleet()
        if smap is None or fleet is None:
            return {"ok": False,
                    "reason": VETO_NO_MAP if smap is None else VETO_NO_SIGNAL}
        win = int(window_seconds if window_seconds is not None
                  else config.rebalance_window_seconds())
        fold = fleet.slice_loads(self._flow_of(), smap.n_slices,
                                 window_seconds=win)
        by_leader: Dict[str, int] = {s.machine_id: 0 for s in smap.servers}
        for sl, load in fold["slices"].items():
            by_leader[smap.slice_owner[int(sl)]] = \
                by_leader.get(smap.slice_owner[int(sl)], 0) + int(load)
        loads = list(by_leader.values())
        mean = (sum(loads) / len(loads)) if loads else 0.0
        skew = ((max(loads) - min(loads)) / mean) if mean > 0 else 0.0
        self.last_skew = float(skew)
        return {
            "ok": True, "mapVersion": int(smap.version),
            "settledThroughMs": fold["settledThroughMs"],
            "seconds": fold["seconds"],
            "sliceLoads": {int(s): int(v)
                           for s, v in sorted(fold["slices"].items())},
            "leaderLoads": dict(sorted(by_leader.items())),
            "unattributed": fold["unattributed"],
            "meanLoad": mean, "skew": float(skew),
            "degraded": self.degraded_leaders(),
        }

    def _freeze(self, reason: str) -> dict:
        fleet = self._fleet()
        settled = fleet.settled_through_ms() if fleet is not None else -1
        degraded = () if reason == "leave" else tuple(self.degraded_leaders())
        st = self.gate.evaluate(
            self._now(), manual_frozen=self.manual_frozen,
            settled_through_ms=int(settled), degraded_leaders=degraded,
            backoff_until_ms=self.backoff_until_ms)
        return {"frozen": st.frozen, "reason": st.reason}

    # -- propose -----------------------------------------------------------

    def propose(self, reason: str = "skew",
                window_seconds: Optional[int] = None) -> dict:
        """Build a minimal-movement plan draining the hottest leader
        toward the coldest, greedy heaviest-slice-first, under the full
        safety envelope. Returns the plan dict or a veto dict."""
        with self._lock:
            return self._propose_locked(reason, window_seconds)

    def _propose_locked(self, reason: str, window_seconds) -> dict:
        now = self._now()
        frozen = self._freeze(reason)
        if frozen["frozen"]:
            self.vetoes_total += 1
            self._record("rebalanceVeto", reason=VETO_FROZEN,
                         frozenBy=frozen["reason"])
            return {"ok": False, "veto": VETO_FROZEN,
                    "frozenBy": frozen["reason"]}
        smap = self.current_map()
        if smap is None:
            self.vetoes_total += 1
            return {"ok": False, "veto": VETO_NO_MAP}
        sensed = self.sense(window_seconds)
        if not sensed.get("ok"):
            self.vetoes_total += 1
            return {"ok": False, "veto": sensed.get("reason", VETO_NO_SIGNAL)}
        deadband = config.rebalance_skew_deadband_pct()
        if sensed["skew"] <= deadband and reason == "skew":
            return {"ok": False, "veto": VETO_DEADBAND,
                    "skew": sensed["skew"], "deadband": deadband}
        moves, vetoed, skew_after = self._greedy_moves(
            smap, sensed, now, reason)
        if not moves:
            self.vetoes_total += 1
            self._record("rebalanceVeto", reason=VETO_DEADBAND,
                         detail="no admissible move improves skew",
                         vetoedSlices={str(k): v for k, v in vetoed.items()})
            return {"ok": False, "veto": VETO_DEADBAND,
                    "vetoedSlices": vetoed}
        proposed = smap.with_moves({sl: to for sl, (_f, to) in moves.items()})
        plan = self._commit_plan(reason, now, smap, moves, proposed,
                                 sensed["skew"], skew_after, vetoed)
        return {"ok": True, "plan": plan.to_dict()}

    def _greedy_moves(self, smap: ShardMap, sensed: dict, now: int,
                      reason: str):
        """Heaviest-slice-first from hottest to coldest leader, bounded
        by the movement cap and the per-slice envelope."""
        cap = config.rebalance_max_slices_per_epoch()
        slice_load = sensed["sliceLoads"]
        loads = dict(sensed["leaderLoads"])
        degraded = set(sensed["degraded"])
        ha = self._ha()
        mid_handoff = bool(ha is not None and hasattr(ha, "transition_pending")
                           and ha.transition_pending())
        moves: Dict[int, tuple] = {}
        vetoed: Dict[int, str] = {}
        for _ in range(cap):
            if len(loads) < 2:
                break
            hot = max(loads, key=lambda m: (loads[m], m))
            cold = min(loads, key=lambda m: (loads[m], m))
            if hot == cold or loads[hot] <= loads[cold]:
                break
            candidates = sorted(
                (sl for sl in smap.slices_of(hot) if sl not in moves),
                key=lambda sl: (-slice_load.get(sl, 0), sl))
            moved = False
            for sl in candidates:
                load = slice_load.get(sl, 0)
                if load <= 0:
                    # Candidates are load-sorted: everything from here
                    # on carries no traffic and cannot improve skew.
                    break
                # A move only helps while the donor stays at least as
                # loaded as the recipient becomes (else it overshoots
                # and the flip hysteresis would fight the next plan).
                if loads[hot] - load < loads[cold] + load:
                    continue
                if mid_handoff:
                    vetoed[sl] = VETO_HANDOFF
                    continue
                if hot in degraded and reason != "leave":
                    vetoed[sl] = VETO_DEGRADED
                    break
                paced = self.ledger.check(sl, cold, now)
                if paced is not None:
                    vetoed[sl] = paced  # "cooldown" | "hysteresis"
                    continue
                moves[sl] = (hot, cold)
                loads[hot] -= load
                loads[cold] += load
                moved = True
                break
            if not moved:
                break
        mean = sensed["meanLoad"]
        skew_after = ((max(loads.values()) - min(loads.values())) / mean
                      if mean > 0 and loads else 0.0)
        return moves, vetoed, skew_after

    def _commit_plan(self, reason, now, smap, moves, proposed,
                     skew_before, skew_after, vetoed) -> RebalancePlan:
        plan_id = self._next_plan
        self._next_plan += 1
        seq = self._record(
            "rebalancePropose", planId=plan_id, reason=reason,
            baseVersion=int(smap.version),
            proposedVersion=int(proposed.version),
            moves={str(sl): {"from": frm, "to": to}
                   for sl, (frm, to) in sorted(moves.items())},
            skewBefore=float(skew_before), skewAfter=float(skew_after),
            vetoedSlices={str(k): v for k, v in sorted(vetoed.items())})
        plan = RebalancePlan(plan_id, reason, now, smap.version, moves,
                             proposed, skew_before, skew_after, vetoed, seq)
        self.plans[plan_id] = plan
        while len(self.plans) > self.MAX_PLANS:
            victim = min(self.plans)
            if victim == plan_id:
                break
            del self.plans[victim]
        self.plans_total += 1
        return plan

    # -- autoscaling: leader join/leave ------------------------------------

    def plan_join(self, machine_id: str, host: str, port: int) -> dict:
        """Fold a NEW seat in: add it to the server set and move up to
        the movement cap of the heaviest slices onto it — the same
        certify -> apply pipeline as a skew plan."""
        from sentinel_tpu.cluster.ha import ClusterServerSpec

        with self._lock:
            now = self._now()
            frozen = self._freeze("join")
            if frozen["frozen"]:
                self.vetoes_total += 1
                self._record("rebalanceVeto", reason=VETO_FROZEN,
                             frozenBy=frozen["reason"], join=machine_id)
                return {"ok": False, "veto": VETO_FROZEN,
                        "frozenBy": frozen["reason"]}
            smap = self.current_map()
            if smap is None:
                self.vetoes_total += 1
                return {"ok": False, "veto": VETO_NO_MAP}
            if smap.server_for(machine_id) is not None:
                return {"ok": False, "veto": "already-member"}
            sensed = self.sense(None)
            slice_load = sensed.get("sliceLoads", {}) if sensed.get("ok") \
                else {}
            cap = config.rebalance_max_slices_per_epoch()
            degraded = set(self.degraded_leaders())
            donors = sorted(
                ((sl, smap.slice_owner[sl]) for sl in range(smap.n_slices)
                 if smap.slice_owner[sl] not in degraded),
                key=lambda p: (-slice_load.get(p[0], 0), p[0]))
            moves: Dict[int, tuple] = {}
            vetoed: Dict[int, str] = {}
            for sl, owner in donors:
                if len(moves) >= cap:
                    break
                paced = self.ledger.check(sl, machine_id, now)
                if paced is not None:
                    vetoed[sl] = paced
                    continue
                moves[sl] = (owner, machine_id)
            grown = smap._replace(
                servers=smap.servers
                + (ClusterServerSpec(machine_id, host, int(port)),))
            proposed = grown.with_moves(
                {sl: to for sl, (_f, to) in moves.items()})
            plan = self._commit_plan("join", now, smap, moves, proposed,
                                     sensed.get("skew", 0.0), 0.0, vetoed)
            return {"ok": True, "plan": plan.to_dict()}

    def plan_leave(self, machine_id: str) -> dict:
        """Fold a seat OUT: move up to the cap of its slices to the
        least-loaded survivors; the seat leaves the server set once it
        owns nothing. The freeze gate is evaluated WITHOUT the degraded
        set — the sick leader is the reason to move."""
        with self._lock:
            now = self._now()
            frozen = self._freeze("leave")
            if frozen["frozen"]:
                self.vetoes_total += 1
                self._record("rebalanceVeto", reason=VETO_FROZEN,
                             frozenBy=frozen["reason"], leave=machine_id)
                return {"ok": False, "veto": VETO_FROZEN,
                        "frozenBy": frozen["reason"]}
            smap = self.current_map()
            if smap is None:
                self.vetoes_total += 1
                return {"ok": False, "veto": VETO_NO_MAP}
            if smap.server_for(machine_id) is None:
                return {"ok": False, "veto": "not-a-member"}
            survivors = [s.machine_id for s in smap.servers
                         if s.machine_id != machine_id]
            if not survivors:
                self.vetoes_total += 1
                return {"ok": False, "veto": "last-seat"}
            sensed = self.sense(None)
            slice_load = sensed.get("sliceLoads", {}) if sensed.get("ok") \
                else {}
            loads = {m: 0 for m in survivors}
            if sensed.get("ok"):
                for m in survivors:
                    loads[m] = sensed["leaderLoads"].get(m, 0)
            cap = config.rebalance_max_slices_per_epoch()
            owned = sorted(smap.slices_of(machine_id),
                           key=lambda sl: (-slice_load.get(sl, 0), sl))
            moves: Dict[int, tuple] = {}
            for sl in owned[:cap]:
                cold = min(loads, key=lambda m: (loads[m], m))
                moves[sl] = (machine_id, cold)
                loads[cold] += slice_load.get(sl, 0)
            remaining = len(owned) - len(moves)
            base = smap.with_moves({sl: to for sl, (_f, to)
                                    in moves.items()})
            if remaining == 0:
                base = base._replace(servers=tuple(
                    s for s in base.servers if s.machine_id != machine_id))
            plan = self._commit_plan("leave", now, smap, moves, base,
                                     sensed.get("skew", 0.0), 0.0, {})
            out = {"ok": True, "plan": plan.to_dict()}
            if remaining:
                out["remainingSlices"] = remaining  # next epoch's plan
            return out

    # -- certify: the chaos-mesh dry-run -----------------------------------

    def certify(self, plan_id: int, campaign_seed: int = 0,
                seconds: Optional[int] = None, per_second: int = 2,
                max_faults: int = 4) -> dict:
        """Replay the plan's handoff on a synthetic in-process mesh
        under the seeded fault schedule; veto on ANY invariant
        violation. Pure function of ``(campaign_seed, plan)`` — the
        verdict/fault shas replay bit-identically."""
        with self._lock:
            plan = self.plans.get(int(plan_id))
            if plan is None:
                return {"ok": False, "veto": "unknown-plan"}
            secs = int(seconds if seconds is not None
                       else config.rebalance_certify_seconds())
            cert = self._certify_episode(plan, int(campaign_seed), secs,
                                         int(per_second), int(max_faults))
            plan.cert = cert
            plan.certified = cert["ok"]
            plan.certify_seq = self._record(
                "rebalanceCertify", cause_seq=plan.propose_seq,
                planId=plan.plan_id, ok=cert["ok"], seed=cert["seed"],
                verdictSha256=cert["verdictSha256"],
                faultSha256=cert["faultSha256"],
                violations=cert["violations"])
            if not cert["ok"]:
                self.vetoes_total += 1
                self.backoff_until_ms = (self._now()
                                         + config.rebalance_abort_backoff_ms())
            return {"ok": cert["ok"], "planId": plan.plan_id, "cert": cert}

    def _certify_episode(self, plan: RebalancePlan, campaign_seed: int,
                         seconds: int, per_second: int,
                         max_faults: int) -> dict:
        from sentinel_tpu.chaos.invariants import History, check_all
        from sentinel_tpu.chaos.mesh import ChaosMesh
        from sentinel_tpu.chaos.scheduler import FaultScheduler, episode_seed
        from sentinel_tpu.resilience import FaultInjector
        from sentinel_tpu.simulator.clock import SimClock

        smap = plan.proposed
        base = self.current_map()
        # The mesh needs every seat that appears on EITHER side of the
        # diff: a fold-out plan's donor is gone from the proposed server
        # set but must be live to hand its slices off.
        base_mids = (tuple(s.machine_id for s in base.servers)
                     if base is not None else ())
        leaders = tuple(dict.fromkeys(
            tuple(s.machine_id for s in smap.servers) + base_mids))
        n = int(smap.n_slices)
        flows = self._certify_flows(plan, n)
        # The synthetic mesh renumbers epochs (1 = mesh-initial, 2 =
        # seeded current, 3 = the plan) — topology is what is under
        # test, and the live map's absolute epochs would collide with
        # the mesh's own version-1 bootstrap map.
        cur_assign = {m: [] for m in leaders}
        if base is not None:
            for sl in range(min(n, base.n_slices)):
                cur_assign.setdefault(base.slice_owner[sl], []).append(sl)
        inject_assign = {m: [] for m in leaders}
        inject_epochs = {}
        for sl in range(n):
            inject_assign.setdefault(smap.slice_owner[sl], []).append(sl)
            changed = (base is None or sl >= base.n_slices
                       or smap.slice_epoch[sl] != base.slice_epoch[sl])
            inject_epochs[sl] = 3 if changed else 2
        seed = episode_seed(campaign_seed, plan.plan_id)
        scheduler = FaultScheduler(leaders=leaders, flows=flows,
                                   n_slices=n, seconds=seconds,
                                   max_faults=max_faults)
        # A schedule-random rebalance could override the plan under
        # certification — drop that kind, keep every real fault.
        sched = [a for a in scheduler.schedule(campaign_seed, plan.plan_id)
                 if a.get("kind") != "rebalance"]
        workdir = tempfile.mkdtemp(prefix="sentinel-rebalance-cert-")
        clock = SimClock(config.chaos_epoch_ms())
        history = History()
        mesh = None
        violations: List = []
        inject_at = max(1, seconds // 2)
        try:
            with FaultInjector(seed=seed, scope_thread=True) as injector:
                mesh = ChaosMesh(clock, history, workdir, leaders=leaders,
                                 n_slices=n, flows=flows)
                mesh.rebalance(cur_assign, {sl: 2 for sl in range(n)},
                               version=2)
                by_sec: Dict[int, List[dict]] = {}
                for act in sched:
                    by_sec.setdefault(int(act["at"]), []).append(act)
                restores: Dict[int, List[str]] = {}
                flow_order = sorted(flows)
                transfers_before = 0
                for sec in range(seconds):
                    for mid in restores.pop(sec, ()):
                        mesh.link_up[mid] = True
                        mesh.log_fault("link.up", mid, sec=sec)
                    for act in by_sec.get(sec, ()):
                        up_at = mesh.apply_action(act, injector, sec)
                        if up_at is not None:
                            restores.setdefault(min(up_at, seconds),
                                                []).append(act["leader"])
                    if sec == inject_at:
                        transfers_before = len(history.of("transfer"))
                        mesh.rebalance(inject_assign, inject_epochs,
                                       version=3)
                    for fid in flow_order:
                        for _ in range(per_second):
                            mesh.request(fid, sec)
                    violations = check_all(history, mesh.thresholds,
                                           mesh.divisor)
                    if violations:
                        break
                    clock.advance(1000)
                mesh.collect_journals()
                if not violations:
                    violations = check_all(history, mesh.thresholds,
                                           mesh.divisor)
                verdict_sha = _sha(
                    f"{ev['op']}:{ev['flow']}:{ev['status']}:{ev['by']}"
                    f":{ev.get('wire')}"
                    for ev in history.of("verdict"))
                fault_sha = _sha(repr(entry) for entry in mesh.fault_log)
                all_transfers = history.of("transfer")
                transfers = len(all_transfers) - transfers_before
                ops = len(history.of("offered"))
                grant_evs = history.of("grant")
                grants = len(grant_evs)
                # The observed handoff margin: grants already standing
                # in each transfer's window when ownership moved — the
                # evidence the over-admission bound credits.
                margin = sum(
                    1 for t in all_transfers[transfers_before:]
                    for g in grant_evs
                    if g.get("flow") == t["flow"]
                    and g.get("win") == t["win"])
        finally:
            if mesh is not None:
                mesh.stop()
            shutil.rmtree(workdir, ignore_errors=True)
        return {
            "ok": not violations, "seed": seed, "seconds": seconds,
            "violations": [v.to_dict() for v in violations],
            "verdictSha256": verdict_sha, "faultSha256": fault_sha,
            "transfers": transfers, "ops": ops, "grants": grants,
            "handoffMarginGrants": margin,
            "schedule": sched,
        }

    @staticmethod
    def _certify_flows(plan: RebalancePlan, n_slices: int,
                       rate: float = 6.0) -> Dict[int, float]:
        """Deterministic flow set exercising the handoff: one flowId
        per MOVED slice (so every move is driven through grant/fence
        traffic), plus two background flows on untouched slices."""
        flows: Dict[int, float] = {}
        want = sorted(plan.moves)
        untouched = [sl for sl in range(n_slices) if sl not in plan.moves]
        want += untouched[:2]
        fid = 9000
        need = set(want)
        while need and fid < 9000 + 50_000:
            sl = slice_of(fid, n_slices)
            if sl in need:
                flows[fid] = rate
                need.discard(sl)
            fid += 1
        return flows

    # -- apply / rollback --------------------------------------------------

    def apply(self, plan_id: int, force: bool = False) -> dict:
        """Actuate a certified plan through the journal-audited HA
        path; the ONLY mutation is ``ha.apply_map``. Saves the prior
        map as last-known-good and stamps the per-slice cooldown
        ledger (cooldowns start at APPLY)."""
        with self._lock:
            plan = self.plans.get(int(plan_id))
            if plan is None:
                return {"ok": False, "veto": "unknown-plan"}
            if plan.certified is not True and not force:
                self.vetoes_total += 1
                self._record("rebalanceVeto", reason=VETO_CERTIFY,
                             planId=plan.plan_id,
                             detail="apply without certification")
                return {"ok": False, "veto": VETO_CERTIFY,
                        "certified": plan.certified}
            frozen = self._freeze(plan.reason)
            if frozen["frozen"] and not force:
                self.vetoes_total += 1
                self._record("rebalanceVeto", reason=VETO_FROZEN,
                             frozenBy=frozen["reason"], planId=plan.plan_id)
                return {"ok": False, "veto": VETO_FROZEN,
                        "frozenBy": frozen["reason"]}
            smap = self.current_map()
            if smap is not None and smap.version != plan.base_version:
                self.vetoes_total += 1
                return {"ok": False, "veto": "stale-plan",
                        "baseVersion": plan.base_version,
                        "currentVersion": int(smap.version)}
            now = self._now()
            plan.apply_seq = self._record(
                "rebalanceApply",
                cause_seq=(plan.certify_seq if plan.certify_seq is not None
                           else plan.propose_seq),
                planId=plan.plan_id, reason=plan.reason, forced=bool(force),
                version=int(plan.proposed.version),
                slicesMoved=sorted(plan.moves))
            self.last_known_good = smap
            self._actuate(plan.proposed, plan.apply_seq)
            for sl, (_frm, to) in plan.moves.items():
                self.ledger.stamp(sl, to, now)
            plan.applied_ms = now
            self.applies_total += 1
            self.slices_moved_total += len(plan.moves)
            return {"ok": True, "planId": plan.plan_id,
                    "applySeq": plan.apply_seq,
                    "version": int(plan.proposed.version),
                    "slicesMoved": len(plan.moves)}

    def _actuate(self, smap: ShardMap, apply_seq: Optional[int]) -> None:
        """The single actuation path: ``ha.apply_map`` under the apply
        record's causeSeq, so the downstream ``shardMapApply`` /
        ``haRoleFlip`` records chain back to the rebalancer."""
        apply_via = self._apply_via
        if apply_via is None:
            ha = self._ha()
            if ha is None:
                raise RuntimeError("no HA seat to apply through")
            apply_via = ha.apply_map
        with acting("rebalancer"):
            if apply_seq is not None:
                with causing(apply_seq):
                    apply_via(smap)
            else:
                apply_via(smap)

    def rollback(self) -> dict:
        """One-command restore of last-known-good OWNERSHIP: a fresh
        forward map (version and moved-slice epochs necessarily bump —
        per-slice fencing forbids reviving old epochs) whose owners are
        the retained map's."""
        with self._lock:
            lkg = self.last_known_good
            if lkg is None:
                return {"ok": False, "veto": "no-lkg"}
            smap = self.current_map()
            if smap is None:
                return {"ok": False, "veto": VETO_NO_MAP}
            moves = {sl: lkg.slice_owner[sl]
                     for sl in range(min(smap.n_slices, lkg.n_slices))
                     if smap.slice_owner[sl] != lkg.slice_owner[sl]}
            restored = smap.with_moves(moves)
            if lkg.servers != smap.servers:
                restored = restored._replace(servers=lkg.servers)
            seq = self._record(
                "rebalanceRollback", version=int(restored.version),
                restoredOwnershipOf=int(lkg.version),
                slicesMoved=sorted(moves))
            self._actuate(restored, seq)
            now = self._now()
            for sl, to in moves.items():
                self.ledger.stamp(sl, to, now)
            self.last_known_good = smap
            self.rollbacks_total += 1
            return {"ok": True, "version": int(restored.version),
                    "slicesMoved": len(moves), "rollbackSeq": seq}

    # -- governance --------------------------------------------------------

    def freeze(self, on: bool) -> dict:
        with self._lock:
            self.manual_frozen = bool(on)
            self._record("rebalanceFreeze", frozen=self.manual_frozen)
            return {"ok": True, "frozen": self.manual_frozen}

    def reset_timebase(self) -> None:
        """Clock-swap hygiene (the engine's set_clock discipline): the
        ledger's stamps and the abort backoff are absolute times of the
        OLD timebase."""
        with self._lock:
            self.ledger.reset()
            self.backoff_until_ms = 0

    # -- surfaces ----------------------------------------------------------

    def status(self) -> dict:
        with self._lock:
            smap = self.current_map()
            frozen = self._freeze("status")
            return {
                "frozen": frozen["frozen"], "frozenBy": frozen["reason"],
                "manualFrozen": self.manual_frozen,
                "backoffUntilMs": self.backoff_until_ms,
                "mapVersion": int(smap.version) if smap else None,
                "lastKnownGoodVersion": (int(self.last_known_good.version)
                                         if self.last_known_good else None),
                "lastSkew": self.last_skew,
                "degraded": self.degraded_leaders(),
                "counters": {
                    "plans": self.plans_total,
                    "applies": self.applies_total,
                    "rollbacks": self.rollbacks_total,
                    "vetoes": self.vetoes_total,
                    "slicesMoved": self.slices_moved_total,
                },
                "plans": [self.plans[pid].to_dict()
                          for pid in sorted(self.plans)],
            }

    def metrics_state(self) -> dict:
        """The exporter's read: counter values + gauges, one flat dict
        (``sentinel_tpu_rebalance_*`` families)."""
        with self._lock:
            frozen = self._freeze("status")
            return {
                "plans": self.plans_total,
                "applies": self.applies_total,
                "rollbacks": self.rollbacks_total,
                "vetoes": self.vetoes_total,
                "slices_moved": self.slices_moved_total,
                "frozen": 1 if frozen["frozen"] else 0,
                "skew": float(self.last_skew),
            }

package com.alibaba.csp.sentinel.cluster;

/** Vendored signature stub (see vendored/README.md). Reference:
 * core:cluster/ClusterConstants.java. */
public final class ClusterConstants {

    public static final String DEFAULT_CLUSTER_NAMESPACE = "default";

    public static final int CLIENT_STATUS_OFF = 0;
    public static final int CLIENT_STATUS_PENDING = 1;
    public static final int CLIENT_STATUS_STARTED = 2;

    private ClusterConstants() {
    }
}

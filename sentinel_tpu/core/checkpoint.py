"""Stats checkpoint / warm restart.

The reference has NO runtime-stats persistence (SURVEY.md §5: "restart =
cold stats"; rules persist via datasources). This module is the strict
superset the survey proposes: snapshot the node-statistics tensors (1s +
minute windows, concurrency gauges, staged second, occupy borrows) plus
the row registry, and restore them into a fresh engine so sliding windows
and breaker inputs survive a process restart instead of giving a
restarted instance a burst of un-tracked quota.

Scope matches the reference's rule-state stance: per-rule controller
state (warm-up tokens, leaky-bucket heads, breaker timers, param tables)
is NOT checkpointed — it is re-created on rule load anyway (§3.2 "WarmUp
state re-created!"), and rules themselves are the datasources' job.
Stale checkpoints are harmless: window buckets older than their span
rotate out on the first step after restore.

Format: one ``.npz`` (arrays + a JSON header); no orbax dependency so the
checkpoint is greppable and the loader has no version coupling.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Optional

import numpy as np

CHECKPOINT_VERSION = 1


def _tensor_schema(capacity: int, w1_buckets: Optional[int] = None):
    """name -> (shape, dtype) of every persisted tensor — the single list
    driving save and restore-validation, derivable WITHOUT compiling (so
    restore can reject an incompatible file before mutating anything).
    ``w1_buckets`` defaults to the static sample count; engines with a
    retuned instant window (set_window_geometry) pass their own."""
    from sentinel_tpu.core import constants as C

    E, R = C.NUM_EVENTS, capacity
    b1 = C.SECOND_BUCKETS if w1_buckets is None else w1_buckets
    return {
        "w1_counts": ((b1, E, R), np.int32),
        "w1_min_rt": ((b1, R), np.int32),
        "w1_starts": ((b1,), np.int64),
        "w60_counts": ((C.MINUTE_BUCKETS, E, R), np.int32),
        "w60_min_rt": ((C.MINUTE_BUCKETS, R), np.int32),
        "w60_starts": ((C.MINUTE_BUCKETS,), np.int64),
        "cur_threads": ((R,), np.int32),
        "sec_counts": ((E, R), np.int32),
        "sec_min_rt": ((R,), np.int32),
        "sec_stamp": ((), np.int64),
        "occupied_next": ((R,), np.int32),
        "occupied_stamp": ((), np.int64),
    }


def _state_arrays(state):
    """The persisted tensors, in schema order."""
    return {
        "w1_counts": state.w1.counts, "w1_min_rt": state.w1.min_rt,
        "w1_starts": state.w1.starts,
        "w60_counts": state.w60.counts, "w60_min_rt": state.w60.min_rt,
        "w60_starts": state.w60.starts,
        "cur_threads": state.cur_threads,
        "sec_counts": state.sec.counts, "sec_min_rt": state.sec.min_rt,
        "sec_stamp": state.sec.stamp,
        "occupied_next": state.occupied_next,
        "occupied_stamp": state.occupied_stamp,
    }


def _atomic_savez(path: str, header: dict, arrays: dict) -> None:
    """Write header + arrays as one ``.npz`` via tmp-file + fsync +
    rename (+ directory fsync), so neither a crash mid-write NOR a power
    loss after the rename can leave a truncated or unlinked checkpoint
    at ``path`` — rename alone only orders the metadata, not the data
    blocks, and a restore-after-power-cut of a non-fsync'd file is
    exactly the truncated-file failure restore must never see."""
    from sentinel_tpu.resilience import faults

    target_dir = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=target_dir, suffix=".ckpt.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez_compressed(f, __header__=np.frombuffer(
                json.dumps(header).encode("utf-8"), dtype=np.uint8), **arrays)
            f.flush()
            os.fsync(f.fileno())
        # Torn-write seam (resilience/faults.py "checkpoint.torn.write"
        # — ISSUE 15): error mode raises HERE, before the rename — the
        # crash-before-publish case (the previous file survives intact);
        # garbage mode TEARS the fully-fsync'd temp file to half its
        # bytes and lets the rename publish the wreck — the power-cut-
        # mid-data-blocks case restore must reject as ONE ValueError.
        if faults.mutate("checkpoint.torn.write", b"\x01") != b"\x01":
            size = os.path.getsize(tmp)
            with open(tmp, "r+b") as tf:
                tf.truncate(max(1, size // 2))
        os.replace(tmp, path)
        try:
            dfd = os.open(target_dir, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:
            pass  # platforms/filesystems without directory fsync
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _load_npz(path: str):
    """Load an ``.npz`` checkpoint defensively: every way a truncated,
    byte-chopped, or otherwise corrupted file can fail inside numpy/zip
    machinery surfaces as ONE clear ``ValueError`` naming the file,
    never a zipfile/zlib/pickle traceback. A missing file still raises
    ``FileNotFoundError`` (callers distinguish "no checkpoint yet").

    Returns ``(header dict, {name: array})`` with every member fully
    materialized (a chopped member fails HERE, not mid-restore)."""
    import zipfile
    import zlib

    try:
        with np.load(path, allow_pickle=False) as z:
            raw = z["__header__"]
            header = json.loads(bytes(raw).decode("utf-8"))
            if not isinstance(header, dict):
                raise ValueError("header is not a JSON object")
            arrays = {k: np.asarray(z[k]) for k in z.files
                      if k != "__header__"}
        return header, arrays
    except FileNotFoundError:
        raise
    except (zipfile.BadZipFile, zlib.error, EOFError, OSError, KeyError,
            UnicodeDecodeError, ValueError) as ex:
        raise ValueError(
            f"corrupted or truncated checkpoint {path!r}: {ex!r:.200}"
        ) from ex


def save_checkpoint(engine, path: str) -> None:
    """Atomically snapshot the engine's node statistics to ``path``."""
    import jax

    with engine._lock:
        engine._ensure_compiled()
        state = jax.block_until_ready(engine._state)
        header = {
            "version": CHECKPOINT_VERSION,
            "capacity": engine.capacity,
            "sealed_sec": engine._sealed_sec,
            "registry": engine.registry.to_dict(),
            # w1 geometry: bucket COUNT alone can't distinguish a 1s/2 from
            # a 2s/2 window, and grafting counts that covered a different
            # span misreads QPS until rotation flushes them.
            "w1_interval_ms": engine._spec1.interval_ms,
            "w1_sample_count": engine._spec1.buckets,
            # Streaming-reservation leases (sentinel_tpu/llm/ — ISSUE
            # 17): streamId-keyed rows, the flowId-row idiom — a restore
            # grafts survivors, unknown streams start cold. Host-side
            # JSON rows in the header, not a tensor: the ledger is tiny
            # and never device-resident.
            "llm_streams": engine.streams.checkpoint_rows(),
        }
        # Slot mode (core/slots.py): the saved device arrays are SLOT-
        # indexed, so the assignment + generations that bind slots to
        # resources travel in the header. Spill records and cold-tail
        # tallies are NOT persisted: the cold tail cold-restarts across
        # a process restart (the reference's "restart = cold stats"
        # stance, bounded to resources OUTSIDE the hot set) —
        # docs/SEMANTICS.md "Eviction conservation bound".
        if engine.slots is not None:
            header["slots"] = engine.slots.checkpoint_dict()
        arrays = {k: np.asarray(v) for k, v in _state_arrays(state).items()}
    _atomic_savez(path, header, arrays)


def restore_checkpoint(engine, path: str, force: bool = False) -> None:
    """Warm-restart ``engine`` from a checkpoint.

    The registry is replaced wholesale (row ids must match the stats
    rows); rule tensors and per-rule state are rebuilt fresh from the
    engine's CURRENT rule managers against the restored registry.
    Capacity must match the snapshot's.

    Restore is a BOOT-time operation: the engine must not have served
    traffic yet (``entry()`` reads the registry lock-free, so swapping it
    under a live engine would let in-flight entries commit row indices
    that mean a different resource in the restored tensors). Enforced by
    refusing engines whose registry already allocated rows; ``force=True``
    overrides only for callers that have externally quiesced the engine.
    Loading rules BEFORE restoring is fine — rule row interning happens
    during this call's recompile, against the restored registry.
    """
    import jax.numpy as jnp

    from sentinel_tpu.core.registry import NodeRegistry
    from sentinel_tpu.ops.step import SecondAccum
    from sentinel_tpu.ops.window import Window

    if not force and engine.registry.rows_in_use() > 2:  # ROOT + ENTRY
        raise RuntimeError(
            "restore_checkpoint requires a fresh engine (rows already "
            "allocated — it has served traffic or compiled rules); restore "
            "at boot, or pass force=True after quiescing the engine")

    header, arrays = _load_npz(path)
    if header.get("version") != CHECKPOINT_VERSION:
        raise ValueError(
            f"unsupported checkpoint version {header.get('version')}")
    if header.get("capacity") != engine.capacity:
        raise ValueError(
            f"checkpoint capacity {header.get('capacity')} != engine "
            f"capacity {engine.capacity}")
    ck_slots = header.get("slots")
    if (ck_slots is not None) != (engine.slots is not None):
        raise ValueError(
            "checkpoint slot mode does not match the engine: "
            f"checkpoint {'has' if ck_slots is not None else 'lacks'} a "
            "slot assignment, engine is in "
            f"{'slot' if engine.slots is not None else 'fixed-capacity'} "
            "mode")
    ck_spec = (header.get("w1_interval_ms", 1000),
               header.get("w1_sample_count",
                          engine._spec1.buckets))
    if ck_spec != (engine._spec1.interval_ms, engine._spec1.buckets):
        raise ValueError(
            f"checkpoint w1 geometry {ck_spec[0]}ms/{ck_spec[1]} buckets"
            f" != engine {engine._spec1.interval_ms}ms/"
            f"{engine._spec1.buckets}; retune with set_window_geometry"
            " before restoring")

    # Validate BEFORE any mutation (shapes are derivable from capacity +
    # window constants, no compile needed): an incompatible or truncated
    # file must leave the engine exactly as it was.
    schema = _tensor_schema(engine.capacity,
                            w1_buckets=engine._spec1.buckets)
    for name, (shape, dtype) in schema.items():
        got = arrays.get(name)
        if got is None:
            raise ValueError(f"incompatible checkpoint: missing {name}")
        if tuple(got.shape) != shape or np.dtype(got.dtype) != np.dtype(dtype):
            raise ValueError(
                f"incompatible checkpoint: {name} is "
                f"{got.dtype}{list(got.shape)}, engine expects "
                f"{np.dtype(dtype)}{list(shape)}")

    with engine._lock:
        engine.registry = NodeRegistry.from_dict(header["registry"])
        if ck_slots is not None:
            # Re-bind the slot assignment BEFORE the recompile below:
            # rule rows resolve through the slot table, so ruled
            # resources must already sit at their checkpointed slots.
            engine.slots.restore_assignment(ck_slots)
        engine._sealed_sec = int(header["sealed_sec"])
        # Rebuild rule tensors + fresh rule state against the restored
        # registry, then graft the persisted statistics tensors in.
        engine._state = None
        engine._dirty = {k: True for k in engine._dirty}
        engine._ensure_compiled()
        engine._state = engine._state._replace(
            w1=Window(jnp.asarray(arrays["w1_counts"]),
                      jnp.asarray(arrays["w1_min_rt"]),
                      jnp.asarray(arrays["w1_starts"])),
            w60=Window(jnp.asarray(arrays["w60_counts"]),
                       jnp.asarray(arrays["w60_min_rt"]),
                       jnp.asarray(arrays["w60_starts"])),
            # The gauge measures LIVE in-process concurrency, not history:
            # entries in flight at the crash died with their process and
            # will never exit, so grafting their count back would starve
            # THREAD-grade rules forever. Windows persist; gauges reset.
            # (docs/SEMANTICS.md "checkpoint warm restart".)
            cur_threads=jnp.zeros_like(engine._state.cur_threads),
            sec=SecondAccum(jnp.asarray(arrays["sec_counts"]),
                            jnp.asarray(arrays["sec_min_rt"]),
                            jnp.asarray(arrays["sec_stamp"])),
            occupied_next=jnp.asarray(arrays["occupied_next"]),
            occupied_stamp=jnp.asarray(arrays["occupied_stamp"]),
        )
    # Lease mirrors must match the restored windows, or host admission
    # would re-grant quota the snapshot already spent.
    engine._seed_leases_from_state()
    # Streaming reservations graft AFTER the windows: a restored lease's
    # ticks reconcile against the restored debits. last_ms re-stamps to
    # now so a restore never mass-evicts; a client that truly vanished
    # evicts one idle period later (remainder returns as credit).
    engine.streams.graft(header.get("llm_streams") or [], engine.now_ms())


def save_pod_checkpoint(pod_state, path: str) -> None:
    """Snapshot a pod-parallel state tree (``parallel.cluster
    .make_pod_state``): every leaf with its leading device axis, so a
    restarted pod resumes with each device's share of the global window
    intact (the psum'd view is reconstructed from the shares)."""
    import jax

    leaves = jax.tree.leaves(jax.block_until_ready(pod_state))
    _atomic_savez(
        path, {"version": CHECKPOINT_VERSION, "n_leaves": len(leaves)},
        {f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)})


def restore_pod_checkpoint(like, path: str):
    """Rebuild a pod state from ``save_pod_checkpoint`` output. ``like``
    is a template with the target structure/shapes (a fresh
    ``make_pod_state``); every leaf is validated against it before any
    value is returned, so a mismatched file cannot half-load."""
    import jax
    import jax.numpy as jnp

    leaves, treedef = jax.tree.flatten(like)
    header, arrays = _load_npz(path)
    if header.get("version") != CHECKPOINT_VERSION:
        raise ValueError(
            f"unsupported pod checkpoint version {header.get('version')}")
    if header.get("n_leaves") != len(leaves):
        raise ValueError(
            f"pod checkpoint has {header.get('n_leaves')} leaves, "
            f"template expects {len(leaves)}")
    try:
        loaded = [arrays[f"leaf_{i}"] for i in range(len(leaves))]
    except KeyError as ex:
        raise ValueError(
            f"corrupted pod checkpoint {path!r}: missing {ex}") from ex
    for i, (got, want) in enumerate(zip(loaded, leaves)):
        if tuple(got.shape) != tuple(want.shape) \
                or np.dtype(got.dtype) != np.dtype(want.dtype):
            raise ValueError(
                f"pod checkpoint leaf {i} is {got.dtype}{list(got.shape)}, "
                f"template expects {np.dtype(want.dtype)}"
                f"{list(want.shape)}")
    return jax.tree.unflatten(treedef, [jnp.asarray(x) for x in loaded])


# ---------------------------------------------------------------------------
# Cluster token-server window checkpoint (cluster/ha.py state-preserving
# recovery): the leader snapshots its per-flow global sliding windows so a
# successor warm-starts from them instead of handing the whole fleet a
# fresh window of quota at failover. Rows are keyed by flowId (slot layout
# is a compile artifact that differs across processes); a flow whose bucket
# geometry changed starts cold, same stance as the service's own rule-push
# carry-over. Param-flow buckets are NOT checkpointed: they are 1-second
# QPS buckets, so skipping them bounds their over-admission to at most one
# second of per-key quota (docs/SEMANTICS.md "Degraded-quota bound").
# ---------------------------------------------------------------------------

CLUSTER_CHECKPOINT_VERSION = 1


def _peek_header_epoch(path: str) -> Optional[int]:
    """The existing checkpoint's header epoch, or None when there is no
    readable checkpoint (missing/corrupted files never block a save)."""
    try:
        with np.load(path, allow_pickle=False) as z:
            header = json.loads(bytes(z["__header__"]).decode("utf-8"))
        return int(header.get("epoch", 0))
    except Exception:  # noqa: BLE001 — any unreadable file: overwritable
        return None


def save_cluster_checkpoint(service, path: str, slices=None,
                            n_slices: Optional[int] = None,
                            epoch: Optional[int] = None) -> None:
    """Atomically snapshot a ``DefaultTokenService``'s flow windows.

    ``slices``/``n_slices`` (cluster/sharding.py rebalancing): restrict
    the snapshot to flows hashing into those slices of an ``n_slices``
    ring — a slice HANDOFF publishes exactly the donor's rows for the
    moving slice, nothing else. ``epoch`` overrides the header's fencing
    epoch with the slice's own term (per-slice epochs, not the
    service-global max).

    The shared file is epoch-fenced like the wire: a save from a service
    whose epoch is BELOW the file's is refused, so a deposed leader's
    still-running CheckpointTimer cannot clobber the successor's
    published state (which would un-bound the failover over-admission
    margin docs/SEMANTICS.md proves). The peek-and-replace is held under
    an exclusive sidecar flock so two same-host writers cannot interleave
    between the epoch check and the rename; filesystems without flock
    fall back to the unlocked check. Epoch-0 services (pre-HA, no
    fencing) keep last-writer-wins."""
    import jax

    keep = None
    if slices is not None:
        from sentinel_tpu.cluster.sharding import slice_of

        n = int(n_slices) if n_slices is not None else 0
        if n <= 0:
            raise ValueError("slice-filtered save needs n_slices > 0")
        wanted = {int(s) for s in slices}
        keep = lambda fid: slice_of(fid, n) in wanted  # noqa: E731

    # Snapshot first (service lock only) — never hold the file lock
    # while waiting on the device.
    with service._lock:
        service._ensure_compiled()
        state = jax.block_until_ready(service._state)
        header = {
            "version": CLUSTER_CHECKPOINT_VERSION,
            "epoch": int(epoch if epoch is not None
                         else getattr(service, "epoch", 0)),
            "flows": {str(fid): slot
                      for fid, slot in service._slot_of.items()
                      if keep is None or keep(fid)},
        }
        if slices is not None:
            header["slices"] = sorted(int(s) for s in slices)
            header["nSlices"] = int(n_slices)
        arrays = {
            "counts": np.asarray(state.win.counts),
            "starts": np.asarray(state.win.starts),
            "bucket_ms": np.asarray(state.win.bucket_ms),
        }

    epoch = header["epoch"]
    if not epoch:
        _atomic_savez(path, header, arrays)
        return
    with open(path + ".lock", "a+b") as lk:
        try:
            import fcntl

            fcntl.flock(lk, fcntl.LOCK_EX)
        except (ImportError, OSError):
            pass  # no flock here: keep the (unlocked) epoch check
        try:
            existing = _peek_header_epoch(path)
            if existing is not None and existing > epoch:
                raise ValueError(
                    f"refusing to overwrite checkpoint {path!r} from epoch "
                    f"{existing} with state from deposed epoch {epoch}")
            _atomic_savez(path, header, arrays)
        finally:
            try:
                import fcntl

                fcntl.flock(lk, fcntl.LOCK_UN)
            except (ImportError, OSError):
                pass


def restore_cluster_checkpoint(service, path: str, slices=None,
                               n_slices: Optional[int] = None) -> int:
    """Warm-start ``service``'s flow windows from a leader's snapshot.

    Grafts each surviving flowId's window row into the service's OWN
    compiled layout; rows whose bucket geometry differs (rule edit
    between leaders) or whose flowId is unknown here start cold.
    ``slices``/``n_slices`` restrict the graft to flows hashing into
    those slices (cluster/sharding.py: a handoff recipient warm-starts
    ONLY the slice it gained — rows for slices it does not own must not
    shadow their true owner's state). Returns the number of rows
    restored. A corrupted/truncated file raises ``ValueError`` before
    any service state is touched."""
    import jax.numpy as jnp

    keep = None
    if slices is not None:
        from sentinel_tpu.cluster.sharding import slice_of

        n = int(n_slices) if n_slices is not None else 0
        if n <= 0:
            raise ValueError("slice-filtered restore needs n_slices > 0")
        wanted = {int(s) for s in slices}
        keep = lambda fid: slice_of(fid, n) in wanted  # noqa: E731

    header, arrays = _load_npz(path)
    if header.get("version") != CLUSTER_CHECKPOINT_VERSION:
        raise ValueError(
            f"unsupported cluster checkpoint version {header.get('version')}")
    for name, nd in (("counts", 3), ("starts", 2), ("bucket_ms", 1)):
        got = arrays.get(name)
        if got is None or got.ndim != nd:
            raise ValueError(
                f"corrupted or truncated checkpoint {path!r}: bad {name}")
    old_counts, old_starts = arrays["counts"], arrays["starts"]
    old_bucket = arrays["bucket_ms"]
    flows = header.get("flows") or {}

    from sentinel_tpu.cluster.rules import ClusterMetricState

    restored = 0
    with service._lock:
        service._ensure_compiled()
        win = service._state.win
        counts = np.array(win.counts)
        starts = np.array(win.starts)
        new_bucket = np.asarray(win.bucket_ms)
        for fid_str, old_slot in flows.items():
            try:
                fid, old_slot = int(fid_str), int(old_slot)
            except (TypeError, ValueError):
                continue
            if keep is not None and not keep(fid):
                continue
            new_slot = service._slot_of.get(fid)
            # old_slot must index EVERY old array (a corrupted file can
            # carry inconsistent leading dims — never an IndexError out
            # of a leader promotion).
            if (new_slot is None
                    or not 0 <= old_slot < min(old_counts.shape[0],
                                               old_starts.shape[0],
                                               old_bucket.shape[0])
                    or old_counts.shape[1:] != counts.shape[1:]
                    or old_starts.shape[1:] != starts.shape[1:]
                    or old_bucket[old_slot] != new_bucket[new_slot]):
                continue
            counts[new_slot] = old_counts[old_slot]
            starts[new_slot] = old_starts[old_slot]
            restored += 1
        service._state = ClusterMetricState(win=win._replace(
            counts=jnp.asarray(counts), starts=jnp.asarray(starts)))
    return restored


class CheckpointTimer:
    """Optional low-Hz background checkpointer (off by default; SURVEY §5
    'optionally checkpoint the stats tensor at low Hz').

    ``save`` selects the snapshot function — :func:`save_checkpoint`
    (default, ``target`` = engine) or :func:`save_cluster_checkpoint`
    (``target`` = a token service; the HA leader's periodic publish)."""

    def __init__(self, engine, path: str, period_s: float = 30.0,
                 save=None):
        import threading

        self.engine = engine
        self.path = path
        self.period_s = period_s
        self._save = save or save_checkpoint
        self._stop = threading.Event()
        self._thread: Optional[object] = None

    def start(self) -> "CheckpointTimer":
        import threading

        if self._thread is not None and self._thread.is_alive():
            # Includes a thread whose stop() join timed out: clearing the
            # event now would resurrect it alongside a new one.
            return self
        self._thread = None
        self._stop.clear()  # allow start() after a stop()
        self._thread = threading.Thread(
            target=self._run, name="sentinel-checkpoint", daemon=True)
        self._thread.start()
        return self

    def _run(self):
        from sentinel_tpu.log.record_log import record_log

        while not self._stop.wait(self.period_s):
            try:
                self._save(self.engine, self.path)
            except Exception as ex:
                record_log.warn("checkpoint failed: %r", ex)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            if not self._thread.is_alive():
                self._thread = None
            # else: keep the handle so start() can see the straggler and
            # refuse to race a second writer against it

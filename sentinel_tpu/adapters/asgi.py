"""ASGI middleware (reference: ``sentinel-spring-webflux-adapter``'s
``SentinelWebFluxFilter`` + ``SentinelBlockExceptionHandler`` — SURVEY.md
§2.5): the async-web analog of the WSGI filter. The admission check itself
is a fast device micro-step, invoked inline (the reference's reactive
subscriber likewise performs the entry on the subscription signal).
"""

from __future__ import annotations

from typing import Callable, Optional

import sentinel_tpu as st
from sentinel_tpu.core import constants as C
from sentinel_tpu.core.exceptions import BlockException

ASGI_CONTEXT_NAME = "sentinel_web_context"
DEFAULT_BLOCK_BODY = b"Blocked by Sentinel (flow limiting)"


class SentinelASGIMiddleware:
    def __init__(
        self,
        app,
        url_cleaner: Optional[Callable[[str], str]] = None,
        origin_parser: Optional[Callable[[dict], str]] = None,
        block_status: int = 429,
    ):
        self.app = app
        self.url_cleaner = url_cleaner or (lambda p: p)
        self.origin_parser = origin_parser or (lambda scope: "")
        self.block_status = block_status

    async def __call__(self, scope, receive, send):
        if scope.get("type") != "http":
            await self.app(scope, receive, send)
            return
        resource = self.url_cleaner(scope.get("path", "/"))
        origin = self.origin_parser(scope)
        st.context_enter(ASGI_CONTEXT_NAME, origin)
        try:
            try:
                entry = st.entry(resource, entry_type=C.EntryType.IN)
            except BlockException:
                await send({
                    "type": "http.response.start",
                    "status": self.block_status,
                    "headers": [(b"content-type", b"text/plain")],
                })
                await send({
                    "type": "http.response.body",
                    "body": DEFAULT_BLOCK_BODY,
                })
                return
            try:
                await self.app(scope, receive, send)
            except BaseException as ex:
                entry.trace(ex)
                raise
            finally:
                entry.exit()
        finally:
            st.exit_context()

"""Datasource tests (reference: ``sentinel-datasource-extension`` + the
per-config-system modules, SURVEY.md §2.2/§3.2): both datasource shapes
(push, versioned poll) swap the rule managers' property without touching
files, and the writable half round-trips ``setRules`` persistence.
"""

import json

import pytest

import sentinel_tpu as st
from sentinel_tpu.datasource import (
    BrokerDataSource,
    BrokerWritableDataSource,
    InProcessBroker,
    PollingKVDataSource,
    PushDataSource,
    bind,
    flow_rules_from_json,
    flow_rules_to_json,
)


def test_push_source_drives_engine_rules(engine):
    """Rule push propagates engine-side with no file involved."""
    broker = InProcessBroker()
    src = BrokerDataSource(broker, "rules/flow", flow_rules_from_json)
    bind(src, st.load_flow_rules)
    try:
        assert engine.flow_rules.get_rules() == []
        broker.set("rules/flow",
                   json.dumps([{"resource": "pushed", "count": 1.0}]))
        rules = engine.flow_rules.get_rules()
        assert len(rules) == 1 and rules[0].resource == "pushed"
        # enforced immediately
        assert st.entry_ok("pushed") and not st.entry_ok("pushed")
    finally:
        src.close()


def test_push_source_initial_load(engine):
    """A key already present at subscribe time loads like Redis's initial
    GET."""
    broker = InProcessBroker()
    broker.set("k", json.dumps([{"resource": "pre", "count": 5.0}]))
    src = BrokerDataSource(broker, "k", flow_rules_from_json)
    bind(src, st.load_flow_rules)
    # bind() fires the listener with the property's current value
    assert [r.resource for r in engine.flow_rules.get_rules()] == ["pre"]
    src.close()


def test_push_bad_payload_keeps_last_good(engine):
    broker = InProcessBroker()
    src = BrokerDataSource(broker, "k", flow_rules_from_json)
    bind(src, st.load_flow_rules)
    try:
        broker.set("k", json.dumps([{"resource": "good", "count": 2.0}]))
        broker.set("k", "{not json!")
        assert [r.resource for r in engine.flow_rules.get_rules()] == ["good"]
    finally:
        src.close()


def test_polling_kv_source_version_gated(engine):
    broker = InProcessBroker()
    src = PollingKVDataSource(broker, "cfg", flow_rules_from_json,
                              recommend_refresh_ms=100000)
    bind(src, st.load_flow_rules)
    try:
        src.first_load()
        assert engine.flow_rules.get_rules() == []
        src.refresh()  # no version change -> no-op
        broker.set("cfg", json.dumps([{"resource": "polled", "count": 3.0}]))
        src.refresh()
        assert [r.resource for r in engine.flow_rules.get_rules()] == ["polled"]
        # unchanged version: refresh is a cheap no-op (is_modified False)
        assert not src.is_modified()
    finally:
        src.close()


def test_writable_round_trip_via_set_rules():
    """setRules -> BrokerWritableDataSource -> broker -> push source ->
    a SECOND engine's manager: the reference's datasource persistence loop."""
    import urllib.parse
    import urllib.request

    from sentinel_tpu.transport.command_center import CommandCenter
    from sentinel_tpu.transport.handlers import register_writable_datasource

    eng = st.reset(capacity=512)
    broker = InProcessBroker()
    register_writable_datasource(
        "flow", BrokerWritableDataSource(broker, "rules/flow",
                                         flow_rules_to_json))
    observed = []
    reader = PushDataSource(flow_rules_from_json)
    broker.subscribe("rules/flow", reader.on_update)
    reader.property.add_listener(
        type("L", (), {"config_update": lambda self, v: observed.append(v),
                       "config_load": lambda self, v: observed.append(v)})())

    center = CommandCenter(eng, port=0).start()
    try:
        rules = [{"resource": "rt", "count": 4.0}]
        body = f"data={urllib.parse.quote(json.dumps(rules))}"
        req = urllib.request.Request(
            f"http://127.0.0.1:{center.bound_port}/setRules?type=flow",
            data=body.encode(), method="POST")
        with urllib.request.urlopen(req, timeout=5) as r:
            assert r.read().decode() == "success"
        assert observed and observed[-1][0].resource == "rt"
        assert broker.version("rules/flow") == 1
    finally:
        center.stop()
        from sentinel_tpu.transport import handlers as H

        H._writable_datasources.pop("flow", None)  # don't leak across tests
        st.reset(capacity=512)


def test_push_source_has_no_pull_path():
    src = PushDataSource(flow_rules_from_json)
    with pytest.raises(NotImplementedError):
        src.read_source()


class TestHttpPollingSource:
    """HTTP conditional-GET datasource (the Eureka / spring-cloud-config
    poll shape) against the in-repo ETag/304 config server."""

    def test_initial_load_and_change_push(self, engine):
        from sentinel_tpu.datasource import (
            HttpRefreshableDataSource, MiniConfigHTTPServer)

        server = MiniConfigHTTPServer().start()
        try:
            server.set_document(json.dumps(
                [{"resource": "h0", "count": 5.0}]))
            src = HttpRefreshableDataSource(
                server.url, flow_rules_from_json,
                recommend_refresh_ms=100000)
            bind(src, st.load_flow_rules)
            src.first_load()
            assert [r.resource for r in
                    engine.flow_rules.get_rules()] == ["h0"]
            server.set_document(json.dumps(
                [{"resource": "h1", "count": 2.0}]))
            src.refresh()
            assert [r.resource for r in
                    engine.flow_rules.get_rules()] == ["h1"]
        finally:
            server.stop()

    def test_unchanged_poll_is_a_304(self, engine):
        from sentinel_tpu.datasource import (
            HttpRefreshableDataSource, MiniConfigHTTPServer)

        server = MiniConfigHTTPServer().start()
        try:
            server.set_document(json.dumps(
                [{"resource": "same", "count": 1.0}]))
            src = HttpRefreshableDataSource(
                server.url, flow_rules_from_json,
                recommend_refresh_ms=100000)
            bind(src, st.load_flow_rules)
            src.first_load()
            for _ in range(3):
                src.refresh()          # unchanged: conditional GETs
            assert server.not_modified_count == 3
            assert [r.resource for r in
                    engine.flow_rules.get_rules()] == ["same"]
        finally:
            server.stop()

    def test_server_outage_keeps_last_good(self, engine):
        import urllib.error

        from sentinel_tpu.datasource import (
            HttpRefreshableDataSource, MiniConfigHTTPServer)

        server = MiniConfigHTTPServer().start()
        server.set_document(json.dumps([{"resource": "kept", "count": 3.0}]))
        src = HttpRefreshableDataSource(
            server.url, flow_rules_from_json, recommend_refresh_ms=100000,
            timeout_s=0.5)
        bind(src, st.load_flow_rules)
        src.first_load()
        server.stop()
        with pytest.raises((urllib.error.URLError, OSError)):
            src.refresh()              # the poll LOOP logs this; rules hold
        assert [r.resource for r in
                engine.flow_rules.get_rules()] == ["kept"]

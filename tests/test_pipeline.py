"""Pipelined-admission tests: micro-batched steps must preserve the serial
semantics of the synchronous path under concurrency.
"""

import threading

import pytest

import sentinel_tpu as st
from sentinel_tpu.core import constants as C


@pytest.fixture()
def piped(engine, frozen_time):
    engine.start_pipeline(linger_s=0.0005)
    yield engine
    engine.stop_pipeline()


def test_qps_quota_exact_under_pipeline(piped, frozen_time):
    st.load_flow_rules([st.FlowRule(resource="p", count=10)])
    passed = blocked = 0
    for _ in range(16):
        h = st.entry_ok("p")
        if h:
            passed += 1
            h.exit()
        else:
            blocked += 1
    assert passed == 10 and blocked == 6


def test_concurrent_callers_share_quota_exactly(piped, frozen_time):
    st.load_flow_rules([st.FlowRule(resource="conc", count=25)])
    results = []
    lock = threading.Lock()

    def worker(n):
        local = 0
        for _ in range(n):
            h = st.entry_ok("conc")
            if h:
                local += 1
                h.exit()
        with lock:
            results.append(local)

    threads = [threading.Thread(target=worker, args=(10,)) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sum(results) == 25  # 80 attempts, quota 25, no overshoot


def test_exit_before_entry_order_for_thread_grade(piped, frozen_time):
    st.load_flow_rules([
        st.FlowRule(resource="tg", count=1, grade=C.FLOW_GRADE_THREAD)])
    for _ in range(5):
        h = st.entry_ok("tg")
        assert h is not None, "exit must land before the next entry"
        h.exit()


def test_pipeline_batches_concurrent_submissions(piped, frozen_time):
    st.load_flow_rules([st.FlowRule(resource="b", count=1000)])
    barrier = threading.Barrier(16)

    def worker():
        barrier.wait()
        for _ in range(5):
            h = st.entry_ok("b")
            if h:
                h.exit()

    threads = [threading.Thread(target=worker) for _ in range(16)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    pipe = piped._pipeline
    # Some cycles must have carried more than one entry.
    assert pipe.batched > pipe.cycles
    assert pipe.batched == 16 * 5


def test_stop_pipeline_restores_sync_path(engine, frozen_time):
    engine.start_pipeline()
    st.load_flow_rules([st.FlowRule(resource="s", count=2)])
    assert st.entry_ok("s") is not None
    engine.stop_pipeline()
    assert st.entry_ok("s") is not None
    assert st.entry_ok("s") is None  # quota shared across modes


def test_fail_open_is_counted_and_logged(piped, frozen_time, caplog):
    """A pipeline cycle error passes entries UNGUARDED — that outage must be
    observable: fail_open_count increments and a warning is logged."""
    import logging

    st.load_flow_rules([st.FlowRule(resource="fo", count=0)])  # blocks all
    orig = piped._run_entry_batch
    piped._run_entry_batch = lambda batch: (_ for _ in ()).throw(RuntimeError("boom"))
    try:
        with caplog.at_level(logging.WARNING, logger="sentinel_tpu"):
            with st.entry("fo"):  # passes unguarded despite the count=0 rule
                pass
    finally:
        piped._run_entry_batch = orig
    assert piped.fail_open_count == 1
    assert any("UNGUARDED" in r.message for r in caplog.records)


def test_sync_device_failure_fails_open_and_recovers(engine, frozen_time):
    """Backend/tunnel death on the SYNC dispatch path (the round-4 outage
    class): entry() must fail OPEN (counted + logged) like the
    reference's fallbackToLocalOrPass — never surface an XLA error to the
    caller — and the engine must recover with cold stats on the next
    successful dispatch."""
    st.load_flow_rules([st.FlowRule(resource="dead", count=1,
                                    control_behavior=C.CONTROL_BEHAVIOR_RATE_LIMITER,
                                    max_queueing_time_ms=0)])  # device path
    assert st.entry_ok("dead")          # healthy dispatch first
    engine._flush_committer()

    healthy_jit = engine._entry_jit

    def dying_jit(*a, **kw):
        raise RuntimeError("tunnel died mid-dispatch")

    engine._entry_jit = dying_jit
    before = engine.fail_open_count
    h = st.entry_ok("dead")             # must NOT raise RuntimeError
    assert h is not None                # failed open
    assert engine.fail_open_count > before
    assert engine._state is None        # poisoned state dropped
    h.exit()                            # exit rebuilds cold + commits

    # recovery: healthy jit again -> protection resumes on cold stats
    engine._entry_jit = healthy_jit
    assert st.entry_ok("dead") is not None
    snap = engine.node_snapshot()["dead"]
    assert snap["passQps"] >= 1         # stats flowing again


def test_exit_device_failure_never_breaks_caller(engine, frozen_time):
    st.load_flow_rules([st.FlowRule(resource="dx", count=5,
                                    control_behavior=C.CONTROL_BEHAVIOR_RATE_LIMITER,
                                    max_queueing_time_ms=1000)])
    h = st.entry_ok("dx")
    assert h

    def dying_jit(*a, **kw):
        raise RuntimeError("tunnel died on exit")

    engine._exit_jit = dying_jit
    h.exit()                            # must not raise
    assert engine.fail_open_count >= 1
